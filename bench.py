"""Headline benchmark: prints ONE JSON line for the driver.

Config benchmarked: the reference's richest training path — the BN-CNN of
mnist_keras_distributed.py:67-120 at its train batch size 128
(tf2_mnist_distributed.py:33), SGD, sparse-CE loss — as a fully jitted
data-parallel train step over all available chips (one step == one global
batch of 128 images, the observable unit of the reference's hot loop,
SURVEY.md §3.1).

Metric: images/sec/chip (BASELINE.json "metric"). The reference publishes no
numbers (BASELINE.md: "published": {}), so `vs_baseline` is measured against
REFERENCE_ESTIMATE below — a documented estimate of the reference TF stack's
single-GPU throughput for this model/batch (TF1-era Keras MNIST CNN at bs=128
on the K80/P100-class hardware the scripts target: ~10k images/s).
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_ESTIMATE = 10_000.0  # images/sec, see module docstring
GLOBAL_BATCH = 128             # tf2_mnist_distributed.py:33
WARMUP_STEPS = 20
TIMED_STEPS = 400


def main() -> None:
    import jax
    import optax

    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    strategy = MirroredStrategy()
    n_chips = strategy.num_replicas

    model = BatchNormCNN()
    tx = optax.sgd(0.01)
    sample = np.zeros((GLOBAL_BATCH, 784), np.float32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_train_step(strategy, state, donate=True)

    rng = np.random.default_rng(0)
    images = rng.random((GLOBAL_BATCH, 784), np.float32)
    labels = rng.integers(0, 10, (GLOBAL_BATCH, 1)).astype(np.int32)
    batch_sh = strategy.batch_sharding()
    images = jax.device_put(images, batch_sh)
    labels = jax.device_put(labels, batch_sh)
    key = jax.random.key(0)

    for _ in range(WARMUP_STEPS):
        state, metrics = step_fn(state, (images, labels), key)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step_fn(state, (images, labels), key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    images_per_sec = TIMED_STEPS * GLOBAL_BATCH / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "mnist_bncnn_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_ESTIMATE, 3),
    }))


if __name__ == "__main__":
    main()
