"""Headline benchmark: prints ONE JSON line for the driver — always.

Two-process design (round-2 hardening per VERDICT.md "What's weak" #1):

- **Driver mode** (`python bench.py`, no jax import): runs the measurement as
  a subprocess (`python bench.py --run`) and retries with exponential backoff
  when the TPU backend comes up `UNAVAILABLE` (the round-1 failure:
  `BENCH_r01.json` rc=1 at the first `jax.local_devices()` call). A failed
  backend init poisons the in-process jax backend cache, so each attempt gets
  a fresh interpreter. On final failure the driver STILL prints one parseable
  JSON line with an `"error"` field and the last attempt's stderr tail.
- **Run mode** (`--run`): brings up jax, refuses a silent CPU fallback
  (platform is recorded and cpu is an error unless TFDE_BENCH_ALLOW_CPU=1),
  and measures two configs:

  1. The reference's richest training path — the BN-CNN of
     mnist_keras_distributed.py:67-120 at its train batch 128
     (tf2_mnist_distributed.py:33), SGD, sparse-CE — as a jitted DP train
     step. Metric: images/sec/chip. `vs_baseline` divides by
     REFERENCE_ESTIMATE (the reference publishes nothing, BASELINE.md).
  2. A compute-bound config: BERT-base MLM fwd+bwd at bf16, seq 512 —
     reported as **MFU = achieved matmul FLOPs / chip peak** (`bert_mfu`
     field) plus tokens/sec/chip. FLOPs are computed analytically from the
     model dims (training = 3x forward — the "6N" params convention —
     attention matmuls included); chip peak comes from the device_kind table
     below.

Env knobs: TFDE_BENCH_BUDGET_S (total retry budget, default 900),
TFDE_BENCH_ATTEMPT_TIMEOUT_S (per attempt, default 600),
TFDE_BENCH_ALLOW_CPU=1 (let the measurement run on cpu and say so).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_ESTIMATE = 10_000.0  # images/sec; see module docstring
GLOBAL_BATCH = 128             # tf2_mnist_distributed.py:33

# Peak bf16 matmul FLOP/s per chip, keyed by substrings of
# jax.Device.device_kind (public figures; first match wins).
PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
DEFAULT_PEAK = 275e12


def chip_peak_flops(device_kind: str) -> tuple[float, bool]:
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak, True
    return DEFAULT_PEAK, False


def bert_train_flops_per_token(hidden: int, mlp: int, depth: int,
                               seq: int, vocab: int) -> float:
    """Analytic matmul FLOPs per token for one fwd+bwd MLM step.

    fwd per layer per token: qkvo 2*4H^2, mlp 2*2HF, attention matmuls
    (scores + values) 2*2SH. Plus the MLM transform dense 2H^2 and the tied
    decoder 2HV. Training = 3x forward (backward is 2x).
    """
    per_layer = 8 * hidden * hidden + 4 * hidden * mlp + 4 * seq * hidden
    fwd = depth * per_layer + 2 * hidden * hidden + 2 * hidden * vocab
    return 3.0 * fwd


# --------------------------------------------------------------------------
# Run mode: the actual measurement (fresh interpreter per attempt).
# --------------------------------------------------------------------------

def _bench_mnist(strategy, n_chips: int, smoke: bool = False) -> dict:
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.training.step import init_state, make_train_step

    model = BatchNormCNN()
    tx = optax.sgd(0.01)
    sample = np.zeros((GLOBAL_BATCH, 784), np.float32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_train_step(strategy, state, donate=True)

    rng = np.random.default_rng(0)
    images = rng.random((GLOBAL_BATCH, 784), np.float32)
    labels = rng.integers(0, 10, (GLOBAL_BATCH, 1)).astype(np.int32)
    batch_sh = strategy.batch_sharding()
    images = jax.device_put(images, batch_sh)
    labels = jax.device_put(labels, batch_sh)
    key = jax.random.key(0)

    warmup, timed = (3, 20) if smoke else (20, 400)
    for _ in range(warmup):
        state, _ = step_fn(state, (images, labels), key)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(timed):
        state, _ = step_fn(state, (images, labels), key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    per_chip = timed * GLOBAL_BATCH / dt / n_chips
    return {
        "mnist_images_per_sec_per_chip": round(per_chip, 1),
        "mnist_step_ms": round(dt / timed * 1e3, 3),
    }


def _bench_bert_mfu(strategy, n_chips: int, device_kind: str,
                    smoke: bool = False) -> dict:
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.bert import Bert, BertBase
    from tfde_tpu.ops import losses
    from tfde_tpu.training.step import init_state, make_custom_train_step

    if smoke:  # CPU-sized config: validates the path, not a real number
        seq, per_chip_batch = 128, 2
        model = Bert(vocab_size=1024, hidden_size=128, depth=2, num_heads=4,
                     mlp_dim=256, dropout_rate=0.0, pad_vocab=True)
        warmup, timed = 1, 3
    else:
        seq, per_chip_batch = 512, 16
        model = BertBase(dropout_rate=0.0, pad_vocab=True)
        warmup, timed = 3, 20
    dims = (model.hidden_size, model.mlp_dim, model.depth)
    global_batch = per_chip_batch * n_chips
    vocab = model.padded_vocab

    def loss_fn(state, params, batch, rng):
        input_ids, labels = batch
        logits = state.apply_fn({"params": params}, input_ids, train=True,
                                rngs={"dropout": rng})
        loss, acc = losses.masked_lm_loss(logits, labels)
        return loss, {"mlm_accuracy": acc}

    tx = optax.adamw(1e-4)
    sample = np.zeros((global_batch, seq), np.int32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_custom_train_step(strategy, state, loss_fn)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -100, np.int32)
    labels[:, ::7] = ids[:, ::7]  # ~15% positions predicted
    key = jax.random.key(0)

    for _ in range(warmup):
        state, _ = step_fn(state, (ids, labels), key)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(timed):
        state, _ = step_fn(state, (ids, labels), key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    step_s = dt / timed
    tokens_per_step = global_batch * seq
    hidden, mlp, depth = dims
    flops_per_token = bert_train_flops_per_token(hidden, mlp, depth, seq, vocab)
    achieved = tokens_per_step * flops_per_token / step_s / n_chips
    peak, known = chip_peak_flops(device_kind)
    return {
        "bert_mfu": round(achieved / peak, 4),
        "bert_tokens_per_sec_per_chip": round(tokens_per_step / step_s / n_chips, 1),
        "bert_step_ms": round(step_s * 1e3, 2),
        "bert_achieved_tflops_per_chip": round(achieved / 1e12, 2),
        "chip_peak_tflops": round(peak / 1e12, 1),
        "chip_peak_known": known,
    }


def run_mode() -> None:
    import jax

    if os.environ.get("TFDE_BENCH_FORCE_CPU") == "1":
        # jax.config (not the env var): the axon site shim intercepts
        # backend bring-up when JAX_PLATFORMS is consulted and can hang on a
        # dead tunnel; the lazy-config route sidesteps it (same trick as
        # tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        os.environ["TFDE_BENCH_ALLOW_CPU"] = "1"

    devices = jax.local_devices()
    platform = devices[0].platform
    device_kind = str(devices[0].device_kind)
    if platform == "cpu" and os.environ.get("TFDE_BENCH_ALLOW_CPU") != "1":
        print(json.dumps({"error": "backend came up as cpu; refusing a "
                          "silent-fallback number (set TFDE_BENCH_ALLOW_CPU=1 "
                          "to override)", "platform": platform}))
        sys.exit(3)

    from tfde_tpu.parallel.strategies import MirroredStrategy

    strategy = MirroredStrategy()
    n_chips = strategy.num_replicas
    print(f"platform={platform} kind={device_kind} chips={n_chips}",
          file=sys.stderr)

    smoke = os.environ.get("TFDE_BENCH_SMOKE") == "1"
    result = {"platform": platform, "device_kind": device_kind,
              "n_chips": n_chips}
    if smoke:
        result["smoke"] = True
    result.update(_bench_mnist(strategy, n_chips, smoke))
    print(f"mnist done: {result}", file=sys.stderr)
    try:
        result.update(_bench_bert_mfu(strategy, n_chips, device_kind, smoke))
    except Exception as e:  # OOM on small chips etc. — keep the mnist number
        result["bert_error"] = f"{type(e).__name__}: {e}"[:400]
    print(f"bert done: {result}", file=sys.stderr)

    per_chip = result["mnist_images_per_sec_per_chip"]
    line = {
        "metric": "mnist_bncnn_train_images_per_sec_per_chip",
        "value": per_chip,
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_ESTIMATE, 3),
        **result,
    }
    print(json.dumps(line))


# --------------------------------------------------------------------------
# Driver mode: retry loop, no jax in this process.
# --------------------------------------------------------------------------

def probe_mode() -> None:
    """Fast backend check: bring up jax, print one JSON line, exit."""
    import jax

    devices = jax.local_devices()
    print(json.dumps({"ok": True, "platform": devices[0].platform,
                      "n": len(devices)}))


def _last_json(stdout: str) -> dict | None:
    """Last stdout line that parses as a JSON object, or None."""
    for ln in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def _backend_probe(timeout_s: float) -> tuple[str, str]:
    """('up'|'cpu_only'|'down', detail) for a fresh-interpreter backend check.

    The round-1 failure raised UNAVAILABLE at the first device query; the
    failure observed while building round 2 *hangs* there instead (tunnel
    never answers). Probing in a 2-minute subprocess keeps either mode from
    eating the whole benchmark budget before we know the backend is up.
    'cpu_only' is permanent (no TPU plugin on this host) — don't retry it.
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "down", "probe hang: backend init did not answer"
    parsed = _last_json(proc.stdout)
    if parsed and parsed.get("ok"):
        if parsed.get("platform") == "cpu" and \
                os.environ.get("TFDE_BENCH_ALLOW_CPU") != "1":
            return "cpu_only", "backend came up as cpu only"
        return "up", parsed.get("platform", "?")
    return "down", (proc.stderr or "")[-800:]


def driver_mode() -> None:
    budget = float(os.environ.get("TFDE_BENCH_BUDGET_S", "900"))
    attempt_timeout = float(os.environ.get("TFDE_BENCH_ATTEMPT_TIMEOUT_S", "600"))
    probe_timeout = float(os.environ.get("TFDE_BENCH_PROBE_TIMEOUT_S", "120"))
    skip_probe = os.environ.get("TFDE_BENCH_FORCE_CPU") == "1"
    deadline = time.monotonic() + budget
    backoff = 15.0
    attempt = 0
    last_tail = ""
    last_rc: object = None

    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            break
        attempt += 1
        print(f"[bench driver] attempt {attempt} "
              f"(remaining budget {remaining:.0f}s)", file=sys.stderr)
        if not skip_probe:
            status, detail = _backend_probe(min(probe_timeout, remaining))
            if status == "cpu_only":
                last_rc, last_tail = "cpu_only", detail
                break  # permanent on this host; don't burn the budget
            if status == "down":
                last_rc, last_tail = "probe_failed", detail
                sleep = min(backoff, max(deadline - time.monotonic() - 60, 0))
                print(f"[bench driver] backend probe failed ({detail[:200]}); "
                      f"retrying in {sleep:.0f}s", file=sys.stderr)
                if sleep > 0:
                    time.sleep(sleep)
                backoff = min(backoff * 2, 120)
                continue
            print(f"[bench driver] backend up: {detail}", file=sys.stderr)
            remaining = deadline - time.monotonic()  # probe time is spent
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run"],
                capture_output=True, text=True,
                timeout=max(min(attempt_timeout, remaining), 30),
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            last_rc = proc.returncode
            sys.stderr.write(proc.stderr[-4000:])
            last_tail = (proc.stderr or "")[-1500:]
            parsed = _last_json(proc.stdout)
            if parsed and "metric" in parsed:
                print(json.dumps(parsed))
                return
            if parsed and "error" in parsed:
                last_tail = parsed["error"]
        except subprocess.TimeoutExpired as e:
            last_rc = "timeout"
            last_tail = ((e.stderr or b"")[-1500:].decode("utf-8", "replace")
                         if isinstance(e.stderr, bytes) else str(e.stderr)[-1500:])
            print(f"[bench driver] attempt timed out", file=sys.stderr)

        sleep = min(backoff, max(deadline - time.monotonic() - 60, 0))
        if sleep > 0:
            print(f"[bench driver] backend not ready (rc={last_rc}); "
                  f"retrying in {sleep:.0f}s", file=sys.stderr)
            time.sleep(sleep)
        backoff = min(backoff * 2, 120)

    print(json.dumps({
        "metric": "mnist_bncnn_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": f"TPU backend unavailable after {attempt} attempts "
                 f"within {budget:.0f}s budget",
        "last_rc": last_rc,
        "last_stderr_tail": last_tail,
    }))
    sys.exit(0)  # the JSON line IS the deliverable; don't hand back a traceback rc


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_mode()
    elif "--probe" in sys.argv:
        probe_mode()
    else:
        driver_mode()
