"""Headline benchmark: prints ONE JSON line for the driver — always.

Two-process design (round-2 hardening): driver mode retries a fresh-interpreter
run mode while the TPU backend comes up; on final failure it still prints one
parseable JSON line with an "error" field.

Round-3 trust layer (VERDICT r2 "What's weak" #1: the round-2 bench printed
2531 achieved TFLOPs on a 197-TFLOP chip — 1285% MFU — without noticing):

- **Host-fetch timing.** Measured on this hardware ('axon' experimental
  platform): `jax.block_until_ready` returns ~immediately with device work
  still pending (10 chained 4096^3 matmuls "completed" in 0.3 ms), so every
  round-2 number was enqueue time, not compute. Every timed window now ends
  with a device->host fetch of a scalar that is data-dependent on the final
  step (the jitted step's own loss output / the calibration chain's out[0,0]),
  which no backend can fake, minus a separately-measured fetch latency. The
  residual block->fetch gap is reported as `sync_block_gap_ms` — direct
  evidence of whether block_until_ready lied.
- **Calibration matmul.** A bf16 matmul chain of analytically-known FLOPs
  (lax.fori_loop inside one jit, so dispatch overhead is out of the picture)
  runs first; its achieved TFLOPs vs chip peak (`calib_frac_of_peak`) gates
  everything: >1.05x peak means timing is broken and the bench says so in an
  `"error"` field instead of printing numbers.
- **Peak gate per config.** Any config whose achieved FLOPs exceed 1.05x chip
  peak withholds its number and reports `<cfg>_error` instead.
- **Loss-motion check.** The loss scalar is fetched before and after each
  timed window and must change (`<cfg>_loss_moved`) — a window that executes
  nothing cannot pass.
- **No invented baseline.** The reference publishes no numbers (BASELINE.md),
  so `vs_baseline` is null with a note — round 2's `/ 10_000.0` estimate was
  fiction and is gone.
- **End-to-end config.** `mnist_e2e_*` times training *through the host input
  pipeline* (data.Dataset shuffle/repeat/batch/prefetch + device_prefetch),
  not just a resident device batch — the overlap the >=90% scaling story
  depends on (SURVEY.md §7).
- **Flash qualification.** `flash_*` runs the Pallas flash-attention kernel
  vs the reference einsum at S=2048 on the real chip: max |err| + fwd+bwd
  speedup (`flash_speedup`). This is the hardware qualification that flips
  ops/attention.py auto-dispatch.

Configs measured (each in try/except; one failure never kills the line):
  calib   — bf16 4096^3 matmul chain, known FLOPs (the trust anchor)
  mnist   — BN-CNN of mnist_keras_distributed.py:67-120 @ batch 128, SGD,
            resident device batch: images/sec/chip (compute path)
  mnist_e2e — same model fed by the real host pipeline: images/sec/chip
  bert    — BERT-base MLM fwd+bwd bf16 @ seq 512: MFU vs chip peak
  flash   — Pallas flash kernel vs reference attention @ S=2048
  gpt_long_win — gpt_long with Gemma-2 deltas (alternating window 1024 +
            softcap 50) on the fused path, MFU vs the windowed-flop model
            (ops/roofline.py; tools/roofline.py has the per-op view)

Env knobs: TFDE_BENCH_BUDGET_S (total retry budget, default 900),
TFDE_BENCH_ATTEMPT_TIMEOUT_S (per attempt, default 600),
TFDE_BENCH_ALLOW_CPU=1 (let the measurement run on cpu and say so),
TFDE_BENCH_SMOKE=1 (tiny shapes, path validation only).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

GLOBAL_BATCH = 128  # tf2_mnist_distributed.py:33

# Peak bf16 matmul FLOP/s per chip, keyed by substrings of
# jax.Device.device_kind (public figures; first match wins).
PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
DEFAULT_PEAK = 275e12
PEAK_TOLERANCE = 1.05  # achieved/peak above this = broken timing, not speed


def chip_peak_flops(device_kind: str) -> tuple[float, bool]:
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak, True
    return DEFAULT_PEAK, False


def bert_train_flops_per_token(hidden: int, mlp: int, depth: int,
                               seq: int, vocab: int) -> float:
    """Analytic matmul FLOPs per token for one fwd+bwd MLM step.

    fwd per layer per token: qkvo 2*4H^2, mlp 2*2HF, attention matmuls
    (scores + values) 2*2SH. Plus the MLM transform dense 2H^2 and the tied
    decoder 2HV. Training = 3x forward (backward is 2x).
    """
    per_layer = 8 * hidden * hidden + 4 * hidden * mlp + 4 * seq * hidden
    fwd = depth * per_layer + 2 * hidden * hidden + 2 * hidden * vocab
    return 3.0 * fwd


# --------------------------------------------------------------------------
# Trusted timing: the clock stops at a host fetch, never at block_until_ready.
# --------------------------------------------------------------------------

class _Clock:
    """Timing helper calibrated against the backend's sync behavior.

    fetch(x): device_get a scalar jit *output* (cheap: no new compile) —
    the only synchronization this backend honors.
    """

    def __init__(self):
        import jax
        import numpy as np

        from tfde_tpu.observability import recompile

        self._jax = jax
        self._np = np
        self._recompile = recompile
        # every timed window asserts zero jit-cache misses (the 0.7-TFLOP
        # round-2 hazard was a recompile inside the window)
        recompile.install()
        # Warm the transfer channel, then measure steady-state fetch latency
        # on an already-ready scalar.
        z = jax.jit(lambda: jax.numpy.zeros(()))()
        self.fetch_scalar(z)
        lats = []
        for _ in range(5):
            t0 = time.perf_counter()
            self.fetch_scalar(z)
            lats.append(time.perf_counter() - t0)
        self.fetch_latency_s = float(np.median(lats))

    def fetch_scalar(self, x) -> float:
        return float(self._np.asarray(self._jax.device_get(x)))

    def timed(self, run_reps, scalar_of, min_window_s: float,
              start_reps: int, max_reps: int):
        """Run `run_reps(n)` (returns an object whose scalar_of(obj) is a
        jit-output scalar data-dependent on the final rep), growing n until
        the fetched window is long enough to swamp fetch latency.

        Returns (reps, window_s, block_gap_s, fetched_value).

        Windows are compile-free by construction: the recompile sentinel's
        process-wide compile counter is diffed around every window, and a
        window that caught an XLA compile (insufficient warm-up, a shape
        the warm pass missed) is discarded and re-measured ONCE with a
        stderr warning — the second recurrence is reported as-is so a
        genuinely thrashing program cannot hide.
        """
        jax = self._jax
        reps = start_reps
        remeasured = False
        while True:
            c0 = self._recompile.process_compiles()
            t0 = time.perf_counter()
            out = run_reps(reps)
            jax.block_until_ready(out)
            t_block = time.perf_counter()
            val = self.fetch_scalar(scalar_of(out))
            t_fetch = time.perf_counter()
            window = t_fetch - t0 - self.fetch_latency_s
            in_window = self._recompile.process_compiles() - c0
            if in_window and not remeasured:
                remeasured = True
                print(
                    f"bench: {in_window} XLA compile(s) landed inside a "
                    f"timed window ({reps} reps) — discarding and "
                    f"re-measuring once",
                    file=sys.stderr,
                )
                continue
            if window >= min_window_s or reps >= max_reps:
                return reps, max(window, 1e-9), t_fetch - t_block, val
            scale = max(2.0, 1.3 * min_window_s / max(window, 1e-3))
            reps = min(max_reps, int(reps * scale) + 1)


def _gate(result: dict, prefix: str, achieved: float, peak: float) -> bool:
    """False (and records an error) if achieved FLOPs are physically
    impossible — the round-2 failure mode, now a refusal instead of a
    headline."""
    if achieved > PEAK_TOLERANCE * peak:
        result[f"{prefix}_error"] = (
            f"achieved {achieved / 1e12:.1f} TFLOPs/chip exceeds "
            f"{PEAK_TOLERANCE:.2f}x chip peak {peak / 1e12:.1f} — timing or "
            f"synchronization is broken; number withheld"
        )
        return False
    return True


# --------------------------------------------------------------------------
# Run mode: the actual measurement (fresh interpreter per attempt).
# --------------------------------------------------------------------------

def _bench_calibration(clock: _Clock, peak: float, smoke: bool) -> dict:
    """bf16 matmul chain of known FLOPs inside ONE jit (fori_loop), so
    per-call dispatch overhead — ~2 ms/call through the axon tunnel, the
    entire round-2 'BERT step' — cannot contaminate it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = 256 if smoke else 4096
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)) , jnp.bfloat16)
    # scale so the chained product stays O(1) (bf16 overflow -> inf/nan
    # could let the backend shortcut; keep the numerics honest)
    b = jnp.asarray(rng.standard_normal((n, n)) / np.sqrt(n), jnp.bfloat16)

    @jax.jit
    def chain(x, reps):
        # reps is TRACED (fori_loop -> while_loop): one compile serves every
        # rep count the adaptive window picks. With a static rep count the
        # recompile landed inside the timed window and read as 0.7 TFLOPs.
        def body(_, acc):
            return jax.lax.dot(
                acc, b, preferred_element_type=jnp.float32
            ).astype(jnp.bfloat16)
        out = jax.lax.fori_loop(0, reps, body, x)
        return out[0, 0].astype(jnp.float32)

    clock.fetch_scalar(chain(a, jnp.int32(2)))  # compile + warm
    flops_per = 2.0 * n ** 3
    min_window = 0.02 if smoke else 1.0
    reps, window, gap, val = clock.timed(
        lambda r: chain(a, jnp.int32(r)), lambda s: s, min_window,
        start_reps=4 if smoke else 64, max_reps=1 << 14,
    )
    achieved = reps * flops_per / window
    out = {
        "calib_matmul_n": n,
        "calib_reps": reps,
        "calib_tflops": round(achieved / 1e12, 1),
        "calib_frac_of_peak": round(achieved / peak, 4),
        "calib_value_finite": bool(np.isfinite(val)),
        "sync_fetch_latency_ms": round(clock.fetch_latency_s * 1e3, 3),
        "sync_block_gap_ms": round(gap * 1e3, 2),
    }
    if achieved > PEAK_TOLERANCE * peak and not smoke:
        out["calib_error"] = (
            f"calibration matmul 'achieved' {achieved / 1e12:.0f} TFLOPs on a "
            f"{peak / 1e12:.0f}-TFLOP chip: the timing itself is broken on "
            f"this backend; all numbers below are untrustworthy"
        )
    return out


def _mnist_setup(strategy):
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.training.step import init_state, make_train_step

    model = BatchNormCNN()
    tx = optax.sgd(0.01)
    sample = np.zeros((GLOBAL_BATCH, 784), np.float32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_train_step(strategy, state, donate=True)
    return state, step_fn


def _bench_mnist(clock: _Clock, strategy, n_chips: int, smoke: bool) -> dict:
    """Compute-path MNIST: resident device batch (no host feed)."""
    import jax
    import numpy as np

    state, step_fn = _mnist_setup(strategy)
    rng = np.random.default_rng(0)
    images = rng.random((GLOBAL_BATCH, 784), np.float32)
    labels = rng.integers(0, 10, (GLOBAL_BATCH, 1)).astype(np.int32)
    batch_sh = strategy.batch_sharding()
    images = jax.device_put(images, batch_sh)
    labels = jax.device_put(labels, batch_sh)
    key = jax.random.key(0)

    holder = {"state": state}
    metrics = None
    for _ in range(2 if smoke else 20):  # warmup
        holder["state"], metrics = step_fn(holder["state"], (images, labels), key)
    loss_start = clock.fetch_scalar(metrics["loss"])

    def run(reps):
        m = None
        for _ in range(reps):
            holder["state"], m = step_fn(holder["state"], (images, labels), key)
        return m

    reps, window, gap, loss_end = clock.timed(
        run, lambda m: m["loss"], 0.05 if smoke else 1.5,
        start_reps=5 if smoke else 200, max_reps=20_000,
    )
    step_s = window / reps
    return {
        "mnist_images_per_sec_per_chip": round(GLOBAL_BATCH / step_s / n_chips, 1),
        "mnist_step_ms": round(step_s * 1e3, 3),
        "mnist_timed_steps": reps,
        "mnist_block_gap_ms": round(gap * 1e3, 2),
        "mnist_loss_start": round(loss_start, 5),
        "mnist_loss_end": round(loss_end, 5),
        "mnist_loss_moved": bool(abs(loss_end - loss_start) > 1e-9),
    }


def _bench_mnist_e2e(clock: _Clock, strategy, n_chips: int, smoke: bool) -> dict:
    """End-to-end MNIST: host pipeline (Dataset shuffle/repeat/batch/prefetch)
    + device_prefetch feeding the same train step — measures what the
    reference's input_fn path (mnist_keras:123-148) actually delivers,
    including host->device transfer overlap."""
    import numpy as np

    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.data.pipeline import Dataset

    state, step_fn = _mnist_setup(strategy)
    n = 1024 if smoke else 16384
    rng = np.random.default_rng(0)
    images = rng.random((n, 784), np.float32)
    labels = rng.integers(0, 10, (n, 1)).astype(np.int32)
    ds = (
        Dataset.from_tensor_slices((images, labels))
        .shuffle(n, seed=0)
        .repeat()
        .batch(GLOBAL_BATCH, drop_remainder=True)
        .prefetch(4)
    )
    # background=True: host pull + device_put in a worker thread, so a
    # link whose device_put is effectively synchronous (the axon tunnel)
    # still overlaps transfer with the device step
    feed = device_prefetch(iter(ds), strategy.mesh, buffer_size=2,
                           background=True)
    import jax

    key = jax.random.key(0)
    holder = {"state": state}
    metrics = None
    for _ in range(2 if smoke else 20):  # warmup
        holder["state"], metrics = step_fn(holder["state"], next(feed), key)
    loss_start = clock.fetch_scalar(metrics["loss"])

    def run(reps):
        m = None
        for _ in range(reps):
            holder["state"], m = step_fn(holder["state"], next(feed), key)
        return m

    reps, window, gap, loss_end = clock.timed(
        run, lambda m: m["loss"], 0.05 if smoke else 1.5,
        start_reps=5 if smoke else 200, max_reps=20_000,
    )
    step_s = window / reps
    return {
        "mnist_e2e_images_per_sec_per_chip": round(
            GLOBAL_BATCH / step_s / n_chips, 1
        ),
        "mnist_e2e_step_ms": round(step_s * 1e3, 3),
        "mnist_e2e_timed_steps": reps,
        "mnist_e2e_loss_moved": bool(abs(loss_end - loss_start) > 1e-9),
    }


def _bench_mnist_dev(clock: _Clock, strategy, n_chips: int,
                     smoke: bool) -> dict:
    """Device-resident input (data.device.device_resident_feed): the whole
    dataset staged in HBM, per-batch shuffle/gather ON DEVICE — zero
    per-step host transfer. On a co-located host this should track the
    compute-path number; through the tunnel it PROVES the e2e gap is the
    link (same step, same data-shape, transfer removed)."""
    import jax
    import numpy as np

    from tfde_tpu.data.device import device_resident_feed

    state, step_fn = _mnist_setup(strategy)
    n = 1024 if smoke else 16384
    rng = np.random.default_rng(0)
    images = rng.random((n, 784), np.float32)
    labels = rng.integers(0, 10, (n, 1)).astype(np.int32)
    feed = device_resident_feed((images, labels), strategy.mesh,
                                GLOBAL_BATCH, seed=0)
    key = jax.random.key(0)
    holder = {"state": state, "step": 0}
    metrics = None
    for _ in range(2 if smoke else 20):
        holder["state"], metrics = step_fn(
            holder["state"], feed(holder["step"]), key
        )
        holder["step"] += 1
    loss_start = clock.fetch_scalar(metrics["loss"])

    def run(reps):
        m = None
        for _ in range(reps):
            holder["state"], m = step_fn(
                holder["state"], feed(holder["step"]), key
            )
            holder["step"] += 1
        return m

    reps, window, gap, loss_end = clock.timed(
        run, lambda m: m["loss"], 0.05 if smoke else 1.5,
        start_reps=5 if smoke else 200, max_reps=20_000,
    )
    step_s = window / reps
    return {
        "mnist_dev_images_per_sec_per_chip": round(
            GLOBAL_BATCH / step_s / n_chips, 1
        ),
        "mnist_dev_step_ms": round(step_s * 1e3, 3),
        "mnist_dev_loss_moved": bool(abs(loss_end - loss_start) > 1e-9),
    }


def _bench_obs(strategy, smoke: bool) -> dict:
    """Observability self-measurement: a short Estimator-driven run with
    the goodput ledger attached — reports where the wall-clock of a real
    instrumented train loop goes (compile, data-wait, goodput) and how much
    the span accounting leaves unexplained (obs_other_fraction; the
    acceptance bar is <= 0.05 on a summary-synced run)."""
    import tempfile
    import time

    import numpy as np
    import optax

    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.observability.goodput import GoodputLedger
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    steps = 10 if smoke else 40
    n = GLOBAL_BATCH * 4
    rng = np.random.default_rng(0)
    images = rng.random((n, 784), np.float32)
    labels = rng.integers(0, 10, (n, 1)).astype(np.int32)

    def input_fn():
        def gen():
            i = 0
            while True:
                s = (i * GLOBAL_BATCH) % n
                yield (images[s:s + GLOBAL_BATCH],
                       labels[s:s + GLOBAL_BATCH])
                i += 1

        return gen()

    est = Estimator(
        model=PlainCNN(),
        optimizer=optax.sgd(0.1),
        strategy=strategy,
        config=RunConfig(
            model_dir=tempfile.mkdtemp(prefix="tfde-bench-obs-"),
            save_summary_steps=5,
            log_step_count_steps=steps,
            save_checkpoints_steps=None,  # no checkpoint I/O in the number
        ),
    )
    ledger = GoodputLedger()
    t0 = time.perf_counter()
    est.train(input_fn, steps)
    wall = time.perf_counter() - t0
    est.close()
    rep = ledger.report(wall)
    # memory + compile columns from the memwatch ledger / recompile
    # sentinel the lifecycle wires around the train step
    from tfde_tpu.observability import memwatch, recompile

    pm = memwatch.programs().get("train_step")
    sites = recompile.sites().get("train_step", {})
    return {
        "obs_steps": rep["steps"],
        "obs_compile_seconds": round(rep["seconds"]["compile"], 3),
        "obs_compile_count": int(sites.get("misses", 0)),
        "obs_peak_hbm_bytes": int(pm.peak_bytes) if pm else 0,
        "obs_data_wait_fraction": round(rep["fractions"]["data_wait"], 4),
        "obs_goodput": round(rep["goodput"], 4),
        "obs_other_fraction": round(rep["fractions"]["other"], 4),
        "obs_mean_step_ms": round(rep["mean_step_seconds"] * 1e3, 3),
        "obs_sentry_overhead_pct": _sentry_overhead_pct(
            strategy, images, labels, smoke
        ),
    }


def _sentry_overhead_pct(strategy, images, labels, smoke: bool) -> float:
    """Per-step cost of the fused numerics sentry (observability/sentry.py)
    relative to the identical step without it — same model, same strategy,
    min-of-repeats on both sides so scheduler noise cancels. The sentry is
    a handful of scalar ops fused into an already-compiled step (no extra
    dispatch, no host sync), so the acceptance bar is < 2%."""
    import time

    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.observability import sentry as sentry_lib
    from tfde_tpu.training.step import init_state, make_train_step

    batch = (images[:GLOBAL_BATCH], labels[:GLOBAL_BATCH])
    key = jax.random.key(0)
    reps = 3 if smoke else 5
    k = 10 if smoke else 40

    def per_step_s(sentry_cfg) -> float:
        st, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy,
                           np.zeros_like(batch[0]))
        step_fn = make_train_step(strategy, st, sentry=sentry_cfg)
        sst = sentry_lib.init_state() if sentry_cfg is not None else None
        best = float("inf")
        m = None
        for r in range(reps + 1):  # rep 0 = compile warmup, untimed
            t0 = time.perf_counter()
            for _ in range(k):
                if sst is not None:
                    st, m, sst = step_fn(st, batch, key, sst)
                else:
                    st, m = step_fn(st, batch, key)
            jax.block_until_ready(m)
            if r > 0:
                best = min(best, time.perf_counter() - t0)
        return best / k

    plain = per_step_s(None)
    fused = per_step_s(sentry_lib.SentryConfig())
    return round(max(0.0, (fused - plain) / plain * 100.0), 3)


def _bench_link(clock: _Clock, smoke: bool) -> dict:
    """Host->device transfer microbenchmark — the attribution control for
    the e2e gap (VERDICT r3 #3). Measures the per-transfer latency floor
    (4-byte put), the MNIST batch payload's per-batch cost, and streaming
    bandwidth (16 MiB put). On a co-located host, link_batch_ms is tens of
    microseconds and e2e==compute; through the tunnel it is the gap. The
    derived fields land in the cumulative result via run_mode."""
    import jax
    import numpy as np

    rng = np.random.default_rng(0)

    def put_time_s(arr, budget):
        def run(reps):
            out = None
            for _ in range(reps):
                out = jax.device_put(arr)
            return out

        reps, window, _gap, _ = clock.timed(
            # a device-side scalar slice: the fetch must move 4 bytes, not
            # the whole buffer (a full device_get inside the window would
            # inflate link_batch_ms on exactly the links this measures)
            run, lambda o: o.ravel()[0],
            budget, start_reps=3 if smoke else 20, max_reps=5000,
        )
        return window / reps

    budget = 0.05 if smoke else 1.0
    lat_s = put_time_s(np.ones((1,), np.float32), budget)
    batch = rng.random((GLOBAL_BATCH, 784), np.float32)
    batch_s = put_time_s(batch, budget)
    big = rng.random((1 << 22,), np.float32)  # 16 MiB
    big_s = put_time_s(big, budget)
    return {
        "link_latency_ms": round(lat_s * 1e3, 3),
        "link_batch_ms": round(batch_s * 1e3, 3),
        "link_batch_bytes": int(batch.nbytes),
        "link_bandwidth_mb_s": round(
            big.nbytes / max(big_s - lat_s, 1e-9) / 1e6, 1
        ),
    }


def _bench_bert_mfu(clock: _Clock, strategy, n_chips: int, peak: float,
                    smoke: bool, per_chip_batch: int = 16,
                    prefix: str = "bert", fused_qkv: bool = False) -> dict:
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.bert import Bert, BertBase
    from tfde_tpu.ops import losses
    from tfde_tpu.training.step import init_state, make_custom_train_step

    if smoke:  # CPU-sized config: validates the path, not a real number
        seq, per_chip_batch = 128, 2
        model = Bert(vocab_size=1024, hidden_size=128, depth=2, num_heads=4,
                     mlp_dim=256, dropout_rate=0.0, pad_vocab=True,
                     fused_qkv=fused_qkv)
        warmup = 1
    else:
        seq = 512
        model = BertBase(dropout_rate=0.0, pad_vocab=True,
                         fused_qkv=fused_qkv)
        warmup = 3
    global_batch = per_chip_batch * n_chips
    vocab = model.padded_vocab

    def loss_fn(state, params, batch, rng):
        input_ids, labels = batch
        logits = state.apply_fn({"params": params}, input_ids, train=True,
                                rngs={"dropout": rng})
        loss, acc = losses.masked_lm_loss(logits, labels)
        return loss, {"mlm_accuracy": acc}

    tx = optax.adamw(1e-4)
    sample = np.zeros((global_batch, seq), np.int32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_custom_train_step(strategy, state, loss_fn)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size, (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -100, np.int32)
    labels[:, ::7] = ids[:, ::7]  # ~15% positions predicted
    key = jax.random.key(0)

    holder = {"state": state}
    metrics = None
    for _ in range(warmup):
        holder["state"], metrics = step_fn(holder["state"], (ids, labels), key)
    loss_start = clock.fetch_scalar(metrics["loss"])

    def run(reps):
        m = None
        for _ in range(reps):
            holder["state"], m = step_fn(holder["state"], (ids, labels), key)
        return m

    reps, window, gap, loss_end = clock.timed(
        run, lambda m: m["loss"], 0.05 if smoke else 2.0,
        start_reps=2 if smoke else 10, max_reps=2_000,
    )
    step_s = window / reps

    out = {
        f"{prefix}_step_ms": round(step_s * 1e3, 2),
        f"{prefix}_timed_steps": reps,
        f"{prefix}_block_gap_ms": round(gap * 1e3, 2),
        f"{prefix}_loss_moved": bool(abs(loss_end - loss_start) > 1e-9),
        f"{prefix}_per_chip_batch": per_chip_batch,
    }
    if prefix == "bert":
        # Diagnostic (VERDICT r2 next-steps 1b): a short per-step-synced
        # window — each step's loss fetched to host before the next starts.
        # Dispatch overhead + fetch latency make this an upper bound on step
        # time; the primary (amortized-fetch) number must lie between
        # compute truth and this bound.
        t0 = time.perf_counter()
        synced_reps = 2 if smoke else 5
        for _ in range(synced_reps):
            holder["state"], m = step_fn(holder["state"], (ids, labels), key)
            clock.fetch_scalar(m["loss"])
        out["bert_step_ms_synced"] = round(
            (time.perf_counter() - t0) / synced_reps * 1e3, 2
        )

    tokens_per_step = global_batch * seq
    flops_per_token = bert_train_flops_per_token(
        model.hidden_size, model.mlp_dim, model.depth, seq, vocab
    )
    achieved = tokens_per_step * flops_per_token / step_s / n_chips
    if _gate(out, prefix, achieved, peak):
        out.update({
            f"{prefix}_mfu": round(achieved / peak, 4),
            f"{prefix}_tokens_per_sec_per_chip": round(
                tokens_per_step / step_s / n_chips, 1
            ),
            f"{prefix}_achieved_tflops_per_chip": round(achieved / 1e12, 2),
        })
    return out


def _bench_comms(n_chips: int, smoke: bool) -> dict:
    """Quantized gradient exchange (parallel/comms.py): analytic wire bytes
    for the bert config plus a measured fp32-vs-int8 A/B on a CPU mesh.

    Two layers because they answer different questions:

    - **Analytic bytes** come from the real BertBase parameter shapes
      (`comms.comm_bytes`, the same accounting behind the `comm/*` gauges)
      — the per-step gradient traffic the int8 transport removes. This is
      a cost model, not a measurement, so it works on any backend; the
      acceptance bar is `comm_bytes_per_step_int8 <= 0.3 x fp32`.
    - **The A/B run** (step time + loss-trajectory parity vs the
      uncompressed oracle) happens in a `--comms-child` subprocess forced
      to an 8-way CPU mesh, so the exchange, the error feedback, and the
      shard_map path execute for real even when the parent process sees a
      single device (plain `bench.py` on a laptop) or a TPU. Smoke-sized
      bert shapes keep the child ~seconds; on CPU the int8 path is
      *slower* (quantize/dequantize compute with zero network to save) —
      the number validates the path, the byte ratio is the perf claim.
    """
    import jax
    import numpy as np

    from tfde_tpu.models.bert import BertBase
    from tfde_tpu.parallel import comms as comms_lib

    # -- analytic: real BertBase shapes, no device work -----------------------
    model = BertBase(dropout_rate=0.0, pad_vocab=True)
    sample = np.zeros((2, 8), np.int32)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), sample, train=False)
    )["params"]
    cfg = comms_lib.CommsConfig(transport="int8")
    nshards = n_chips if n_chips >= 2 else 8
    b = comms_lib.comm_bytes(abstract, cfg, nshards)
    out = {
        "comm_bytes_per_step_fp32": int(b["fp32"]),
        "comm_bytes_per_step_int8": int(b["int8"]),
        "comms_ratio": round(b["ratio"], 4),
        "comms_analytic_nshards": nshards,
        "comms_compressed_elems": int(b["compressed_elems"]),
        "comms_fp32_elems": int(b["fp32_elems"]),
    }

    # -- measured A/B: fresh interpreter pinned to an 8-way CPU mesh ----------
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--comms-child"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        child = _last_json(proc.stdout)
        if child is None:
            out["comms_child_error"] = (proc.stderr or "no output")[-400:]
        else:
            out.update(child)
    except subprocess.TimeoutExpired:
        out["comms_child_error"] = "comms child timed out"
    return out


def comms_child_mode() -> None:
    """`bench.py --comms-child`: the fp32-vs-int8 A/B on the 8-way CPU mesh
    the parent pinned via env. Prints one JSON line."""
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.bert import Bert
    from tfde_tpu.ops import losses
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    seq, per_chip_batch, steps = 128, 2, 10
    model = Bert(vocab_size=1024, hidden_size=128, depth=2, num_heads=4,
                 mlp_dim=256, dropout_rate=0.0, pad_vocab=True)
    n_chips = len(jax.local_devices())
    global_batch = per_chip_batch * n_chips

    def loss_fn(state, params, batch, rng):
        input_ids, labels = batch
        logits = state.apply_fn({"params": params}, input_ids, train=True,
                                rngs={"dropout": rng})
        loss, acc = losses.masked_lm_loss(logits, labels)
        return loss, {"mlm_accuracy": acc}

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size,
                       (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -100, np.int32)
    labels[:, ::7] = ids[:, ::7]
    key = jax.random.key(0)

    def trajectory(transport):
        strategy = MirroredStrategy(grad_transport=transport)
        state, _ = init_state(model, optax.adamw(1e-4), strategy, ids)
        step_fn = make_custom_train_step(strategy, state, loss_fn,
                                         comms=transport)
        state, m = step_fn(state, (ids, labels), key)  # compile + step 0
        jax.block_until_ready(m["loss"])
        t0 = time.perf_counter()
        traj = [float(m["loss"])]
        for _ in range(steps - 1):
            state, m = step_fn(state, (ids, labels), key)
            traj.append(float(m["loss"]))
        dt = (time.perf_counter() - t0) / (steps - 1)
        return traj, dt

    fp32_traj, fp32_dt = trajectory("fp32")
    int8_traj, int8_dt = trajectory("int8")
    max_diff = max(abs(a - b) for a, b in zip(fp32_traj, int8_traj))
    # tolerance: the loss is O(ln 1024)~7 at init; a transport that tracks
    # the oracle stays within a few percent over 10 steps, a broken one
    # (no error feedback / wrong scales) diverges by whole units
    scale = max(1.0, abs(fp32_traj[0]))
    print(json.dumps({
        "comms_step_ms_fp32": round(fp32_dt * 1e3, 2),
        "comms_step_ms_int8": round(int8_dt * 1e3, 2),
        "comms_step_delta_pct": round(
            (int8_dt - fp32_dt) / fp32_dt * 100.0, 1),
        "comms_loss_moved": bool(
            abs(int8_traj[-1] - int8_traj[0]) > 1e-9),
        "comms_loss_max_diff": round(max_diff, 5),
        "comms_parity_ok": bool(max_diff < 0.05 * scale),
        "comms_child_n_chips": n_chips,
    }))


def _bench_zero(n_chips: int, smoke: bool) -> dict:
    """ZeRO weight-update sharding (parallel/zero.py): analytic optimizer
    memory for the real BERT-base shapes plus a measured replicated-vs-
    sharded A/B on a CPU mesh.

    Same two-layer shape as `_bench_comms`:

    - **Analytic bytes** price Adam's mu/nu for BertBase under both
      layouts (`zero.state_bytes`, the accounting behind the
      `opt/state_bytes` gauge): replicated ~= 2 x params x 4B per device,
      sharded ~= 1/N of that (quantum padding keeps it off the exact 1/N).
      The acceptance bar is sharded <= 1/4 x replicated on the 8-way mesh.
    - **The A/B run** happens in a `--zero-child` subprocess forced to an
      8-way CPU mesh: step time + measured per-device opt-state bytes +
      loss parity for all four transport x sharding combos. fp32 x shard
      must match the replicated fp32 oracle BITWISE; int8 x shard within
      the int8 tolerance. On CPU the gather/scatter is compute, not
      network, so step-time deltas validate the path rather than the perf
      claim — the byte ratio is the claim.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tfde_tpu.models.bert import BertBase
    from tfde_tpu.parallel import comms as comms_lib
    from tfde_tpu.parallel import zero as zero_lib

    model = BertBase(dropout_rate=0.0, pad_vocab=True)
    sample = np.zeros((2, 8), np.int32)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), sample, train=False)
    )["params"]
    nshards = n_chips if n_chips >= 2 else 8
    tx = optax.adam(1e-3)
    layout = zero_lib.build_layout(abstract, comms_lib.CommsConfig(), nshards)
    rep_bytes = zero_lib.state_bytes(jax.eval_shape(tx.init, abstract))
    sh_bytes = zero_lib.state_bytes(
        jax.eval_shape(lambda p: tx.init(zero_lib.pack_params(p, layout)),
                       abstract),
        layout,
    )
    out = {
        "zero_opt_bytes_per_device_replicated": int(rep_bytes),
        "zero_opt_bytes_per_device_sharded": int(sh_bytes),
        "zero_opt_bytes_ratio": round(sh_bytes / rep_bytes, 4),
        "zero_analytic_nshards": nshards,
        "zero_param_gather_bytes": int(zero_lib.param_gather_bytes(layout)),
    }

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env.pop(zero_lib.ENV_OPT_SHARDING, None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--zero-child"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        child = _last_json(proc.stdout)
        if child is None:
            out["zero_child_error"] = (proc.stderr or "no output")[-400:]
        else:
            out.update(child)
    except subprocess.TimeoutExpired:
        out["zero_child_error"] = "zero child timed out"
    return out


def zero_child_mode() -> None:
    """`bench.py --zero-child`: the replicated-vs-sharded x fp32-vs-int8
    A/B on the 8-way CPU mesh the parent pinned via env. Prints one JSON
    line."""
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.bert import Bert
    from tfde_tpu.ops import losses
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.parallel import zero as zero_lib
    from tfde_tpu.training.step import init_state, make_custom_train_step

    seq, per_chip_batch, steps = 128, 2, 8
    model = Bert(vocab_size=1024, hidden_size=128, depth=2, num_heads=4,
                 mlp_dim=256, dropout_rate=0.0, pad_vocab=True)
    n_chips = len(jax.local_devices())
    global_batch = per_chip_batch * n_chips

    def loss_fn(state, params, batch, rng):
        input_ids, labels = batch
        logits = state.apply_fn({"params": params}, input_ids, train=True,
                                rngs={"dropout": rng})
        loss, acc = losses.masked_lm_loss(logits, labels)
        return loss, {"mlm_accuracy": acc}

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.vocab_size,
                       (global_batch, seq)).astype(np.int32)
    labels = np.full((global_batch, seq), -100, np.int32)
    labels[:, ::7] = ids[:, ::7]
    key = jax.random.key(0)

    from tfde_tpu.observability import memwatch, recompile

    recompile.install()

    def trajectory(mode, transport):
        strategy = MirroredStrategy(grad_transport=transport,
                                    opt_sharding=mode)
        state, _ = init_state(model, optax.adamw(1e-4), strategy, ids)
        step_fn = make_custom_train_step(strategy, state, loss_fn)
        opt_analytic = zero_lib.state_bytes(state.opt_state,
                                            state.opt_layout)
        c0 = recompile.process_compiles()
        s0 = recompile.seconds_total()
        state, m = step_fn(state, (ids, labels), key)  # compile + step 0
        jax.block_until_ready(m["loss"])
        compiles = recompile.process_compiles() - c0
        csecs = recompile.seconds_total() - s0
        # MEASURED per-device bytes of the arrays XLA committed for the
        # post-step opt state — the number the analytic accounting claims
        opt_measured = zero_lib.measured_state_bytes(state.opt_state)
        pm = memwatch.register(f"zero/step_{mode}_{transport}", step_fn,
                               args=(state, (ids, labels), key),
                               donated=None)
        peak = int(pm.peak_bytes) if pm is not None else 0
        t0 = time.perf_counter()
        traj = [float(m["loss"])]
        for _ in range(steps - 1):
            state, m = step_fn(state, (ids, labels), key)
            traj.append(float(m["loss"]))
        dt = (time.perf_counter() - t0) / (steps - 1)
        return traj, dt, opt_analytic, opt_measured, compiles, csecs, peak

    runs = {
        (mode, transport): trajectory(mode, transport)
        for mode in ("replicated", "shard")
        for transport in ("fp32", "int8")
    }
    oracle = runs[("replicated", "fp32")][0]

    def max_diff(mode, transport):
        return max(abs(a - b)
                   for a, b in zip(oracle, runs[(mode, transport)][0]))

    scale = max(1.0, abs(oracle[0]))
    fp32_rep_dt = runs[("replicated", "fp32")][1]
    fp32_sh_dt = runs[("shard", "fp32")][1]
    rep_run = runs[("replicated", "fp32")]
    sh_run = runs[("shard", "fp32")]
    measured_rep, measured_sh = rep_run[3], sh_run[3]
    print(json.dumps({
        "zero_step_ms_fp32_replicated": round(fp32_rep_dt * 1e3, 2),
        "zero_step_ms_fp32_sharded": round(fp32_sh_dt * 1e3, 2),
        "zero_step_ms_int8_replicated": round(
            runs[("replicated", "int8")][1] * 1e3, 2),
        "zero_step_ms_int8_sharded": round(
            runs[("shard", "int8")][1] * 1e3, 2),
        "zero_step_delta_pct": round(
            (fp32_sh_dt - fp32_rep_dt) / fp32_rep_dt * 100.0, 1),
        # measured = per-device bytes of the committed arrays (memwatch
        # shard walk); analytic = the shape-derived accounting. The ratio
        # confirms the ~Nx replicated->sharded saving with XLA's own
        # allocations, and measured-vs-analytic agreement (within padding)
        # is the cross-check tests/test_memwatch.py pins
        "zero_measured_opt_bytes_replicated": int(measured_rep),
        "zero_measured_opt_bytes_sharded": int(measured_sh),
        "zero_analytic_opt_bytes_replicated": int(rep_run[2]),
        "zero_analytic_opt_bytes_sharded": int(sh_run[2]),
        "zero_measured_bytes_ratio": round(
            measured_sh / max(measured_rep, 1.0), 4),
        "zero_peak_hbm_bytes": int(max(r[6] for r in runs.values())),
        "zero_compile_count": int(sum(r[4] for r in runs.values())),
        "zero_compile_seconds": round(
            sum(r[5] for r in runs.values()), 3),
        # fp32 x shard is bitwise vs the oracle for plain-mean losses
        # (tests/test_zero.py pins that); the masked-LM loss here
        # normalizes by non-power-of-two token counts, so the local-sum
        # decomposition rounds differently — tight, not bitwise
        "zero_loss_max_diff_fp32": round(max_diff("shard", "fp32"), 7),
        "zero_parity_ok_fp32": bool(max_diff("shard", "fp32") < 0.01 * scale),
        "zero_loss_max_diff_int8": round(max_diff("shard", "int8"), 5),
        "zero_parity_ok_int8": bool(
            max_diff("shard", "int8") < 0.05 * scale),
        "zero_child_n_chips": n_chips,
    }))


def _bench_flash(clock: _Clock, smoke: bool) -> dict:
    """Hardware qualification of the Pallas flash-attention kernel
    (VERDICT r2 next-steps 4): numerics vs the reference einsum, then
    fwd+bwd timing at S=2048. On CPU/smoke, interpret-mode numerics only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.ops.attention import reference_attention
    from tfde_tpu.ops.flash_attention import flash_attention

    interpret = jax.default_backend() != "tpu"

    def ref_loss(q, k, v):
        return reference_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    def flash_loss(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=interpret).astype(
            jnp.float32).sum()

    def make_qkv(b, s, h, d):
        rng = np.random.default_rng(0)
        return tuple(
            jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
            for _ in range(3)
        )

    # numerics first (small enough for either backend)
    b, s, h, d = (1, 256, 2, 64) if (smoke or interpret) else (2, 2048, 4, 64)
    q, k, v = make_qkv(b, s, h, d)
    ref_fwd = jax.jit(lambda q, k, v: reference_attention(q, k, v, causal=True))
    fl_fwd = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=interpret)
    )
    o_ref = ref_fwd(q, k, v)
    o_fl = fl_fwd(q, k, v)
    err = float(
        jnp.max(jnp.abs(o_ref.astype(jnp.float32) - o_fl.astype(jnp.float32)))
    )
    scale_ref = float(jnp.max(jnp.abs(o_ref.astype(jnp.float32))))
    ok = err <= 2e-2 * max(scale_ref, 1.0)  # bf16 tolerance
    out = {
        "flash_max_abs_err": round(err, 5),
        "flash_numerics_ok": bool(ok),
        "flash_interpret": interpret,
    }
    if interpret or smoke:
        return out  # interpret-mode timing is meaningless

    # fwd+bwd timing across the length sweep (token count held constant):
    # XLA's fused attention is strong at moderate S; the flash win is the
    # long-S regime where the O(S^2) score tensor stops fitting.
    ref_g = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))
    fl_g = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))

    # backward numerics on hardware: the default flash backward (blockwise,
    # TFDE_FLASH_BWD) vs autodiff through the reference einsum
    gr = ref_g(q, k, v)
    gf = fl_g(q, k, v)
    gerr = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(gr, gf)
    )
    gscale = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32)))) for a in gr
    )
    out["flash_grad_max_abs_err"] = round(gerr, 5)
    out["flash_grad_ok"] = bool(gerr <= 5e-2 * max(gscale, 1.0))

    def time_impl(g, q, k, v):
        def run(reps):
            dq = None
            for _ in range(reps):
                dq, _, _ = g(q, k, v)
            return dq
        reps, window, _, _ = clock.timed(
            run, lambda dq: dq[0, 0, 0, 0].astype(jnp.float32), 1.0,
            start_reps=5, max_reps=5_000,
        )
        return window / reps

    def ab_pair(g_ref, g_fl, q, k, v):
        """Warm both compiled grads, then time each — the ONE A/B
        protocol for causal and non-causal sweeps."""
        clock.fetch_scalar(g_ref(q, k, v)[0][0, 0, 0, 0].astype(jnp.float32))
        clock.fetch_scalar(g_fl(q, k, v)[0][0, 0, 0, 0].astype(jnp.float32))
        return time_impl(g_ref, q, k, v), time_impl(g_fl, q, k, v)

    # S=1024 joins the sweep for the causal dispatch threshold decision
    # (ops/attention.py dispatches causal at S>=2048 from the 128-tile
    # A/Bs; the 512-tile auto default needs the 1024 point re-measured)
    for b, s in ((8, 1024), (4, 2048), (2, 4096), (1, 8192)):
        try:
            t_ref, t_fl = ab_pair(ref_g, fl_g, *make_qkv(b, s, 12, 64))
            out[f"flash_speedup_s{s}"] = round(t_ref / t_fl, 3)
            out[f"flash_ref_ms_s{s}"] = round(t_ref * 1e3, 3)
            out[f"flash_ms_s{s}"] = round(t_fl * 1e3, 3)
        except Exception as e:
            out[f"flash_error_s{s}"] = f"{type(e).__name__}: {e}"[:200]
    speedups = [v for k_, v in out.items() if k_.startswith("flash_speedup_s")]
    if speedups:
        out["flash_speedup"] = max(speedups)

    # non-causal A/B at the auto tile size: at 128 tiles this measured
    # 0.87-0.97x (dispatch threshold stayed memory-motivated at S>=4096);
    # the 512-tile default may flip it — this measurement decides whether
    # the non-causal threshold drops (round-5 queue, BASELINE.md). ONE
    # warm+time protocol (ab_pair) serves the causal sweep above and this,
    # so the two stay comparable.
    def nc_ref_loss(q, k, v):
        return reference_attention(q, k, v).astype(jnp.float32).sum()

    def nc_flash_loss(q, k, v):
        return flash_attention(q, k, v, interpret=interpret).astype(
            jnp.float32).sum()

    b, s = 2, 4096
    try:
        t_ref, t_fl = ab_pair(
            jax.jit(jax.grad(nc_ref_loss, argnums=(0, 1, 2))),
            jax.jit(jax.grad(nc_flash_loss, argnums=(0, 1, 2))),
            *make_qkv(b, s, 12, 64),
        )
        out[f"flash_nc_speedup_s{s}"] = round(t_ref / t_fl, 3)
    except Exception as e:
        out[f"flash_nc_error_s{s}"] = f"{type(e).__name__}: {e}"[:200]
    return out


def gpt_train_flops_per_token(hidden: int, mlp: int, depth: int,
                              seq: int, vocab: int, window=None,
                              window_pattern: str = "all") -> float:
    """Analytic matmul FLOPs per token for one causal-LM fwd+bwd step: qkvo
    + mlp per-layer terms as in BERT; attention matmuls credited by the
    EXACT in-band count from ops/roofline.py — (S+1)/2 mean attended keys
    for plain causal (the flash kernels skip future tiles in forward AND
    backward, so counting full bidirectional attention would inflate MFU
    by ~20% at S=4096; the old half-count 2*S*H was ~1/(2n) conservative
    on the diagonal, now exact), the triangle-plus-band mean for a
    sliding `window`, and the per-layer average when `window_pattern=
    'alternate'` windows only even layers (gpt_long_win / Gemma-2). Plus
    the tied LM head 2HV; training = 3x forward."""
    from tfde_tpu.ops.roofline import stacked_attention_flops_per_token

    per_layer = 8 * hidden * hidden + 4 * hidden * mlp
    attn = stacked_attention_flops_per_token(
        hidden, seq, depth, causal=True, window=window,
        window_pattern=window_pattern,
    )
    return 3.0 * (depth * per_layer + attn + 2 * hidden * vocab)


def _bench_gpt_long(clock: _Clock, strategy, n_chips: int, peak: float,
                    smoke: bool, prefix: str = "gpt_long") -> dict:
    """GPT training MFU configs on the flash-attention path:

    - ``gpt_long``: GPT-2-small at S=4096, per-chip batch 1 — the
      long-context regime where attention auto-dispatches to the Pallas
      flash kernel (ops/attention.py). Capability measured, not just
      qualified.
    - ``gpt_medium``: GPT-2-medium (h=1024, 24 layers) at S=1024, batch 8,
      attn_impl='flash' explicitly (below the auto threshold) — the
      model-width axis of the MFU story: the BERT roofline (BASELINE.md)
      attributes the 42%-vs-73% gap to h=768 GEMM efficiency, and this
      config measures what wider GEMMs recover (36.6% at first light vs
      20% for gpt_long: width + shorter S both lift it).
    - ``gpt_long_win``: the Gemma-2-shaped variant of gpt_long — sliding
      window 1024 with window_pattern='alternate' plus attention logit
      softcap 50.0, all running through the fused flash kernels (forward
      AND backward skip out-of-band tiles). MFU is reported against the
      corrected windowed-flop model (gpt_train_flops_per_token with
      window/pattern — ops/roofline.py credits banded layers their true
      in-band work), so the number is comparable to gpt_long instead of
      flattered by phantom full-causal flops.
    """
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.gpt import GPT, next_token_loss
    from tfde_tpu.training.step import init_state, make_custom_train_step

    medium = prefix == "gpt_medium"
    windowed = prefix == "gpt_long_win"
    if smoke:
        import jax.numpy as jnp

        seq, per_chip_batch = 128, 1
        model = GPT(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                    mlp_dim=128, max_position=seq, dtype=jnp.float32,
                    attn_impl="flash" if medium else "auto",
                    # smoke must cover the knob composition the full
                    # configs ship with: gpt_long4's remat, gpt_long_win's
                    # alternating window + softcap
                    sliding_window=64 if windowed else None,
                    sliding_window_pattern="alternate" if windowed
                    else "all",
                    attn_logit_cap=50.0 if windowed else None,
                    remat="dots" if prefix == "gpt_long4" else False)
        warmup = 1
    elif windowed:
        # gpt_long with the Gemma-2 attention deltas: even layers banded at
        # 1024, odd layers full causal, logits softcapped at 50 — the
        # whole stack stays on the fused flash path (auto-dispatch at
        # S=4096), and MFU below uses the windowed-flop model
        seq, per_chip_batch = 4096, 1
        model = GPT(max_position=seq, dropout_rate=0.0,  # GPT-2 small dims
                    sliding_window=1024,
                    sliding_window_pattern="alternate",
                    attn_logit_cap=50.0)
        warmup = 2
    elif medium:
        seq, per_chip_batch = 1024, 8
        model = GPT(hidden_size=1024, depth=24, num_heads=16, mlp_dim=4096,
                    max_position=seq, dropout_rate=0.0, attn_impl="flash")
        warmup = 2
    else:
        # gpt_long2 (b=2) / gpt_long4 (b=4 + remat='dots'): the round-5
        # batch-lever ladder — b=1 measured ~20% MFU after the 512-tile
        # flip; more tokens/step lifts the h=768 GEMM efficiency term, and
        # at b=4 the dots-only remat trades recompute FLOPs for the
        # activation memory that would otherwise bound the batch
        seq = 4096
        per_chip_batch = {"gpt_long2": 2, "gpt_long4": 4}.get(prefix, 1)
        model = GPT(max_position=seq, dropout_rate=0.0,  # GPT-2 small dims
                    remat="dots" if prefix == "gpt_long4" else False)
        warmup = 2
    global_batch = per_chip_batch * n_chips

    tx = optax.adamw(1e-4)
    sample = np.zeros((global_batch, seq), np.int32)
    state, _ = init_state(model, tx, strategy, sample, seed=0)
    step_fn = make_custom_train_step(strategy, state, next_token_loss)

    rng = np.random.default_rng(0)
    toks = rng.integers(0, model.vocab_size, (global_batch, seq)).astype(np.int32)
    key = jax.random.key(0)
    holder = {"state": state}
    metrics = None
    for _ in range(warmup):
        holder["state"], metrics = step_fn(holder["state"], (toks,), key)
    loss_start = clock.fetch_scalar(metrics["loss"])

    def run(reps):
        m = None
        for _ in range(reps):
            holder["state"], m = step_fn(holder["state"], (toks,), key)
        return m

    reps, window, gap, loss_end = clock.timed(
        run, lambda m: m["loss"], 0.05 if smoke else 2.0,
        start_reps=2 if smoke else 5, max_reps=500,
    )
    step_s = window / reps
    tokens_per_step = global_batch * seq
    flops_per_token = gpt_train_flops_per_token(
        model.hidden_size, model.mlp_dim, model.depth, seq,
        model.vocab_size, window=model.sliding_window,
        window_pattern=model.sliding_window_pattern,
    )
    achieved = tokens_per_step * flops_per_token / step_s / n_chips
    out = {
        f"{prefix}_seq": seq,
        f"{prefix}_step_ms": round(step_s * 1e3, 2),
        f"{prefix}_loss_moved": bool(abs(loss_end - loss_start) > 1e-9),
    }
    if model.sliding_window is not None:
        out[f"{prefix}_window"] = model.sliding_window
        out[f"{prefix}_window_pattern"] = model.sliding_window_pattern
    if _gate(out, prefix, achieved, peak):
        out.update({
            f"{prefix}_mfu": round(achieved / peak, 4),
            f"{prefix}_tokens_per_sec_per_chip": round(
                tokens_per_step / step_s / n_chips, 1
            ),
            f"{prefix}_achieved_tflops_per_chip": round(achieved / 1e12, 2),
        })
    return out


def moe_gpt_train_flops_per_token(hidden: int, mlp: int, depth: int,
                                  seq: int, vocab: int, num_experts: int,
                                  experts_per_token: int,
                                  moe_every: int) -> float:
    """Analytic *useful* matmul FLOPs per token for a routed causal-LM
    fwd+bwd step: the gpt formula with the MLP term split — dense layers
    keep 4HF, MoE layers cost k*4HF (each token through k experts) plus
    the router GEMM 2HE. The dispatch/combine one-hot einsums are real
    MXU work but move no information per FLOP, so they are NOT counted:
    `moe_mfu` is useful-FLOP MFU and understates hardware utilization —
    the honest direction (attention credited at the exact in-band count
    from ops/roofline.py, same as gpt_train_flops_per_token)."""
    from tfde_tpu.ops.roofline import attention_flops_per_token

    n_moe = depth // moe_every
    n_dense = depth - n_moe
    attn_qkvo = (8 * hidden * hidden
                 + attention_flops_per_token(hidden, seq, causal=True))
    dense_layer = attn_qkvo + 4 * hidden * mlp
    moe_layer = (attn_qkvo + experts_per_token * 4 * hidden * mlp
                 + 2 * hidden * num_experts)
    return 3.0 * (n_dense * dense_layer + n_moe * moe_layer
                  + 2 * hidden * vocab)


def _bench_moe(clock: _Clock, strategy, n_chips: int, peak: float,
               smoke: bool) -> dict:
    """Routed-MoE training on hardware (VERDICT r4 weak #5: the only model
    family with no chip number). GPT-2-small dims with every 2nd MLP
    routed (8 experts, top-2, ST-MoE z-loss) at S=1024, per-chip batch 8,
    vs its dense-FLOP-matched twin: the twin's mlp_dim is scaled so total
    MLP GEMM FLOPs match (12 dense units vs 6 + 6*k units), isolating the
    routing machinery's overhead at equal useful work. Reports moe_mfu
    (useful-FLOP), the step-time ratio, and router-balance evidence: the
    load-balance aux summed over layers (n_moe * weight — the emitted
    moe_aux_balanced_value — = perfectly balanced top-1 routing) and
    z-loss at the start and end of the timed window."""
    import jax
    import numpy as np
    import optax

    from tfde_tpu.models.gpt import GPT, next_token_loss
    from tfde_tpu.training.step import init_state, make_custom_train_step

    e, k, every = 8, 2, 2
    if smoke:
        import jax.numpy as jnp

        seq, per_chip_batch = 64, 8
        dims = dict(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                    max_position=seq, dtype=jnp.float32)
        mlp, warmup = 128, 1
    else:
        seq, per_chip_batch = 1024, 8
        dims = dict(hidden_size=768, depth=12, num_heads=12,
                    max_position=seq, dropout_rate=0.0)
        mlp, warmup = 3072, 2
    depth = dims["depth"]
    n_moe = depth // every
    # FLOP-matched dense twin: depth*F_twin = (depth-n_moe)*F + n_moe*k*F
    twin_mlp = mlp * ((depth - n_moe) + n_moe * k) // depth
    global_batch = per_chip_batch * n_chips

    def build(model):
        tx = optax.adamw(1e-4)
        sample = np.zeros((global_batch, seq), np.int32)
        state, _ = init_state(model, tx, strategy, sample, seed=0)
        return state, make_custom_train_step(strategy, state, next_token_loss)

    def timed_steps(state, step_fn, toks, key):
        holder = {"state": state}
        metrics = None
        for _ in range(warmup):
            holder["state"], metrics = step_fn(holder["state"], (toks,), key)
        first = {kk: clock.fetch_scalar(v) for kk, v in metrics.items()
                 if kk in ("loss", "moe_aux", "moe_z")}

        def run(reps):
            m = None
            for _ in range(reps):
                holder["state"], m = step_fn(holder["state"], (toks,), key)
            holder["last"] = m
            return m

        reps, window, _gap, loss_end = clock.timed(
            run, lambda m: m["loss"], 0.05 if smoke else 2.0,
            start_reps=2 if smoke else 5, max_reps=500,
        )
        last = {kk: clock.fetch_scalar(v)
                for kk, v in holder["last"].items()
                if kk in ("moe_aux", "moe_z")}
        return window / reps, first, loss_end, last

    rng = np.random.default_rng(0)
    moe_model = GPT(mlp_dim=mlp, num_experts=e, moe_every=every,
                    router_z_loss_weight=1e-3, **dims)
    toks = rng.integers(0, moe_model.vocab_size,
                        (global_batch, seq)).astype(np.int32)
    key = jax.random.key(0)
    state, step_fn = build(moe_model)
    step_s, first, loss_end, last = timed_steps(state, step_fn, toks, key)

    tokens_per_step = global_batch * seq
    flops_per_token = moe_gpt_train_flops_per_token(
        moe_model.hidden_size, mlp, depth, seq, moe_model.vocab_size,
        e, k, every,
    )
    achieved = tokens_per_step * flops_per_token / step_s / n_chips
    out = {
        "moe_experts": e,
        "moe_top_k": k,
        "moe_seq": seq,
        "moe_step_ms": round(step_s * 1e3, 2),
        "moe_loss_moved": bool(abs(loss_end - first["loss"]) > 1e-9),
    }
    # router balance: the metric sums E*sum(f*p)*weight over all n_moe
    # layers, so perfectly balanced routing reads n_moe * aux_loss_weight
    # (= 6 * 0.01 here), larger = more collapsed; z-loss shrinking means
    # logit magnitudes are controlled
    from tfde_tpu.models.moe import MoEMlp

    out["moe_aux_balanced_value"] = round(
        (depth // every) * MoEMlp.aux_loss_weight, 6
    )
    for kk in ("moe_aux", "moe_z"):
        if kk in first:
            out[f"{kk}_start"] = round(first[kk], 6)
        if kk in last:
            out[f"{kk}_end"] = round(last[kk], 6)
    if _gate(out, "moe", achieved, peak):
        out.update({
            "moe_mfu": round(achieved / peak, 4),
            "moe_tokens_per_sec_per_chip": round(
                tokens_per_step / step_s / n_chips, 1
            ),
        })

    # dense-FLOP-matched twin (own try: its failure keeps the moe numbers)
    try:
        dense_model = GPT(mlp_dim=twin_mlp, **dims)
        dstate, dstep = build(dense_model)
        d_step_s, _f, d_loss_end, _l = timed_steps(dstate, dstep, toks, key)
        d_flops = gpt_train_flops_per_token(
            dims["hidden_size"], twin_mlp, depth, seq,
            dense_model.vocab_size,
        )
        d_achieved = tokens_per_step * d_flops / d_step_s / n_chips
        out["moe_dense_twin_mlp_dim"] = twin_mlp
        out["moe_dense_twin_step_ms"] = round(d_step_s * 1e3, 2)
        # routing overhead at equal useful FLOPs: >1 = MoE step is slower
        out["moe_over_dense_step_ratio"] = round(step_s / d_step_s, 3)
        if _gate(out, "moe_dense_twin", d_achieved, peak):
            out["moe_dense_twin_mfu"] = round(d_achieved / peak, 4)
    except Exception as ex:
        out["moe_dense_twin_error"] = f"{type(ex).__name__}: {ex}"[:300]
    return out


def _bench_serve(clock: _Clock, smoke: bool) -> dict:
    """Continuous-batching serving throughput (inference/server.py): a
    stream of mixed-length requests through a fixed decode batch, rows
    re-used mid-flight. Complements `decode_*` (steady one-shot batch):
    this measures the throughput of the loop a server actually runs —
    admission prefills, the fused K-tick decode scan, and the per-step
    host sync included. Alongside the raw rate it reports the HOST
    OVERHEAD the device-resident loop exists to eliminate: an in-config
    greedy `generate` run (same model, same batch, one XLA program, zero
    scheduling) is the device ceiling, and `serve_host_overhead` = 1 −
    serve/decode throughput is the fraction of that ceiling the serving
    loop still spends on the host (the 97× gap of BENCH_r05 was this
    number at ~0.99). Latency rides the serving histograms: TTFT
    (submit → first token at admission) and per-output-token latency."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.decode import generate
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.observability import metrics as _metrics
    from tfde_tpu.models.gpt import GPT, GPT2Small

    if smoke:
        batch, new, n_req, max_len, depth = 2, 6, 4, 48, 4
        model = GPT(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                    mlp_dim=128, max_position=64, dtype=jnp.float32)
    else:
        batch, new, n_req, max_len, depth = 8, 96, 24, 256, 8
        model = GPT2Small(max_position=256, dropout_rate=0.0)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)
    # warm the scan/prefill compiles outside the timed window (two prompt
    # lengths cover the bucket set below; the warm run drains through the
    # same adaptive-depth ladder the timed run will use)
    warm = ContinuousBatcher(model, params, batch_size=batch,
                             max_len=max_len, scan_depth=depth)
    lens = (16, 32) if not smoke else (4, 8)
    for i in range(2 * batch):
        warm.submit(rng.integers(0, model.vocab_size, lens[i % len(lens)]),
                    new)
    warm.run()

    srv = ContinuousBatcher(model, params, batch_size=batch,
                            max_len=max_len, scan_depth=depth)
    reg = _metrics.default_registry()
    reg.reset("serving/")  # drop the warm run's TTFT/latency samples
    for i in range(n_req):
        srv.submit(
            rng.integers(0, model.vocab_size, lens[i % len(lens)]), new
        )
    t0 = _time.perf_counter()
    # step (rather than run) so slab occupancy can be sampled per decode
    # round — kv_stats is the same host-side read _publish_stats already
    # does every step, so the timed path is unchanged
    done = []
    occ_samples = []
    min_headroom = batch
    while not srv.idle:
        done.extend(srv.step())
        kv = srv.kv_stats()
        occ_samples.append(1.0 - kv["waste_frac"])
        min_headroom = min(min_headroom, kv["headroom_rows"])
    total = sum(len(t) for _, t in done)
    # the loop's own host round-trips are part of what's measured; the
    # final host sync is implicit in the per-step bundled fetch
    dt = _time.perf_counter() - t0
    stats = srv.stats()
    serve_tps = total / max(dt, 1e-9)
    pad = srv._ledger.pad_stats()
    out = {
        "serve_tokens_per_sec": round(serve_tps, 1),
        "serve_requests": len(done),
        "serve_batch": batch,
        "serve_total_tokens": int(total),
        "serve_scan_depth": depth,
        "serve_ms_per_token": round(dt * 1e3 / max(total, 1), 3),
        # host cost per generated token — the O(1/K) bound the fused scan
        # buys (the old loop paid >= 3); admission waves included
        "serve_dispatches_per_token": round(
            stats["dispatches_per_token"], 3
        ),
        "serve_syncs_per_token": round(stats["syncs_per_token"], 3),
        # capacity ledger columns (observability/capacity.py): the
        # paged-KV PR's before/after baseline. waste_frac is the
        # pad-ladder fraction (prefill cells computed beyond the true
        # prompt); occupancy is mean committed/allocated slab fraction
        # across decode rounds; headroom_rows is the tightest admission
        # headroom the run saw
        "serve_kv_waste_frac": round(
            pad["pad_waste_tokens"] / max(pad["pad_alloc_tokens"], 1), 4),
        "serve_kv_occupancy": round(
            sum(occ_samples) / max(len(occ_samples), 1), 4),
        "serve_headroom_rows": int(min_headroom),
    }
    # memory + compile columns: peak bytes over every serve/* program the
    # ledger registered (prefill buckets + decode depths) and the serve
    # sites' sentinel counters — misses here are the pad-ladder compiles
    # the warm run is supposed to have prepaid
    from tfde_tpu.observability import memwatch as _memwatch
    from tfde_tpu.observability import recompile as _recompile

    serve_pms = [p for n, p in _memwatch.programs().items()
                 if n.startswith("serve/")]
    serve_sites = [s for n, s in _recompile.sites().items()
                   if n.startswith("serve/")]
    out["serve_peak_hbm_bytes"] = int(max(
        (p.peak_bytes for p in serve_pms), default=0))
    out["serve_compile_count"] = int(sum(
        s["misses"] for s in serve_sites))
    out["serve_compile_seconds"] = round(sum(
        s["seconds"] for s in serve_sites), 3)
    ttft = reg.get("serving/ttft_ms")
    if ttft is not None and ttft.count:
        out["serve_ttft_ms"] = round(ttft.percentile(50), 2)
        out["serve_ttft_p95_ms"] = round(ttft.percentile(95), 2)
        out["serve_ttft_p99_ms"] = round(ttft.percentile(99), 2)
    # TTFT decomposition: queue wait (submit -> wave start, which includes
    # sitting behind in-flight decode scans) + prefill (the serving/prefill
    # span) account for the first token; the residual is per-wave host
    # bookkeeping (planning, scatter, the admission fetch)
    qw = reg.get("serving/queue_wait_ms")
    if qw is not None and qw.count:
        out["serve_ttft_queue_wait_ms"] = round(qw.percentile(50), 2)
    pf = reg.get("serving/prefill")   # span histogram, seconds
    if pf is not None and pf.count:
        out["serve_ttft_prefill_ms"] = round(pf.percentile(50) * 1e3, 2)
    if {"serve_ttft_ms", "serve_ttft_queue_wait_ms",
            "serve_ttft_prefill_ms"} <= out.keys():
        out["serve_ttft_other_ms"] = round(max(
            0.0, out["serve_ttft_ms"] - out["serve_ttft_queue_wait_ms"]
            - out["serve_ttft_prefill_ms"]), 2)

    # device ceiling: the same model generating the same per-request
    # budget as ONE program (prompt = the stream's shorter bucket) — what
    # the chip does with the host fully out of the loop
    prompt = jnp.asarray(
        rng.integers(0, model.vocab_size, (batch, lens[0])), jnp.int32
    )

    def run(reps):
        toks = None
        for _ in range(reps):
            toks, _ = generate(model, params, prompt, max_new_tokens=new)
        return toks

    clock.fetch_scalar(run(1)[0, -1].astype(jnp.float32))  # compile+warm
    reps, window, _, _ = clock.timed(
        run, lambda t: t[0, -1].astype(jnp.float32),
        0.05 if smoke else 1.0, start_reps=1, max_reps=100,
    )
    decode_tps = batch * new / (window / reps)
    out["serve_decode_ceiling_tokens_per_sec"] = round(decode_tps, 1)
    # fraction of the device ceiling still lost to the serving loop's
    # host work (0 = fully device-resident; admission makes a small
    # irreducible floor). Negative means serving BEAT the one-shot
    # program (possible: continuous batching refills rows the one-shot
    # batch leaves padding) — report 0, not a nonsense negative.
    out["serve_host_overhead"] = round(
        max(0.0, 1.0 - serve_tps / max(decode_tps, 1e-9)), 4
    )

    # ---- prefix-KV cache A/B: shared system prompt, cold vs warm TTFT ----
    # The serving win the cache exists for: every request opens with the
    # same system prompt; after the first (cold) request seeds the trie,
    # admission scatters the cached K/V and prefills only the per-request
    # tail. Cold = full-prompt prefill TTFT; warm = suffix-only TTFT for a
    # wave of requests sharing the prefix. Compiles are warmed with a
    # same-shape throwaway system prompt so neither phase times XLA.
    from tfde_tpu.inference.prefix_cache import PrefixCache

    if smoke:
        sys_len, tail, pnew, pblock, pmax_len = 40, 4, 6, 32, 64
        pmodel, pparams = model, params
    else:
        sys_len, tail, pnew, pblock, pmax_len = 512, 16, 32, 16, 640
        pmodel = GPT2Small(max_position=640, dropout_rate=0.0)
        pparams = pmodel.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    rng2 = np.random.default_rng(7)

    def mk_reqs(sys_tokens, n):
        return [
            np.concatenate([
                sys_tokens,
                rng2.integers(0, pmodel.vocab_size, tail),
            ])
            for _ in range(n)
        ]

    def phase(b, reqs):
        """Submit `reqs`, run to drain, return (ttft_p50_ms, outputs)."""
        reg.reset("serving/ttft_ms")
        for p in reqs:
            b.submit(p, pnew)
        finished = b.run()
        h = reg.get("serving/ttft_ms")
        toks = [list(map(int, t)) for _, t in sorted(finished)]
        return (h.percentile(50) if h is not None and h.count
                else float("nan")), toks

    pc = PrefixCache(block=pblock)
    pb = ContinuousBatcher(pmodel, pparams, batch_size=batch,
                           max_len=pmax_len, scan_depth=depth,
                           prefix_cache=pc)
    wsys = rng2.integers(0, pmodel.vocab_size, sys_len)
    msys = rng2.integers(0, pmodel.vocab_size, sys_len)
    phase(pb, mk_reqs(wsys, 1))       # compile the cold single-row wave
    phase(pb, mk_reqs(wsys, batch))   # compile the warm wave (wsys cached)
    cold, _ = phase(pb, mk_reqs(msys, 1))
    reqs_warm = mk_reqs(msys, batch)
    warmed, warm_toks = phase(pb, reqs_warm)
    # correctness rider: the warm wave must be bit-identical to a
    # cache-off batcher fed the same requests (greedy decode)
    ref = ContinuousBatcher(pmodel, pparams, batch_size=batch,
                            max_len=pmax_len, scan_depth=depth)
    for p in reqs_warm:
        ref.submit(p, pnew)
    ref_toks = [list(map(int, t)) for _, t in sorted(ref.run())]
    st = pc.stats()
    out["serve_prefix_cold_ttft_ms"] = round(cold, 2)
    out["serve_prefix_warm_ttft_ms"] = round(warmed, 2)
    out["serve_prefix_warm_over_cold"] = round(
        warmed / max(cold, 1e-9), 3
    )
    out["serve_prefix_hit_rate"] = round(st["hit_rate"], 3)
    out["serve_prefix_reused_tokens"] = int(st["reused_tokens"])
    out["serve_prefix_bytes_saved_mb"] = round(
        st["bytes_saved"] / 2**20, 2
    )
    out["serve_prefix_parity_ok"] = warm_toks == ref_toks

    # ---- paged-KV A/B (inference/paged.py): same byte budget, short ----
    # requests. The block pool's capacity claim needs a number: a dense
    # batcher allocates max_len cells per row up front, so a fixed KV
    # byte budget affords batch = budget / row_bytes rows; the paged
    # batcher allocates blocks_for(prompt + new + 1) blocks per row, so
    # short requests (1 block here vs max_len/block = 5 dense) pack ~5x
    # more concurrent rows into the SAME bytes. Both sides run under the
    # same TFDE_CAPACITY_BUDGET_BYTES; the paged pool is sized to exactly
    # the dense slab's bytes, and max in-flight rows is measured from the
    # actual step loop, not computed. Greedy parity across the two runs
    # rides along (same stream, same rids).
    ab_batch = 2 if smoke else 4
    ab_max_len, ab_block_rows = 80, 16 if smoke else 32
    ab_new, ab_nreq = 6, (2 * ab_block_rows)
    ab_model = GPT(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                   mlp_dim=128, max_position=128, dtype=jnp.float32)
    ab_params = ab_model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng3 = np.random.default_rng(11)
    ab_reqs = [rng3.integers(0, ab_model.vocab_size, int(rng3.integers(4, 9)))
               for _ in range(ab_nreq)]

    def ab_run(paged: bool, budget: int):
        from tfde_tpu.inference.prefix_cache import DEFAULT_BLOCK as _blk
        kwargs = dict(batch_size=ab_batch, max_len=ab_max_len,
                      scan_depth=depth, paged=False)
        if paged:
            usable = ab_batch * ab_max_len // _blk
            kwargs = dict(batch_size=ab_block_rows, max_len=ab_max_len,
                          scan_depth=depth, paged=True,
                          pool_blocks=usable + 1)
        prev_budget = os.environ.get("TFDE_CAPACITY_BUDGET_BYTES")
        os.environ["TFDE_CAPACITY_BUDGET_BYTES"] = str(budget)
        try:
            b = ContinuousBatcher(ab_model, ab_params, **kwargs)
        finally:
            if prev_budget is None:
                os.environ.pop("TFDE_CAPACITY_BUDGET_BYTES", None)
            else:
                os.environ["TFDE_CAPACITY_BUDGET_BYTES"] = prev_budget
        for p in ab_reqs:
            b.submit(p, ab_new)
        fin, inflight, blk_active, blk_free = [], 0, 0, None
        while not b.idle:
            fin.extend(b.step())
            inflight = max(inflight,
                           sum(r is not None for r in b._req))
            kv = b.kv_stats()
            if "pool_blocks_active" in kv:
                blk_active = max(blk_active, int(kv["pool_blocks_active"]))
                free = int(kv["pool_blocks_free"])
                blk_free = free if blk_free is None else min(blk_free, free)
        toks = [list(map(int, t)) for _, t in sorted(fin)]
        return toks, inflight, blk_active, blk_free, b.kv_stats()

    # the budget is the DENSE slab's bytes — measured, not assumed
    from tfde_tpu.observability.capacity import kv_slab_bytes as _ksb
    probe = ContinuousBatcher(ab_model, ab_params, batch_size=ab_batch,
                              max_len=ab_max_len, scan_depth=depth)
    ab_budget = int(_ksb(probe._cache))
    del probe
    dense_toks, dense_rows, _a, _f, _kv = ab_run(False, ab_budget)
    paged_toks, paged_rows, blk_active, blk_free, pkv = ab_run(
        True, ab_budget)
    out["serve_paged_budget_bytes"] = ab_budget
    out["serve_max_inflight_rows"] = int(paged_rows)
    out["serve_max_inflight_rows_dense"] = int(dense_rows)
    out["serve_paged_inflight_gain"] = round(
        paged_rows / max(dense_rows, 1), 2)
    out["serve_kv_blocks_active"] = int(blk_active)
    out["serve_kv_blocks_free"] = int(0 if blk_free is None else blk_free)
    out["serve_paged_kv_waste_frac"] = round(
        float(pkv.get("waste_frac", 0.0)), 4)
    out["serve_paged_parity_ok"] = paged_toks == dense_toks

    # ---- int8 KV-cache A/B (TFDE_KV_QUANT, ops/quant.kv_quantize) ----
    # The quantization claim needs numbers at a FIXED byte budget (the
    # config's own fp dense slab, measured): each ledger prices rows by
    # its dtype-true cost — int8 payload is a quarter of fp32 plus a
    # per-(position, head) fp32 scale sidecar — so the same budget
    # admits ~2.7x the rows at this head_dim (the >= 1.8x bar; the
    # sidecar's share shrinks as head_dim grows). Headroom is read from
    # the kv/headroom_rows surface of idle batchers whose row count
    # does NOT clamp the budget. Greedy parity runs on a small-head
    # config where argmax gaps dwarf the amax/254 round-trip error —
    # the mechanism bar (>= 0.98), not a model-quality claim: a
    # random-init wide-vocab model near-ties its logits, where ANY
    # eps-perturbation (a dtype cast included) flips coin-flip argmaxes
    # the 0.98 bar was never about.
    kvq_model = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4,
                    mlp_dim=64, max_position=128, dtype=jnp.float32)
    kvq_params = kvq_model.init(
        jax.random.key(2), jnp.zeros((1, 8), jnp.int32))["params"]
    kvq_batch, kvq_rows, kvq_new = (2 if smoke else 4), ab_block_rows, 6
    rng4 = np.random.default_rng(13)
    kvq_reqs = [rng4.integers(0, 97, int(rng4.integers(4, 9)))
                for _ in range(ab_nreq)]

    def kvq_build(kv_quant, *, use_paged, rows, pool_mult=1, budget=None):
        from tfde_tpu.inference.prefix_cache import DEFAULT_BLOCK as _blk
        kwargs = dict(batch_size=rows, max_len=ab_max_len,
                      scan_depth=depth, paged=use_paged,
                      kv_quant=kv_quant)
        if use_paged:
            usable = kvq_batch * ab_max_len // _blk
            kwargs["pool_blocks"] = usable * pool_mult + 1
        prev = os.environ.get("TFDE_CAPACITY_BUDGET_BYTES")
        if budget is not None:
            os.environ["TFDE_CAPACITY_BUDGET_BYTES"] = str(budget)
        try:
            return ContinuousBatcher(kvq_model, kvq_params, **kwargs)
        finally:
            if budget is not None:
                if prev is None:
                    os.environ.pop("TFDE_CAPACITY_BUDGET_BYTES", None)
                else:
                    os.environ["TFDE_CAPACITY_BUDGET_BYTES"] = prev

    def kvq_drain(b):
        for p in kvq_reqs:
            b.submit(p, kvq_new)
        ts = _time.perf_counter()
        fin = b.run()
        wall = max(_time.perf_counter() - ts, 1e-9)
        toks = [list(map(int, t)) for _, t in sorted(fin)]
        return toks, sum(len(t) for t in toks) / wall

    def kvq_match(got, ref):
        hit = tot = 0
        for g, r in zip(got, ref):
            tot += max(len(g), len(r))
            hit += sum(1 for a, b in zip(g, r) if a == b)
        return hit / max(tot, 1)

    # the fixed envelope: this config's own fp dense slab, measured
    kvq_probe = kvq_build("fp", use_paged=False, rows=kvq_batch)
    kvq_budget = int(_ksb(kvq_probe._cache))
    # headroom probes: idle batchers under that envelope; the int8
    # sides carry 4x the rows/blocks so the BUDGET binds, not the batch
    hd_fp = kvq_build("fp", use_paged=False, rows=kvq_batch,
                      budget=kvq_budget).kv_stats()["headroom_rows"]
    hd_q8 = kvq_build("int8", use_paged=False, rows=4 * kvq_batch,
                      budget=kvq_budget).kv_stats()["headroom_rows"]
    hdp_fp = kvq_build("fp", use_paged=True, rows=kvq_rows,
                       budget=kvq_budget).kv_stats()["headroom_rows"]
    hdp_q8 = kvq_build("int8", use_paged=True, rows=kvq_rows,
                       pool_mult=4,
                       budget=kvq_budget).kv_stats()["headroom_rows"]
    out["serve_kv_quant_budget_bytes"] = kvq_budget
    out["serve_kv_quant_headroom_rows"] = int(hd_q8)
    out["serve_kv_quant_headroom_gain"] = round(hd_q8 / max(hd_fp, 1), 2)
    out["serve_kv_quant_headroom_gain_paged"] = round(
        hdp_q8 / max(hdp_fp, 1), 2)
    # parity + throughput on the live stream (budget off: this leg
    # measures tokens, not admission). Each batcher drains the stream
    # twice and the second pass is the number — pass one swallows the
    # XLA compiles, so the int8 wall never includes its own program
    # builds while fp rides the cache-warm twins from the A/Bs above.
    b_fp = kvq_probe
    b_q8 = kvq_build("int8", use_paged=False, rows=kvq_batch)
    b_q8p = kvq_build("int8", use_paged=True, rows=kvq_rows, pool_mult=4)
    fp_toks, _ = kvq_drain(b_fp)
    q8_toks, _ = kvq_drain(b_q8)
    q8p_toks, _ = kvq_drain(b_q8p)
    _, fp_tps = kvq_drain(b_fp)
    _, q8_tps = kvq_drain(b_q8)
    out["serve_kv_quant_greedy_match"] = round(
        min(kvq_match(q8_toks, fp_toks), kvq_match(q8p_toks, fp_toks)), 4)
    out["serve_kv_quant_decode_tps"] = round(q8_tps, 1)
    out["serve_kv_quant_decode_tps_ratio"] = round(
        q8_tps / max(fp_tps, 1e-9), 3)

    # ---- tracing A/B (observability/trace.py): same stream, ring on ----
    # The zero-cost-when-off claim needs a number: re-run the serving
    # stream with every request carrying a trace id and the process ring
    # recording queue/prefill/decode-round/done events, and report the
    # throughput give-up. Ring appends are nanoseconds but the wall clock
    # is not: interleaved best-of-N per side (drift hits both alike; the
    # per-round spread on a tiny CPU smoke run is ~15%, far above the
    # effect being measured — 8 rounds converge it, 3 suffice on the
    # longer full-config walls), clamped at 0. Compiles are already
    # warm — the A/B times scheduling, not XLA.
    from tfde_tpu.observability import trace as reqtrace

    def stream_tps(traced: bool) -> float:
        b = ContinuousBatcher(model, params, batch_size=batch,
                              max_len=max_len, scan_depth=depth)
        srng = np.random.default_rng(0)
        for i in range(n_req):
            b.submit(
                srng.integers(0, model.vocab_size, lens[i % len(lens)]),
                new, trace=reqtrace.new_id() if traced else None,
            )
        ts = _time.perf_counter()
        fin = b.run()
        return (sum(len(t) for _, t in fin)
                / max(_time.perf_counter() - ts, 1e-9))

    trace_was_on = reqtrace.active()
    if not trace_was_on:
        reqtrace.enable()
    try:
        plain_tps, traced_tps = 0.0, 0.0
        for _ in range(8 if smoke else 3):
            plain_tps = max(plain_tps, stream_tps(False))
            traced_tps = max(traced_tps, stream_tps(True))
        out["serve_trace_overhead_pct"] = round(
            max(0.0, 1.0 - traced_tps / max(plain_tps, 1e-9)) * 100, 2
        )
        # exemplar linking: the trace ids a p99 hunt would start from
        ex = reqtrace.exemplars("serving/ttft_ms")
        if ex:
            out["serve_ttft_p99_exemplar_traces"] = [
                r["trace"] for r in ex[:3]
            ]
    finally:
        if not trace_was_on:
            reqtrace.disable()
    return out


def serve_replica_child_mode() -> None:
    """Child of the serve_cluster config: one tiny-GPT ContinuousBatcher
    behind a ReplicaServer on an ephemeral port, announced through an
    atomically renamed port file. argv:
    ``--serve-replica-child <replica_id> <port_file> <push_url|->``.
    Compiles are warmed before the port is announced, so the parent's
    Poisson load never times a child's XLA. Request tracing follows the
    inherited ``TFDE_TRACE`` env (the parent spawns recording and
    non-recording twins for the overhead A/B). Runs until the parent
    kills it — SIGTERM at teardown, SIGKILL in the drill."""
    i = sys.argv.index("--serve-replica-child")
    rid = int(sys.argv[i + 1])
    port_file = sys.argv[i + 2]
    push_url = None if sys.argv[i + 3] == "-" else sys.argv[i + 3]

    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.router import ReplicaServer
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import GPT
    from tfde_tpu.observability import boot as boot_lib

    # the boot ledger narrates this child's cold start: init (backdated
    # to process birth) -> restore (a real file round-trip, so the
    # bandwidth gauge is a disk number) -> compile (the warm loop's XLA)
    # -> warmup -> ready. The parent reads the phases off the push
    # gauges for the serve_cluster_* cold-boot columns.
    led = boot_lib.current()
    led.begin("init")
    model = GPT(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                mlp_dim=128, max_position=64, dtype=jnp.float32)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    ckpt = port_file + ".ckpt"
    with open(ckpt, "wb") as f:
        pickle.dump(jax.device_get(params), f)
    led.begin("restore")
    t_r = time.perf_counter()
    with open(ckpt, "rb") as f:
        params = pickle.load(f)
    led.note_restore_leaf(
        "params",
        sum(x.nbytes for x in jax.tree_util.tree_leaves(params)),
        max(time.perf_counter() - t_r, 1e-9),
    )
    os.remove(ckpt)
    led.begin("compile")
    # batch 2 on purpose: the cluster bench wants per-replica saturation
    # (queueing behind a small decode batch) so adding the second replica
    # shows up as throughput, not idle rows
    b = ContinuousBatcher(model, params, batch_size=2, max_len=48,
                          scan_depth=4)
    rng = np.random.default_rng(rid)
    for ln in (4, 8, 4, 8):
        b.submit(rng.integers(0, model.vocab_size, ln), 16)
    b.run()
    led.begin("warmup")
    b.submit(rng.integers(0, model.vocab_size, 4), 4)
    b.run()
    srv = ReplicaServer(b, replica_id=rid, push_url=push_url,
                        push_interval=0.5, boot_ledger=led).start()
    led.ready()
    with open(port_file + ".tmp", "w") as f:
        f.write(str(srv.port))
    os.replace(port_file + ".tmp", port_file)
    while True:
        time.sleep(3600)


def _bench_serve_cluster(smoke: bool) -> dict:
    """Serving front door at cluster scale (inference/router.py): two
    batcher replicas in SUBPROCESSES (each its own CPU jax runtime — the
    real multi-host shape, not threads sharing one dispatch lock) behind
    the Router under open-loop Poisson load. Three phases: the same load
    against one replica (baseline tok/s), against both (the scaling
    claim: ~2x when each replica saturates), then the kill drill —
    SIGKILL one replica mid-run and verify queued sessions re-route, the
    survivor absorbs the load, the router's flight ring dumps the
    `replica_down` story, and the chief aggregator's host-up gauge
    flips. Replicas run a tiny GPT on CPU regardless of the bench
    platform: the claim here is routing/scaling behaviour, not model
    speed. NOTE the speedup is only meaningful with at least one core
    per replica (plus one for the router/load) — on a 1-core container
    both replicas time-share the same CPU and the honest answer is ~1x;
    `serve_cluster_host_cores` is reported so the reader can tell which
    regime produced the number."""
    import shutil
    import signal as _signal
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from tfde_tpu.inference.router import Router, request_generate
    from tfde_tpu.observability import metrics as _metrics
    from tfde_tpu.observability import trace as reqtrace
    from tfde_tpu.observability.aggregate import ClusterAggregator
    from tfde_tpu.observability.exposition import serve_metrics

    n_req = 8 if smoke else 24
    new = 16
    rate = 50.0   # arrivals/sec: the queue builds well past one replica
    reg = _metrics.default_registry()
    tmp = tempfile.mkdtemp(prefix="tfde_serve_cluster_")
    procs, routers, ms = [], [], None
    # the parent holds the routers, so its ring carries the router half of
    # every stitched waterfall below
    trace_was_on = reqtrace.active()
    if not trace_was_on:
        reqtrace.enable()
    try:
        agg = ClusterAggregator(stale_after=2.0)
        ms = serve_metrics(host="127.0.0.1", aggregator=agg)
        push = f"http://127.0.0.1:{ms.port}/push"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"   # replicas never contend for the TPU
        env.pop("XLA_FLAGS", None)
        # children 0/1 are the cluster (rings recording — the drill below
        # wants the survivor's half of a stitched waterfall); child 2 is a
        # tracing-OFF twin of child 0 for the overhead A/B, kept out of
        # the routers' tables and the aggregator
        port_files = [os.path.join(tmp, f"port{i}") for i in range(3)]
        for i in range(3):
            cenv = dict(env)
            cenv["TFDE_TRACE"] = "on" if i < 2 else "off"
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--serve-replica-child", str(i), port_files[i],
                 push if i < 2 else "-"],
                env=cenv, cwd=os.path.dirname(os.path.abspath(__file__)),
                stdout=open(os.path.join(tmp, f"child{i}.out"), "w"),
                stderr=subprocess.STDOUT,
            ))
        deadline = time.time() + 240
        while not all(os.path.exists(p) for p in port_files):
            if time.time() > deadline:
                raise RuntimeError(
                    "replica children never announced their ports"
                )
            if any(p.poll() is not None for p in procs):
                raise RuntimeError("a replica child died during startup")
            time.sleep(0.2)
        urls = []
        for p in port_files:
            with open(p) as f:
                urls.append(f"http://127.0.0.1:{int(f.read())}")

        def run_load(router_url, seed, kill_at=None, kill_fn=None):
            """Open-loop Poisson arrivals: fire-and-thread at exponential
            gaps regardless of completions; returns (results, wall_s)."""
            lrng = np.random.default_rng(seed)
            gaps = lrng.exponential(1.0 / rate, size=n_req)
            prompts = [
                lrng.integers(0, 512, int(lrng.integers(3, 9))).tolist()
                for _ in range(n_req)
            ]
            results: list = [None] * n_req
            threads = []
            t0 = time.perf_counter()
            for k in range(n_req):
                time.sleep(gaps[k])
                if kill_at is not None and k == kill_at:
                    kill_fn()

                def call(idx=k, p=prompts[k]):
                    try:
                        results[idx] = request_generate(
                            router_url, p, new, timeout=60.0
                        )
                    except Exception as e:  # retriable mid-stream death
                        results[idx] = {
                            "error": f"{type(e).__name__}: {e}"
                        }
                th = threading.Thread(target=call)
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=120.0)
            return results, time.perf_counter() - t0

        def tps(results, wall):
            toks = sum(len(r["tokens"]) for r in results
                       if r and "tokens" in r)
            return toks / max(wall, 1e-9)

        out = {"serve_cluster_replicas": 2,
               "serve_cluster_requests": n_req,
               "serve_cluster_new_tokens": new,
               "serve_cluster_poisson_rate": rate,
               "serve_cluster_host_cores": os.cpu_count() or 1}

        r1 = Router([urls[0]]).start()
        routers.append(r1)
        single, wall = run_load(r1.url, seed=1)
        single_tps = tps(single, wall)
        out["serve_cluster_single_tokens_per_sec"] = round(single_tps, 1)

        # tracing overhead at cluster scale: the identical load against
        # the tracing-OFF twin replica (child 2). The router side records
        # in both runs (same parent process), so the delta isolates the
        # replica-side ring cost on the serving path.
        r0 = Router([urls[2]]).start()
        routers.append(r0)
        untraced, wall = run_load(r0.url, seed=1)
        out["serve_cluster_trace_overhead_pct"] = round(
            max(0.0, 1.0 - single_tps / max(tps(untraced, wall), 1e-9))
            * 100, 2
        )

        r2 = Router(urls[:2]).start()
        routers.append(r2)
        pair, wall = run_load(r2.url, seed=1)
        pair_tps = tps(pair, wall)
        out["serve_cluster_pair_tokens_per_sec"] = round(pair_tps, 1)
        out["serve_cluster_speedup"] = round(
            pair_tps
            / max(out["serve_cluster_single_tokens_per_sec"], 1e-9), 2
        )
        ttfts = sorted(r["ttft_s"] * 1e3 for r in pair
                       if r and r.get("ttft_s") is not None)
        if ttfts:
            out["serve_cluster_ttft_p95_ms"] = round(
                ttfts[min(len(ttfts) - 1, int(0.95 * len(ttfts)))], 2
            )
            out["serve_cluster_ttft_p99_ms"] = round(
                ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))], 2
            )
        # overload accounting on the same pair run (no extra phase, so
        # the trendgate series stay comparable): with TFDE_ADMIT_* caps
        # unset these stay 0 and the columns just pin the orderly-exit
        # taxonomy — completed / 429-rejected / deadline-shed
        adm = [r for r in pair if r and "tokens" in r]
        rej = [r for r in pair if r and "429" in r.get("error", "")]
        sheds = [r for r in pair
                 if r and "deadline_shed" in r.get("error", "")]
        out["serve_cluster_rejected_429"] = len(rej)
        out["serve_cluster_shed"] = len(sheds)
        out["serve_cluster_reject_rate"] = round(
            (len(rej) + len(sheds)) / max(len(pair), 1), 3)
        adm_ttfts = sorted(r["ttft_s"] * 1e3 for r in adm
                           if r.get("ttft_s") is not None)
        if adm_ttfts:
            out["serve_cluster_admitted_ttft_p99_ms"] = round(
                adm_ttfts[min(len(adm_ttfts) - 1,
                              int(0.99 * len(adm_ttfts)))], 2
            )
        # fleet KV capacity after the pair run: the replicas pushed their
        # kv/* gauges with every metrics push, so the chief's rollup has
        # the allocation-weighted waste and summed headroom (the cluster
        # face of the paged-KV baseline)
        roll = agg.rollup()
        if "kv_waste_frac" in roll:
            out["serve_cluster_kv_waste_frac"] = round(
                roll["kv_waste_frac"], 4)
            out["serve_cluster_kv_headroom_rows"] = int(
                roll["kv_headroom_rows"])
        flat_hosts = agg.host_metrics(("kv/",))
        occ = [1.0 - h["kv/waste_frac"] for h in flat_hosts.values()
               if "kv/waste_frac" in h]
        if occ:
            out["serve_cluster_kv_occupancy"] = round(
                sum(occ) / len(occ), 4)
        # block-pool columns (paged replicas only — the kv/pool_blocks_*
        # gauges exist exactly when TFDE_PAGED_KV reached the children):
        # summed across the fleet like headroom, the capacity story in
        # blocks instead of rows
        blk_act = [h["kv/pool_blocks_active"] for h in flat_hosts.values()
                   if "kv/pool_blocks_active" in h]
        if blk_act:
            out["serve_cluster_kv_blocks_active"] = int(sum(blk_act))
            out["serve_cluster_kv_blocks_free"] = int(sum(
                h.get("kv/pool_blocks_free", 0)
                for h in flat_hosts.values()))
        # cold-boot columns (informational, gate:false): the children
        # pushed their boot/* ledger gauges; report the slowest replica's
        # time-to-ready, its boot-attributed compile wall, and the mean
        # restore bandwidth — the serving face of WORKFLOWS.md §21
        boot_hosts = agg.host_metrics(("boot/",))
        ttrs = [h["boot/time_to_ready_seconds"]
                for h in boot_hosts.values()
                if "boot/time_to_ready_seconds" in h]
        if ttrs:
            out["serve_cluster_time_to_ready_s"] = round(max(ttrs), 3)
        compiles = [h["boot/compile_wall_seconds"]
                    for h in boot_hosts.values()
                    if "boot/compile_wall_seconds" in h]
        if compiles:
            out["serve_cluster_boot_compile_s"] = round(max(compiles), 3)
        bws = [h["boot/restore_bandwidth_bps"]
               for h in boot_hosts.values()
               if "boot/restore_bandwidth_bps" in h]
        if bws:
            out["serve_cluster_restore_bw_mbps"] = round(
                sum(bws) / len(bws) / 1e6, 2)

        # kill drill: router with the aggregator attached (staleness is a
        # second down signal) and a flight ring to dump the post-mortem
        reg.reset("router/")
        router_dir = os.path.join(tmp, "router")
        os.makedirs(router_dir, exist_ok=True)
        rk = Router(urls[:2], aggregator=agg, model_dir=router_dir).start()
        routers.append(rk)
        killed, wall = run_load(
            rk.url, seed=2, kill_at=max(1, n_req // 3),
            kill_fn=lambda: os.kill(procs[0].pid, _signal.SIGKILL),
        )
        done = [r for r in killed if r and "tokens" in r]
        errs = [r for r in killed if r and "error" in r]
        out["serve_cluster_kill_completed"] = len(done)
        out["serve_cluster_kill_retriable_errors"] = len(errs)
        c = reg.get("router/reroutes")
        out["serve_cluster_kill_reroutes"] = int(c.value) if c else 0
        try:
            survivor = request_generate(rk.url, [5, 6, 7, 8], new,
                                        timeout=60.0)
            out["serve_cluster_kill_survivor_ok"] = (
                len(survivor["tokens"]) == new
            )
        except Exception as e:
            out["serve_cluster_kill_survivor_ok"] = False
            out["serve_cluster_kill_survivor_error"] = str(e)[:200]
        out["serve_cluster_kill_flight_dump"] = bool(
            _find_flight_dumps(router_dir)
        )
        # the acceptance waterfall: find a completed request the drill
        # re-routed and fetch its stitched trace from the router — the
        # router's attempts (replica 0, then the reroute to 1) and the
        # survivor's serve/* events must land in ONE trace. The dead
        # replica's ring died with it (SIGKILL), which is exactly the
        # post-mortem shape: attempts tell the routing story, the
        # survivor tells the serving story.
        stitched_ok = False
        for r in done:
            tid = r.get("trace")
            if not tid:
                continue
            try:
                with urllib.request.urlopen(
                    rk.url + f"/trace/{tid}", timeout=5.0
                ) as resp:
                    tr = json.loads(resp.read())
            except Exception:
                continue
            evs = tr.get("events", [])
            attempts = {e.get("replica") for e in evs
                        if e.get("name") == "router/attempt"}
            if {0, 1} <= attempts:
                out["serve_cluster_trace_stitched_procs"] = tr.get(
                    "procs", []
                )
                out["serve_cluster_trace_events"] = len(evs)
                stitched_ok = any(
                    str(e.get("name", "")).startswith("serve/")
                    for e in evs
                )
                break
        out["serve_cluster_trace_rerouted_ok"] = stitched_ok
        ex = reqtrace.exemplars("router/ttft_ms")
        if ex:
            out["serve_cluster_ttft_exemplar_traces"] = [
                r["trace"] for r in ex[:3]
            ]
        # the dead replica stops pushing; after stale_after the chief
        # scrape must report it down
        time.sleep(agg.stale_after + 0.5)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ms.port}/metrics", timeout=5.0
        ) as resp:
            text = resp.read().decode()
        out["serve_cluster_kill_host_up_flipped"] = (
            'tfde_cluster_host_up{host="0"} 0' in text
        )
        return out
    finally:
        if not trace_was_on:
            reqtrace.disable()
        for r in routers:
            try:
                r.close()
            except Exception:
                pass
        if ms is not None:
            ms.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _find_flight_dumps(root: str) -> list:
    """Flight-recorder dump files under `root` (any depth)."""
    hits = []
    for dirpath, _dirs, files in os.walk(root):
        hits.extend(os.path.join(dirpath, f) for f in files
                    if "flight" in f)
    return hits


def _bench_decode(clock: _Clock, smoke: bool) -> dict:
    """Serving-side decode throughput: GPT-2-small KV-cache generation
    (inference/decode.py) — tokens/sec at batch 8, prompt 128. The decode
    regime is HBM-bandwidth-bound (every step streams the full weights +
    cache for one token per row), so this measures a different ceiling than
    the training MFU configs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.gpt import GPT, GPT2Small

    if smoke:
        batch, prompt_len, new = 2, 16, 8
        model = GPT(vocab_size=512, hidden_size=64, depth=2, num_heads=2,
                    mlp_dim=128, max_position=64, dtype=jnp.float32)
    else:
        batch, prompt_len, new = 8, 128, 128
        model = GPT2Small(max_position=prompt_len + new, dropout_rate=0.0)
    params = model.init(
        jax.random.key(0), jnp.zeros((batch, prompt_len + new), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, model.vocab_size, (batch, prompt_len)), jnp.int32
    )

    def make_run(mdl, prms, n_new):
        def run(reps):
            toks = None
            for i in range(reps):
                toks, _ = generate(mdl, prms, prompt, max_new_tokens=n_new,
                                   rng=jax.random.key(i), temperature=1.0,
                                   top_k=40)
            return toks
        return run

    def time_call(mdl, prms, n_new):
        run = make_run(mdl, prms, n_new)
        clock.fetch_scalar(run(1)[0, -1].astype(jnp.float32))  # compile+warm
        reps, window, _, _ = clock.timed(
            run, lambda t: t[0, -1].astype(jnp.float32),
            0.05 if smoke else 2.0, start_reps=1, max_reps=200,
        )
        return window / reps, reps

    # The full call includes the prompt prefill; an N=1 baseline isolates
    # it (prefill + a single sample), so the difference over new-1 tokens
    # is the pure per-token decode cost — the HBM-bandwidth figure.
    per_call, reps = time_call(model, params, new)
    prefill_call, _ = time_call(model, params, 1)
    delta = per_call - prefill_call
    out = {
        "decode_batch": batch,
        "decode_prompt_len": prompt_len,
        "decode_new_tokens": new,
        # whole-call generation throughput (prefill amortized over the call)
        "decode_gen_tokens_per_sec": round(batch * new / per_call, 1),
        "decode_call_ms": round(per_call * 1e3, 2),
        "decode_prefill_ms": round(prefill_call * 1e3, 2),
        "decode_calls_timed": reps,
    }
    # decode-only rate: prefill subtracted via the N=1 baseline. A delta
    # within noise of zero is an invalid measurement — report it as such,
    # never a clamped absurdity (the trust rule every config follows).
    if new > 1 and delta > 0.05 * per_call:
        out["decode_ms_per_token"] = round(delta / (new - 1) * 1e3, 3)
        out["decode_tokens_per_sec"] = round(batch * (new - 1) / delta, 1)
    else:
        out["decode_error"] = (
            "prefill baseline >= full call within noise; decode-only rate "
            "unmeasurable at this config"
        )

    def twin(prefix: str, mdl, prms) -> None:
        """One serving-lever twin, measured exactly like the base model:
        full call, N=1 prefill baseline, decode-only delta — with the SAME
        5% noise gate on the twin's own delta (a noise-level delta must
        report as unmeasurable, never as an absurd tokens/sec; the trust
        rule every config follows). Speedup is decode-only vs decode-only:
        the full call is prefill-diluted, which would understate the
        bandwidth effect the twins measure. Own try/except — a twin
        failure must not discard the numbers already measured."""
        try:
            t_call, _ = time_call(mdl, prms, new)
            t_prefill, _ = time_call(mdl, prms, 1)
            t_delta = t_call - t_prefill
            out[f"{prefix}_gen_tokens_per_sec"] = round(
                batch * new / t_call, 1
            )
            if (new > 1 and delta > 0.05 * per_call
                    and t_delta > 0.05 * t_call):
                out[f"{prefix}_tokens_per_sec"] = round(
                    batch * (new - 1) / t_delta, 1
                )
                out[f"{prefix}_speedup"] = round(delta / t_delta, 3)
            else:
                out[f"{prefix}_error"] = (
                    f"decode-only delta unmeasurable for the {prefix} twin"
                )
        except Exception as e:
            out[f"{prefix}_error"] = f"{type(e).__name__}: {e}"[:300]

    if not smoke:
        # GQA twin (4 KV heads instead of 12): the serving memory/bandwidth
        # knob — same dims, random init (throughput only, quality N/A)
        gqa = GPT2Small(max_position=prompt_len + new, dropout_rate=0.0,
                        num_kv_heads=4)
        gparams = gqa.init(
            jax.random.key(0),
            jnp.zeros((batch, prompt_len + new), jnp.int32),
        )["params"]
        out["decode_gqa_kv_heads"] = 4
        twin("decode_gqa", gqa, gparams)

    # int8 W8A8 twin (ops/quant.py): weight HBM traffic halves and the
    # matmuls ride the v5e's double-rate int8 MXU — the quantization
    # serving lever. Runs in smoke mode too (unlike GQA) so CI exercises
    # the quantized decode path end to end.
    try:
        from tfde_tpu.ops.quant import quantize_model

        qmodel, qparams = quantize_model(model, params)
        twin("decode_int8", qmodel, qparams["params"])
    except Exception as e:
        out["decode_int8_error"] = f"{type(e).__name__}: {e}"[:300]
    return out


#: bench_meta schema: 1 = implicit (pre-provenance lines, no meta block);
#: 2 = bench_meta {schema, git_sha, backend, knobs} on every emitted line
BENCH_SCHEMA_VERSION = 2


def _git_sha() -> str | None:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode == 0:
            return proc.stdout.strip() or None
    except Exception:
        pass
    return None  # tarball checkouts bench too


def _knob_snapshot() -> dict:
    """Every TFDE_* knob actually set in this environment — the capture's
    configuration fingerprint. Unregistered names are included on purpose:
    a knob the registry doesn't know yet is exactly the drift a cross-round
    diff needs to surface (registry: tfde_tpu/knobs.py)."""
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith("TFDE_")}


def _bench_meta(platform: str | None = None, device_kind: str | None = None,
                n_chips: int | None = None) -> dict:
    """Provenance block stamped onto every emitted JSON line so captures
    are alignable across machines and rounds (trendgate's raw material)."""
    meta: dict = {
        "schema": BENCH_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "knobs": _knob_snapshot(),
    }
    if platform is not None:
        meta["backend"] = {"platform": platform, "device_kind": device_kind,
                           "n_chips": n_chips}
    return meta


def run_mode() -> None:
    import jax

    if os.environ.get("TFDE_BENCH_FORCE_CPU") == "1":
        # jax.config (not the env var): the axon site shim intercepts
        # backend bring-up when JAX_PLATFORMS is consulted and can hang on a
        # dead tunnel; the lazy-config route sidesteps it (same trick as
        # tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        os.environ["TFDE_BENCH_ALLOW_CPU"] = "1"

    devices = jax.local_devices()
    platform = devices[0].platform
    device_kind = str(devices[0].device_kind)
    if platform == "cpu" and os.environ.get("TFDE_BENCH_ALLOW_CPU") != "1":
        print(json.dumps({"error": "backend came up as cpu; refusing a "
                          "silent-fallback number (set TFDE_BENCH_ALLOW_CPU=1 "
                          "to override)", "platform": platform}))
        sys.exit(3)

    from tfde_tpu.parallel.strategies import MirroredStrategy

    strategy = MirroredStrategy()
    n_chips = strategy.num_replicas
    peak, peak_known = chip_peak_flops(device_kind)
    print(f"platform={platform} kind={device_kind} chips={n_chips}",
          file=sys.stderr)

    smoke = os.environ.get("TFDE_BENCH_SMOKE") == "1"
    result = {"platform": platform, "device_kind": device_kind,
              "n_chips": n_chips,
              "chip_peak_tflops": round(peak / 1e12, 1),
              "chip_peak_known": peak_known}
    if smoke:
        result["smoke"] = True

    clock = _Clock()
    configs = [
        ("calib", lambda: _bench_calibration(clock, peak, smoke)),
        ("mnist", lambda: _bench_mnist(clock, strategy, n_chips, smoke)),
        ("mnist_e2e", lambda: _bench_mnist_e2e(clock, strategy, n_chips, smoke)),
        ("link", lambda: _bench_link(clock, smoke)),
        ("mnist_dev", lambda: _bench_mnist_dev(clock, strategy, n_chips,
                                               smoke)),
        ("obs", lambda: _bench_obs(strategy, smoke)),
        ("bert", lambda: _bench_bert_mfu(clock, strategy, n_chips, peak, smoke)),
        ("comms", lambda: _bench_comms(n_chips, smoke)),
        ("zero", lambda: _bench_zero(n_chips, smoke)),
        ("flash", lambda: _bench_flash(clock, smoke)),
        # stretch configs: ordered last so an attempt-timeout salvages the
        # core numbers above (run mode emits a cumulative line per config)
        ("bert32", lambda: _bench_bert_mfu(clock, strategy, n_chips, peak,
                                           smoke, per_chip_batch=32,
                                           prefix="bert32")),
        # fusion A/B at equal batch: bert_fused_mfu - bert_mfu isolates the
        # one-GEMM qkv projection (transformer.fused_qkv)
        ("bert_fused", lambda: _bench_bert_mfu(clock, strategy, n_chips,
                                               peak, smoke,
                                               prefix="bert_fused",
                                               fused_qkv=True)),
        ("gpt_long", lambda: _bench_gpt_long(clock, strategy, n_chips, peak,
                                             smoke)),
        ("gpt_medium", lambda: _bench_gpt_long(clock, strategy, n_chips,
                                               peak, smoke,
                                               prefix="gpt_medium")),
        ("gpt_long2", lambda: _bench_gpt_long(clock, strategy, n_chips,
                                              peak, smoke,
                                              prefix="gpt_long2")),
        ("gpt_long4", lambda: _bench_gpt_long(clock, strategy, n_chips,
                                              peak, smoke,
                                              prefix="gpt_long4")),
        ("gpt_long_win", lambda: _bench_gpt_long(clock, strategy, n_chips,
                                                 peak, smoke,
                                                 prefix="gpt_long_win")),
        ("moe", lambda: _bench_moe(clock, strategy, n_chips, peak, smoke)),
        ("decode", lambda: _bench_decode(clock, smoke)),
        ("serve", lambda: _bench_serve(clock, smoke)),
        ("serve_cluster", lambda: _bench_serve_cluster(smoke)),
    ]

    def emit(partial: bool) -> None:
        # One cumulative JSON line after every config: if the driver's
        # attempt timeout fires mid-run (a full TPU pass is ~10 min through
        # the tunnel), the captured stdout still carries every number
        # measured so far and the driver salvages the last line.
        value = result.get("mnist_images_per_sec_per_chip", 0.0)
        line = {
            "metric": "mnist_bncnn_train_images_per_sec_per_chip",
            "value": value,
            "unit": "images/sec/chip",
            # The reference publishes no numbers (BASELINE.md; README is a
            # bare title) — a ratio against an invented constant is not a
            # baseline.
            "vs_baseline": None,
            "vs_baseline_note": "reference publishes no benchmark numbers",
            **result,
            "bench_meta": _bench_meta(platform, device_kind, n_chips),
        }
        if partial:
            line["partial"] = True
        if "calib_error" in result:
            line["error"] = result["calib_error"]
            line["value"] = 0.0
        print(json.dumps(line), flush=True)

    def attribute_e2e() -> None:
        """e2e-gap attribution (VERDICT r3 #3): how much of
        e2e_step - compute_step the measured per-batch link cost explains.
        A fraction near 1.0 proves the residual is pure transfer (tunnel
        latency); well below 1.0 points at pipeline overhead instead."""
        need = ("mnist_step_ms", "mnist_e2e_step_ms", "link_batch_ms")
        if not all(k in result for k in need):
            return
        gap = result["mnist_e2e_step_ms"] - result["mnist_step_ms"]
        result["e2e_gap_ms"] = round(gap, 3)
        if gap > 1e-3:
            result["e2e_gap_link_fraction"] = round(
                result["link_batch_ms"] / gap, 3
            )

    for i, (name, fn) in enumerate(configs):
        try:
            result.update(fn())
        except Exception as e:  # OOM on small chips etc. — keep the rest
            result[f"{name}_error"] = f"{type(e).__name__}: {e}"[:400]
        print(f"{name} done", file=sys.stderr)
        if name == "calib" and "calib_error" in result:
            break  # timing itself is broken; more numbers would be noise
        if name == "link":
            attribute_e2e()
        if i < len(configs) - 1:
            emit(partial=True)
    emit(partial=False)


# --------------------------------------------------------------------------
# Driver mode: retry loop, no jax in this process.
# --------------------------------------------------------------------------

def probe_mode() -> None:
    """Fast backend check: bring up jax, print one JSON line, exit."""
    import jax

    devices = jax.local_devices()
    print(json.dumps({"ok": True, "platform": devices[0].platform,
                      "n": len(devices)}))


def _last_json(stdout: str) -> dict | None:
    """Last stdout line that parses as a JSON object, or None."""
    for ln in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def _backend_probe(timeout_s: float) -> tuple[str, str]:
    """('up'|'cpu_only'|'down', detail) for a fresh-interpreter backend check.

    The round-1 failure raised UNAVAILABLE at the first device query; the
    failure observed while building round 2 *hangs* there instead (tunnel
    never answers). Probing in a 2-minute subprocess keeps either mode from
    eating the whole benchmark budget before we know the backend is up.
    'cpu_only' is permanent (no TPU plugin on this host) — don't retry it.
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "down", "probe hang: backend init did not answer"
    parsed = _last_json(proc.stdout)
    if parsed and parsed.get("ok"):
        if parsed.get("platform") == "cpu" and \
                os.environ.get("TFDE_BENCH_ALLOW_CPU") != "1":
            return "cpu_only", "backend came up as cpu only"
        return "up", parsed.get("platform", "?")
    return "down", (proc.stderr or "")[-800:]


def _attempt_full_run(timeout_s: float):
    """One full `--run` subprocess attempt, shared by driver_mode and
    watch_mode. Returns (parsed_json_or_None, rc, stderr_tail). On
    timeout, salvages the cumulative JSON line run mode prints after
    every config and marks it partial — a timed-out attempt still yields
    real numbers."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run"],
            capture_output=True, text=True, timeout=max(timeout_s, 30),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        sys.stderr.write(proc.stderr[-4000:])
        return (_last_json(proc.stdout), proc.returncode,
                (proc.stderr or "")[-1500:])
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"")[-1500:].decode("utf-8", "replace")
                if isinstance(e.stderr, bytes) else str(e.stderr)[-1500:])
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        parsed = _last_json(out or "")
        if parsed and "metric" in parsed:
            parsed["partial"] = True
            parsed["partial_reason"] = (
                f"attempt exceeded {timeout_s:.0f}s; reporting configs "
                f"completed before the timeout"
            )
        return parsed, "timeout", tail


def _newest_builder_artifact(repo_dir: str) -> tuple[dict, str] | None:
    """Newest trustworthy in-repo hardware capture (the armed watch's
    output), for the outage fallback (VERDICT r4 next #1a). Trustworthy =
    parses, carries the metric contract, and its calibration anchor hit
    >= 0.8 of chip peak (the BASELINE.md trust rule) — a capture that
    can't vouch for its own clock is not a fallback.

    Returns (artifact_dict, filename) or None."""
    import glob

    candidates = []
    for path in glob.glob(os.path.join(repo_dir, "BENCH_builder_*.json")):
        # the whole vetting is inside the try: a malformed artifact (null
        # calib, string value, file deleted between glob and stat) must
        # skip, not crash the driver at the exact outage moment it exists
        # to cover
        try:
            with open(path) as f:
                art = json.load(f)
            if not isinstance(art, dict) or "metric" not in art:
                continue
            if art.get("platform") != "tpu":
                continue
            if float(art.get("calib_frac_of_peak", 0.0)) < 0.8:
                continue
            if not float(art.get("value", 0.0)) > 0.0:
                continue
            candidates.append((os.path.getmtime(path), art, path))
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            continue
    if not candidates:
        return None
    _, art, path = max(candidates, key=lambda t: t[0])
    return art, os.path.basename(path)


def _emit_fallback(reason: str, last_rc, last_tail: str,
                   attempt: int, budget: float) -> bool:
    """On a dead backend, report the newest builder-watch hardware capture
    WITH explicit provenance instead of a bare 0.0 (three rounds of zeroed
    driver records for a framework benching at 90% calibration was a
    reporting defect — VERDICT r4 weak #1). The stale numbers are never
    silently relabeled as live: `source`, `captured_at`, and
    `staleness_note` say exactly what this is. Returns False if no
    trustworthy artifact exists (caller falls back to the honest zero)."""
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    found = _newest_builder_artifact(repo_dir)
    if not found:
        return False
    art, fname = found
    # artifacts carry the capture stamp under either name (watch_mode vs
    # the builder's manual captures); mtime is a last resort and can be
    # checkout time on a fresh clone
    captured = (art.get("watch_captured_at")
                or art.get("builder_captured_at"))
    if not captured:
        try:
            captured = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ",
                time.gmtime(os.path.getmtime(os.path.join(repo_dir, fname))),
            ) + " (file mtime; capture stamp absent)"
        except OSError:
            captured = "unknown"
    line = dict(art)
    line.update({
        "source": "builder_watch_artifact",
        "source_file": fname,
        "captured_at": captured,
        "staleness_note": (
            "TPU backend unreachable at report time; these numbers are the "
            f"newest in-repo hardware capture ({fname}, captured "
            f"{captured}) by the armed bench watch on the SAME chip with "
            "the same trust gates (calib_frac_of_peak "
            f"{art.get('calib_frac_of_peak')}). They are NOT live — the "
            "live attempt's failure is in live_probe_error."
        ),
        "live_probe_error": reason,
        "live_attempts": attempt,
        "live_budget_s": budget,
        "live_last_rc": str(last_rc),
        "live_last_stderr_tail": last_tail,
        # bench_meta describes THIS reporting process; the replayed
        # artifact's own provenance (if stamped) moves aside untouched
        "source_bench_meta": art.get("bench_meta"),
        "bench_meta": {**_bench_meta(), "replayed": True},
    })
    print(json.dumps(line))
    return True


def _probe_give_up(consecutive_fails: int, probe_spent_s: float,
                   budget_s: float, max_fails: int = 3,
                   probe_budget_frac: float = 0.4) -> tuple[bool, str]:
    """Probe give-up policy (pure, unit-testable): stop probing after
    `max_fails` CONSECUTIVE failures, or once cumulative probe time has
    eaten `probe_budget_frac` of the whole budget. Rounds r03/r04 burned
    their entire hardware budget on back-to-back 2-minute probe hangs —
    a hung tunnel now costs at most a bounded slice before the driver
    falls through to the skip-with-reason fallback path."""
    if consecutive_fails >= max_fails:
        return True, (f"{consecutive_fails} consecutive backend-probe "
                      f"failures (cap {max_fails})")
    if budget_s > 0 and probe_spent_s > probe_budget_frac * budget_s:
        return True, (f"probing consumed {probe_spent_s:.0f}s, over "
                      f"{probe_budget_frac:.0%} of the {budget_s:.0f}s "
                      f"budget")
    return False, ""


def driver_mode() -> None:
    budget = float(os.environ.get("TFDE_BENCH_BUDGET_S", "1200"))
    attempt_timeout = float(os.environ.get("TFDE_BENCH_ATTEMPT_TIMEOUT_S", "900"))
    probe_timeout = float(os.environ.get("TFDE_BENCH_PROBE_TIMEOUT_S", "120"))
    max_probe_fails = int(os.environ.get("TFDE_BENCH_MAX_PROBE_FAILS", "3"))
    skip_probe = os.environ.get("TFDE_BENCH_FORCE_CPU") == "1"
    deadline = time.monotonic() + budget
    backoff = 15.0
    attempt = 0
    last_tail = ""
    last_rc: object = None
    probe_fails = 0     # consecutive
    probe_spent = 0.0   # cumulative seconds inside _backend_probe

    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            break
        attempt += 1
        print(f"[bench driver] attempt {attempt} "
              f"(remaining budget {remaining:.0f}s)", file=sys.stderr)
        if not skip_probe:
            t_probe = time.monotonic()
            status, detail = _backend_probe(min(probe_timeout, remaining))
            probe_spent += time.monotonic() - t_probe
            if status == "cpu_only":
                last_rc, last_tail = "cpu_only", detail
                break  # permanent on this host; don't burn the budget
            if status == "down":
                probe_fails += 1
                last_rc, last_tail = "probe_failed", detail
                give_up, why = _probe_give_up(
                    probe_fails, probe_spent, budget,
                    max_fails=max_probe_fails,
                )
                if give_up:
                    last_rc = "probe_gave_up"
                    last_tail = f"{why}; last probe: {detail[:400]}"
                    print(f"[bench driver] giving up on probes: {why}",
                          file=sys.stderr)
                    break
                sleep = min(backoff, max(deadline - time.monotonic() - 60, 0))
                print(f"[bench driver] backend probe failed ({detail[:200]}); "
                      f"retrying in {sleep:.0f}s", file=sys.stderr)
                if sleep > 0:
                    time.sleep(sleep)
                backoff = min(backoff * 2, 120)
                continue
            probe_fails = 0  # a live backend re-arms the consecutive cap
            print(f"[bench driver] backend up: {detail}", file=sys.stderr)
            remaining = deadline - time.monotonic()  # probe time is spent
        parsed, last_rc, last_tail = _attempt_full_run(
            min(attempt_timeout, remaining)
        )
        if parsed and "metric" in parsed:
            print(json.dumps(parsed))
            return
        if parsed and "error" in parsed:
            last_tail = parsed["error"]
        if last_rc == "timeout":
            print(f"[bench driver] attempt timed out", file=sys.stderr)

        sleep = min(backoff, max(deadline - time.monotonic() - 60, 0))
        if sleep > 0:
            print(f"[bench driver] backend not ready (rc={last_rc}); "
                  f"retrying in {sleep:.0f}s", file=sys.stderr)
            time.sleep(sleep)
        backoff = min(backoff * 2, 120)

    reason = (f"TPU backend unavailable after {attempt} attempts "
              f"within {budget:.0f}s budget")
    if last_rc == "probe_gave_up":
        reason += f" (probe give-up: {last_tail[:200]})"
    # cpu_only is a PERMANENT condition (no TPU plugin on this host), not
    # a tunnel outage — replaying a committed TPU capture there would
    # claim "same chip" on a machine that never had one
    fell_back = False
    if last_rc != "cpu_only":
        try:
            fell_back = _emit_fallback(reason, last_rc, last_tail, attempt,
                                       budget)
        except Exception as e:  # the always-emit invariant beats fallback
            print(f"[bench driver] fallback reporting failed: {e}",
                  file=sys.stderr)
    if fell_back:
        sys.exit(0)
    print(json.dumps({
        "metric": "mnist_bncnn_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "vs_baseline_note": "reference publishes no benchmark numbers",
        "error": reason,
        "last_rc": last_rc,
        "last_stderr_tail": last_tail,
        "bench_meta": _bench_meta(),
    }))
    sys.exit(0)  # the JSON line IS the deliverable; don't hand back a traceback rc


def watch_mode() -> None:
    """Tunnel watch (VERDICT r3 next-round #1): the axon tunnel dies for
    long stretches — hours — and a fixed-budget driver run can land
    entirely inside an outage (round 3's BENCH_r03.json did). This mode
    probes indefinitely and runs the FULL bench on the first successful
    probe, writing the result to TFDE_BENCH_WATCH_OUT (default
    BENCH_builder_watch.json) so a mid-round tunnel window is never
    missed. Exits 0 after one successful full run; keeps watching after a
    run that starts but dies mid-way (the window may reopen)."""
    # resolve against the repo (script dir), not the watcher's CWD — the
    # documented use is a nohup'd background watcher launched from anywhere
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get("TFDE_BENCH_WATCH_OUT",
                              "BENCH_builder_watch.json")
    if not os.path.isabs(out_path):
        out_path = os.path.join(repo_dir, out_path)
    budget = float(os.environ.get("TFDE_WATCH_BUDGET_S", str(11 * 3600)))
    probe_timeout = float(os.environ.get("TFDE_BENCH_PROBE_TIMEOUT_S", "120"))
    interval = float(os.environ.get("TFDE_WATCH_INTERVAL_S", "180"))
    deadline = time.monotonic() + budget
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        status, detail = _backend_probe(probe_timeout)
        stamp = time.strftime("%H:%M:%S")
        if status == "cpu_only":
            print(f"[bench watch {stamp}] cpu only — nothing to watch",
                  file=sys.stderr)
            return
        if status != "up":
            print(f"[bench watch {stamp}] probe {attempt}: down "
                  f"({detail[:120]})", file=sys.stderr)
            time.sleep(interval)
            continue
        print(f"[bench watch {stamp}] backend UP ({detail}) — running full "
              f"bench", file=sys.stderr)
        parsed, _rc, _tail = _attempt_full_run(1800)
        if parsed and "metric" in parsed:
            parsed["watch_captured_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )
            with open(out_path, "w") as f:
                json.dump(parsed, f, indent=1)
            print(json.dumps(parsed))
            print(f"[bench watch] captured -> {out_path}", file=sys.stderr)
            return
        print(f"[bench watch] run died mid-window; resuming watch",
              file=sys.stderr)
        time.sleep(interval)
    print(f"[bench watch] budget exhausted after {attempt} probes without "
          f"a TPU window", file=sys.stderr)
    sys.exit(1)


if __name__ == "__main__":
    if "--run" in sys.argv:
        run_mode()
    elif "--comms-child" in sys.argv:
        comms_child_mode()
    elif "--serve-replica-child" in sys.argv:
        serve_replica_child_mode()
    elif "--zero-child" in sys.argv:
        zero_child_mode()
    elif "--probe" in sys.argv:
        probe_mode()
    elif "--watch" in sys.argv:
        watch_mode()
    else:
        driver_mode()
