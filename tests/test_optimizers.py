"""Weight-decay masking (training/optimizers.py): decay must touch kernels
and embeddings only — never biases or norm scales."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.training.optimizers import adamw, decay_mask


def test_mask_excludes_biases_and_scales():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    mask = decay_mask(params)
    flat = {
        jax.tree_util.keystr(p): v
        for p, v in jax.tree_util.tree_flatten_with_path(mask)[0]
    }
    for path, decayed in flat.items():
        if path.endswith("['bias']") or path.endswith("['scale']"):
            assert not decayed, path
        elif path.endswith("['kernel']") or path.endswith("['embedding']"):
            assert decayed, path


def test_masked_decay_leaves_biases_untouched_by_decay():
    """With zero gradients, masked adamw must not move biases/scales at
    all, while unmasked optax.adamw shrinks every leaf."""
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def one_step(tx):
        state = tx.init(params)
        updates, _ = tx.update(zeros, state, params)
        return optax.apply_updates(params, updates)

    ours = one_step(adamw(1e-2, weight_decay=0.1))
    plain = one_step(optax.adamw(1e-2, weight_decay=0.1))

    ln = params["decoder"]["ln_final"]
    np.testing.assert_array_equal(
        np.asarray(ours["decoder"]["ln_final"]["scale"]),
        np.asarray(ln["scale"]),
    )
    assert not np.allclose(
        np.asarray(plain["decoder"]["ln_final"]["scale"]),
        np.asarray(ln["scale"]),
    )
    # kernels still decay under the masked variant
    k0 = params["decoder"]["block_0"]["mlp"]["fc1"]["kernel"]
    assert not np.allclose(
        np.asarray(ours["decoder"]["block_0"]["mlp"]["fc1"]["kernel"]),
        np.asarray(k0),
    )
