"""ResNet scale-config tests: shapes, parameter parity with the canonical
architecture, sharded train-step integration (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.resnet import ResNet18, ResNet50, resnet50_cifar
from tfde_tpu.parallel.strategies import FSDPStrategy, MultiWorkerMirroredStrategy
from tfde_tpu.training.step import init_state, make_train_step


def test_resnet50_imagenet_param_count():
    # Canonical ResNet-50 (torchvision/flax examples): 25,557,032 params.
    m = ResNet50(num_classes=1000)
    v = jax.eval_shape(
        m.init, jax.random.key(0), jnp.zeros((1, 224, 224, 3))
    )  # abstract init: shapes only, no conv execution
    n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
    assert n == 25_557_032


@pytest.mark.slow
def test_resnet50_cifar_forward():
    m = resnet50_cifar()
    v = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    logits = m.apply(v, jnp.zeros((4, 32, 32, 3)), train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32  # fp32 head over bf16 trunk
    assert "batch_stats" in v


def test_resnet18_forward():
    m = ResNet18(num_classes=10, cifar_stem=True, dtype=jnp.float32)
    v = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    logits = m.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)


@pytest.mark.slow
@pytest.mark.parametrize("strategy_cls", [MultiWorkerMirroredStrategy, FSDPStrategy])
def test_resnet_sharded_train_step_loss_decreases(strategy_cls):
    # ResNet-18 fp32 keeps CPU runtime tolerable while exercising the same
    # BN/residual/train-step machinery as the ResNet-50 config.
    if strategy_cls is FSDPStrategy:
        strategy = strategy_cls(data=2, min_shard_elems=1)
    else:
        strategy = strategy_cls()
    m = ResNet18(num_classes=10, cifar_stem=True, dtype=jnp.float32)
    sample = np.zeros((16, 32, 32, 3), np.float32)
    state, _ = init_state(m, optax.sgd(0.05, momentum=0.9), strategy, sample)
    step = make_train_step(strategy, state, donate=False)
    rng = np.random.default_rng(0)
    images = rng.random((16, 32, 32, 3), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    key = jax.random.key(0)
    first = None
    for _ in range(6):
        state, metrics = step(state, (images, labels), key)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


