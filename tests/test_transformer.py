"""Transformer-core tests: attention numerics, masking, Megatron-compatible
weight shapes, remat equivalence, activation constraints (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tfde_tpu.models.transformer import Encoder, MultiHeadAttention
from tfde_tpu.ops.attention import attention, padding_mask, reference_attention
from tfde_tpu.parallel import axes as axes_lib
from tfde_tpu.runtime.mesh import make_mesh


def _qkv(rng, b=2, s=6, h=2, d=4):
    return (
        rng.random((b, s, h, d), np.float32),
        rng.random((b, s, h, d), np.float32),
        rng.random((b, s, h, d), np.float32),
    )


def test_reference_attention_matches_manual(rng):
    q, k, v = _qkv(rng)
    out = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    # manual per-head softmax
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    expect = np.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


def test_causal_masking_blocks_future(rng):
    q, k, v = _qkv(rng)
    out = reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True
    )
    # perturbing future keys/values must not change earlier outputs
    k2, v2 = k.copy(), v.copy()
    k2[:, -1] += 100.0
    v2[:, -1] += 100.0
    out2 = reference_attention(
        jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), causal=True
    )
    np.testing.assert_allclose(
        np.asarray(out)[:, :-1], np.asarray(out2)[:, :-1], rtol=1e-5, atol=1e-5
    )


def test_padding_mask_excludes_padded_keys(rng):
    q, k, v = _qkv(rng)
    valid = np.ones((2, 6), np.float32)
    valid[:, 4:] = 0.0
    out = reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        mask=padding_mask(jnp.asarray(valid)),
    )
    k2, v2 = k.copy(), v.copy()
    k2[:, 4:] += 50.0
    v2[:, 4:] += 50.0
    out2 = reference_attention(
        jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2),
        mask=padding_mask(jnp.asarray(valid)),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-5, atol=1e-5)


def test_mha_megatron_weight_shapes(rng):
    m = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32)
    x = jnp.asarray(rng.random((2, 5, 32), np.float32))
    v = m.init(jax.random.key(0), x)
    # qkv kernels: [embed, heads, head_dim] — heads trailing => column-shard
    assert v["params"]["query"]["kernel"].shape == (32, 4, 8)
    # out kernel: [heads, head_dim, embed] — sharded dims leading => row-shard
    assert v["params"]["out"]["kernel"].shape == (4, 8, 32)
    y = m.apply(v, x)
    assert y.shape == x.shape


@pytest.mark.slow
def test_encoder_remat_matches_plain(rng):
    x = jnp.asarray(rng.random((2, 5, 16), np.float32))
    kw = dict(depth=2, num_heads=2, head_dim=8, mlp_dim=32, dtype=jnp.float32)
    plain = Encoder(**kw, remat=False)
    v = plain.init(jax.random.key(0), x)
    y0 = plain.apply(v, x)
    y1 = Encoder(**kw, remat=True).apply(v, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), rtol=1e-6, atol=1e-6)

    # gradients agree too (remat only changes the schedule, not the math)
    def loss(mod, v):
        return jnp.sum(mod.apply(v, x) ** 2)

    g0 = jax.grad(lambda v: loss(plain, v))(v)
    g1 = jax.grad(lambda v: loss(Encoder(**kw, remat=True), v))(v)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5), g0, g1
    )


def test_constrain_is_identity_without_mesh(rng):
    x = jnp.asarray(rng.random((4, 6), np.float32))
    assert axes_lib.constrain(x, "data", "tensor") is x


def test_constrain_applies_sharding_in_jit(rng):
    mesh = make_mesh({"data": 2, "tensor": 4})
    x = jnp.asarray(rng.random((4, 8), np.float32))

    @jax.jit
    def f(x):
        with axes_lib.use_axes(mesh):
            return axes_lib.constrain(x, "data", "tensor") * 2.0

    y = f(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 2.0, rtol=1e-6)
    assert y.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(mesh, P("data", "tensor")), y.ndim
    )


def test_constrain_drops_absent_axes(rng):
    mesh = make_mesh({"data": 8})
    with axes_lib.use_axes(mesh):
        spec = axes_lib._filter_spec(mesh, ("data", "seq", ("data", "tensor")))
    assert spec == P("data", None, "data")


def test_attention_dispatcher_reference_path(rng):
    q, k, v = (jnp.asarray(t) for t in _qkv(rng))
    out = attention(q, k, v, impl="auto")  # CPU, no seq mesh -> reference
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)), rtol=1e-6
    )


@pytest.mark.slow
def test_remat_policies_match_no_remat_numerics(rng):
    """remat=False / 'full' / 'dots' are schedule choices, not math changes:
    identical forward values and gradients."""
    import optax

    from tfde_tpu.models.transformer import Encoder, remat_policy

    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)

    def run(remat):
        m = Encoder(depth=2, num_heads=2, head_dim=8, mlp_dim=32,
                    dtype=jnp.float32, remat=remat)
        v = m.init(jax.random.key(0), x)

        def loss(params):
            return jnp.sum(m.apply({"params": params}, x) ** 2)

        val, grads = jax.jit(jax.value_and_grad(loss))(v["params"])
        return float(val), grads

    v0, g0 = run(False)
    for mode in (True, "full", "dots"):
        v1, g1 = run(mode)
        np.testing.assert_allclose(v0, v1, rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            g0, g1,
        )

    with pytest.raises(ValueError, match="remat"):
        remat_policy("bogus")


def test_fused_qkv_matches_unfused(rng):
    """fused_qkv computes the SAME attention as the three-GEMM layout when
    its stacked kernel carries the same weights — the fusion is a pure
    MXU-utilization change, never a numerics change."""
    import jax
    import jax.numpy as jnp

    from tfde_tpu.models.transformer import MultiHeadAttention

    x = jnp.asarray(rng.standard_normal((2, 10, 32)), jnp.float32)
    unfused = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32,
                                 causal=True)
    fused = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32,
                               causal=True, fused_qkv=True)
    pu = unfused.init(jax.random.key(0), x)["params"]
    pf = fused.init(jax.random.key(1), x)["params"]
    # map: stack [E,H,D] kernels on a new axis 1 -> [E,3,H,D]
    pf = dict(pf)
    pf["qkv"] = {
        "kernel": jnp.stack(
            [pu["query"]["kernel"], pu["key"]["kernel"],
             pu["value"]["kernel"]], axis=1,
        ),
        "bias": jnp.stack(
            [pu["query"]["bias"], pu["key"]["bias"], pu["value"]["bias"]],
            axis=0,
        ),
    }
    pf["out"] = pu["out"]
    a = unfused.apply({"params": pu}, x)
    b = fused.apply({"params": pf}, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-6, atol=1e-6)


def test_fused_qkv_rejects_gqa():
    import jax.numpy as jnp
    import pytest as _pytest

    from tfde_tpu.models.transformer import MultiHeadAttention

    m = MultiHeadAttention(num_heads=4, head_dim=8, num_kv_heads=2,
                           dtype=jnp.float32, fused_qkv=True)
    with _pytest.raises(NotImplementedError, match="fused_qkv"):
        m.init(jax.random.key(0), jnp.zeros((1, 4, 32)))


def test_fused_qkv_gpt_decodes_and_tp_matches_dp(rng):
    """fused_qkv composes with the KV-cache decode path and with Megatron
    TP (the 'qkv' kernel column-shards over heads)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.gpt import gpt_tiny_test, next_token_loss
    from tfde_tpu.parallel.strategies import (
        MultiWorkerMirroredStrategy,
        TensorParallelStrategy,
    )
    from tfde_tpu.runtime.mesh import make_mesh
    from tfde_tpu.training.step import init_state, make_custom_train_step

    model = gpt_tiny_test(fused_qkv=True)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 97, (2, 5)), jnp.int32)
    toks, _ = generate(model, params, prompt, max_new_tokens=6)
    assert toks.shape == (2, 11)

    tokens = rng.integers(0, 97, (16, 24)).astype(np.int32)
    strat_t = TensorParallelStrategy(
        make_mesh({"data": 2, "tensor": 2}, jax.devices()[:4])
    )
    state_t, _ = init_state(model, optax.adam(1e-3), strat_t, tokens)
    # the fused kernel must actually shard over 'tensor'
    qkv_leaf = jax.tree_util.tree_leaves_with_path(state_t.params)
    sharded = [
        (jax.tree_util.keystr(p), l.sharding.spec)
        for p, l in qkv_leaf if "qkv" in jax.tree_util.keystr(p)
    ]
    assert sharded and all("tensor" in str(spec) for _, spec in sharded), sharded
    step_t = make_custom_train_step(strat_t, state_t, next_token_loss,
                                    donate=False)
    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)
    key = jax.random.key(0)
    for _ in range(3):
        state_t, m_t = step_t(state_t, (tokens,), key)
        state_d, m_d = step_d(state_d, (tokens,), key)
    np.testing.assert_allclose(float(m_t["loss"]), float(m_d["loss"]),
                               rtol=2e-5)
