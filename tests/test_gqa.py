"""Grouped-query attention (models/transformer.py num_kv_heads): cache
shrinkage, decode-oracle equivalence, degenerate-case equality, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate, init_cache
from tfde_tpu.models.gpt import GPT
from tfde_tpu.models.transformer import MultiHeadAttention


def _gqa_lm(kv_heads, **kw):
    return GPT(vocab_size=83, hidden_size=32, depth=2, num_heads=4,
               mlp_dim=64, max_position=64, dtype=jnp.float32,
               num_kv_heads=kv_heads, **kw)


def test_kv_param_and_cache_shrink(rng):
    """KV projections and the decode cache carry kv_heads, not num_heads —
    the memory/bandwidth saving that motivates GQA."""
    m = _gqa_lm(1)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    attn = params["decoder"]["block_0"]["attn"]
    assert attn["query"]["kernel"].shape == (32, 4, 8)
    assert attn["key"]["kernel"].shape == (32, 1, 8)
    assert attn["value"]["kernel"].shape == (32, 1, 8)
    cache = init_cache(m, 2, 16)
    ck = cache["decoder"]["block_0"]["attn"]["cached_key"]
    assert ck.shape == (2, 16, 1, 8)


@pytest.mark.slow
def test_mqa_decode_matches_full_forward(rng):
    """Multi-query (kv=1) cached generation must equal the uncached
    full-forward rollout — the expansion happens identically either way."""
    m = _gqa_lm(1)
    params = m.init(jax.random.key(1), jnp.zeros((2, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 83, (2, 5)), jnp.int32)
    out, _ = generate(m, params, prompt, max_new_tokens=7)
    toks = np.asarray(prompt, np.int32)
    for _ in range(7):
        logits = m.apply({"params": params}, jnp.asarray(toks))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), toks)


@pytest.mark.slow
def test_gqa2_rope_decode_matches_full_forward(rng):
    """GQA composes with RoPE through the cache (rotation applies to the
    kv_heads-shaped keys before the write)."""
    m = _gqa_lm(2, position="rope")
    params = m.init(jax.random.key(2), jnp.zeros((2, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 83, (1, 4)), jnp.int32)
    out, _ = generate(m, params, prompt, max_new_tokens=6)
    toks = np.asarray(prompt, np.int32)
    for _ in range(6):
        logits = m.apply({"params": params}, jnp.asarray(toks))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), toks)


def test_full_kv_heads_equals_mha(rng):
    """num_kv_heads == num_heads is exactly classic MHA (same params, same
    math) — the degenerate-case identity."""
    x = jnp.asarray(rng.standard_normal((2, 6, 32)), jnp.float32)
    mha = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32,
                             causal=True)
    gqa = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32,
                             causal=True, num_kv_heads=4)
    params = mha.init(jax.random.key(0), x)["params"]
    np.testing.assert_allclose(
        np.asarray(mha.apply({"params": params}, x)),
        np.asarray(gqa.apply({"params": params}, x)),
        atol=0,
    )


def test_gqa_heads_share_kv(rng):
    """With kv=1 every query head attends the same K/V: perturbing the one
    KV head changes all query heads' outputs."""
    x = jnp.asarray(rng.standard_normal((1, 4, 32)), jnp.float32)
    m = MultiHeadAttention(num_heads=4, head_dim=8, dtype=jnp.float32,
                           causal=True, num_kv_heads=1)
    params = m.init(jax.random.key(0), x)["params"]
    base = np.asarray(m.apply({"params": params}, x))
    import flax

    p2 = flax.core.unfreeze(jax.tree_util.tree_map(lambda a: a, params))
    p2["value"]["kernel"] = params["value"]["kernel"] + 1.0
    out = np.asarray(m.apply({"params": p2}, x))
    assert not np.allclose(base, out)


@pytest.mark.slow
def test_gqa_trains(rng):
    import optax

    from tfde_tpu.models.gpt import next_token_loss
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    strategy = MultiWorkerMirroredStrategy()
    m = _gqa_lm(2)
    tokens = rng.integers(0, 83, (16, 16)).astype(np.int32)
    state, _ = init_state(m, optax.adamw(3e-3), strategy,
                          np.zeros((16, 16), np.int32))
    step = make_custom_train_step(strategy, state, next_token_loss,
                                  donate=False)
    state, m0 = step(state, (tokens,), jax.random.key(0))
    for _ in range(8):
        state, met = step(state, (tokens,), jax.random.key(0))
    assert float(met["loss"]) < float(m0["loss"])


def test_invalid_kv_heads_rejected():
    m = _gqa_lm(3)  # 3 does not divide 4
    with pytest.raises(ValueError, match="num_kv_heads"):
        m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    m = _gqa_lm(-4)  # 4 % -4 == 0 in Python; the sign check must catch it
    with pytest.raises(ValueError, match="positive"):
        m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))


def test_gqa_trains_under_seq_parallel_ring(rng):
    """GQA composes with the 'seq' ring: the grouped ring body rotates
    kv_heads-sized KV shards (ops/ring_attention._chunk_attention), so a
    GQA LM trains under SequenceParallelStrategy with the same numerics
    as plain DP — the oracle pattern of tests/test_train_dp.py."""
    import optax

    from tfde_tpu.parallel.strategies import (
        MultiWorkerMirroredStrategy,
        SequenceParallelStrategy,
    )
    from tfde_tpu.training.step import init_state, make_custom_train_step

    from tfde_tpu.models.gpt import next_token_loss

    tokens = rng.integers(0, 83, (8, 16)).astype(np.int32)
    losses = {}
    for name, strategy in (
        ("seq", SequenceParallelStrategy(data=2)),
        ("dp", MultiWorkerMirroredStrategy()),
    ):
        m = _gqa_lm(2)
        state, _ = init_state(m, optax.sgd(1e-2), strategy,
                              np.zeros((8, 16), np.int32))
        step = make_custom_train_step(strategy, state, next_token_loss,
                                      donate=False)
        first = None
        for _ in range(3):
            state, metrics = step(state, (tokens,), jax.random.key(0))
            if first is None:
                first = float(metrics["loss"])
        losses[name] = (first, float(metrics["loss"]))
    # identical init (same seed) -> identical first-step loss across
    # parallelism; and training moves it
    np.testing.assert_allclose(losses["seq"][0], losses["dp"][0],
                               rtol=1e-5)
    assert losses["seq"][1] != losses["seq"][0]


def test_gqa_explicit_flash_matches_reference(rng):
    """GQA routes through the flash kernel when asked (the kernel's K/V
    index maps fold each q head onto its serving KV head) — a converted
    Mistral/LLaMA checkpoint rides the O(S) path, not the O(S^2) einsum.
    S=128 with the CPU interpreter keeps the test fast; divisibility by
    the 128-lane tile is what the kernel requires."""
    def lm(impl):
        return GPT(vocab_size=83, hidden_size=32, depth=2, num_heads=4,
                   mlp_dim=64, max_position=128, dtype=jnp.float32,
                   num_kv_heads=2, attn_impl=impl)

    mf = lm("flash")
    mr = lm("reference")
    toks = jnp.asarray(rng.integers(0, 83, (2, 128)), jnp.int32)
    params = mf.init(jax.random.key(0), toks)["params"]
    got = mf.apply({"params": params}, toks, train=False)
    expect = mr.apply({"params": params}, toks, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-4)


def test_gqa_explicit_ring_requires_seq_mesh():
    """attn_impl='ring' still needs a mesh with a 'seq' axis — without one
    the GQA model fails with the dispatcher's guidance error, not silent
    shard-local math."""
    m = _gqa_lm(2, attn_impl="ring")
    with pytest.raises(ValueError, match="seq"):
        m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
