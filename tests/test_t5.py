"""T5 encoder-decoder family (models/t5.py): bucket math vs hand-derived
values, logit parity vs transformers (v1.0 relu/tied and v1.1 gated/untied),
KV-cache generation equal to HF greedy generate, conversion round trip, and
seq2seq training under DP on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.models.t5 import (
    T5,
    relative_position_bucket,
    shift_right,
    t5_generate,
    t5_seq2seq_loss,
    t5_tiny_test,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def test_bucket_math_matches_hf():
    """Oracle: transformers' own _relative_position_bucket."""
    hf_bucket = transformers.models.t5.modeling_t5.T5Attention._relative_position_bucket
    rel = torch.arange(-40, 41).reshape(1, -1)
    for bidirectional in (True, False):
        ref = hf_bucket(rel, bidirectional=bidirectional, num_buckets=8,
                        max_distance=16).numpy()
        ours = np.asarray(relative_position_bucket(
            jnp.asarray(rel.numpy()), bidirectional=bidirectional,
            num_buckets=8, max_distance=16,
        ))
        np.testing.assert_array_equal(ours, ref)
    # default config too
    ref = hf_bucket(rel, bidirectional=True).numpy()
    ours = np.asarray(relative_position_bucket(jnp.asarray(rel.numpy())))
    np.testing.assert_array_equal(ours, ref)


def test_shift_right_matches_hf_convention():
    labels = jnp.asarray([[5, 6, -100, 7], [1, -100, -100, 2]], jnp.int32)
    out = np.asarray(shift_right(labels, start_id=0))
    np.testing.assert_array_equal(
        out, [[0, 5, 6, 0], [0, 1, 0, 0]]
    )


@pytest.fixture(scope="module")
def hf_t5():
    cfg = transformers.T5Config(
        vocab_size=101, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=16, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0,
    )
    torch.manual_seed(20)
    m = transformers.T5ForConditionalGeneration(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def hf_t5_v11():
    # v1.1 arrangement: gated tanh-gelu, untied head, decoupled inner
    # attention dim (heads * d_kv = 48 != d_model 32)
    cfg = transformers.T5Config(
        vocab_size=101, d_model=32, d_kv=12, d_ff=64, num_layers=2,
        num_decoder_layers=3, num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=16, dropout_rate=0.0,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
    )
    torch.manual_seed(21)
    m = transformers.T5ForConditionalGeneration(cfg)
    m.eval()
    return m


def _logits_match(hf, rng, rtol=2e-4, atol=2e-4):
    from tfde_tpu.models.convert import t5_from_hf

    model, params = t5_from_hf(hf, dtype=jnp.float32)
    vocab = hf.config.vocab_size
    enc = rng.integers(2, vocab, (2, 10)).astype(np.int32)
    dec = rng.integers(2, vocab, (2, 7)).astype(np.int32)
    with torch.no_grad():
        ref = hf(
            input_ids=torch.tensor(enc.astype(np.int64)),
            decoder_input_ids=torch.tensor(dec.astype(np.int64)),
        ).logits.numpy()
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(enc), jnp.asarray(dec))
    )
    np.testing.assert_allclose(ours, ref, rtol=rtol, atol=atol)
    return model, params


def test_t5_logits_match(hf_t5, rng):
    """v1.0: relu MLP, tied head (d_model^-0.5 rescale), unscaled
    attention, shared relative bias — one converted forward checks all."""
    model, _ = _logits_match(hf_t5, rng)
    assert model.tie_embeddings and model.mlp_act == "relu"


def test_t5_v11_logits_match(hf_t5_v11, rng):
    """v1.1: gated tanh-gelu (gate<->wi_0), untied lm_head, inner
    attention dim != d_model, encoder/decoder depth mismatch."""
    model, params = _logits_match(hf_t5_v11, rng)
    assert not model.tie_embeddings and model.mlp_act == "geglu"
    assert model.head_dim * model.num_heads != model.hidden_size
    assert model.decoder_depth == 3
    assert "lm_head" in params


def test_t5_generate_matches_hf_greedy(hf_t5, rng):
    """The whole serving path: encoder once + cross-K/V cache + causal
    cache decode must reproduce HF's greedy generate token-for-token."""
    from tfde_tpu.models.convert import t5_from_hf

    model, params = t5_from_hf(hf_t5, dtype=jnp.float32)
    enc = rng.integers(2, 101, (2, 9)).astype(np.int32)
    new = 8
    with torch.no_grad():
        ref = hf_t5.generate(
            torch.tensor(enc.astype(np.int64)), max_new_tokens=new,
            do_sample=False, num_beams=1,
        ).numpy()
    ours, _ = t5_generate(model, params, jnp.asarray(enc),
                          max_new_tokens=new, eos_id=1)
    ours = np.asarray(ours)
    # HF stops the whole batch at its stopping criterion; compare the
    # overlapping prefix (both start with decoder_start_token_id = 0)
    n = min(ours.shape[1], ref.shape[1])
    np.testing.assert_array_equal(ours[:, :n], ref[:, :n])


def test_t5_cache_decode_equals_full_forward(rng):
    """Hermetic (no HF): teacher-forcing the generated sequence through
    the full forward must predict exactly the tokens the cached decode
    emitted — the cross-cache and self-cache paths cannot drift from the
    training forward."""
    m = t5_tiny_test()
    enc = jnp.asarray(rng.integers(0, 97, (2, 10)), jnp.int32)
    v = m.init(jax.random.key(0), enc, jnp.zeros((2, 4), jnp.int32))
    toks, _ = t5_generate(m, v["params"], enc, max_new_tokens=6,
                          eos_id=None)
    full = m.apply({"params": v["params"]}, enc, toks[:, :-1])
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(full, -1)), np.asarray(toks[:, 1:])
    )


def test_t5_roundtrip_to_hf(hf_t5, hf_t5_v11, rng):
    from tfde_tpu.models.convert import t5_from_hf, t5_to_hf

    for hf in (hf_t5, hf_t5_v11):
        model, params = t5_from_hf(hf, dtype=jnp.float32)
        hf2 = t5_to_hf(model, params)
        vocab = hf.config.vocab_size
        enc = torch.tensor(rng.integers(2, vocab, (2, 10)).astype(np.int64))
        dec = torch.tensor(rng.integers(2, vocab, (2, 6)).astype(np.int64))
        with torch.no_grad():
            a = hf(input_ids=enc, decoder_input_ids=dec).logits
            b = hf2(input_ids=enc, decoder_input_ids=dec).logits
        assert float((a - b).abs().max()) < 1e-4


def test_t5_save_load_cli_roundtrip(tmp_path, hf_t5, rng):
    from tfde_tpu.models.convert import _cli, load_converted

    src = str(tmp_path / "hf")
    art = str(tmp_path / "art")
    back = str(tmp_path / "back")
    hf_t5.save_pretrained(src)
    _cli(["t5", src, art])
    model, params = load_converted(art, dtype=jnp.float32)
    enc = rng.integers(2, 101, (1, 8)).astype(np.int32)
    dec = rng.integers(2, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_t5(
            input_ids=torch.tensor(enc.astype(np.int64)),
            decoder_input_ids=torch.tensor(dec.astype(np.int64)),
        ).logits.numpy()
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(enc), jnp.asarray(dec))
    )
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    _cli(["t5", art, back, "--reverse"])
    hf2 = transformers.T5ForConditionalGeneration.from_pretrained(
        back, local_files_only=True
    )
    with torch.no_grad():
        b = hf2(
            input_ids=torch.tensor(enc.astype(np.int64)),
            decoder_input_ids=torch.tensor(dec.astype(np.int64)),
        ).logits.numpy()
    np.testing.assert_allclose(b, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_t5_trains_under_dp(rng):
    """Seq2seq training through make_custom_train_step on the virtual
    mesh: a copy task's loss must fall."""
    import optax

    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    m = t5_tiny_test()
    s = MultiWorkerMirroredStrategy()
    enc = rng.integers(2, 97, (16, 8)).astype(np.int32)
    labels = enc.copy()  # copy task
    sample = (np.zeros((16, 8), np.int32), np.zeros((16, 8), np.int32))

    def loss_fn(state, params, batch, rng_):
        return t5_seq2seq_loss(state, params, batch, rng_)

    # init_state feeds the model one sample batch positionally
    state, _ = init_state(m, optax.adamw(3e-3), s, sample, seed=0)
    step = make_custom_train_step(s, state, loss_fn, donate=False)
    key = jax.random.key(0)
    first = last = None
    for i in range(30):
        state, metr = step(state, (enc, labels), key)
        if first is None:
            first = float(metr["loss"])
        last = float(metr["loss"])
    assert last < first * 0.7, (first, last)


def test_t5_enc_mask_teacher_forced_matches_unpadded(hf_t5, rng):
    """Right-padding the encoder input with enc_mask must reproduce the
    unpadded run's logits in the teacher-forced forward (the path review
    r5 caught passing a raw [B, S] mask where [B,1,1,S] was needed) — and
    match HF under the same attention_mask."""
    from tfde_tpu.models.convert import t5_from_hf

    model, params = t5_from_hf(hf_t5, dtype=jnp.float32)
    enc = rng.integers(2, 101, (2, 8)).astype(np.int32)
    dec = rng.integers(2, 101, (2, 5)).astype(np.int32)
    pad = np.concatenate([enc, np.zeros((2, 3), np.int32)], axis=1)
    mask = np.concatenate(
        [np.ones((2, 8), bool), np.zeros((2, 3), bool)], axis=1
    )
    unpadded = np.asarray(
        model.apply({"params": params}, jnp.asarray(enc), jnp.asarray(dec))
    )
    padded = np.asarray(
        model.apply({"params": params}, jnp.asarray(pad), jnp.asarray(dec),
                    enc_mask=jnp.asarray(mask))
    )
    np.testing.assert_allclose(padded, unpadded, rtol=1e-5, atol=1e-5)
    with torch.no_grad():
        ref = hf_t5(
            input_ids=torch.tensor(pad.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
            decoder_input_ids=torch.tensor(dec.astype(np.int64)),
        ).logits.numpy()
    np.testing.assert_allclose(padded, ref, rtol=2e-4, atol=2e-4)


def test_t5_generate_with_enc_mask_matches_unpadded(rng):
    m = t5_tiny_test()
    enc = jnp.asarray(rng.integers(1, 97, (2, 8)), jnp.int32)
    v = m.init(jax.random.key(0), enc, jnp.zeros((2, 4), jnp.int32))
    toks, _ = t5_generate(m, v["params"], enc, max_new_tokens=5,
                          eos_id=None)
    pad = jnp.concatenate([enc, jnp.zeros((2, 3), jnp.int32)], axis=1)
    mask = jnp.concatenate(
        [jnp.ones((2, 8), bool), jnp.zeros((2, 3), bool)], axis=1
    )
    toks_p, _ = t5_generate(m, v["params"], pad, max_new_tokens=5,
                            eos_id=None, enc_mask=mask)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks_p))


def test_t5_loss_start_token_follows_model_pad_id(rng):
    """Training and generation must agree on the decoder start token when
    pad_id != 0: the loss reads it off the bound model."""
    import optax

    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    m = t5_tiny_test(pad_id=3)
    s = MirroredStrategy()
    sample = (np.zeros((8, 6), np.int32), np.zeros((8, 6), np.int32))
    state, _ = init_state(m, optax.sgd(0.01), s, sample, seed=0)

    captured = {}
    orig = m.apply

    # capture the decoder inputs the loss builds (outside jit: call the
    # loss directly, not through the compiled step)
    labels = rng.integers(4, 97, (8, 6)).astype(np.int32)
    enc = rng.integers(4, 97, (8, 6)).astype(np.int32)
    dec_in = np.asarray(shift_right(jnp.asarray(labels), start_id=3))
    assert (dec_in[:, 0] == 3).all()
    # and the full loss path runs green with the non-zero pad id
    step = make_custom_train_step(s, state, t5_seq2seq_loss, donate=False)
    _, metr = step(state, (enc, labels), jax.random.key(0))
    assert np.isfinite(float(metr["loss"]))


@pytest.mark.slow
def test_t5_tp_matches_dp_numerics(rng):
    """T5 reuses the transformer vocabulary (query/key/value/out kernels,
    fc1/gate/fc2), so the Megatron TP rules shard it with NO T5-specific
    code — trained params must match pure DP to float tolerance (the
    TP==DP law every other family obeys)."""
    import optax

    from tfde_tpu.parallel.strategies import (
        MultiWorkerMirroredStrategy,
        TensorParallelStrategy,
    )
    from tfde_tpu.training.step import init_state, make_custom_train_step

    enc = rng.integers(2, 97, (16, 8)).astype(np.int32)
    labels = enc[:, ::-1].copy()

    def run(strategy):
        m = t5_tiny_test()
        sample = (np.zeros((16, 8), np.int32), np.zeros((16, 8), np.int32))
        state, _ = init_state(m, optax.sgd(0.05), strategy, sample, seed=0)
        step = make_custom_train_step(strategy, state, t5_seq2seq_loss,
                                      donate=False)
        for i in range(3):
            state, metr = step(state, (enc, labels), jax.random.key(0))
        return jax.device_get(state.params), float(metr["loss"])

    p_dp, l_dp = run(MultiWorkerMirroredStrategy())
    p_tp, l_tp = run(TensorParallelStrategy())
    # layout-parity tolerances, matching test_tensor_parallel.py: TP's
    # psum reduction order differs from DP's, so bit-exactness is not
    # the contract
    assert l_tp == pytest.approx(l_dp, rel=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p_dp, p_tp,
    )
