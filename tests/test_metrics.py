"""Metrics-registry acceptance tests (ISSUE 2, satellite 3): concurrency
safety, histogram percentile fidelity vs numpy, the Prometheus round-trip,
the legacy counters shim, spans, and the /metrics HTTP surface.

Everything here runs on a private Registry (or carefully-namespaced default
registry entries) so tests stay independent of the train-loop metrics other
tests emit into the process-wide default."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from tfde_tpu.observability import counters, metrics, spans
from tfde_tpu.observability.exposition import (
    JsonlMetricsLog,
    MetricsServer,
    PROM_CONTENT_TYPE,
    parse_prometheus_text,
    prom_name,
    to_prometheus_text,
)


# -- registry primitives ------------------------------------------------------
def test_counter_gauge_basics():
    reg = metrics.Registry()
    c = reg.counter("a/b")
    assert c.incr() == 1.0
    assert c.incr(2.5) == 3.5
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.incr(-1.0)  # counters are monotonic
    g = reg.gauge("a/g")
    g.set(7.0)
    g.add(-2.0)
    assert g.value == 5.0
    assert reg.scalars() == {"a/b": 3.5, "a/g": 5.0}


def test_get_or_create_returns_same_object_and_kind_mismatch_raises():
    reg = metrics.Registry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")  # name already registered as a counter
    with pytest.raises(ValueError):
        reg.histogram("x")


def test_concurrent_increments_preserve_totals():
    """8 threads x 2000 increments each race on one counter, one gauge and
    one histogram; no update may be lost."""
    reg = metrics.Registry()
    n_threads, n_iter = 8, 2000

    def work():
        c = reg.counter("hot/counter")
        g = reg.gauge("hot/gauge")
        h = reg.histogram("hot/hist")
        for i in range(n_iter):
            c.incr()
            g.add(1.0)
            h.observe(0.001 * (i % 50))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * n_iter
    assert reg.counter("hot/counter").value == total
    assert reg.gauge("hot/gauge").value == total
    assert reg.histogram("hot/hist").count == total


def test_histogram_percentiles_match_numpy():
    rng = np.random.default_rng(42)
    samples = rng.uniform(0.0, 2.0, 20_000)
    # fine uniform buckets over the support: interpolation error is bounded
    # by the bucket width
    buckets = tuple(np.linspace(0.0, 2.0, 41)[1:])  # width 0.05
    reg = metrics.Registry()
    h = reg.histogram("lat", buckets=buckets)
    for s in samples:
        h.observe(float(s))
    for q in (50, 95, 99):
        est = h.percentile(q)
        ref = float(np.percentile(samples, q))
        assert abs(est - ref) <= 0.05, (q, est, ref)
    # interpolated values stay inside the observed range
    snap = reg.snapshot()["lat"]
    assert snap["min"] <= h.percentile(50) <= snap["max"]
    assert snap["count"] == 20_000
    assert snap["sum"] == pytest.approx(float(samples.sum()), rel=1e-6)


def test_histogram_percentile_clamps_to_observed_extremes():
    reg = metrics.Registry()
    h = reg.histogram("one", buckets=(1.0, 10.0))
    h.observe(3.0)
    assert h.percentile(50) == 3.0  # single sample: every quantile is it
    assert h.percentile(99) == 3.0


def test_snapshot_reset_and_flatten():
    reg = metrics.Registry()
    reg.counter("train/steps").incr(5)
    reg.histogram("train/step").observe(0.2)
    reg.gauge("serving/depth").set(3)
    snap = reg.snapshot()
    assert snap["train/steps"] == {"type": "counter", "value": 5.0}
    assert snap["train/step"]["type"] == "histogram"
    flat = metrics.flatten_snapshot(snap)
    assert flat["train/steps"] == 5.0
    assert flat["train/step/count"] == 1.0
    assert flat["train/step/p95"] > 0.0
    reg.reset("train/")
    assert set(reg.snapshot()) == {"serving/depth"}


# -- the legacy counters shim -------------------------------------------------
def test_counters_shim_round_trip():
    counters.reset("shimtest/")
    counters.incr("shimtest/a")
    counters.incr("shimtest/a", 2.0)
    assert counters.value("shimtest/a") == 3.0
    assert counters.value("shimtest/never") == 0.0
    snap = counters.snapshot()
    assert snap["shimtest/a"] == 3.0
    # shim writes land in the shared default registry
    assert metrics.default_registry().counter("shimtest/a").value == 3.0
    counters.reset("shimtest/")
    assert counters.value("shimtest/a") == 0.0


def test_counters_reset_leaves_other_kinds_alone():
    reg = metrics.default_registry()
    reg.gauge("shimkeep/gauge").set(1.0)
    counters.incr("shimkeep/c")
    counters.reset("shimkeep/")
    assert counters.value("shimkeep/c") == 0.0
    assert reg.gauge("shimkeep/gauge").value == 1.0
    reg.reset("shimkeep/")


# -- spans --------------------------------------------------------------------
def test_span_records_into_histogram_even_on_raise():
    reg = metrics.Registry()
    with spans.span("unit/ok", registry=reg):
        pass
    with pytest.raises(RuntimeError):
        with spans.span("unit/ok", registry=reg):
            raise RuntimeError("boom")
    h = reg.get("unit/ok")
    assert h.count == 2
    spans.record("unit/ext", 1.5, registry=reg)
    assert reg.get("unit/ext").sum == 1.5


# -- Prometheus exposition ----------------------------------------------------
def test_prom_name_sanitizes():
    assert prom_name("train/step") == "tfde_train_step"
    assert prom_name("a-b.c d") == "tfde_a_b_c_d"


def test_prometheus_round_trip():
    reg = metrics.Registry()
    reg.counter("train/steps").incr(17)
    reg.gauge("train/steps_per_sec").set(3.25)
    h = reg.histogram("train/step", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    text = to_prometheus_text(registry=reg)
    # counters carry the _total convention, histograms the classic triplet
    assert "tfde_train_steps_total 17.0" in text
    assert 'tfde_train_step_bucket{le="+Inf"} 4' in text
    back = parse_prometheus_text(text)
    assert back["tfde_train_steps_total"]["type"] == "counter"
    assert back["tfde_train_steps_total"]["value"] == 17.0
    assert back["tfde_train_steps_per_sec"]["value"] == 3.25
    hist = back["tfde_train_step"]
    assert hist["count"] == 4
    assert hist["sum"] == pytest.approx(6.05)
    assert dict(hist["buckets"]) == {0.1: 1, 1.0: 3, 10.0: 4}  # cumulative


def test_metrics_server_serves_prometheus_and_json():
    reg = metrics.Registry()
    reg.counter("srv/hits").incr(3)
    srv = MetricsServer(port=0, host="127.0.0.1", registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert r.headers["Content-Type"] == PROM_CONTENT_TYPE
            body = r.read().decode()
        assert "tfde_srv_hits_total 3" in body
        with urllib.request.urlopen(base + "/metrics.json", timeout=5) as r:
            flat = json.loads(r.read().decode())
        assert flat["srv/hits"] == 3.0
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        srv.close()


def test_jsonl_metrics_log(tmp_path):
    reg = metrics.Registry()
    reg.counter("j/steps").incr(2)
    log = JsonlMetricsLog(str(tmp_path), registry=reg)
    log.write(1)
    reg.counter("j/steps").incr()
    log.write(2, extra={"note": 1.0})
    log.close()
    lines = [json.loads(l) for l in open(log.path)]
    assert [l["step"] for l in lines] == [1, 2]
    assert lines[0]["metrics"]["j/steps"] == 2.0
    assert lines[1]["metrics"]["j/steps"] == 3.0
    assert lines[1]["metrics"]["note"] == 1.0
