"""End-to-end data-parallel training on the 8-device CPU mesh.

The minimum slice of SURVEY.md §7: Flax CNN + host pipeline + jit DP step with
XLA-inserted psum. Asserts loss decreases (the reference's only observable
training signal beyond accuracy, SURVEY.md §4) and that single-device and
8-way-DP runs agree numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfde_tpu.data import Dataset, device_prefetch, datasets
from tfde_tpu.models.cnn import PlainCNN, BatchNormCNN
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    ParameterServerStrategy,
    FSDPStrategy,
)
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import init_state, make_train_step, make_eval_step


def _mnist_batches(batch=64, steps=10, flatten=False):
    (tx, ty), _ = datasets.mnist(flatten=flatten, n_train=1024, n_test=128)
    ds = (
        Dataset.from_tensor_slices((tx, ty))
        .shuffle(len(tx), seed=0)
        .repeat()
        .batch(batch, drop_remainder=True)
    )
    it = iter(ds)
    return [next(it) for _ in range(steps)]


def _run(strategy, model, batches, lr=0.05, momentum=None, seed=0):
    sample = jnp.asarray(batches[0][0])
    state, _ = init_state(model, optax.sgd(lr, momentum=momentum), strategy, sample, seed=seed)
    step = make_train_step(strategy, state)
    rng = jax.random.key(seed)
    losses = []
    for dev_batch in device_prefetch(batches, strategy.mesh):
        state, m = step(state, dev_batch, rng)
        losses.append(float(m["loss"]))
    return state, losses


def test_dp_loss_decreases_plain_cnn():
    strat = MultiWorkerMirroredStrategy()
    batches = _mnist_batches(batch=64, steps=30)
    _, losses = _run(strat, PlainCNN(), batches, lr=0.2, momentum=0.9)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) * 0.9, losses


@pytest.mark.slow
def test_dp_loss_decreases_bn_cnn_with_dropout_and_stats():
    strat = MultiWorkerMirroredStrategy()
    batches = _mnist_batches(batch=64, steps=12, flatten=True)
    state, losses = _run(strat, BatchNormCNN(), batches, lr=0.2, momentum=0.9)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]) * 0.5, losses
    # running stats must have moved off init
    mean_leaf = jax.tree_util.tree_leaves(state.batch_stats)[0]
    assert float(jnp.abs(np.asarray(mean_leaf)).sum()) > 0


def test_dp_matches_single_device_numerics(monkeypatch):
    """8-way DP and 1-device runs must produce the same params (sync DP is
    math-identical to single-device large-batch SGD). An exact-parity
    property of the fp32 exchange, so pin the transport: under
    `TFDE_GRAD_TRANSPORT=int8 tools/tier1.sh` the 8-way side would
    quantize while the 1-device side falls back (nothing to exchange)."""
    monkeypatch.setenv("TFDE_GRAD_TRANSPORT", "fp32")
    batches = _mnist_batches(batch=64, steps=5)
    model = PlainCNN()

    dp = MultiWorkerMirroredStrategy()
    single = MultiWorkerMirroredStrategy(
        mesh=make_mesh({"data": 1}, devices=jax.devices()[:1])
    )
    s_dp, _ = _run(dp, model, batches)
    s_1, _ = _run(single, model, batches)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_dp.params), jax.tree_util.tree_leaves(s_1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_zero1_ps_strategy_shards_opt_state_and_matches_dp():
    batches = _mnist_batches(batch=64, steps=5)
    model = PlainCNN()
    ps = ParameterServerStrategy(min_shard_elems=1024)
    s_ps, losses = _run(ps, model, batches)
    # sharded opt state: at least one momentum-free SGD has no slots; use adam
    import optax

    state, shardings = init_state(
        model, optax.adam(1e-3), ps, jnp.asarray(batches[0][0])
    )
    specs = [
        s.spec
        for s in jax.tree_util.tree_leaves(
            shardings.opt_state, is_leaf=lambda x: hasattr(x, "spec")
        )
    ]
    assert any(any(ax == "data" for ax in s if ax) for s in specs), specs
    # and numerics still match plain DP
    dp = MultiWorkerMirroredStrategy()
    s_dp, _ = _run(dp, model, batches)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_ps.params), jax.tree_util.tree_leaves(s_dp.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fsdp_strategy_shards_params(monkeypatch):
    # exact FSDP-vs-DP parity is an fp32-exchange property: under an
    # int8 sweep the DP oracle would quantize while the FSDP mesh
    # warn-falls-back (model axes > 1), so pin the transport
    monkeypatch.setenv("TFDE_GRAD_TRANSPORT", "fp32")
    batches = _mnist_batches(batch=64, steps=5)
    model = PlainCNN()
    fsdp = FSDPStrategy(data=2, min_shard_elems=256)
    state, shardings = init_state(
        model, optax.sgd(0.05), fsdp, jnp.asarray(batches[0][0])
    )
    specs = [
        s.spec
        for s in jax.tree_util.tree_leaves(
            shardings.params, is_leaf=lambda x: hasattr(x, "spec")
        )
    ]
    assert any(any(ax == "fsdp" for ax in s if ax) for s in specs), specs
    s_fsdp, losses = _run(fsdp, model, batches)
    # numerics match plain DP
    dp = MultiWorkerMirroredStrategy()
    s_dp, _ = _run(dp, model, batches)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_fsdp.params), jax.tree_util.tree_leaves(s_dp.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_eval_step_runs_without_mutating_stats():
    from tfde_tpu.training.step import pad_batch_for_mesh

    strat = MultiWorkerMirroredStrategy()
    batches = _mnist_batches(batch=64, steps=3, flatten=True)
    model = BatchNormCNN()
    state, _ = init_state(model, optax.sgd(0.05), strat, jnp.asarray(batches[0][0]))
    ev = make_eval_step(strat, state)
    padded = pad_batch_for_mesh(batches[0], strat.batch_divisor)
    m = ev(state, next(iter(device_prefetch([padded], strat.mesh))))
    assert set(m) == {"loss_sum", "correct_sum", "weight"}
    assert np.isfinite(float(m["loss_sum"]))


def test_eval_masked_padding_exact_metrics():
    """Padding a ragged batch must not change the metrics: a 50-example batch
    padded to 56 (divisor 8) counts only the 50 real examples."""
    from tfde_tpu.training.step import pad_batch_for_mesh

    strat = MultiWorkerMirroredStrategy()
    (tx, ty), _ = datasets.mnist(flatten=False, n_train=64, n_test=1)
    model = PlainCNN()
    state, _ = init_state(model, optax.sgd(0.1), strat, jnp.asarray(tx[:8]))
    ev = make_eval_step(strat, state)

    ragged = (tx[:50], ty[:50])
    padded = pad_batch_for_mesh(ragged, strat.batch_divisor)
    assert padded[0].shape[0] == 56 and float(padded[2].sum()) == 50
    m = ev(state, next(iter(device_prefetch([padded], strat.mesh))))
    assert float(m["weight"]) == 50.0

    # reference value: same 50 examples with no padding via divisor-1 path
    single = MultiWorkerMirroredStrategy(
        mesh=make_mesh({"data": 1}, devices=jax.devices()[:1])
    )
    state1, _ = init_state(model, optax.sgd(0.1), single, jnp.asarray(tx[:8]))
    ev1 = make_eval_step(single, state1)
    exact = pad_batch_for_mesh(ragged, 1)
    m1 = ev1(state1, next(iter(device_prefetch([exact], single.mesh))))
    np.testing.assert_allclose(float(m["loss_sum"]), float(m1["loss_sum"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(m["correct_sum"]), float(m1["correct_sum"]), rtol=1e-6
    )


def test_grad_norm_metric_emitted(rng):
    """Both step builders emit a finite, positive global grad_norm — the
    divergence/clipping telemetry the lifecycle summarizes."""
    import optax

    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    strategy = MultiWorkerMirroredStrategy()
    state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy,
                          np.zeros((16, 784), np.float32))
    step = make_train_step(strategy, state, donate=False)
    images = rng.standard_normal((16, 784)).astype(np.float32)
    labels = rng.integers(0, 10, (16,)).astype(np.int32)
    _, m = step(state, (jnp.asarray(images), jnp.asarray(labels)),
                jax.random.key(0))
    gn = float(m["grad_norm"])
    assert np.isfinite(gn) and gn > 0.0
