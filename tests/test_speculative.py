"""Speculative decoding (inference/speculative.py): the output must equal
the target model's plain greedy generate() token for token — regardless of
draft quality, draft size, or acceptance pattern. Draft quality changes
only the speed, never the text; these tests pin the text."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.speculative import generate_speculative
from tfde_tpu.models.gpt import GPT, gpt_tiny_test


@pytest.fixture(scope="module")
def target():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


@pytest.fixture(scope="module")
def draft():
    """Smaller and differently-initialized: a WRONG draft — proposals will
    frequently be rejected, exercising the partial-acceptance rewinds."""
    m = GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2, mlp_dim=32,
            max_position=64, dtype=jnp.float32)
    params = m.init(jax.random.key(9), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


@pytest.mark.parametrize("num_draft", [1, 2, 4, 8])
def test_matches_target_greedy_any_draft_size(target, draft, rng, num_draft):
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray(rng.integers(0, 97, (1, 5)), jnp.int32)
    ref, ref_len = generate(model, params, prompt, max_new_tokens=12)
    out, out_len = generate_speculative(
        model, dmodel, params, dparams, prompt, max_new_tokens=12,
        num_draft=num_draft,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(out_len), np.asarray(ref_len))


def test_perfect_draft_full_acceptance(target, rng):
    """Draft == target: every proposal accepted, every round commits
    num_draft+1 tokens — and the text still matches plain greedy."""
    model, params = target
    prompt = jnp.asarray(rng.integers(0, 97, (1, 4)), jnp.int32)
    ref, _ = generate(model, params, prompt, max_new_tokens=10)
    out, _ = generate_speculative(
        model, model, params, params, prompt, max_new_tokens=10, num_draft=3
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_eos_matches_generate(target, draft, rng):
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray(rng.integers(0, 97, (1, 4)), jnp.int32)
    free, _ = generate(model, params, prompt, max_new_tokens=10)
    eos = int(np.asarray(free)[0, 6])  # third generated token
    ref, ref_len = generate(model, params, prompt, max_new_tokens=10,
                            eos_id=eos, pad_id=0)
    out, out_len = generate_speculative(
        model, dmodel, params, dparams, prompt, max_new_tokens=10,
        num_draft=4, eos_id=eos, pad_id=0,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(out_len), np.asarray(ref_len))


def test_rope_gqa_target(draft, rng):
    """Cache-index surgery works for rope models (no position table, no
    position_index counter) and GQA caches."""
    dmodel, dparams = draft
    m = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=64, dtype=jnp.float32, position="rope",
            num_kv_heads=2)
    params = m.init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 97, (1, 6)), jnp.int32)
    ref, _ = generate(m, params, prompt, max_new_tokens=9)
    out, _ = generate_speculative(
        m, dmodel, params, dparams, prompt, max_new_tokens=9, num_draft=3
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_single_token_prompt(target, draft):
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray([[7]], jnp.int32)
    ref, _ = generate(model, params, prompt, max_new_tokens=8)
    out, _ = generate_speculative(
        model, dmodel, params, dparams, prompt, max_new_tokens=8, num_draft=2
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_rejects_bad_args(target, draft):
    model, params = target
    dmodel, dparams = draft
    with pytest.raises(ValueError, match="num_draft"):
        generate_speculative(model, dmodel, params, dparams,
                             jnp.zeros((1, 4), jnp.int32), max_new_tokens=4,
                             num_draft=0)


def test_batched_matches_per_row_greedy(target, draft, rng):
    """Batch 4 with a WRONG draft: per-row acceptance lengths diverge
    every round, so the per-row cache-index rewind is fully exercised —
    and every row must still equal its own solo greedy generate()
    (generate() is row-independent, so the batched reference IS the
    per-row reference)."""
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray(rng.integers(0, 97, (4, 5)), jnp.int32)
    ref, ref_len = generate(model, params, prompt, max_new_tokens=12)
    out, out_len = generate_speculative(
        model, dmodel, params, dparams, prompt, max_new_tokens=12,
        num_draft=4,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(out_len), np.asarray(ref_len))
    # and each row equals its own solo run (belt and braces for the
    # per-row independence claim)
    for r in range(4):
        solo, solo_len = generate(
            model, params, prompt[r : r + 1], max_new_tokens=12
        )
        np.testing.assert_array_equal(np.asarray(out)[r], np.asarray(solo)[0])
        assert int(out_len[r]) == int(solo_len[0])


def test_batched_rope_gqa(draft, rng):
    """Per-row indices compose with rope (per-row rotation offsets) and
    GQA caches."""
    dmodel, dparams = draft
    m = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=64, dtype=jnp.float32, position="rope",
            num_kv_heads=2)
    params = m.init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 97, (3, 6)), jnp.int32)
    ref, _ = generate(m, params, prompt, max_new_tokens=9)
    out, _ = generate_speculative(
        m, dmodel, params, dparams, prompt, max_new_tokens=9, num_draft=3
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_batched_eos_rows_finish_independently(target, draft, rng):
    """Rows hit EOS at different times; finished rows freeze (pad fill)
    while the rest keep generating — matching generate()'s semantics."""
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray(rng.integers(0, 97, (3, 4)), jnp.int32)
    free, _ = generate(model, params, prompt, max_new_tokens=10)
    # an eos that appears at different offsets across rows (fall back to
    # any generated token if the rows happen to agree — still a valid run)
    eos = int(np.asarray(free)[0, 6])
    ref, ref_len = generate(model, params, prompt, max_new_tokens=10,
                            eos_id=eos, pad_id=0)
    out, out_len = generate_speculative(
        model, dmodel, params, dparams, prompt, max_new_tokens=10,
        num_draft=4, eos_id=eos, pad_id=0,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(out_len), np.asarray(ref_len))


def test_batched_sampled_reproducible(target, draft):
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray([[5, 9], [2, 11], [40, 1], [8, 8]], jnp.int32)
    kw = dict(max_new_tokens=8, num_draft=3, temperature=0.7,
              rng=jax.random.key(11))
    a, la = generate_speculative(model, dmodel, params, dparams, prompt, **kw)
    b, lb = generate_speculative(model, dmodel, params, dparams, prompt, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.slow
def test_sampled_mode_matches_target_distribution():
    """Speculative SAMPLING correctness (the Leviathan theorem): the round's
    committed token must be distributed as target-model sampling,
    REGARDLESS of the (wrong) draft. Tiny vocab so every sample carries
    signal: compare the empirical MARGINAL of the round-produced second
    token over many seeded runs against the analytic marginal
    sum_i p_t(t1=i) p_t(t2=j | t1=i). Deterministic: fixed seeds, CPU."""
    vocab, temp, n = 13, 1.0, 1200
    model = GPT(vocab_size=vocab, hidden_size=16, depth=1, num_heads=2,
                mlp_dim=32, max_position=16, dtype=jnp.float32)
    params = model.init(jax.random.key(2), jnp.zeros((1, 4), jnp.int32))["params"]
    dmodel = GPT(vocab_size=vocab, hidden_size=8, depth=1, num_heads=1,
                 mlp_dim=16, max_position=16, dtype=jnp.float32)
    dparams = dmodel.init(jax.random.key(8), jnp.zeros((1, 4), jnp.int32))["params"]
    prompt = jnp.asarray([[3, 7]], jnp.int32)

    seconds = []
    for i in range(n):
        out, _ = generate_speculative(
            model, dmodel, params, dparams, prompt, max_new_tokens=2,
            num_draft=1, temperature=temp, rng=jax.random.key(i),
        )
        seconds.append(int(np.asarray(out)[0, 3]))

    # analytic marginal: p(t2=j) = sum_i p(t1=i) p(t2=j | prompt+[i])
    p1 = np.asarray(jax.nn.softmax(
        model.apply({"params": params}, prompt)[0, -1] / temp
    ))
    ctxs = jnp.concatenate(
        [jnp.tile(prompt, (vocab, 1)),
         jnp.arange(vocab, dtype=jnp.int32)[:, None]], axis=1
    )
    p2_given = np.asarray(jax.nn.softmax(
        model.apply({"params": params}, ctxs)[:, -1] / temp, axis=-1
    ))
    expected = p1 @ p2_given  # [vocab]
    empirical = np.bincount(seconds, minlength=vocab) / n
    tv = 0.5 * np.abs(empirical - expected).sum()
    assert tv < 0.07, f"total variation {tv:.3f} vs target marginal"


def test_sampled_mode_reproducible_and_respects_eos(target, draft):
    model, params = target
    dmodel, dparams = draft
    prompt = jnp.asarray([[5, 9]], jnp.int32)
    kw = dict(max_new_tokens=8, num_draft=3, temperature=0.7,
              rng=jax.random.key(11))
    a, la = generate_speculative(model, dmodel, params, dparams, prompt, **kw)
    b, lb = generate_speculative(model, dmodel, params, dparams, prompt, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    eos = int(np.asarray(a)[0, 2])  # first generated token
    c, lc = generate_speculative(model, dmodel, params, dparams, prompt,
                                 max_new_tokens=8, num_draft=3,
                                 temperature=0.7, rng=jax.random.key(11),
                                 eos_id=eos, pad_id=0)
    assert int(lc[0]) == 3  # prompt 2 + the EOS token
    assert (np.asarray(c)[0, 3:] == 0).all()
