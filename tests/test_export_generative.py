"""Generative serving export (export/generative.py): the exported StableHLO
decode loop must reproduce the in-process generate() exactly, round-trip
through deserialization, and work on remote filesystems."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.export.generative import export_generate, load_generate
from tfde_tpu.inference.decode import generate
from tfde_tpu.models.gpt import gpt_tiny_test


@pytest.fixture(scope="module")
def tiny_lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((2, 8), jnp.int32))["params"]
    return m, params


def test_exported_generate_matches_inprocess(tmp_path, tiny_lm, rng):
    model, params = tiny_lm
    d = export_generate(model, params, str(tmp_path), prompt_len=5,
                        max_new_tokens=6, batch_size=2, temperature=0.9,
                        top_k=8)
    served = load_generate(d)
    prompt = rng.integers(0, 97, (2, 5)).astype(np.int32)
    toks, lengths = served.generate(prompt, seed=3)
    ref_toks, ref_lengths = generate(
        model, params, jnp.asarray(prompt), max_new_tokens=6,
        rng=jax.random.key(3), temperature=0.9, top_k=8,
    )
    np.testing.assert_array_equal(toks, np.asarray(ref_toks))
    np.testing.assert_array_equal(lengths, np.asarray(ref_lengths))
    assert served.signature["sampling"]["top_k"] == 8


def test_load_resolves_newest_timestamp(tmp_path, tiny_lm):
    model, params = tiny_lm
    export_generate(model, params, str(tmp_path), prompt_len=4,
                    max_new_tokens=2)
    served = load_generate(str(tmp_path))  # parent dir
    toks, _ = served.generate(np.zeros((1, 4), np.int32))
    assert toks.shape == (1, 6)


def test_generative_artifact_on_remote_fs(tiny_lm):
    model, params = tiny_lm
    d = export_generate(model, params, "memory://exports/gen", prompt_len=4,
                        max_new_tokens=3)
    served = load_generate(d)
    toks, _ = served.generate(np.zeros((1, 4), np.int32), seed=1)
    assert toks.shape == (1, 7)


def test_load_generate_rejects_classifier_artifact(tmp_path, tiny_lm):
    from tfde_tpu.export.serving import export_serving

    model, params = tiny_lm
    d = export_serving(
        lambda v, x: model.apply({"params": v["params"]}, x),
        {"params": params}, (None, 8), str(tmp_path),
        input_dtype=jnp.int32, apply_softmax=False,
    )
    with pytest.raises(ValueError, match="not a generative artifact"):
        load_generate(d)


def test_load_serving_rejects_generative_artifact(tmp_path, tiny_lm):
    """The kind check is bidirectional: pointing the classifier loader at a
    generative artifact must fail with guidance, not an arity error at the
    first predict()."""
    from tfde_tpu.export.serving import load_serving

    model, params = tiny_lm
    d = export_generate(model, params, str(tmp_path), prompt_len=4,
                        max_new_tokens=2)
    with pytest.raises(ValueError, match="load_generate"):
        load_serving(d)
