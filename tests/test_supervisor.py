"""Supervisor acceptance tests (ISSUE 1 criterion): under an injected
failure schedule — SIGTERM at step k, transient checkpoint-save IOError,
stalled step — a supervised run resumes from the last committed checkpoint
and reaches the target step with final params IDENTICAL to an uninterrupted
run on the same data order.

Methodology: deterministic CPU mesh (the 8 virtual devices from conftest),
ONE constant batch every step so the objective is independent of how many
batches a failed attempt consumed — bit-exact resume is then decidable by
comparing a params digest against an uninterrupted oracle. All runs happen
in-process: the supervisor's resume_on_preemption mode turns the guard's
post-commit signal re-raise into a `Preempted` restart, which is exactly the
single-process pool-simulation it exists for (test_preemption.py keeps
covering the real exit-by-signal path in subprocesses).
"""

import hashlib
import signal

import jax
import numpy as np
import optax
import pytest

from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import counters
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.resilience import (
    DelayFault,
    FaultInjector,
    FaultSchedule,
    RaiseFault,
    RetryPolicy,
    SignalFault,
    StepFaults,
    Supervisor,
    SupervisorAborted,
    SupervisorConfig,
)
from tfde_tpu.training.lifecycle import Estimator, RunConfig

MAX_STEPS = 12
SAVE_EVERY = 4

_rngd = np.random.default_rng(0)
IMAGES = _rngd.random((32, 784), np.float32)
LABELS = _rngd.integers(0, 10, (32, 1)).astype(np.int32)


def constant_input_fn():
    def gen():
        while True:
            yield (IMAGES, LABELS)

    return gen()


def make_factory(model_dir):
    def factory():
        return Estimator(
            model=PlainCNN(),
            optimizer=optax.sgd(0.1),
            strategy=MirroredStrategy(),
            config=RunConfig(
                model_dir=model_dir,
                save_checkpoints_steps=SAVE_EVERY,
                save_summary_steps=10_000,
                log_step_count_steps=10_000,
            ),
        )

    return factory


def fast_restart(**kw):
    kw.setdefault("restart_policy",
                  RetryPolicy(initial_backoff=0.01, jitter=0.0))
    return SupervisorConfig(**kw)


def digest(state) -> str:
    h = hashlib.sha256()
    flat, _ = jax.tree_util.tree_flatten_with_path(jax.device_get(state.params))
    for path, leaf in sorted(flat, key=lambda kv: str(kv[0])):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


@pytest.fixture(scope="module")
def oracle(tmp_path_factory):
    """Digest of an uninterrupted run on the same data order."""
    est = make_factory(str(tmp_path_factory.mktemp("oracle")))()
    state = est.train(constant_input_fn, MAX_STEPS)
    est.close()
    return digest(state)


# -- the acceptance schedule --------------------------------------------------
def test_sigterm_at_step_k_resumes_bit_exact(tmp_path, oracle):
    d = str(tmp_path / "run")
    faults = StepFaults({7: SignalFault(signal.SIGTERM)})
    sup = Supervisor(
        make_factory(d),
        fast_restart(max_restarts=3, resume_on_preemption=True),
    )
    state = sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert int(jax.device_get(state.step)) == MAX_STEPS
    assert sup.restarts == 1  # one preemption, one resume
    # the guard force-saved on the way out: the resumed attempt started
    # from a committed step, not from zero
    assert CheckpointManager(d + "/checkpoints").latest_step == MAX_STEPS
    assert digest(state) == oracle


def test_transient_save_ioerror_restarts_bit_exact(tmp_path, oracle):
    counters.reset("resilience/")
    d = str(tmp_path / "run")
    # the 2nd periodic save (step 8) dies with IOError — past the internal
    # retry (the class-level patch replaces CheckpointManager.save whole),
    # so the supervisor's restart-from-step-4 path is what's under test
    inj = FaultInjector(FaultSchedule.fail_on(2, exc_type=IOError,
                                              message="transient gs:// blip"))
    with inj.patch(CheckpointManager, "save"):
        sup = Supervisor(make_factory(d), fast_restart(max_restarts=3))
        state = sup.run(constant_input_fn, MAX_STEPS)
    assert int(jax.device_get(state.step)) == MAX_STEPS
    assert sup.restarts == 1
    assert digest(state) == oracle
    assert counters.value("resilience/failures_transient") == 1
    assert counters.value("resilience/restarts") == 1


def test_stalled_step_escalates_to_checkpoint_and_restart(tmp_path, oracle):
    counters.reset("resilience/")
    d = str(tmp_path / "run")
    # step 6's batch draw hangs for 12s; the 4s watchdog SIGTERMs the
    # process -> guard force-saves -> supervisor restarts from the commit
    faults = StepFaults({6: DelayFault(seconds=12.0)})
    sup = Supervisor(
        make_factory(d),
        fast_restart(max_restarts=3, resume_on_preemption=True,
                     stall_timeout_secs=4.0),
    )
    state = sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert int(jax.device_get(state.step)) == MAX_STEPS
    assert sup.restarts == 1
    assert digest(state) == oracle
    assert counters.value("resilience/stalls_detected") >= 1


# -- bounds and classification ------------------------------------------------
def test_poison_failure_aborts_without_restart(tmp_path):
    faults = StepFaults({3: RaiseFault(exc_type=ValueError,
                                       message="malformed example")})
    sup = Supervisor(make_factory(str(tmp_path / "p")),
                     fast_restart(max_restarts=5))
    with pytest.raises(SupervisorAborted) as ei:
        sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 0  # poison never earns a restart
    assert isinstance(ei.value.__cause__, ValueError)


def test_restart_budget_is_bounded(tmp_path):
    # every attempt dies at its 2nd batch draw — transient by type,
    # but the budget must stop the loop
    faults = StepFaults({2: RaiseFault(exc_type=IOError)}, fires_once=False)
    sup = Supervisor(make_factory(str(tmp_path / "b")),
                     fast_restart(max_restarts=1, no_progress_limit=99))
    with pytest.raises(SupervisorAborted, match="budget"):
        sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 1


def test_no_forward_progress_aborts(tmp_path):
    # fails before the first checkpoint every time: restarts would never
    # advance the committed step, so the progress bound aborts well before
    # the (large) restart budget
    faults = StepFaults({2: RaiseFault(exc_type=IOError)}, fires_once=False)
    sup = Supervisor(make_factory(str(tmp_path / "np")),
                     fast_restart(max_restarts=50, no_progress_limit=2))
    with pytest.raises(SupervisorAborted, match="progress"):
        sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts < 50


def test_clean_run_needs_no_restarts(tmp_path, oracle):
    sup = Supervisor(make_factory(str(tmp_path / "c")), fast_restart())
    state = sup.run(constant_input_fn, MAX_STEPS)
    assert sup.restarts == 0
    assert digest(state) == oracle
