"""Loss-value tests against hand-computed references (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from tfde_tpu.ops import losses, metrics


def test_ce_matches_hand_computed():
    logits = jnp.array([[2.0, 0.0, 0.0], [0.0, 3.0, 0.0]])
    labels = jnp.array([0, 1])
    # per-example: -log softmax[label]
    e = np.exp([2.0, 0.0, 0.0])
    l0 = -np.log(e[0] / e.sum())
    e1 = np.exp([0.0, 3.0, 0.0])
    l1 = -np.log(e1[1] / e1.sum())
    got = losses.sparse_categorical_crossentropy(logits, labels)
    np.testing.assert_allclose(float(got), (l0 + l1) / 2, rtol=1e-6)


def test_ce_sum_over_global_batch_convention():
    # sum x 1/global_batch (tf2_mnist:81-83): with explicit global batch 8 and
    # only 2 local rows, denominator must still be 8.
    logits = jnp.zeros((2, 4))
    labels = jnp.array([1, 2])
    got = losses.sparse_categorical_crossentropy(logits, labels, global_batch_size=8)
    np.testing.assert_allclose(float(got), 2 * np.log(4) / 8, rtol=1e-6)


def test_ce_from_probs():
    import jax
    logits = jnp.array([[1.0, 2.0, 0.5], [0.1, 0.1, 3.0]])
    labels = jnp.array([2, 0])
    probs = jax.nn.softmax(logits, axis=-1)
    a = losses.sparse_categorical_crossentropy(logits, labels)
    b = losses.sparse_categorical_crossentropy(probs, labels, from_logits=False)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


def test_column_vector_labels_accepted():
    # reference labels are [N,1] int columns (mnist_keras:215-216)
    logits = jnp.zeros((4, 10))
    labels = jnp.ones((4, 1), jnp.int32)
    loss = losses.sparse_categorical_crossentropy(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(10), rtol=1e-6)
    acc = metrics.accuracy(logits + jnp.eye(10)[1] * 5, labels)
    assert float(acc) == 1.0
