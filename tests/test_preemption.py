"""Preemption-safe checkpointing (VERDICT r4 weak #6): a SIGTERM mid-run
must force-save, wait for the async commit, and exit with the signal's
semantics — and the restarted process must resume BIT-EXACTLY where the
preempted one stopped (the restart-tolerance contract,
/root/reference/mnist_keras_distributed.py:245-248, extended to preemption:
TPU pools SIGTERM their workers).

Methodology: three subprocesses on CPU. Run A trains uninterrupted to
max_steps and records a params digest. Run B (fresh model_dir, same seed,
constant per-step batch so resume order cannot matter) is SIGTERMed mid-loop:
it must die BY the signal (returncode -SIGTERM, not 0 — the run must not
pretend it finished) yet leave a committed checkpoint at the step it reached.
Run C resumes B's model_dir to max_steps; its digest must equal run A's —
zero lost steps, zero replayed steps.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

_CHILD = r"""
import hashlib, json, sys, time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import optax

from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.training.lifecycle import Estimator, RunConfig

model_dir, out_json, sentinel = sys.argv[1], sys.argv[2], sys.argv[3]
max_steps = int(sys.argv[4])

rngd = np.random.default_rng(0)
# ONE constant batch every step: the objective is then independent of how
# many batches a previous process consumed, so bit-exact resume is decidable
images = rngd.random((32, 784), np.float32)
labels = rngd.integers(0, 10, (32, 1)).astype(np.int32)


def input_fn():
    def gen():
        i = 0
        while True:
            i += 1
            if i == 6:
                with open(sentinel, "w") as f:
                    f.write("go")
            time.sleep(0.05)  # paces the loop so the signal lands mid-run
            yield (images, labels)
    return gen()


resumed_from = CheckpointManager(model_dir + "/checkpoints").latest_step or 0
est = Estimator(
    model=PlainCNN(), optimizer=optax.sgd(0.1),
    strategy=MirroredStrategy(),
    config=RunConfig(model_dir=model_dir,
                     save_checkpoints_steps=10_000,  # only preemption saves
                     save_summary_steps=10_000,
                     log_step_count_steps=10_000),
)
state = est.train(input_fn, max_steps=max_steps)
h = hashlib.sha256()
flat, _ = jax.tree_util.tree_flatten_with_path(jax.device_get(state.params))
for path, leaf in sorted(flat, key=lambda kv: str(kv[0])):
    h.update(np.asarray(leaf).tobytes())
with open(out_json, "w") as f:
    json.dump({"final_step": int(jax.device_get(state.step)),
               "resumed_from": int(resumed_from),
               "digest": h.hexdigest()}, f)
"""

MAX_STEPS = 30


def _run_child(tmp_path, tag: str, model_dir: str):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    out_json = str(tmp_path / f"{tag}.json")
    sentinel = str(tmp_path / f"{tag}.sentinel")
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.Popen(
        [sys.executable, str(script), model_dir, out_json, sentinel,
         str(MAX_STEPS)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    return proc, out_json, sentinel


def _wait_for(path: str, proc, timeout_s: float = 240.0) -> None:
    t0 = time.time()
    while not os.path.exists(path):
        if proc.poll() is not None:
            _, err = proc.communicate()
            raise AssertionError(
                f"child exited rc={proc.returncode} before {path}:\n"
                f"{err[-2000:]}"
            )
        if time.time() - t0 > timeout_s:
            proc.kill()
            raise AssertionError(f"timed out waiting for {path}")
        time.sleep(0.1)


def test_sigterm_saves_and_resume_is_bit_exact(tmp_path):
    # Run A: uninterrupted oracle
    proc, out_a, _ = _run_child(tmp_path, "a", str(tmp_path / "dir_a"))
    _wait_for(out_a, proc)
    proc.wait(timeout=60)
    assert proc.returncode == 0
    a = json.load(open(out_a))
    assert a["final_step"] == MAX_STEPS and a["resumed_from"] == 0

    # Run B: SIGTERM mid-loop
    dir_b = str(tmp_path / "dir_b")
    proc, out_b, sentinel = _run_child(tmp_path, "b", dir_b)
    _wait_for(sentinel, proc)
    time.sleep(0.3)  # let a few more steps land
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=120)
    # killed BY the re-raised signal after the save — not a fake-clean exit
    assert proc.returncode == -signal.SIGTERM
    assert not os.path.exists(out_b)  # train() never returned

    from tfde_tpu.checkpoint.manager import CheckpointManager

    saved = CheckpointManager(dir_b + "/checkpoints").latest_step
    assert saved is not None and 0 < saved < MAX_STEPS, saved

    # Run C: resume B's dir to completion; digest must equal the oracle's
    proc, out_c, _ = _run_child(tmp_path, "c", dir_b)
    _wait_for(out_c, proc)
    proc.wait(timeout=60)
    assert proc.returncode == 0
    c = json.load(open(out_c))
    assert c["resumed_from"] == saved
    assert c["final_step"] == MAX_STEPS
    assert c["digest"] == a["digest"], (
        f"resumed digest differs from uninterrupted oracle "
        f"(resumed_from={saved})"
    )


def test_preemption_guard_inert_off_main_thread():
    """The concurrent evaluator drives train() from a worker thread, where
    signal.signal raises — the guard must stay inert there, not break."""
    import threading

    from tfde_tpu.training.lifecycle import _PreemptionGuard

    results = {}

    def run():
        g = _PreemptionGuard()
        with g:
            results["installed"] = bool(g._prev)
        results["ok"] = True

    t = threading.Thread(target=run)
    t.start()
    t.join(10)
    assert results.get("ok") and results.get("installed") is False


def test_preemption_guard_sets_flag_and_restores_handler():
    """In the main thread: first signal sets the flag and restores the
    previous handler (second-signal escape hatch); __exit__ restores."""
    from tfde_tpu.training.lifecycle import _PreemptionGuard

    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        def kill_and_settle(done):
            """Deliver SIGTERM and poll until `done()` observes the
            handler's effect (delivery is asynchronous at bytecode
            granularity)."""
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(500):
                if done():
                    return
                time.sleep(0.01)
            raise AssertionError("signal handler never ran")

        g = _PreemptionGuard()
        with g:
            kill_and_settle(lambda: g.fired is not None)
            # the guard's handler ran: flag set, nothing propagated
            assert g.fired == signal.SIGTERM
            assert seen == []
            # handler already restored to OUR lambda (escape hatch)
            kill_and_settle(lambda: len(seen) == 1)
            assert seen == [signal.SIGTERM]
        # after exit the outer handler is still ours
        kill_and_settle(lambda: len(seen) == 2)
        assert seen == [signal.SIGTERM, signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)
