"""Lowered-program linter (tfde_tpu/analysis/hlolint.py): the census
helper against the pinned collective budgets, donation survival and the
dropped-donation violation, seeded host-callback / f64 / large-constant
programs failing the lint, the text-level census mechanics, and the
offer/collect registration seam that tools/lintgate.py drains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.analysis import hlolint
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import init_state, make_train_step


def _cnn_step(transport, opt_sharding, donate=False):
    strategy = MirroredStrategy(
        mesh=make_mesh({"data": -1}, jax.devices()[:4]),
        grad_transport=transport, opt_sharding=opt_sharding)
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy, images)
    step = make_train_step(strategy, state, donate=donate)
    return getattr(step, "jitted", step), state, (images, labels)


# -- census vs the pinned budgets ---------------------------------------------
def test_census_int8_replicated_budget():
    """The PR 5 budget triple for the quantized replicated exchange —
    and the payload-byte side the string pins never had: the int8
    reduce-scatter + all-gather must dominate the fp32 sidecar psum."""
    jitted, state, batch = _cnn_step("int8", "replicated")
    c = hlolint.census(jitted, state, batch, jax.random.key(0))
    assert c.collective_counts == (2, 1, 2)
    assert c.callbacks == 0
    assert c.f64_tensors == 0
    assert c.large_constants == []
    # payload bytes: every counted collective carries a nonzero payload
    for kind in ("all_reduce", "reduce_scatter", "all_gather"):
        assert c.collective_bytes[kind] > 0, c.collective_bytes
    # the two all-reduces are the tiny fp32 sidecar + pmax scale probe;
    # the compressed grad vector rides the reduce-scatter/all-gather
    assert c.collective_bytes["all_reduce"] < c.collective_bytes["all_gather"]


@pytest.mark.parametrize("transport,sharding,budget", [
    ("fp32", "shard", (1, 1, 1)),
    ("int8", "shard", (2, 1, 1)),
])
def test_census_sharded_budgets(transport, sharding, budget):
    jitted, state, batch = _cnn_step(transport, sharding)
    c = hlolint.census(jitted, state, batch, jax.random.key(0))
    assert c.collective_counts == budget
    assert c.callbacks == 0


# -- donation -----------------------------------------------------------------
def test_donation_survives_and_lints_clean():
    jitted, state, batch = _cnn_step("int8", "replicated", donate=True)
    rep = hlolint.lint("t", jitted, (state, batch, jax.random.key(0)),
                       donated=state)
    assert rep.ok, rep.violations
    assert rep.census.aliased_outputs > 0


def test_dropped_donation_is_a_violation():
    """donate_argnums on an arg whose shape matches no output: XLA drops
    the alias and the linter must say so."""

    dn = jax.jit(lambda x: jnp.sum(x, axis=0), donate_argnums=(0,))
    x = jnp.ones((8, 8), jnp.float32)
    with pytest.warns(UserWarning, match="donated buffers were not usable"):
        rep = hlolint.lint("shrink", dn, (x,), donated=x)
    assert not rep.ok
    assert "donation was dropped" in rep.violations[0]
    # the same program with donation undeclared is clean
    rep2 = hlolint.lint("shrink", jax.jit(lambda x: jnp.sum(x, axis=0)), (x,))
    assert rep2.ok


# -- seeded violations --------------------------------------------------------
def test_host_callback_is_a_violation_unless_allowed():
    def poll(x):
        flag = jax.pure_callback(
            lambda v: np.asarray(float(v) > 0, np.float32),
            jax.ShapeDtypeStruct((), jnp.float32), jnp.sum(x))
        return x * flag

    cb = jax.jit(poll)
    args = (jnp.ones((4, 4), jnp.float32),)
    rep = hlolint.lint("poll", cb, args)
    assert not rep.ok
    assert "host-callback" in rep.violations[0]
    assert "ALLOW" in rep.violations[0]  # the message names the escape hatch
    # an explicit per-program allowance clears it
    allowed = hlolint.lint(
        "poll", cb, args,
        policy=hlolint.Policy(allow_callbacks=rep.census.callbacks))
    assert allowed.ok, allowed.violations


def test_f64_leaf_is_a_violation():
    text = ('func.func @main(%arg0: tensor<4xf64>) -> tensor<4xf64> {\n'
            '  return %arg0 : tensor<4xf64>\n}\n')
    rep = hlolint.lint("dbl", text=text)
    assert not rep.ok
    assert "f64" in rep.violations[0]
    assert hlolint.lint(
        "dbl", text=text, policy=hlolint.Policy(allow_f64=True)).ok


def test_large_constant_is_a_violation():
    text = ('%0 = stablehlo.constant dense_resource<w> : tensor<512x1024xf32>\n'
            '%1 = stablehlo.constant dense<0.0> : tensor<4xf32>\n')
    rep = hlolint.lint("tbl", text=text)
    assert len(rep.census.large_constants) == 1
    assert rep.census.large_constants[0][0] == 512 * 1024 * 4
    assert not rep.ok and "constant" in rep.violations[0]
    # raising the threshold past the table clears it
    assert hlolint.lint("tbl", text=text, policy=hlolint.Policy(
        max_constant_bytes=4 << 20)).ok


# -- text-level census mechanics ----------------------------------------------
def test_census_text_counts_and_payload_bytes():
    text = (
        '%0 = "stablehlo.all_reduce"(%a) ({...}) : '
        '(tensor<100xf32>) -> tensor<100xf32>\n'
        '%1 = "stablehlo.all_reduce"(%b) ({...}) : '
        '(tensor<2x3xf32>) -> tensor<2x3xf32>\n'
        '%2 = "stablehlo.reduce_scatter"(%c) ({...}) : '
        '(tensor<64xi8>) -> tensor<16xi8>\n'
        '%3 = stablehlo.convert %d : (tensor<8xbf16>) -> tensor<8xf32>\n'
    )
    c = hlolint.census_text(text)
    assert c.collective_counts == (2, 1, 0)
    assert c.collective_bytes["all_reduce"] == 400 + 24  # result bytes
    assert c.collective_bytes["reduce_scatter"] == 16
    assert c.bf16_to_f32_converts == 1
    assert c.callbacks == 0 and c.f64_tensors == 0


def test_census_text_pretty_print_fallback():
    # non-generic spelling (no quotes) must still be counted
    text = '%0 = stablehlo.all_gather %x : tensor<8xf32> -> tensor<32xf32>\n'
    assert hlolint.census_text(text).all_gather == 1


# -- the registration seam ----------------------------------------------------
def test_offer_collect_seam_arm_disarm():
    hlolint.reset()
    try:
        f = jax.jit(lambda x: x * 2)
        x = jnp.ones((4,), jnp.float32)
        # disarmed: offers vanish
        hlolint.arm(False)
        hlolint.offer("off/one", f, (x,))
        assert hlolint.offers() == ()
        # armed: recorded once, deduped, collectable
        hlolint.arm(True)
        hlolint.offer("on/one", f, (x,))
        hlolint.offer("on/one", f, (x,))
        assert hlolint.offers() == ("on/one",)
        reports = hlolint.collect()
        assert reports["on/one"].ok
        assert reports["on/one"].census.callbacks == 0
    finally:
        hlolint.reset()


def test_offer_snapshot_outlives_donated_buffer():
    """The memwatch-seam contract: lowering at collect() time must work
    from avals even after the offered buffers are deleted."""
    hlolint.reset()
    try:
        hlolint.arm(True)
        f = jax.jit(lambda x: x + 1, donate_argnums=(0,))
        x = jnp.ones((16,), jnp.float32)
        hlolint.offer("donated/add", f, (x,), donated=x)
        f(x)  # consumes x
        x.delete()
        reports = hlolint.collect()
        assert reports["donated/add"].ok, reports["donated/add"].violations
        assert reports["donated/add"].census.aliased_outputs == 1
    finally:
        hlolint.reset()


def test_collect_reports_dropped_donation_from_offer():
    hlolint.reset()
    try:
        hlolint.arm(True)
        dn = jax.jit(lambda x: jnp.sum(x, axis=0), donate_argnums=(0,))
        x = jnp.ones((8, 8), jnp.float32)
        hlolint.offer("donated/shrink", dn, (x,), donated=x)
        with pytest.warns(UserWarning, match="donated buffers were not usable"):
            reports = hlolint.collect()
        assert not reports["donated/shrink"].ok
        assert "donation was dropped" in reports["donated/shrink"].violations[0]
    finally:
        hlolint.reset()


def test_offer_never_raises_when_disarmed_or_on_bad_input():
    hlolint.reset()
    try:
        hlolint.arm(True)
        hlolint.offer("bad/none", None, (object(),))  # snapshot-proof leaf
        # the offer is recorded (object() passes through _aval as-is) and
        # collect() turns the lowering failure into a violation, not a raise
        reports = hlolint.collect()
        assert not reports["bad/none"].ok
        assert "could not lower" in reports["bad/none"].violations[0]
    finally:
        hlolint.reset()
