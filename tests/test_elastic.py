"""Elastic topology-change tests (ISSUE 13): a supervised run that loses a
peer does not merely restart — it re-resolves the cluster from the
survivors, comes back up at the smaller world size, and resumes from the
last committed checkpoint bit-exact against an uninterrupted oracle.

Methodology mirrors test_supervisor.py: ONE constant batch every step so
resume equivalence is decidable by a params digest. The in-process drill
fakes a 2-process world through the bare ``TFDE_*`` env contract (no
``jax.distributed`` runtime is ever started — world 2 is never
bootstrapped, and after the shrink world 1 needs none), so the elastic
machinery under test is exactly the production sequence: classify
TOPOLOGY -> consume suspects -> shrink env -> re-bootstrap -> resume.
The real two-OS-process kill drill lives in test_multiprocess.py.
"""

import json
import os

import jax
import pytest

from tfde_tpu.observability import counters, metrics
from tfde_tpu.resilience import (
    ElasticConfig,
    PeerLossFault,
    StepFaults,
    Supervisor,
    SupervisorAborted,
)
from tfde_tpu.resilience import elastic
from tfde_tpu.resilience.supervisor import FailureKind, classify_failure
from tfde_tpu.runtime import cluster

from test_supervisor import (
    MAX_STEPS,
    constant_input_fn,
    digest,
    fast_restart,
    make_factory,
    oracle,  # noqa: F401  (module-scoped fixture, reused by name)
)


@pytest.fixture(autouse=True)
def _isolate_elastic_state():
    """Module-global state (suspects, last bootstrap info, batch segment)
    must not leak between tests — or into other test files."""
    saved_info = cluster._LAST_INFO
    saved_seg = elastic._LAST_SEGMENT
    elastic.clear_suspects()
    counters.reset("resilience/")
    cluster._LAST_INFO = None
    elastic._LAST_SEGMENT = None
    yield
    elastic.clear_suspects()
    cluster._LAST_INFO = saved_info
    elastic._LAST_SEGMENT = saved_seg


def _fake_world(monkeypatch, n=2, rank=0, coordinator=None):
    """Declare an n-process world through the bare TFDE_* contract."""
    monkeypatch.setenv("TFDE_NUM_PROCESSES", str(n))
    monkeypatch.setenv("TFDE_PROCESS_ID", str(rank))
    if coordinator:
        monkeypatch.setenv("TFDE_COORDINATOR", coordinator)
    else:
        monkeypatch.delenv("TFDE_COORDINATOR", raising=False)
    monkeypatch.delenv("TF_CONFIG", raising=False)
    monkeypatch.delenv("CLUSTER_SPEC", raising=False)


# -- config resolution ---------------------------------------------------------
def test_resolve_semantics(monkeypatch):
    monkeypatch.delenv("TFDE_ELASTIC", raising=False)
    assert elastic.resolve(None) is None  # off by default
    assert elastic.resolve(False) is None
    cfg = ElasticConfig(min_world=3)
    assert elastic.resolve(cfg) is cfg  # explicit config passes through
    monkeypatch.setenv("TFDE_ELASTIC", "on")
    monkeypatch.setenv("TFDE_ELASTIC_MAX_CHANGES", "7")
    monkeypatch.setenv("TFDE_ELASTIC_MIN_WORLD", "2")
    tuned = elastic.resolve(None)
    assert tuned is not None
    assert tuned.max_topology_changes == 7
    assert tuned.min_world == 2
    monkeypatch.setenv("TFDE_ELASTIC", "off")
    assert elastic.resolve(None) is None
    assert elastic.resolve(True) is not None  # True overrides the off flag


# -- suspicion registry & failure shapes ---------------------------------------
def test_suspect_registry_dedups(monkeypatch):
    elastic.note_peer_lost(3, "heartbeat silence")
    elastic.note_peer_lost(3, "socket died")  # re-note: free, keeps first-seen
    assert counters.value("resilience/peers_lost") == 1
    assert set(elastic.suspects()) == {3}
    elastic.note_peer_lost(1, "drill")
    assert counters.value("resilience/peers_lost") == 2
    elastic.clear_suspects()
    assert elastic.suspects() == {}


def test_looks_like_peer_loss_shapes():
    assert elastic.looks_like_peer_loss(elastic.PeerLostError(1, "x"))
    assert elastic.looks_like_peer_loss(
        RuntimeError("gloo: Connection reset by peer [rank 1]"))
    assert elastic.looks_like_peer_loss(OSError("Broken pipe"))
    # a local shape bug or file error must never trigger a topology change
    assert not elastic.looks_like_peer_loss(RuntimeError("shape mismatch"))
    assert not elastic.looks_like_peer_loss(ValueError("connection reset"))


def test_peer_loss_fault_raises_and_registers_suspect():
    fault = PeerLossFault(rank=1, reason="injected")
    with pytest.raises(elastic.PeerLostError) as ei:
        fault.fire("batch draw")
    assert ei.value.rank == 1
    assert classify_failure(ei.value) is FailureKind.TOPOLOGY
    assert 1 in elastic.suspects()


# -- env shrink ----------------------------------------------------------------
def test_shrink_env_tfde_contract(monkeypatch):
    _fake_world(monkeypatch, n=4, rank=2, coordinator="a:1234")
    old = cluster.resolve_cluster()
    assert old.num_processes == 4 and old.process_id == 2
    new_world, new_rank = elastic.shrink_env(old, [1])
    assert (new_world, new_rank) == (3, 1)  # survivors [0, 2, 3], dense
    assert os.environ["TFDE_NUM_PROCESSES"] == "3"
    assert os.environ["TFDE_PROCESS_ID"] == "1"
    # rank 0 survived: same coordinator host, but the port moves one over
    # — the abandoned topology's coordination service still holds :1234
    # (tearing it down with a dead peer is fatal, so it is parked alive)
    assert os.environ["TFDE_COORDINATOR"] == "a:1235"


def test_shrink_env_refuses_to_shrink_around_self(monkeypatch):
    _fake_world(monkeypatch, n=2, rank=0)
    with pytest.raises(ValueError, match="cannot shrink around self"):
        elastic.shrink_env(cluster.resolve_cluster(), [0, 1])


def test_shrink_env_drops_coordinator_when_alone(monkeypatch):
    # bare TFDE_* contract, rank 0 (the coordinator host) lost, one
    # survivor: no coordinator is needed at world 1, so the stale env
    # entry must go away instead of pointing at a dead host
    _fake_world(monkeypatch, n=2, rank=1, coordinator="dead:1234")
    new_world, new_rank = elastic.shrink_env(cluster.resolve_cluster(), [0])
    assert (new_world, new_rank) == (1, 0)
    assert "TFDE_COORDINATOR" not in os.environ


def test_shrink_env_tf_config_reelects_coordinator(monkeypatch):
    monkeypatch.delenv("TFDE_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("TFDE_PROCESS_ID", raising=False)
    monkeypatch.delenv("TFDE_COORDINATOR", raising=False)
    monkeypatch.setenv("TF_CONFIG", json.dumps({
        "cluster": {"worker": ["a:1000", "b:1000", "c:1000"]},
        "task": {"type": "worker", "index": 2},
    }))
    old = cluster.resolve_cluster()
    assert old.num_processes == 3 and old.coordinator_address == "a:1000"
    new_world, new_rank = elastic.shrink_env(old, [0])  # the chief died
    assert (new_world, new_rank) == (2, 1)
    fresh = cluster.resolve_cluster()
    assert fresh.num_processes == 2 and fresh.process_id == 1
    # coordinator re-election = lowest surviving rank's host
    assert fresh.coordinator_address == "b:1000"


# -- semantic continuity -------------------------------------------------------
def test_per_process_batch_preserves_global(monkeypatch):
    assert elastic.per_process_batch(64, world=4) == 16
    assert elastic.per_process_batch(64, world=1) == 64
    with pytest.raises(ValueError, match="does not divide"):
        elastic.per_process_batch(64, world=3)
    with pytest.raises(ValueError, match="world must be"):
        elastic.per_process_batch(64, world=0)
    _fake_world(monkeypatch, n=2)
    assert elastic.per_process_batch(64) == 32  # world from the env


def test_note_batch_tracks_world_segments():
    elastic.note_batch(8, 2)
    assert metrics.gauge("cluster/world_size").value == 2
    elastic.note_batch(16, 1)  # same global batch at the smaller world
    assert metrics.gauge("cluster/world_size").value == 1
    assert elastic._LAST_SEGMENT == (1, 16)


# -- the elastic drill (acceptance criterion) ----------------------------------
def test_lost_peer_shrinks_world_and_resumes_bit_exact(
        tmp_path, oracle, monkeypatch):  # noqa: F811
    """The acceptance drill, in-process: a declared 2-process run loses
    peer rank 1 mid-training (after the step-4 checkpoint committed). The
    supervisor classifies TOPOLOGY, shrinks the env to world 1,
    re-bootstraps, and resumes — final params identical to an
    uninterrupted single-process run on the same data order (the data
    order IS preserved: one constant batch, global batch unchanged)."""
    _fake_world(monkeypatch, n=2, rank=0)
    d = str(tmp_path / "run")
    faults = StepFaults({7: PeerLossFault(rank=1)})
    sup = Supervisor(
        make_factory(d),
        fast_restart(max_restarts=3, elastic=ElasticConfig()),
    )
    state = sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert int(jax.device_get(state.step)) == MAX_STEPS
    assert sup.restarts == 1
    assert digest(state) == oracle
    # the world actually shrank: env rewritten, runtime re-resolved
    assert os.environ["TFDE_NUM_PROCESSES"] == "1"
    assert os.environ["TFDE_PROCESS_ID"] == "0"
    assert cluster.last_info() is not None
    assert cluster.last_info().num_processes == 1
    assert metrics.gauge("cluster/world_size").value == 1
    assert counters.value("resilience/topology_changes") == 1
    assert counters.value("resilience/peers_lost") == 1
    # the re-bootstrap tax feeds the goodput ledger's restart_loss
    assert counters.value("resilience/rebootstrap_seconds") > 0
    assert elastic.suspects() == {}  # consumed by the re-bootstrap


def test_untyped_peer_loss_upgrades_to_topology(
        tmp_path, oracle, monkeypatch):  # noqa: F811
    """A survivor's collective usually dies with an untyped RuntimeError,
    not a PeerLostError. With elastic on and a distributed env declared,
    the message heuristic upgrades it to TOPOLOGY; with no identified
    suspect, presume-lost shrinks to self."""
    from tfde_tpu.resilience import RaiseFault

    _fake_world(monkeypatch, n=2, rank=0)
    d = str(tmp_path / "run")
    faults = StepFaults({7: RaiseFault(
        exc_type=RuntimeError,
        message="gloo: Connection reset by peer [rank 1]")})
    sup = Supervisor(
        make_factory(d),
        fast_restart(max_restarts=3, elastic=ElasticConfig()),
    )
    state = sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert int(jax.device_get(state.step)) == MAX_STEPS
    assert sup.restarts == 1
    assert digest(state) == oracle
    assert os.environ["TFDE_NUM_PROCESSES"] == "1"
    assert counters.value("resilience/topology_changes") == 1


def test_elastic_disabled_restarts_at_old_world(
        tmp_path, oracle, monkeypatch):  # noqa: F811
    """Without elastic, a peer loss is still a restartable failure — but
    nothing rewrites the env (the pre-elastic behavior, preserved)."""
    monkeypatch.delenv("TFDE_ELASTIC", raising=False)
    _fake_world(monkeypatch, n=2, rank=0)
    d = str(tmp_path / "run")
    faults = StepFaults({7: PeerLossFault(rank=1)})
    sup = Supervisor(make_factory(d), fast_restart(max_restarts=3))
    state = sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 1
    assert digest(state) == oracle
    assert os.environ["TFDE_NUM_PROCESSES"] == "2"  # untouched
    assert counters.value("resilience/topology_changes") == 0


def test_topology_change_budget_aborts(tmp_path, monkeypatch):
    """A cluster that keeps losing peers must converge to an abort, not
    loop forever re-bootstrapping."""
    _fake_world(monkeypatch, n=2, rank=0)
    faults = StepFaults({2: PeerLossFault(rank=1)}, fires_once=False)
    sup = Supervisor(
        make_factory(str(tmp_path / "b")),
        fast_restart(max_restarts=9, no_progress_limit=99,
                     elastic=ElasticConfig(max_topology_changes=1)),
    )
    with pytest.raises(SupervisorAborted, match="topology-change budget"):
        sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 1


def test_min_world_refuses_to_resume(tmp_path, monkeypatch):
    """min_world > survivors: the re-bootstrap refuses and the supervisor
    aborts — a run that NEEDS N hosts must not silently limp on at 1."""
    _fake_world(monkeypatch, n=2, rank=0)
    faults = StepFaults({7: PeerLossFault(rank=1)})
    sup = Supervisor(
        make_factory(str(tmp_path / "m")),
        fast_restart(max_restarts=3,
                     elastic=ElasticConfig(min_world=2)),
    )
    with pytest.raises(SupervisorAborted, match="re-bootstrap failed"):
        sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
