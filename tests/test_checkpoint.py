"""Checkpoint save/auto-resume tests (SURVEY.md §5 checkpoint/resume)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy, ParameterServerStrategy
from tfde_tpu.training.step import init_state, make_train_step


def _state(strategy, seed=0):
    state, _ = init_state(
        PlainCNN(), optax.sgd(0.1, momentum=0.9), strategy, jnp.zeros((8, 28, 28, 1)), seed=seed
    )
    return state


def test_save_and_restore_roundtrip(tmp_path):
    strat = MultiWorkerMirroredStrategy()
    state = _state(strat)
    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    assert mngr.latest_step is None
    assert mngr.restore_latest(state) is None

    state = state.replace(step=state.step + 5)
    mngr.save(state, force=True)
    mngr.wait()
    assert mngr.latest_step == 5

    fresh = _state(strat, seed=1)  # different init
    restored = mngr.restore_latest(fresh)
    assert int(jax.device_get(restored.step)) == 5
    for a, b in zip(
        jax.tree_util.tree_leaves(restored.params), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mngr.close()


def test_restore_respects_sharded_opt_state(tmp_path):
    """ZeRO-1 sharded optimizer state must restore with its shardings."""
    strat = ParameterServerStrategy(min_shard_elems=1024)
    state = _state(strat)
    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(state, force=True)
    mngr.wait()
    restored = mngr.restore_latest(_state(strat, seed=1))
    for a, b in zip(
        jax.tree_util.tree_leaves(restored.opt_state),
        jax.tree_util.tree_leaves(state.opt_state),
    ):
        assert a.sharding == b.sharding
    mngr.close()


def test_training_resumes_from_checkpoint(tmp_path):
    """Kill-and-restart: a new process (fresh state) continues at saved step
    with saved params — the Estimator restart contract (SURVEY.md §5)."""
    strat = MultiWorkerMirroredStrategy()
    state = _state(strat)
    step_fn = make_train_step(strat, state)
    rng = jax.random.key(0)
    batch = (
        jnp.ones((16, 28, 28, 1)),
        jnp.zeros((16, 1), jnp.int32),
    )
    from tfde_tpu.data.device import device_prefetch

    dev_batch = next(iter(device_prefetch([batch], strat.mesh)))
    for _ in range(3):
        state, _ = step_fn(state, dev_batch, rng)

    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(state, force=True)
    mngr.wait()
    mngr.close()

    # "restart": fresh process state, fresh compiled step
    mngr2 = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    resumed = mngr2.restore_latest(_state(strat, seed=9))
    assert int(jax.device_get(resumed.step)) == 3
    step_fn2 = make_train_step(strat, resumed)
    state2, _ = step_fn2(resumed, dev_batch, rng)
    assert int(jax.device_get(state2.step)) == 4
    mngr2.close()


@pytest.mark.parametrize("transport", ["fp32", "int8"])
@pytest.mark.parametrize("opt_sharding", ["replicated", "shard"])
@pytest.mark.parametrize("save_n,restore_n", [(2, 4), (4, 2)])
def test_cross_world_restore_matrix(tmp_path, save_n, restore_n, opt_sharding,
                                    transport):
    """Elastic restore: an M-way checkpoint restores onto an N-way mesh,
    both directions, replicated and ZeRO-packed optimizer state, fp32 and
    int8 gradient transport. Params must be bit-exact and the unpacked
    optimizer slots must match the writer's values (the ZeRO cells force
    the packed re-chunk path — the M-way packed shapes cannot restore
    directly into the N-way layout)."""
    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.parallel import zero as zero_lib
    from tfde_tpu.runtime.mesh import make_mesh

    def strat(n):
        return MultiWorkerMirroredStrategy(
            mesh=make_mesh({"data": n}, jax.devices()[:n]),
            grad_transport=transport, opt_sharding=opt_sharding,
        )

    src = strat(save_n)
    state = _state(src)
    # advance a few steps so the momentum slots hold non-trivial values
    step_fn = make_train_step(src, state)
    rng = jax.random.key(0)
    batch = (jnp.ones((8, 28, 28, 1)), jnp.zeros((8, 1), jnp.int32))
    dev_batch = next(iter(device_prefetch([batch], src.mesh)))
    for _ in range(3):
        state, _ = step_fn(state, dev_batch, rng)
    if opt_sharding == "shard":
        assert state.opt_layout is not None, "ZeRO cell did not pack"

    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(state, force=True)
    mngr.wait()
    mngr.close()

    dst = strat(restore_n)
    mngr2 = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    restored = mngr2.restore_latest(_state(dst, seed=9))
    mngr2.close()
    assert int(jax.device_get(restored.step)) == 3

    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def opt_values(st):
        opt = jax.device_get(st.opt_state)
        layout = getattr(st, "opt_layout", None)
        if layout is not None:
            opt = zero_lib.unpack_opt_state(opt, layout)
        return jax.tree_util.tree_leaves(opt)

    got, want = opt_values(restored), opt_values(state)
    assert len(got) == len(want)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)

    # and the restored state must keep training at the new world size
    step_fn2 = make_train_step(dst, restored)
    dev_batch2 = next(iter(device_prefetch([batch], dst.mesh)))
    again, _ = step_fn2(restored, dev_batch2, rng)
    assert int(jax.device_get(again.step)) == 4


def test_packed_geometry_check_discriminates(tmp_path):
    """_packed_geometry_differs: True only when both sides hold ZeRO-packed
    slots with different chunk geometry — the trigger for the packed
    re-chunk branch of _restore_cross_format."""
    from tfde_tpu.runtime.mesh import make_mesh

    def strat(n):
        return MultiWorkerMirroredStrategy(
            mesh=make_mesh({"data": n}, jax.devices()[:n]),
            opt_sharding="shard",
        )

    state2 = _state(strat(2))
    assert state2.opt_layout is not None
    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(state2, force=True)
    mngr.wait()
    step = mngr.latest_step

    assert not mngr._packed_geometry_differs(step, state2)
    state4 = _state(strat(4), seed=1)
    assert mngr._packed_geometry_differs(step, state4)
    # replicated live state: no layout, never this trigger (the
    # replicated<->sharded bridge owns that direction)
    rep = _state(MultiWorkerMirroredStrategy(
        mesh=make_mesh({"data": 4}, jax.devices()[:4]),
        opt_sharding="replicated"), seed=2)
    assert not mngr._packed_geometry_differs(step, rep)
    mngr.close()


def test_optimizer_change_relabeled_with_guidance(tmp_path):
    """Restoring an adamw checkpoint into an sgd(momentum) state must fail
    with the optimizer-changed guidance (a genuine structure mismatch,
    detected via orbax metadata — not error-text sniffing)."""
    strat = MultiWorkerMirroredStrategy()
    saved, _ = init_state(
        PlainCNN(), optax.adamw(1e-3), strat, jnp.zeros((8, 28, 28, 1))
    )
    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(saved, force=True)
    mngr.wait()
    with pytest.raises(ValueError, match="optimizer configuration"):
        mngr.restore_latest(_state(strat, seed=1))
    mngr.close()


def test_structure_check_discriminates(tmp_path):
    """_saved_structure_differs: False for the matching state (so unrelated
    restore errors keep their original message), True for a changed
    optimizer."""
    strat = MultiWorkerMirroredStrategy()
    saved, _ = init_state(
        PlainCNN(), optax.adamw(1e-3), strat, jnp.zeros((8, 28, 28, 1))
    )
    mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mngr.save(saved, force=True)
    mngr.wait()
    step = mngr.latest_step

    def abstract_of(state):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            mngr._tree(state),
        )

    assert not mngr._saved_structure_differs(step, abstract_of(saved))
    changed, _ = init_state(
        PlainCNN(), optax.sgd(0.1, momentum=0.9), strat,
        jnp.zeros((8, 28, 28, 1)),
    )
    assert mngr._saved_structure_differs(step, abstract_of(changed))
    mngr.close()
