"""Boot & readiness observability (observability/boot.py): the phase
ledger's arithmetic is pinned against an injected clock (phases tile,
the first is backdated to birth, and they sum exactly to time-to-ready),
the compile attribution against an injected probe (boot vs steady split
at the ready edge), the restore accounting against hand-computed
proportional attribution, and the whole instrument is cross-checked
against the goodput ledger fed the same simulated events."""

import pytest

from tfde_tpu.observability import boot, goodput, metrics


class _Clock:
    """Deterministic monotonic clock for phase arithmetic."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def _mk(clk, reg=None, birth=None, probe=None):
    return boot.BootLedger(
        birth=clk.t if birth is None else birth,
        registry=reg or metrics.Registry(),
        clock=clk,
        compile_probe=probe or (lambda: (0, 0.0)),
    )


# --------------------------------------------------------------------------
# phase arithmetic: tiling, backdating, exact sum to time-to-ready
# --------------------------------------------------------------------------

def test_phases_tile_and_first_backdates_to_birth():
    clk = _Clock(100.0)
    reg = metrics.Registry()
    led = _mk(clk, reg, birth=90.0)
    led.begin("init")            # backdated: starts at birth, not now
    clk.tick(2.0)
    led.begin("bootstrap")       # closes init at the same instant
    clk.tick(3.0)
    led.begin("restore")
    clk.tick(1.5)
    led.begin("compile")
    clk.tick(4.0)
    led.begin("warmup")
    clk.tick(0.5)
    led.ready()

    ph = led.phase_seconds()
    assert ph == pytest.approx({"init": 12.0, "bootstrap": 3.0,
                                "restore": 1.5, "compile": 4.0,
                                "warmup": 0.5})
    # the acceptance identity: phases tile birth -> ready with no gap
    assert led.time_to_ready() == pytest.approx(sum(ph.values()))
    assert reg.get("boot/init_seconds").value == pytest.approx(12.0)
    assert reg.get("boot/bootstrap_seconds").value == pytest.approx(3.0)
    # the compile PHASE wall has its own gauge name; compile_seconds is
    # the backend attribution
    assert reg.get("boot/compile_wall_seconds").value == pytest.approx(4.0)
    assert reg.get("boot/time_to_ready_seconds").value == pytest.approx(21.0)


def test_phase_decomposition_sums_to_ttft_within_tolerance():
    """The ISSUE acceptance bar, in-process: phase sum vs the wall from
    birth to the first served token, within 5% (here the only slack is
    the post-ready wait for the first request)."""
    clk = _Clock(50.0)
    led = _mk(clk)
    led.begin("init")
    clk.tick(4.0)
    led.begin("compile")
    clk.tick(5.5)
    led.ready()
    clk.tick(0.3)                # serve wait: ready -> first token
    led.note_first_token()
    snap = led.snapshot()
    ttft_s = snap["ttft_from_birth_ms"] / 1e3
    assert sum(snap["phases"].values()) == pytest.approx(9.5)
    assert abs(ttft_s - sum(snap["phases"].values())) <= 0.05 * ttft_s


def test_unknown_phase_rejected():
    led = _mk(_Clock())
    with pytest.raises(ValueError):
        led.begin("reticulating")
    with pytest.raises(ValueError):
        led.note_phase("reticulating", 1.0)


# --------------------------------------------------------------------------
# monotonicity + state machine
# --------------------------------------------------------------------------

def test_ledger_monotonic_and_states_walk_lifecycle():
    clk = _Clock()
    led = _mk(clk)
    assert led.state == "starting"
    led.begin("restore")
    assert led.state == "restoring"
    clk.tick(1.0)
    open_before = led.phase_seconds()["restore"]
    clk.tick(1.0)
    # an OPEN phase counts up to now — never down
    assert led.phase_seconds()["restore"] >= open_before
    led.begin("compile")
    assert led.state == "compiling"
    led.begin("warmup")
    assert led.state == "warming"
    assert led.time_to_ready() is None
    led.ready()
    assert led.state == "ready"
    ttr = led.time_to_ready()
    clk.tick(10.0)
    led.ready()                  # idempotent: the edge does not move
    assert led.time_to_ready() == pytest.approx(ttr)
    led.draining()
    assert led.state == "draining"
    # age keeps counting; closed phases do not
    snap = led.snapshot()
    assert snap["age_s"] >= ttr
    assert sum(snap["phases"].values()) == pytest.approx(ttr)


def test_new_epoch_resets_everything():
    clk = _Clock()
    reg = metrics.Registry()
    led = _mk(clk, reg)
    led.begin("init")
    clk.tick(2.0)
    led.ready()
    led.note_first_token()
    led.note_restore_leaf("params", 1000, 1.0)
    ep = led.new_epoch(cause="topology_change")
    assert ep == 1 and led.epoch == 1
    assert led.state == "starting"
    assert led.birth == pytest.approx(clk.t)
    assert led.phase_seconds() == {}
    assert led.time_to_ready() is None
    snap = led.snapshot()
    assert snap["ttft_from_birth_ms"] is None
    assert snap["restore"]["bytes"] == 0
    assert reg.get("boot/epochs").value == 1
    # the fresh epoch measures its rejoin with the same instrument
    with led.phase("bootstrap"):
        clk.tick(3.0)
    led.ready()
    assert led.time_to_ready() == pytest.approx(3.0)


# --------------------------------------------------------------------------
# compile attribution: boot vs steady split at the ready edge
# --------------------------------------------------------------------------

def test_compile_attribution_splits_at_ready_edge():
    probe = {"v": (0, 0.0)}
    clk = _Clock()
    reg = metrics.Registry()
    led = _mk(clk, reg, probe=lambda: probe["v"])
    led.begin("compile")
    probe["v"] = (5, 2.5)        # the pad-ladder enumeration
    clk.tick(1.0)
    # still booting: everything so far is boot cost
    attr = led.compile_attribution()
    assert attr["boot"] == {"count": 5, "seconds": 2.5}
    assert attr["steady"] == {"count": 0, "seconds": 0.0}
    led.ready()
    probe["v"] = (7, 3.1)        # steady-state recompiles after ready
    attr = led.compile_attribution()
    assert attr["boot"] == {"count": 5, "seconds": 2.5}
    assert attr["steady"]["count"] == 2
    assert attr["steady"]["seconds"] == pytest.approx(0.6)
    # the gauges snapshot the BOOT half at the ready edge
    assert reg.get("boot/compile_count").value == 5
    assert reg.get("boot/compile_seconds").value == pytest.approx(2.5)
    snap = led.snapshot()
    assert snap["compile"]["boot_count"] == 5
    assert snap["compile"]["steady_count"] == 2


# --------------------------------------------------------------------------
# restore accounting
# --------------------------------------------------------------------------

def test_restore_bandwidth_hand_computed():
    clk = _Clock()
    reg = metrics.Registry()
    led = _mk(clk, reg)
    led.note_restore_leaf("params", 6_000_000, 2.0)
    led.note_restore_leaf("opt_state", 2_000_000, 2.0)
    snap = led.snapshot()["restore"]
    assert snap["bytes"] == 8_000_000
    assert snap["bandwidth_bps"] == pytest.approx(2_000_000.0)
    assert reg.get("boot/restore_bandwidth_bps").value == pytest.approx(
        2_000_000.0)


def test_module_note_restore_targets_only_booting_ledgers():
    clk = _Clock()
    booting = _mk(clk)
    booting.begin("init")
    served = _mk(clk)
    served.ready()
    boot.note_restore({"params": 3_000_000, "opt": 1_000_000}, 2.0)
    snap = booting.snapshot()
    # proportional-by-bytes attribution of the shared call's wall
    assert snap["restore"]["leaves"]["params"]["seconds"] == pytest.approx(
        1.5)
    assert snap["restore"]["leaves"]["opt"]["seconds"] == pytest.approx(0.5)
    assert snap["phases"]["restore"] == pytest.approx(2.0)
    # a steady-state restore is not boot cost
    assert served.snapshot()["restore"]["bytes"] == 0


# --------------------------------------------------------------------------
# serving-path marks: warm-up gating + idempotence
# --------------------------------------------------------------------------

def test_first_marks_ignore_warmup_and_are_idempotent():
    clk = _Clock()
    led = _mk(clk)
    led.begin("warmup")
    # the replica feeding itself warm-up prompts drives the same batcher
    # path — the module-level marks must not count it
    boot.note_first_admit()
    boot.note_first_token()
    assert led.snapshot()["first_admit_s"] is None
    assert led.snapshot()["ttft_from_birth_ms"] is None
    clk.tick(2.0)
    led.ready()
    clk.tick(0.25)
    boot.note_first_admit()
    boot.note_first_token()
    snap = led.snapshot()
    assert snap["first_admit_s"] == pytest.approx(2.25)
    assert snap["ttft_from_birth_ms"] == pytest.approx(2250.0)
    clk.tick(60.0)
    boot.note_first_token()      # later tokens do not move the mark
    assert led.snapshot()["ttft_from_birth_ms"] == pytest.approx(2250.0)


# --------------------------------------------------------------------------
# goodput cross-check: both ledgers fed the same simulated events agree
# --------------------------------------------------------------------------

def test_boot_ledger_cross_checks_against_goodput_buckets():
    """An elastic rejoin simulated into BOTH instruments: the boot
    ledger's bootstrap phase must match goodput's rebootstrap share of
    restart_loss, and the boot compile attribution must match goodput's
    mid-run site-compile bucket, within 5%."""
    reg = metrics.Registry()
    gp = goodput.GoodputLedger(registry=reg)
    probe = {"v": (0, 0.0)}
    clk = _Clock()
    led = boot.BootLedger(birth=clk.t, registry=reg, clock=clk,
                          compile_probe=lambda: probe["v"])
    led.new_epoch(cause="topology_change")

    # the re-bootstrap: supervisor.py times it as a bootstrap phase;
    # elastic.rebootstrap feeds the same wall into the resilience counter
    with led.phase("bootstrap"):
        clk.tick(2.0)
    reg.counter("resilience/rebootstrap_seconds").incr(2.0)

    # the compile storm: the recompile sentinel's site counter is what
    # goodput consumes; the boot probe sees the same process totals
    with led.phase("compile"):
        probe["v"] = (4, 1.2)
        reg.counter("compile/serve_decode/seconds_total").incr(1.2)
        clk.tick(1.3)
    led.ready()

    rep = gp.report(wall_seconds=10.0)
    ph = led.phase_seconds()
    assert abs(rep["seconds"]["restart_loss"] - ph["bootstrap"]) \
        <= 0.05 * ph["bootstrap"]
    boot_compile = led.compile_attribution()["boot"]["seconds"]
    assert abs(rep["seconds"]["compile"] - boot_compile) \
        <= 0.05 * max(boot_compile, 1e-9)
    # disjoint accounting holds with the boot events folded in
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)


# --------------------------------------------------------------------------
# process birth
# --------------------------------------------------------------------------

def test_process_birth_is_before_now_and_sane():
    import time

    birth = boot.process_birth_monotonic()
    now = time.monotonic()
    assert birth <= now
    # a test process is minutes old at most, not days
    assert now - birth < 86400.0


def test_default_ledger_uses_process_birth():
    led = boot.BootLedger(registry=metrics.Registry(),
                          compile_probe=lambda: (0, 0.0))
    led.begin("init")            # backdated: init absorbs pre-import time
    led.ready()
    assert led.time_to_ready() > 0.0
    assert led.phase_seconds()["init"] == pytest.approx(
        led.time_to_ready())
