"""Capacity observability (observability/capacity.py): the KV occupancy
ledger's arithmetic is pinned against hand-computed admission waves, its
used-bytes figure against memwatch's measured bytes over the live cache
cells, the headroom model against the budget math, and the usage meter's
token totals bit-exact against the per-request outputs."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.observability import capacity, metrics


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _solo(model, params, prompt, n, **kw):
    toks, lengths = generate(
        model, params, jnp.asarray(prompt[None, :], jnp.int32),
        max_new_tokens=n, **kw,
    )
    p = prompt.size
    return np.asarray(toks)[0, p : int(lengths[0])]


# --------------------------------------------------------------------------
# CapacityLedger: occupancy + pad-waste arithmetic against hand computation
# --------------------------------------------------------------------------

def test_ledger_observe_hand_computed():
    """A synthetic 4-row/32-cell slab of 1024 bytes: per-cell cost is
    8 bytes, and every gauge follows from the committed counts alone."""
    reg = metrics.Registry()
    led = capacity.CapacityLedger(4, 32, 1024, registry=reg)
    assert led.cell_bytes == pytest.approx(8.0)
    assert led.row_bytes == pytest.approx(256.0)
    s = led.observe(np.asarray([5, 0, 12, 7]), [1, None, 3, 4])
    assert s["used_cells"] == 24                 # 5 + 12 + 7; idle row 1 out
    assert s["used_bytes"] == pytest.approx(24 * 8.0)
    assert s["rows_active"] == 3 and s["rows_free"] == 1
    assert s["waste_frac"] == pytest.approx(1.0 - 24 / 128.0)
    assert reg.get("kv/allocated_bytes").value == 1024
    assert reg.get("kv/used_bytes").value == pytest.approx(192.0)
    assert reg.get("kv/rows_free").value == 1
    # empty slab: zero used, full waste
    s = led.observe(np.zeros(4, np.int64), [None] * 4)
    assert s["used_cells"] == 0 and s["waste_frac"] == pytest.approx(1.0)


def test_ledger_pad_waste_hand_computed_waves():
    """Three admission waves with known bucket/prompt shapes: the
    cumulative and per-bucket pad counters match the hand sums, and the
    waste histogram saw one observation per admitted request."""
    reg = metrics.Registry()
    led = capacity.CapacityLedger(4, 64, 4096, registry=reg)
    # wave 1 (cold, bucket 8): prompts of 5 and 8 -> waste 3 + 0
    led.note_admission("cold", 8, 5)
    led.note_admission("cold", 8, 8)
    # wave 2 (cold, bucket 16): prompt of 9 -> waste 7
    led.note_admission("cold", 16, 9)
    # wave 3 (warm, suffix bucket 8): 3 suffix tokens -> waste 5
    led.note_admission("warm", 8, 3)
    p = led.pad_stats()
    assert p["pad_alloc_tokens"] == 8 + 8 + 16 + 8
    assert p["pad_waste_tokens"] == 3 + 0 + 7 + 5
    assert p["per_bucket"] == {
        8: {"alloc": 24, "waste": 8},
        16: {"alloc": 16, "waste": 7},
    }
    assert reg.get("kv/pad_alloc_tokens").value == 40
    assert reg.get("kv/pad_waste_tokens").value == 15
    assert reg.get("kv/pad_alloc_tokens/bucket_8").value == 24
    assert reg.get("kv/pad_waste_tokens/bucket_16").value == 7
    h = reg.get("kv/pad_waste_frac")
    assert h.count == 4
    assert h.sum == pytest.approx(3 / 8 + 0.0 + 7 / 16 + 5 / 8)


def test_ledger_tracks_batcher_waves(lm, rng):
    """The real batcher feeds the ledger: a wave of known prompt lengths
    on the default power-of-two ladder lands in the hand-computed
    buckets, and after the run the slab drains back to zero occupancy."""
    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=4, max_len=64)
    # buckets default to (8, 16, 32, 64); prompts 5, 6, 12 -> 8, 8, 16
    plens = (5, 6, 12)
    for plen in plens:
        srv.submit(rng.integers(0, 97, plen).astype(np.int64), 4)
    srv.run()
    p = srv._ledger.pad_stats()
    assert p["pad_alloc_tokens"] == 8 + 8 + 16
    assert p["pad_waste_tokens"] == 3 + 2 + 4
    assert p["per_bucket"][8] == {"alloc": 16, "waste": 5}
    assert p["per_bucket"][16] == {"alloc": 16, "waste": 4}
    s = srv.kv_stats()
    assert s["rows_active"] == 0 and s["used_cells"] == 0
    assert s["headroom_rows"] == 4
    assert s["allocated_bytes"] == srv._ledger.slab_bytes


def test_ledger_used_bytes_matches_memwatch_device_bytes(lm, rng):
    """The acceptance pin: mid-flight, `kv/used_bytes` is within 20% of
    memwatch.device_bytes measured over the LIVE cache cells (each
    active row's committed slice of every K/V leaf). The ledger's
    per-cell cost comes from the slab's own leaf bytes, so on the CPU
    mesh the two agree to rounding."""
    from tfde_tpu.inference.prefix_cache import is_index_leaf
    from tfde_tpu.observability import memwatch

    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=3, max_len=48)
    for plen, n in [(5, 24), (9, 20), (3, 28)]:
        srv.submit(rng.integers(0, 97, plen).astype(np.int64), n)
    for _ in range(2):
        srv.step()
    s = srv.kv_stats()
    assert s["rows_active"] == 3 and s["used_cells"] > 0
    live = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(srv._cache):
        if is_index_leaf(path):
            continue
        for r in range(3):
            if srv._req[r] is not None and srv._committed[r]:
                live.append(leaf[r : r + 1, : int(srv._committed[r])])
    measured = memwatch.device_bytes(live)
    assert measured > 0
    assert s["used_bytes"] == pytest.approx(measured, rel=0.2)
    srv.run()


# --------------------------------------------------------------------------
# CapacityModel: headroom math, budget on and off
# --------------------------------------------------------------------------

def test_capacity_model_headroom_math():
    reg = metrics.Registry()
    led = capacity.CapacityLedger(4, 32, 1024, registry=reg)  # row: 256 B
    occ = led.observe(np.asarray([10, 0, 0, 0]), [1, None, None, None])
    # budget off: headroom is simply the free slab rows/cells
    free = capacity.CapacityModel(led, budget_bytes=0, registry=reg)
    hd = free.headroom(occ)
    assert hd == {"headroom_rows": 3, "headroom_tokens": 96}
    assert reg.get("kv/headroom_rows").value == 3
    # budget binding: 10 cells * 8 B = 80 B used; 600 B budget leaves
    # 520 B spare -> 2 rows (520 // 256), 65 tokens (520 // 8)
    tight = capacity.CapacityModel(led, budget_bytes=600, registry=reg)
    hd = tight.headroom(occ)
    assert hd == {"headroom_rows": 2, "headroom_tokens": 65}
    # budget exhausted: clamps to zero, never negative
    broke = capacity.CapacityModel(led, budget_bytes=64, registry=reg)
    hd = broke.headroom(occ)
    assert hd == {"headroom_rows": 0, "headroom_tokens": 0}


def test_capacity_model_env_budget(monkeypatch):
    monkeypatch.setenv("TFDE_CAPACITY_BUDGET_BYTES", "600")
    reg = metrics.Registry()
    led = capacity.CapacityLedger(4, 32, 1024, registry=reg)
    occ = led.observe(np.asarray([10, 0, 0, 0]), [1, None, None, None])
    model = capacity.CapacityModel(led, registry=reg)
    assert model.budget_bytes == 600
    assert model.headroom(occ)["headroom_rows"] == 2


# --------------------------------------------------------------------------
# UsageLog: bounded JSONL with oldest-first compaction
# --------------------------------------------------------------------------

def test_usage_log_bounded_compaction(tmp_path):
    path = str(tmp_path / "usage.jsonl")
    log = capacity.UsageLog(path, max_bytes=400)
    for i in range(50):
        log.write({"rid": i, "prompt_tokens": 7})
    log.close()
    with open(path) as f:
        lines = f.readlines()
    assert sum(len(ln) for ln in lines) <= 400
    recs = [json.loads(ln) for ln in lines]
    # newest records survive, in order, and the latest is always present
    assert recs[-1]["rid"] == 49
    rids = [r["rid"] for r in recs]
    assert rids == sorted(rids)
    # reopening appends (the restart path) and stays bounded
    log = capacity.UsageLog(path, max_bytes=400)
    log.write({"rid": 50, "prompt_tokens": 7})
    log.close()
    with open(path) as f:
        assert json.loads(f.readlines()[-1])["rid"] == 50


def test_resolve_usage_log_spec(tmp_path, monkeypatch):
    monkeypatch.delenv("TFDE_USAGE_LOG", raising=False)
    assert capacity.resolve_usage_log(str(tmp_path)) is None
    monkeypatch.setenv("TFDE_USAGE_LOG", "off")
    assert capacity.resolve_usage_log(str(tmp_path)) is None
    monkeypatch.setenv("TFDE_USAGE_LOG", "on")
    assert capacity.resolve_usage_log(None) is None  # nothing to anchor
    log = capacity.resolve_usage_log(str(tmp_path))
    assert log is not None
    assert log.path.startswith(str(tmp_path))
    assert "metrics/usage_" in log.path.replace("\\", "/")
    log.close()
    explicit = str(tmp_path / "explicit.jsonl")
    monkeypatch.setenv("TFDE_USAGE_LOG", explicit)
    log = capacity.resolve_usage_log(None)
    assert log.path == explicit
    log.close()


# --------------------------------------------------------------------------
# UsageMeter: per-request accounting, bit-exact totals, outcome stamps
# --------------------------------------------------------------------------

def test_usage_meter_residency_and_outcomes():
    import time as _time

    reg = metrics.Registry()
    meter = capacity.UsageMeter(registry=reg)
    meter.begin(1, 10, "interactive")
    meter.admitted(1)
    _time.sleep(0.02)    # a real resident window, well above the 1e-6
    rec = meter.finish(1, 6)       # rounding in the journal record
    # trapezoid: 10 cells at admit, 16 at finish, over the resident window
    assert rec["kv_token_seconds"] == pytest.approx(
        13.0 * rec["resident_s"], rel=1e-3)
    assert rec["prompt_tokens"] == 10 and rec["generated_tokens"] == 6
    assert rec["outcome"] == "ok" and rec["priority"] == "interactive"
    # queue-side shed: never admitted -> zero residency, outcome stamped
    meter.begin(2, 4, "batch")
    rec = meter.finish(2, 0, outcome="shed")
    assert rec["kv_token_seconds"] == 0.0 and rec["resident_s"] == 0.0
    # idempotent: closing an unknown/closed rid is a no-op
    assert meter.finish(2, 0) is None
    assert meter.totals() == {
        "requests": 2, "prompt_tokens": 14, "generated_tokens": 6,
        "kv_token_seconds": pytest.approx(
            reg.get("usage/kv_token_seconds").value),
    }
    assert reg.get("usage/requests").value == 2
    assert reg.get("usage/requests/interactive").value == 1
    assert reg.get("usage/requests/batch").value == 1
    assert reg.get("usage/requests/ok").value == 1
    assert reg.get("usage/requests/shed").value == 1
    assert reg.get("usage/prompt_tokens").value == 14
    assert reg.get("usage/generated_tokens").value == 6


def test_usage_totals_bit_exact_vs_solo_staggered(lm, rng, tmp_path,
                                                  monkeypatch):
    """The acceptance pin: under a staggered-admission parity sweep the
    usage log's per-request prompt/generated token counts sum bit-exact
    to the solo-generate references — metering never invents or drops a
    token, even across mid-flight admission on recycled rows."""
    monkeypatch.setenv("TFDE_USAGE_LOG", str(tmp_path / "usage.jsonl"))
    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48)
    reqs = [(rng.integers(0, 97, plen).astype(np.int64), n)
            for plen, n in [(3, 9), (5, 4), (2, 12), (7, 1), (4, 7)]]
    rids = [srv.submit(p, max_new_tokens=n) for p, n in reqs[:3]]
    done = dict(srv.step())          # late arrivals land on recycled rows
    rids += [srv.submit(p, max_new_tokens=n) for p, n in reqs[3:]]
    done.update(srv.run())
    solos = [_solo(model, params, p, n) for p, n in reqs]
    for rid, ref in zip(rids, solos):
        np.testing.assert_array_equal(done[rid], ref)
    totals = srv.usage.totals()
    assert totals["requests"] == len(reqs)
    assert totals["prompt_tokens"] == sum(p.size for p, _ in reqs)
    assert totals["generated_tokens"] == sum(len(s) for s in solos)
    assert totals["kv_token_seconds"] > 0.0
    # and the JSONL journal carries the same sums, record for record
    srv.usage.close()
    with open(srv.usage.log_path or str(tmp_path / "usage.jsonl")) as f:
        recs = [json.loads(ln) for ln in f]
    assert len(recs) == len(reqs)
    assert {r["rid"] for r in recs} == set(rids)
    assert sum(r["prompt_tokens"] for r in recs) == totals["prompt_tokens"]
    assert (sum(r["generated_tokens"] for r in recs)
            == totals["generated_tokens"])
    assert all(r["outcome"] == "ok" for r in recs)
    by_rid = {r["rid"]: r for r in recs}
    for rid, ref in zip(rids, solos):
        assert by_rid[rid]["generated_tokens"] == len(ref)


def test_usage_meter_stamps_cancel_and_shed(lm, rng):
    """Queue-side cancels meter zero residency; row-side cancels meter
    the tokens actually emitted; shed requests stamp their outcome."""
    import time as _time

    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=1, max_len=48)
    p = rng.integers(1, 90, 4).astype(np.int64)
    active = srv.submit(p, 20)
    queued = srv.submit(p, 6)
    doomed = srv.submit(p, 5, priority="batch", ttft_deadline_ms=1.0)
    srv.step()                        # admits `active`
    srv.cancel(queued)                # still queued: zero tokens
    _time.sleep(0.01)                 # `doomed`'s deadline expires in queue
    srv.cancel(active)                # mid-flight: emitted tokens metered
    srv.run()                         # the freed row dequeues -> shed fires
    totals = srv.usage.totals()
    assert totals["requests"] == 3
    reg = metrics.default_registry()
    assert reg.get("usage/requests/cancelled").value >= 2
    assert reg.get("usage/requests/shed").value >= 1
