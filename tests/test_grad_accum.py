"""Gradient accumulation (training/step.py grad_accum): the microbatched
step must reproduce the full-batch step exactly for microbatch-independent
losses — the same single-device-oracle strategy as the DP/TP numerics tests
(SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.cnn import BatchNormCNN, PlainCNN
from tfde_tpu.models.gpt import gpt_tiny_test, next_token_loss
from tfde_tpu.parallel.strategies import FSDPStrategy, MirroredStrategy
from tfde_tpu.training.step import (
    init_state,
    make_custom_train_step,
    make_train_step,
)


def _leaves_allclose(a, b, **tol):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **tol)


def test_grad_accum_matches_full_batch_classification(rng):
    """SGD + a BN-free CNN: mean-of-microbatch-grads == full-batch grad, so
    accum=4 must track accum=1 step for step."""
    strategy = MirroredStrategy()
    images = rng.random((32, 784), np.float32)
    labels = rng.integers(0, 10, (32, 1)).astype(np.int32)
    key = jax.random.key(0)

    results = {}
    for accum in (1, 4):
        state, _ = init_state(
            PlainCNN(), optax.sgd(0.1), strategy, np.zeros((32, 784), np.float32)
        )
        step = make_train_step(strategy, state, donate=False, grad_accum=accum)
        for _ in range(3):
            state, metrics = step(state, (images, labels), key)
        results[accum] = (state.params, metrics)

    _leaves_allclose(results[1][0], results[4][0], rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(
        float(results[1][1]["loss"]), float(results[4][1]["loss"]),
        rtol=1e-5,
    )


@pytest.mark.slow
def test_grad_accum_custom_loss_matches_under_fsdp(rng):
    """The custom-loss path, sharded: accum=2 on an FSDP mesh must match the
    accum=1 update. SGD, not adam: adam's bias-corrected first step is
    ~sign(g)*lr, which amplifies fp32 reduction-order noise in near-zero
    gradients into full-lr parameter differences — a property of the
    optimizer, not of the accumulation being tested."""
    strategy = FSDPStrategy(min_shard_elems=1)
    tokens = rng.integers(0, 97, (16, 16)).astype(np.int32)
    key = jax.random.key(1)

    params = {}
    for accum in (1, 2):
        state, _ = init_state(
            gpt_tiny_test(), optax.sgd(1e-2), strategy,
            np.zeros((16, 16), np.int32),
        )
        step = make_custom_train_step(
            strategy, state, next_token_loss, donate=False, grad_accum=accum
        )
        for _ in range(2):
            state, _ = step(state, (tokens,), key)
        params[accum] = state.params

    _leaves_allclose(params[1], params[2], rtol=2e-5, atol=2e-6)


def test_grad_accum_batchnorm_stats_chain(rng):
    """BatchNorm stats thread through the microbatches in order; the step
    must run and keep finite, updated stats (exact equality with accum=1 is
    not expected — BN statistics are batch-dependent by construction)."""
    strategy = MirroredStrategy()
    state, _ = init_state(
        BatchNormCNN(), optax.sgd(0.05), strategy,
        np.zeros((16, 784), np.float32),
    )
    step = make_train_step(strategy, state, donate=False, grad_accum=2)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    before = jax.tree_util.tree_map(np.asarray, state.batch_stats)
    state, metrics = step(state, (images, labels), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    after = state.batch_stats
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)
        )
    )
    assert moved, "BN stats did not update through the accumulation scan"


@pytest.mark.slow
def test_grad_accum_weighted_matches_masked_loss(rng):
    """Mask-normalized losses (denominator = per-microbatch target count)
    are a mean-of-means under uniform accumulation; the reserved
    `grad_weight` metrics key must restore the exact full-batch update."""
    from tfde_tpu.ops.losses import masked_lm_loss

    def loss_fn(state, params, batch, rng_):
        tokens, labels = batch
        logits = state.apply_fn({"params": params}, tokens, train=True,
                                rngs={"dropout": rng_})
        loss, acc = masked_lm_loss(logits, labels)
        n = jnp.sum((labels != -100).astype(jnp.float32))
        return loss, {"mlm_accuracy": acc, "grad_weight": n}

    strategy = MirroredStrategy()
    tokens = rng.integers(0, 97, (16, 16)).astype(np.int32)
    # deliberately unbalanced target counts between the microbatches: the
    # device-major split (training/step.py) sends even global rows to
    # microbatch 0 and odd rows to microbatch 1 at batch 16 / 8 shards /
    # accum 2, so imbalance by row parity lands 64 targets in one
    # microbatch and 16 in the other
    labels = np.full((16, 16), -100, np.int32)
    labels[::2, ::2] = tokens[::2, ::2]   # 8 targets in even rows
    labels[1::2, ::8] = tokens[1::2, ::8]  # 2 targets in odd rows
    key = jax.random.key(0)

    out = {}
    for accum in (1, 2):
        state, _ = init_state(
            gpt_tiny_test(), optax.sgd(1e-2), strategy,
            np.zeros((16, 16), np.int32),
        )
        step = make_custom_train_step(
            strategy, state, loss_fn, donate=False, grad_accum=accum
        )
        state, metrics = step(state, (tokens, labels), key)
        out[accum] = (state.params, metrics)

    _leaves_allclose(out[1][0], out[2][0], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        float(out[1][1]["loss"]), float(out[2][1]["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(out[1][1]["mlm_accuracy"]), float(out[2][1]["mlm_accuracy"]),
        rtol=1e-5,
    )
    # the directive key must not leak into reported metrics
    assert "grad_weight" not in out[1][1] and "grad_weight" not in out[2][1]


@pytest.mark.slow
def test_grad_accum_all_zero_weights_is_noop_not_nan(rng):
    """Every microbatch weightless (an all-IGNORE MLM batch): the update
    must be a clean zero-gradient step, not 0 * inf = NaN params."""
    from tfde_tpu.ops.losses import masked_lm_loss

    def loss_fn(state, params, batch, rng_):
        tokens, labels = batch
        logits = state.apply_fn({"params": params}, tokens, train=True,
                                rngs={"dropout": rng_})
        loss, acc = masked_lm_loss(logits, labels)
        n = jnp.sum((labels != -100).astype(jnp.float32))
        return loss, {"mlm_accuracy": acc, "grad_weight": n}

    strategy = MirroredStrategy()
    tokens = rng.integers(0, 97, (16, 16)).astype(np.int32)
    labels = np.full((16, 16), -100, np.int32)  # zero targets everywhere
    state, _ = init_state(
        gpt_tiny_test(), optax.sgd(1e-2), strategy,
        np.zeros((16, 16), np.int32),
    )
    before = jax.tree_util.tree_map(np.asarray, state.params)
    step = make_custom_train_step(strategy, state, loss_fn, donate=False,
                                  grad_accum=2)
    state, metrics = step(state, (tokens, labels), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    _leaves_allclose(before, state.params, rtol=0, atol=0)


def test_grad_accum_rejects_indivisible_batch(rng):
    strategy = MirroredStrategy()
    state, _ = init_state(
        PlainCNN(), optax.sgd(0.1), strategy, np.zeros((8, 784), np.float32)
    )
    step = make_train_step(strategy, state, donate=False, grad_accum=3)
    images = rng.random((8, 784), np.float32)
    labels = np.zeros((8, 1), np.int32)
    with pytest.raises(ValueError, match="grad_accum"):
        step(state, (images, labels), jax.random.key(0))


def test_grad_accum_rejects_nonpositive():
    strategy = MirroredStrategy()
    state, _ = init_state(
        PlainCNN(), optax.sgd(0.1), strategy, np.zeros((8, 784), np.float32)
    )
    with pytest.raises(ValueError, match="grad_accum"):
        make_train_step(strategy, state, grad_accum=0)
