"""LoRA fine-tuning (training/lora.py).

Contracts under test: (a) the adapted model IS the base model at step 0
(b starts at zero); (b) training moves only the adapters — the frozen
base never changes and the optimizer state is rank-r sized; (c) a LoRA
fine-tune actually learns (loss drops on a synthetic next-token task);
(d) merge_lora at export time reproduces the trained forward exactly, so
the merged checkpoint feeds export/serving.py unchanged; (e) targeting
is regex-scoped and loud on a miss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.data.datasets import synthetic_tokens
from tfde_tpu.models.gpt import GPT, next_token_loss
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.lora import (
    LoraConfig,
    init_lora,
    init_lora_state,
    lora_param_count,
    lora_target_paths,
    make_lora_loss,
    merge_lora,
)
from tfde_tpu.training.step import init_state, make_custom_train_step


def _model():
    return GPT(vocab_size=97, hidden_size=16, depth=2, num_heads=2,
               mlp_dim=32, max_position=32, dtype=jnp.float32)


@pytest.fixture(scope="module")
def base():
    model = _model()
    params = model.init(
        jax.random.key(0), jnp.zeros((2, 8), jnp.int32), train=False
    )["params"]
    return model, params


def test_zero_init_is_identity(base):
    model, params = base
    cfg = LoraConfig(rank=4)
    lora = init_lora(params, cfg, jax.random.key(1))
    merged = merge_lora(params, lora, cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 8)),
                       jnp.int32)
    a = model.apply({"params": params}, toks, train=False)
    b = model.apply({"params": merged}, toks, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_targeting_scope_and_miss(base):
    _, params = base
    all_kernels = lora_target_paths(params, LoraConfig())
    attn_only = lora_target_paths(
        params, LoraConfig(target=r"attn.*/kernel$")
    )
    assert attn_only and set(attn_only) < set(all_kernels)
    assert all(
        "attn" in "/".join(p) for p in attn_only
    )
    with pytest.raises(ValueError, match="matches no rank>=2 kernel"):
        init_lora(params, LoraConfig(target=r"no_such_layer"),
                  jax.random.key(0))


def test_attention_kernels_factorize_on_true_contraction(base):
    """q/k/v kernels are [embed, heads, hd] DenseGeneral layouts contracting
    axis 0; `out` contracts the leading (heads, hd). The adapter must be
    rank-r w.r.t. that map, and fused-qkv models must adapt too."""
    _, params = base
    cfg = LoraConfig(rank=4)
    from flax import traverse_util

    lora = traverse_util.flatten_dict(
        init_lora(params, cfg, jax.random.key(0))
    )
    h = 16
    q = ("decoder", "block_0", "attn", "query", "kernel")
    assert lora[q + ("a",)].shape == (h, 4)
    assert lora[q + ("b",)].shape == (4, h)  # heads*hd == embed here
    o = ("decoder", "block_0", "attn", "out", "kernel")
    assert lora[o + ("a",)].shape == (h, 4)

    fused = GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2,
                mlp_dim=32, max_position=32, dtype=jnp.float32,
                fused_qkv=True)
    fparams = fused.init(
        jax.random.key(0), jnp.zeros((2, 8), jnp.int32), train=False
    )["params"]
    fcfg = LoraConfig(rank=4, target=r"qkv/kernel$")
    flora = init_lora(fparams, fcfg, jax.random.key(1))
    flat = traverse_util.flatten_dict(flora)
    (a_path,) = [p for p in flat if p[-1] == "a"]
    assert flat[a_path].shape == (h, 4)          # contracts embed only
    assert flat[a_path[:-1] + ("b",)].shape == (4, 3 * h)
    merged = merge_lora(fparams, flora, fcfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 97, (2, 8)),
                       jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(fused.apply({"params": fparams}, toks, train=False)),
        np.asarray(fused.apply({"params": merged}, toks, train=False)),
    )


def test_adapter_size_is_rank_r(base):
    _, params = base
    cfg = LoraConfig(rank=2)
    lora = init_lora(params, cfg, jax.random.key(1))
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(params))
    n_lora = lora_param_count(lora)
    assert n_lora < n_base / 5
    # every adapter leaf carries the rank as a dimension
    from flax import traverse_util

    for _path, leaf in traverse_util.flatten_dict(lora).items():
        assert 2 in leaf.shape


@pytest.mark.slow
def test_lora_trains_base_frozen_and_merge_matches(base):
    model, params = base
    cfg = LoraConfig(rank=4, alpha=8.0)
    strategy = MultiWorkerMirroredStrategy()
    base_params = jax.device_put(
        params, strategy.params_sharding(params)
    )
    state, _ = init_lora_state(
        model, optax.adamw(5e-3), strategy, base_params, cfg
    )
    loss_fn = make_lora_loss(base_params, next_token_loss, cfg)
    step_fn = make_custom_train_step(strategy, state, loss_fn, donate=False)

    tokens = synthetic_tokens(256, 16, vocab=96)
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    first = None
    for i in range(60):
        idx = rng.integers(0, len(tokens), 16)
        state, m = step_fn(state, (jnp.asarray(tokens[idx]),), key)
        if first is None:
            first = float(m["loss"])
    last = float(m["loss"])
    assert last < first - 0.3, (first, last)

    # the frozen base was never touched
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(base_params)[0]),
        np.asarray(jax.tree_util.tree_leaves(params)[0]),
    )
    # optimizer state is adapter-sized (the actual memory win)
    opt_elems = sum(
        x.size for x in jax.tree_util.tree_leaves(state.opt_state)
    )
    assert opt_elems < sum(
        x.size for x in jax.tree_util.tree_leaves(params)
    )

    # export contract: merged plain params are base-shaped (they feed
    # export/serving.py unchanged) and reproduce a tuned — not base — model
    merged = merge_lora(base_params, state.params, cfg)
    assert (
        jax.tree_util.tree_structure(merged)
        == jax.tree_util.tree_structure(params)
    )
    toks = jnp.asarray(tokens[:2], jnp.int32)
    via_merge = model.apply({"params": merged}, toks, train=False)
    base_out = model.apply({"params": params}, toks, train=False)
    assert float(jnp.max(jnp.abs(via_merge - base_out))) > 1e-3
