"""TFRecord IO (data/tfrecord.py): round-trip, corruption detection,
cross-compatibility with the event-file framing, Dataset integration,
remote filesystems."""

import struct

import numpy as np
import pytest

from tfde_tpu.data.tfrecord import (
    TFRecordWriter,
    read_tfrecord,
    tfrecord_dataset,
    write_tfrecord,
)


def test_round_trip(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    records = [b"", b"x", b"hello world", bytes(range(256)) * 33]
    assert write_tfrecord(path, records) == 4
    assert list(read_tfrecord(path)) == records


def test_event_files_are_tfrecords(tmp_path):
    """TensorBoard event files use the identical framing — the reader must
    parse a SummaryWriter's output (shared wire format, not a lookalike)."""
    from tfde_tpu.observability.tensorboard import SummaryWriter, _event

    d = str(tmp_path)
    w = SummaryWriter(d)
    w.scalars(1, {"loss": 0.5})
    w.flush()
    w.close()
    import os

    event_file = [f for f in os.listdir(d) if "tfevents" in f][0]
    records = list(read_tfrecord(str(tmp_path / event_file)))
    # first record is the file_version Event, then our summary
    assert len(records) >= 2
    assert b"loss" in records[1]


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "b.tfrecord")
    write_tfrecord(path, [b"payload-one", b"payload-two"])
    blob = bytearray(open(path, "rb").read())
    blob[14] ^= 0xFF  # flip a payload byte of record 0
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="data crc mismatch"):
        list(read_tfrecord(path))
    # opt-out still reads (the corrupted byte passes through)
    recs = list(read_tfrecord(path, verify_crc=False))
    assert len(recs) == 2 and recs[1] == b"payload-two"


def test_truncation_detected(tmp_path):
    path = str(tmp_path / "c.tfrecord")
    write_tfrecord(path, [b"abcdef"])
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-3])  # cut the trailing crc
    with pytest.raises(ValueError, match="truncated"):
        list(read_tfrecord(path))


def test_writer_refuses_after_close(tmp_path):
    w = TFRecordWriter(str(tmp_path / "d.tfrecord"))
    w.write(b"one")
    w.close()
    with pytest.raises(ValueError, match="closed"):
        w.write(b"two")


def test_dataset_integration(tmp_path):
    """records -> parse_fn -> Dataset.shuffle/batch: the tf.data-shaped
    consumption path over serialized examples."""
    path = str(tmp_path / "e.tfrecord")
    write_tfrecord(
        path, [struct.pack("<if", i, i * 0.5) for i in range(10)]
    )

    def parse(rec):
        i, f = struct.unpack("<if", rec)
        return np.int32(i), np.float32(f)

    ds = tfrecord_dataset(path, parse).shuffle(10, seed=0).batch(5)
    batches = list(iter(ds))
    assert len(batches) == 2
    ints = np.concatenate([b[0] for b in batches])
    assert sorted(ints.tolist()) == list(range(10))
    floats = np.concatenate([b[1] for b in batches])
    np.testing.assert_allclose(np.sort(floats), np.arange(10) * 0.5)


def test_remote_fs(tmp_path):
    path = "memory://records/f.tfrecord"
    write_tfrecord(path, [b"r1", b"r2"])
    assert list(read_tfrecord(path)) == [b"r1", b"r2"]


def test_multiple_files(tmp_path):
    p1, p2 = str(tmp_path / "g1.tfrecord"), str(tmp_path / "g2.tfrecord")
    write_tfrecord(p1, [b"a"])
    write_tfrecord(p2, [b"b"])
    ds = tfrecord_dataset([p1, p2])
    assert [e[0] for e in iter(ds)] == [b"a", b"b"]


@pytest.mark.slow
def test_interop_tfdata_reads_our_files(tmp_path):
    """Cross-implementation wire-format check: records written by our
    TFRecordWriter must parse byte-for-byte in real tf.data (the consumer
    a reference-era shop already runs)."""
    tf = pytest.importorskip("tensorflow")

    path = str(tmp_path / "ours.tfrecord")
    payloads = [f"record-{i}".encode() for i in range(7)] + [b"", b"\x00" * 33]
    write_tfrecord(path, payloads)
    got = [bytes(r.numpy()) for r in tf.data.TFRecordDataset(path)]
    assert got == payloads


def test_interop_we_read_tf_written_files(tmp_path):
    """And the other direction: files from tf.io.TFRecordWriter stream
    through our reader with CRC verification on."""
    tf = pytest.importorskip("tensorflow")

    path = str(tmp_path / "theirs.tfrecord")
    payloads = [f"tf-rec-{i}".encode() for i in range(5)] + [b"\xff" * 100]
    with tf.io.TFRecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    assert list(read_tfrecord(path, verify_crc=True)) == payloads
