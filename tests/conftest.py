"""Test harness: 8 virtual CPU devices (SURVEY.md §4).

The JAX-native analog of a fake backend: mesh/psum/sharding/checkpoint tests
run hermetically with no TPU. Must run before any JAX backend is initialized;
the axon site shim imports jax at interpreter start, so we override via
jax.config (backend creation is lazy) rather than env vars.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_devices():
    assert jax.device_count() == 8, "tests expect 8 virtual CPU devices"
    yield


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
