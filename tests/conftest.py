"""Test harness: 8 virtual CPU devices (SURVEY.md §4).

The JAX-native analog of a fake backend: mesh/psum/sharding/checkpoint tests
run hermetically with no TPU. The device count must be set before the CPU
backend is created; the XLA_FLAGS env var works on every JAX release (the
`jax_num_cpu_devices` config option does not exist on all of them), so it is
the primary mechanism and the config update is a guarded extra for versions
that prefer it.
"""

import glob
import mmap
import os


def _xla_flag_supported(flag: str) -> bool:
    """True when the installed jaxlib knows `flag`. An unknown entry in
    XLA_FLAGS is a hard process ABORT at backend creation (not an
    exception), so each optional flag is probed against the jaxlib shared
    objects — flag names are literal strings in the binary — before being
    added."""
    try:
        import jaxlib

        pat = flag.lstrip("-").split("=", 1)[0].encode()
        root = os.path.dirname(jaxlib.__file__)
        for so in glob.glob(os.path.join(root, "**", "*.so"), recursive=True):
            with open(so, "rb") as f:
                with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                    if m.find(pat) != -1:
                        return True
    except Exception:
        return False  # can't tell -> don't risk the abort
    return False


# XLA's in-process CPU collective rendezvous SIGABRTs the whole pytest
# process when the box is oversubscribed (8 virtual devices on 1-2 cores
# under a loaded CI: "Expected 8 threads to join ... only N arrived").
# Raise the warn/terminate timeouts well past any scheduler hiccup where the
# jaxlib has the knobs; the backend is created lazily, so setting the env
# here (before first device use) takes effect, and subprocess-isolated
# tests inherit it.
_flags = [" --xla_force_host_platform_device_count=8"]
for _f in (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200",
):
    if _xla_flag_supported(_f):
        _flags.append(_f)
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + "".join(_flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older/newer JAX without the option: XLA_FLAGS above covers it

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Smoke tier (VERDICT r3 next-round #8): one fast, load-bearing test per
# subsystem, runnable in <3 minutes on one core — `pytest -m smoke`. The
# full 300-test suite stays as the deep tier. Maintained here (not as
# scattered decorators) so the subsystem coverage is reviewable in one
# place; names are nodeid bases (parametrized variants inherit the mark).
SMOKE = {
    "test_models.py::test_bn_cnn_param_count_matches_keras",   # models/cnn
    "test_data.py::test_from_tensor_slices_roundtrip",         # data pipeline
    "test_data.py::test_shard_partitions_examples",            # sharding math
    "test_losses.py::test_ce_matches_hand_computed",           # ops/losses
    "test_mesh.py::test_data_parallel_mesh_spans_all_devices", # runtime/mesh
    "test_train_dp.py::test_dp_matches_single_device_numerics",  # DP psum
    "test_lifecycle.py::test_train_and_evaluate_end_to_end",   # lifecycle
    "test_checkpoint.py::test_save_and_restore_roundtrip",     # checkpoint
    "test_export.py::test_export_and_load_roundtrip",          # export
    "test_tensorboard.py::test_event_file_structure",          # observability
    "test_fs.py::test_fs_helpers_on_memory",                   # remote fs
    "test_optimizers.py::test_mask_excludes_biases_and_scales",  # optimizers
    "test_tensor_parallel.py::test_tp_matches_dp_numerics",    # TP
    "test_pipeline.py::test_pipeline_gradients_match_sequential",  # PP core
    "test_decode.py::test_greedy_cache_matches_full_forward_rollout",  # KV
    "test_speculative.py::test_perfect_draft_full_acceptance", # speculation
    "test_flash_attention.py::test_flash_single_block",        # Pallas kernel
    "test_ring_attention.py::test_ring_causal_matches_reference",  # SP ring
    "test_native_loader.py::test_one_epoch_covers_every_row_once",  # C++ IO
    "test_tfrecord.py::test_round_trip",                       # TFRecord IO
    "test_gpt.py::test_gpt_is_causal",                         # GPT family
    "test_bert.py::test_bert_tiny_forward_shapes",             # BERT family
    "test_vit.py::test_vit_tiny_forward",                      # ViT family
    "test_resnet.py::test_resnet18_forward",                   # ResNet family
    "test_moe.py::test_moe_output_shape_and_aux_loss",         # MoE/EP
    "test_grad_accum.py::test_grad_accum_rejects_indivisible_batch",
    "test_transformer.py::test_causal_masking_blocks_future",  # attention
    "test_transformer.py::test_fused_qkv_matches_unfused",     # fused qkv
    "test_streaming.py::test_one_epoch_exact_multiset",   # streaming input
    "test_pipelined_lm.py::test_1f1b_single_stage_direct",  # 1F1B schedule
    "test_rotary.py",  # whole file: tiny pure-math checks            (RoPE)
    "test_lora.py::test_zero_init_is_identity",            # LoRA adapters
    "test_bert_classifier.py::test_classifier_shapes_and_mask",  # clf head
    # round-5 subsystems
    "test_t5.py::test_t5_cache_decode_equals_full_forward",  # T5 seq2seq
    "test_packing.py::test_packed_forward_equals_solo_forward",  # packing
    "test_rolling_cache.py::test_rolling_cache_is_window_bounded",
    "test_preemption.py::test_preemption_guard_sets_flag_and_restores_handler",
    "test_ema.py::test_ema_tracks_post_update_params",     # param EMA
    "test_bench_logic.py::test_emit_fallback_provenance",  # outage fallback
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        base = base.split("tests/")[-1]
        if base in SMOKE or base.split("::")[0] in SMOKE:
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_devices():
    assert jax.device_count() == 8, "tests expect 8 virtual CPU devices"
    yield


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
