"""ZeRO weight-update sharding (parallel/zero.py): knob resolution and
strategy/RunConfig plumbing, the packed two-segment layout round-trips,
chunk-update bit-parity with the replicated per-leaf update, sharded
init_state on the 8-device mesh (opt-state memory accounting + shardings +
gauges), the eligibility warn-fallbacks, loss-trajectory parity for every
transport x sharding combo, and checkpoint cross-format resume in both
directions against an uninterrupted oracle.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import metrics as obs_metrics
from tfde_tpu.parallel import comms, zero
from tfde_tpu.parallel.strategies import FSDPStrategy, MirroredStrategy
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training import optimizers
from tfde_tpu.training.lifecycle import Estimator, RunConfig
from tfde_tpu.training.step import init_state, make_train_step


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    # every test below states its mode explicitly; the operator's (or
    # tier1.sh's) $TFDE_OPT_SHARDING must not leak in
    monkeypatch.delenv(zero.ENV_OPT_SHARDING, raising=False)


def _dp_mesh(n=8):
    return make_mesh({"data": -1}, jax.devices()[:n])


def _setup(opt_sharding, transport="fp32", n=8, tx=None, model=None,
           grad_accum=1, strategy=None):
    strategy = strategy or MirroredStrategy(
        mesh=_dp_mesh(n), grad_transport=transport, opt_sharding=opt_sharding)
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(model or PlainCNN(), tx or optax.adam(1e-2),
                          strategy, images)
    step = make_train_step(strategy, state, donate=False,
                           grad_accum=grad_accum)
    return strategy, state, step, (images, labels)


def _digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


# -- knob resolution + plumbing -----------------------------------------------
def test_resolve_sugar(monkeypatch):
    assert zero.resolve(None) == "replicated"
    assert zero.resolve("shard") == "shard"
    monkeypatch.setenv(zero.ENV_OPT_SHARDING, "shard")
    assert zero.resolve(None) == "shard"
    with pytest.raises(ValueError):
        zero.resolve("zero1")
    with pytest.raises(TypeError):
        zero.resolve(123)


def test_strategy_knob_plumbing(monkeypatch):
    assert MirroredStrategy(
        mesh=_dp_mesh(4), opt_sharding="shard").opt_sharding == "shard"
    # None defers to the env, resolved lazily at first use
    s = MirroredStrategy(mesh=_dp_mesh(4))
    monkeypatch.setenv(zero.ENV_OPT_SHARDING, "shard")
    assert s.opt_sharding == "shard"
    s.opt_sharding = "replicated"
    assert s.opt_sharding == "replicated"


def test_runconfig_overrides_strategy_knob(tmp_path):
    est = Estimator(
        PlainCNN(), optax.sgd(0.1),
        config=RunConfig(model_dir=str(tmp_path), opt_sharding="shard"),
    )
    assert est.strategy.opt_sharding == "shard"


# -- the packed layout --------------------------------------------------------
def _toy_params():
    return {
        "w": jnp.arange(5000, dtype=jnp.float32).reshape(50, 100) / 7.0,
        "b": jnp.arange(7, dtype=jnp.float32) - 3.0,
        "scale": jnp.full((3,), 1.5, jnp.bfloat16),
    }


def test_layout_and_pack_roundtrip():
    params = _toy_params()
    ccfg = comms.CommsConfig()
    layout = zero.build_layout(params, ccfg, 4)
    # big segment pads to the int8 quantum so fp32- and int8-written
    # sharded checkpoints share chunk boundaries
    assert layout.total_big == 5000 and layout.total_small == 10
    assert layout.padded_big % (4 * ccfg.block) == 0
    assert layout.padded_small % 4 == 0
    packed = zero.pack_params(params, layout)
    assert packed[zero.BIG].shape == (4, layout.chunk_big)
    assert packed[zero.SMALL].shape == (4, layout.chunk_small)
    rt = zero.unpack_packed(packed, layout)
    for k in params:
        assert rt[k].dtype == params[k].dtype
        np.testing.assert_array_equal(np.asarray(rt[k], np.float32),
                                      np.asarray(params[k], np.float32))
    with pytest.raises(ValueError):
        zero.build_layout(params, ccfg, 1)


def test_pack_opt_state_roundtrip_bitwise():
    params = {"w": jnp.arange(4096, dtype=jnp.float32).reshape(64, 64),
              "b": jnp.ones((5,), jnp.float32)}
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    layout = zero.build_layout(params, comms.CommsConfig(), 4)
    packed = zero.pack_opt_state(opt, layout)
    # params-congruent slots became [N, C] chunk trees, scalars untouched
    mu = packed[0].mu
    assert set(mu.keys()) == {zero.BIG, zero.SMALL}
    assert mu[zero.BIG].shape == (4, layout.chunk_big)
    assert packed[0].count.shape == ()
    rt = zero.unpack_opt_state(packed, layout)
    for a, b in zip(jax.tree_util.tree_leaves(opt),
                    jax.tree_util.tree_leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_chunk_update_bitwise_matches_per_leaf_update():
    """The correctness contract: for an elementwise transform (adam), the
    packed-chunk update is bit-identical to the replicated per-leaf one."""
    params = _toy_params()
    params = {k: v.astype(jnp.float32) for k, v in params.items()}
    grads = jax.tree_util.tree_map(lambda p: jnp.cos(p) * 0.1, params)
    tx = optax.adam(1e-2)

    # replicated oracle: two per-leaf updates
    opt = tx.init(params)
    p_ref = params
    for _ in range(2):
        upd, opt = tx.update(grads, opt, p_ref)
        p_ref = optax.apply_updates(p_ref, upd)

    # packed: same numbers, [N, C] chunks (zero-padded tails)
    layout = zero.build_layout(params, comms.CommsConfig(), 4)
    p_pack = zero.pack_params(params, layout)
    g_pack = zero.pack_params(grads, layout)
    opt_p = tx.init(p_pack)
    for _ in range(2):
        upd, opt_p = tx.update(g_pack, opt_p, p_pack)
        p_pack = optax.apply_updates(p_pack, upd)

    out = zero.unpack_packed(p_pack, layout)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p_ref[k]),
                                      np.asarray(out[k]))


# -- sharded init_state -------------------------------------------------------
def test_init_state_shards_opt_state_and_cuts_memory():
    _, sharded, _, _ = _setup("shard")
    _, replicated, _, _ = _setup("replicated")
    assert sharded.opt_sharded and sharded.opt_layout.nshards == 8
    assert not replicated.opt_sharded

    chunk_leaves = [
        l for l in jax.tree_util.tree_leaves(sharded.opt_state)
        if getattr(l, "ndim", 0) >= 1 and l.shape[0] == 8
    ]
    assert chunk_leaves, "no packed [N, C] slots found"
    for leaf in chunk_leaves:
        # genuinely distributed: row-sharded over the data axis
        assert leaf.sharding.spec == P("data")

    rep_bytes = zero.state_bytes(replicated.opt_state)
    sh_bytes = zero.state_bytes(sharded.opt_state, sharded.opt_layout)
    # acceptance floor is 1/4; padding keeps it from the exact 1/8
    assert sh_bytes <= rep_bytes / 4.0
    assert sh_bytes == pytest.approx(rep_bytes / 8.0, rel=0.2)


def test_opt_gauges_exported_at_step_build():
    _, state, _, _ = _setup("shard")
    reg = obs_metrics.default_registry()
    assert reg.gauge("opt/state_bytes").value == pytest.approx(
        zero.state_bytes(state.opt_state, state.opt_layout))
    assert reg.gauge("opt/param_gather_bytes").value > 0.0
    _setup("replicated")
    assert reg.gauge("opt/param_gather_bytes").value == 0.0


def test_comm_bytes_accounts_param_gather_leg():
    tree = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((5,))}
    rep = comms.comm_bytes(tree, comms.CommsConfig(), 8)
    sh = comms.comm_bytes(tree, comms.CommsConfig(), 8,
                          opt_sharding="shard")
    assert rep["param_gather"] == 0.0
    assert sh["param_gather"] > 0.0


def test_sharded_step_census_budget_and_payloads():
    """The lowered sharded step through the census helper
    (analysis/hlolint.py — the tools/lintgate.py pin): the ZeRO budget
    triple at 8-way, no host callback, and the wire asymmetry the packed
    layout promises — the full-param all-gather result outweighs the
    1/8-shard reduce-scatter result."""
    from tfde_tpu.analysis import hlolint

    _, state, step, batch = _setup("shard")
    assert state.opt_sharded
    c = hlolint.census(step.jitted, state, batch, jax.random.key(0))
    assert c.collective_counts == (1, 1, 1)
    assert c.callbacks == 0
    assert c.f64_tensors == 0
    assert c.collective_bytes["all_gather"] > c.collective_bytes[
        "reduce_scatter"]


# -- eligibility fallbacks ----------------------------------------------------
def test_fsdp_falls_back_to_replicated(caplog):
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    strategy = FSDPStrategy(min_shard_elems=1, opt_sharding="shard")
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    with caplog.at_level("WARNING"):
        state, _ = init_state(PlainCNN(), optax.adam(1e-2), strategy, images)
    assert state.opt_layout is None
    assert any("replicated params" in r.message for r in caplog.records)


def test_masked_optimizer_falls_back_to_replicated(caplog):
    """optimizers.adamw carries a path-keyed decay mask (MaskedState): the
    packed tree would silently change what the mask saw, so init_state
    warn-falls-back."""
    with caplog.at_level("WARNING"):
        _, state, step, batch = _setup("shard", tx=optimizers.adamw(1e-3))
    assert state.opt_layout is None
    assert any("masked" in r.message for r in caplog.records)
    new_state, m = step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_state_without_layout_falls_back(caplog):
    """Asking for 'shard' at step-build time against a replicated state
    downgrades with a warning instead of crashing (mirrors the int8
    missing-residual fallback)."""
    strategy, state, _, batch = _setup("replicated")
    with caplog.at_level("WARNING"):
        step = make_train_step(strategy, state, donate=False,
                               opt_sharding="shard")
    assert any("falling back to the replicated update" in r.message
               for r in caplog.records)
    new_state, m = step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


# -- step parity --------------------------------------------------------------
def test_fp32_shard_trajectory_bitwise_matches_replicated():
    """fp32 x shard must be BIT-IDENTICAL to the replicated fp32 oracle:
    the psum-scatter + chunk update + all-gather computes the same fp32
    numbers (power-of-two batch/shard scalings commute exactly)."""
    _, rep_state, rep_step, batch = _setup("replicated")
    _, sh_state, sh_step, _ = _setup("shard")
    for i in range(4):
        rep_state, mr = rep_step(rep_state, batch, jax.random.key(i))
        sh_state, ms = sh_step(sh_state, batch, jax.random.key(i))
        assert float(mr["loss"]) == float(ms["loss"])
    assert _digest(rep_state.params) == _digest(sh_state.params)


def test_fp32_shard_with_grad_accum_tracks_replicated():
    """Under grad_accum the comms-style body accumulates LOCAL weighted
    sums and exchanges once, while the replicated custom body psums every
    microbatch — same math, different summation order, so parity is tight
    but not bitwise (the int8 grad_accum contract)."""
    _, rep_state, rep_step, batch = _setup("replicated", grad_accum=2)
    _, sh_state, sh_step, _ = _setup("shard", grad_accum=2)
    for i in range(3):
        rep_state, mr = rep_step(rep_state, batch, jax.random.key(i))
        sh_state, ms = sh_step(sh_state, batch, jax.random.key(i))
        assert abs(float(mr["loss"]) - float(ms["loss"])) < 5e-3


def test_int8_shard_tracks_fp32_oracle():
    """int8 x shard composes: quantized scatter + sharded update stays
    within the documented int8 tolerance of the fp32 oracle."""
    tx = optax.sgd(0.1, momentum=0.9)
    _, f_state, f_step, batch = _setup("replicated", transport="fp32", tx=tx)
    _, i_state, i_step, _ = _setup("shard", transport="int8", tx=tx)
    assert i_state.opt_sharded and i_state.comm_residual is not None
    diffs = []
    for i in range(6):
        f_state, mf = f_step(f_state, batch, jax.random.key(0))
        i_state, mi = i_step(i_state, batch, jax.random.key(0))
        diffs.append(abs(float(mf["loss"]) - float(mi["loss"])))
    assert max(diffs) < 0.05, diffs
    # grad_norm still reported (folded into the param-gather payload)
    assert float(mi["grad_norm"]) > 0.0


# -- checkpoint cross-compat --------------------------------------------------
def _run_steps(state, step, batch, keys):
    for k in keys:
        state, _ = step(state, batch, jax.random.key(k))
    return state


@pytest.mark.parametrize("write_mode,resume_mode", [
    ("replicated", "shard"),
    ("shard", "replicated"),
])
def test_checkpoint_cross_format_resume_bit_exact(tmp_path, write_mode,
                                                  resume_mode):
    """A checkpoint written under one opt_sharding mode resumes under the
    other and lands bit-exact on the uninterrupted oracle — pack/unpack
    are pure reshapes of the same numbers."""
    _, oracle, oracle_step, batch = _setup(write_mode)
    oracle = _run_steps(oracle, oracle_step, batch, range(4))

    _, writer, writer_step, _ = _setup(write_mode)
    writer = _run_steps(writer, writer_step, batch, range(2))
    mngr = CheckpointManager(str(tmp_path), async_save=False)
    assert mngr.save(writer, force=True)
    mngr.wait()

    _, fresh, resume_step, _ = _setup(resume_mode)
    resumed = mngr.restore_latest(fresh)
    mngr.close()
    assert resumed is not None
    assert int(resumed.step) == 2
    assert resumed.opt_sharded == (resume_mode == "shard")
    resumed = _run_steps(resumed, resume_step, batch, range(2, 4))
    assert _digest(resumed.params) == _digest(oracle.params)
