"""Pipeline-parallelism tests: GPipe schedule equals sequential stage
application (forward + gradients), microbatch order preserved
(SURVEY.md §4 fake-device methodology)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from tfde_tpu.utils import compat
from tfde_tpu.runtime.mesh import make_mesh


def _mesh(shape):
    import math

    n = math.prod(shape.values())
    return make_mesh(shape, jax.devices()[:n])


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(rng, s, d):
    return [
        {
            "w": jnp.asarray(rng.standard_normal((d, d)) * 0.5, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((d,)) * 0.1, jnp.float32),
        }
        for _ in range(s)
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("s,m", [(4, 6), (2, 2), (8, 8)])
def test_pipeline_matches_sequential(rng, s, m):
    mesh = _mesh({"pipe": s})
    d = 8
    stages = _stages(rng, s, d)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((m, 4, d)), jnp.float32)

    got = jax.jit(
        lambda p, x: pipeline_apply(_stage_fn, p, x, mesh)
    )(stacked, x)
    expect = jnp.stack([_sequential(stages, x[i]) for i in range(m)])
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential(rng):
    mesh = _mesh({"pipe": 4})
    d, m = 8, 6
    stages = _stages(rng, 4, d)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((m, 4, d)), jnp.float32)

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)

    def loss_seq(p):
        ys = jnp.stack([
            _sequential(
                [jax.tree_util.tree_map(lambda l: l[i], p) for i in range(4)],
                x[j],
            )
            for j in range(m)
        ])
        return jnp.sum(ys ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe, g_seq,
    )


def test_pipeline_requires_pipe_axis(rng):
    mesh = _mesh({"data": 8})
    stages = _stages(rng, 2, 4)
    with pytest.raises(ValueError, match="pipe"):
        pipeline_apply(
            _stage_fn, stack_stage_params(stages),
            jnp.zeros((2, 2, 4)), mesh,
        )


def test_pipeline_rejects_stage_count_mismatch(rng):
    """4 stacked stages on a 2-rank pipe must error, not silently skip
    stages (regression: shard_map would slice [4,...] to [2,...] and run
    stage2(stage0(x)))."""
    mesh = _mesh({"pipe": 2})
    stages = _stages(rng, 4, 4)
    with pytest.raises(ValueError, match="leading dim"):
        pipeline_apply(
            _stage_fn, stack_stage_params(stages), jnp.zeros((2, 2, 4)), mesh
        )


_partial_auto = pytest.mark.skipif(
    not compat.supports_partial_manual(),
    reason="partial-auto shard_map unsupported on this jax",
)


@_partial_auto
@pytest.mark.parametrize("s,m", [(2, 4), (4, 8)])
def test_pipeline_auto_mode_matches_sequential(rng, s, m):
    """mode='auto' (manual over 'pipe' only; data under the automatic
    partitioner) must equal the sequential stage application — same contract
    as the fully-manual mode."""
    mesh = _mesh({"data": 2, "pipe": s})
    stages = _stages(rng, s, 8)
    x = jnp.asarray(rng.standard_normal((m, 4, 8)), jnp.float32)
    got = jax.jit(
        lambda p, x: pipeline_apply(_stage_fn, p, x, mesh, mode="auto")
    )(stack_stage_params(stages), x)
    want = _sequential(stages, x.reshape(m * 4, 8)).reshape(m, 4, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@_partial_auto
def test_pipeline_auto_mode_gradients_match_manual(rng):
    mesh = _mesh({"data": 2, "pipe": 2})
    stages = stack_stage_params(_stages(rng, 2, 8))
    x = jnp.asarray(rng.standard_normal((4, 4, 8)), jnp.float32)

    def loss(mode):
        def fn(p):
            return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh, mode=mode) ** 2)
        return fn

    va, ga = jax.jit(jax.value_and_grad(loss("auto")))(stages)
    vm, gm = jax.jit(jax.value_and_grad(loss("manual")))(stages)
    np.testing.assert_allclose(float(va), float(vm), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        ga, gm,
    )
