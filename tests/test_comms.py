"""Quantized gradient exchange (parallel/comms.py): config resolution and
mesh eligibility, the packed-buffer plumbing, exchange correctness + the
error-feedback identity on a real multi-device mesh, the fixed-collective
and no-callback guarantees from the lowered HLO, the fp32 no-op
bit-identity, loss-trajectory parity vs the uncompressed oracle, and the
overflow -> numerics-sentry path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tfde_tpu.analysis import hlolint
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability.sentry import (
    FLAG_COMM_OVERFLOW,
    SentryConfig,
    init_state as sentry_init,
)
from tfde_tpu.parallel import comms
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import (
    init_state,
    make_custom_train_step,
    make_train_step,
)
from tfde_tpu.utils import compat


def _dp_mesh(n=4):
    return make_mesh({"data": -1}, jax.devices()[:n])


# -- config resolution --------------------------------------------------------
def test_resolve_sugar(monkeypatch):
    monkeypatch.delenv(comms.ENV_TRANSPORT, raising=False)
    assert comms.resolve(None).transport == "fp32"
    assert comms.resolve("int8").transport == "int8"
    cfg = comms.CommsConfig(transport="int8", block=64)
    assert comms.resolve(cfg) is cfg
    monkeypatch.setenv(comms.ENV_TRANSPORT, "int8")
    assert comms.resolve(None).transport == "int8"
    with pytest.raises(TypeError):
        comms.resolve(123)
    with pytest.raises(ValueError):
        comms.CommsConfig(transport="int4")
    with pytest.raises(ValueError):
        comms.CommsConfig(block=0)


def test_effective_downgrades_ineligible_meshes():
    int8 = comms.CommsConfig(transport="int8")
    # pure-DP multi-device mesh: int8 survives
    assert comms.effective(int8, _dp_mesh(4)).transport == "int8"
    # single data shard: nothing to exchange
    assert comms.effective(int8, _dp_mesh(1)).transport == "fp32"
    # model axis > 1: params not replicated over the exchange axis
    tp = make_mesh({"data": 2, "tensor": 4}, jax.devices())
    assert comms.effective(int8, tp).transport == "fp32"
    # fp32 passes through untouched regardless of mesh
    fp = comms.CommsConfig()
    assert comms.effective(fp, tp) is fp


def test_strategy_knob_and_env(monkeypatch):
    monkeypatch.delenv(comms.ENV_TRANSPORT, raising=False)
    assert MirroredStrategy().comms.transport == "fp32"
    assert MirroredStrategy(grad_transport="int8").comms.transport == "int8"
    monkeypatch.setenv(comms.ENV_TRANSPORT, "int8")
    assert MirroredStrategy().comms.transport == "int8"
    s = MirroredStrategy()
    s.comms = "fp32"  # explicit setter wins over env
    assert s.comms.transport == "fp32"


# -- packing + residual structure ---------------------------------------------
def test_pack_unpack_roundtrip(rng):
    leaves = [
        jnp.asarray(rng.normal(size=s), jnp.float32)
        for s in [(3, 4), (7,), (2, 2, 2)]
    ]
    vec, shapes = comms.pack(leaves)
    assert vec.shape == (3 * 4 + 7 + 8,)
    out = comms.unpack(vec, shapes)
    for a, b in zip(leaves, out):
        assert jnp.array_equal(a, b)
    empty, eshapes = comms.pack([])
    assert empty.size == 0 and comms.unpack(empty, eshapes) == []


def test_compress_mask_and_residual_structure():
    cfg = comms.CommsConfig(transport="int8", min_elems=100)
    params = {"big": jnp.zeros((50, 4)), "small": jnp.zeros((3,)),
              "nest": {"w": jnp.zeros((200,))}}
    mask = comms.compress_mask(params, cfg)
    assert mask == {"big": True, "small": False, "nest": {"w": True}}
    res = comms.init_residual(params, cfg)
    # congruent structure: compressed leaves full-shape, others scalar stubs
    assert res["big"].shape == (50, 4)
    assert res["small"].shape == ()
    assert res["nest"]["w"].shape == (200,)
    assert jax.tree_util.tree_structure(res) == \
        jax.tree_util.tree_structure(params)


def test_comm_bytes_ratio_under_bar():
    cfg = comms.CommsConfig(transport="int8")
    tree = {"w": jnp.zeros((1024, 1024)), "b": jnp.zeros((1024,))}
    b = comms.comm_bytes(tree, cfg, nshards=8)
    assert b["ratio"] <= 0.3, b
    assert b["compressed_elems"] == 1024 * 1024
    assert b["fp32_elems"] == 1024
    # fp32 transport reports identical wire cost on both keys
    b32 = comms.comm_bytes(tree, comms.CommsConfig(), nshards=8)
    assert b32["int8"] == b32["fp32"]


# -- the exchange itself ------------------------------------------------------
def _run_exchange(vecs, residuals, cfg, mesh):
    """Run int8_reduce inside shard_map; returns per-device stacked
    (out, new_res, overflow)."""
    n = mesh.devices.size

    def body(v, r):
        out, new_r, ov = comms.int8_reduce(
            v.reshape(-1), r.reshape(-1), cfg, "data", n,
            rng=jax.random.key(0) if cfg.stochastic else None,
        )
        # keep per-device outputs visible: fake a leading device dim
        return out[None], new_r[None], ov[None]

    f = compat.shard_map(
        body, mesh,
        in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data")),
        check_vma=False,
    )
    return f(jnp.stack(vecs), jnp.stack(residuals))


@pytest.mark.parametrize("stochastic", [False, True])
def test_int8_reduce_matches_fp32_sum(rng, stochastic):
    mesh = _dp_mesh(4)
    L = 1000  # deliberately not a multiple of nshards*block
    cfg = comms.CommsConfig(transport="int8", block=64, stochastic=stochastic)
    vecs = [jnp.asarray(rng.normal(size=(L,)), jnp.float32) for _ in range(4)]
    res = [jnp.zeros((L,), jnp.float32) for _ in range(4)]
    out, new_res, ov = _run_exchange(vecs, res, cfg, mesh)
    ref = sum(vecs)
    # every device reconstructs the same bytes
    for d in range(1, 4):
        assert jnp.array_equal(out[0], out[d])
    # blockwise int8 against the shared absmax: per-element error is
    # bounded by ~2 quantization steps of the block absmax (two stages)
    err = jnp.max(jnp.abs(out[0] - ref))
    bound = 2.5 * jnp.max(jnp.abs(ref)) / 127
    assert err < bound, (err, bound)
    assert float(jnp.max(ov)) == 0.0


def test_int8_reduce_error_feedback_identity(rng):
    """The EF invariant: output + sum_devices(new_residual) ==
    sum_devices(input + old_residual) exactly (up to fp32 rounding) — no
    gradient signal is ever lost, only delayed."""
    mesh = _dp_mesh(4)
    L = 512
    cfg = comms.CommsConfig(transport="int8", block=64, stochastic=False)
    vecs = [jnp.asarray(rng.normal(size=(L,)), jnp.float32) for _ in range(4)]
    res = [jnp.asarray(rng.normal(size=(L,)) * 0.01, jnp.float32)
           for _ in range(4)]
    out, new_res, _ = _run_exchange(vecs, res, cfg, mesh)
    total_in = sum(vecs) + sum(res)
    recovered = out[0] + jnp.sum(new_res, axis=0)
    assert jnp.max(jnp.abs(recovered - total_in)) < 1e-4


def test_int8_reduce_overflow_flag(rng):
    mesh = _dp_mesh(4)
    cfg = comms.CommsConfig(transport="int8", block=64, stochastic=False)
    vecs = [jnp.asarray(rng.normal(size=(256,)), jnp.float32)
            for _ in range(4)]
    vecs[2] = vecs[2].at[10].set(jnp.nan)
    res = [jnp.zeros((256,), jnp.float32) for _ in range(4)]
    _, _, ov = _run_exchange(vecs, res, cfg, mesh)
    assert float(jnp.max(ov)) == 1.0


# -- step integration ---------------------------------------------------------
def _cnn_setup(transport, n=4, batch=16, grad_accum=1, sentry=None,
               opt_sharding=None):
    strategy = MirroredStrategy(mesh=_dp_mesh(n), grad_transport=transport,
                                opt_sharding=opt_sharding)
    rng = np.random.default_rng(0)
    images = rng.random((batch, 784), np.float32)
    labels = rng.integers(0, 10, (batch, 1)).astype(np.int32)
    state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy, images)
    step = make_train_step(strategy, state, grad_accum=grad_accum,
                           sentry=sentry, donate=False)
    return step, state, (images, labels)


def test_fp32_default_is_bit_identical_noop(monkeypatch):
    """grad_transport='fp32' (and unset) must not change the traced program
    at all: identical lowered HLO text."""
    from tfde_tpu.parallel import zero

    monkeypatch.delenv(comms.ENV_TRANSPORT, raising=False)
    monkeypatch.delenv(zero.ENV_OPT_SHARDING, raising=False)
    strategy = MirroredStrategy(mesh=_dp_mesh(4))
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = np.zeros((16, 1), np.int32)
    state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy, images)
    assert state.comm_residual is None  # state structure untouched

    def loss_fn(state, params, batch, rng):
        from tfde_tpu.training.step import _classification_loss
        return _classification_loss(state, params, batch, rng)

    args = (state, (images, labels), jax.random.key(0))
    base = make_custom_train_step(strategy, state, loss_fn, donate=False)
    explicit = make_custom_train_step(strategy, state, loss_fn, donate=False,
                                      comms="fp32")
    assert base.jitted.lower(*args).as_text() == \
        explicit.jitted.lower(*args).as_text()


def test_int8_without_residual_falls_back(caplog):
    """A state built under fp32 has no residual; asking for int8 at
    step-build time downgrades with a warning instead of crashing."""
    strategy = MirroredStrategy(mesh=_dp_mesh(4))  # fp32 default
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    state, _ = init_state(PlainCNN(), optax.sgd(0.1), strategy, images)
    step = make_train_step(strategy, state, comms="int8", donate=False)
    new_state, m = step(state, (images, np.zeros((16, 1), np.int32)),
                        jax.random.key(0))
    assert "comm_overflow" not in m  # fp32 path ran


def test_int8_step_lowering_collective_count_and_no_callback():
    """The fixed-five-collectives guarantee, pinned from the lowered HLO:
    pmax + fp32-sidecar psum (all_reduce x2), int8 reduce_scatter x1,
    all_gather x2 — independent of model tensor count — and no host
    callback sneaks in (the sentry/async-dispatch contract). Pins the
    REPLICATED budget explicitly — under opt_sharding='shard' the trailing
    gradient all-gather becomes a param all-gather (see
    test_sharded_step_lowering_collective_counts)."""
    step, state, batch = _cnn_setup("int8", opt_sharding="replicated")
    c = hlolint.census(step.jitted, state, batch, jax.random.key(0))
    assert c.callbacks == 0
    assert c.collective_counts == (2, 1, 2)


def test_int8_collective_count_independent_of_grad_accum():
    """Compression happens once per update, AFTER accumulation: the
    collective count must not scale with grad_accum."""
    step, state, batch = _cnn_setup("int8", grad_accum=4,
                                    opt_sharding="replicated")
    c = hlolint.census(step.jitted, state, batch, jax.random.key(0))
    assert c.collective_counts == (2, 1, 2)


def test_sharded_step_lowering_collective_counts():
    """The ZeRO x transport collective budgets, pinned from the lowered
    HLO: fp32 x shard = 3 (fp32-sidecar psum + fp32 reduce_scatter + the
    param all_gather), int8 x shard = 4 (sidecar + pmax all_reduce x2 +
    int8 reduce_scatter + param all_gather). The trailing gradient
    all-gather of the replicated int8 path is REPLACED by the updated-
    param all-gather (grad_norm rides its payload), so every combo stays
    within PR 5's five-collective budget — and no host callback."""
    for transport, budget in [("fp32", (1, 1, 1)), ("int8", (2, 1, 1))]:
        step, state, batch = _cnn_setup(transport, opt_sharding="shard")
        assert state.opt_sharded
        c = hlolint.census(step.jitted, state, batch, jax.random.key(0))
        assert c.callbacks == 0
        assert c.collective_counts == budget, transport


def test_explicit_replicated_pin_keeps_int8_budget_exact(monkeypatch):
    """opt_sharding='replicated' (explicit, env cleared) must leave the
    int8 step exactly as before the ZeRO work: five collectives, no packed
    opt state — the tier1.sh TFDE_OPT_SHARDING=replicated contract."""
    from tfde_tpu.parallel import zero

    monkeypatch.delenv(zero.ENV_OPT_SHARDING, raising=False)
    step, state, batch = _cnn_setup("int8", opt_sharding="replicated")
    assert not state.opt_sharded
    c = hlolint.census(step.jitted, state, batch, jax.random.key(0))
    assert c.collective_counts == (2, 1, 2)


def test_int8_step_runs_and_reports_comm_metrics():
    step, state, batch = _cnn_setup("int8")
    state, m = step(state, batch, jax.random.key(0))
    assert {"loss", "grad_norm", "comm_residual_norm",
            "comm_overflow"} <= set(m)
    assert float(m["comm_overflow"]) == 0.0
    assert np.isfinite(float(m["loss"]))
    # residual becomes nonzero after the first exchange
    state, m = step(state, batch, jax.random.key(0))
    assert float(m["comm_residual_norm"]) > 0.0


def test_int8_loss_trajectory_tracks_fp32_oracle():
    """Short-horizon parity on synthetic data: the compressed trajectory
    must stay within a tight tolerance of the uncompressed psum oracle."""
    steps = 6
    f32_step, f32_state, batch = _cnn_setup("fp32")
    i8_step, i8_state, _ = _cnn_setup("int8")
    key = jax.random.key(0)
    diffs = []
    for _ in range(steps):
        f32_state, mf = f32_step(f32_state, batch, key)
        i8_state, mi = i8_step(i8_state, batch, key)
        diffs.append(abs(float(mf["loss"]) - float(mi["loss"])))
    assert max(diffs) < 0.05, diffs


def test_int8_with_grad_accum_tracks_fp32():
    f32_step, f32_state, batch = _cnn_setup("fp32", grad_accum=4)
    i8_step, i8_state, _ = _cnn_setup("int8", grad_accum=4)
    key = jax.random.key(1)
    for _ in range(4):
        f32_state, mf = f32_step(f32_state, batch, key)
        i8_state, mi = i8_step(i8_state, batch, key)
    assert abs(float(mf["loss"]) - float(mi["loss"])) < 0.05


def test_overflow_trips_sentry_flag():
    """NaN input -> non-finite quantizer scale -> FLAG_COMM_OVERFLOW in the
    fused sentry carry (saturation never passes silently)."""
    step, state, batch = _cnn_setup(
        "int8", sentry=SentryConfig(action="warn"))
    images, labels = batch
    images = images.copy()
    images[0, 0] = np.nan
    sstate = sentry_init()
    state, m, sstate = step(state, (images, labels), jax.random.key(0),
                            sstate)
    assert float(m["comm_overflow"]) == 1.0
    assert int(sstate["flag"]) & FLAG_COMM_OVERFLOW


def test_sentry_res_ewma_tracks_residual():
    step, state, batch = _cnn_setup(
        "int8", sentry=SentryConfig(action="warn"))
    sstate = sentry_init()
    for _ in range(3):
        state, m, sstate = step(state, batch, jax.random.key(0), sstate)
    assert int(sstate["flag"]) == 0
    assert float(sstate["res_ewma"]) > 0.0


@pytest.mark.slow
def test_int8_mnist_trajectory_parity_slow():
    """The satellite acceptance run: int8 + error feedback matches the fp32
    psum oracle's loss trajectory over a short MNIST training run on the
    4-device CPU mesh."""
    from tfde_tpu.data import datasets

    (tx, ty), _ = datasets.mnist(flatten=True, n_train=512, n_test=1)
    batches = [(tx[i * 64:(i + 1) * 64], ty[i * 64:(i + 1) * 64])
               for i in range(8)]

    def run(transport):
        strategy = MirroredStrategy(mesh=_dp_mesh(4),
                                    grad_transport=transport)
        state, _ = init_state(PlainCNN(), optax.sgd(0.2), strategy,
                              batches[0][0])
        step = make_train_step(strategy, state, donate=False)
        key = jax.random.key(0)
        losses = []
        for b in batches * 2:  # 16 steps
            state, m = step(state, b, key)
            losses.append(float(m["loss"]))
        return losses

    fp32 = run("fp32")
    int8 = run("int8")
    # both train...
    assert np.mean(fp32[-3:]) < np.mean(fp32[:3])
    assert np.mean(int8[-3:]) < np.mean(int8[:3])
    # ...and the compressed trajectory tracks the oracle step for step
    diffs = [abs(a - b) for a, b in zip(fp32, int8)]
    assert max(diffs) < 0.1, diffs
