"""Fault-injection harness tests: schedules must be deterministic, actions
must fire exactly at the scheduled call/step, and patching must restore."""

import pytest

from tfde_tpu.resilience.faults import (
    DelayFault,
    FaultInjector,
    FaultSchedule,
    RaiseFault,
    StepFaults,
)


def test_fail_on_nth_call():
    inj = FaultInjector(FaultSchedule.fail_on(2, 4))
    calls = []
    op = inj.wrap(lambda x: calls.append(x) or x)
    assert op(1) == 1
    with pytest.raises(IOError):
        op(2)
    assert op(3) == 3
    with pytest.raises(IOError):
        op(4)
    assert op(5) == 5
    assert calls == [1, 3, 5]  # faulted calls never reach the callable


def test_custom_exception_type():
    inj = FaultInjector(FaultSchedule.fail_on(1, exc_type=TimeoutError,
                                              message="slow backend"))
    with pytest.raises(TimeoutError, match="slow backend"):
        inj.wrap(lambda: None)()


def test_slow_on_injects_latency():
    slept = []
    sched = FaultSchedule.slow_on(2, seconds=1.5, sleep=slept.append)
    op = FaultInjector(sched).wrap(lambda: "ok")
    assert op() == "ok" and slept == []
    assert op() == "ok" and slept == [1.5]  # delayed, not failed
    assert op() == "ok" and slept == [1.5]


def test_seeded_schedule_is_reproducible():
    a = FaultSchedule.seeded(seed=42, n_calls=100, p_fail=0.3)
    b = FaultSchedule.seeded(seed=42, n_calls=100, p_fail=0.3)
    c = FaultSchedule.seeded(seed=43, n_calls=100, p_fail=0.3)
    assert set(a.plan) == set(b.plan)
    assert set(a.plan) != set(c.plan)
    assert 10 < len(a.plan) < 50  # ~30 of 100


def test_schedule_rejects_zero_index():
    with pytest.raises(ValueError, match="1-based"):
        FaultSchedule({0: RaiseFault()})


def test_patch_restores_on_exit():
    class Store:
        def save(self, x):
            return f"saved {x}"

    s = Store()
    orig = s.save
    with FaultInjector(FaultSchedule.fail_on(1)).patch(s, "save"):
        with pytest.raises(IOError):
            s.save(1)
        assert s.save(2) == "saved 2"
    assert s.save.__func__ is orig.__func__ if hasattr(s.save, "__func__") else True
    assert s.save(3) == "saved 3"


def test_step_faults_fire_at_step_and_disarm():
    slept = []
    sf = StepFaults({3: DelayFault(seconds=9.0, sleep=slept.append)})
    batches = list(sf.wrap(iter(range(10, 16))))
    assert batches == [10, 11, 12, 13, 14, 15]  # batches unchanged
    assert slept == [9.0]  # fired exactly once, at the 3rd draw
    # a second pass (the restarted attempt) does not re-fire
    assert list(sf.wrap(iter(range(3)))) == [0, 1, 2]
    assert slept == [9.0]


def test_step_faults_raise_interrupts_iteration():
    sf = StepFaults({2: RaiseFault(exc_type=RuntimeError)})
    it = sf.wrap(iter("abc"))
    assert next(it) == "a"
    with pytest.raises(RuntimeError):
        next(it)
