"""Int8 KV cache (TFDE_KV_QUANT, ops/quant.kv_quantize + the
transformer decode paths): the quantizer pinned bit-exact against a
numpy hand oracle with its round-trip bound proven per vector, greedy
serving parity int8-vs-fp through the REAL batcher (dense and paged,
cold and warm-prefix, mid-flight cancel), the per-step logit-error
bound, env-knob resolution, the compile pin (int8 adds ZERO extra
prefill/decode programs), the dtype census + memwatch cross-check on
int8 cells, and the stall-triggered pool defrag carrying the scale
sidecars and the trie's block ids intact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference import decode, paged, server
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.observability import capacity, metrics
from tfde_tpu.ops.quant import kv_dequantize, kv_quantize


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _drain(b, reqs, budgets, max_steps=80):
    ids = [b.submit(p, n) for p, n in zip(reqs, budgets)]
    out = {}
    for _ in range(max_steps):
        for rid, toks in b.step():
            out[rid] = list(map(int, toks))
        if len(out) == len(ids):
            break
    assert len(out) == len(ids), "batcher did not drain"
    return [out[i] for i in ids]


def _match_rate(got, ref):
    """Fraction of greedily matching tokens across the request set —
    the acceptance metric (greedy-match >= 0.98)."""
    hit = tot = 0
    for g, r in zip(got, ref):
        tot += max(len(g), len(r))
        hit += sum(1 for a, b in zip(g, r) if a == b)
    return hit / max(tot, 1)


# the test_paged request stream: two admission waves over three rows,
# one duplicate prompt (the warm trie case), mixed budgets
_PROMPTS = [np.arange(3, 10) % 97, np.arange(5, 11) % 97,
            np.arange(40, 59) % 97, np.arange(7, 12) % 97,
            np.arange(40, 59) % 97]
_BUDGETS = [8, 5, 12, 6, 9]


# --------------------------------------------------------------------------
# kv_quantize / kv_dequantize: oracle, bound, junk tolerance
# --------------------------------------------------------------------------

def _np_kv_quantize(x):
    xf = np.nan_to_num(np.asarray(x, np.float32), posinf=0.0, neginf=0.0)
    amax = np.max(np.abs(xf), axis=-1)
    scale = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.round(xf / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def test_kv_quantize_matches_numpy_oracle(rng):
    x = rng.standard_normal((3, 5, 4, 8)).astype(np.float32) * 7.0
    q, s = kv_quantize(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == x.shape and s.shape == x.shape[:-1]
    qr, sr = _np_kv_quantize(x)
    np.testing.assert_array_equal(np.asarray(q), qr)
    np.testing.assert_allclose(np.asarray(s), sr, rtol=0, atol=0)


def test_kv_roundtrip_error_bound(rng):
    """|x - dequant(quant(x))| <= amax/254 per vector: half a quant step
    at the per-(position, head) grain — the bound the logit-error
    budget in ISSUE/BASELINE derives from."""
    x = rng.standard_normal((4, 9, 2, 16)).astype(np.float32)
    x[0, 0] *= 1e3                    # wide dynamic range across vectors
    x[1, 1] *= 1e-4
    q, s = kv_quantize(jnp.asarray(x))
    back = np.asarray(kv_dequantize(q, s, jnp.float32))
    amax = np.max(np.abs(x), axis=-1, keepdims=True)
    bound = amax / 254.0 + 1e-7
    assert (np.abs(back - x) <= bound).all()
    # dequantize honors the requested storage dtype
    assert kv_dequantize(q, s, jnp.bfloat16).dtype == jnp.bfloat16


def test_kv_quantize_survives_nonfinite_junk():
    """Junk positions (the uninitialized-cache / masked-column hazard)
    must not poison the scale or round-trip to NaN."""
    x = np.zeros((2, 3, 4), np.float32)
    x[0, 0, 0] = np.nan
    x[1, 2, 1] = np.inf
    x[0, 1, 2] = 5.0
    q, s = kv_quantize(jnp.asarray(x))
    assert np.isfinite(np.asarray(s)).all()
    back = np.asarray(kv_dequantize(q, s, jnp.float32))
    assert np.isfinite(back).all()
    assert back[0, 1, 2] == pytest.approx(5.0, rel=1e-2)
    # all-zero vectors quantize to zero, not to garbage via a 0 scale
    assert (np.asarray(q)[1, :2] == 0).all()


# --------------------------------------------------------------------------
# Greedy parity through the real batcher: dense/paged x cold/warm
# --------------------------------------------------------------------------

@pytest.mark.parametrize("use_paged", [False, True])
@pytest.mark.parametrize("prefix", [False, True])
def test_int8_greedy_parity_multiwave(lm, use_paged, prefix):
    model, params = lm
    kw = dict(batch_size=3, max_len=48, scan_depth=4, prefix_cache=prefix)
    ref = _drain(ContinuousBatcher(model, params, paged=False, **kw),
                 _PROMPTS, _BUDGETS)
    bq = ContinuousBatcher(model, params, paged=use_paged,
                           kv_quant="int8", **kw)
    got = _drain(bq, _PROMPTS, _BUDGETS)
    assert _match_rate(got, ref) >= 0.98
    if prefix:
        assert bq._prefix.stats()["hits"] >= 1   # warm path exercised


@pytest.mark.parametrize("use_paged", [False, True])
def test_int8_parity_with_midflight_cancel(lm, use_paged):
    """Cancel one row mid-decode: the survivors' int8 streams still
    match the fp streams of the identical cancel schedule, and (paged)
    the pool drains back to the trie-only residue."""
    model, params = lm

    def run(kv_quant):
        b = ContinuousBatcher(model, params, batch_size=3, max_len=48,
                              scan_depth=2, prefix_cache=False,
                              paged=use_paged, kv_quant=kv_quant)
        rids = [b.submit(p, n) for p, n in zip(_PROMPTS[:3], _BUDGETS[:3])]
        out = {}
        out.update(b.step())
        assert b.cancel(rids[1])
        for _ in range(60):
            out.update(b.step())
            if b.idle:
                break
        if use_paged:
            assert b.block_pool.stats()["active"] == 0
        return [list(map(int, out[r])) for r in (rids[0], rids[2])]

    assert _match_rate(run("int8"), run("fp")) >= 0.98


# --------------------------------------------------------------------------
# Logit error: per-step bound against the fp reference
# --------------------------------------------------------------------------

def test_int8_logit_error_bounded_per_step(lm):
    """Prefill + 6 greedy decode steps, logits captured per step from
    the fp and int8 dense caches: max-abs logit error stays under the
    budget the round-trip bound implies for this depth/width (observed
    ~0.01; budget 0.1), and the argmax never flips."""
    model, params = lm
    prompt = (np.arange(11) * 5 + 2) % 97

    def run(kv_quant):
        dm = decode._decode_clone(model, kv_quant=kv_quant)
        cache = decode.init_cache(model, 1, 24, kv_quant=kv_quant)
        toks = jnp.asarray(prompt[None, :], jnp.int32)
        logits, mut = dm.apply({"params": params, "cache": cache}, toks,
                               train=False, mutable=["cache"])
        cache = mut["cache"]
        outs = [np.asarray(logits[:, -1], np.float32)]
        tok = int(jnp.argmax(logits[0, -1]))
        for _ in range(6):
            logits, mut = dm.apply(
                {"params": params, "cache": cache},
                jnp.asarray([[tok]], jnp.int32), train=False,
                mutable=["cache"])
            cache = mut["cache"]
            outs.append(np.asarray(logits[:, -1], np.float32))
            tok = int(jnp.argmax(logits[0, -1]))
        return outs, cache

    fp, cache_fp = run(None)
    q8, cache_q8 = run("int8")
    for a, b in zip(fp, q8):
        assert np.max(np.abs(a - b)) < 0.1
        assert int(np.argmax(a)) == int(np.argmax(b))
    # the cells themselves honor the round-trip bound plus a small
    # propagation allowance: layer-0 cells see identical inputs in both
    # runs (pure quantization error, amax/254); deeper layers project
    # hidden states that already absorbed the lower layers' quant error
    c = int(prompt.size) + 6

    def leaves(cache, name):
        return [leaf for p, leaf in
                jax.tree_util.tree_leaves_with_path(cache)
                if str(getattr(p[-1], "key", p[-1])) == name]

    for kname in ("cached_key", "cached_value"):
        for ql, sl, fl in zip(leaves(cache_q8, kname),
                              leaves(cache_q8, kname + "_scale"),
                              leaves(cache_fp, kname)):
            back = np.asarray(kv_dequantize(ql, sl, jnp.float32))[:, :c]
            ref = np.asarray(fl, np.float32)[:, :c]
            bound = (np.max(np.abs(ref), -1, keepdims=True) / 254.0
                     + 0.02)
            assert (np.abs(back - ref) <= bound).all()


# --------------------------------------------------------------------------
# Env-knob resolution
# --------------------------------------------------------------------------

def _scale_leaves(cache):
    return [str(getattr(p[-1], "key", p[-1])) for p, _ in
            jax.tree_util.tree_leaves_with_path(cache)
            if str(getattr(p[-1], "key", p[-1])).endswith("_scale")]


def test_env_knob_selects_kv_quant(lm, monkeypatch):
    model, params = lm
    kw = dict(batch_size=2, max_len=32, scan_depth=2, prefix_cache=False)
    monkeypatch.setenv("TFDE_KV_QUANT", "int8")
    b = ContinuousBatcher(model, params, **kw)
    assert b._kv_quant == "int8" and _scale_leaves(b._cache)
    monkeypatch.setenv("TFDE_KV_QUANT", "fp")
    b = ContinuousBatcher(model, params, **kw)
    assert b._kv_quant is None and not _scale_leaves(b._cache)
    # junk spelling: warn-and-default, never a crash mid-boot
    monkeypatch.setenv("TFDE_KV_QUANT", "int5")
    b = ContinuousBatcher(model, params, **kw)
    assert b._kv_quant is None
    # the explicit constructor arg overrides the env
    b = ContinuousBatcher(model, params, kv_quant="int8", **kw)
    assert b._kv_quant == "int8" and _scale_leaves(b._cache)


def test_int8_refuses_rolling_and_bad_spelling(lm):
    model, params = lm
    with pytest.raises(ValueError, match="rolling"):
        decode._decode_clone(model, rolling=True, kv_quant="int8")
    with pytest.raises(ValueError, match="kv_quant"):
        decode._decode_clone(model, kv_quant="int4")


def test_int8_headroom_vs_fp_allocated_bytes(lm):
    """The point of the exercise: at fp32 storage the int8 slab prices
    >= 1.8x more rows into the same bytes (head_dim 8 here -> payload
    4x smaller, scale sidecar 1/8 of a cell: ratio 4 / 1.5 = 2.67)."""
    model, params = lm
    kw = dict(batch_size=2, max_len=32, scan_depth=2, prefix_cache=False)
    fp = ContinuousBatcher(model, params, kv_quant="fp", **kw)
    q8 = ContinuousBatcher(model, params, kv_quant="int8", **kw)
    ratio = fp.kv_stats()["allocated_bytes"] / q8.kv_stats()["allocated_bytes"]
    assert ratio >= 1.8


# --------------------------------------------------------------------------
# Compile pin: int8 adds ZERO extra prefill/decode programs
# --------------------------------------------------------------------------

def _program_count():
    return sum(f._cache_size() for f in (
        server._decode_scan, server._prefill_rows, server._prefill_suffix,
        server._paged_prefill_chunk))


@pytest.mark.parametrize("use_paged", [False, True])
def test_int8_compiles_no_extra_programs(lm, use_paged):
    """Same request stream, fresh shape (batch 3 / max_len 44 is unique
    to this test): the int8 drain must add exactly as many program
    signatures as the fp drain — quantization changes leaf dtypes, not
    the static program set."""
    model, params = lm
    kw = dict(batch_size=3, max_len=44, scan_depth=3, prefix_cache=False,
              paged=use_paged)
    deltas = []
    for kv_quant in ("fp", "int8"):
        before = _program_count()
        _drain(ContinuousBatcher(model, params, kv_quant=kv_quant, **kw),
               _PROMPTS, _BUDGETS)
        deltas.append(_program_count() - before)
    assert deltas[1] <= deltas[0], (
        f"int8 compiled {deltas[1]} programs where fp compiled "
        f"{deltas[0]} — the zero-extra-programs claim regressed"
    )


# --------------------------------------------------------------------------
# Census + ledger: dtype-true byte accounting, memwatch cross-check
# --------------------------------------------------------------------------

def test_kv_dtype_census_hand_computed(lm):
    model, _ = lm
    # fp32 dense cache, B=2, S=16: per layer 2 x [2,16,4,8] f32 = 8192 B
    fp = decode.init_cache(model, 2, 16)
    c = capacity.kv_dtype_census(fp)
    assert c["kv_dtype"] == "float32" and c["kv_quant_bits"] == 32
    assert c["kv_payload_bytes"] == 2 * 2 * (2 * 16 * 4 * 8) * 4
    assert c["kv_scale_bytes"] == 0
    assert c["kv_fp32_equiv_bytes"] == c["kv_payload_bytes"]
    # int8: payload shrinks 4x, scale sidecars [2,16,4] f32 appear
    q8 = decode.init_cache(model, 2, 16, kv_quant="int8")
    c = capacity.kv_dtype_census(q8)
    assert c["kv_dtype"] == "int8" and c["kv_quant_bits"] == 8
    assert c["kv_payload_bytes"] == 2 * 2 * (2 * 16 * 4 * 8)
    assert c["kv_scale_bytes"] == 2 * 2 * (2 * 16 * 4) * 4
    assert c["kv_fp32_equiv_bytes"] == 4 * c["kv_payload_bytes"]


def test_int8_ledger_census_gauges_published(lm):
    model, params = lm
    b = ContinuousBatcher(model, params, batch_size=2, max_len=32,
                          scan_depth=2, prefix_cache=False, kv_quant="int8")
    s = b.kv_stats()
    assert s["kv_quant_bits"] == 8
    assert s["kv_payload_bytes"] + s["kv_scale_bytes"] == s["allocated_bytes"]
    assert s["kv_fp32_equiv_bytes"] == 4 * s["kv_payload_bytes"]
    reg = metrics.default_registry()
    assert reg.get("kv/quant_bits").value == 8
    assert reg.get("kv/payload_bytes").value == s["kv_payload_bytes"]


def test_int8_used_bytes_matches_memwatch_device_bytes(lm, rng):
    """The satellite-2 pin on int8 cells: mid-flight, the ledger's
    used_bytes (per-cell cost from the slab's OWN bytes — int8 payload
    plus fp32 scale sidecars) tracks memwatch.device_bytes over the
    live cache cells within 20%."""
    from tfde_tpu.inference.prefix_cache import is_index_leaf
    from tfde_tpu.observability import memwatch

    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=3, max_len=48,
                            kv_quant="int8")
    for plen, n in [(5, 24), (9, 20), (3, 28)]:
        srv.submit(rng.integers(0, 97, plen).astype(np.int64), n)
    for _ in range(2):
        srv.step()
    s = srv.kv_stats()
    assert s["rows_active"] == 3 and s["used_cells"] > 0
    live = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(srv._cache):
        if is_index_leaf(path):
            continue
        for r in range(3):
            if srv._req[r] is not None and srv._committed[r]:
                live.append(leaf[r: r + 1, : int(srv._committed[r])])
    measured = memwatch.device_bytes(live)
    assert measured > 0
    assert s["used_bytes"] == pytest.approx(measured, rel=0.2)
    srv.run()


# --------------------------------------------------------------------------
# Stall-triggered defrag: scale sidecars, trie ids, parity
# --------------------------------------------------------------------------

def test_pool_fragmentation_measure():
    pool = paged.BlockPool(10, 16)
    assert pool.fragmentation() == 0.0        # empty
    a = pool.alloc(6)
    assert pool.fragmentation() == 0.0        # dense prefix
    pool.free([a[0], a[2], a[4]])             # live {2, 4, 6}
    assert pool.fragmentation() == pytest.approx(0.5)
    pool.defrag()                             # live -> {1, 2, 3}
    assert pool.fragmentation() == 0.0


def test_apply_defrag_moves_scale_sidecars():
    n, blk = 6, 4
    ids = jnp.arange(n, dtype=jnp.float32)
    cache = {"layer": {
        "pool_key": ids[:, None, None, None]
        * jnp.ones((n, blk, 1, 1), jnp.float32),
        "pool_key_scale": ids[:, None, None]
        * jnp.ones((n, blk, 1), jnp.float32),
        "pool_value": jnp.zeros((n, blk, 1, 1), jnp.float32),
        "pool_value_scale": jnp.zeros((n, blk, 1), jnp.float32),
    }}
    tables = np.asarray([[4, 2, 0]], np.int32)
    cache, tables = paged.apply_defrag(cache, tables, {2: 1, 4: 2})
    assert tables.tolist() == [[2, 1, 0]]
    sc = np.asarray(cache["layer"]["pool_key_scale"])[:, 0, 0]
    assert sc[1] == 2.0 and sc[2] == 4.0      # sidecar followed its payload


def test_trie_remap_follows_defrag_plan():
    pool = paged.BlockPool(8, 4)
    trie = paged.PagedPrefixCache(pool, block_bytes=64.0)
    ids = pool.alloc(2)
    toks = np.arange(9) % 7                   # 2 complete blocks
    assert trie.insert(toks, ids) == 2
    assert trie.remap({ids[0]: 6, ids[1]: 7}) == 2
    got, matched = trie.lookup(toks)
    assert got == 8 and matched == [6, 7]
    assert trie.remap({}) == 0


def test_stall_hook_fires_on_capacity_stall(lm, monkeypatch):
    """The wiring: an admission that cannot fit the pool must invoke
    _on_capacity_stall on the stall path."""
    model, params = lm
    b = ContinuousBatcher(model, params, batch_size=2, max_len=48,
                          scan_depth=2, prefix_cache=False, paged=True,
                          pool_blocks=5)          # 4 allocatable blocks
    fired = []
    monkeypatch.setattr(b, "_on_capacity_stall", lambda: fired.append(1))
    first = b.submit(np.arange(25) % 97, 4)       # 2 blocks: admitted
    b.step()
    rid = b.submit(np.arange(40) % 97, 4)         # needs 3, 1 free: stalls
    b.step()
    assert fired
    b.cancel(rid)
    b.cancel(first)


def test_defrag_on_stall_preserves_outputs(lm, monkeypatch):
    """The end-to-end parity pin: with the threshold knob armed, a
    defrag fired mid-flight on a fragmented int8 pool leaves every
    token stream bit-identical, moves the trie's blocks, bumps the
    kv/pool_defrags counter and drops a flightrec breadcrumb."""
    from tfde_tpu.observability import flightrec

    model, params = lm
    prompts = _PROMPTS + [np.arange(17, 30) % 97]
    budgets = _BUDGETS + [7]

    def run(thr):
        monkeypatch.setenv("TFDE_KV_DEFRAG_THRESHOLD", thr)
        b = ContinuousBatcher(model, params, batch_size=3, max_len=48,
                              scan_depth=2, prefix_cache=True, paged=True,
                              kv_quant="int8")
        ids = [b.submit(p, n) for p, n in zip(prompts, budgets)]
        out, fired = {}, 0
        for _ in range(80):
            if b._pool.fragmentation() > 0 and not fired:
                b._on_capacity_stall()
                fired += 1
            for rid, toks in b.step():
                out[rid] = list(map(int, toks))
            if len(out) == len(ids):
                break
        assert len(out) == len(ids)
        return [out[i] for i in ids]

    before = metrics.default_registry().counter("kv/pool_defrags").value
    ref = run("0")                            # 0 disables: no defrag
    assert metrics.default_registry().counter("kv/pool_defrags").value \
        == before
    got = run("0.01")
    assert got == ref
    after = metrics.default_registry().counter("kv/pool_defrags").value
    assert after >= before + 1
    crumbs = [e for e in flightrec.default_recorder().events()
              if e["kind"] == "kv_defrag"]
    assert crumbs and crumbs[-1]["moved"] >= 1
