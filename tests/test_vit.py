"""ViT scale-config tests: canonical parameter parity, forward shapes, FSDP
sharded training, example smoke (SURVEY.md §4; BASELINE.json configs[3])."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tfde_tpu.models.vit import ViT_B16, vit_tiny_test
from tfde_tpu.parallel.strategies import FSDPStrategy
from tfde_tpu.training.step import init_state, make_train_step
import pytest


def test_vit_b16_param_count():
    # Canonical ViT-B/16 with 1000-class head: 86,567,656 params
    # (86.6M, Dosovitskiy et al. Table 1).
    m = ViT_B16(num_classes=1000)
    v = jax.eval_shape(m.init, jax.random.key(0), jnp.zeros((1, 224, 224, 3)))
    n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
    assert n == 86_567_656


def test_vit_tiny_forward(rng):
    m = vit_tiny_test()
    x = jnp.asarray(rng.random((3, 32, 32, 3), np.float32))
    v = m.init(jax.random.key(0), x, train=False)
    logits = m.apply(v, x, train=False)
    assert logits.shape == (3, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" not in v  # no BN anywhere in the transformer path


def test_vit_gap_pool_matches_seq_len(rng):
    m = vit_tiny_test(pool="gap")
    x = jnp.asarray(rng.random((2, 32, 32, 3), np.float32))
    v = m.init(jax.random.key(0), x, train=False)
    # gap variant has no cls token parameter
    assert "cls_token" not in v["params"]
    assert v["params"]["pos_embed"].shape == (1, 64, 32)  # (32/4)^2 patches


@pytest.mark.slow
def test_vit_fsdp_train_loss_decreases(rng):
    strategy = FSDPStrategy(data=2, min_shard_elems=1)
    m = vit_tiny_test()
    sample = np.zeros((16, 32, 32, 3), np.float32)
    state, _ = init_state(m, optax.adamw(1e-3), strategy, sample)
    step = make_train_step(strategy, state, donate=False)
    images = rng.random((16, 32, 32, 3), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    key = jax.random.key(0)
    first = None
    for _ in range(5):
        state, metrics = step(state, (images, labels), key)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_vit_fsdp_params_actually_sharded():
    strategy = FSDPStrategy(data=1, min_shard_elems=1)
    m = vit_tiny_test()
    state, _ = init_state(m, optax.sgd(0.1), strategy, np.zeros((8, 32, 32, 3), np.float32))
    fc1 = state.params["encoder"]["block_0"]["mlp"]["fc1"]["kernel"]
    specs = {s for s in fc1.sharding.spec}
    assert "fsdp" in specs, f"fc1 kernel should shard over fsdp, got {fc1.sharding.spec}"


def test_imagenet_vit_example_smoke():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples import imagenet_vit

    state = imagenet_vit.main(
        ["--tiny", "--image-size", "32", "--max-steps", "2",
         "--batch-size", "16", "--data", "2", "--train-examples", "64"]
    )
    assert int(jax.device_get(state.step)) == 2
