"""Multi-process distributed test (SURVEY.md §4: "spawn N local processes
with jax.distributed.initialize — the TF_CONFIG analog"): two real OS
processes bootstrap from the reference's CLUSTER_SPEC env contract, form one
SPMD group over loopback, train sync-DP, and must agree bit-for-bit on the
final replicated params."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent(
    """
    import hashlib, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data import device_prefetch
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    info = bootstrap()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2

    strategy = MultiWorkerMirroredStrategy()
    rng = np.random.default_rng(0)  # same stream on both hosts (policy OFF)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(
        BatchNormCNN(), optax.sgd(0.1), strategy,
        np.zeros((16, 784), np.float32),
    )
    step = make_train_step(strategy, state, donate=False)
    feed = device_prefetch(
        iter([(images, labels)] * 4), strategy.mesh,
        policy=AutoShardPolicy.OFF,
    )
    losses = []
    for batch in feed:
        state, m = step(state, batch, jax.random.key(0))
        losses.append(float(jax.device_get(m["loss"])))
    leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    digest = hashlib.sha256(
        b"".join(np.ascontiguousarray(l).tobytes() for l in leaves)
    ).hexdigest()
    print(json.dumps({
        "process_id": info.process_id,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "params_sha": digest,
    }))
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sync_dp_agrees(tmp_path):
    # runaway children are bounded by communicate(timeout=240) below
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}

    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )

    results = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    assert {r["process_id"] for r in results} == {0, 1}
    # sync DP: replicated params identical across processes, loss decreased
    assert results[0]["params_sha"] == results[1]["params_sha"]
    assert results[0]["last_loss"] < results[0]["first_loss"]
    assert results[0]["last_loss"] == pytest.approx(results[1]["last_loss"])


_LIFECYCLE_CHILD = textwrap.dedent(
    """
    import json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data import Dataset
    from tfde_tpu.data.device import local_slice_for_process
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.export.serving import FinalExporter
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    phase, model_dir = sys.argv[1], sys.argv[2]
    info = bootstrap()
    assert jax.process_count() == 2, jax.process_count()

    rng = np.random.default_rng(0)  # same stream on both hosts (policy OFF)
    X = rng.random((64, 784), np.float32)
    Y = rng.integers(0, 10, (64, 1)).astype(np.int32)
    train_fn = lambda: (
        Dataset.from_tensor_slices((X, Y))
        .shuffle(64, seed=0).repeat().batch(16, drop_remainder=True)
    )
    eval_fn = lambda: Dataset.from_tensor_slices((X[:32], Y[:32])).batch(16)

    cfg = RunConfig(model_dir=model_dir, save_checkpoints_steps=5,
                    save_summary_steps=5)
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)

    if phase == "first":
        state = est.train(train_fn, max_steps=10,
                          shard_policy=AutoShardPolicy.OFF)
    else:
        # 'restarted cluster': same model_dir, fresh processes. max_steps is
        # absolute, so the completed 10 steps must be a no-op...
        state = est.train(train_fn, max_steps=10,
                          shard_policy=AutoShardPolicy.OFF)
        assert int(jax.device_get(state.step)) == 10, "resume failed"
        # ...and training continues from the checkpoint to 16
        state = est.train(train_fn, max_steps=16,
                          shard_policy=AutoShardPolicy.OFF)

    metrics = est.evaluate(eval_fn)
    export_path = None
    if phase == "resume":
        export_path = est.export_saved_model(
            FinalExporter("exporter", (None, 784))
        )
    est.close()

    per, sl = local_slice_for_process(16)
    print(json.dumps({
        "process_id": info.process_id,
        "step": int(jax.device_get(state.step)),
        "loss": metrics["loss"],
        "accuracy": metrics["accuracy"],
        "chief_gating_ok": (est._writer() is not None) == (info.process_id == 0),
        "slice": [sl.start, sl.stop],
        "per_host": per,
        "export": export_path,
    }))
    """
)


def _run_group(script_path, argv, n=2, timeout=300):
    ports = [_free_port() for _ in range(n)]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script_path)] + argv,
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))
    return results


def test_two_process_estimator_lifecycle_and_resume(tmp_path):
    """VERDICT r2 #7: the full Estimator lifecycle across 2 real processes —
    train with chief-only summaries, collective checkpointing, eval, restart
    the whole group and resume from the checkpoint, final export; OFF-policy
    host slices reconstruct the global batch."""
    script = tmp_path / "child_lifecycle.py"
    script.write_text(_LIFECYCLE_CHILD)
    model_dir = str(tmp_path / "run")

    first = _run_group(script, ["first", model_dir])
    assert {r["process_id"] for r in first} == {0, 1}
    assert all(r["step"] == 10 for r in first)
    assert all(r["chief_gating_ok"] for r in first)
    # sync SPMD: both processes computed identical eval metrics
    assert first[0]["loss"] == pytest.approx(first[1]["loss"])
    assert first[0]["accuracy"] == first[1]["accuracy"]
    # OFF-policy slices tile the global batch exactly (data/device.py)
    slices = sorted(tuple(r["slice"]) for r in first)
    assert slices == [(0, 8), (8, 16)]
    assert all(r["per_host"] == 8 for r in first)
    # checkpoints landed in the shared model_dir
    ckpts = os.listdir(os.path.join(model_dir, "checkpoints"))
    assert any(d.isdigit() for d in ckpts)

    # "kill" the cluster (phase-1 processes have exited) and restart
    resumed = _run_group(script, ["resume", model_dir])
    assert all(r["step"] == 16 for r in resumed)
    assert resumed[0]["loss"] == pytest.approx(resumed[1]["loss"])
    # chief exported; non-chief didn't
    exports = {r["process_id"]: r["export"] for r in resumed}
    assert exports[0] is not None and os.path.exists(exports[0])
    assert exports[1] is None


_FSDP_CHILD = textwrap.dedent(
    """
    import hashlib, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import FSDPStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    info = bootstrap()
    assert jax.process_count() == 2
    strategy = FSDPStrategy(min_shard_elems=1)  # fsdp axis spans both hosts

    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(PlainCNN(), optax.adam(1e-3), strategy,
                          np.zeros((16, 784), np.float32))
    # params are actually sharded across the two processes
    kernel = state.params["Dense_0"]["kernel"]
    assert kernel.sharding.spec[0] == "fsdp", kernel.sharding.spec
    assert not kernel.is_fully_addressable  # cross-host array

    step = make_train_step(strategy, state, donate=False)
    import jax.numpy as jnp
    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.data.pipeline import AutoShardPolicy
    feed = device_prefetch([(images, labels)] * 3, strategy.mesh,
                           policy=AutoShardPolicy.OFF)
    for batch in feed:
        state, m = step(state, batch, jax.random.key(0))
    # gather the sharded params to host (allowed: fetch per-shard, hash the
    # process-local bytes of the replicated loss + local shards)
    loss = float(jax.device_get(m["loss"]))
    local = [np.ascontiguousarray(s.data) for s in kernel.addressable_shards]
    digest = hashlib.sha256(b"".join(x.tobytes() for x in local)).hexdigest()
    print(json.dumps({"process_id": info.process_id, "loss": loss,
                      "shard_sha": digest}))
    """
)


def test_two_process_fsdp_shards_and_agrees(tmp_path):
    """ZeRO/FSDP across two real processes (the DCN-analog layout): params
    shard over the cross-host 'fsdp' axis (not fully addressable anywhere),
    training runs, and both processes agree on the replicated loss."""
    script = tmp_path / "child_fsdp.py"
    script.write_text(_FSDP_CHILD)
    results = _run_group(script, [])
    assert {r["process_id"] for r in results} == {0, 1}
    assert results[0]["loss"] == pytest.approx(results[1]["loss"])
    # each host holds a different shard of the same kernel
    assert results[0]["shard_sha"] != results[1]["shard_sha"]


_OBS_CHILD = textwrap.dedent(
    """
    import json, os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    from tfde_tpu import bootstrap
    from tfde_tpu.observability import aggregate, flightrec, metrics
    from tfde_tpu.observability.exposition import MetricsServer

    model_dir, port_file, stop_file = sys.argv[1:4]
    info = bootstrap()
    assert jax.process_count() == 2

    if info.process_id == 0:
        # chief: /metrics + aggregator; stays up after the worker is killed
        reg = metrics.Registry()
        agg = aggregate.ClusterAggregator(registry=reg, include_local=0,
                                          stale_after=1.5)
        srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                            aggregator=agg)
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, port_file)
        deadline = time.time() + 180
        while not os.path.exists(stop_file) and time.time() < deadline:
            time.sleep(0.05)
        out = agg.rollup()
        print(json.dumps({"process_id": 0,
                          "hosts_stale": out["hosts_stale"],
                          "stale_hosts": out["stale_hosts"]}))
        sys.stdout.flush()
        os._exit(0)  # peer was SIGKILLed: skip jax.distributed teardown
    else:
        # worker: flight recorder armed + metrics pusher, then wait to die
        flightrec.arm(model_dir)
        flightrec.record("worker_alive", pid=os.getpid())
        wreg = metrics.Registry()
        wreg.gauge("train/steps_per_sec").set(21.0)
        wreg.histogram("train/step").observe(0.1)
        deadline = time.time() + 180
        while not os.path.exists(port_file) and time.time() < deadline:
            time.sleep(0.05)
        with open(port_file) as f:
            port = int(f.read())
        pusher = aggregate.MetricsPusher(
            f"http://127.0.0.1:{port}/push", interval=0.25,
            registry=wreg, host=info.process_id)
        time.sleep(300)  # the parent SIGTERMs us here
    """
)


_REPLICA_CHILD = textwrap.dedent(
    """
    import os, pickle, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import jax.numpy as jnp
    import numpy as np
    from tfde_tpu.inference.router import ReplicaServer
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import gpt_tiny_test
    from tfde_tpu.observability import boot as boot_lib

    rid, port_file = int(sys.argv[1]), sys.argv[2]
    push_url = sys.argv[3] or None   # "" -> no metrics pusher
    model_dir = sys.argv[4] if len(sys.argv) > 4 else None
    hold_file = sys.argv[5] if len(sys.argv) > 5 else ""
    led = boot_lib.current()   # init phase backdates to process birth
    led.begin("init")
    model = gpt_tiny_test()
    params = model.init(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    # a real (tiny) checkpoint round-trip so the restore phase and its
    # bandwidth gauge carry measured numbers in the drill
    ckpt = port_file + ".ckpt"
    with open(ckpt, "wb") as f:
        pickle.dump(jax.device_get(params), f)
    led.begin("restore")
    t0 = time.perf_counter()
    with open(ckpt, "rb") as f:
        params = pickle.load(f)
    led.note_restore_leaf(
        "params",
        sum(x.nbytes for x in jax.tree_util.tree_leaves(params)),
        max(time.perf_counter() - t0, 1e-9))
    os.remove(ckpt)
    led.begin("compile")
    b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64)
    rng = np.random.default_rng(rid)
    for ln in (4, 6):   # warm the compiles before announcing the port
        b.submit(rng.integers(1, 90, ln), 6)
    b.run()
    led.begin("warmup")
    b.submit(rng.integers(1, 90, 4), 4)
    b.run()
    srv = ReplicaServer(b, replica_id=rid, push_url=push_url,
                        push_interval=0.3, model_dir=model_dir,
                        boot_ledger=led).start()

    def announce():
        with open(port_file + ".tmp", "w") as f:
            f.write(str(srv.port))
        os.replace(port_file + ".tmp", port_file)

    if hold_file:
        # joining-replica drill: announce while still warming so the
        # router can observe a not-ready boot; become ready only when
        # the parent releases the hold (the wait is warmup wall)
        announce()
        while not os.path.exists(hold_file):
            time.sleep(0.05)
        led.ready()
    else:
        led.ready()
        announce()
    while True:
        time.sleep(3600)   # the parent SIGKILLs replica 0, SIGTERMs 1
    """
)


def test_killed_replica_drains_to_survivor(tmp_path):
    """The PR's serving acceptance drill, in-suite: two REAL replica
    processes behind the Router; SIGKILL one mid-service and verify the
    next sessions re-route to the survivor with solo-correct outputs,
    the router's flight ring dumps the `replica_down` story, and the
    chief aggregator's host-up gauge flips when the dead replica's
    metric pushes go stale. Tracing rides along (children spawn with
    TFDE_TRACE=on): the re-routed request's stitched waterfall must show
    BOTH replicas in the routing story and the survivor's serve events,
    and the replica_down flight record must cross-reference the traces
    stranded on the dead replica. Boot observability closes the loop: a
    REPLACEMENT replica then rejoins, serves zero requests before its
    readiness state is `ready`, and its boot-phase decomposition must
    sum to the birth->first-token wall within 5%."""
    import glob
    import signal
    import time
    import urllib.error
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.decode import generate
    from tfde_tpu.inference.router import Router, request_generate
    from tfde_tpu.models.gpt import gpt_tiny_test
    from tfde_tpu.observability import flightrec, metrics
    from tfde_tpu.observability import trace as reqtrace
    from tfde_tpu.observability.aggregate import ClusterAggregator
    from tfde_tpu.observability.exposition import serve_metrics

    model = gpt_tiny_test()
    params = model.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]

    def solo(prompt, n):
        toks, lengths = generate(
            model, params,
            jnp.asarray(np.asarray(prompt)[None, :], jnp.int32),
            max_new_tokens=n,
        )
        return np.asarray(toks)[0, len(prompt) : int(lengths[0])].tolist()

    script = tmp_path / "child_replica.py"
    script.write_text(_REPLICA_CHILD)
    router_dir = str(tmp_path / "router")
    port_files = [str(tmp_path / f"port{i}") for i in range(2)]

    reg = metrics.default_registry()
    reg.reset("router/")
    agg = ClusterAggregator(stale_after=3.0)
    ms = serve_metrics(host="127.0.0.1", aggregator=agg)
    push = f"http://127.0.0.1:{ms.port}/push"

    procs, router, router2 = [], None, None
    # the parent's ring carries the router half of the stitched waterfall
    trace_was_on = reqtrace.active()
    if not trace_was_on:
        reqtrace.enable()
    try:
        for i in range(2):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)   # children run 1 device, not 8
            env["TFDE_TRACE"] = "on"     # replicas record their rings
            env["TFDE_USAGE_LOG"] = "on"  # journal per-request usage
            env["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), str(i), port_files[i],
                     push, str(tmp_path / f"rep{i}")],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True,
                )
            )
        deadline = time.time() + 240
        while not all(os.path.exists(p) for p in port_files):
            for p in procs:
                assert p.poll() is None, p.communicate()[1][-3000:]
            assert time.time() < deadline, "children never announced ports"
            time.sleep(0.1)
        urls = []
        for pf in port_files:
            with open(pf) as f:
                urls.append(f"http://127.0.0.1:{int(f.read())}")
        router = Router(urls, aggregator=agg, model_dir=router_dir).start()

        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 90, 5).tolist() for _ in range(4)]
        # sequential requests tie on outstanding tokens -> replica 0
        pre = [request_generate(router.url, p, 6) for p in prompts[:2]]
        assert all(o["replica"] == 0 for o in pre)
        for o, p in zip(pre, prompts):
            assert o["tokens"] == solo(p, 6)

        scrape_url = f"http://127.0.0.1:{ms.port}/metrics"

        def scrape():
            return urllib.request.urlopen(
                scrape_url, timeout=5).read().decode()

        while ('tfde_cluster_host_up{host="0"} 1' not in scrape()
               and time.time() < deadline):
            time.sleep(0.1)

        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].wait(timeout=60)

        # queued/new sessions re-route and still decode solo-correct
        out = request_generate(router.url, prompts[2], 6)
        assert out["replica"] == 1 and out["tokens"] == solo(prompts[2], 6)
        rerouted_tid = out["trace"]
        assert rerouted_tid, "router did not return a trace id"
        assert reg.get("router/reroutes").value >= 1
        assert reg.get("router/replicas_lost").value >= 1
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["up"] is False and tab[1]["up"] is True
        # the survivor keeps serving fresh sessions
        out = request_generate(router.url, prompts[3], 6)
        assert out["replica"] == 1 and out["tokens"] == solo(prompts[3], 6)

        # the dead replica can't dump its own ring (SIGKILL) — the
        # router's ring carries the routing-side story
        files = glob.glob(os.path.join(router_dir, "debug",
                                       "flight_*.jsonl"))
        assert files, "router left no flight dump for the lost replica"
        flight = flightrec.load(sorted(files)[-1])
        kinds = [e["kind"] for e in flight]
        assert "replica_down" in kinds
        # the post-mortem cross-reference: the down record names the
        # traces that were in flight on the dead replica
        down = next(e for e in flight if e["kind"] == "replica_down")
        assert rerouted_tid in down.get("traces", [])

        # the re-routed request's stitched waterfall: ONE trace holding
        # the router's both attempts (0, then the reroute to 1) and the
        # survivor's serving events — the dead replica's ring died with
        # it, which is exactly the post-mortem shape
        body = json.loads(urllib.request.urlopen(
            router.url + f"/trace/{rerouted_tid}", timeout=5).read())
        evs = body["events"]
        assert "router" in body["procs"]
        assert "replica1" in body["procs"]
        attempts = [e["replica"] for e in evs
                    if e["name"] == "router/attempt"]
        assert 0 in attempts and 1 in attempts
        names = [e["name"] for e in evs]
        assert "serve/queued" in names        # survivor admitted it
        assert "serve/first_token" in names
        assert "serve/stream_out" in names
        assert "router/done" in names
        # SLO layer rode the same requests: /replicas embeds the summary
        rep_body = json.loads(urllib.request.urlopen(
            router.url + "/replicas", timeout=5).read())
        assert rep_body["slo"]["ttft_requests"] >= 3
        assert rep_body["slo"]["ttft_attainment"] is not None

        # capacity rode the same pushes: /replicas carries the per-
        # replica kv table and the chief rollup folds the fleet's
        # waste/headroom — the survivor's slab is visible end to end
        assert rep_body["kv"]["1"]["allocated_bytes"] > 0
        assert rep_body["kv"]["1"]["headroom_rows"] is not None
        roll = agg.rollup()
        assert "kv_waste_frac" in roll and 0.0 <= roll["kv_waste_frac"] <= 1.0
        assert roll["kv_headroom_rows"] >= 0

        # both replicas journaled per-request usage to their model_dir —
        # replica 0's records survived the SIGKILL because the log
        # flushes at finish, and the warmup requests (pre-arm) are
        # absent, so each file holds exactly its two served requests
        for i in (0, 1):
            uf = os.path.join(str(tmp_path / f"rep{i}"),
                              "metrics", "usage_0.jsonl")
            assert os.path.exists(uf), f"replica {i} left no usage journal"
            with open(uf) as f:
                recs = [json.loads(ln) for ln in f]
            assert len(recs) == 2, (i, recs)
            assert all(r["prompt_tokens"] == 5 for r in recs)
            assert all(r["generated_tokens"] == 6 for r in recs)
            assert all(r["outcome"] == "ok" for r in recs)
            assert all(r["kv_token_seconds"] > 0 for r in recs)

        # host-up flips once the dead replica's pushes go stale
        body = scrape()
        while ('tfde_cluster_host_up{host="0"} 0' not in body
               and time.time() < deadline):
            time.sleep(0.2)
            body = scrape()
        assert 'tfde_cluster_host_up{host="0"} 0' in body
        assert 'tfde_cluster_host_up{host="1"} 1' in body

        # -- the rejoin drill: replica 0 comes back as a NEW process
        # that announces its port while still warming (hold file), so
        # the parent can observe the not-ready boot from outside. The
        # acceptance bars: it serves ZERO requests before `ready`, its
        # boot ledger arrives complete over /load and /replicas, and
        # the phase decomposition sums to the wall from process birth
        # to its first served token within 5%.
        hold = str(tmp_path / "hold2")
        port2 = str(tmp_path / "port2")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env["TFDE_TRACE"] = "on"
        env["TFDE_USAGE_LOG"] = "on"
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + env.get("PYTHONPATH", "").split(os.pathsep)
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), "2", port2, "",
                 str(tmp_path / "rep2"), hold],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
        deadline = time.time() + 240
        while not os.path.exists(port2):
            assert procs[-1].poll() is None, \
                procs[-1].communicate()[1][-3000:]
            assert time.time() < deadline, "rejoiner never announced"
            time.sleep(0.1)
        with open(port2) as f:
            url2 = f"http://127.0.0.1:{int(f.read())}"
        # a fresh router epoch over [survivor, rejoiner]; no aggregator —
        # the old host ids would not line up with the new replica indices
        router2 = Router([urls[1], url2]).start()
        router2._load_ttl = 0.05   # age snapshots fast: tight ready flip
        # while the rejoiner warms, everything lands on the survivor...
        outs = [request_generate(router2.url, prompts[0], 6)
                for _ in range(3)]
        assert all(o["replica"] == 0 for o in outs)
        boot_blk = json.loads(urllib.request.urlopen(
            router2.url + "/replicas", timeout=5).read())["boot"]["1"]
        assert boot_blk["state"] in ("starting", "restoring",
                                     "compiling", "warming")
        assert boot_blk["time_to_ready_s"] is None
        # ...and the gate is hard: with the survivor drained the router
        # 503s rather than placing on the not-ready rejoiner
        urllib.request.urlopen(urllib.request.Request(
            router2.url + "/drain",
            data=json.dumps({"replica": 0}).encode(),
            headers={"Content-Type": "application/json"}), timeout=5)
        with pytest.raises(urllib.error.HTTPError):
            request_generate(router2.url, prompts[0], 6)
        load2 = json.loads(urllib.request.urlopen(
            url2 + "/load", timeout=5).read())
        assert load2["boot"]["ttft_from_birth_ms"] is None  # zero served
        # release the hold: the rejoiner flips ready and takes traffic
        with open(hold, "w"):
            pass
        out2 = None
        while out2 is None and time.time() < deadline:
            try:
                out2 = request_generate(router2.url, prompts[0], 6)
            except urllib.error.HTTPError:
                time.sleep(0.05)
        assert out2 is not None, "rejoiner never became placeable"
        assert out2["replica"] == 1
        assert out2["tokens"] == solo(prompts[0], 6)
        # the complete cold-start ledger, phase by phase
        snap = json.loads(urllib.request.urlopen(
            url2 + "/load", timeout=5).read())["boot"]
        assert snap["state"] == "ready"
        for ph in ("init", "restore", "compile", "warmup"):
            assert snap["phases"].get(ph, 0.0) > 0.0, (ph, snap)
        assert snap["restore"]["bytes"] > 0
        assert snap["restore"]["bandwidth_bps"] > 0
        assert snap["time_to_ready_s"] > 0
        # the acceptance identity, cross-process: phases tile the wall
        # from process birth to the first served token within 5% (the
        # only untiled slack is the post-ready placement latency)
        ttft_s = snap["ttft_from_birth_ms"] / 1e3
        assert abs(sum(snap["phases"].values()) - ttft_s) \
            <= 0.05 * ttft_s, snap
    finally:
        if not trace_was_on:
            reqtrace.disable()
        if router2 is not None:
            router2.close()
        if router is not None:
            router.close()
        ms.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()


_ELASTIC_CHILD = textwrap.dedent(
    """
    import hashlib, json, os, signal, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.observability import counters, flightrec, metrics
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.resilience import (
        ElasticConfig, PeerLossFault, RetryPolicy, Supervisor,
        SupervisorConfig,
    )
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    mode, model_dir, hb_path, opt_sharding = sys.argv[1:5]
    # kill two full steps after the step-5 save: the async commit barrier
    # needs both processes alive to finalize, and steps 6-7's collectives
    # guarantee they were
    MAX_STEPS, SAVE_EVERY, KILL_AT = 12, 5, 10
    rng = np.random.default_rng(0)  # same arrays on every host
    X = rng.random((16, 784), np.float32)
    Y = rng.integers(0, 10, (16, 1)).astype(np.int32)

    info = bootstrap()

    if info.num_processes == 2 and info.process_id == 1:
        # a TRUE liveness heartbeat, decoupled from step timing: beating
        # from the training loop itself would conflate "slow step" (ZeRO
        # compile, loaded machine) with "dead peer" and let rank 0 accuse
        # a live rank 1 — SIGKILL stops this thread with the process
        import threading

        def _beat():
            while True:
                with open(hb_path + ".tmp", "w") as f:
                    f.write("alive")
                os.replace(hb_path + ".tmp", hb_path)
                time.sleep(0.25)

        threading.Thread(target=_beat, daemon=True).start()

    def input_fn():
        # every host yields the full GLOBAL batch; OFF policy slices the
        # current process's portion — so the global batch (and with it
        # the loss trajectory) is preserved across a world change with
        # no caller-side re-tuning
        world, rank = jax.process_count(), jax.process_index()
        def gen():
            n = 0
            while True:
                n += 1
                if world == 2 and rank == 1 and n == KILL_AT:
                    os.kill(os.getpid(), signal.SIGKILL)  # no teardown
                if world == 2 and rank == 0 and n == KILL_AT:
                    # production detection channel, deterministic in-suite:
                    # the peer's heartbeat file goes stale (the analog of
                    # health.note_stale_host's metric-push staleness) --
                    # accuse BEFORE entering the step's collective
                    deadline = time.time() + 120
                    while time.time() < deadline:
                        if time.time() - os.path.getmtime(hb_path) > 2.0:
                            PeerLossFault(
                                rank=1, reason="heartbeat stale",
                            ).fire("input_fn")
                        time.sleep(0.1)
                    raise RuntimeError("peer heartbeat never went stale")
                yield (X, Y)
        return gen()

    def factory():
        return Estimator(
            model=PlainCNN(),
            optimizer=optax.sgd(0.1),
            strategy=MultiWorkerMirroredStrategy(opt_sharding=opt_sharding),
            config=RunConfig(
                model_dir=model_dir,
                save_checkpoints_steps=SAVE_EVERY,
                save_summary_steps=10_000,
                log_step_count_steps=10_000,
            ),
        )

    if mode == "elastic":
        sup = Supervisor(factory, SupervisorConfig(
            max_restarts=3,
            restart_policy=RetryPolicy(initial_backoff=0.01, jitter=0.0),
            elastic=ElasticConfig(),
        ))
        state = sup.run(input_fn, MAX_STEPS,
                        shard_policy=AutoShardPolicy.OFF)
        restarts = sup.restarts
        dump = flightrec.dump("elastic_drill")
    else:  # oracle: plain single-process resume from the copied checkpoint
        est = factory()
        state = est.train(input_fn, MAX_STEPS,
                          shard_policy=AutoShardPolicy.OFF)
        est.close()
        restarts, dump = 0, None

    leaves = jax.tree_util.tree_flatten_with_path(
        jax.device_get(state.params))[0]
    h = hashlib.sha256()
    for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
        h.update(np.ascontiguousarray(leaf).tobytes())
    print(json.dumps({
        "process_id": info.process_id,
        "step": int(jax.device_get(state.step)),
        "restarts": restarts,
        "world": jax.process_count(),
        "topology_changes": counters.value("resilience/topology_changes"),
        "world_gauge": metrics.gauge("cluster/world_size").value,
        "params_sha": h.hexdigest(),
        "flight_dump": dump,
    }))
    """
)


@pytest.mark.parametrize("opt_sharding", ["replicated", "shard"])
def test_sigkill_peer_elastic_resume(tmp_path, opt_sharding):
    """ISSUE 13 acceptance drill: two REAL processes train sync-DP over
    loopback; rank 1 SIGKILLs itself mid-training (after the step-5
    checkpoint committed). The survivor classifies the loss as TOPOLOGY,
    shrinks the cluster env around the dead rank, re-bootstraps at world
    1, and resumes from the checkpoint to max_steps — with final params
    IDENTICAL to a single-process oracle resumed from the same
    checkpoint (loss-trajectory continuity: OFF-policy hosts feed slices
    of one constant global batch, so the post-resume segment is bit-
    comparable). The 'shard' cell saves 2-way ZeRO-packed optimizer
    state and must restore it at world 1 through the cross-world
    bridge."""
    import glob
    import shutil
    import signal
    import time

    from tfde_tpu.observability import flightrec

    script = tmp_path / "child_elastic.py"
    script.write_text(_ELASTIC_CHILD)
    model_dir = str(tmp_path / "run")
    hb_path = str(tmp_path / "hb1")
    # rank 1's heartbeat exists before rank 0 can stat it
    with open(hb_path, "w") as f:
        f.write("0")

    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}
    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        # stderr to files, not pipes: a hung child's log survives the
        # timeout kill and is the only record of where it stuck
        errf = open(tmp_path / f"rank{i}.stderr", "w")
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script),
                 "elastic", model_dir, hb_path, opt_sharding],
                env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
            )
        )

    def child_err(i):
        return (tmp_path / f"rank{i}.stderr").read_text()[-5000:]

    try:
        # rank 1 dies BY SIGKILL — unannounced, no flight dump, no teardown
        out1, _ = procs[1].communicate(timeout=300)
        assert procs[1].returncode == -signal.SIGKILL, (
            procs[1].returncode, child_err(1))
        # the survivor finishes the run at world 1
        out0, _ = procs[0].communicate(timeout=300)
        assert procs[0].returncode == 0, f"survivor failed:\n{child_err(0)}"
        res = json.loads(out0.strip().splitlines()[-1])
        assert res["step"] == 12
        assert res["restarts"] == 1
        assert res["world"] == 1
        assert res["world_gauge"] == 1
        assert res["topology_changes"] == 1

        # the flight ring tells the whole story
        assert res["flight_dump"] and os.path.exists(res["flight_dump"])
        kinds = [e["kind"] for e in flightrec.load(res["flight_dump"])]
        for kind in ("peer_lost", "env_shrunk", "topology_change",
                     "batch_retune"):
            assert kind in kinds, (kind, kinds)

        # loss-trajectory continuity: a single-process oracle resuming the
        # SAME step-5 checkpoint must land on identical params (prune the
        # later checkpoints the survivor wrote after its re-bootstrap)
        oracle_dir = str(tmp_path / "oracle")
        shutil.copytree(model_dir, oracle_dir)
        ckdir = os.path.join(oracle_dir, "checkpoints")
        steps = sorted(int(d) for d in os.listdir(ckdir) if d.isdigit())
        assert 5 in steps, f"step-5 checkpoint not retained: {steps}"
        for d in steps:
            if d > 5:
                shutil.rmtree(os.path.join(ckdir, str(d)))
        env = dict(os.environ)
        for k in ("TF_CONFIG", "CLUSTER_SPEC", "TASK_INDEX", "JOB_NAME",
                  "TFDE_NUM_PROCESSES", "TFDE_PROCESS_ID",
                  "TFDE_COORDINATOR"):
            env.pop(k, None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + env.get("PYTHONPATH", "").split(os.pathsep)
        )
        oracle = subprocess.run(
            [sys.executable, str(script),
             "oracle", oracle_dir, hb_path, opt_sharding],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert oracle.returncode == 0, f"oracle failed:\n{oracle.stderr[-3000:]}"
        ores = json.loads(oracle.stdout.strip().splitlines()[-1])
        assert ores["step"] == 12
        assert ores["params_sha"] == res["params_sha"], (
            "survivor's post-shrink trajectory diverged from the oracle")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()


def test_killed_worker_leaves_flight_file_and_goes_stale(tmp_path):
    """The PR's cluster acceptance: chief /metrics carries the worker's
    host-labelled series; SIGTERM-killing the worker (a) leaves a parseable
    flight_*.jsonl under model_dir/debug and the process dies BY SIGNAL,
    and (b) flips the chief's staleness gauges within ~one push interval."""
    import glob
    import signal
    import time
    import urllib.error
    import urllib.request

    from tfde_tpu.observability import flightrec

    script = tmp_path / "child_obs.py"
    script.write_text(_OBS_CHILD)
    model_dir = str(tmp_path / "run")
    port_file = str(tmp_path / "chief_port")
    stop_file = str(tmp_path / "chief_stop")

    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}
    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script),
                 model_dir, port_file, stop_file],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    chief, worker = procs
    try:
        deadline = time.time() + 180
        while not os.path.exists(port_file) and time.time() < deadline:
            assert chief.poll() is None, chief.communicate()[1][-3000:]
            time.sleep(0.05)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read())}/metrics"

        def scrape():
            return urllib.request.urlopen(url, timeout=5).read().decode()

        body = ""
        while time.time() < deadline:
            body = scrape()
            if 'tfde_train_steps_per_sec{host="1"} 21.0' in body:
                break
            time.sleep(0.1)
        # the worker's pushed snapshot shows up host-labelled, and live
        assert 'tfde_train_steps_per_sec{host="1"} 21.0' in body
        assert 'tfde_cluster_host_up{host="1"} 1' in body

        worker.send_signal(signal.SIGTERM)
        worker.wait(timeout=60)
        # the flight hook dumped, then chained to SIG_DFL: death BY SIGNAL
        assert worker.returncode == -signal.SIGTERM, worker.returncode
        files = glob.glob(os.path.join(model_dir, "debug",
                                       "flight_*.jsonl"))
        assert files, "killed worker left no flight file"
        kinds = [e["kind"] for e in flightrec.load(files[0])]
        assert "worker_alive" in kinds and "sigterm" in kinds
        assert kinds[-1] == "dump"

        while time.time() < deadline:
            body = scrape()
            if 'tfde_cluster_host_up{host="1"} 0' in body:
                break
            time.sleep(0.2)
        assert 'tfde_cluster_host_up{host="1"} 0' in body
        assert "tfde_cluster_hosts_stale 1" in body

        with open(stop_file, "w") as f:
            f.write("x")
        out, err = chief.communicate(timeout=60)
        assert chief.returncode == 0, err[-3000:]
        res = json.loads(out.strip().splitlines()[-1])
        assert res["hosts_stale"] == 1 and res["stale_hosts"] == [1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()


def test_open_loop_poisson_overload_drill(tmp_path):
    """The PR-14 acceptance drill: two REAL capped replica processes
    (TFDE_ADMIT_MAX_QUEUE from env) behind the Router, driven with an
    open-loop Poisson arrival stream at ~2x measured capacity. Every
    request must end in exactly one of three orderly ways — completed
    with tokens greedy-bit-identical to solo generate(), rejected with a
    well-formed 429 + Retry-After, or deadline-shed in-band — with zero
    in-flight drops, at least one well-formed rejection, and admitted
    p99 TTFT holding near the unloaded baseline."""
    import signal
    import threading
    import time
    import urllib.error
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.decode import generate
    from tfde_tpu.inference.router import Router, request_generate
    from tfde_tpu.models.gpt import gpt_tiny_test
    from tfde_tpu.observability import metrics

    model = gpt_tiny_test()
    params = model.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]

    def solo(prompt, n):
        toks, lengths = generate(
            model, params,
            jnp.asarray(np.asarray(prompt)[None, :], jnp.int32),
            max_new_tokens=n,
        )
        return np.asarray(toks)[0, len(prompt) : int(lengths[0])].tolist()

    script = tmp_path / "child_replica.py"
    script.write_text(_REPLICA_CHILD)
    port_files = [str(tmp_path / f"port{i}") for i in range(2)]
    reg = metrics.default_registry()
    reg.reset("router/")

    procs, router = [], None
    try:
        for i in range(2):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)
            env["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            )
            # the overload levers: tight queue cap per replica so ~2x
            # load MUST overflow into 429s instead of unbounded queueing
            env["TFDE_ADMIT_MAX_QUEUE"] = "2"
            env.pop("TFDE_ADMIT_MAX_QUEUED_TOKENS", None)
            env.pop("TFDE_ADMIT_TTFT_DEADLINE_MS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), str(i), port_files[i],
                     ""],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True,
                )
            )
        deadline = time.time() + 240
        while not all(os.path.exists(p) for p in port_files):
            for p in procs:
                assert p.poll() is None, p.communicate()[1][-3000:]
            assert time.time() < deadline, "children never announced ports"
            time.sleep(0.1)
        urls = []
        for pf in port_files:
            with open(pf) as f:
                urls.append(f"http://127.0.0.1:{int(f.read())}")
        router = Router(urls).start()

        rng = np.random.default_rng(14)
        budget = 6
        prompts = [rng.integers(1, 90, int(ln)).tolist()
                   for ln in rng.integers(4, 7, 28)]
        want = [solo(p, budget) for p in prompts]

        # -- phase 1: unloaded baseline ---------------------------------
        base_ttfts = []
        t0 = time.perf_counter()
        for p, w in zip(prompts[:6], want[:6]):
            out = request_generate(router.url, p, budget)
            assert out["tokens"] == w
            base_ttfts.append(out["ttft_s"])
        base_elapsed = time.perf_counter() - t0
        base_p99 = float(np.percentile(base_ttfts, 99))
        svc_rate = 6.0 / base_elapsed      # req/s at concurrency 1

        # -- phase 2: open-loop Poisson at ~2x capacity -----------------
        # capacity ~= concurrency-1 throughput x (2 replicas x batch 2);
        # offer twice that so the capped queues must overflow
        offered = 2.0 * svc_rate * 4.0
        arrivals = np.cumsum(rng.exponential(1.0 / offered,
                                             len(prompts) - 6))
        results = [None] * len(arrivals)
        classes = ["interactive", "batch", "best_effort"]
        # admitted interactive work gets a TTFT deadline generous enough
        # that only genuinely stuck requests shed
        dl_ms = max(2000.0, base_p99 * 1e3 * 20.0)

        def fire(k, prompt, at):
            time.sleep(max(0.0, at - (time.perf_counter() - t_load)))
            try:
                out = request_generate(
                    router.url, prompt, budget, timeout=120,
                    priority=classes[k % 3], ttft_deadline_ms=dl_ms)
                results[k] = ("ok", out)
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                results[k] = ("http", e.code,
                              e.headers.get("Retry-After"), body)
            except RuntimeError as e:
                results[k] = ("runtime", str(e))
            except Exception as e:   # anything else is a dropped request
                results[k] = ("drop", repr(e))

        t_load = time.perf_counter()
        threads = [
            threading.Thread(target=fire, args=(k, prompts[6 + k], at),
                             daemon=True)
            for k, at in enumerate(arrivals)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive(), "drill request never finished"

        completed, rejected, shed = [], [], []
        for k, res in enumerate(results):
            assert res is not None, f"request {k} vanished"
            kind = res[0]
            if kind == "ok":
                out = res[1]
                # greedy bit-identity survives overload for every
                # admitted request
                assert out["tokens"] == want[6 + k], f"request {k}"
                completed.append(out)
            elif kind == "http":
                _, code, retry_after, body = res
                assert code == 429, res
                assert retry_after is not None and int(retry_after) >= 1
                parsed = json.loads(body)
                assert parsed.get("retriable", True) in (True,)
                assert float(parsed["retry_after_s"]) > 0
                rejected.append(parsed)
            elif kind == "runtime":
                assert "deadline_shed" in res[1], res
                shed.append(res)
            else:
                raise AssertionError(f"in-flight drop: {res}")

        # the drill only proves something if the cluster actually both
        # served and shed under the 2x offered load
        assert completed, results
        assert rejected, "2x overload produced no 429s"
        # admitted latency holds: p99 TTFT within 1.5x the unloaded
        # baseline plus absolute slack for CI scheduling noise
        adm_p99 = float(np.percentile(
            [o["ttft_s"] for o in completed], 99))
        assert adm_p99 <= 1.5 * base_p99 + 0.75, (adm_p99, base_p99)

        # recovery: once the wave passes, the cluster admits again and
        # still decodes solo-correct
        time.sleep(0.5)
        out = request_generate(router.url, prompts[0], budget)
        assert out["tokens"] == want[0]
    finally:
        if router is not None:
            router.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
