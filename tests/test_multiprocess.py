"""Multi-process distributed test (SURVEY.md §4: "spawn N local processes
with jax.distributed.initialize — the TF_CONFIG analog"): two real OS
processes bootstrap from the reference's CLUSTER_SPEC env contract, form one
SPMD group over loopback, train sync-DP, and must agree bit-for-bit on the
final replicated params."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent(
    """
    import hashlib, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data import device_prefetch
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    info = bootstrap()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2

    strategy = MultiWorkerMirroredStrategy()
    rng = np.random.default_rng(0)  # same stream on both hosts (policy OFF)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(
        BatchNormCNN(), optax.sgd(0.1), strategy,
        np.zeros((16, 784), np.float32),
    )
    step = make_train_step(strategy, state, donate=False)
    feed = device_prefetch(
        iter([(images, labels)] * 4), strategy.mesh,
        policy=AutoShardPolicy.OFF,
    )
    losses = []
    for batch in feed:
        state, m = step(state, batch, jax.random.key(0))
        losses.append(float(jax.device_get(m["loss"])))
    leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    digest = hashlib.sha256(
        b"".join(np.ascontiguousarray(l).tobytes() for l in leaves)
    ).hexdigest()
    print(json.dumps({
        "process_id": info.process_id,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "params_sha": digest,
    }))
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sync_dp_agrees(tmp_path):
    # runaway children are bounded by communicate(timeout=240) below
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}

    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )

    results = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    assert {r["process_id"] for r in results} == {0, 1}
    # sync DP: replicated params identical across processes, loss decreased
    assert results[0]["params_sha"] == results[1]["params_sha"]
    assert results[0]["last_loss"] < results[0]["first_loss"]
    assert results[0]["last_loss"] == pytest.approx(results[1]["last_loss"])


_LIFECYCLE_CHILD = textwrap.dedent(
    """
    import json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data import Dataset
    from tfde_tpu.data.device import local_slice_for_process
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.export.serving import FinalExporter
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    phase, model_dir = sys.argv[1], sys.argv[2]
    info = bootstrap()
    assert jax.process_count() == 2, jax.process_count()

    rng = np.random.default_rng(0)  # same stream on both hosts (policy OFF)
    X = rng.random((64, 784), np.float32)
    Y = rng.integers(0, 10, (64, 1)).astype(np.int32)
    train_fn = lambda: (
        Dataset.from_tensor_slices((X, Y))
        .shuffle(64, seed=0).repeat().batch(16, drop_remainder=True)
    )
    eval_fn = lambda: Dataset.from_tensor_slices((X[:32], Y[:32])).batch(16)

    cfg = RunConfig(model_dir=model_dir, save_checkpoints_steps=5,
                    save_summary_steps=5)
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)

    if phase == "first":
        state = est.train(train_fn, max_steps=10,
                          shard_policy=AutoShardPolicy.OFF)
    else:
        # 'restarted cluster': same model_dir, fresh processes. max_steps is
        # absolute, so the completed 10 steps must be a no-op...
        state = est.train(train_fn, max_steps=10,
                          shard_policy=AutoShardPolicy.OFF)
        assert int(jax.device_get(state.step)) == 10, "resume failed"
        # ...and training continues from the checkpoint to 16
        state = est.train(train_fn, max_steps=16,
                          shard_policy=AutoShardPolicy.OFF)

    metrics = est.evaluate(eval_fn)
    export_path = None
    if phase == "resume":
        export_path = est.export_saved_model(
            FinalExporter("exporter", (None, 784))
        )
    est.close()

    per, sl = local_slice_for_process(16)
    print(json.dumps({
        "process_id": info.process_id,
        "step": int(jax.device_get(state.step)),
        "loss": metrics["loss"],
        "accuracy": metrics["accuracy"],
        "chief_gating_ok": (est._writer() is not None) == (info.process_id == 0),
        "slice": [sl.start, sl.stop],
        "per_host": per,
        "export": export_path,
    }))
    """
)


def _run_group(script_path, argv, n=2, timeout=300):
    ports = [_free_port() for _ in range(n)]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script_path)] + argv,
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))
    return results


def test_two_process_estimator_lifecycle_and_resume(tmp_path):
    """VERDICT r2 #7: the full Estimator lifecycle across 2 real processes —
    train with chief-only summaries, collective checkpointing, eval, restart
    the whole group and resume from the checkpoint, final export; OFF-policy
    host slices reconstruct the global batch."""
    script = tmp_path / "child_lifecycle.py"
    script.write_text(_LIFECYCLE_CHILD)
    model_dir = str(tmp_path / "run")

    first = _run_group(script, ["first", model_dir])
    assert {r["process_id"] for r in first} == {0, 1}
    assert all(r["step"] == 10 for r in first)
    assert all(r["chief_gating_ok"] for r in first)
    # sync SPMD: both processes computed identical eval metrics
    assert first[0]["loss"] == pytest.approx(first[1]["loss"])
    assert first[0]["accuracy"] == first[1]["accuracy"]
    # OFF-policy slices tile the global batch exactly (data/device.py)
    slices = sorted(tuple(r["slice"]) for r in first)
    assert slices == [(0, 8), (8, 16)]
    assert all(r["per_host"] == 8 for r in first)
    # checkpoints landed in the shared model_dir
    ckpts = os.listdir(os.path.join(model_dir, "checkpoints"))
    assert any(d.isdigit() for d in ckpts)

    # "kill" the cluster (phase-1 processes have exited) and restart
    resumed = _run_group(script, ["resume", model_dir])
    assert all(r["step"] == 16 for r in resumed)
    assert resumed[0]["loss"] == pytest.approx(resumed[1]["loss"])
    # chief exported; non-chief didn't
    exports = {r["process_id"]: r["export"] for r in resumed}
    assert exports[0] is not None and os.path.exists(exports[0])
    assert exports[1] is None


_FSDP_CHILD = textwrap.dedent(
    """
    import hashlib, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import FSDPStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    info = bootstrap()
    assert jax.process_count() == 2
    strategy = FSDPStrategy(min_shard_elems=1)  # fsdp axis spans both hosts

    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(PlainCNN(), optax.adam(1e-3), strategy,
                          np.zeros((16, 784), np.float32))
    # params are actually sharded across the two processes
    kernel = state.params["Dense_0"]["kernel"]
    assert kernel.sharding.spec[0] == "fsdp", kernel.sharding.spec
    assert not kernel.is_fully_addressable  # cross-host array

    step = make_train_step(strategy, state, donate=False)
    import jax.numpy as jnp
    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.data.pipeline import AutoShardPolicy
    feed = device_prefetch([(images, labels)] * 3, strategy.mesh,
                           policy=AutoShardPolicy.OFF)
    for batch in feed:
        state, m = step(state, batch, jax.random.key(0))
    # gather the sharded params to host (allowed: fetch per-shard, hash the
    # process-local bytes of the replicated loss + local shards)
    loss = float(jax.device_get(m["loss"]))
    local = [np.ascontiguousarray(s.data) for s in kernel.addressable_shards]
    digest = hashlib.sha256(b"".join(x.tobytes() for x in local)).hexdigest()
    print(json.dumps({"process_id": info.process_id, "loss": loss,
                      "shard_sha": digest}))
    """
)


def test_two_process_fsdp_shards_and_agrees(tmp_path):
    """ZeRO/FSDP across two real processes (the DCN-analog layout): params
    shard over the cross-host 'fsdp' axis (not fully addressable anywhere),
    training runs, and both processes agree on the replicated loss."""
    script = tmp_path / "child_fsdp.py"
    script.write_text(_FSDP_CHILD)
    results = _run_group(script, [])
    assert {r["process_id"] for r in results} == {0, 1}
    assert results[0]["loss"] == pytest.approx(results[1]["loss"])
    # each host holds a different shard of the same kernel
    assert results[0]["shard_sha"] != results[1]["shard_sha"]


_OBS_CHILD = textwrap.dedent(
    """
    import json, os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tfde_tpu.utils.devices import request_cpu_devices
    request_cpu_devices(1)
    from tfde_tpu import bootstrap
    from tfde_tpu.observability import aggregate, flightrec, metrics
    from tfde_tpu.observability.exposition import MetricsServer

    model_dir, port_file, stop_file = sys.argv[1:4]
    info = bootstrap()
    assert jax.process_count() == 2

    if info.process_id == 0:
        # chief: /metrics + aggregator; stays up after the worker is killed
        reg = metrics.Registry()
        agg = aggregate.ClusterAggregator(registry=reg, include_local=0,
                                          stale_after=1.5)
        srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                            aggregator=agg)
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, port_file)
        deadline = time.time() + 180
        while not os.path.exists(stop_file) and time.time() < deadline:
            time.sleep(0.05)
        out = agg.rollup()
        print(json.dumps({"process_id": 0,
                          "hosts_stale": out["hosts_stale"],
                          "stale_hosts": out["stale_hosts"]}))
        sys.stdout.flush()
        os._exit(0)  # peer was SIGKILLed: skip jax.distributed teardown
    else:
        # worker: flight recorder armed + metrics pusher, then wait to die
        flightrec.arm(model_dir)
        flightrec.record("worker_alive", pid=os.getpid())
        wreg = metrics.Registry()
        wreg.gauge("train/steps_per_sec").set(21.0)
        wreg.histogram("train/step").observe(0.1)
        deadline = time.time() + 180
        while not os.path.exists(port_file) and time.time() < deadline:
            time.sleep(0.05)
        with open(port_file) as f:
            port = int(f.read())
        pusher = aggregate.MetricsPusher(
            f"http://127.0.0.1:{port}/push", interval=0.25,
            registry=wreg, host=info.process_id)
        time.sleep(300)  # the parent SIGTERMs us here
    """
)


def test_killed_worker_leaves_flight_file_and_goes_stale(tmp_path):
    """The PR's cluster acceptance: chief /metrics carries the worker's
    host-labelled series; SIGTERM-killing the worker (a) leaves a parseable
    flight_*.jsonl under model_dir/debug and the process dies BY SIGNAL,
    and (b) flips the chief's staleness gauges within ~one push interval."""
    import glob
    import signal
    import time
    import urllib.request

    from tfde_tpu.observability import flightrec

    script = tmp_path / "child_obs.py"
    script.write_text(_OBS_CHILD)
    model_dir = str(tmp_path / "run")
    port_file = str(tmp_path / "chief_port")
    stop_file = str(tmp_path / "chief_stop")

    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}
    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script),
                 model_dir, port_file, stop_file],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    chief, worker = procs
    try:
        deadline = time.time() + 180
        while not os.path.exists(port_file) and time.time() < deadline:
            assert chief.poll() is None, chief.communicate()[1][-3000:]
            time.sleep(0.05)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read())}/metrics"

        def scrape():
            return urllib.request.urlopen(url, timeout=5).read().decode()

        body = ""
        while time.time() < deadline:
            body = scrape()
            if 'tfde_train_steps_per_sec{host="1"} 21.0' in body:
                break
            time.sleep(0.1)
        # the worker's pushed snapshot shows up host-labelled, and live
        assert 'tfde_train_steps_per_sec{host="1"} 21.0' in body
        assert 'tfde_cluster_host_up{host="1"} 1' in body

        worker.send_signal(signal.SIGTERM)
        worker.wait(timeout=60)
        # the flight hook dumped, then chained to SIG_DFL: death BY SIGNAL
        assert worker.returncode == -signal.SIGTERM, worker.returncode
        files = glob.glob(os.path.join(model_dir, "debug",
                                       "flight_*.jsonl"))
        assert files, "killed worker left no flight file"
        kinds = [e["kind"] for e in flightrec.load(files[0])]
        assert "worker_alive" in kinds and "sigterm" in kinds
        assert kinds[-1] == "dump"

        while time.time() < deadline:
            body = scrape()
            if 'tfde_cluster_host_up{host="1"} 0' in body:
                break
            time.sleep(0.2)
        assert 'tfde_cluster_host_up{host="1"} 0' in body
        assert "tfde_cluster_hosts_stale 1" in body

        with open(stop_file, "w") as f:
            f.write("x")
        out, err = chief.communicate(timeout=60)
        assert chief.returncode == 0, err[-3000:]
        res = json.loads(out.strip().splitlines()[-1])
        assert res["hosts_stale"] == 1 and res["stale_hosts"] == [1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
