"""Multi-process distributed test (SURVEY.md §4: "spawn N local processes
with jax.distributed.initialize — the TF_CONFIG analog"): two real OS
processes bootstrap from the reference's CLUSTER_SPEC env contract, form one
SPMD group over loopback, train sync-DP, and must agree bit-for-bit on the
final replicated params."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent(
    """
    import hashlib, json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)
    import numpy as np, optax
    from tfde_tpu import bootstrap
    from tfde_tpu.data import device_prefetch
    from tfde_tpu.data.pipeline import AutoShardPolicy
    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    info = bootstrap()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2

    strategy = MultiWorkerMirroredStrategy()
    rng = np.random.default_rng(0)  # same stream on both hosts (policy OFF)
    images = rng.random((16, 784), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    state, _ = init_state(
        BatchNormCNN(), optax.sgd(0.1), strategy,
        np.zeros((16, 784), np.float32),
    )
    step = make_train_step(strategy, state, donate=False)
    feed = device_prefetch(
        iter([(images, labels)] * 4), strategy.mesh,
        policy=AutoShardPolicy.OFF,
    )
    losses = []
    for batch in feed:
        state, m = step(state, batch, jax.random.key(0))
        losses.append(float(jax.device_get(m["loss"])))
    leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    digest = hashlib.sha256(
        b"".join(np.ascontiguousarray(l).tobytes() for l in leaves)
    ).hexdigest()
    print(json.dumps({
        "process_id": info.process_id,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "params_sha": digest,
    }))
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sync_dp_agrees(tmp_path):
    # runaway children are bounded by communicate(timeout=240) below
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    ports = [_free_port(), _free_port()]
    cluster = {"worker": [f"127.0.0.1:{p}" for p in ports]}

    procs = []
    for i in range(2):
        env = dict(os.environ)
        env.update(
            CLUSTER_SPEC=json.dumps(cluster),
            TASK_INDEX=str(i),
            JOB_NAME="worker",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep)
            ),
        )
        env.pop("TF_CONFIG", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )

    results = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    assert {r["process_id"] for r in results} == {0, 1}
    # sync DP: replicated params identical across processes, loss decreased
    assert results[0]["params_sha"] == results[1]["params_sha"]
    assert results[0]["last_loss"] < results[0]["first_loss"]
    assert results[0]["last_loss"] == pytest.approx(results[1]["last_loss"])
