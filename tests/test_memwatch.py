"""Measured memory ledger (observability/memwatch.py): program
registration publishes honest per-program byte gauges, the donated-alias
estimate keeps the peak below naive arg+out, `device_bytes` agrees with
the ZeRO layer's analytic accounting on the 8-way CPU mesh for both
replicated and sharded optimizer states, the live-array sampler rides
the registry snapshot cadence, and the mem/* gauges round-trip through
the Prometheus text exposition."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import exposition, memwatch, metrics, recompile
from tfde_tpu.parallel import zero
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import init_state


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # the ledger must see its default 'on' mode, not tier1.sh's override,
    # and every test starts from an empty program table / compile ledger
    monkeypatch.delenv(memwatch.ENV_MEMWATCH, raising=False)
    memwatch.reset()
    recompile.reset()
    yield
    memwatch.reset()
    recompile.reset()


def test_resolve_modes():
    assert memwatch.resolve("on") == "on"
    assert memwatch.resolve("") == "on"
    assert memwatch.resolve("1") == "on"
    assert memwatch.resolve("off") == "off"
    assert memwatch.resolve("0") == "off"
    assert memwatch.resolve("full") == "full"
    assert memwatch.resolve("measured") == "full"
    assert memwatch.resolve("garbage") == "on"  # warn + default


def test_register_publishes_gauges():
    @jax.jit
    def f(x):
        return x @ x.T

    x = jnp.ones((16, 32), jnp.float32)
    pm = memwatch.register("t/matmul", f, args=(x,))
    assert pm is not None
    assert pm.argument_bytes == x.nbytes
    assert pm.output_bytes == 16 * 16 * 4
    assert pm.peak_bytes >= max(pm.argument_bytes, pm.output_bytes)
    reg = metrics.default_registry()
    flat = metrics.flatten_snapshot(reg.snapshot())
    assert flat["mem/t/matmul/peak_bytes"] == pm.peak_bytes
    assert flat["mem/t/matmul/argument_bytes"] == x.nbytes
    assert "mem/t/matmul/measured" in flat
    assert memwatch.programs()["t/matmul"].name == "t/matmul"


def test_donated_args_reduce_peak_estimate():
    @jax.jit
    def f(x):
        return x + 1.0

    x = jnp.ones((64, 64), jnp.float32)
    no_alias = memwatch.register("t/plain", f, args=(x,))
    aliased = memwatch.register("t/donated", f, args=(x,), donated=x)
    assert aliased.alias_bytes == x.nbytes
    # arg+out-alias collapses to one buffer's worth; plain pays for two
    assert aliased.peak_bytes < no_alias.peak_bytes
    assert aliased.peak_bytes == max(aliased.argument_bytes,
                                     aliased.output_bytes)


def test_register_off_mode_is_noop():
    pm = memwatch.register("t/off", lambda x: x, args=(jnp.ones(4),),
                           mode="off")
    assert pm is None
    assert "t/off" not in memwatch.programs()


def test_register_never_raises_on_bad_program():
    # eval_shape on a fn that throws: the ledger logs once and moves on
    def bad(x):
        raise ValueError("boom")

    assert memwatch.register("t/bad", bad, args=(jnp.ones(4),)) is None
    assert "t/bad" not in memwatch.programs()


def test_full_mode_compile_is_suppressed_from_sentinel():
    recompile.install()

    @jax.jit
    def f(x):
        return jnp.sin(x) * 2.0

    # build the argument first: jnp.ones is itself a (legitimate) process
    # compile and must not be confused with the ledger's AOT compile
    x = jax.block_until_ready(jnp.ones((8, 8)))
    before = recompile.process_compiles()
    pm = memwatch.register("t/full", f, args=(x,), mode="full")
    assert pm is not None
    assert pm.peak_bytes > 0
    # the AOT lower+compile for the ledger must not read as a process
    # compile (it runs under recompile.suppress())
    assert recompile.process_compiles() == before


def _dp_mesh(n=8):
    return make_mesh({"data": -1}, jax.devices()[:n])


def _opt_state(opt_sharding):
    strategy = MirroredStrategy(mesh=_dp_mesh(), grad_transport="fp32",
                                opt_sharding=opt_sharding)
    rng = np.random.default_rng(0)
    images = rng.random((16, 784), np.float32)
    state, _ = init_state(PlainCNN(), optax.adam(1e-2), strategy, images)
    return state


def test_device_bytes_vs_analytic_zero_accounting(monkeypatch):
    monkeypatch.delenv(zero.ENV_OPT_SHARDING, raising=False)
    rep = _opt_state("replicated")
    shd = _opt_state("shard")
    for state in (rep, shd):
        analytic = zero.state_bytes(state.opt_state, state.opt_layout)
        measured = memwatch.device_bytes(state.opt_state)
        assert measured == pytest.approx(analytic, rel=0.2)
        assert zero.measured_state_bytes(state.opt_state) == measured
    # the point of ZeRO: per-device measured bytes drop ~8x on the 8-way
    # mesh (padding keeps it from being exactly 1/8)
    ratio = (memwatch.device_bytes(shd.opt_state)
             / memwatch.device_bytes(rep.opt_state))
    assert ratio == pytest.approx(1 / 8, rel=0.2)


def test_live_sampler_sees_device_buffers():
    marker = jnp.ones((128, 128), jnp.float32)  # 64 KiB, easy to spot
    sample = memwatch.sample_live(top_k=4)
    assert sample["bytes"] >= marker.nbytes
    assert sample["buffers"] >= 1
    assert len(sample["top"]) <= 4
    sizes = [row["bytes"] for row in sample["top"]]
    assert sizes == sorted(sizes, reverse=True)
    assert any(row["shape"] == [128, 128] for row in sample["top"])
    del marker


def test_collector_rides_snapshot_cadence():
    reg = metrics.Registry()
    ledger = memwatch.MemoryLedger(registry=reg)
    assert "mem/live/bytes" not in reg.snapshot()
    ledger.install_collector()
    ledger.install_collector()  # idempotent
    marker = jnp.ones((64, 64), jnp.float32)  # keep one buffer live
    flat = metrics.flatten_snapshot(reg.snapshot())
    del marker
    assert flat["mem/live/bytes"] > 0
    assert flat["mem/live/buffers"] >= 1
    assert flat["mem/live/largest_bytes"] <= flat["mem/live/bytes"]


def test_mem_gauges_roundtrip_prometheus():
    reg = metrics.Registry()
    ledger = memwatch.MemoryLedger(registry=reg)

    @jax.jit
    def f(x):
        return x * 2

    pm = ledger.register("t/rt", f, args=(jnp.ones((32, 8)),))
    text = exposition.to_prometheus_text(registry=reg)
    parsed = exposition.parse_prometheus_text(text)
    pname = exposition.prom_name("mem/t/rt/peak_bytes")
    assert parsed[pname]["type"] == "gauge"
    assert parsed[pname]["value"] == float(pm.peak_bytes)
