"""Differential tests: data/pipeline.Dataset vs REAL tf.data.

The data layer claims tf.data-compatible semantics throughout
(data/pipeline.py docstring; SURVEY.md §2b). TensorFlow ships in this
image (pulled in by transformers), so the claims are testable against the
genuine article rather than against our own reading of the docs:

- deterministic chains (map/batch/shard/cache/repeat) must match
  tf.data ELEMENT FOR ELEMENT;
- seeded shuffle uses a different PRNG, so order cannot match — there the
  SEMANTICS must: per-epoch multiset equality, reshuffle-each-iteration,
  repeat-crosses-epoch batching, drop_remainder shapes.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from tfde_tpu.data.pipeline import Dataset  # noqa: E402


def _ours(ds):
    return [tuple(np.asarray(x) for x in el) for el in iter(ds)]


def _tfs(ds):
    out = []
    for el in ds:
        if not isinstance(el, (tuple, list)):
            el = (el,)
        out.append(tuple(np.asarray(x) for x in el))
    return out


def _assert_same(ours, theirs):
    assert len(ours) == len(theirs), (len(ours), len(theirs))
    for a, b in zip(ours, theirs):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def test_deterministic_map_batch_chain_matches():
    x = np.arange(20, dtype=np.float32)
    y = np.arange(20, dtype=np.int32) % 3
    ours = _ours(
        Dataset.from_tensor_slices((x, y))
        .map(lambda a, b: (a * 2.0 + 1.0, b))
        .batch(6)
    )
    theirs = _tfs(
        tf.data.Dataset.from_tensor_slices((x, y))
        .map(lambda a, b: (a * 2.0 + 1.0, b))
        .batch(6)
    )
    _assert_same(ours, theirs)


def test_drop_remainder_matches():
    x = np.arange(10, dtype=np.int32)
    for drop in (True, False):
        ours = _ours(
            Dataset.from_tensor_slices((x,)).batch(4, drop_remainder=drop)
        )
        theirs = _tfs(
            tf.data.Dataset.from_tensor_slices(x).batch(
                4, drop_remainder=drop
            )
        )
        _assert_same(ours, theirs)


def test_repeat_crosses_epoch_boundaries_like_tfdata():
    """repeat().batch() must batch ACROSS epochs — never a short batch at
    an epoch boundary (the property jit static shapes rely on)."""
    x = np.arange(5, dtype=np.int32)
    ours = _ours(Dataset.from_tensor_slices((x,)).repeat(4).batch(3))
    theirs = _tfs(tf.data.Dataset.from_tensor_slices(x).repeat(4).batch(3))
    _assert_same(ours, theirs)


def test_shard_matches():
    x = np.arange(17, dtype=np.int32)
    for n, i in ((2, 0), (2, 1), (3, 2)):
        ours = _ours(Dataset.from_tensor_slices((x,)).shard(n, i))
        theirs = _tfs(tf.data.Dataset.from_tensor_slices(x).shard(n, i))
        _assert_same(ours, theirs)


def test_cache_repeat_matches():
    x = np.arange(8, dtype=np.float32)
    ours = _ours(
        Dataset.from_tensor_slices((x,)).map(lambda a: a + 1).cache()
        .repeat(3).batch(4)
    )
    theirs = _tfs(
        tf.data.Dataset.from_tensor_slices(x).map(lambda a: a + 1).cache()
        .repeat(3).batch(4)
    )
    _assert_same(ours, theirs)


def test_shuffle_semantics_match_tfdata():
    """PRNGs differ, so compare SEMANTICS: full-buffer seeded shuffle is a
    permutation of each epoch (multiset equality with tf.data's output),
    reshuffled differently each epoch, deterministic per seed."""
    x = np.arange(32, dtype=np.int32)
    ds = Dataset.from_tensor_slices((x,)).shuffle(32, seed=7).repeat(2)
    flat = [int(el[0]) for el in iter(ds)]
    ours_epochs = [flat[:32], flat[32:]]

    tfds = tf.data.Dataset.from_tensor_slices(x).shuffle(
        32, seed=7, reshuffle_each_iteration=True
    ).repeat(2)
    tflat = [int(np.asarray(el)) for el in tfds]
    tf_epochs = [tflat[:32], tflat[32:]]

    for o, t in zip(ours_epochs, tf_epochs):
        assert sorted(o) == sorted(t) == list(range(32))
    # both reshuffle per epoch...
    assert ours_epochs[0] != ours_epochs[1]
    assert tf_epochs[0] != tf_epochs[1]
    # ...and both are deterministic under the seed
    flat2 = [int(el[0]) for el in iter(
        Dataset.from_tensor_slices((x,)).shuffle(32, seed=7).repeat(2)
    )]
    assert flat == flat2


def test_windowed_shuffle_semantics():
    """buffer < n: tf.data's windowed shuffle guarantees element i appears
    only after at least i - buffer elements have been emitted (an element
    can't leave the buffer before entering it). Same law must hold here."""
    n, buf = 64, 8
    x = np.arange(n, dtype=np.int32)
    for seq in (
        [int(el[0]) for el in iter(
            Dataset.from_tensor_slices((x,)).shuffle(buf, seed=3)
        )],
        [int(np.asarray(el)) for el in
         tf.data.Dataset.from_tensor_slices(x).shuffle(buf, seed=3)],
    ):
        assert sorted(seq) == list(range(n))
        for pos, val in enumerate(seq):
            assert val <= pos + buf, (pos, val)


def test_streaming_loader_matches_tfdata_epoch_semantics(tmp_path):
    """data.StreamingTFRecordLoader vs the real
    `TFRecordDataset(files).shuffle(W).repeat().batch(B)` chain on the
    same shard files: same batch shapes, per-epoch exact multisets, and
    batches crossing the epoch boundary — the tf.data laws the streaming
    path claims (data/streaming.py docstring)."""
    import struct

    from tfde_tpu.data.streaming import StreamingTFRecordLoader
    from tfde_tpu.data.tfrecord import write_tfrecord

    n_files, per_file, batch = 3, 20, 8
    n = n_files * per_file
    paths = []
    rid = 0
    for f in range(n_files):
        recs = []
        for _ in range(per_file):
            recs.append(struct.pack("<i", rid))
            rid += 1
        p = str(tmp_path / f"s{f}.tfrecord")
        write_tfrecord(p, recs)
        paths.append(p)

    ours = StreamingTFRecordLoader(
        paths, lambda r: (np.int32(struct.unpack("<i", r)[0]),),
        batch_size=batch, window=24, seed=0, repeat=None,
    )
    our_stream = []
    while len(our_stream) < 2 * n:
        b = next(ours)[0]
        assert b.shape == (batch,)
        our_stream.extend(b.tolist())
    ours.close()

    tf_ds = (
        tf.data.TFRecordDataset(paths)
        .map(lambda r: tf.io.decode_raw(r, tf.int32)[0])
        .shuffle(24, seed=0, reshuffle_each_iteration=True)
        .repeat()
        .batch(batch)
    )
    tf_stream = []
    for b in tf_ds:
        assert b.shape[0] == batch
        tf_stream.extend(int(v) for v in b.numpy())
        if len(tf_stream) >= 2 * n:
            break

    # both: each epoch is an exact permutation, reshuffled, crossing
    # batch boundaries — the orders themselves are implementation noise
    for stream in (our_stream, tf_stream):
        assert sorted(stream[:n]) == list(range(n))
        assert sorted(stream[n : 2 * n]) == list(range(n))
        assert stream[:n] != stream[n : 2 * n]
