"""Flash-attention kernel tests — interpret mode on CPU (the fake-backend
methodology of SURVEY.md §4 applied to Pallas kernels); numerics + grads
against the reference einsum implementation."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.ops.attention import reference_attention
from tfde_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=2, s=256, h=2, d=8, dtype=jnp.float32):
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    expect = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 128, 64, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_flash_single_block(rng):
    q, k, v = _qkv(rng, s=64)
    got = flash_attention(q, k, v, False, 128, 128, True)  # blocks clamp to 64
    expect = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(rng, causal):
    q, k, v = _qkv(rng, s=128, d=4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 64, 32, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal,window", [(False, None), (True, None),
                                           (True, 64)])
def test_flash_gqa_matches_grouped_reference(rng, causal, window):
    """GQA shapes (k/v with fewer heads): forward and all three gradients
    must match the grouped-einsum oracle — the K/V index maps fold each q
    head onto its serving KV head, the kernel body is unchanged."""
    from tfde_tpu.ops.attention import grouped_attention

    b, s, h, kv, d = 2, 128, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal, 64, 32, True, window) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            grouped_attention(q, k, v, causal=causal, window=window) ** 2
        )

    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal, 64, 32, True, window)),
        np.asarray(grouped_attention(q, k, v, causal=causal, window=window)),
        rtol=2e-5, atol=2e-5,
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (b, s, kv, d) and gf[2].shape == (b, s, kv, d)
    for a, bb in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-5)


def test_flash_rejects_bad_gqa_heads(rng):
    q = jnp.zeros((1, 128, 4, 8), jnp.float32)
    k = v = jnp.zeros((1, 128, 3, 8), jnp.float32)
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, k, v, False, 64, 64, True)


def test_flash_rejects_indivisible_seq(rng):
    q, k, v = _qkv(rng, s=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, False, 64, 64, True)


def test_flash_bf16_inputs(rng):
    q, k, v = _qkv(rng, s=128, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, False, 64, 64, True)
    assert got.dtype == jnp.bfloat16
    expect = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expect, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_flash_rejects_cross_attention_shapes(rng):
    """All tiling derives from q.shape; Sk != Sq must be a loud error, not a
    silent wrong-range attend (ADVICE r1)."""
    from tfde_tpu.ops.flash_attention import flash_attention

    q = jnp.asarray(rng.standard_normal((1, 128, 2, 16)), jnp.float32)
    kv = jnp.asarray(rng.standard_normal((1, 256, 2, 16)), jnp.float32)
    with pytest.raises(ValueError, match="cross-attention"):
        flash_attention(q, kv, kv, interpret=True)


def test_auto_dispatch_flash_on_tpu_threshold(monkeypatch):
    """Auto-dispatch (hardware A/B r04, tools/flash_ab.py): flash on TPU
    from S>=2048, reference below; TFDE_FLASH=0 disables, =1 lowers the
    threshold."""
    import tfde_tpu.ops.attention as att
    import tfde_tpu.ops.flash_attention as fa

    chosen = []
    monkeypatch.setattr(att, "_on_tpu", lambda: True)

    def fake_flash(q, k, v, causal=False, **kw):
        chosen.append("flash")
        return q

    def fake_ref(q, k, v, mask=None, causal=False, window=None, **kw):
        chosen.append("reference")
        return q

    monkeypatch.setattr(fa, "flash_attention", fake_flash)
    monkeypatch.setattr(att, "reference_attention", fake_ref)
    monkeypatch.delenv("TFDE_FLASH", raising=False)

    long = jnp.zeros((1, 2048, 1, 4), jnp.bfloat16)
    # strictly between the TFDE_FLASH=1 threshold (1024) and the causal
    # default (2048): proves the two thresholds are distinct
    mid = jnp.zeros((1, 1536, 1, 4), jnp.bfloat16)
    longer = jnp.zeros((1, 4096, 1, 4), jnp.bfloat16)

    att.attention(long, long, long, causal=True)
    att.attention(mid, mid, mid, causal=True)
    assert chosen == ["flash", "reference"]

    # non-causal: the flash win is the causal tile skip — threshold 4096
    # (memory-motivated; r04 A/B measured 0.87-0.97x there)
    chosen.clear()
    att.attention(long, long, long)
    att.attention(longer, longer, longer)
    assert chosen == ["reference", "flash"]

    chosen.clear()
    monkeypatch.setenv("TFDE_FLASH", "0")
    att.attention(long, long, long, causal=True)
    assert chosen == ["reference"]

    chosen.clear()
    monkeypatch.setenv("TFDE_FLASH", "1")
    att.attention(mid, mid, mid, causal=True)
    assert chosen == ["flash"]

    # cross-attention shapes never auto-pick flash
    chosen.clear()
    monkeypatch.delenv("TFDE_FLASH", raising=False)
    kv = jnp.zeros((1, 8192, 1, 4), jnp.bfloat16)
    att.attention(long, kv, kv)
    assert chosen == ["reference"]


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_jax_backward(rng, causal, monkeypatch):
    """The Pallas dKV/dQ kernels against the blockwise-JAX backward oracle
    (TFDE_FLASH_BWD=jax), asymmetric tile sizes, bf16 inputs."""
    q, k, v = _qkv(rng, s=128, d=8, dtype=jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal, 64, 32, True).astype(jnp.float32)
            ** 2
        )

    monkeypatch.setenv("TFDE_FLASH_BWD", "pallas")
    gp = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("TFDE_FLASH_BWD", "jax")
    gj = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gj):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=5e-2,  # bf16 grads
        )


def test_flash_dispatch_keeps_batch_sharded():
    """pallas_call under plain jit GATHERS sharded operands and replicates
    the kernel (silently destroying DP); the dispatcher must shard_map the
    flash path over the active mesh's batch axes instead — output stays
    batch-sharded and numerics match the reference."""
    import tfde_tpu.ops.attention as att
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tfde_tpu.parallel import axes as axes_lib
    from tfde_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 4}, jax.devices()[:4])
    rng = np.random.default_rng(0)
    q = jax.device_put(
        jnp.asarray(rng.standard_normal((8, 128, 2, 16)), jnp.float32),
        NamedSharding(mesh, P("data")),
    )

    @jax.jit
    def f(q):
        with axes_lib.use_axes(mesh):
            return att.attention(q, q, q, causal=True, impl="flash")

    out = f(q)
    assert out.sharding.spec == P("data"), out.sharding
    want = att.reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    # grads flow through the shard_map'd custom_vjp
    @jax.jit
    def loss(q):
        with axes_lib.use_axes(mesh):
            return jnp.sum(att.attention(q, q, q, causal=True,
                                         impl="flash") ** 2)

    g = jax.grad(loss)(q)
    g_ref = jax.grad(
        lambda q: jnp.sum(att.reference_attention(q, q, q, causal=True) ** 2)
    )(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=2e-3, atol=2e-4)
