"""Project lint (tools/tfdelint.py) + gate diff logic (tools/lintgate.py):
the repo itself must pass clean, seeded fixtures (unlocked threaded
write, unguarded greedy-path split, unregistered knob) must each be
flagged with an actionable message, and lintgate's check() must fail on
census drift, unknown programs, and project violations.
"""

import importlib.util
import os
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tl():
    return _load("tfdelint")


@pytest.fixture(scope="module")
def lg():
    # lintgate's module-top env setup uses setdefault; everything it
    # wants (JAX_PLATFORMS, XLA_FLAGS) is already pinned by conftest.
    # Pre-set the arm flag to off so importing the gate never arms the
    # in-process hlolint seam for unrelated tests.
    os.environ.setdefault("TFDE_HLOLINT", "0")
    return _load("lintgate")


# -- the repo itself ----------------------------------------------------------
def test_repo_passes_project_lint_clean(tl):
    result = tl.lint_repo()
    assert result["violations"] == []
    # the threaded-class table is live: every entry resolved
    assert set(result["lock_audit"]) == {
        f"{f}::{c}" for f, c in tl.LOCKED_CLASSES}
    assert "TFDE_HLOLINT" in result["knobs_seen"]


# -- rule 1: lock discipline --------------------------------------------------
_BOX = textwrap.dedent("""
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._items = {}

        def bad_aug(self):
            self._n += 1                      # line 11: unlocked RMW

        def bad_publish(self, k, v):
            self._items[k] = v                # line 14: unlocked publish

        def good(self, k, v):
            with self._lock:
                self._n += 1
                self._items[k] = v

        def local_object_ok(self):
            obj = object.__new__(Box)
            obj.fresh = 1                     # local publish: legal
            return obj

        def closure_bad(self):
            with self._lock:
                def cb():
                    self._n = 5               # closure outlives the lock
                return cb
""")


def _write_pkg(tmp_path, rel, src):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return str(tmp_path)


def test_unlocked_write_fixture_is_flagged(tl, tmp_path):
    root = _write_pkg(tmp_path, "pkg/box.py", _BOX)
    table = {("pkg/box.py", "Box"): tl.LockSpec(lock="_lock")}
    violations, audit = tl.lint_locks(root, table=table)
    assert audit["pkg/box.py::Box"] == "checked"
    lines = sorted(int(v.split(":")[1]) for v in violations)
    assert len(violations) == 3, violations
    # the aug-assign, the subscript publish, and the closure write — and
    # nothing from good()/local_object_ok()/__init__
    for v in violations:
        assert "with self._lock" in v
    assert any("augmented write to ._n" in v for v in violations)
    assert any("write to self._items" in v for v in violations)
    assert lines[-1] > lines[0]


def test_exempt_attrs_and_external_lock(tl, tmp_path):
    root = _write_pkg(tmp_path, "pkg/box.py", _BOX)
    # exempting the attrs silences exactly those findings
    table = {("pkg/box.py", "Box"): tl.LockSpec(
        lock="_lock", exempt_attrs=("_n", "_items"))}
    violations, _ = tl.lint_locks(root, table=table)
    assert violations == []
    # an external-lock declaration skips the class with its reason
    table = {("pkg/box.py", "Box"): tl.LockSpec(
        external="owner holds the lock")}
    violations, audit = tl.lint_locks(root, table=table)
    assert violations == []
    assert "owner holds the lock" in audit["pkg/box.py::Box"]


def test_stale_locked_classes_table_is_loud(tl, tmp_path):
    root = _write_pkg(tmp_path, "pkg/box.py", _BOX)
    table = {("pkg/box.py", "Vanished"): tl.LockSpec()}
    violations, _ = tl.lint_locks(root, table=table)
    assert len(violations) == 1 and "stale" in violations[0]


def test_lock_rule_catches_the_pr10_aggregate_bug(tl, tmp_path):
    """The exact shape fixed in this PR: ClusterAggregator.rollup()
    mutated `self._known_stale &= ...` and `self._flagged_straggler = ...`
    outside the lock while handler threads read them."""
    src = textwrap.dedent("""
        import threading

        class Agg:
            def __init__(self):
                self._lock = threading.Lock()
                self._known_stale = set()
                self._flagged_straggler = None

            def rollup(self, stale, straggler):
                self._known_stale &= set(stale)
                if straggler >= 0:
                    self._flagged_straggler = straggler
    """)
    root = _write_pkg(tmp_path, "pkg/agg.py", src)
    violations, _ = tl.lint_locks(
        root, table={("pkg/agg.py", "Agg"): tl.LockSpec(lock="_lock")})
    assert len(violations) == 2
    assert any("_known_stale" in v for v in violations)
    assert any("_flagged_straggler" in v for v in violations)


# -- rule 2: greedy-path split ban --------------------------------------------
def test_greedy_split_fixture(tl, tmp_path):
    src = textwrap.dedent("""
        import jax

        def bad(key):
            return jax.random.split(key)          # unguarded

        def guarded(key, temperature):
            if temperature > 0.0:
                return jax.random.split(key)      # sampling branch: ok
            return key

        def else_branch(key, greedy):
            if greedy:
                return key
            else:
                return jax.random.split(key)      # other side: still ok

        def _round_sampled(key):
            return jax.random.split(key)          # sampled-only program: ok
    """)
    root = _write_pkg(tmp_path, "pkg/dec.py", src)
    violations = tl.lint_greedy_split(root, dirs=("pkg",))
    assert len(violations) == 1, violations
    assert "pkg/dec.py:5" in violations[0]
    assert "temperature/greedy" in violations[0]


def test_repo_inference_tree_passes_greedy_split(tl):
    assert tl.lint_greedy_split(ROOT) == []


# -- rule 3: knob audit -------------------------------------------------------
def test_unregistered_knob_fixture(tl, tmp_path):
    src = 'import os\nX = os.environ.get("TFDE_NOT_A_KNOB")\n' \
          'Y = os.environ.get("TFDE_TRACE")\n' \
          'Z = os.environ.get("TFDE_RETRY_MAX_ATTEMPTS")\n'
    root = _write_pkg(tmp_path, "tfde_tpu/mod.py", src)
    violations, seen = tl.lint_knobs(root)
    assert seen == ["TFDE_NOT_A_KNOB", "TFDE_RETRY_MAX_ATTEMPTS",
                    "TFDE_TRACE"]
    # registered name and registered prefix family pass; the stray fails
    # with a pointer at the registry
    assert len(violations) == 1, violations
    assert "TFDE_NOT_A_KNOB" in violations[0]
    assert "tfde_tpu/knobs.py" in violations[0]


# -- lintgate diff logic ------------------------------------------------------
def _census(**over):
    c = {"all_reduce": 2, "reduce_scatter": 1, "all_gather": 2,
         "collective_permute": 0, "callbacks": 0, "aliased_outputs": 13,
         "f64_tensors": 0, "bf16_to_f32_converts": 0,
         "collective_bytes": {"all_reduce": 9560}, "large_constants": []}
    c.update(over)
    return c


def _obs(census=None, violations=(), project_violations=(),
         knobs=("TFDE_TRACE",), name="train_step/int8+replicated"):
    return {
        "programs": {name: {"census": census or _census(),
                            "violations": list(violations)}},
        "project": {"violations": list(project_violations),
                    "lock_audit": {"a.py::A": "checked"},
                    "knobs_seen": list(knobs)},
    }


def test_lintgate_check_clean(lg):
    base = _obs()
    assert lg.check(_obs(), base) == []


def test_lintgate_check_fails_on_extra_collective(lg):
    base = _obs()
    fails = lg.check(_obs(census=_census(all_reduce=3)), base)
    assert len(fails) == 1
    assert "all_reduce 3 != baseline 2" in fails[0]
    assert "--update" in fails[0]  # actionable: names the re-baseline cmd


def test_lintgate_check_fails_on_payload_drift(lg):
    base = _obs()
    drifted = _census(collective_bytes={"all_reduce": 99999})
    fails = lg.check(_obs(census=drifted), base)
    assert len(fails) == 1 and "payload bytes" in fails[0]


def test_lintgate_check_fails_on_violation_and_unknown_names(lg):
    base = _obs()
    fails = lg.check(_obs(violations=["p: stray host callback"]), base)
    assert any("violation: p: stray host callback" in f for f in fails)
    # a program the baseline has never seen
    fails = lg.check(_obs(name="serve/decode/k9"), base)
    assert any("not in baseline" in f for f in fails)
    # a baseline program the workload lost
    lost = _obs()
    lost["programs"] = {}
    fails = lg.check(lost, base)
    assert any("not observed" in f for f in fails)


def test_lintgate_check_fails_on_project_drift(lg):
    base = _obs()
    fails = lg.check(_obs(project_violations=["x.py:3: unlocked write"]),
                     base)
    assert any("unlocked write" in f for f in fails)
    fails = lg.check(_obs(knobs=("TFDE_TRACE", "TFDE_NEW")), base)
    assert any("knob census changed" in f for f in fails)


def test_lintgate_baseline_is_committed_and_covers_the_matrix(lg):
    import json

    with open(os.path.join(ROOT, "tools", "lintgate_baseline.json")) as f:
        base = json.load(f)
    names = set(base["programs"])
    # all four transport x sharding combos
    for t, s in lg.TRAIN_COMBOS:
        assert f"train_step/{t}+{s}" in names
    # decode scan + all three prefill admission kinds
    assert any(n.startswith("serve/decode/") for n in names)
    assert any(n.startswith("serve/prefill/") for n in names)
    assert any(n.startswith("serve/prefill_warm/") for n in names)
    assert any(n.startswith("serve/prefill_primed/") for n in names)
    # the baseline itself is violation-free
    for prog in base["programs"].values():
        assert prog["violations"] == []
    assert base["project"]["violations"] == []


def test_guarded_attrs_flag_unlocked_reads(tl, tmp_path):
    """The PR-14 regression shape: router.load() read `self.batcher._queue`
    without the replica lock. `guarded_attrs` makes the lock rule flag ANY
    access — reads included — to the named attributes outside the lock."""
    src = textwrap.dedent("""
        import threading

        class Rep:
            def __init__(self):
                self._lock = threading.Lock()
                self.batcher = object()       # __init__ is exempt

            def bad_read(self):
                return len(self.batcher._queue)   # unlocked read

            def bad_alias(self):
                b = self.batcher                  # unlocked alias grab
                return b

            def good(self):
                with self._lock:
                    return len(self.batcher._queue)
    """)
    root = _write_pkg(tmp_path, "pkg/rep.py", src)
    table = {("pkg/rep.py", "Rep"): tl.LockSpec(
        lock="_lock", guarded_attrs=("batcher",))}
    violations, audit = tl.lint_locks(root, table=table)
    assert audit["pkg/rep.py::Rep"] == "checked"
    assert len(violations) == 2, violations
    for v in violations:
        assert "access to self.batcher" in v
    # without the guard, plain reads stay legal (writes-only rule)
    table = {("pkg/rep.py", "Rep"): tl.LockSpec(lock="_lock")}
    violations, _ = tl.lint_locks(root, table=table)
    assert violations == []
