"""Distillation (training/distill.py) and its payoff: a distilled draft
raises speculative-decoding acceptance.

The end-to-end story: train a teacher on the structured synthetic stream,
distill a half-size student against its soft targets through the standard
custom-loss machinery, and verify (a) the distillation metrics move the
right way, (b) the distilled student accelerates speculative decoding
measurably versus an undistilled twin — tokens_per_round is the
acceptance telemetry the serving side exposes for exactly this."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.data.datasets import synthetic_tokens
from tfde_tpu.models.gpt import GPT, gpt_tiny_test
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.distill import make_distill_loss
from tfde_tpu.training.step import init_state, make_custom_train_step


def _student():
    return GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2,
               mlp_dim=32, max_position=64, dtype=jnp.float32)


@pytest.mark.slow
def test_distill_improves_agreement_and_speculation():
    """Runs in a subprocess: the 400-step train+distill loop is stable
    standalone but can abort inside pytest's process environment (an XLA
    CPU runtime issue unrelated to the code under test — no Python frame
    beyond the jitted call in the crash dump); subprocess isolation is the
    same methodology as tests/test_multiprocess.py."""
    import json
    import subprocess
    import sys

    script = r"""
import json
import jax
jax.config.update("jax_platforms", "cpu")
from tfde_tpu.utils.devices import request_cpu_devices
request_cpu_devices(8)
import jax.numpy as jnp, numpy as np, optax
from tfde_tpu.data.datasets import synthetic_tokens
from tfde_tpu.inference.speculative import generate_speculative
from tfde_tpu.models.gpt import GPT, gpt_tiny_test, next_token_loss
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.distill import make_distill_loss
from tfde_tpu.training.step import init_state, make_custom_train_step

tokens = synthetic_tokens(512, 16, vocab=96)
strategy = MultiWorkerMirroredStrategy()
teacher = gpt_tiny_test()
tstate, _ = init_state(teacher, optax.adamw(3e-3), strategy,
                       np.zeros((32, 16), np.int32))
tstep = make_custom_train_step(strategy, tstate, next_token_loss, donate=False)
rng = np.random.default_rng(0)
key = jax.random.key(0)
for _ in range(120):
    idx = rng.integers(0, len(tokens), 32)
    tstate, _ = tstep(tstate, (tokens[idx],), key)
tparams = jax.device_get(tstate.params)

student = GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2, mlp_dim=32,
              max_position=64, dtype=jnp.float32)
state, _ = init_state(student, optax.adamw(3e-3), strategy,
                      np.zeros((32, 16), np.int32))
undistilled = jax.device_get(state.params)
loss_fn = make_distill_loss(teacher, tparams, temperature=1.0)
step = make_custom_train_step(strategy, state, loss_fn, donate=False)
rng = np.random.default_rng(1)
key = jax.random.key(1)
state, m0 = step(state, (tokens[rng.integers(0, 512, 32)],), key)
metrics = m0
for _ in range(150):
    idx = rng.integers(0, len(tokens), 32)
    state, metrics = step(state, (tokens[idx],), key)
distilled = jax.device_get(state.params)

prompt = jnp.asarray(tokens[:1, :6], jnp.int32)
def rate(dp):
    _, _, stats = generate_speculative(teacher, student, tparams, dp, prompt,
                                       max_new_tokens=24, num_draft=4,
                                       return_stats=True)
    return stats["tokens_per_round"]

print(json.dumps({
    "first_kl": float(m0["kl"]), "first_agree": float(m0["agreement"]),
    "kl": float(metrics["kl"]), "agreement": float(metrics["agreement"]),
    "rate_distilled": rate(distilled), "rate_undistilled": rate(undistilled),
}))
"""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for attempt in range(3):
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=800, cwd=repo,
        )
        if proc.returncode == 0:
            break
        if "rendezvous" not in proc.stderr:
            break
        # XLA's in-process CPU collective rendezvous times out when the
        # box is oversubscribed (8 virtual devices on few cores under a
        # loaded CI: "Expected 8 threads to join ... only N arrived") and
        # SIGABRTs the subprocess — a load flake, not a code defect.
        # Retry; a real failure reproduces.
    assert proc.returncode == 0, proc.stderr[-1500:]
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    assert r["kl"] < r["first_kl"]
    assert r["agreement"] > max(r["first_agree"], 0.25)
    # the payoff: identical speculative runs, draft params the only delta —
    # the distilled draft commits more tokens per target forward
    assert r["rate_distilled"] > r["rate_undistilled"]


def test_distill_hard_mix_and_validation():
    import pytest

    teacher = gpt_tiny_test()
    tparams = teacher.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="temperature"):
        make_distill_loss(teacher, tparams, temperature=0.0)

    strategy = MultiWorkerMirroredStrategy()
    student = _student()
    state, _ = init_state(student, optax.sgd(1e-2), strategy,
                          np.zeros((16, 16), np.int32))
    loss_fn = make_distill_loss(teacher, tparams, temperature=1.0,
                                hard_weight=0.5)
    step = make_custom_train_step(strategy, state, loss_fn, donate=False)
    toks = synthetic_tokens(64, 16, vocab=96)
    state, metrics = step(state, (toks[:16],), jax.random.key(0))
    assert np.isfinite(float(metrics["kl"]))
    assert np.isfinite(float(metrics["hard_loss"]))
    assert 0.0 <= float(metrics["agreement"]) <= 1.0
