"""Retry-policy unit tests — virtual time throughout (injected sleep/clock),
so backoff/deadline behavior is tested without wall-clock waits."""

import random

import pytest

from tfde_tpu.observability import counters
from tfde_tpu.resilience.policy import (
    NO_RETRY,
    RetryBudgetExceeded,
    RetryPolicy,
    TransientError,
    policy_from_env,
    retry,
    retry_call,
)


class Flaky:
    """Fails the first `n_failures` calls with `exc`, then returns 'ok'."""

    def __init__(self, n_failures, exc=IOError("blip")):
        self.n_failures = n_failures
        self.exc = exc
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.calls <= self.n_failures:
            raise self.exc
        return "ok"


def _virtual():
    """(sleep, clock, slept-log) sharing one virtual timeline."""
    t = {"now": 0.0}
    slept = []

    def sleep(s):
        slept.append(s)
        t["now"] += s

    return sleep, (lambda: t["now"]), slept


def test_succeeds_after_transient_failures():
    f = Flaky(2)
    sleep, clock, slept = _virtual()
    out = retry_call(f, policy=RetryPolicy(max_attempts=4, jitter=0.0),
                     sleep=sleep, clock=clock)
    assert out == "ok" and f.calls == 3
    assert len(slept) == 2


def test_backoff_is_exponential_and_capped():
    p = RetryPolicy(initial_backoff=1.0, multiplier=2.0, max_backoff=3.0,
                    jitter=0.0)
    assert [p.backoff(i) for i in (1, 2, 3, 4)] == [1.0, 2.0, 3.0, 3.0]


def test_jitter_is_seeded_and_bounded():
    p = RetryPolicy(initial_backoff=1.0, jitter=0.25)
    a = [p.backoff(1, random.Random(7)) for _ in range(3)]
    b = [p.backoff(1, random.Random(7)) for _ in range(3)]
    assert a == b  # same seed -> same schedule
    assert all(0.75 <= x <= 1.25 for x in a)


def test_budget_exhaustion_raises_with_cause():
    f = Flaky(10)
    sleep, clock, _ = _virtual()
    with pytest.raises(RetryBudgetExceeded) as ei:
        retry_call(f, policy=RetryPolicy(max_attempts=3, jitter=0.0),
                   sleep=sleep, clock=clock)
    assert f.calls == 3
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, IOError)
    # OSError-compat: I/O call sites guarding with `except OSError` still
    # catch the exhausted form
    assert isinstance(ei.value, OSError)


def test_non_retryable_propagates_immediately():
    f = Flaky(10, exc=ValueError("poison"))
    with pytest.raises(ValueError):
        retry_call(f, policy=RetryPolicy(max_attempts=5))
    assert f.calls == 1


def test_deterministic_oserrors_are_not_retried():
    f = Flaky(10, exc=FileNotFoundError("no such object"))
    with pytest.raises(FileNotFoundError):
        retry_call(f, policy=RetryPolicy(max_attempts=5))
    assert f.calls == 1  # FileNotFoundError is OSError but never transient


def test_transient_marker_forces_retry():
    f = Flaky(1, exc=TransientError("wrapped"))
    sleep, clock, _ = _virtual()
    assert retry_call(f, policy=RetryPolicy(max_attempts=2, jitter=0.0),
                      sleep=sleep, clock=clock) == "ok"


def test_deadline_bounds_total_budget():
    f = Flaky(10)
    sleep, clock, slept = _virtual()
    p = RetryPolicy(max_attempts=100, initial_backoff=1.0, multiplier=1.0,
                    jitter=0.0, deadline=2.5)
    with pytest.raises(RetryBudgetExceeded):
        retry_call(f, policy=p, sleep=sleep, clock=clock)
    # 1s + 1s sleeps fit the 2.5s budget; the third would exceed it
    assert slept == [1.0, 1.0] and f.calls == 3


def test_no_retry_policy_is_single_attempt():
    f = Flaky(1)
    with pytest.raises(RetryBudgetExceeded):
        retry_call(f, policy=NO_RETRY)
    assert f.calls == 1


def test_decorator_form():
    calls = {"n": 0}
    sleep, clock, _ = _virtual()

    @retry(RetryPolicy(max_attempts=3, jitter=0.0), sleep=sleep, clock=clock)
    def op(x):
        calls["n"] += 1
        if calls["n"] < 2:
            raise IOError("blip")
        return x * 2

    assert op(21) == 42 and calls["n"] == 2


def test_policy_from_env(monkeypatch):
    monkeypatch.setenv("TFDE_RETRY_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("TFDE_RETRY_INITIAL_BACKOFF", "0.5")
    monkeypatch.setenv("TFDE_RETRY_DEADLINE", "12")
    p = policy_from_env()
    assert p.max_attempts == 7
    assert p.initial_backoff == 0.5
    assert p.deadline == 12.0
    assert p.max_backoff == RetryPolicy().max_backoff  # untouched field


def test_policy_from_env_rejects_garbage(monkeypatch):
    monkeypatch.setenv("TFDE_RETRY_MAX_ATTEMPTS", "many")
    with pytest.raises(ValueError, match="TFDE_RETRY_MAX_ATTEMPTS"):
        policy_from_env()


def test_retries_are_counted():
    counters.reset("resilience/")
    f = Flaky(2)
    sleep, clock, _ = _virtual()
    retry_call(f, policy=RetryPolicy(max_attempts=4, jitter=0.0),
               sleep=sleep, clock=clock)
    assert counters.value("resilience/retries") == 2


def test_invalid_policy_rejected():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)
