"""Softcap (Gemma-2 tanh logit capping) and custom scale inside the fused
flash kernels, the reference einsum, the ring body, and the dispatcher.

Oracle chain: hand-built einsum with cap * tanh(s * scale / cap) ->
reference/grouped_attention(scale=, logit_cap=) -> flash_attention in
interpret mode (multi-tile shapes, both backward implementations, GQA) ->
the seq ring -> models/gpt.py end to end with window_pattern='alternate'.
Forward pins at 1e-5 relative Frobenius, grads at 1e-4 (the acceptance
bars)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.ops.attention import grouped_attention, reference_attention
from tfde_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=1, s=256, h=2, d=8, kv=None, dtype=jnp.float32):
    kv = kv or h
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), dtype)
    return q, k, v


def _rel(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30))


def test_reference_softcap_matches_hand_einsum(rng):
    """Ground truth for the whole chain: cap applied AFTER the scale and
    BEFORE the causal mask, s -> cap * tanh(s * scale / cap)."""
    q, k, v = _qkv(rng, s=32)
    cap, scale = 30.0, 0.2
    s_ = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s_ = cap * jnp.tanh(s_ / cap)
    n = q.shape[1]
    mask = jnp.tril(jnp.ones((n, n), bool))
    s_ = jnp.where(mask, s_, -jnp.inf)
    out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s_, axis=-1), v)
    got = reference_attention(q, k, v, causal=True, scale=scale,
                              logit_cap=cap)
    assert _rel(got, out) <= 1e-6


def test_reference_rejects_nonpositive_cap(rng):
    q, k, v = _qkv(rng, s=16)
    with pytest.raises(ValueError, match="logit_cap"):
        reference_attention(q, k, v, causal=True, logit_cap=0.0)


# (causal, window, scale, cap, kv_heads): MHA and GQA, every knob combo the
# Gemma-2 family exercises; s=256 with 64-blocks -> 4x4 tiles (multi-tile)
CASES = [
    ("cap", True, None, None, 50.0, None),
    ("cap_win", True, 64, None, 30.0, None),
    ("cap_win_scale_gqa", True, 64, 0.125, 30.0, 2),
    ("cap_bidir", False, None, 0.2, 20.0, None),
    ("scale_only", True, None, 0.5, None, None),
    ("cap_scale_gqa_bidir", False, None, 0.25, 40.0, 2),
]


@pytest.mark.parametrize("name,causal,window,scale,cap,kv",
                         CASES, ids=[c[0] for c in CASES])
def test_flash_softcap_forward_parity(rng, name, causal, window, scale,
                                      cap, kv):
    h = 4 if kv else 2
    q, k, v = _qkv(rng, s=256, h=h, kv=kv, d=16)
    ref = grouped_attention(q, k, v, causal=causal, window=window,
                            scale=scale, logit_cap=cap)
    got = flash_attention(q, k, v, causal, 64, 64, True, window, scale, cap)
    assert _rel(got, ref) <= 1e-5


@pytest.mark.parametrize("bwd", ["jax", "pallas"])
@pytest.mark.parametrize("name,causal,window,scale,cap,kv",
                         CASES, ids=[c[0] for c in CASES])
def test_flash_softcap_grads_parity(rng, monkeypatch, bwd, name, causal,
                                    window, scale, cap, kv):
    """All three gradients against the grouped oracle, 1e-4 relative
    Frobenius, through BOTH backward implementations (the Pallas kernel
    pair serves MHA; GQA falls back to the blockwise scan either way)."""
    monkeypatch.setenv("TFDE_FLASH_BWD", bwd)
    h = 4 if kv else 2
    q, k, v = _qkv(rng, s=128, h=h, kv=kv, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal, 32, 32, True, window, scale,
                            cap) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            grouped_attention(q, k, v, causal=causal, window=window,
                              scale=scale, logit_cap=cap) ** 2
        )

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert _rel(a, b) <= 1e-4


def test_ring_softcap_matches_reference(rng):
    """scale + cap ride the ring body's online-softmax chunk step — exact
    across shard boundaries under the seq mesh."""
    from tfde_tpu.ops.attention import attention
    from tfde_tpu.parallel import axes as axes_lib
    from tfde_tpu.runtime.mesh import make_mesh

    q, k, v = _qkv(rng, b=2, s=32)
    expect = reference_attention(q, k, v, causal=True, scale=0.2,
                                 logit_cap=25.0)
    mesh = make_mesh({"seq": 4, "data": 2})
    with axes_lib.use_axes(mesh):
        got = jax.jit(
            lambda q, k, v: attention(q, k, v, causal=True, scale=0.2,
                                      logit_cap=25.0)
        )(q, k, v)
    assert _rel(got, expect) <= 1e-5


def test_tfde_flash_typo_warns_and_keeps_default(monkeypatch):
    """A typo like TFDE_FLASH=ture used to silently LOWER the auto-dispatch
    threshold to 1024; it must now warn and keep the measured default."""
    import tfde_tpu.ops.attention as att

    monkeypatch.setenv("TFDE_FLASH", "ture")
    with pytest.warns(UserWarning, match="TFDE_FLASH"):
        assert att._flash_min_seq(causal=True) == 2048
    with pytest.warns(UserWarning, match="TFDE_FLASH"):
        assert att._flash_min_seq(causal=False) == 4096


def test_tfde_flash_recognized_values_do_not_warn(monkeypatch):
    import warnings

    import tfde_tpu.ops.attention as att

    expect = {"0": None, "false": None, "1": 1024, "true": 1024,
              "auto": 2048, "": 2048}
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for env, want in expect.items():
            monkeypatch.setenv("TFDE_FLASH", env)
            assert att._flash_min_seq(causal=True) == want
        monkeypatch.delenv("TFDE_FLASH")
        assert att._flash_min_seq(causal=True) == 2048


def test_auto_dispatch_picks_flash_with_softcap(monkeypatch):
    """Gemma-2-style capped/scaled attention must still auto-pick the
    flash kernel on TPU-eligible shapes (the old transformer.py hard-coded
    grouped_attention whenever a cap was set), with both knobs forwarded
    into the kernel call."""
    import tfde_tpu.ops.attention as att
    import tfde_tpu.ops.flash_attention as fa

    monkeypatch.setattr(att, "_on_tpu", lambda: True)
    monkeypatch.delenv("TFDE_FLASH", raising=False)
    seen = []

    def fake_flash(q, k, v, causal=False, **kw):
        seen.append(("flash", kw.get("scale"), kw.get("logit_cap")))
        return q

    def fake_ref(q, k, v, **kw):
        seen.append(("reference", kw.get("scale"), kw.get("logit_cap")))
        return q

    monkeypatch.setattr(fa, "flash_attention", fake_flash)
    monkeypatch.setattr(att, "reference_attention", fake_ref)

    long = jnp.zeros((1, 2048, 1, 4), jnp.bfloat16)
    att.attention(long, long, long, causal=True, scale=0.0625,
                  logit_cap=50.0)
    assert seen == [("flash", 0.0625, 50.0)]

    # below the threshold the reference path gets the same knobs
    seen.clear()
    short = jnp.zeros((1, 512, 1, 4), jnp.bfloat16)
    att.attention(short, short, short, causal=True, logit_cap=50.0)
    assert seen == [("reference", None, 50.0)]


def test_cap_on_incapable_impl_warns_and_falls_back(monkeypatch, rng):
    """The safety net: if a selected impl ever drops out of _CAP_IMPLS,
    capped calls warn and run the grouped reference einsum instead of
    refusing (the model keeps training)."""
    import tfde_tpu.ops.attention as att

    monkeypatch.setattr(att, "_CAP_IMPLS", frozenset({"reference"}))
    used = []
    real_ref = att.reference_attention

    def spy_ref(q, k, v, **kw):
        used.append("reference")
        return real_ref(q, k, v, **kw)

    monkeypatch.setattr(att, "reference_attention", spy_ref)
    q, k, v = _qkv(rng, s=64)
    with pytest.warns(UserWarning, match="scale/logit_cap"):
        got = att.attention(q, k, v, causal=True, impl="flash",
                            logit_cap=30.0)
    assert used == ["reference"]
    expect = real_ref(q, k, v, causal=True, logit_cap=30.0)
    assert _rel(got, expect) <= 1e-6


def test_gpt_alternate_softcap_flash_matches_reference(rng):
    """models/gpt.py end to end: sliding_window_pattern='alternate' +
    attn_logit_cap + GQA routed through the attention() dispatcher — the
    forced-flash model (interpret kernels on CPU) must reproduce the
    reference-impl model on the same params, logits and grads."""
    from tfde_tpu.models.gpt import gpt_tiny_test

    kw = dict(sliding_window=8, sliding_window_pattern="alternate",
              attn_logit_cap=30.0, num_kv_heads=2, position="rope")
    m_ref = gpt_tiny_test(attn_impl="reference", **kw)
    m_fl = gpt_tiny_test(attn_impl="flash", **kw)
    tokens = jnp.asarray(rng.integers(0, 97, size=(2, 64)), jnp.int32)
    params = m_ref.init(jax.random.key(0), tokens)["params"]

    a = m_ref.apply({"params": params}, tokens, train=False)
    b = m_fl.apply({"params": params}, tokens, train=False)
    assert _rel(b, a) <= 1e-5

    def loss(m, p):
        return jnp.mean(m.apply({"params": p}, tokens, train=False) ** 2)

    ga = jax.grad(lambda p: loss(m_ref, p))(params)
    gb = jax.grad(lambda p: loss(m_fl, p))(params)
    flat_a = jax.tree_util.tree_leaves(ga)
    flat_b = jax.tree_util.tree_leaves(gb)
    assert len(flat_a) == len(flat_b)
    for a_, b_ in zip(flat_a, flat_b):
        assert _rel(b_, a_) <= 1e-4
