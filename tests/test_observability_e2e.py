"""End-to-end observability acceptance (ISSUE 2 criteria): on a
deterministic CPU mesh, an instrumented training run must produce

- a step-time breakdown (data-wait / compute / checkpoint / compile) whose
  components sum to the measured wall-clock within 5%,
- a live /metrics endpoint (RunConfig.metrics_port) serving valid
  Prometheus text with the training series, plus the JSONL event log under
  model_dir/metrics/,
- and, after a supervised SIGTERM-restart schedule, resilience and goodput
  series on the same exposition surface.
"""

import json
import signal
import time
import urllib.request

import numpy as np
import optax
import pytest

from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import metrics
from tfde_tpu.observability.exposition import (
    MetricsServer,
    PROM_CONTENT_TYPE,
    parse_prometheus_text,
)
from tfde_tpu.observability.goodput import GoodputLedger
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.resilience import (
    RetryPolicy,
    SignalFault,
    StepFaults,
    Supervisor,
    SupervisorConfig,
)
from tfde_tpu.training.lifecycle import Estimator, RunConfig

MAX_STEPS = 20

_rngd = np.random.default_rng(0)
IMAGES = _rngd.random((32, 784), np.float32)
LABELS = _rngd.integers(0, 10, (32, 1)).astype(np.int32)


def constant_input_fn():
    def gen():
        while True:
            yield (IMAGES, LABELS)

    return gen()


def _reset_run_metrics():
    reg = metrics.default_registry()
    for p in ("train/", "eval/", "checkpoint/", "resilience/", "goodput/"):
        reg.reset(p)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One instrumented run shared by the breakdown/endpoint assertions:
    summaries (and their device sync) every step, a mid-run checkpoint,
    metrics server on an ephemeral port."""
    _reset_run_metrics()
    md = str(tmp_path_factory.mktemp("run"))
    est = Estimator(
        model=PlainCNN(),
        optimizer=optax.sgd(0.1),
        strategy=MirroredStrategy(),
        config=RunConfig(
            model_dir=md,
            save_summary_steps=1,
            log_step_count_steps=5,
            save_checkpoints_steps=10,
            metrics_port=0,
        ),
    )
    ledger = GoodputLedger()
    t0 = time.perf_counter()
    est.train(constant_input_fn, MAX_STEPS)
    wall = time.perf_counter() - t0
    rep = ledger.report(wall)
    yield est, md, wall, rep
    est.close()


def test_breakdown_sums_to_wall_within_5pct(trained):
    _, _, wall, rep = trained
    s = rep["seconds"]
    # every advertised phase was actually observed
    assert s["compile"] > 0.0
    assert s["compute"] > 0.0
    assert s["data_wait"] > 0.0
    assert s["checkpoint"] > 0.0  # step-10 save + the end-of-run commit
    assert s["init"] > 0.0
    accounted = sum(v for k, v in s.items() if k != "other")
    assert accounted == pytest.approx(wall, rel=0.05), rep
    assert rep["fractions"]["other"] <= 0.05, rep
    assert rep["steps"] == MAX_STEPS
    # honest rate accounting: compile was carved out of the step histogram
    assert s["compile"] > rep["mean_step_seconds"] * 3


def test_metrics_endpoint_serves_training_series(trained):
    est, _, _, _ = trained
    assert est.metrics_server is not None
    base = f"http://127.0.0.1:{est.metrics_server.port}"
    with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
        assert r.headers["Content-Type"] == PROM_CONTENT_TYPE
        body = r.read().decode()
    back = parse_prometheus_text(body)
    assert back["tfde_train_step"]["count"] == MAX_STEPS
    assert back["tfde_train_compile_seconds_total"]["value"] > 0.0
    assert back["tfde_checkpoint_saves_total"]["value"] >= 1.0
    assert back["tfde_train_steps_per_sec"]["value"] > 0.0


def test_jsonl_event_log_written(trained):
    _, md, _, _ = trained
    import glob
    import os

    files = glob.glob(os.path.join(md, "metrics", "metrics-*.jsonl"))
    assert len(files) == 1
    lines = [json.loads(l) for l in open(files[0])]
    # one line per summary step plus the end-of-run flush
    assert len(lines) >= MAX_STEPS
    assert lines[-1]["step"] == MAX_STEPS
    assert lines[-1]["metrics"]["train/step/count"] == MAX_STEPS
    assert "goodput/goodput" in lines[-1]["metrics"]


def test_supervised_sigterm_run_exposes_resilience_and_goodput(tmp_path):
    _reset_run_metrics()
    faults = StepFaults({7: SignalFault(signal.SIGTERM)})
    sup = Supervisor(
        lambda: Estimator(
            model=PlainCNN(),
            optimizer=optax.sgd(0.1),
            strategy=MirroredStrategy(),
            config=RunConfig(
                model_dir=str(tmp_path / "run"),
                save_checkpoints_steps=4,
                save_summary_steps=10_000,
                log_step_count_steps=10_000,
            ),
        ),
        SupervisorConfig(
            max_restarts=3,
            resume_on_preemption=True,
            restart_policy=RetryPolicy(initial_backoff=0.01, jitter=0.0),
        ),
    )
    sup.run(faults.wrap_input_fn(constant_input_fn), 12)
    assert sup.restarts == 1

    srv = MetricsServer(port=0, host="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = r.read().decode()
        back = parse_prometheus_text(body)
        # training AND resilience AND goodput series on one surface
        assert back["tfde_train_step"]["count"] == 12
        assert back["tfde_resilience_restarts_total"]["value"] == 1.0
        assert back["tfde_resilience_failures_preemption_total"]["value"] == 1.0
        assert 0.0 < back["tfde_goodput_goodput"]["value"] < 1.0
        assert back["tfde_goodput_restart_loss_fraction"]["value"] > 0.0
    finally:
        srv.close()
