"""Continuous batching (inference/server.py): every request's greedy
output must equal its solo generate() run, no matter what shares the
batch, when it was admitted, or which recycled row it landed on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import GPT, gpt_tiny_test


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


# generate() is rolling-window and therefore always full-precision
# (int8 KV refuses rolling), so tests that pin a batcher bit-exact
# against this reference construct it with kv_quant="fp" — the
# TFDE_KV_QUANT=int8 tier-1 sweep would otherwise flip near-tie
# argmaxes (int8 parity is statistical, tests/test_kv_quant.py).
def _solo(model, params, prompt, n, **kw):
    toks, lengths = generate(
        model, params, jnp.asarray(prompt[None, :], jnp.int32),
        max_new_tokens=n, **kw,
    )
    p = prompt.size
    return np.asarray(toks)[0, p : int(lengths[0])]


@pytest.mark.slow
def test_batch_of_varied_requests_matches_solo(lm, rng):
    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=3, max_len=48)
    reqs = {}
    for i, (plen, n) in enumerate([(3, 9), (5, 4), (2, 12), (7, 7), (4, 1),
                                   (6, 10), (3, 3)]):
        prompt = rng.integers(0, 97, plen).astype(np.int64)
        rid = srv.submit(prompt, max_new_tokens=n)
        reqs[rid] = (prompt, n)
    done = dict(srv.run())
    assert srv.idle
    assert set(done) == set(reqs)
    for rid, (prompt, n) in reqs.items():
        np.testing.assert_array_equal(
            done[rid], _solo(model, params, prompt, n), err_msg=f"req {rid}"
        )


def test_staggered_submission_mid_flight(lm, rng):
    """Requests submitted while others are mid-generation take freed rows
    and still match solo runs — the continuous part of the batching."""
    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48)
    p0 = rng.integers(0, 97, 4).astype(np.int64)
    p1 = rng.integers(0, 97, 3).astype(np.int64)
    r0 = srv.submit(p0, max_new_tokens=3)   # finishes quickly
    r1 = srv.submit(p1, max_new_tokens=10)  # keeps running
    done = {}
    for _ in range(3):
        done.update(srv.step())
    assert r0 in done  # the short request already finished
    p2 = rng.integers(0, 97, 5).astype(np.int64)  # lands in r0's old row
    r2 = srv.submit(p2, max_new_tokens=6)
    done.update(srv.run())
    assert set(done) == {r0, r1, r2}
    np.testing.assert_array_equal(done[r0], _solo(model, params, p0, 3))
    np.testing.assert_array_equal(done[r1], _solo(model, params, p1, 10))
    np.testing.assert_array_equal(done[r2], _solo(model, params, p2, 6))


def test_eos_and_instant_finish(lm, rng):
    model, params = lm
    prompt = rng.integers(0, 97, 4).astype(np.int64)
    free = _solo(model, params, prompt, 10)
    eos = int(free[2])  # third generated token
    ref = _solo(model, params, prompt, 10, eos_id=eos, pad_id=0)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48,
                            eos_id=eos)
    rid = srv.submit(prompt, max_new_tokens=10)
    one = srv.submit(prompt, max_new_tokens=1)  # budget-1: first token only
    done = dict(srv.run())
    np.testing.assert_array_equal(done[rid], ref)
    np.testing.assert_array_equal(done[one], free[:1])


@pytest.mark.slow
def test_rope_gqa_model(rng):
    m = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=64, dtype=jnp.float32, position="rope",
            num_kv_heads=2)
    params = m.init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    srv = ContinuousBatcher(m, params, kv_quant="fp", batch_size=2, max_len=40)
    prompts = [rng.integers(0, 97, p).astype(np.int64) for p in (3, 5, 4)]
    rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
    done = dict(srv.run())
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(done[rid], _solo(m, params, p, 6))


def test_queue_longer_than_batch_and_validation(lm, rng):
    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=1, max_len=32)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(np.zeros(30, np.int64), max_new_tokens=10)
    with pytest.raises(ValueError, match="at least one"):
        srv.submit(np.zeros(0, np.int64), max_new_tokens=4)
    rids = [srv.submit(rng.integers(0, 97, 3).astype(np.int64), 4)
            for _ in range(5)]
    done = dict(srv.run())
    assert set(done) == set(rids)
    assert all(len(v) == 4 for v in done.values())


# --------------------------------------------------------------------------
# SpeculativeContinuousBatcher: draft-accelerated continuous serving
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def draft():
    m = GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2, mlp_dim=32,
            max_position=64, dtype=jnp.float32)
    params = m.init(jax.random.key(9), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


@pytest.mark.slow
def test_speculative_batcher_matches_solo(lm, draft, rng):
    from tfde_tpu.inference.server import SpeculativeContinuousBatcher

    model, params = lm
    dmodel, dparams = draft
    srv = SpeculativeContinuousBatcher(
        model, dmodel, params, dparams, batch_size=2, max_len=40,
        num_draft=3,
    )
    reqs = {}
    for plen, n in [(3, 8), (5, 5), (2, 11), (6, 4), (4, 9)]:
        prompt = rng.integers(0, 97, plen).astype(np.int64)
        reqs[srv.submit(prompt, max_new_tokens=n)] = (prompt, n)
    done = dict(srv.run())
    assert srv.idle
    assert set(done) == set(reqs)
    for rid, (prompt, n) in reqs.items():
        np.testing.assert_array_equal(
            done[rid], _solo(model, params, prompt, n), err_msg=f"req {rid}"
        )
    assert srv.stats()["generated"] == sum(n for _, n in reqs.values())
    assert srv.stats()["rounds"] > 0


def test_speculative_batcher_perfect_draft_accelerates(lm, rng):
    """Draft == target: every proposal accepted — tokens/round approaches
    num_draft+1, the speedup the batcher exists for."""
    from tfde_tpu.inference.server import SpeculativeContinuousBatcher

    model, params = lm
    srv = SpeculativeContinuousBatcher(
        model, model, params, params, batch_size=2, max_len=48, num_draft=3,
    )
    prompts = [rng.integers(0, 97, 4).astype(np.int64) for _ in range(2)]
    rids = [srv.submit(p, max_new_tokens=12) for p in prompts]
    done = dict(srv.run())
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(done[rid], _solo(model, params, p, 12))
    assert srv.stats()["tokens_per_round"] > 2.0, srv.stats()
    # a perfect draft is accepted except where max_new truncation discards
    # the round's tail, and the stats ride the registry (the /metrics
    # export path) as serving/speculative/* gauges
    assert srv.stats()["acceptance_rate"] > 0.8
    from tfde_tpu.observability import metrics

    reg = metrics.default_registry()
    assert (reg.get("serving/speculative/acceptance_rate").value
            == pytest.approx(srv.stats()["acceptance_rate"]))
    assert (reg.get("serving/speculative/generated").value
            == srv.stats()["generated"])


def test_speculative_batcher_eos_and_staggering(lm, draft, rng):
    from tfde_tpu.inference.server import SpeculativeContinuousBatcher

    model, params = lm
    dmodel, dparams = draft
    p0 = rng.integers(0, 97, 4).astype(np.int64)
    free = _solo(model, params, p0, 10)
    eos = int(free[3])
    ref = _solo(model, params, p0, 10, eos_id=eos, pad_id=0)
    srv = SpeculativeContinuousBatcher(
        model, dmodel, params, dparams, batch_size=1, max_len=40,
        num_draft=4, eos_id=eos,
    )
    r0 = srv.submit(p0, max_new_tokens=10)
    # second request queued behind the first on the single row
    p1 = rng.integers(0, 97, 3).astype(np.int64)
    r1 = srv.submit(p1, max_new_tokens=5)
    done = dict(srv.run())
    np.testing.assert_array_equal(done[r0], ref)
    np.testing.assert_array_equal(
        done[r1], _solo(model, params, p1, 5, eos_id=eos, pad_id=0)
    )


def test_speculative_batcher_sampled_mode(lm, draft, rng):
    """temperature > 0: the sampled rounds drain the queue, outputs are
    reproducible per rng, and budgets/EOS hold per row."""
    from tfde_tpu.inference.server import SpeculativeContinuousBatcher

    model, params = lm
    dmodel, dparams = draft

    def serve(key):
        srv = SpeculativeContinuousBatcher(
            model, dmodel, params, dparams, batch_size=2, max_len=40,
            num_draft=3, temperature=0.8, rng=jax.random.key(key),
        )
        prompts = [rng.integers(0, 97, p).astype(np.int64)
                   for p in (3, 5, 4)]
        # rng fixture advances between calls; pin prompts instead
        prompts = [np.asarray([7, 11, 2]), np.asarray([3, 1, 4, 1, 5]),
                   np.asarray([9, 2, 6, 5])]
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        return {r: tuple(v.tolist()) for r, v in dict(srv.run()).items()}

    a, b, c = serve(11), serve(11), serve(12)
    assert a == b          # deterministic per key
    assert a != c          # key moves the draws
    assert all(len(v) == 6 for v in a.values())


def test_speculative_batcher_rope_gqa(rng):
    """Per-row spec rounds + admission compose with rotary positions and
    grouped-query caches."""
    from tfde_tpu.inference.server import SpeculativeContinuousBatcher

    m = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=64, dtype=jnp.float32, position="rope",
            num_kv_heads=2)
    params = m.init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    d = GPT(vocab_size=97, hidden_size=16, depth=1, num_heads=2, mlp_dim=32,
            max_position=64, dtype=jnp.float32)
    dparams = d.init(jax.random.key(9), jnp.zeros((1, 8), jnp.int32))["params"]
    srv = SpeculativeContinuousBatcher(m, d, params, dparams, batch_size=2,
                                       max_len=36, num_draft=3)
    prompts = [rng.integers(0, 97, p).astype(np.int64) for p in (3, 5, 4)]
    rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
    done = dict(srv.run())
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(done[rid], _solo(m, params, p, 6))


def test_prompt_buckets():
    """Bucket arithmetic: defaults are powers of two capped by max_len;
    prompts pad to the smallest fitting bucket with logits read at the
    true last position."""
    from tfde_tpu.inference.server import _bucketed, _normalize_buckets

    assert _normalize_buckets(None, 100) == (8, 16, 32, 64, 100)
    assert _normalize_buckets((32, 8, 64), 64) == (8, 32, 64)
    # oversized buckets clamp to max_len (a larger bucket would overflow
    # the row cache at admission time)
    assert _normalize_buckets((16, 128), 64) == (16, 64)
    with pytest.raises(ValueError, match="cover max_len"):
        _normalize_buckets((8, 16), 64)
    ids, last = _bucketed(np.asarray([5, 6, 7]), (8, 16), pad_id=0)
    assert ids.shape == (1, 8) and last == 2
    assert ids[0, :3].tolist() == [5, 6, 7]
    assert ids[0, 3:].tolist() == [0] * 5
    ids, last = _bucketed(np.arange(9), (8, 16), pad_id=0)
    assert ids.shape == (1, 16) and last == 8


# --------------------------------------------------------------------------
# Device-resident loop: K-step scan parity, adaptive depth, host-cost bound
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_scan_depth_staggered_parity_sweep(lm, rng):
    """Greedy outputs stay bit-identical to solo generate() across scan
    depths with requests admitted mid-flight — the fused K-tick scan must
    freeze finishing rows and admit into their place without perturbing
    the surviving rows' streams."""
    model, params = lm
    reqs = [(rng.integers(0, 97, plen).astype(np.int64), n)
            for plen, n in [(3, 9), (5, 4), (2, 12), (7, 1), (4, 7)]]
    refs = [_solo(model, params, p, n) for p, n in reqs]
    for depth in (1, 2, 4):
        srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48,
                                scan_depth=depth)
        rids = [srv.submit(p, max_new_tokens=n) for p, n in reqs[:3]]
        done = dict(srv.step())  # late arrivals land on recycled rows
        rids += [srv.submit(p, max_new_tokens=n) for p, n in reqs[3:]]
        done.update(srv.run())
        assert srv.idle
        for rid, ref in zip(rids, refs):
            np.testing.assert_array_equal(
                done[rid], ref, err_msg=f"depth {depth} req {rid}"
            )


def test_eos_mid_scan(lm, rng):
    """An EOS landing in the middle of a K-tick scan must freeze the row
    on device: no post-EOS tokens leak out, and the emitted stream equals
    the solo run's."""
    model, params = lm
    prompt = rng.integers(0, 97, 4).astype(np.int64)
    free = _solo(model, params, prompt, 12)
    # EOS on the 4th generated token: admission emits token 1, the first
    # depth-4 scan hits EOS on its 3rd tick — strictly mid-scan
    eos = int(free[3])
    ref = _solo(model, params, prompt, 12, eos_id=eos, pad_id=0)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48,
                            eos_id=eos, scan_depth=4)
    rid = srv.submit(prompt, max_new_tokens=12)
    done = dict(srv.run())
    np.testing.assert_array_equal(done[rid], ref)
    # EOS truncated the stream (possibly even earlier than free[3] when
    # the greedy stream repeats that id) and the EOS token itself is kept
    assert len(done[rid]) < 12
    assert int(done[rid][-1]) == eos


def test_budget_one_admitted_mid_flight(lm, rng):
    """A budget-1 request queued behind a full batch finishes AT admission
    (its only token samples inside the prefill program) the moment a row
    frees mid-flight, without touching the surviving rows' parity."""
    model, params = lm
    p_long = rng.integers(0, 97, 3).astype(np.int64)
    p_short = rng.integers(0, 97, 5).astype(np.int64)
    p_one = rng.integers(0, 97, 4).astype(np.int64)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=48,
                            scan_depth=2)
    r_long = srv.submit(p_long, max_new_tokens=12)
    r_short = srv.submit(p_short, max_new_tokens=3)
    done = dict(srv.step())  # both admitted, batch full
    r_one = srv.submit(p_one, max_new_tokens=1)  # queues behind them
    done.update(srv.run())
    assert set(done) == {r_long, r_short, r_one}
    np.testing.assert_array_equal(done[r_one], _solo(model, params, p_one, 1))
    np.testing.assert_array_equal(
        done[r_long], _solo(model, params, p_long, 12)
    )
    np.testing.assert_array_equal(
        done[r_short], _solo(model, params, p_short, 3)
    )


def test_ladder_depth():
    """Adaptive K picks from the power-of-two ladder {1, 2, 4, ..., cap}
    (cap included), never exceeding the completion bound — the compile-
    count/admission-latency compromise."""
    from tfde_tpu.inference.server import _ladder_depth

    assert _ladder_depth(4, 9) == 4    # bound beyond cap: full depth
    assert _ladder_depth(4, 4) == 4
    assert _ladder_depth(4, 3) == 2    # shrink toward the completion
    assert _ladder_depth(4, 1) == 1
    assert _ladder_depth(4, 0) == 1    # degenerate bounds clamp to 1
    assert _ladder_depth(1, 99) == 1
    assert _ladder_depth(8, 6) == 4
    assert _ladder_depth(6, 5) == 4    # non-power cap still ladders below


def test_steady_state_host_cost_bound(lm, rng, monkeypatch):
    """Regression guard for the device-resident loop: in steady state
    (full batch, empty queue) one step of the depth-K scan costs ONE
    jitted dispatch and ONE host sync for K tokens per row — so
    dispatches + syncs per generated token must stay <= 2/K, where the
    old per-token loop paid >= 3. Host syncs are counted by intercepting
    the module's single fetch seam, so a stray np.asarray() on a device
    array elsewhere in the loop would show up as a count mismatch."""
    import tfde_tpu.inference.server as server_mod

    model, params = lm
    depth = 4
    fetches = {"n": 0}
    real_fetch = server_mod._fetch

    def counting_fetch(tree):
        fetches["n"] += 1
        return real_fetch(tree)

    monkeypatch.setattr(server_mod, "_fetch", counting_fetch)
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=96,
                            scan_depth=depth)
    for _ in range(2):
        srv.submit(rng.integers(0, 97, 4).astype(np.int64),
                   max_new_tokens=60)
    srv.step()  # admission + first scan: compile + upload, not steady state
    before = srv.stats()
    f0 = fetches["n"]
    steps = 4
    for _ in range(steps):
        srv.step()
    after = srv.stats()
    d_disp = after["dispatches"] - before["dispatches"]
    d_sync = after["syncs"] - before["syncs"]
    d_tok = after["generated"] - before["generated"]
    assert d_tok == steps * depth * 2  # 2 rows x K tokens per step
    # the monkeypatched seam agrees with the batcher's own accounting
    assert fetches["n"] - f0 == d_sync == steps
    assert d_disp == steps  # ONE jitted call per step, state stays resident
    assert (d_disp + d_sync) / d_tok <= 2.0 / depth
    # and the published per-token stats reflect the amortization
    assert after["syncs_per_token"] < 1.0


def test_prefill_buffers_are_donated(lm):
    """The admission prefills must alias the freshly-allocated row cache
    into their output (donate_argnums) so a wave's scratch K/V is not
    double-resident. Pin the `tf.aliasing_output` markers in the lowered
    StableHLO for BOTH the cold path (`_prefill_rows`) and the warm
    suffix path (`_prefill_suffix`) — a dropped donation shows up here
    before it shows up as an HBM regression."""
    import tfde_tpu.inference.server as server_mod
    from tfde_tpu.inference.prefix_cache import is_index_leaf, leaf_name

    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=64)
    tpl = srv._row_template(1)
    low = server_mod._prefill_rows.lower(
        srv._decode_model, tpl, params, jnp.zeros((1, 8), jnp.int32),
        jnp.zeros((1,), jnp.int32), None, None, temperature=0.0,
        top_k=None, top_p=None, min_p=None, repetition_penalty=1.0,
    )
    assert low.as_text().count("tf.aliasing_output") >= 2

    tpl = srv._row_template(1)
    prefix_kv = {
        leaf_name(p): jnp.zeros((1, 4) + leaf.shape[2:], leaf.dtype)
        for p, leaf in jax.tree_util.tree_leaves_with_path(tpl)
        if not is_index_leaf(p)
    }
    low = server_mod._prefill_suffix.lower(
        srv._decode_model, tpl, params, prefix_kv,
        jnp.zeros((1, 4), jnp.int32), jnp.zeros((1,), jnp.int32),
        None, None, None, temperature=0.0, top_k=None, top_p=None,
        min_p=None, repetition_penalty=1.0,
    )
    assert low.as_text().count("tf.aliasing_output") >= 2


def test_role_split_primed_handoff_parity(lm, rng):
    """Disaggregated prefill: a prefill-role batcher primes prompts, a
    decode-role batcher scatters the shipped K/V and streams — primed
    requests must match solo bit for bit, and may mix in one wave with
    plainly-submitted ones."""
    model, params = lm
    prompts = [rng.integers(1, 90, k).astype(np.int64) for k in (3, 7, 5, 4)]
    pre = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=64,
                            role="prefill")
    dec = ContinuousBatcher(model, params, kv_quant="fp", batch_size=4, max_len=64,
                            role="decode")
    primed = [pre.prime(p, 8) for p in prompts[:3]]
    rids = [dec.submit_primed(pr) for pr in primed]
    rid_plain = dec.submit(prompts[3], 8)
    done = dict(dec.run())
    for rid, p in zip(rids + [rid_plain], prompts):
        np.testing.assert_array_equal(done[rid], _solo(model, params, p, 8))
    # role guards: each half of the split rejects the other's entry point
    with pytest.raises(RuntimeError):
        pre.submit(prompts[0], 4)
    with pytest.raises(RuntimeError):
        dec.prime(prompts[0], 4)


def test_progress_streaming_matches_final_output(lm, rng):
    """take_progress chunks, concatenated, must equal the request's final
    output — the SSE streaming surface (router.py) rides on this."""
    model, params = lm
    p = rng.integers(1, 90, 5).astype(np.int64)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64)
    srv.enable_progress()
    rid = srv.submit(p, 6)
    got, done = [], False
    while not srv.idle:
        srv.step()
        if not done:
            toks, done = srv.take_progress(rid)
            got.extend(int(t) for t in toks)
    assert done
    np.testing.assert_array_equal(
        np.asarray(got, np.int32), _solo(model, params, p, 6)
    )


def test_batcher_repetition_penalty_no_repeats(rng):
    """repetition_penalty at extreme strength: every token a request emits
    is distinct from its prompt and its own prior output, across
    admission recycling — the presence mask resets per row."""
    model = gpt_tiny_test()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=48,
                            repetition_penalty=1e9)
    prompts = {}
    for i in range(5):
        p = rng.integers(0, model.vocab_size, int(rng.integers(2, 6)))
        rid = srv.submit(p, 8)
        prompts[rid] = list(p)
    done = srv.run()
    assert len(done) == 5
    for rid, toks in done:
        emitted = list(prompts[rid])
        for t in toks:
            assert t not in emitted, (rid, t, emitted)
            emitted.append(int(t))


def test_cancel_frees_row_and_queue(lm, rng):
    """cancel() abandons a request whose consumer is gone (router client
    disconnect): queued entries drop, active rows free so the decode
    scan stops spending ticks on them, and the progress entry never
    leaks. The recycled row must then serve fresh work bit-identically."""
    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=64)
    srv.enable_progress()
    p = rng.integers(1, 90, 5).astype(np.int64)
    active = srv.submit(p, 40)
    queued = srv.submit(p, 6)
    srv.step()                       # admits `active`; `queued` waits
    assert srv.free_rows == 0 and len(srv._queue) == 1
    assert srv.cancel(queued)
    assert queued not in srv._stream and len(srv._queue) == 0
    assert srv.cancel(active)
    assert active not in srv._stream
    assert srv.free_rows == 1 and srv.idle
    assert not srv.cancel(active)    # already gone
    rid = srv.submit(p, 6)
    done = dict(srv.run())
    np.testing.assert_array_equal(done[rid], _solo(model, params, p, 6))


# --------------------------------------------------------------------------
# Admission control: caps, priority classes, deadline shedding (PR 14)
# --------------------------------------------------------------------------

def test_admission_depth_cap_rejects_with_queue_full(lm, rng):
    """max_queue bounds QUEUED requests: the overflow submit raises a
    typed QueueFull carrying depth + drain estimate, and everything that
    WAS admitted still decodes bit-identical to solo."""
    from tfde_tpu.inference.admission import (
        AdmissionController, QueueFull, MIN_RETRY_AFTER_S,
    )

    model, params = lm
    srv = ContinuousBatcher(
        model, params, kv_quant="fp", batch_size=1, max_len=48,
        admission_ctl=AdmissionController(max_queue=1),
    )
    p = rng.integers(1, 90, 4).astype(np.int64)
    admitted = srv.submit(p, 6)        # queue depth 0 -> in
    with pytest.raises(QueueFull) as ei:
        srv.submit(p, 6)               # queue depth 1 >= cap
    e = ei.value
    assert e.reason == "queue_depth"
    assert e.queue_depth == 1 and e.queued_tokens == 6
    assert e.retry_after_s >= MIN_RETRY_AFTER_S
    # QueueFull is a RuntimeError: overload-unaware callers stay correct
    assert isinstance(e, RuntimeError)
    body = e.as_json()
    assert set(body) == {"error", "reason", "queue_depth",
                         "queued_tokens", "retry_after_s"}
    done = dict(srv.run())
    np.testing.assert_array_equal(done[admitted],
                                  _solo(model, params, p, 6))
    # the queue drained: the same submit is admitted now
    rid = srv.submit(p, 4)
    np.testing.assert_array_equal(dict(srv.run())[rid],
                                  _solo(model, params, p, 4))


def test_admission_token_budget_cap(lm, rng):
    """max_queued_tokens bounds the queued OUTPUT-token backlog — the
    unit the drain rate is measured in, so the Retry-After estimate
    derived from it is honest."""
    from tfde_tpu.inference.admission import AdmissionController, QueueFull

    model, params = lm
    srv = ContinuousBatcher(
        model, params, batch_size=1, max_len=48,
        admission_ctl=AdmissionController(max_queued_tokens=10),
    )
    p = rng.integers(1, 90, 3).astype(np.int64)
    srv.submit(p, 8)                   # backlog 8 <= 10
    with pytest.raises(QueueFull) as ei:
        srv.submit(p, 8)               # 8 + 8 > 10
    assert ei.value.reason == "queued_tokens"
    srv.submit(p, 2)                   # 8 + 2 == 10: exactly at cap is in
    done = dict(srv.run())
    assert len(done) == 2


def test_priority_ordered_dequeue(lm, rng):
    """The queue drains interactive > batch > best_effort regardless of
    submission order (FIFO within a class), and every admitted request
    still matches its solo run."""
    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=1, max_len=48)
    p = rng.integers(1, 90, 4).astype(np.int64)
    blocker = srv.submit(p, 8)
    srv.step()                         # blocker occupies the single row
    r_be = srv.submit(p, 3, priority="best_effort")
    r_ba = srv.submit(p, 3, priority="batch")
    r_in = srv.submit(p, 3)            # unlabeled == interactive
    assert srv._queue.depths() == {
        "interactive": 1, "batch": 1, "best_effort": 1}
    order = []
    while not srv.idle:
        for rid, _toks in srv.step():
            order.append(rid)
    assert order == [blocker, r_in, r_ba, r_be]
    # parity rode along: re-run one of each against solo
    srv2 = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=48)
    rid = srv2.submit(p, 3, priority="best_effort")
    np.testing.assert_array_equal(dict(srv2.run())[rid],
                                  _solo(model, params, p, 3))


def test_expired_deadline_shed_before_prefill(lm, rng):
    """A queued request whose wait already blew its TTFT deadline is
    dropped AT DEQUEUE — no prefill is spent on it, was_shed() answers
    exactly once, and the shed counters tick."""
    import time as _time

    from tfde_tpu.observability import metrics

    model, params = lm
    reg = metrics.default_registry()
    reg.reset("serving/shed")
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=48)
    srv.enable_progress()
    p = rng.integers(1, 90, 4).astype(np.int64)
    blocker = srv.submit(p, 6)
    doomed = srv.submit(p, 5, priority="batch", ttft_deadline_ms=1.0)
    _time.sleep(0.01)                  # the deadline expires in queue
    done = dict(srv.run())
    assert blocker in done and doomed not in done
    np.testing.assert_array_equal(done[blocker],
                                  _solo(model, params, p, 6))
    toks, fin = srv.take_progress(doomed)
    assert toks == [] and fin is True
    assert srv.was_shed(doomed) is True
    assert srv.was_shed(doomed) is False   # answers once
    assert reg.get("serving/shed_expired").value == 1
    assert reg.get("serving/shed_batch").value == 1
    assert reg.get("serving/shed_tokens").value == 5
    assert srv.idle and not srv._deadline_at and not srv._priority


def test_forced_overload_fault_rejects_then_recovers(lm, rng):
    """resilience.OverloadFault arms the module-wide saturation lever:
    while armed every submit is rejected as forced_overload; after
    clear_overload the same batcher admits again."""
    from tfde_tpu.inference import admission
    from tfde_tpu.inference.admission import QueueFull
    from tfde_tpu.resilience.faults import OverloadFault

    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=48)
    p = rng.integers(1, 90, 3).astype(np.int64)
    OverloadFault(seconds=30.0).fire("test")
    try:
        with pytest.raises(QueueFull) as ei:
            srv.submit(p, 4)
        assert ei.value.reason == "forced_overload"
    finally:
        admission.clear_overload()
    rid = srv.submit(p, 4)
    np.testing.assert_array_equal(dict(srv.run())[rid],
                                  _solo(model, params, p, 4))


def test_unknown_priority_rejected_loudly(lm, rng):
    """A typo'd priority class must raise, not silently become
    best_effort (which would get it brownout-shed in production)."""
    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=1, max_len=48)
    p = rng.integers(1, 90, 3).astype(np.int64)
    with pytest.raises(ValueError, match="priority"):
        srv.submit(p, 4, priority="urgent")
    assert len(srv._queue) == 0


# --------------------------------------------------------------------------
# KV-headroom admission: reject on memory before queue depth collapses
# --------------------------------------------------------------------------

def test_kv_headroom_gate_rejects_with_kv_payload(lm, rng):
    """min_headroom_rows armed: once the slab's free rows fall below the
    floor the submit is rejected as kv_headroom, the QueueFull carries
    the ledger's kv block, and Retry-After falls back to the drain-rate
    estimate over the OUTSTANDING tokens (the queue is empty — queued
    backlog alone would undersell the wait). Draining restores
    admission; everything admitted still matches solo."""
    from tfde_tpu.inference.admission import (
        AdmissionController, QueueFull, MIN_RETRY_AFTER_S,
    )

    model, params = lm
    srv = ContinuousBatcher(
        model, params, kv_quant="fp", batch_size=2, max_len=48,
        admission_ctl=AdmissionController(min_headroom_rows=2),
    )
    p = rng.integers(1, 90, 4).astype(np.int64)
    admitted = srv.submit(p, 6)        # 2 free rows == floor: in
    srv.step()                         # admitted to a row: 1 free < 2
    with pytest.raises(QueueFull) as ei:
        srv.submit(p, 6)
    e = ei.value
    assert e.reason == "kv_headroom"
    assert e.kv is not None
    assert e.kv["headroom_rows"] == 1 and e.kv["rows_active"] == 1
    assert e.kv["used_bytes"] > 0
    body = e.as_json()
    assert body["reason"] == "kv_headroom"
    assert body["kv"]["headroom_rows"] == 1
    assert e.retry_after_s >= MIN_RETRY_AFTER_S
    done = dict(srv.run())
    np.testing.assert_array_equal(done[admitted],
                                  _solo(model, params, p, 6))
    rid = srv.submit(p, 4)             # slab drained: admitted again
    np.testing.assert_array_equal(dict(srv.run())[rid],
                                  _solo(model, params, p, 4))


def test_kv_headroom_env_knob_and_low_budget_drill(lm, rng, monkeypatch):
    """The forced low-budget drill: TFDE_ADMIT_KV_HEADROOM armed via env
    with a TFDE_CAPACITY_BUDGET_BYTES far below one row's cost — every
    submit 429s with the kv payload showing zero headroom BEFORE any
    request can stall waiting on a row that memory could never back."""
    from tfde_tpu.inference.admission import QueueFull

    monkeypatch.setenv("TFDE_ADMIT_KV_HEADROOM", "1")
    monkeypatch.setenv("TFDE_CAPACITY_BUDGET_BYTES", "64")
    model, params = lm
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=48)
    assert srv._cap_model.budget_bytes == 64
    assert srv._ledger.row_bytes > 64   # the budget can't back one row
    p = rng.integers(1, 90, 4).astype(np.int64)
    with pytest.raises(QueueFull) as ei:
        srv.submit(p, 6)               # rejected with all rows still free
    e = ei.value
    assert e.reason == "kv_headroom"
    assert e.kv["headroom_rows"] == 0 and e.kv["rows_free"] == 2
    assert len(srv._queue) == 0 and srv.idle


def test_kv_headroom_default_off_admits_identically(lm, rng, monkeypatch):
    """Default-off parity: with the knob unset the gate never consults
    the ledger, and a full batch plus a deep queue admits exactly as
    before this PR — memory pressure alone must not reject."""
    monkeypatch.delenv("TFDE_ADMIT_KV_HEADROOM", raising=False)
    model, params = lm
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=48)
    assert srv._admission.min_headroom_rows == 0
    assert not srv._admission.enabled
    p = rng.integers(1, 90, 4).astype(np.int64)
    rids = [srv.submit(p, 4) for _ in range(4)]  # 1 row, 3 queued: all in
    done = dict(srv.run())
    assert set(done) == set(rids)
    for rid in rids:
        np.testing.assert_array_equal(done[rid],
                                      _solo(model, params, p, 4))
