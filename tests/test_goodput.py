"""Goodput-ledger tests (ISSUE 2): synthetic classification arithmetic on a
private registry, then the acceptance schedule — a supervised run with an
injected SIGTERM restart must report fractions that sum to ~1.0 and a
goodput fraction demonstrably below the uninterrupted run's.

The e2e tests reuse the supervisor-test methodology (ONE constant batch,
deterministic CPU mesh, in-process resume_on_preemption restarts); they
run real multi-attempt training so they are marked `slow` (tier-2), per
the tier-1 budget rule — tier-1 keeps the synthetic arithmetic here plus
the instrumented-run assertions in test_observability_e2e.py."""

import signal

import numpy as np
import optax
import pytest

from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import metrics, spans
from tfde_tpu.observability.goodput import CATEGORIES, GoodputLedger
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.resilience import (
    RaiseFault,
    RetryPolicy,
    SignalFault,
    StepFaults,
    Supervisor,
    SupervisorConfig,
)
from tfde_tpu.training.lifecycle import Estimator, RunConfig

MAX_STEPS = 12
SAVE_EVERY = 4

_rngd = np.random.default_rng(0)
IMAGES = _rngd.random((32, 784), np.float32)
LABELS = _rngd.integers(0, 10, (32, 1)).astype(np.int32)


def constant_input_fn():
    def gen():
        while True:
            yield (IMAGES, LABELS)

    return gen()


def make_factory(model_dir):
    def factory():
        return Estimator(
            model=PlainCNN(),
            optimizer=optax.sgd(0.1),
            strategy=MirroredStrategy(),
            config=RunConfig(
                model_dir=model_dir,
                save_checkpoints_steps=SAVE_EVERY,
                save_summary_steps=10_000,
                log_step_count_steps=10_000,
            ),
        )

    return factory


def fast_restart(**kw):
    kw.setdefault("restart_policy",
                  RetryPolicy(initial_backoff=0.01, jitter=0.0))
    return SupervisorConfig(**kw)


def _reset_run_metrics():
    reg = metrics.default_registry()
    for p in ("train/", "eval/", "checkpoint/", "resilience/", "goodput/"):
        reg.reset(p)


# -- synthetic arithmetic -----------------------------------------------------
def test_fractions_sum_to_one_and_categories_land():
    reg = metrics.Registry()
    led = GoodputLedger(registry=reg)
    spans.record("train/init", 1.0, registry=reg)
    spans.record("train/data_wait", 0.5, registry=reg)
    for _ in range(10):
        spans.record("train/step", 0.1, registry=reg)
    spans.record("train/device_sync", 0.2, registry=reg)
    spans.record("checkpoint/save", 0.3, registry=reg)
    spans.record("train/summary_write", 0.1, registry=reg)
    reg.counter("train/compile_seconds").incr(2.0)
    rep = led.report(wall_seconds=6.0)
    s = rep["seconds"]
    assert s["init"] == pytest.approx(1.0)
    assert s["data_wait"] == pytest.approx(0.5)
    assert s["compute"] == pytest.approx(1.2)  # step sum + device_sync
    assert s["checkpoint"] == pytest.approx(0.3)
    assert s["summary"] == pytest.approx(0.1)
    assert s["compile"] == pytest.approx(2.0)
    assert s["other"] == pytest.approx(6.0 - 5.1)
    assert rep["steps"] == 10
    assert rep["mean_step_seconds"] == pytest.approx(0.12)
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)
    assert set(rep["seconds"]) == set(CATEGORIES)
    assert rep["goodput"] == pytest.approx(1.2 / 6.0)


def test_restart_loss_consumes_resilience_counters():
    reg = metrics.Registry()
    led = GoodputLedger(registry=reg)
    for _ in range(10):
        spans.record("train/step", 0.1, registry=reg)
    reg.counter("resilience/lost_steps").incr(3)
    reg.counter("resilience/restart_backoff_seconds").incr(0.5)
    reg.counter("resilience/restarts").incr()
    rep = led.report(wall_seconds=2.0)
    # 3 replayed steps x 0.1s mean burn step-shaped time that trained nothing
    assert rep["lost_steps"] == 3
    assert rep["restarts"] == 1
    assert rep["seconds"]["restart_loss"] == pytest.approx(0.3 + 0.5)
    assert rep["seconds"]["compute"] == pytest.approx(1.0 - 0.3)
    assert rep["goodput"] == pytest.approx(0.7 / 2.0)
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)


def test_ledger_baseline_excludes_prior_history():
    reg = metrics.Registry()
    spans.record("train/step", 5.0, registry=reg)  # a previous run's steps
    led = GoodputLedger(registry=reg)
    spans.record("train/step", 0.2, registry=reg)
    rep = led.report(wall_seconds=1.0)
    assert rep["steps"] == 1
    assert rep["seconds"]["compute"] == pytest.approx(0.2)


def test_export_publishes_gauges():
    reg = metrics.Registry()
    led = GoodputLedger(registry=reg)
    spans.record("train/step", 0.4, registry=reg)
    rep = led.export(wall_seconds=1.0)
    assert reg.get("goodput/goodput").value == pytest.approx(rep["goodput"])
    assert reg.get("goodput/compute_fraction").value == pytest.approx(0.4)
    assert reg.get("goodput/wall_seconds").value == pytest.approx(1.0)


# -- the acceptance schedule --------------------------------------------------
def _goodput_gauges():
    reg = metrics.default_registry()
    rep = {c: reg.get(f"goodput/{c}_fraction").value for c in CATEGORIES}
    return rep, reg.get("goodput/goodput").value


@pytest.fixture(scope="module")
def clean_goodput(tmp_path_factory):
    """Goodput of an uninterrupted supervised run (the comparison bar)."""
    _reset_run_metrics()
    sup = Supervisor(make_factory(str(tmp_path_factory.mktemp("clean"))),
                     fast_restart())
    sup.run(constant_input_fn, MAX_STEPS)
    fracs, g = _goodput_gauges()
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
    assert g > 0.0
    return g


@pytest.mark.slow
def test_goodput_drops_under_sigterm_restart_schedule(tmp_path, clean_goodput):
    _reset_run_metrics()
    faults = StepFaults({7: SignalFault(signal.SIGTERM)})
    sup = Supervisor(
        make_factory(str(tmp_path / "run")),
        fast_restart(max_restarts=3, resume_on_preemption=True),
    )
    sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 1
    fracs, g = _goodput_gauges()
    # disjoint-by-construction: the breakdown still sums to the wall
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
    assert fracs["restart_loss"] > 0.0
    # the restarted attempt re-inits and re-compiles and sleeps the backoff;
    # all of that is wall that trained nothing, so goodput must fall well
    # below the uninterrupted run's
    assert g < clean_goodput * 0.9


@pytest.mark.slow
def test_lost_steps_become_replay_loss(tmp_path):
    """A transient failure between checkpoints loses committed-to-reached
    steps; the ledger prices them as restart_loss (mean-step replay)."""
    _reset_run_metrics()
    # dies at step 7, last commit at 4 -> ~3 steps replayed. The heartbeat
    # (armed via stall_timeout, never firing) tracks the reached step.
    faults = StepFaults({7: RaiseFault(exc_type=IOError, message="blip")})
    sup = Supervisor(
        make_factory(str(tmp_path / "run")),
        fast_restart(max_restarts=3, stall_timeout_secs=60.0),
    )
    sup.run(faults.wrap_input_fn(constant_input_fn), MAX_STEPS)
    assert sup.restarts == 1
    reg = metrics.default_registry()
    assert reg.get("resilience/lost_steps").value > 0
    fracs, _ = _goodput_gauges()
    assert fracs["restart_loss"] > 0.0
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
