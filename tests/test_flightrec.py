"""Flight recorder acceptance: ring eviction order, dump/load round-trip,
and the death hooks proven in real child processes (SIGTERM dump composing
with prior handlers; excepthook dump on an unhandled exception)."""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from tfde_tpu.observability import flightrec
from tfde_tpu.observability.flightrec import FlightRecorder


# -- ring semantics -----------------------------------------------------------
def test_ring_evicts_oldest_in_order():
    rec = FlightRecorder(capacity=4)
    for i in range(6):
        rec.record("e", i=i)
    evs = rec.events()
    assert len(evs) == 4
    assert [e["i"] for e in evs] == [2, 3, 4, 5]  # oldest two evicted
    assert all(e["kind"] == "e" for e in evs)
    assert all("ts" in e for e in evs)


def test_capacity_validated():
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_dump_load_roundtrip(tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.arm(str(tmp_path), install_handlers=False)
    rec.record("step", step=3, sps=1.5)
    rec.record("sentry_trip", flag=1, trip_step=3)
    path = rec.dump("roundtrip")
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path).startswith("flight_")
    evs = flightrec.load(path)
    kinds = [e["kind"] for e in evs]
    # armed + the two events + the trailing dump marker, in order
    assert kinds == ["armed", "step", "sentry_trip", "dump"]
    assert evs[1]["step"] == 3 and evs[1]["sps"] == 1.5
    assert evs[-1]["reason"] == "roundtrip"


def test_dump_unarmed_is_noop():
    rec = FlightRecorder()
    rec.record("x")
    assert rec.dump("nowhere") is None


def test_redump_replaces_whole_file(tmp_path):
    rec = FlightRecorder(capacity=16)
    rec.arm(str(tmp_path), install_handlers=False)
    rec.record("a")
    p1 = rec.dump("one")
    rec.record("b")
    p2 = rec.dump("two")
    assert p1 == p2
    evs = flightrec.load(p2)
    # one file, newest dump wins, both events present exactly once
    assert [e["kind"] for e in evs].count("a") == 1
    assert [e["kind"] for e in evs].count("b") == 1
    assert evs[-1] == {**evs[-1], "kind": "dump", "reason": "two"}


def test_load_tolerates_truncated_tail(tmp_path):
    p = tmp_path / "flight_0_1.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "kind": "ok"}) + "\n"
                 + '{"ts": 2.0, "kind": "trunc')  # crash mid-write
    evs = flightrec.load(str(p))
    assert [e["kind"] for e in evs] == ["ok"]


# -- death hooks in real processes -------------------------------------------
def _run_child(code: str, tmp_path, timeout=60):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(__file__))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    return subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=str(tmp_path),
        capture_output=True, text=True, timeout=timeout,
    )


def test_sigterm_dumps_then_dies_by_signal(tmp_path):
    """SIGTERM with no prior handler: the chained hook dumps the ring,
    restores SIG_DFL and re-raises — the process still dies BY SIGNAL
    (exit -SIGTERM), so schedulers observe the normal preemption exit."""
    code = textwrap.dedent(f"""
        import os, signal
        from tfde_tpu.observability import flightrec
        flightrec.arm({str(tmp_path)!r})
        flightrec.record("work", step=7)
        os.kill(os.getpid(), signal.SIGTERM)
        raise SystemExit("signal did not kill us")
    """)
    res = _run_child(code, tmp_path)
    assert res.returncode == -signal.SIGTERM, (res.returncode, res.stderr)
    files = [f for f in os.listdir(tmp_path / "debug")
             if f.startswith("flight_")]
    assert len(files) == 1
    evs = flightrec.load(str(tmp_path / "debug" / files[0]))
    kinds = [e["kind"] for e in evs]
    assert "work" in kinds and "sigterm" in kinds
    assert kinds[-1] == "dump"


def test_sigterm_chains_to_prior_handler(tmp_path):
    """A handler installed BEFORE arming still runs after the dump — the
    recorder must compose with the preemption guard's save path, not
    replace it."""
    code = textwrap.dedent(f"""
        import os, signal, sys
        fired = []
        signal.signal(signal.SIGTERM, lambda s, f: fired.append(s))
        from tfde_tpu.observability import flightrec
        flightrec.arm({str(tmp_path)!r})
        os.kill(os.getpid(), signal.SIGTERM)
        assert fired == [signal.SIGTERM], fired
        print("chained")
    """)
    res = _run_child(code, tmp_path)
    assert res.returncode == 0, res.stderr
    assert "chained" in res.stdout


def test_unhandled_exception_dumps(tmp_path):
    code = textwrap.dedent(f"""
        from tfde_tpu.observability import flightrec
        flightrec.arm({str(tmp_path)!r})
        flightrec.record("about_to_die")
        raise RuntimeError("boom")
    """)
    res = _run_child(code, tmp_path)
    assert res.returncode == 1
    assert "RuntimeError: boom" in res.stderr  # traceback still printed
    files = os.listdir(tmp_path / "debug")
    assert len(files) == 1
    evs = flightrec.load(str(tmp_path / "debug" / files[0]))
    kinds = [e["kind"] for e in evs]
    assert "about_to_die" in kinds and "unhandled_exception" in kinds
    err = next(e for e in evs if e["kind"] == "unhandled_exception")
    assert "boom" in err["error"]


def test_default_recorder_module_api(tmp_path):
    rec = flightrec.default_recorder()
    flightrec.record("module_level_probe", n=1)
    assert any(e["kind"] == "module_level_probe" for e in rec.events())
