"""Streaming TFRecord input (data/streaming.py): file-backed shuffle/
repeat/batch with bounded memory — the tf.data `TFRecordDataset ->
shuffle -> batch -> prefetch` composition (SURVEY.md §2b row 3)."""

import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.data.streaming import StreamingTFRecordLoader, shard_files
from tfde_tpu.data.tfrecord import write_tfrecord


def _write_shards(tmp_path, n_files, rows_per_file, dim=4):
    """Each record: <i32 id><dim f32 features deterministic in id>."""
    paths = []
    rid = 0
    for f in range(n_files):
        recs = []
        for _ in range(rows_per_file):
            feat = (np.arange(dim, dtype=np.float32) + rid).tobytes()
            recs.append(struct.pack("<i", rid) + feat)
            rid += 1
        p = str(tmp_path / f"part-{f:03d}.tfrecord")
        write_tfrecord(p, recs)
        paths.append(p)
    return paths, rid


def _parse(dim=4):
    def parse(rec):
        (i,) = struct.unpack("<i", rec[:4])
        feat = np.frombuffer(rec[4:], np.float32)
        return np.int32(i), feat

    return parse


def test_one_epoch_exact_multiset(tmp_path):
    paths, n = _write_shards(tmp_path, 3, 40)
    loader = StreamingTFRecordLoader(
        paths, _parse(), batch_size=16, window=32, seed=1, repeat=1
    )
    ids, feats = [], []
    for i, f in loader:
        ids.extend(i.tolist())
        feats.append(f.copy())
    assert sorted(ids) == list(range(n))
    # features stay paired with their ids through the shuffle
    feats = np.concatenate(feats)
    for row_id, row in zip(ids, feats):
        np.testing.assert_array_equal(
            row, np.arange(4, dtype=np.float32) + row_id
        )


def test_final_partial_batch_and_drop_remainder(tmp_path):
    paths, n = _write_shards(tmp_path, 1, 37)
    kept = list(
        StreamingTFRecordLoader(paths, _parse(), batch_size=8, window=16,
                                repeat=1)
    )
    assert sum(b[0].shape[0] for b in kept) == 37
    assert kept[-1][0].shape[0] == 37 % 8
    dropped = list(
        StreamingTFRecordLoader(paths, _parse(), batch_size=8, window=16,
                                repeat=1, drop_remainder=True)
    )
    assert all(b[0].shape[0] == 8 for b in dropped)
    assert sum(b[0].shape[0] for b in dropped) == 37 - 37 % 8


def test_shuffle_windowed_and_seeded(tmp_path):
    paths, n = _write_shards(tmp_path, 2, 64)
    run = lambda seed: [
        i for b in StreamingTFRecordLoader(
            paths, _parse(), batch_size=16, window=64, seed=seed, repeat=1
        ) for i in b[0].tolist()
    ]
    a, b, c = run(5), run(5), run(6)
    assert a == b  # deterministic per seed
    assert a != c  # seed moves the order
    assert a != sorted(a)  # actually shuffled
    assert sorted(a) == list(range(n))


def test_infinite_repeat_reshuffles_epochs(tmp_path):
    """window < dataset: per-epoch exactness holds ONLY because windows
    flush at epoch boundaries — a window spanning epochs would let an
    epoch-2 record displace an epoch-1 straggler out of the first n."""
    paths, n = _write_shards(tmp_path, 2, 32)
    loader = StreamingTFRecordLoader(
        paths, _parse(), batch_size=16, window=48, seed=3, repeat=None
    )
    seen = [next(loader)[0].tolist() for _ in range(12)]  # 3 epochs
    flat = [i for b in seen for i in b]
    assert sorted(flat[:n]) == list(range(n))
    assert sorted(flat[n : 2 * n]) == list(range(n))
    assert flat[:n] != flat[n : 2 * n]  # reshuffled across epochs
    loader.close()


def test_shard_files_round_robin():
    paths = [f"p{i}" for i in range(7)]
    assert shard_files(paths, 0, 3) == ["p0", "p3", "p6"]
    assert shard_files(paths, 2, 3) == ["p2", "p5"]
    union = sorted(sum((shard_files(paths, h, 3) for h in range(3)), []))
    assert union == sorted(paths)
    with pytest.raises(ValueError, match="file-shard"):
        shard_files(paths[:2], 0, 3)
    with pytest.raises(ValueError, match="host_index"):
        shard_files(paths, 3, 3)


def test_hosts_partition_records(tmp_path):
    paths, n = _write_shards(tmp_path, 4, 16)
    all_ids = []
    for h in range(2):
        ids = [
            i for b in StreamingTFRecordLoader(
                paths, _parse(), batch_size=8, window=32, repeat=1,
                host_index=h, host_count=2,
            ) for i in b[0].tolist()
        ]
        assert len(ids) == n // 2
        all_ids.extend(ids)
    assert sorted(all_ids) == list(range(n))


def test_corrupt_record_surfaces_in_consumer(tmp_path):
    paths, _ = _write_shards(tmp_path, 1, 8)
    raw = bytearray(open(paths[0], "rb").read())
    raw[20] ^= 0xFF
    open(paths[0], "wb").write(bytes(raw))
    loader = StreamingTFRecordLoader(paths, _parse(), batch_size=4,
                                     window=8, repeat=1)
    with pytest.raises(ValueError, match="crc"):
        list(loader)


def test_bad_args(tmp_path):
    paths, _ = _write_shards(tmp_path, 1, 8)
    with pytest.raises(ValueError, match="window"):
        StreamingTFRecordLoader(paths, _parse(), batch_size=16, window=8)
    with pytest.raises(ValueError, match="at least one"):
        StreamingTFRecordLoader([], _parse(), batch_size=4)
    with pytest.raises(ValueError, match="together"):
        StreamingTFRecordLoader(paths, _parse(), batch_size=4, host_index=0)


def test_streaming_to_device_training(tmp_path):
    """The full file->chip path: TFRecord shards stream through the loader
    and device_prefetch into a sharded train step; loss falls."""
    import optax

    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    rng = np.random.default_rng(0)
    # learnable structure: label = brightest quadrant
    imgs = rng.uniform(0, 0.3, (256, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 4, 256).astype(np.int32)
    for k in range(256):
        q = labels[k]
        imgs[k, (q // 2) * 14 : (q // 2) * 14 + 14,
             (q % 2) * 14 : (q % 2) * 14 + 14] += 0.7
    recs = [
        imgs[k].tobytes() + struct.pack("<i", labels[k]) for k in range(256)
    ]
    path = str(tmp_path / "train.tfrecord")
    write_tfrecord(path, recs)

    def parse(rec):
        img = np.frombuffer(rec[:-4], np.float32).reshape(28, 28, 1)
        (lab,) = struct.unpack("<i", rec[-4:])
        return img, np.asarray([lab], np.int32)

    strat = MultiWorkerMirroredStrategy()
    state, _ = init_state(
        PlainCNN(num_classes=4), optax.sgd(0.1, momentum=0.9), strat,
        jnp.zeros((16, 28, 28, 1)),
    )
    step = make_train_step(strat, state)
    loader = StreamingTFRecordLoader(
        path, parse, batch_size=16, window=64, seed=0
    )
    key = jax.random.key(0)
    losses = []
    for i, batch in zip(range(60), device_prefetch(loader, strat.mesh)):
        state, m = step(state, batch, key)
        losses.append(float(m["loss"]))
    loader.close()
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_streaming_throughput_not_pathological(tmp_path):
    """Host-throughput sanity vs the in-memory native loader on identical
    data. With 256-byte records the stream path is bounded by per-record
    Python (framing + parse_fn), ~165k rec/s on this host once the CRC
    runs natively (native/loader.cc tfde_crc32c; the Python CRC loop was
    13k rec/s) — the per-record overhead amortizes at the KB-to-100KB
    record sizes real image/token shards use. This guards the floor and
    the ratio against an accidental O(n^2), a serialization stall, or the
    CRC silently falling back to Python."""
    import time

    rng = np.random.default_rng(0)
    imgs = rng.standard_normal((2048, 64), dtype=np.float32)
    recs = [imgs[k].tobytes() for k in range(2048)]
    path = str(tmp_path / "tp.tfrecord")
    write_tfrecord(path, recs)
    parse = lambda rec: (np.frombuffer(rec, np.float32),)

    def time_stream():
        loader = StreamingTFRecordLoader(path, parse, batch_size=128,
                                         window=512, repeat=4)
        t0 = time.perf_counter()
        n = sum(b[0].shape[0] for b in loader)
        return n / (time.perf_counter() - t0)

    def time_mem():
        from tfde_tpu import native

        if not native.available():
            from tfde_tpu.data.pipeline import Dataset

            src = (Dataset.from_tensor_slices((imgs,))
                   .shuffle(2048, seed=0).repeat(4).batch(128))
            t0 = time.perf_counter()
            n = sum(b[0].shape[0] for b in iter(src))
            return n / (time.perf_counter() - t0)
        ldr = native.NativeBatchLoader([imgs], 128, repeat=4)
        t0 = time.perf_counter()
        n = sum(b[0].shape[0] for b in ldr)
        return n / (time.perf_counter() - t0)

    stream_rps, mem_rps = time_stream(), time_mem()
    # relative-only guard plus a floor far below healthy throughput
    # (~165k rec/s measured): catches regressions of 10x+ without flaking
    # on contended CI hosts
    from tfde_tpu import native

    floor = 15_000 if native.available() else 2_000
    assert stream_rps > floor, (stream_rps, mem_rps)
    assert stream_rps * 300 > mem_rps, (stream_rps, mem_rps)
