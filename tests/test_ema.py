"""Param EMA (training/optimizers.with_param_ema): closed-form math,
post-update tracking, structural extraction, FSDP sharding inheritance,
and checkpoint round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.training.optimizers import (
    ParamEmaState,
    ema_params,
    with_param_ema,
)


def test_ema_tracks_post_update_params():
    """decay=0 makes the EMA equal the freshly-updated params exactly —
    the post-update (not pre-update) convention."""
    tx = with_param_ema(optax.sgd(0.1), decay=0.0)
    params = {"w": jnp.ones((3,))}
    state = tx.init(params)
    grads = {"w": jnp.full((3,), 2.0)}
    updates, state = tx.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(
        np.asarray(ema_params(state)["w"]), np.asarray(new_params["w"])
    )


def test_ema_closed_form():
    """n identical SGD steps: ema_n = d^n p0 + (1-d) sum d^k p_{n-k}."""
    d = 0.5
    tx = with_param_ema(optax.sgd(1.0), decay=d)
    p = {"w": jnp.zeros(())}
    state = tx.init(p)
    g = {"w": jnp.ones(())}
    expect = 0.0
    for n in range(1, 5):
        updates, state = tx.update(g, state, p)
        p = optax.apply_updates(p, updates)  # p_n = -n
        expect = d * expect + (1 - d) * float(p["w"])
    assert float(ema_params(state)["w"]) == pytest.approx(expect)


def test_ema_requires_params():
    tx = with_param_ema(optax.sgd(0.1))
    state = tx.init({"w": jnp.ones(())})
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.ones(())}, state)


def test_ema_params_extraction_errors():
    with pytest.raises(ValueError, match="ParamEmaState"):
        ema_params(optax.sgd(0.1).init({"w": jnp.ones(())}))


def test_ema_shards_like_params_under_fsdp(rng):
    """The EMA copy in opt_state inherits the params' FSDP layout via
    opt_state_spec's structural matching — no EMA-specific sharding
    code."""
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import FSDPStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    s = FSDPStrategy(min_shard_elems=1)
    tx = with_param_ema(optax.sgd(0.1), decay=0.9)
    state, _ = init_state(PlainCNN(), tx, s,
                          np.zeros((16, 784), np.float32), seed=0)
    ema = ema_params(state.opt_state)
    flat_p = jax.tree_util.tree_leaves_with_path(state.params)
    flat_e = dict(
        (jax.tree_util.keystr(p), l.sharding)
        for p, l in jax.tree_util.tree_leaves_with_path(ema)
    )
    for path, leaf in flat_p:
        assert flat_e[jax.tree_util.keystr(path)] == leaf.sharding

    # and a real sharded train step advances it toward the new params
    step = make_train_step(s, state, donate=False)
    images = rng.random((16, 784)).astype(np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    before = jax.device_get(ema_params(state.opt_state))
    state2, _ = step(state, (images, labels), jax.random.key(0))
    after = jax.device_get(ema_params(state2.opt_state))
    moved = any(
        np.abs(a - b).max() > 0
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(after))
    )
    assert moved
    # eval on the averaged weights: a plain forward runs
    logits = state2.apply_fn(
        {"params": ema_params(state2.opt_state)},
        jnp.asarray(images), train=False,
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_ema_survives_checkpoint_roundtrip(tmp_path, rng):
    from tfde_tpu.checkpoint.manager import CheckpointManager
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    s = MirroredStrategy()
    tx = with_param_ema(optax.sgd(0.1), decay=0.9)
    state, _ = init_state(PlainCNN(), tx, s,
                          np.zeros((8, 784), np.float32), seed=0)
    step = make_train_step(s, state, donate=False)
    images = rng.random((8, 784)).astype(np.float32)
    labels = rng.integers(0, 10, (8, 1)).astype(np.int32)
    for _ in range(3):
        state, _ = step(state, (images, labels), jax.random.key(0))
    mngr = CheckpointManager(str(tmp_path / "ck"))
    mngr.save(state, force=True)
    mngr.wait()
    fresh, _ = init_state(PlainCNN(), tx, s,
                          np.zeros((8, 784), np.float32), seed=1)
    restored = mngr.restore_latest(fresh)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(ema_params(state.opt_state)),
        jax.device_get(ema_params(restored.opt_state)),
    )
