"""Input-pipeline determinism and sharding-arithmetic tests (SURVEY.md §4)."""

import numpy as np
import pytest

from tfde_tpu.data.pipeline import Dataset
from tfde_tpu.data import datasets


def _arrays(n=20):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.int64)
    return x, y


def test_from_tensor_slices_roundtrip():
    x, y = _arrays()
    els = list(Dataset.from_tensor_slices((x, y)))
    assert len(els) == 20
    np.testing.assert_array_equal(els[3][0], x[3])
    assert els[3][1] == 3


def test_batch_vectorized_no_shuffle_keeps_order():
    x, y = _arrays()
    b = list(Dataset.from_tensor_slices((x, y)).batch(8))
    assert len(b) == 3  # 8+8+4, no drop
    np.testing.assert_array_equal(b[0][1], y[:8])
    assert b[2][0].shape[0] == 4


def test_batch_drop_remainder():
    x, y = _arrays()
    b = list(Dataset.from_tensor_slices((x, y)).batch(8, drop_remainder=True))
    assert len(b) == 2


def test_full_shuffle_is_permutation_and_deterministic():
    x, y = _arrays()
    ds = lambda: Dataset.from_tensor_slices((x, y)).shuffle(100, seed=7).batch(20)
    (bx1, by1), = list(ds())
    (bx2, by2), = list(ds())
    np.testing.assert_array_equal(by1, by2)  # deterministic under a seed
    assert sorted(by1.tolist()) == y.tolist()  # a permutation
    assert not np.array_equal(by1, y)  # actually shuffled


def test_windowed_shuffle_semantics():
    x, y = _arrays(200)
    got = [int(e[1]) for e in Dataset.from_tensor_slices((x, y)).shuffle(10, seed=0)]
    assert sorted(got) == y.tolist()
    assert got != y.tolist()
    # windowed: displacement is buffer-bounded in distribution (geometric
    # tail), so check a high percentile rather than the max
    disp = sorted(abs(p - v) for p, v in enumerate(got))
    assert disp[int(len(disp) * 0.9)] <= 40


def test_repeat_infinite_and_counted():
    x, y = _arrays(4)
    it = iter(Dataset.from_tensor_slices((x, y)).repeat().batch(4))
    for _ in range(5):
        next(it)  # infinite stream never raises
    b = list(Dataset.from_tensor_slices((x, y)).repeat(3).batch(4))
    assert len(b) == 3


def test_shuffle_repeat_reshuffles_each_epoch():
    x, y = _arrays(16)
    it = iter(Dataset.from_tensor_slices((x, y)).shuffle(16, seed=3).repeat().batch(16))
    e1, e2 = next(it)[1], next(it)[1]
    assert sorted(e1.tolist()) == sorted(e2.tolist())
    assert not np.array_equal(e1, e2)


def test_map_vectorized_fast_path():
    x, y = _arrays()
    ds = Dataset.from_tensor_slices((x, y)).map(lambda a, b: (a / 2.0, b)).batch(20)
    (bx, by), = list(ds)
    np.testing.assert_allclose(bx, x / 2.0)


def test_shard_partitions_examples():
    x, y = _arrays(10)
    got0 = [int(e[1]) for e in Dataset.from_tensor_slices((x, y)).shard(2, 0)]
    got1 = [int(e[1]) for e in Dataset.from_tensor_slices((x, y)).shard(2, 1)]
    assert got0 == [0, 2, 4, 6, 8]
    assert got1 == [1, 3, 5, 7, 9]


def test_prefetch_transparent():
    x, y = _arrays()
    a = [e[1] for e in Dataset.from_tensor_slices((x, y)).prefetch(4)]
    np.testing.assert_array_equal(np.array(a), y)


def test_cache_materializes():
    calls = []
    x, y = _arrays(5)

    def fn(a, b):
        calls.append(1)
        return a, b

    ds = Dataset.from_tensor_slices((x, y)).map(fn).cache()
    # no fast path for this test: remove slices to force per-element map
    ds._slices = None
    list(ds)
    first = len(calls)
    list(ds)
    assert len(calls) == first  # second pass served from cache


def test_synthetic_mnist_shapes_and_learnability():
    (tx, ty), (ex, ey) = datasets.mnist(flatten=True, n_train=2000, n_test=200)
    assert tx.shape == (2000, 784) and tx.dtype == np.float32
    assert ty.shape == (2000, 1) and ey.shape == (200, 1)
    assert 0.0 <= tx.min() and tx.max() <= 1.0
    # classes must be separable: nearest-class-mean on raw pixels beats chance
    means = np.stack([tx[ty[:, 0] == c].mean(0) for c in range(10)])
    pred = np.argmin(((ex[:, None] - means[None]) ** 2).sum(-1), axis=1)
    assert (pred == ey[:, 0]).mean() > 0.5


def test_repeat_batch_carries_across_epochs():
    """repeat().batch() must never emit per-epoch short batches (tf.data
    semantics): 10 examples repeated, batch 8 -> all batches full-size."""
    x, y = _arrays(10)
    it = iter(Dataset.from_tensor_slices((x, y)).repeat().batch(8))
    seen = [next(it) for _ in range(10)]
    assert all(b[0].shape[0] == 8 for b in seen)
    # every example appears 8*10/10 = 8 times across 80 drawn rows
    counts = np.bincount(np.concatenate([b[1] for b in seen]), minlength=10)
    np.testing.assert_array_equal(counts, np.full(10, 8))


def test_repeat_counted_batch_total():
    x, y = _arrays(10)
    b = list(Dataset.from_tensor_slices((x, y)).repeat(3).batch(8))
    assert [e[0].shape[0] for e in b] == [8, 8, 8, 6]


def test_map_fast_path_rejected_for_non_elementwise_fn():
    x, y = _arrays(8)
    ds = Dataset.from_tensor_slices((x, y)).map(lambda a, b: (a - a.mean(), b))
    (bx, _), = list(ds.batch(8))
    want = np.stack([row - row.mean() for row in x])  # per-element semantics
    np.testing.assert_allclose(bx, want, rtol=1e-6)


def test_unknown_size_repeat_keeps_unknown():
    def gen():
        yield (np.zeros(3),)

    ds = Dataset(gen, None).repeat(3)
    assert ds.size is None


def test_map_after_repeat_keeps_infinite_stream():
    x, y = _arrays(10)
    it = iter(Dataset.from_tensor_slices((x, y)).repeat().map(lambda a, b: (a, b)).batch(4))
    for _ in range(10):  # > one epoch; must not stop
        next(it)


def test_shuffle_then_map_keeps_shuffling():
    x, y = _arrays(20)
    (bx, by), = list(
        Dataset.from_tensor_slices((x, y)).shuffle(20, seed=0)
        .map(lambda a, b: (a, b)).batch(20)
    )
    assert not np.array_equal(by, y)
    assert sorted(by.tolist()) == y.tolist()


def test_repeat_zero_is_empty_both_paths():
    x, y = _arrays(8)
    assert list(Dataset.from_tensor_slices((x, y)).repeat(0).batch(4)) == []
    ds = Dataset.from_tensor_slices((x, y)).repeat(0)
    ds._fast = None  # force iterator path
    assert list(ds.batch(4)) == []


def test_iterator_path_seeded_shuffle_reshuffles_each_epoch():
    x, y = _arrays(20)
    ds = Dataset.from_tensor_slices((x, y)).shuffle(5, seed=0).repeat(2)
    ds._fast = None  # force the windowed iterator path
    got = [int(e[1]) for e in ds]
    assert got[:20] != got[20:]  # epochs differ
    assert sorted(got[:20]) == y.tolist() and sorted(got[20:]) == y.tolist()


def test_prefetch_propagates_upstream_errors():
    def bad_gen(epoch=0):
        yield (np.zeros(2),)
        raise RuntimeError("io error")

    ds = Dataset(bad_gen, None).prefetch(2)
    with pytest.raises(RuntimeError, match="io error"):
        list(ds)


def test_malformed_cluster_env_raises_descriptive():
    import os
    from tfde_tpu.runtime import cluster

    os.environ["TF_CONFIG"] = "{bad"
    try:
        with pytest.raises(ValueError, match="TF_CONFIG"):
            cluster.resolve_cluster()
    finally:
        del os.environ["TF_CONFIG"]
    os.environ["CLUSTER_SPEC"] = "{bad"
    try:
        with pytest.raises(ValueError, match="CLUSTER_SPEC"):
            cluster.resolve_cluster()
    finally:
        del os.environ["CLUSTER_SPEC"]


def test_coordinator_endpoint_derives_offset_port():
    """The jax.distributed coordinator must NOT reuse the cluster spec's
    application port (a leftover TF gRPC server bound there would break
    init): it derives spec+1011, wraps near the range top, respects
    TFDE_COORD_PORT, and defaults when the spec has no port."""
    import os

    from tfde_tpu.runtime.cluster import coordinator_endpoint

    assert coordinator_endpoint("host-a:2222") == "host-a:3233"
    assert coordinator_endpoint("host-a") == "host-a:8476"
    assert coordinator_endpoint("[::1]:2222") == "[::1]:3233"
    assert coordinator_endpoint("[::1]") == "[::1]:8476"
    assert coordinator_endpoint("h:65000") == "h:63989"  # wrap stays valid
    os.environ["TFDE_COORD_PORT"] = "9999"
    try:
        assert coordinator_endpoint("host-a:2222") == "host-a:9999"
    finally:
        del os.environ["TFDE_COORD_PORT"]


def test_download_verifies_checksum(tmp_path, monkeypatch):
    """The opt-in dataset download (reference parity: mnist_keras:207-208
    fetches over the network) must refuse a payload whose sha256 does not
    match, and install a matching one atomically. Exercised hermetically
    via a file:// URL."""
    import hashlib

    from tfde_tpu.data import datasets as ds

    payload = b"not really mnist but bytes all the same"
    src = tmp_path / "src.npz"
    src.write_bytes(payload)
    url = src.as_uri()

    monkeypatch.setitem(
        ds._DOWNLOADS, "mnist",
        {"url": url, "sha256": "0" * 64, "filename": "mnist.npz"},
    )
    with pytest.raises(ValueError, match="checksum mismatch"):
        ds.download("mnist", str(tmp_path / "data"))
    assert not (tmp_path / "data" / "mnist.npz").exists()
    assert not list((tmp_path / "data").glob("*.download"))

    monkeypatch.setitem(
        ds._DOWNLOADS, "mnist",
        {"url": url, "sha256": hashlib.sha256(payload).hexdigest(),
         "filename": "mnist.npz"},
    )
    out = ds.download("mnist", str(tmp_path / "data"))
    assert open(out, "rb").read() == payload
    # idempotent: second call resolves without refetching
    assert ds.download("mnist", str(tmp_path / "data")) == out


def test_download_unknown_dataset():
    from tfde_tpu.data import datasets as ds

    with pytest.raises(ValueError, match="unknown dataset"):
        ds.download("imagenet-22k")


def test_cifar_tarball_conversion(tmp_path):
    """The cifar-10-python tarball converts to the npz layout the loader
    resolves."""
    import pickle
    import tarfile

    from tfde_tpu.data import datasets as ds

    rng = np.random.default_rng(0)

    def batch(n):
        return {
            b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
            b"labels": rng.integers(0, 10, n).tolist(),
        }

    tar = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tar, "w:gz") as tf:
        import io as _io

        for name, n in [("data_batch_1", 20), ("data_batch_2", 20),
                        ("test_batch", 10)]:
            raw = pickle.dumps(batch(n))
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(raw)
            tf.addfile(info, _io.BytesIO(raw))
    out = tmp_path / "cifar10.npz"
    ds._convert_cifar_tarball(tar, out)
    with np.load(out) as d:
        assert d["x_train"].shape == (40, 32, 32, 3)
        assert d["x_test"].shape == (10, 32, 32, 3)
        assert d["y_train"].shape == (40,)


def test_device_prefetch_background_matches_inline():
    """background=True (worker-thread device_put, the tunnel-overlap mode)
    must yield the same stream in the same order, and surface source
    errors in the consumer."""
    import jax

    from tfde_tpu.data.device import device_prefetch
    from tfde_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    batches = [
        (np.full((16, 4), i, np.float32), np.full((16, 1), i, np.int32))
        for i in range(6)
    ]
    inline = [jax.device_get(b[0])
              for b in device_prefetch(iter(batches), mesh)]
    bg = [jax.device_get(b[0])
          for b in device_prefetch(iter(batches), mesh, background=True)]
    assert len(inline) == len(bg) == 6
    for a, b in zip(inline, bg):
        np.testing.assert_array_equal(a, b)

    def broken():
        yield batches[0]
        raise RuntimeError("source exploded")

    feed = device_prefetch(broken(), mesh, background=True)
    next(feed)
    with pytest.raises(RuntimeError, match="source exploded"):
        next(feed)


def test_device_resident_feed_semantics():
    """On-device input pipeline: per-epoch permutation exactness,
    determinism per seed, reshuffle across epochs, sharded output."""
    import jax

    from tfde_tpu.data.device import device_resident_feed
    from tfde_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    n, batch = 48, 16
    x = np.arange(n, dtype=np.int32)
    y = (x * 2).astype(np.float32)
    feed = device_resident_feed((x, y), mesh, batch, seed=3)
    per_epoch = n // batch
    ids = []
    for step in range(2 * per_epoch):
        bx, by = feed(step)
        assert bx.sharding.spec[0] is not None  # batch dim sharded
        np.testing.assert_array_equal(np.asarray(by),
                                      np.asarray(bx) * 2.0)  # rows paired
        ids.extend(np.asarray(bx).tolist())
    assert sorted(ids[:n]) == list(range(n))          # epoch 1 exact
    assert sorted(ids[n:]) == list(range(n))          # epoch 2 exact
    assert ids[:n] != ids[n:]                         # reshuffled
    assert ids[:n] != list(range(n))                  # actually shuffled
    # deterministic per seed
    again = device_resident_feed((x, y), mesh, batch, seed=3)
    np.testing.assert_array_equal(np.asarray(again(1)[0]),
                                  np.asarray(feed(1)[0]))
    # seed moves the order
    other = device_resident_feed((x, y), mesh, batch, seed=4)
    assert not np.array_equal(np.asarray(other(0)[0]),
                              np.asarray(feed(0)[0]))


def test_device_resident_feed_trains():
    """The feed drops into a sharded train step like any batch; loss
    falls with zero per-step host transfer."""
    import jax
    import jax.numpy as jnp
    import optax

    from tfde_tpu.data.device import device_resident_feed
    from tfde_tpu.models.cnn import PlainCNN
    from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
    from tfde_tpu.training.step import init_state, make_train_step

    rng = np.random.default_rng(0)
    imgs = rng.uniform(0, 0.3, (128, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 4, 128).astype(np.int64)
    for k in range(128):
        q = labels[k]
        imgs[k, (q // 2) * 14 : (q // 2) * 14 + 14,
             (q % 2) * 14 : (q % 2) * 14 + 14] += 0.7
    strat = MultiWorkerMirroredStrategy()
    state, _ = init_state(PlainCNN(num_classes=4),
                          optax.sgd(0.1, momentum=0.9), strat,
                          jnp.zeros((16, 28, 28, 1)))
    step_fn = make_train_step(strat, state)
    feed = device_resident_feed(
        (imgs, labels.reshape(-1, 1)), strat.mesh, 16, seed=0
    )
    key = jax.random.key(0)
    losses = []
    for step in range(40):
        state, m = step_fn(state, feed(step), key)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.6, losses[::10]


def test_device_resident_feed_validation():
    from tfde_tpu.data.device import device_resident_feed
    from tfde_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="leading dimension"):
        device_resident_feed(
            (np.zeros((8, 2)), np.zeros((6,))), mesh, 4
        )
    with pytest.raises(ValueError, match="drop_remainder"):
        device_resident_feed((np.zeros((10, 2)),), mesh, 4,
                             drop_remainder=False)
    with pytest.raises(ValueError, match="exceeds the dataset"):
        device_resident_feed((np.zeros((8, 2)),), mesh, 16)
