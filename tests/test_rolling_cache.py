"""Rolling KV cache (transformer.MultiHeadAttention.rolling_cache): decode
memory bounded by the sliding window, outputs identical to the full-budget
cache. The slot-arithmetic mask (b_j = P - ((P - j) mod Wc)) must reproduce
the band exactly through prefill, per-token decode, long prompts, per-row
ragged offsets, and beam reordering."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import (
    _decode_clone,
    generate,
    generate_ragged,
    init_cache,
)
from tfde_tpu.models.gpt import GPT


def _window_model(**kw):
    defaults = dict(
        vocab_size=97, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
        max_position=128, dtype=jnp.float32, position="rope",
        num_kv_heads=2, sliding_window=8,
    )
    defaults.update(kw)
    return GPT(**defaults)


@pytest.fixture(scope="module")
def model_and_params():
    m = _window_model()
    params = m.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return m, params


def test_rolling_cache_is_window_bounded(model_and_params):
    """The memory claim itself: cache length = window, not budget."""
    m, _ = model_and_params
    cache = init_cache(m, 2, 64, rolling=True)
    k = cache["decoder"]["block_0"]["attn"]["cached_key"]
    assert k.shape[1] == 8  # window, not 64
    full = init_cache(m, 2, 64, rolling=False)
    assert full["decoder"]["block_0"]["attn"]["cached_key"].shape[1] == 64


@pytest.mark.slow
def test_rolling_generate_matches_full_cache(model_and_params, rng):
    """Token-for-token equality with the full-budget cache, far past the
    window (budget 40 >> window 8): greedy generate through the rolling
    path vs a manual full-cache decode loop."""
    m, params = model_and_params
    prompt = jnp.asarray(rng.integers(0, 97, (2, 6)), jnp.int32)
    new = 34

    # rolling path (generate enables it for window models)
    toks, _ = generate(m, params, prompt, max_new_tokens=new)

    # full-cache oracle: the same loop with rolling off
    decode_model = _decode_clone(m, rolling=False)
    cache = init_cache(m, 2, 6 + new, rolling=False)

    def step(cache, tokens):
        logits, mutated = decode_model.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            mutable=["cache"],
        )
        return mutated["cache"], logits[:, -1]

    cache, logits = step(cache, prompt)
    out = [jnp.argmax(logits, -1)]
    for _ in range(new - 1):
        cache, logits = step(cache, out[-1][:, None])
        out.append(jnp.argmax(logits, -1))
    oracle = jnp.stack(out, axis=1)
    np.testing.assert_array_equal(
        np.asarray(toks[:, 6:]), np.asarray(oracle)
    )


@pytest.mark.slow
def test_rolling_long_prompt_prefill(model_and_params, rng):
    """Prompt (20) longer than the window cache (8): the prefill attends
    in-batch and keeps only the newest window of K/V — continuations must
    still match the full-cache oracle exactly."""
    m, params = model_and_params
    prompt = jnp.asarray(rng.integers(0, 97, (2, 20)), jnp.int32)
    new = 12
    toks, _ = generate(m, params, prompt, max_new_tokens=new)

    decode_model = _decode_clone(m, rolling=False)
    cache = init_cache(m, 2, 20 + new, rolling=False)

    def step(cache, tokens):
        logits, mutated = decode_model.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            mutable=["cache"],
        )
        return mutated["cache"], logits[:, -1]

    cache, logits = step(cache, prompt)
    out = [jnp.argmax(logits, -1)]
    for _ in range(new - 1):
        cache, logits = step(cache, out[-1][:, None])
        out.append(jnp.argmax(logits, -1))
    np.testing.assert_array_equal(
        np.asarray(toks[:, 20:]), np.asarray(jnp.stack(out, axis=1))
    )


@pytest.mark.slow
def test_rolling_ragged_rows_match_solo(model_and_params, rng):
    """Ragged prompts under the rolling cache (generate_ragged
    teacher-forces rows on a SHARED scalar index — the per-row-index
    rolling combination is refused in the layer): every row equals its
    solo run."""
    m, params = model_and_params
    lens = [3, 6]
    maxlen = max(lens)
    rows = [rng.integers(0, 97, (n,)).astype(np.int32) for n in lens]
    padded = np.zeros((2, maxlen), np.int32)
    for i, r in enumerate(rows):
        padded[i, : len(r)] = r
    new = 20
    toks, _ = generate_ragged(
        m, params, jnp.asarray(padded), jnp.asarray(lens, jnp.int32),
        max_new_tokens=new,
    )
    for i, r in enumerate(rows):
        solo, _ = generate(m, params, jnp.asarray(r[None, :]),
                           max_new_tokens=new)
        np.testing.assert_array_equal(
            np.asarray(toks[i, lens[i]:lens[i] + new]),
            np.asarray(solo[0, lens[i]:]),
        )


@pytest.mark.slow
def test_rolling_off_for_speculation(model_and_params):
    """Speculative decoding rewinds the cache, which aliases rolling
    slots — its clone must stay on the full-budget cache."""
    from tfde_tpu.inference.speculative import generate_speculative

    m, params = model_and_params
    draft = _window_model(depth=1)
    dparams = draft.init(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    # greedy speculation must equal plain greedy generate (the exactness
    # contract) — which it could not if the target cache rolled
    ref, _ = generate(m, params, prompt, max_new_tokens=16)
    out, _ = generate_speculative(
        m, draft, params, dparams, prompt, max_new_tokens=16, num_draft=3,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
