"""Serving-export tests: artifact roundtrip + signature (SURVEY.md §3.4)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.export.serving import FinalExporter, export_serving, load_serving
from tfde_tpu.models.cnn import BatchNormCNN, PlainCNN
import pytest


def _trained_vars():
    m = BatchNormCNN()
    variables = m.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)
    return m, variables


def test_export_and_load_roundtrip(tmp_path):
    m, variables = _trained_vars()

    def apply_fn(v, x):
        return m.apply(v, x, train=False)

    out = export_serving(apply_fn, variables, (None, 784), str(tmp_path / "exp"))
    assert os.path.exists(os.path.join(out, "model.stablehlo"))
    assert os.path.exists(os.path.join(out, "params.npz"))

    sig = json.load(open(os.path.join(out, "signature.json")))
    assert sig["input"]["shape"] == [None, 784]
    assert sig["output"]["shape"] == [None, 10]

    served = load_serving(out)
    x = np.random.default_rng(0).random((7, 784), np.float32)
    probs = served.predict(x)
    assert probs.shape == (7, 10)
    np.testing.assert_allclose(probs.sum(-1), np.ones(7), rtol=1e-5)

    # probabilities must match direct apply + softmax (reference signature:
    # [None,784] float -> 10 probs, mnist_keras:108,159)
    want = jax.nn.softmax(m.apply(variables, jnp.asarray(x), train=False), axis=-1)
    np.testing.assert_allclose(probs, np.asarray(want), atol=1e-5)


def test_export_serves_any_batch_size(tmp_path):
    m, variables = _trained_vars()
    out = export_serving(
        lambda v, x: m.apply(v, x, train=False), variables, (None, 784), str(tmp_path / "e")
    )
    served = load_serving(out)
    for n in (1, 3, 64):
        assert served.predict(np.zeros((n, 784), np.float32)).shape == (n, 10)


def test_load_resolves_latest_timestamp(tmp_path):
    m, variables = _trained_vars()
    exporter = FinalExporter("exporter", (None, 784))
    base = str(tmp_path)
    p1 = exporter.export(base, lambda v, x: m.apply(v, x, train=False), variables)
    served = load_serving(os.path.join(base, "export", "exporter"))
    assert served.predict(np.zeros((2, 784), np.float32)).shape == (2, 10)


def test_export_token_model_int_signature(tmp_path):
    """Transformer-era serving: a GPT export over int32 token ids — the
    export layer isn't MNIST-shaped (SURVEY.md §3.4 generalized to the
    scale-config model families)."""
    from tfde_tpu.models.gpt import gpt_tiny_test

    m = gpt_tiny_test()
    toks = jnp.zeros((1, 16), jnp.int32)
    variables = m.init(jax.random.key(0), toks, train=False)

    def apply_fn(v, x):
        return m.apply(v, x, train=False)

    out = export_serving(
        apply_fn, variables, (None, 16), str(tmp_path / "exp"),
        input_dtype=jnp.int32,
    )
    sig = json.load(open(os.path.join(out, "signature.json")))
    assert sig["input"]["dtype"] == "int32"

    served = load_serving(out)
    x = np.random.default_rng(0).integers(0, 97, (3, 16)).astype(np.int32)
    probs = served.predict(x)
    assert probs.shape == (3, 16, 97)
    np.testing.assert_allclose(probs.sum(-1), np.ones((3, 16)), rtol=1e-4)


@pytest.mark.slow
def test_savedmodel_export_serves_in_tensorflow(tmp_path):
    """Opt-in TF-Serving interop (reference FinalExporter writes a
    SavedModel, mnist_keras:151-162): the jax2tf-wrapped artifact must
    load in plain TensorFlow and agree with the native path's outputs,
    at any batch size."""
    import pytest as _pytest

    tf = _pytest.importorskip("tensorflow")

    from tfde_tpu.export.savedmodel import export_savedmodel

    model = PlainCNN()
    variables = model.init(jax.random.key(0), jnp.zeros((2, 28, 28, 1)))

    def apply_fn(v, x):
        return model.apply(v, x, train=False)

    out = export_savedmodel(
        apply_fn, variables, (None, 28, 28, 1), str(tmp_path / "sm")
    )
    loaded = tf.saved_model.load(out)
    x = np.random.default_rng(0).normal(size=(5, 28, 28, 1)).astype(np.float32)
    served = loaded.signatures["serving_default"](tf.constant(x))
    probs = next(iter(served.values())).numpy()
    ref = jax.nn.softmax(apply_fn(variables, jnp.asarray(x)), axis=-1)
    np.testing.assert_allclose(probs, np.asarray(ref), rtol=1e-5, atol=1e-6)
    assert probs.shape == (5, 10)
