"""Paged KV (inference/paged.py + the ContinuousBatcher paged mode):
the BlockPool allocator's refcount/free-list/defrag invariants unit by
unit, the block-table gather pinned bit-exact against the dense slab,
greedy serving parity dense-vs-paged through the REAL batcher (multi-
wave row reuse, warm trie sharing, solo-generate cross-check), cancel
returning blocks to the pool, and the one-paged-prefill-program compile
sentinel across mixed prompt lengths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference import paged, server
from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.prefix_cache import DEFAULT_BLOCK
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import gpt_tiny_test


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _drain(b, reqs, budgets, max_steps=60):
    ids = [b.submit(p, n) for p, n in zip(reqs, budgets)]
    out = {}
    for _ in range(max_steps):
        for rid, toks in b.step():
            out[rid] = list(map(int, toks))
        if len(out) == len(ids):
            break
    assert len(out) == len(ids), "batcher did not drain"
    return [out[i] for i in ids]


# five requests through three rows: two admission waves, one row freed
# and re-used mid-flight, one duplicate prompt (the warm-sharing case —
# 19 tokens, so its first block is COMPLETE and trie-shareable; a
# shorter duplicate would share nothing), and rider rows decoding while
# a later wave chunk-prefills — the exact shape that once poisoned the
# pool with non-finite junk writes
_PROMPTS = [np.arange(3, 10) % 97, np.arange(5, 11) % 97,
            np.arange(40, 59) % 97, np.arange(7, 12) % 97,
            np.arange(40, 59) % 97]
_BUDGETS = [8, 5, 12, 6, 9]


# --------------------------------------------------------------------------
# BlockPool: allocator unit matrix
# --------------------------------------------------------------------------

def test_blocks_for():
    assert paged.blocks_for(0, 16) == 0
    assert paged.blocks_for(1, 16) == 1
    assert paged.blocks_for(16, 16) == 1
    assert paged.blocks_for(17, 16) == 2
    assert paged.blocks_for(48, 16) == 3


def test_pool_alloc_free_refcount():
    pool = paged.BlockPool(8, 16)
    assert pool.free_blocks == 7            # null excluded
    a = pool.alloc(3)
    assert a == [1, 2, 3]                   # lowest-id-first, deterministic
    assert all(pool.refcount(b) == 1 for b in a)
    pool.incref([2])
    assert pool.refcount(2) == 2
    pool.free([2])                          # one ref down, still held
    assert pool.refcount(2) == 1 and pool.free_blocks == 4
    pool.free(a)                            # all the way back
    assert pool.free_blocks == 7
    s = pool.stats()
    assert s == {"total": 7, "free": 7, "active": 0, "block": 16}
    with pytest.raises(ValueError):
        pool.free([1])                      # double free
    with pytest.raises(ValueError):
        pool.free([paged.NULL_BLOCK])       # null pinned
    with pytest.raises(ValueError):
        pool.incref([5])                    # unallocated


def test_pool_exhausted_rolls_back_and_evictor_drains():
    pool = paged.BlockPool(4, 16)           # 3 allocatable
    pool.alloc(2)
    with pytest.raises(paged.PoolExhausted):
        pool.alloc(2)
    assert pool.free_blocks == 1            # partial take rolled back
    # an evictor that frees one of the held blocks on demand
    held = pool.alloc(1)
    freed = []

    def evictor(need):
        pool.free([held[0]])
        freed.append(need)
        return 1

    pool.set_evictor(evictor)
    got = pool.alloc(1)                     # starves -> evictor -> satisfied
    assert freed == [1] and len(got) == 1
    assert pool.available(evictable=5) == pool.free_blocks + 5


def test_pool_defrag_compacts_to_lowest_ids():
    pool = paged.BlockPool(10, 16)
    a = pool.alloc(6)                       # 1..6
    pool.incref([a[5]])                     # block 6 shared (ref 2)
    pool.free([a[0], a[2], a[4]])           # holes at 1, 3, 5
    plan = pool.defrag()
    # live blocks {2, 4, 6} compact to {1, 2, 3}; refcounts move intact
    assert plan == {2: 1, 4: 2, 6: 3}
    assert pool.refcount(1) == 1 and pool.refcount(2) == 1
    assert pool.refcount(3) == 2            # the shared ref followed
    assert pool.free_blocks == 6
    # idempotent: already compact -> empty plan
    assert pool.defrag() == {}


def test_apply_defrag_moves_pool_rows_and_tables():
    # synthetic 1-leaf cache: pool rows hold their own id as payload
    n, blk = 6, 4
    cache = {"layer": {"pool_key": jnp.arange(n, dtype=jnp.float32)[
        :, None, None, None] * jnp.ones((n, blk, 1, 1), jnp.float32),
        "pool_value": jnp.zeros((n, blk, 1, 1), jnp.float32)}}
    tables = np.asarray([[4, 2, 0]], np.int32)
    plan = {2: 1, 4: 2}
    cache, tables = paged.apply_defrag(cache, tables, plan)
    assert tables.tolist() == [[2, 1, 0]]
    got = np.asarray(cache["layer"]["pool_key"])[:, 0, 0, 0]
    # new id 1 holds old block 2's payload, new id 2 holds old block 4's
    assert got[1] == 2.0 and got[2] == 4.0


# --------------------------------------------------------------------------
# Bit-exactness: table gather == dense slab, column for column
# --------------------------------------------------------------------------

def _kv_leaves(cache, names):
    out = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        nm = str(getattr(path[-1], "key", path[-1]))
        if nm in names:
            out.setdefault(nm, []).append(np.asarray(leaf))
    return out


def test_paged_gather_bit_exact_vs_dense(lm):
    """After one admission wave + scan, gathering each row's block table
    into position order must reproduce the dense cached_key/cached_value
    cells bit for bit (the docstring claim in _paged_attention)."""
    model, params = lm
    kw = dict(batch_size=3, max_len=48, scan_depth=4, prefix_cache=False)
    bd = ContinuousBatcher(model, params, paged=False, **kw)
    bp = ContinuousBatcher(model, params, paged=True, **kw)
    for b in (bd, bp):
        for p, n in zip(_PROMPTS[:3], _BUDGETS[:3]):
            b.submit(p, n)
        b.step()
    dense = _kv_leaves(bd._cache, ("cached_key", "cached_value"))
    pool = _kv_leaves(bp._cache, ("pool_key", "pool_value"))
    tables = _kv_leaves(bp._cache, ("block_table",))["block_table"][0]
    # the device table mirrors the host's unless a row was released
    # mid-step — then the host row is zeroed and the upload is deferred
    # to the next program (_tables_dirty); the gather below uses the
    # DEVICE tables, the state the scan actually ran with
    assert bp._tables_dirty or (tables == bp._tables).all()
    for dname, pname in (("cached_key", "pool_key"),
                         ("cached_value", "pool_value")):
        for dl, pl in zip(dense[dname], pool[pname]):
            gathered = pl[tables].reshape(tables.shape[0], -1,
                                          *pl.shape[2:])
            for r in range(3):
                c = int(bd._committed[r])
                np.testing.assert_array_equal(dl[r, :c], gathered[r, :c])


# --------------------------------------------------------------------------
# Greedy parity through the real batcher
# --------------------------------------------------------------------------

@pytest.mark.parametrize("prefix", [False, True])
def test_paged_greedy_parity_multiwave(lm, prefix):
    """Dense and paged batchers fed the identical 5-request stream
    (2 admission waves, rows freed and re-used, a duplicate prompt for
    the warm path when the trie is on) must emit bit-identical greedy
    tokens."""
    model, params = lm
    kw = dict(batch_size=3, max_len=48, scan_depth=4, prefix_cache=prefix)
    got_d = _drain(ContinuousBatcher(model, params, paged=False, **kw),
                   _PROMPTS, _BUDGETS)
    bp = ContinuousBatcher(model, params, paged=True, **kw)
    got_p = _drain(bp, _PROMPTS, _BUDGETS)
    assert got_p == got_d
    # drain returns every row's blocks; only the trie may keep blocks
    st = bp.block_pool.stats()
    trie = bp._prefix.segments if prefix else 0
    assert st["active"] == trie
    if prefix:
        assert bp._prefix.stats()["hits"] >= 1   # the duplicate prompt


def test_paged_parity_vs_solo_generate(lm):
    """Each batched-paged output must equal the same request run alone
    through decode.generate — the no-scheduler reference."""
    model, params = lm
    bp = ContinuousBatcher(model, params, kv_quant="fp", batch_size=3, max_len=48,
                           scan_depth=4, paged=True, prefix_cache=False)
    got = _drain(bp, _PROMPTS, _BUDGETS)
    for p, n, toks in zip(_PROMPTS, _BUDGETS, got):
        solo, lengths = generate(model, params,
                                 jnp.asarray(p[None, :], jnp.int32),
                                 max_new_tokens=n)
        ref = list(map(int, np.asarray(solo)[0, p.size:int(lengths[0])]))
        assert toks == ref


def test_warm_admission_shares_trie_blocks(lm):
    """A second request with a cached prompt must adopt the trie's
    blocks by refcount (no recompute): after warm admission the shared
    blocks carry refcount 2 — one trie ref, one row ref."""
    model, params = lm
    bp = ContinuousBatcher(model, params, batch_size=2, max_len=48,
                           scan_depth=4, paged=True, prefix_cache=True)
    prompt = (np.arange(0, 33) * 3) % 97     # 33 tokens = 2 full blocks
    rid = bp.submit(prompt, 4)
    while rid not in dict(bp.step()):
        pass
    before = bp._prefix.stats()["hits"]
    trie_blocks = [b for b in range(1, bp.block_pool.num_blocks)
                   if bp.block_pool.refcount(b) == 1]
    assert bp._prefix.segments >= 2          # the prompt's complete blocks
    bp.submit(prompt, 4)
    bp._admit()                              # warm wave runs
    assert bp._prefix.stats()["hits"] == before + 1
    shared = [b for b in trie_blocks if bp.block_pool.refcount(b) == 2]
    assert len(shared) >= 1                  # trie ref + row ref
    while not bp.idle:
        bp.step()


def test_env_flag_selects_paged(lm, monkeypatch):
    model, params = lm
    monkeypatch.setenv("TFDE_PAGED_KV", "on")
    b = ContinuousBatcher(model, params, batch_size=2, max_len=32,
                          scan_depth=2)
    assert b.paged and b.block_pool is not None
    monkeypatch.setenv("TFDE_PAGED_KV", "off")
    b = ContinuousBatcher(model, params, batch_size=2, max_len=32,
                          scan_depth=2)
    assert not b.paged and b.block_pool is None


# --------------------------------------------------------------------------
# Lifecycle: cancel / completion return blocks
# --------------------------------------------------------------------------

def test_cancel_returns_blocks_to_pool(lm):
    model, params = lm
    bp = ContinuousBatcher(model, params, batch_size=2, max_len=48,
                           scan_depth=2, paged=True, prefix_cache=False)
    rid = bp.submit(np.arange(5, 30) % 97, 16)
    bp.step()                                # admitted, decoding
    held = bp.block_pool.stats()["active"]
    assert held >= paged.blocks_for(25, DEFAULT_BLOCK)
    assert bp.cancel(rid)
    assert bp.block_pool.stats()["active"] == 0
    assert bp.block_pool.free_blocks == bp.block_pool.num_blocks - 1
    # the freed row's table is re-pointed at null before the next program
    assert bp._tables_dirty or (bp._tables == 0).all()
    bp.step()                                # no crash on the empty batch
    assert bp.idle


def test_paged_capacity_ledger_blocks_account(lm):
    """kv_stats in paged mode: the pool split must add up, and
    waste_frac is intra-block slack — bounded by (block-1)/block of the
    held cells, 0 when every committed count fills its blocks."""
    model, params = lm
    bp = ContinuousBatcher(model, params, batch_size=3, max_len=48,
                           scan_depth=4, paged=True, prefix_cache=False)
    for p, n in zip(_PROMPTS[:3], _BUDGETS[:3]):
        bp.submit(p, n)
    bp.step()
    s = bp.kv_stats()
    assert s["pool_blocks_total"] == bp.block_pool.num_blocks - 1
    assert (s["pool_blocks_free"] + s["pool_blocks_active"]
            + s["pool_blocks_trie"]) == s["pool_blocks_total"]
    assert 0.0 <= s["waste_frac"] <= 1.0
    # headroom speaks blocks: free pool blocks cap admissible rows
    assert s["headroom_tokens"] == s["pool_blocks_free"] * DEFAULT_BLOCK
    while not bp.idle:
        bp.step()


# --------------------------------------------------------------------------
# Compile discipline: ONE paged prefill program across prompt shapes
# --------------------------------------------------------------------------

def test_paged_prefill_single_compile_across_lengths(lm):
    """Mixed prompt lengths (1 token .. near max_len, crossing chunk
    boundaries) must all run through the same [B, C] chunk program: the
    jit cache grows by exactly one signature for the whole stream."""
    model, params = lm
    bp = ContinuousBatcher(model, params, batch_size=3, max_len=48,
                           scan_depth=4, paged=True, prefix_cache=False)
    before = server._paged_prefill_chunk._cache_size()
    lens = [1, 3, 7, 16, 17, 31, 40]
    reqs = [(np.arange(L) + L) % 97 for L in lens]
    _drain(bp, reqs, [4] * len(reqs))
    grew = server._paged_prefill_chunk._cache_size() - before
    assert grew <= 1, (
        f"paged prefill compiled {grew} programs for {len(lens)} prompt "
        f"lengths — the one-static-program claim regressed"
    )
