"""Int8 quantized serving (ops/quant.py): numerics of the int8 dots, the
structural params conversion, and end-to-end quantized generation.

The fp-vs-int8 comparisons use tolerance/agreement assertions, not
equality: W8A8 carries two rounding steps by design. The structural checks
(conversion fills exactly the quant model's expected tree) are exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.ops.quant import (
    QuantDenseGeneral,
    QuantEmbed,
    absmax_quantize,
    int8_dot_general,
    quantize_model,
    quantize_params,
    stochastic_round,
)


# -- stochastic rounding (the gradient transport's mode) ----------------------
def test_stochastic_round_unbiased_in_expectation():
    # E[floor(x + U[0,1))] == x exactly; averaging over many keys the
    # empirical mean must approach x with s.e. <= 0.5/sqrt(n_keys)
    x = jnp.asarray([0.25, -1.75, 3.5, 0.0, -0.001, 7.999], jnp.float32)
    n = 400
    acc = jnp.zeros_like(x)
    for k in range(n):
        acc = acc + stochastic_round(x, jax.random.key(k))
    mean = acc / n
    # 4 standard errors of the worst-case Bernoulli variance
    assert jnp.all(jnp.abs(mean - x) < 4 * 0.5 / np.sqrt(n)), mean


def test_stochastic_round_deterministic_under_fixed_key(rng):
    x = jnp.asarray(rng.normal(size=(64,)) * 10, jnp.float32)
    key = jax.random.key(7)
    a = stochastic_round(x, key)
    b = stochastic_round(x, key)
    assert jnp.array_equal(a, b)
    # results are integers adjacent to x
    assert jnp.all((a == jnp.floor(x)) | (a == jnp.ceil(x)))
    # a different key flips at least one non-integer element (64 draws)
    c = stochastic_round(x, jax.random.key(8))
    assert not jnp.array_equal(a, c)


def test_absmax_quantize_rng_none_unchanged(rng):
    # the serving path (rng=None) must be bit-identical to the historical
    # nearest-rounding behavior
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    q, scale = absmax_quantize(w, 1)
    expected = jnp.clip(
        jnp.round(w / (jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-12) / 127)),
        -127, 127,
    ).astype(jnp.int8)
    assert jnp.array_equal(q, expected)


def test_absmax_quantize_stochastic_mode_bounded(rng):
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    q, scale = absmax_quantize(w, 1, rng=jax.random.key(0))
    assert q.dtype == jnp.int8
    # stochastic rounding moves at most 1 quantum vs nearest
    qn, _ = absmax_quantize(w, 1)
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32) - qn.astype(jnp.int32)))) <= 1


def test_absmax_roundtrip_error_bound(rng):
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    q, scale = absmax_quantize(w, contract_ndim=1)
    assert q.dtype == jnp.int8 and scale.shape == (16,)
    deq = q.astype(jnp.float32) * scale
    # symmetric absmax: |err| <= scale/2 = amax/254 per element
    amax = jnp.max(jnp.abs(w), axis=0)
    assert jnp.all(jnp.abs(deq - w) <= amax / 254 + 1e-7)


def test_int8_dot_close_to_fp(rng):
    x = jnp.asarray(rng.normal(size=(4, 7, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    q, scale = absmax_quantize(w, 1)
    y = int8_dot_general(x, q, scale, 1, dtype=jnp.float32)
    ref = x @ w
    rel = jnp.linalg.norm(y - ref) / jnp.linalg.norm(ref)
    assert rel < 0.02, f"relative error {rel}"


def test_int8_dot_two_axis_contraction(rng):
    # the attention out-projection layout: [B, S, H, D] x [H, D, E]
    x = jnp.asarray(rng.normal(size=(2, 5, 4, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(4, 8, 16)), jnp.float32)
    q, scale = absmax_quantize(w, 2)
    assert scale.shape == (16,)
    y = int8_dot_general(x, q, scale, 2, dtype=jnp.float32)
    ref = jnp.einsum("bshd,hde->bse", x, w)
    rel = jnp.linalg.norm(y - ref) / jnp.linalg.norm(ref)
    assert rel < 0.02


def test_quant_dense_general_param_shapes(rng):
    m = QuantDenseGeneral(features=(3, 4, 8), axis=-1)
    v = m.init(jax.random.key(0), jnp.zeros((2, 6, 32)))
    p = v["params"]
    assert p["kernel_q"].shape == (32, 3, 4, 8)
    assert p["kernel_q"].dtype == jnp.int8
    assert p["kernel_scale"].shape == (3, 4, 8)
    assert p["bias"].shape == (3, 4, 8)


def test_quant_dense_rejects_non_trailing_axis():
    m = QuantDenseGeneral(features=8, axis=0)
    with pytest.raises(NotImplementedError):
        m.init(jax.random.key(0), jnp.zeros((4, 32)))


def test_quant_embed_gather_matches_dequant(rng):
    emb = jnp.asarray(rng.normal(size=(11, 8)), jnp.float32)
    amax = jnp.max(jnp.abs(emb), axis=1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(emb / scale[:, None]), -127, 127).astype(jnp.int8)
    m = QuantEmbed(11, 8, dtype=jnp.float32)
    ids = jnp.asarray([[0, 3, 10], [5, 5, 1]], jnp.int32)
    out = m.apply({"params": {"embedding_q": q, "scale": scale}}, ids)
    ref = (q.astype(jnp.float32) * scale[:, None])[ids]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_quant_embed_attend_close_to_fp(rng):
    emb = jnp.asarray(rng.normal(size=(13, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(emb), axis=1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(emb / scale[:, None]), -127, 127).astype(jnp.int8)
    m = QuantEmbed(13, 16, dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(3, 16)), jnp.float32)
    out = m.apply({"params": {"embedding_q": q, "scale": scale}}, x,
                  method=m.attend)
    ref = x @ emb.T
    rel = jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref)
    assert rel < 0.03


def _tiny_fp_model_and_params(**kw):
    model = gpt_tiny_test(**kw)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), tokens)["params"]
    return model, {"params": params}


def test_quantize_params_matches_expected_structure():
    model, params = _tiny_fp_model_and_params()
    qmodel, qparams = quantize_model(model, params)
    expected = jax.eval_shape(
        lambda: qmodel.init(jax.random.key(0), jnp.zeros((1, 2), jnp.int32))
    )["params"]
    got = qparams["params"]
    exp_paths = {tuple(str(k) for k in jax.tree_util.tree_flatten_with_path(expected)[0][i][0])
                 for i in range(len(jax.tree_util.tree_leaves(expected)))}
    got_paths = {tuple(str(k) for k in jax.tree_util.tree_flatten_with_path(got)[0][i][0])
                 for i in range(len(jax.tree_util.tree_leaves(got)))}
    assert exp_paths == got_paths
    # shapes/dtypes line up leaf by leaf
    jax.tree_util.tree_map(
        lambda e, g: (e.shape, jnp.dtype(e.dtype)) == (g.shape, jnp.dtype(g.dtype))
        or (_ for _ in ()).throw(AssertionError((e.shape, e.dtype, g.shape, g.dtype))),
        expected, got,
    )


def test_quant_logits_track_fp_logits(rng):
    """Prefill logits of the quantized twin stay directionally faithful to
    fp — cosine similarity per row, the deterministic form of 'the model
    still computes the same function up to quantization noise'."""
    model, params = _tiny_fp_model_and_params()
    qmodel, qparams = quantize_model(model, params)
    tokens = jnp.asarray(rng.integers(0, 97, size=(2, 12)), jnp.int32)
    fp = model.apply(params, tokens, train=False)
    q = qmodel.apply(qparams, tokens, train=False)
    fp_flat = fp.reshape(-1, fp.shape[-1])
    q_flat = q.reshape(-1, q.shape[-1])
    cos = jnp.sum(fp_flat * q_flat, -1) / (
        jnp.linalg.norm(fp_flat, axis=-1) * jnp.linalg.norm(q_flat, axis=-1)
    )
    assert jnp.min(cos) > 0.99, f"min cosine {jnp.min(cos)}"


def test_quant_generate_runs_and_mostly_agrees_with_fp(rng):
    from tfde_tpu.inference.decode import generate

    model, params = _tiny_fp_model_and_params()
    qmodel, qparams = quantize_model(model, params)
    prompt = jnp.asarray(rng.integers(0, 97, size=(2, 4)), jnp.int32)
    fp_toks, fp_len = generate(model, params["params"], prompt, 12)
    q_toks, q_len = generate(qmodel, qparams["params"], prompt, 12)
    assert q_toks.shape == fp_toks.shape == (2, 16)
    agree = np.mean(np.asarray(fp_toks[:, 4:]) == np.asarray(q_toks[:, 4:]))
    # a tiny random model has shallow logit margins — quantization noise may
    # flip some argmaxes, but the sequences must stay substantially aligned
    assert agree >= 0.5, f"greedy agreement {agree}"


def test_quant_untied_lm_head(rng):
    model, params = _tiny_fp_model_and_params(tie_embeddings=False)
    qmodel, qparams = quantize_model(model, params)
    assert "lm_head" in qparams["params"]
    assert qparams["params"]["lm_head"]["kernel_q"].dtype == jnp.int8
    tokens = jnp.asarray(rng.integers(0, 97, size=(1, 6)), jnp.int32)
    out = qmodel.apply(qparams, tokens, train=False)
    assert out.shape == (1, 6, 97) and bool(jnp.all(jnp.isfinite(out)))


def test_quant_refuses_train():
    model, params = _tiny_fp_model_and_params()
    qmodel, qparams = quantize_model(model, params)
    with pytest.raises(ValueError, match="serving-only"):
        qmodel.apply(qparams, jnp.zeros((1, 4), jnp.int32), train=True)


def test_quant_submodule_refuses_train_directly():
    """The guard must also fire one level down (direct Mlp/MHA users) —
    a quantized projection under train would silently zero all grads."""
    from tfde_tpu.models.transformer import Mlp

    m = Mlp(mlp_dim=8, quant="int8", dtype=jnp.float32)
    v = m.init(jax.random.key(0), jnp.zeros((1, 4)))
    with pytest.raises(ValueError, match="serving-only"):
        m.apply(v, jnp.zeros((1, 4)), train=True)


def test_quantize_model_requires_quant_field():
    from tfde_tpu.models.cnn import PlainCNN

    with pytest.raises(ValueError, match="quant"):
        quantize_model(PlainCNN(), {"params": {}})


@pytest.mark.slow
def test_quant_llama_family_config(rng):
    """The LLaMA-shaped config (rope + GQA + swiglu + RMSNorm + bias-free
    + untied head) quantizes end to end: every projection kind the family
    adds (gate, grouped k/v, lm_head) gets an int8 kernel."""
    from tfde_tpu.inference.decode import generate

    model, params = _tiny_fp_model_and_params(
        position="rope", num_kv_heads=2, mlp_act="swiglu", norm="rms",
        use_bias=False, tie_embeddings=False,
    )
    qmodel, qparams = quantize_model(model, params)
    blk = qparams["params"]["decoder"]["block_0"]
    assert blk["mlp"]["gate"]["kernel_q"].dtype == jnp.int8
    assert blk["attn"]["key"]["kernel_q"].shape == (32, 2, 8)  # GQA kv heads
    assert qparams["params"]["lm_head"]["kernel_q"].dtype == jnp.int8
    prompt = jnp.asarray(rng.integers(0, 97, size=(2, 4)), jnp.int32)
    toks, _ = generate(qmodel, qparams["params"], prompt, 8)
    assert toks.shape == (2, 12)
    fp = model.apply(params, prompt, train=False)
    q = qmodel.apply(qparams, prompt, train=False)
    cos = jnp.sum(fp * q) / (jnp.linalg.norm(fp) * jnp.linalg.norm(q))
    assert cos > 0.99


def test_quant_model_through_continuous_server(rng):
    """A quantized model drives the continuous-batching server unchanged —
    the serving stack is model-agnostic, so int8 composes for free."""
    from tfde_tpu.inference.server import ContinuousBatcher

    model, params = _tiny_fp_model_and_params()
    qmodel, qparams = quantize_model(model, params)
    srv = ContinuousBatcher(qmodel, qparams["params"], batch_size=2,
                            max_len=24)
    for _ in range(3):
        srv.submit(np.asarray(rng.integers(0, 97, size=(5,)), np.int32), 6)
    done = srv.run()
    assert len(done) == 3
    for _rid, toks in done:
        assert toks.ndim == 1 and toks.shape == (6,)  # no EOS: full budget


def test_quantize_params_missing_kernel_errors():
    model, params = _tiny_fp_model_and_params()
    qmodel = model.clone(quant="int8")
    broken = jax.tree_util.tree_map(lambda x: x, params)
    del broken["params"]["decoder"]["block_0"]["attn"]["query"]["kernel"]
    with pytest.raises(ValueError, match="kernel"):
        quantize_params(qmodel, broken)
