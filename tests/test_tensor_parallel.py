"""Tensor-parallelism tests: sharding rules hit the right dims, TP training
numerics match pure DP exactly, memory actually shards (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from tfde_tpu.models.bert import bert_tiny_test
from tfde_tpu.models.vit import vit_tiny_test
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    TensorParallelStrategy,
)
from tfde_tpu.training.step import init_state, make_train_step
import pytest


def test_tp_spec_rules():
    m = vit_tiny_test()  # heads=4, mlp=64 — divisible by tensor=4
    v = jax.eval_shape(
        m.init, jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )
    s = TensorParallelStrategy(data=2)
    specs = s.params_spec(v["params"])
    blk = specs["encoder"]["block_0"]
    assert blk["attn"]["query"]["kernel"] == P(None, "tensor", None)
    assert blk["attn"]["query"]["bias"] == P("tensor", None)
    assert blk["attn"]["out"]["kernel"] == P("tensor", None, None)
    assert blk["attn"]["out"]["bias"] == P()
    assert blk["mlp"]["fc1"]["kernel"] == P(None, "tensor")
    assert blk["mlp"]["fc1"]["bias"] == P("tensor")
    assert blk["mlp"]["fc2"]["kernel"] == P("tensor", None)
    assert blk["ln_attn"]["scale"] == P()
    assert specs["patch_embed"]["kernel"] == P()  # conv stem replicated


def _train_params(strategy, steps=3):
    m = vit_tiny_test()
    sample = np.zeros((16, 32, 32, 3), np.float32)
    state, _ = init_state(m, optax.sgd(0.05), strategy, sample, seed=0)
    step = make_train_step(strategy, state, donate=False)
    rng = np.random.default_rng(0)
    images = rng.random((16, 32, 32, 3), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    key = jax.random.key(0)
    for _ in range(steps):
        state, metrics = step(state, (images, labels), key)
    return jax.device_get(state.params), float(metrics["loss"])


def test_tp_matches_dp_numerics():
    """dp=2 x tp=4 must produce the same params as pure dp=8 — TP is a
    layout change, not a math change."""
    p_dp, loss_dp = _train_params(MultiWorkerMirroredStrategy())
    p_tp, loss_tp = _train_params(TensorParallelStrategy(data=2))
    np.testing.assert_allclose(loss_dp, loss_tp, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p_dp, p_tp,
    )


def test_tp_weights_actually_sharded():
    s = TensorParallelStrategy(data=1)  # tensor=8
    m = bert_tiny_test()  # heads=4 not divisible by 8 -> qkv replicated,
    # but fc1 (64) and fc2 shard; graceful per-leaf degradation
    state, _ = init_state(
        m, optax.sgd(0.1), s, np.zeros((8, 16), np.int32)
    )
    blk = state.params["encoder"]["block_0"]
    fc1 = blk["mlp"]["fc1"]["kernel"]
    assert fc1.sharding.spec == P(None, "tensor")
    # per-device shard is 1/8 of the logical array
    assert fc1.addressable_shards[0].data.shape[1] == fc1.shape[1] // 8
    qkv = blk["attn"]["query"]["kernel"]
    assert qkv.sharding.spec in (P(), P(None, None, None))  # 4 heads % 8 != 0


@pytest.mark.slow
def test_tp_zero1_composition_shards_opt_state_and_matches_dp():
    """ZeRO-1 layered on TP (Megatron+ZeRO): params keep the TP layout, Adam
    moments additionally shard their largest TP-unsharded dim over 'data' —
    and the math is still exactly DP."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tfde_tpu.models.vit import vit_tiny_test
    from tfde_tpu.training.step import init_state, make_train_step

    # SGD+momentum, not Adam: the trace slot is params-shaped (what ZeRO-1
    # shards), and Adam's m/sqrt(v) early steps amplify reduction-order
    # noise to O(lr) (same rationale as the other layout-parity tests)
    strat = TensorParallelStrategy(data=2, zero1=True, min_shard_elems=1)
    m = vit_tiny_test()
    sample = np.zeros((16, 32, 32, 3), np.float32)
    tx = optax.sgd(0.05, momentum=0.9)
    state, _ = init_state(m, tx, strat, sample, seed=0)

    # a column-parallel qkv kernel: P(None,'tensor',None) params, and its
    # momentum slot gains 'data' on the embed dim
    enc0 = lambda tree: tree["encoder"]["block_0"]["attn"]["query"]["kernel"]
    assert enc0(state.params).sharding.spec == P(None, "tensor", None)
    trace = state.opt_state[0].trace
    assert tuple(enc0(trace).sharding.spec) == ("data", "tensor", None)

    # numerics: 3 momentum-SGD steps under zero1+TP == plain DP
    step = make_train_step(strat, state, donate=False)
    rng = np.random.default_rng(0)
    images = rng.random((16, 32, 32, 3), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    key = jax.random.key(0)
    for _ in range(3):
        state, metrics = step(state, (images, labels), key)

    strat_d = MultiWorkerMirroredStrategy()
    state_d, _ = init_state(m, optax.sgd(0.05, momentum=0.9), strat_d,
                            sample, seed=0)
    step_d = make_train_step(strat_d, state_d, donate=False)
    for _ in range(3):
        state_d, metrics_d = step_d(state_d, (images, labels), key)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(metrics_d["loss"]), rtol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        ),
        jax.device_get(state.params), jax.device_get(state_d.params),
    )


def test_tp_matches_dp_numerics_llama_decoder():
    """The LLaMA-config decoder (rope + GQA + RMSNorm + swiglu + bias-free)
    under dp x tp must match pure DP exactly: the 'gate' projection shards
    column-parallel like fc1 (same ffn shard, so the elementwise gating
    needs no collective), and the GQA kv heads carry the 'tensor' shard."""
    from tfde_tpu.models.gpt import GPT, next_token_loss
    from tfde_tpu.training.step import make_custom_train_step

    def train(strategy):
        m = GPT(vocab_size=96, hidden_size=32, depth=2, num_heads=4,
                mlp_dim=64, max_position=32, dtype=jnp.float32,
                position="rope", num_kv_heads=2, norm="rms",
                mlp_act="swiglu", use_bias=False, tie_embeddings=False)
        state, _ = init_state(m, optax.sgd(0.05), strategy,
                              np.zeros((16, 16), np.int32), seed=0)
        step = make_custom_train_step(strategy, state, next_token_loss,
                                      donate=False)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 96, (16, 16)).astype(np.int32)
        key = jax.random.key(0)
        for _ in range(3):
            state, metrics = step(state, (toks,), key)
        return jax.device_get(state.params), float(metrics["loss"])

    p_dp, loss_dp = train(MultiWorkerMirroredStrategy())
    # data=4 -> tensor=2: kv_heads=2 divides, so the GQA K/V kernels carry
    # the 'tensor' shard (at tensor=4 they would silently replicate and the
    # documented property would go untested)
    strat_tp = TensorParallelStrategy(data=4)
    specs = strat_tp.params_spec(p_dp)
    blk = specs["decoder"]["block_0"]
    assert blk["mlp"]["gate"]["kernel"] == P(None, "tensor")
    assert blk["attn"]["key"]["kernel"] == P(None, "tensor", None)
    assert blk["attn"]["value"]["kernel"] == P(None, "tensor", None)
    p_tp, loss_tp = train(strat_tp)
    np.testing.assert_allclose(loss_dp, loss_tp, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p_dp, p_tp,
    )
