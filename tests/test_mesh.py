"""Mesh + sharding-rule unit tests (SURVEY.md §4: sharding arithmetic)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tfde_tpu.runtime.mesh import MeshSpec, make_mesh, data_parallel_mesh
from tfde_tpu.parallel import sharding as shd


def test_data_parallel_mesh_spans_all_devices():
    mesh = data_parallel_mesh()
    assert mesh.shape == {"data": 8}


def test_meshspec_fill():
    assert MeshSpec({"data": -1, "tensor": 2}).resolve(8) == {"data": 4, "tensor": 2}


def test_meshspec_rejects_nondivisible():
    with pytest.raises(ValueError):
        MeshSpec({"data": 3}).resolve(8)


def test_meshspec_rejects_unknown_axis():
    with pytest.raises(ValueError):
        MeshSpec({"bogus": 2})


def test_mesh_canonical_axis_order():
    mesh = make_mesh({"tensor": 2, "data": 4})
    assert tuple(mesh.axis_names) == ("data", "tensor")  # canonical order


def test_batch_spec_dp():
    mesh = make_mesh({"data": 8})
    assert shd.batch_spec(mesh) == P("data")


def test_batch_spec_dp_fsdp():
    mesh = make_mesh({"data": 2, "fsdp": 4})
    assert shd.batch_spec(mesh) == P(("data", "fsdp"))


def test_shard_pytree_largest_divisible_dim():
    mesh = make_mesh({"data": 4, "tensor": 2})
    tree = {
        "big": np.zeros((3, 256, 128)),   # 256 divisible by 4 -> dim 1
        "small": np.zeros((8,)),          # below min_elems -> replicated
        "odd": np.zeros((333, 777)),      # nothing divisible -> replicated
    }
    spec = shd.shard_pytree_spec(tree, mesh, "data", min_elems=1024)
    assert spec["big"] == P(None, "data", None)
    assert spec["small"] == P()
    assert spec["odd"] == P()
