"""Prefix-KV cache (inference/prefix_cache.py): the token trie must
return exactly the K/V bytes that were inserted for the longest cached
prefix, stay inside its byte budget via LRU eviction, and — wired into
the batcher — leave greedy outputs bit-identical to a cache-off run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.prefix_cache import (
    PrefixCache,
    is_index_leaf,
    leaf_name,
    resolve,
)
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.observability import metrics


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _solo(model, params, prompt, n, **kw):
    toks, lengths = generate(
        model, params, jnp.asarray(prompt[None, :], jnp.int32),
        max_new_tokens=n, **kw,
    )
    p = prompt.size
    return np.asarray(toks)[0, p : int(lengths[0])]


def _fake_cache(rows=2, length=32, d=2):
    """A stand-in prefill-output pytree: K/V leaves [rows, length, d]
    whose values encode (row, position) so returned segments are
    checkable, plus an index leaf the cache must skip."""
    pos = jnp.arange(length, dtype=jnp.float32)[None, :, None]
    row = 1000.0 * jnp.arange(rows, dtype=jnp.float32)[:, None, None]
    k = jnp.broadcast_to(pos + row, (rows, length, d))
    return {
        "layer0": {"k": k, "v": k + 0.5},
        "cache_index": jnp.zeros((rows,), jnp.int32),
    }


# 64 bytes per trie node with the _fake_cache defaults: two [4, 2]
# float32 segments (k and v)
_NODE_BYTES = 2 * 4 * 2 * 4


def test_insert_and_longest_prefix_match():
    pc = PrefixCache(block=4)
    cache = _fake_cache()
    t = np.arange(10)
    assert pc.insert(t, cache, row=0) == 2   # 8 of 10 tokens are whole blocks

    pre, kv = pc.lookup(t)
    assert pre == 8
    np.testing.assert_array_equal(
        np.asarray(kv["layer0/k"]), np.asarray(cache["layer0"]["k"][0, :8])
    )
    np.testing.assert_array_equal(
        np.asarray(kv["layer0/v"]), np.asarray(cache["layer0"]["v"][0, :8])
    )
    assert "cache_index" not in kv  # index leaves never enter the trie

    # at least one suffix token must remain for the first-token forward:
    # an exactly-covered prompt only reuses up to the previous block
    pre, _ = pc.lookup(t[:8])
    assert pre == 4
    # partial match stops at the first diverging block
    pre, _ = pc.lookup(np.concatenate([t[:4], [99, 98, 97, 96, 95]]))
    assert pre == 4
    # full miss
    pre, kv = pc.lookup(np.asarray([77, 78, 79, 80, 81]))
    assert pre == 0 and kv is None

    st = pc.stats()
    assert st["segments"] == 2
    assert st["bytes"] == 2 * _NODE_BYTES
    assert st["reused_tokens"] == 8 + 4 + 4


def test_lru_eviction_respects_byte_budget():
    cache = _fake_cache()
    pc = PrefixCache(byte_budget=2 * _NODE_BYTES, block=4)
    a = np.arange(9)          # two blocks -> fills the budget
    assert pc.insert(a, cache, row=0) == 2
    assert pc.resident_bytes == 2 * _NODE_BYTES

    b = np.asarray([50, 51, 52, 53, 54])   # one block -> forces eviction
    assert pc.insert(b, cache, row=1) == 1
    assert pc.resident_bytes <= 2 * _NODE_BYTES
    assert pc.stats()["evictions"] == 1
    # the LRU childless victim was a's DEEPEST block; its first block
    # stays reachable, and b is resident
    pre, _ = pc.lookup(a)
    assert pre == 4
    pre, kv = pc.lookup(b)
    assert pre == 4
    np.testing.assert_array_equal(
        np.asarray(kv["layer0/k"]), np.asarray(cache["layer0"]["k"][1, :4])
    )


def test_insert_refuses_rather_than_overruns():
    """Blocks of ONE insert protect each other (op stamps), so an insert
    bigger than the whole budget stores what fits and refuses the rest —
    resident bytes never exceed the budget."""
    cache = _fake_cache()
    pc = PrefixCache(byte_budget=_NODE_BYTES, block=4)
    stored = pc.insert(np.arange(13), cache, row=0)   # wants 3 blocks
    assert stored == 1
    assert pc.resident_bytes <= _NODE_BYTES
    pre, _ = pc.lookup(np.arange(13))
    assert pre == 4


def test_gauges_exported():
    reg = metrics.default_registry()
    reg.reset("serving/prefix")
    pc = PrefixCache(block=4)
    cache = _fake_cache()
    pc.insert(np.arange(9), cache, row=0)
    pc.lookup(np.arange(9))                      # hit
    pc.lookup(np.asarray([90, 91, 92, 93, 94]))  # miss
    st = pc.stats()
    assert reg.get("serving/prefix_hits").value == st["hits"] == 1
    assert reg.get("serving/prefix_misses").value == st["misses"] == 1
    assert reg.get("serving/prefix_bytes").value == st["bytes"]
    assert reg.get("serving/prefix_reused_tokens").value == 8
    assert reg.get("serving/prefix_bytes_saved").value > 0


def test_leaf_name_and_index_filter():
    cache = _fake_cache()
    paths = {
        leaf_name(p): is_index_leaf(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(cache)
    }
    assert paths == {"layer0/k": False, "layer0/v": False,
                     "cache_index": True}


def test_resolve_env_knob(monkeypatch):
    monkeypatch.setenv("TFDE_PREFIX_CACHE", "off")
    assert resolve(None) is None
    monkeypatch.setenv("TFDE_PREFIX_CACHE", "on")
    assert isinstance(resolve(None), PrefixCache)
    monkeypatch.setenv("TFDE_PREFIX_CACHE", "1048576")
    pc = resolve(None)
    assert pc.byte_budget == 1048576
    monkeypatch.delenv("TFDE_PREFIX_CACHE")
    assert resolve(None) is None
    assert resolve(False) is None
    assert resolve(True) is not None
    assert resolve(pc) is pc
    assert resolve(2048).byte_budget == 2048
    with pytest.raises(ValueError):
        resolve("bogus")


def test_batcher_prefix_parity_greedy(lm, rng):
    """The admission fast path end to end: request 1 seeds the trie cold;
    later requests sharing the system prompt admit warm (suffix-only
    prefill onto scattered prefix K/V) and must match their solo runs
    bit for bit."""
    model, params = lm
    sysp = rng.integers(1, 90, 12).astype(np.int64)
    prompts = [
        np.concatenate([sysp, rng.integers(1, 90, k).astype(np.int64)])
        for k in (3, 5, 2, 6)
    ]
    pc = PrefixCache(block=4)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64,
                            prefix_cache=pc)
    assert srv.prefix_cache is pc
    done = {}
    r0 = srv.submit(prompts[0], 8)
    done.update(srv.run())                     # cold: seeds the trie
    rids = [srv.submit(p, 8) for p in prompts[1:]]
    done.update(srv.run())                     # warm waves
    st = pc.stats()
    assert st["hits"] >= len(rids)
    assert st["reused_tokens"] >= 12 * len(rids) - pc.block * len(rids)
    for rid, p in zip([r0] + rids, prompts):
        np.testing.assert_array_equal(
            done[rid], _solo(model, params, p, 8),
            err_msg=f"prompt {p.tolist()}",
        )


def test_plan_clamps_warm_suffix_bucket(lm):
    """A warm admission feeds the suffix at cache position pre_len, so
    its bucket must satisfy pre_len + sbucket <= max_len — otherwise the
    donated suffix prefill's clamped cache write would silently
    overwrite the scattered prefix K/V. The planner shortens the used
    prefix (whole blocks) until a bucket fits, or falls back to cold."""
    model, params = lm
    t = np.arange(1, 65, dtype=np.int64)

    # buckets (8, 48, 64): the matched 24-token prefix leaves no legal
    # bucket for its 30-token suffix (48 > 64 - 24), but a 16-token
    # prefix fits (16 + 48 = 64) — shrink, don't go cold
    pc = PrefixCache(block=8)
    pc.insert(t[:32], _fake_cache(length=32), row=0)
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=64,
                            prompt_buckets=(8, 48, 64), prefix_cache=pc)
    prompt = np.concatenate([t[:24], t[:30] + 100])
    [(kind, key, group)] = srv._plan_wave([(0, prompt, 4, None)])
    assert kind == "warm"
    pre_len, sbucket, _f = key
    assert (pre_len, sbucket) == (16, 48)
    kv = group[0][4]
    assert all(a.shape[0] == 16 for a in kv.values())  # sliced to fit

    # pow-2 buckets, long suffix: NO nonzero prefix admits a legal
    # bucket (suffix 33 rounds to 64 > 64 - 16) -> cold admission
    pc2 = PrefixCache(block=16)
    pc2.insert(t[:32], _fake_cache(length=32), row=0)
    srv2 = ContinuousBatcher(model, params, batch_size=2, max_len=64,
                             prefix_cache=pc2)
    prompt2 = np.concatenate([t[:16], t[:33] + 100])
    [(kind2, key2, _g2)] = srv2._plan_wave([(0, prompt2, 3, None)])
    assert kind2 == "cold" and key2 == 64


def test_batcher_prefix_parity_long_suffix(lm, rng):
    """End to end in the overflow regime: a prefix hit whose suffix
    bucket would not fit past the prefix must still decode bit-identical
    to solo (the planner demotes it to cold instead of corrupting the
    row)."""
    model, params = lm
    sysp = rng.integers(1, 90, 32).astype(np.int64)
    pc = PrefixCache(block=16)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64,
                            prefix_cache=pc)
    done = {}
    r0 = srv.submit(sysp, 6)            # cold: seeds both prefix blocks
    done.update(srv.run())
    long_tail = np.concatenate(
        [sysp[:16], rng.integers(1, 90, 33).astype(np.int64)]
    )                                   # 49 tokens: suffix 33 rounds to 64
    r1 = srv.submit(long_tail, 3)
    done.update(srv.run())
    for rid, p, n in ((r0, sysp, 6), (r1, long_tail, 3)):
        np.testing.assert_array_equal(
            done[rid], _solo(model, params, p, n)
        )


def test_batcher_prefix_parity_shrunk_prefix(lm, rng):
    """End to end through the shrink branch: the planner drops trailing
    prefix blocks until the suffix bucket fits, and the warm wave with
    the SLICED prefix K/V still matches solo bit for bit."""
    model, params = lm
    pc = PrefixCache(block=8)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64,
                            prompt_buckets=(8, 48, 64), prefix_cache=pc)
    base = rng.integers(1, 90, 32).astype(np.int64)
    done = {}
    r0 = srv.submit(base, 8)
    done.update(srv.run())
    p1 = np.concatenate(
        [base[:24], rng.integers(1, 90, 30).astype(np.int64)]
    )                                   # pre_len 24 -> shrunk to 16
    r1 = srv.submit(p1, 8)
    done.update(srv.run())
    assert pc.stats()["hits"] >= 1
    for rid, p, n in ((r0, base, 8), (r1, p1, 8)):
        np.testing.assert_array_equal(
            done[rid], _solo(model, params, p, n)
        )


def test_batcher_prefix_parity_repetition_penalty(lm, rng):
    """The warm path must also reconstruct the penalty presence mask from
    the FULL prompt (cached prefix included), not just the suffix it
    prefills."""
    model, params = lm
    sysp = rng.integers(1, 90, 10).astype(np.int64)
    prompts = [
        np.concatenate([sysp, rng.integers(1, 90, k).astype(np.int64)])
        for k in (3, 4)
    ]
    pc = PrefixCache(block=4)
    srv = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64,
                            repetition_penalty=1.3, prefix_cache=pc)
    done = {}
    r0 = srv.submit(prompts[0], 6)
    done.update(srv.run())
    r1 = srv.submit(prompts[1], 6)
    done.update(srv.run())
    assert pc.stats()["hits"] >= 1
    for rid, p in zip([r0, r1], prompts):
        np.testing.assert_array_equal(
            done[rid],
            _solo(model, params, p, 6, repetition_penalty=1.3),
        )
