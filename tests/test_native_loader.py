"""Native C++ loader tests: build, epoch coverage, tf.data repeat().batch()
semantics parity with the python pipeline, seed determinism, buffer-aliasing
contract (SURVEY.md §4)."""

import threading
import time

import numpy as np
import pytest

from tfde_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)


def _arrays(n=100, d=7):
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.int64).reshape(n, 1)
    return x, y


def test_one_epoch_covers_every_row_once():
    x, y = _arrays()
    loader = native.NativeBatchLoader([x, y], batch_size=16, seed=3, repeat=1)
    seen = []
    for bx, by in loader:
        assert bx.shape[1:] == (7,) and by.shape[1:] == (1,)
        # rows stay consistent across arrays (gather used the same index)
        np.testing.assert_array_equal(bx[:, 0], (by[:, 0] * 7).astype(np.float32))
        seen.extend(by[:, 0].tolist())
    assert sorted(seen) == list(range(100))  # permutation, not sampling
    assert len(seen) == 100  # final short batch of 4 included


def test_drop_remainder_and_repeat_cross_epoch_batches():
    x, y = _arrays(n=10)
    loader = native.NativeBatchLoader(
        [x, y], batch_size=4, seed=0, repeat=2, drop_remainder=True,
        copy=True,  # list() retains batches past slot reuse
    )
    batches = list(loader)
    # 20 rows -> 5 full batches (4th batch spans the epoch boundary)
    assert len(batches) == 5
    all_rows = np.concatenate([b[1][:, 0] for b in batches])
    counts = np.bincount(all_rows, minlength=10)
    assert counts.sum() == 20
    assert counts.max() <= 2  # no row seen 3x in 2 epochs


def test_seed_determinism_and_difference():
    x, y = _arrays(n=50)

    def order(seed):
        loader = native.NativeBatchLoader([y], batch_size=50, seed=seed, repeat=1)
        return next(iter(loader))[0][:, 0].tolist()

    assert order(7) == order(7)
    assert order(7) != order(8)


def test_no_shuffle_is_sequential():
    x, y = _arrays(n=12)
    loader = native.NativeBatchLoader(
        [y], batch_size=5, shuffle=False, repeat=1
    )
    rows = np.concatenate([b[0][:, 0].copy() for b in loader])
    np.testing.assert_array_equal(rows, np.arange(12))


def test_infinite_repeat_streams():
    x, y = _arrays(n=8)
    loader = native.NativeBatchLoader([x], batch_size=8, seed=1)  # infinite
    it = iter(loader)
    for _ in range(10):
        (bx,) = next(it)
        assert bx.shape == (8, 7)
    loader.close()


def test_copy_mode_yields_owned_arrays():
    x, y = _arrays(n=32)
    loader = native.NativeBatchLoader(
        [x], batch_size=8, seed=0, repeat=1, copy=True
    )
    first = next(iter(loader))[0]
    ref = first.copy()
    for _ in loader:  # drain; slot buffers get reused
        pass
    np.testing.assert_array_equal(first, ref)  # copy unaffected by reuse


def test_matches_python_pipeline_multiset():
    """Same multiset of examples per epoch as the python Dataset chain."""
    from tfde_tpu.data import Dataset

    x, y = _arrays(n=40)
    py = Dataset.from_tensor_slices((x, y)).shuffle(40, seed=5).repeat(1).batch(8)
    py_rows = sorted(
        r for b in py for r in b[1][:, 0].tolist()
    )
    nat = native.NativeBatchLoader([x, y], batch_size=8, seed=5, repeat=1)
    nat_rows = sorted(r for b in nat for r in b[1][:, 0].tolist())
    assert py_rows == nat_rows


def test_close_while_consumer_blocked_in_next():
    """Destroying the loader while a consumer thread is blocked inside
    next() must wake it (StopIteration) and return promptly — the round-1/2
    wait predicate ignored `stop`, so this deadlocked (ADVICE r1 low)."""
    n = 1 << 19
    data = np.arange(n, dtype=np.int64).reshape(n, 1)
    # one worker + big batches: the consumer outruns the fill and spends
    # most of its time blocked in next()
    loader = native.NativeBatchLoader(
        [data], batch_size=n // 4, seed=0, num_threads=1, depth=2
    )
    consumed = []

    def consume():
        try:
            for (b,) in loader:  # infinite repeat: only close() ends this
                consumed.append(b.shape[0])
        except Exception:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)  # let it settle into the blocked-in-next steady state
    start = time.perf_counter()
    loader.close()
    t.join(timeout=10.0)
    assert not t.is_alive(), "consumer never woke after destroy"
    assert time.perf_counter() - start < 10.0
    assert consumed, "consumer never received a batch before close"


def test_batch_larger_than_dataset_spans_many_epochs():
    """batch > n_rows: each batch spans 3+ epochs; per-epoch permutation
    coverage must still hold exactly (regression: two-epoch assumption)."""
    y = np.arange(10, dtype=np.int64).reshape(10, 1)
    loader = native.NativeBatchLoader(
        [y], batch_size=32, seed=3, repeat=4, copy=True
    )
    rows = np.concatenate([b[0][:, 0] for b in loader])
    assert len(rows) == 40
    np.testing.assert_array_equal(np.bincount(rows, minlength=10), 4)
