"""Trigger-driven profiling (observability/profiler.py): window parsing,
arm() refusal paths, the ProfileTrigger hub's cooldown/dedupe contract, the
retention-bounded artifact index, and the live drills — an SLO-burn
crossing, a recompile storm, and a straggler flag must each produce a
profile artifact stamped with the trigger reason (and in-flight trace ids)
with no operator action, including the 2-process coordinated capture over
the aggregator push channel."""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from tfde_tpu.observability import metrics
from tfde_tpu.observability import profiler
from tfde_tpu.observability.profiler import (
    ProfileArtifacts,
    ProfileTrigger,
    RoundWindowProfiler,
    StepWindowProfiler,
    _parse_window,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_hub():
    profiler.reset_hub()
    yield
    profiler.reset_hub()


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- window parsing ----------------------------------------------------------
def test_parse_window_matrix():
    assert _parse_window("") is None
    assert _parse_window("0") is None
    assert _parse_window("false") is None
    assert _parse_window("7") == (7, 17)          # 10-step default span
    assert _parse_window("5:9") == (5, 9)
    assert _parse_window("every:0") is None       # disabled, like '0'
    assert _parse_window("every:100") == ("every", 100, 10)
    assert _parse_window("every:100:25") == ("every", 100, 25)
    with pytest.raises(ValueError, match="shorter than the period"):
        _parse_window("every:10:10")              # trace would never close
    with pytest.raises(ValueError):
        _parse_window("every:5:0")


def test_env_garbage_disables_explicit_raises(tmp_path, monkeypatch):
    """The knobs contract: garbage in $TFDE_PROFILE warns and disables
    (a shell typo must not kill a run); the same garbage passed
    explicitly (RunConfig.profile_steps) still raises."""
    monkeypatch.setenv("TFDE_PROFILE", "every:10:10")
    with pytest.warns(UserWarning, match="TFDE_PROFILE"):
        p = StepWindowProfiler(str(tmp_path))
    assert not p.enabled
    with pytest.raises(ValueError):
        StepWindowProfiler(str(tmp_path), window="every:10:10")


def test_resume_global_step_semantics(tmp_path, monkeypatch):
    """Windows are GLOBAL steps: a run resumed at step 6 with window
    (5, 8) opens immediately (mid-window) and closes at 8 — the same
    steps an uninterrupted run would trace."""
    opened, closed = [], []
    monkeypatch.setattr(profiler, "_start_trace", lambda d: opened.append(d))
    monkeypatch.setattr(profiler, "_stop_trace", lambda: closed.append(1))
    p = StepWindowProfiler(str(tmp_path), window=(5, 8))
    for step in range(6, 11):   # resume past the window start
        p.step(step)
    assert len(opened) == 1 and len(closed) == 1
    assert p.windows_traced == 1


def test_arm_refusal_paths(tmp_path, monkeypatch):
    monkeypatch.setattr(profiler, "_start_trace", lambda d: None)
    monkeypatch.setattr(profiler, "_stop_trace", lambda: None)
    # configured window: refuse (operator trace wins over auto-capture)
    p = StepWindowProfiler(str(tmp_path), window=(5, 8))
    assert not p.arm(10, 2)
    # no logdir: refuse
    assert not StepWindowProfiler(None, None).arm(10, 2)
    # bad span: loud
    p2 = StepWindowProfiler(str(tmp_path), window=None)
    with pytest.raises(ValueError):
        p2.arm(10, 0)
    # success, then refuse while the armed window is live
    assert p2.arm(10, span=2, reason="drill")
    assert not p2.arm(20, 2)
    # active trace: refuse
    p2.step(10)
    assert not p2.arm(20, 2)
    # an auto-armed one-shot is consumed on close: armable again
    p2.step(12)
    assert p2.windows_traced == 1
    assert p2.arm(20, 2, reason="drill2")


def test_artifact_index_retention(tmp_path):
    arts = ProfileArtifacts(str(tmp_path), retain=2)
    for i in range(5):
        path = arts.record(f"reason{i}", "step", i, i + 2,
                           traces=["t1", "t2"], logdir=str(tmp_path))
        assert path and os.path.exists(path)
    recs = profiler.list_artifacts(str(tmp_path))
    assert len(recs) == 2                      # oldest pruned
    assert [r["reason"] for r in recs] == ["reason3", "reason4"]
    assert recs[-1]["traces"] == ["t1", "t2"]
    assert recs[-1]["kind"] == "step"
    assert recs[-1]["start"] == 4 and recs[-1]["stop"] == 6
    # no model_dir: record is a no-op, not a crash
    assert ProfileArtifacts(None).record("r", "step", 0, 1) is None


# -- trigger hub -------------------------------------------------------------
def test_trigger_cooldown_and_dedupe():
    clock = _FakeClock()
    hub = ProfileTrigger(cooldown_s=10.0, dedupe_s=60.0, enabled=True,
                         clock=clock)
    calls = []
    hub.register("sink", lambda r, s, i: (calls.append((r, s)), True)[1])
    assert hub.trigger("slo_burn_ttft", span=4)
    assert calls == [("slo_burn_ttft", 4)]
    # global cooldown blocks even a DIFFERENT reason
    assert not hub.trigger("recompile_storm")
    clock.t += 11
    # cooldown passed but the same key is deduped for 60s
    assert not hub.trigger("slo_burn_ttft")
    # a different reason goes through
    assert hub.trigger("recompile_storm", span=2)
    clock.t += 61
    assert hub.trigger("slo_burn_ttft", span=4)
    assert len(calls) == 3


def test_trigger_refusal_preserves_budget():
    """Timestamps are consumed only when a sink actually arms — a refused
    trigger must not start the cooldown and starve the next anomaly."""
    clock = _FakeClock()
    hub = ProfileTrigger(cooldown_s=10.0, dedupe_s=60.0, clock=clock)
    hub.register("refuser", lambda r, s, i: False)
    assert not hub.trigger("slo_burn_ttft")
    hub.register("armer", lambda r, s, i: True)
    # same instant, same key: still fires because nothing was consumed
    assert hub.trigger("slo_burn_ttft")


def test_trigger_disabled_and_broken_sinks():
    hub = ProfileTrigger(cooldown_s=0.0, dedupe_s=0.0, enabled=False,
                         clock=_FakeClock())
    hub.register("sink", lambda r, s, i: True)
    assert not hub.trigger("anything")
    hub2 = ProfileTrigger(cooldown_s=0.0, dedupe_s=0.0, enabled=True,
                          clock=_FakeClock())
    hub2.register("broken", lambda r, s, i: 1 / 0)
    got = []
    # a broken sink is logged, not raised, and the extra_sink still arms
    assert hub2.trigger("x", extra_sink=lambda r, s, i: (got.append(i), True)[1])
    assert got and got[0] == {}


def test_trigger_knob_defaults(monkeypatch):
    monkeypatch.setenv("TFDE_PROFILE_COOLDOWN_S", "5.5")
    monkeypatch.setenv("TFDE_PROFILE_DEDUPE_S", "7.5")
    monkeypatch.setenv("TFDE_PROFILE_TRIGGERS", "off")
    hub = ProfileTrigger()
    assert hub.cooldown_s == 5.5 and hub.dedupe_s == 7.5
    assert not hub.enabled


# -- serving round windows ---------------------------------------------------
def test_round_window_capture(tmp_path, monkeypatch):
    monkeypatch.setattr(profiler, "_start_trace", lambda d: None)
    monkeypatch.setattr(profiler, "_stop_trace", lambda: None)
    arts = ProfileArtifacts(str(tmp_path))
    rp = RoundWindowProfiler(str(tmp_path), artifacts=arts)
    with pytest.raises(ValueError):
        rp.arm(span=0)
    assert rp.arm(span=4, reason="slo_burn_tpot")
    assert not rp.arm(span=4)               # already armed
    rp.on_round(10, traces=["aaa"])         # opens; window [10, 14)
    rp.on_round(12, traces=["bbb"])
    assert rp.windows_traced == 0
    rp.on_round(14, traces=["aaa"])         # closes
    assert rp.windows_traced == 1
    recs = profiler.list_artifacts(str(tmp_path))
    assert len(recs) == 1
    assert recs[0]["reason"] == "slo_burn_tpot"
    assert recs[0]["kind"] == "round"
    assert recs[0]["traces"] == ["aaa", "bbb"]
    assert recs[0]["start"] == 10 and recs[0]["stop"] == 14
    # consumed: re-armable
    assert rp.arm(span=2, reason="again")
    # no logdir: refuses instead of arming a trace it can't write
    assert not RoundWindowProfiler(None).arm(span=2)


# -- live drills: anomaly signal -> artifact, no operator action -------------
def test_slo_burn_drill_produces_stamped_artifact(tmp_path, monkeypatch):
    """The acceptance drill: a forced TTFT SLO burn must arm a serving
    capture through the hub and leave an artifact stamped with the trigger
    reason and the in-flight trace id — record() calls only, no operator
    action. Uses the REAL hub and the real jax.profiler trace."""
    from tfde_tpu.observability.slo import SLOTracker

    monkeypatch.setenv("TFDE_PROFILE_SPAN", "3")
    arts = ProfileArtifacts(str(tmp_path))
    rp = RoundWindowProfiler(str(tmp_path), artifacts=arts)
    profiler.hub().register("serve_round_window", rp.trigger_sink)
    reg = metrics.Registry()
    tracker = SLOTracker(ttft_target_ms=100.0, objective=0.99,
                         registry=reg)
    assert tracker.burn_threshold == 10.0     # TFDE_PROFILE_BURN_THRESHOLD
    for _ in range(10):                       # every request breaches
        tracker.record(ttft_ms=500.0)
    # the batcher side: armed window opens and closes on round boundaries
    rp.on_round(1, traces=["req-trace-1"])
    rp.on_round(5, traces=["req-trace-2"])
    profiler.hub().unregister("serve_round_window")
    recs = profiler.list_artifacts(str(tmp_path))
    assert len(recs) == 1
    assert recs[0]["reason"] == "slo_burn_ttft"
    assert "req-trace-1" in recs[0]["traces"]
    assert "req-trace-2" in recs[0]["traces"]
    # the capture-overhead ledger fed the goodput bucket's source
    cap = metrics.default_registry().snapshot().get("profile/capture")
    assert cap and cap["count"] >= 2          # start + stop observed
    # sustained burn is edge-detected: more breaches don't re-trigger
    # (and the hub cooldown would refuse anyway)
    before = len(profiler.list_artifacts(str(tmp_path)))
    for _ in range(5):
        tracker.record(ttft_ms=500.0)
    assert len(profiler.list_artifacts(str(tmp_path))) == before


def test_recompile_storm_drill_triggers_capture(monkeypatch):
    """A recompile storm (recompile.Site escalation) must reach the hub
    with the site name in the dedupe key."""
    from tfde_tpu.observability import recompile

    fired = []
    profiler.hub().register("probe", lambda r, s, i: (fired.append((r, i)),
                                                      True)[1])
    site = recompile.Site("stormy", stable=True, expect=1,
                          storm_threshold=2, registry=metrics.Registry())
    # settle 3 distinct compiled signatures on a stable expect=1 site:
    # signatures 2 and 3 are unexpected, crossing the storm threshold
    for n in range(3):
        site._settle(("fp", n), 1, 0.01, None)
    assert site.unexpected == 2
    assert fired and fired[0][0] == "recompile_storm"
    assert fired[0][1]["site"] == "stormy"


def test_straggler_drill_triggers_and_broadcasts():
    """A straggler flag must trigger the hub AND (coordinate=True) queue a
    broadcast command that each pushing host receives exactly once."""
    from tfde_tpu.observability.aggregate import ClusterAggregator

    clock = _FakeClock()
    fired = []
    profiler.hub().register("probe", lambda r, s, i: (fired.append((r, i)),
                                                      True)[1])
    agg = ClusterAggregator(
        registry=metrics.Registry(), straggler_factor=1.5,
        coordinate=True, clock=clock,
        on_straggler=lambda h, r: None, on_stale=lambda h, a: None,
    )

    def push(host, step_s, count):
        agg.ingest({"host": host, "metrics": {
            "train/step/sum": step_s * count, "train/step/count": count,
        }})

    for i in range(1, 4):   # deltas need two pushes per host
        push(0, 0.1, i)
        push(1, 1.0, i)     # 10x the median: straggler
    assert fired and fired[0][0] == "straggler"
    assert fired[0][1]["host"] == 1
    # the broadcast sink queued a command; each host drains it once
    cmd = agg.pending_profile(0)
    assert cmd and cmd["reason"] == "straggler"
    assert agg.pending_profile(0) is None      # once per host
    assert agg.pending_profile(1)["id"] == cmd["id"]


def test_push_reply_delivers_coordinated_command():
    """A /push response carrying a profile command must reach the local
    hub stamped `coordinated` (so a chief-side broadcast sink would skip
    it — no broadcast loops); non-JSON legacy replies are ignored."""
    from tfde_tpu.observability.aggregate import _apply_push_reply

    got = []
    profiler.hub().register("probe", lambda r, s, i: (got.append((r, s, i)),
                                                      True)[1])
    _apply_push_reply(b"ok\n")                 # legacy chief: no-op
    assert not got
    _apply_push_reply(json.dumps(
        {"ok": True, "profile": {"id": 3, "reason": "straggler",
                                 "span": 5}}).encode())
    assert len(got) == 1
    reason, span, info = got[0]
    assert reason == "straggler" and span == 5
    assert info["coordinated"] is True


def test_sentry_trip_routes_through_hub(tmp_path, monkeypatch):
    """The sentry's auto-arm now rides the hub (shared cooldown with the
    other triggers) while keeping its own profiler via extra_sink."""
    from tfde_tpu.observability import sentry as sentry_lib

    monkeypatch.setattr(profiler, "_start_trace", lambda d: None)
    monkeypatch.setattr(profiler, "_stop_trace", lambda: None)
    p = StepWindowProfiler(str(tmp_path), window=None)
    mon = sentry_lib.SentryMonitor(
        sentry_lib.SentryConfig(action="warn", profile_span=4), profiler=p)
    mon.on_trip(1, 10, 12)
    assert p._window == (13, 17)               # armed at step+1
    assert p._reason == "sentry_trip"


# -- two-process coordinated capture ----------------------------------------
_CHILD = """
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
model_dir, url = sys.argv[1], sys.argv[2]
from tfde_tpu.observability import aggregate, profiler
rp = profiler.RoundWindowProfiler(
    model_dir, artifacts=profiler.ProfileArtifacts(model_dir))
profiler.hub().register("child_round", rp.trigger_sink)
rounds, deadline = 0, time.time() + 60
while time.time() < deadline:
    aggregate.push_once(url, host=7)
    for _ in range(4):           # drive decode rounds
        rounds += 1
        rp.on_round(rounds, traces=["child-req"])
    if profiler.list_artifacts(model_dir):
        print("CAPTURED", flush=True)
        sys.exit(0)
    time.sleep(0.1)
print("TIMEOUT", flush=True)
sys.exit(1)
"""


def test_two_process_coordinated_capture(tmp_path):
    """The chief-broadcast drill: a trigger on the chief must leave
    profile artifacts on BOTH hosts — locally via its own sink, and on a
    separate pushing process via the /push response channel."""
    from tfde_tpu.observability.aggregate import ClusterAggregator
    from tfde_tpu.observability.exposition import MetricsServer

    chief_dir = str(tmp_path / "chief")
    child_dir = str(tmp_path / "child")
    os.makedirs(child_dir)
    reg = metrics.Registry()
    agg = ClusterAggregator(registry=reg, coordinate=True,
                            on_straggler=lambda h, r: None,
                            on_stale=lambda h, a: None)
    srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                        aggregator=agg)
    rp = RoundWindowProfiler(chief_dir,
                             artifacts=ProfileArtifacts(chief_dir))
    profiler.hub().register("chief_round", rp.trigger_sink)
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""))
    child = subprocess.Popen(
        [sys.executable, str(script), child_dir,
         f"http://127.0.0.1:{srv.port}/push"],
        env=env, cwd=ROOT, stdout=subprocess.PIPE, text=True,
    )
    try:
        # wait for the child's first push, then trigger on the chief
        deadline = time.monotonic() + 60
        while 7 not in agg.hosts() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert 7 in agg.hosts(), "child never pushed"
        assert profiler.trigger("straggler_drill", span=3)
        for r in range(1, 6):                  # chief's own rounds
            rp.on_round(r, traces=["chief-req"])
        out, _ = child.communicate(timeout=90)
    finally:
        child.kill()
        srv.close()
        profiler.hub().unregister("chief_round")
    assert "CAPTURED" in out, f"child saw no coordinated capture: {out!r}"
    chief_recs = profiler.list_artifacts(chief_dir)
    child_recs = profiler.list_artifacts(child_dir)
    assert chief_recs and chief_recs[0]["reason"] == "straggler_drill"
    assert child_recs and child_recs[0]["reason"] == "straggler_drill"
    assert child_recs[0]["traces"] == ["child-req"]


# -- serving front door ------------------------------------------------------
def test_replica_post_profile_end_to_end(tmp_path):
    """POST /profile on a live replica arms a decode-round capture; real
    generated traffic drives the window shut and the artifact lands under
    the replica's model_dir with the operator reason. A second arm while
    one is pending is refused with 409. The Router fans /profile out."""
    import jax
    import jax.numpy as jnp

    from tfde_tpu.inference.router import ReplicaServer, Router
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import gpt_tiny_test

    model = gpt_tiny_test()
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    b = ContinuousBatcher(model, params, batch_size=2, max_len=64)
    rep = ReplicaServer(b, replica_id=0, model_dir=str(tmp_path)).start()
    router = Router([rep.url]).start()
    try:
        def post(url, payload):
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, out = post(f"{router.url}/profile",
                         {"span": 2, "reason": "operator_drill"})
        assert code == 200
        assert out["replicas"] == [{"replica": 0, "armed": True,
                                    "reason": "operator_drill"}]
        # double-arm refused at the replica
        code2, out2 = post(f"{rep.url}/profile", {"span": 2})
        assert code2 == 409 and out2["armed"] is False
        # real traffic closes the window: decode rounds advance in
        # scan_depth jumps and a single short request may finish inside
        # the open window, so keep serving until the artifact lands
        from tfde_tpu.inference.router import request_generate

        deadline = time.monotonic() + 60
        while (not profiler.list_artifacts(str(tmp_path))
               and time.monotonic() < deadline):
            request_generate(router.url, [5, 6, 7], 8)
        recs = profiler.list_artifacts(str(tmp_path))
        assert recs, "no artifact after served traffic"
        assert recs[0]["reason"] == "operator_drill"
        assert recs[0]["kind"] == "round"
    finally:
        router.close()
        rep.close()


# -- goodput bucket ----------------------------------------------------------
def test_goodput_profile_bucket():
    """In-window capture overhead lands in its own ledger bucket and comes
    OUT of compute, so a traced window can't read as a compute
    regression; fractions still sum to 1."""
    from tfde_tpu.observability.goodput import CATEGORIES, GoodputLedger

    assert "profile" in CATEGORIES
    reg = metrics.Registry()
    ledger = GoodputLedger(registry=reg)
    for _ in range(10):
        reg.histogram("train/step").observe(1.0)
    reg.histogram("profile/capture").observe(2.0)   # start+stop dispatch
    rep = ledger.report(wall_seconds=12.0)
    assert rep["seconds"]["profile"] == pytest.approx(2.0)
    assert rep["seconds"]["compute"] == pytest.approx(8.0)  # 10 - 2
    assert sum(rep["seconds"].values()) == pytest.approx(12.0)
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)
