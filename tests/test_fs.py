"""Remote-filesystem working dir: the reference documents --working-dir as a
GCS location (mnist_keras_distributed.py:41-44) and the Estimator machinery
writes events + exports there. These tests drive the same surface against
fsspec's in-memory filesystem (`memory://`) — hermetic stand-in for gs://."""

import json
import struct

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.export.serving import FinalExporter, export_serving, load_serving
from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability.tensorboard import SummaryWriter, _masked_crc
from tfde_tpu.training.lifecycle import Estimator, RunConfig
from tfde_tpu.utils import fs


@pytest.fixture(autouse=True)
def _clean_memory_fs():
    import fsspec

    mem = fsspec.filesystem("memory")
    mem.store.clear()
    yield
    mem.store.clear()


def test_fs_helpers_on_memory():
    base = "memory://fs-helpers"
    assert fs.is_remote(base) and not fs.is_remote("/tmp/x")
    fs.makedirs(fs.join(base, "sub"))
    fs.write_bytes(fs.join(base, "sub", "a.bin"), b"abc")
    assert fs.exists(fs.join(base, "sub", "a.bin"))
    assert fs.isdir(fs.join(base, "sub"))
    assert fs.listdir(fs.join(base, "sub")) == ["a.bin"]
    with fs.fs_open(fs.join(base, "sub", "a.bin"), "rb") as f:
        assert f.read() == b"abc"


def _read_records(data: bytes):
    """TFRecord stream -> list of event payloads, verifying both crcs."""
    records, off = [], 0
    while off < len(data):
        (length,) = struct.unpack("<Q", data[off:off + 8])
        (len_crc,) = struct.unpack("<I", data[off + 8:off + 12])
        assert len_crc == _masked_crc(data[off:off + 8])
        payload = data[off + 12:off + 12 + length]
        (data_crc,) = struct.unpack(
            "<I", data[off + 12 + length:off + 16 + length]
        )
        assert data_crc == _masked_crc(payload)
        records.append(payload)
        off += 16 + length
    return records


def test_summary_writer_remote_logdir():
    w = SummaryWriter("memory://logs")
    w.scalars(1, {"loss": 0.5})
    w.scalars(2, {"loss": 0.25})
    w.flush()
    assert w.path.startswith("memory://logs/events.out.tfevents.")
    with fs.fs_open(w.path, "rb") as f:
        records = _read_records(f.read())
    # file_version header + 2 scalar events, all crc-valid
    assert len(records) == 3
    w.close()


def test_export_roundtrip_remote():
    import jax

    model = PlainCNN()
    variables = model.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)

    def apply_fn(v, x):
        return model.apply(v, x, train=False)

    out_dir = export_serving(
        apply_fn, variables, (None, 784), "memory://exports"
    )
    assert out_dir.startswith("memory://exports/")
    loaded = load_serving("memory://exports")  # resolves newest timestamp
    x = np.random.default_rng(0).random((3, 784), np.float32)
    probs = loaded.predict(x)
    assert probs.shape == (3, 10)
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-5)
    assert loaded.signature["input"]["shape"] == [None, 784]


def test_estimator_remote_model_dir():
    """Full Estimator train + summary + export against a mocked remote
    working dir (checkpointing disabled: Orbax speaks gs:// but not
    memory://; see RunConfig.save_checkpoints_steps)."""
    import jax

    model_dir = "memory://estimator-run"
    est = Estimator(
        PlainCNN(),
        optax.sgd(0.1),
        config=RunConfig(
            model_dir=model_dir,
            save_summary_steps=2,
            log_step_count_steps=2,
            save_checkpoints_steps=None,
        ),
    )
    rng = np.random.default_rng(0)
    images = rng.random((32, 784), np.float32)
    labels = rng.integers(0, 10, (32, 1)).astype(np.int32)

    def input_fn():
        while True:
            yield images, labels

    est.train(input_fn, max_steps=4)
    # events landed remotely
    names = fs.listdir(model_dir)
    events = [n for n in names if n.startswith("events.out.tfevents.")]
    assert events, f"no event file in {names}"

    # export lands under <model_dir>/export/<name>/<timestamp>/
    out = est.export_saved_model(FinalExporter("exporter", (None, 784)))
    assert out.startswith("memory://estimator-run/export/exporter/")
    loaded = load_serving("memory://estimator-run/export/exporter")
    probs = loaded.predict(images[:5])
    assert probs.shape == (5, 10)
    with fs.fs_open(fs.join(out, "signature.json"), "r") as f:
        sig = json.load(f)
    assert sig["framework"] == "tfde_tpu"
    est.close()
