"""Beam-search tests (inference/beam.py): greedy equivalence at K=1,
exhaustive optimality on a tiny vocab, EOS freezing, ordering invariants.

Oracle strategy (SURVEY.md §4): with num_beams == vocab and two generated
tokens, the search is exhaustive over step-1 prefixes, so the best beam must
equal the argmax over ALL vocab^2 continuations scored by the uncached full
forward — beam search checked against brute force, the decode analog of the
TP==DP numerics tests."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.beam import beam_search
from tfde_tpu.inference.decode import generate
from tfde_tpu.models.gpt import GPT


@pytest.fixture(scope="module")
def nano_lm():
    """vocab small enough to brute-force continuations."""
    m = GPT(vocab_size=7, hidden_size=16, depth=2, num_heads=2, mlp_dim=32,
            max_position=16, dtype=jnp.float32)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


def _joint_logprob(model, params, prompt_row, continuation):
    """Sum of log p(token_t | prefix) over the continuation, full forward."""
    toks = list(np.asarray(prompt_row))
    total = 0.0
    for tok in continuation:
        logits = model.apply(
            {"params": params}, jnp.asarray([toks], jnp.int32)
        )
        logp = jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
        total += float(logp[tok])
        toks.append(int(tok))
    return total


def test_beam1_equals_greedy(nano_lm, rng):
    model, params = nano_lm
    prompt = jnp.asarray(rng.integers(0, 7, (2, 3)), jnp.int32)
    greedy, _ = generate(model, params, prompt, max_new_tokens=5)
    beams, scores, lengths = beam_search(
        model, params, prompt, max_new_tokens=5, num_beams=1,
        length_penalty=0.0,
    )
    np.testing.assert_array_equal(np.asarray(beams[:, 0]), np.asarray(greedy))
    np.testing.assert_array_equal(np.asarray(lengths[:, 0]), [8, 8])


@pytest.mark.slow
def test_beam_exhaustive_optimality(nano_lm, rng):
    """num_beams == vocab + 2 steps = exhaustive: the winner must be the
    brute-force argmax over all 49 continuations, and its reported score
    must equal the full-forward joint log-prob."""
    model, params = nano_lm
    prompt = jnp.asarray(rng.integers(0, 7, (1, 3)), jnp.int32)
    beams, scores, _ = beam_search(
        model, params, prompt, max_new_tokens=2, num_beams=7,
        length_penalty=0.0,
    )
    best = tuple(np.asarray(beams)[0, 0, 3:])
    best_score = float(scores[0, 0])

    all_scores = {
        cont: _joint_logprob(model, params, np.asarray(prompt)[0], cont)
        for cont in itertools.product(range(7), repeat=2)
    }
    oracle = max(all_scores, key=all_scores.get)
    assert best == oracle
    np.testing.assert_allclose(best_score, all_scores[oracle], rtol=1e-4,
                               atol=1e-5)


def test_beams_sorted_and_distinct(nano_lm, rng):
    model, params = nano_lm
    prompt = jnp.asarray(rng.integers(0, 7, (2, 3)), jnp.int32)
    beams, scores, _ = beam_search(
        model, params, prompt, max_new_tokens=4, num_beams=4,
        length_penalty=0.0,
    )
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all(), "beams not sorted best-first"
    for row in np.asarray(beams):
        assert len({tuple(x) for x in row}) == 4, "duplicate beams"


def test_beam_eos_freezes_and_pads(nano_lm, rng):
    """Force EOS = the greedy first token: the best beam should finish at
    length prompt+1 and carry pads after it."""
    model, params = nano_lm
    prompt = jnp.asarray(rng.integers(0, 7, (1, 3)), jnp.int32)
    free, _, _ = beam_search(model, params, prompt, max_new_tokens=4,
                             num_beams=3, length_penalty=0.0)
    eos = int(np.asarray(free)[0, 0, 3])
    beams, scores, lengths = beam_search(
        model, params, prompt, max_new_tokens=4, num_beams=3,
        length_penalty=0.0, eos_id=eos, pad_id=0,
    )
    rows = np.asarray(beams)[0]
    lens = np.asarray(lengths)[0]
    finished = [i for i in range(3) if eos in rows[i, 3:]]
    assert finished, "no beam finished despite EOS being the greedy token"
    for i in finished:
        e = list(rows[i, 3:]).index(eos)
        assert lens[i] == 3 + e + 1
        assert (rows[i, 3 + e + 1:] == 0).all()


def test_beam_rejects_bad_args(nano_lm):
    model, params = nano_lm
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="num_beams"):
        beam_search(model, params, prompt, max_new_tokens=2, num_beams=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        beam_search(model, params, prompt, max_new_tokens=0)
    with pytest.raises(ValueError, match="max_position"):
        beam_search(model, params, prompt, max_new_tokens=20)
