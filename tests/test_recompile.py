"""Recompile sentinel (observability/recompile.py): hit/miss counting
against real XLA compiles, bucket-churn storm escalation through the
flight recorder, compile/miss trace breadcrumbs carrying the victim
request ids, the steady-state decode pin (a draining ContinuousBatcher
must produce ZERO unexpected misses), and the memgate gate logic that
turns these counters into a tier-1 failure."""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.observability import (flightrec, memwatch, metrics, recompile,
                                    trace)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    recompile.reset()
    memwatch.reset()
    yield
    recompile.reset()
    memwatch.reset()
    trace.disable()


def _flat():
    return metrics.flatten_snapshot(metrics.default_registry().snapshot())


def test_hit_miss_counting():
    @jax.jit
    def f(x):
        return x * 3.0

    s = recompile.site("t/probe")
    with s.watch((4,)):
        f(jnp.ones(4))  # novel fingerprint, real compile -> expected miss
    with s.watch((4,)):
        f(jnp.ones(4))  # cache hit
    with s.watch((8,)):
        f(jnp.ones(8))  # second bucket: novel again
    snap = s.snapshot()
    assert snap["hits"] == 1
    assert snap["misses"] == 2
    assert snap["signatures"] == 2
    assert snap["unexpected"] == 0
    flat = _flat()
    assert flat["compile/t/probe/misses"] == 2
    assert flat["compile/t/probe/cache_hits"] == 1
    assert flat["compile/t/probe/signatures"] == 2
    if recompile.install():  # monitoring hook present on this JAX
        assert flat["compile/t/probe/seconds_total"] > 0
        assert recompile.process_compiles() >= 2
        assert recompile.seconds_total() > 0
    assert recompile.sites()["t/probe"]["misses"] == 2


def test_stable_site_flags_signatures_past_budget():
    @jax.jit
    def f(x):
        return x + 1.0

    s = recompile.site("t/stable", stable=True, expect=1)
    with s.watch("a"):
        f(jnp.ones(3))
    assert s.unexpected == 0  # first signature is within the budget
    with s.watch("b"):
        f(jnp.ones(5))  # novel, but past expect=1 on a stable site
    assert s.unexpected == 1
    assert _flat()["compile/t/stable/unexpected"] == 1


def test_storm_detection_and_breadcrumbs():
    @jax.jit
    def f(x):
        return jnp.cos(x)

    s = recompile.site("t/storm", storm_threshold=2)
    rec = flightrec.default_recorder()
    for i in range(5):
        with s.watch("pinned-bucket"):
            # a DIFFERENT shape every call forces a real compile while
            # the fingerprint claims nothing changed — cache thrash
            f(jnp.ones(16 + i))
    assert s.misses == 5
    assert s.unexpected == 4  # first call was genuinely novel
    # select by this test's unique site name, not by buffer position:
    # the recorder is a bounded ring shared with every test before this
    # one, so len(events()) plateaus at capacity and an index slice
    # taken when full would always come back empty
    new = [e for e in rec.events() if e.get("site") == "t/storm"]
    crumbs = [e for e in new if e["kind"] == "recompile"]
    assert len(crumbs) == 5
    assert all(e["site"] == "t/storm" for e in crumbs)
    assert [e["unexpected"] for e in crumbs] == [False, True, True, True,
                                                 True]
    storms = [e for e in new if e["kind"] == "recompile_storm"]
    assert len(storms) == 1  # escalates once, not per miss
    assert storms[0]["site"] == "t/storm"
    assert _flat()["compile/storms"] == 1


def test_miss_emits_trace_event_with_victims():
    trace.enable(256)
    trace.clear()

    @jax.jit
    def f(x):
        return x - 1.0

    s = recompile.site("t/traced")
    with s.watch((7,), traces=["req-a", "req-b"]):
        f(jnp.ones(7))
    evs = [e for e in trace.events() if e["name"] == "compile/miss"]
    assert len(evs) == 1
    assert evs[0]["site"] == "t/traced"
    assert evs[0]["traces"] == ["req-a", "req-b"]
    # the victim's own waterfall shows the compile that stalled it
    assert any(e["name"] == "compile/miss"
               for e in trace.events("req-a"))


def test_suppress_routes_to_ledger_overhead():
    if not recompile.install():
        pytest.skip("no jax.monitoring hook on this JAX")

    @jax.jit
    def f(x):
        return x @ x

    before = recompile.process_compiles()
    with recompile.suppress():
        f(jnp.ones((6, 6)))
    assert recompile.process_compiles() == before
    assert _flat()["compile/memwatch_seconds_total"] > 0


def test_steady_state_decode_has_zero_unexpected_misses(rng):
    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import GPT

    # deliberately odd sizes: flax modules hash by field values, so a
    # config another test already decoded with would land warm in the
    # process-wide jit cache and this batcher would (correctly) report
    # all hits — the pin below tolerates that, but a fresh program
    # exercises the novel-miss path too
    model = GPT(vocab_size=89, hidden_size=24, depth=2, num_heads=3,
                mlp_dim=48, max_position=64, dtype=jnp.float32)
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=32,
                            scan_depth=4)
    for plen, n in [(3, 10), (5, 8), (4, 12)]:
        srv.submit(rng.integers(0, 88, plen).astype(np.int64), n)
    srv.run()
    assert srv.idle
    snap = recompile.sites()["serve/decode"]
    # THE pin: the depth ladder (1,2,4) compiles at most once per depth,
    # every one of them a novel fingerprint; steady-state full-depth
    # steps must all be cache hits — zero unexpected misses
    assert snap["unexpected"] == 0
    assert snap["misses"] <= 3
    assert snap["hits"] >= 1
    for name, s in recompile.sites().items():
        if name.startswith("serve/"):
            assert s["unexpected"] == 0, name


def _memgate():
    spec = importlib.util.spec_from_file_location(
        "memgate", os.path.join(ROOT, "tools", "memgate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_memgate_check_fails_on_recompile_regression():
    mg = _memgate()
    base = {"sites": {"serve/decode": {"misses": 3}},
            "programs": {"serve/decode/k4": {"peak_bytes": 1000}}}
    ok = {"sites": {"serve/decode": {"misses": 3}},
          "programs": {"serve/decode/k4": {"peak_bytes": 1000}}}
    assert mg.check(ok, base) == []
    # the injected per-token-recompile pathology: miss count blows past
    # the pinned budget -> the gate must fail
    thrash = {"sites": {"serve/decode": {"misses": 40}},
              "programs": {"serve/decode/k4": {"peak_bytes": 1000}}}
    fails = mg.check(thrash, base)
    assert len(fails) == 1 and "serve/decode" in fails[0]
    assert "40" in fails[0] and "baseline 3" in fails[0]
    # a site the baseline has never seen fails loudly with the
    # re-baseline instruction
    novel = {"sites": {"serve/decode": {"misses": 3},
                       "serve/prefill/new": {"misses": 1}},
             "programs": {"serve/decode/k4": {"peak_bytes": 1000}}}
    assert any("--update" in f for f in mg.check(novel, base))
    # peak-HBM ceiling: slack absorbs drift, a blow-up fails
    within = {"sites": {"serve/decode": {"misses": 3}},
              "programs": {"serve/decode/k4": {"peak_bytes": 1100}}}
    assert mg.check(within, base) == []
    blowup = {"sites": {"serve/decode": {"misses": 3}},
              "programs": {"serve/decode/k4": {"peak_bytes": 1101}}}
    fails = mg.check(blowup, base)
    assert len(fails) == 1 and "ceiling" in fails[0]


def test_memgate_committed_baseline_is_self_consistent():
    mg = _memgate()
    with open(os.path.join(ROOT, "tools", "memgate_baseline.json")) as f:
        base = json.load(f)
    # the baseline must gate the exact observation it was generated from
    obs = {"sites": base["sites"], "programs": base["programs"]}
    assert mg.check(obs, base) == []
    assert "train_step" in base["sites"]
    assert "serve/decode" in base["sites"]
    assert any(n.startswith("serve/prefill") for n in base["programs"])


@pytest.mark.slow
def test_memgate_injection_fails_end_to_end():
    """Acceptance pin: the real gate binary, the real batcher, a genuine
    per-token static-arg churn — memgate --check must exit nonzero."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", TFDE_MEMWATCH="on",
               TFDE_MEMGATE_INJECT="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "memgate.py"),
         "--check"],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=600)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "compiles > baseline" in proc.stdout
