"""Rotary position embeddings (ops/rotary.py + models wiring): the
relative-position invariant, decode-cache equivalence, and mesh
transparency."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.models.gpt import GPT
from tfde_tpu.ops.rotary import apply_rotary


@pytest.fixture(scope="module")
def rope_lm():
    m = GPT(vocab_size=89, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=64, dtype=jnp.float32, position="rope")
    params = m.init(jax.random.key(2), jnp.zeros((2, 8), jnp.int32))["params"]
    return m, params


def test_position_zero_is_identity(rng):
    x = jnp.asarray(rng.standard_normal((2, 1, 3, 8)), jnp.float32)
    out = apply_rotary(x, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_scores_depend_only_on_relative_position(rng):
    """dot(rot(q, i), rot(k, j)) must equal dot(rot(q, i+s), rot(k, j+s))
    — THE RoPE property."""
    q = jnp.asarray(rng.standard_normal((1, 1, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 1, 2, 16)), jnp.float32)

    def score(qpos, kpos):
        qr = apply_rotary(q, jnp.asarray([qpos], jnp.int32))
        kr = apply_rotary(k, jnp.asarray([kpos], jnp.int32))
        return np.asarray(jnp.einsum("bshd,bthd->bhst", qr, kr))

    np.testing.assert_allclose(score(7, 3), score(19, 15), rtol=1e-4,
                               atol=1e-5)
    assert not np.allclose(score(7, 3), score(7, 5), rtol=1e-3)


def test_rope_gpt_has_no_position_table(rope_lm):
    model, params = rope_lm
    assert "wpe" not in params
    assert "wte" in params


def test_rope_gpt_is_causal(rope_lm, rng):
    model, params = rope_lm
    ids = jnp.asarray(rng.integers(0, 89, (2, 16)), jnp.int32)
    out = model.apply({"params": params}, ids)
    ids2 = np.asarray(ids).copy()
    ids2[:, 10:] = (ids2[:, 10:] + 1) % 89
    out2 = model.apply({"params": params}, jnp.asarray(ids2))
    np.testing.assert_allclose(np.asarray(out)[:, :10],
                               np.asarray(out2)[:, :10], rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_rope_decode_matches_full_forward(rope_lm, rng):
    """Rotation rides the cache: cached greedy generation must equal the
    uncached full-forward rollout (the decode oracle, with per-position
    rotation instead of a position table)."""
    from tfde_tpu.inference.decode import generate

    model, params = rope_lm
    prompt = jnp.asarray(rng.integers(0, 89, (2, 5)), jnp.int32)
    out, _ = generate(model, params, prompt, max_new_tokens=7)
    toks = np.asarray(prompt, np.int32)
    for _ in range(7):
        logits = model.apply({"params": params}, jnp.asarray(toks))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), toks)


@pytest.mark.slow
def test_rope_ragged_matches_solo(rope_lm, rng):
    from tfde_tpu.inference.decode import generate, generate_ragged

    model, params = rope_lm
    lengths = [2, 6]
    prompt = np.zeros((2, 6), np.int32)
    rows = [rng.integers(0, 89, (l,)).astype(np.int32) for l in lengths]
    for i, r in enumerate(rows):
        prompt[i, : len(r)] = r
    out, _ = generate_ragged(model, params, jnp.asarray(prompt), lengths,
                             max_new_tokens=4)
    for i, (r, l) in enumerate(zip(rows, lengths)):
        solo, _ = generate(model, params, jnp.asarray(r[None]),
                           max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(out)[i, : l + 4],
                                      np.asarray(solo)[0])


@pytest.mark.slow
def test_rope_trains_and_matches_under_seq_mesh(rope_lm, rng):
    """Rotary is elementwise over the sequence, so the 'seq'-sharded
    forward must equal the unsharded one (ring attention underneath)."""
    import optax

    from tfde_tpu.models.gpt import next_token_loss
    from tfde_tpu.parallel.strategies import SequenceParallelStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    model, params = rope_lm
    ids = jnp.asarray(rng.integers(0, 89, (4, 16)), jnp.int32)
    ref = np.asarray(model.apply({"params": params}, ids))

    strategy = SequenceParallelStrategy(data=2)
    state, _ = init_state(model, optax.sgd(1e-2), strategy,
                          np.zeros((4, 16), np.int32))
    state = state.replace(params=params)
    import jax as _jax

    from tfde_tpu.parallel.axes import use_axes

    with use_axes(strategy.mesh):
        sharded = np.asarray(
            _jax.jit(lambda p, x: model.apply({"params": p}, x))(params, ids)
        )
    np.testing.assert_allclose(sharded, ref, rtol=2e-4, atol=2e-4)

    step = make_custom_train_step(strategy, state, next_token_loss,
                                  donate=False)
    state, m0 = step(state, (ids,), jax.random.key(0))
    for _ in range(5):
        state, m = step(state, (ids,), jax.random.key(0))
    assert float(m["loss"]) < float(m0["loss"])


def test_rope_rejects_odd_head_dim():
    from tfde_tpu.ops.rotary import rotary_angles

    with pytest.raises(ValueError, match="even"):
        rotary_angles(jnp.zeros((4,), jnp.int32), 7)


def test_gpt_rejects_unknown_position_mode():
    m = GPT(vocab_size=89, hidden_size=32, depth=1, num_heads=4, mlp_dim=64,
            max_position=32, dtype=jnp.float32, position="alibi")
    with pytest.raises(ValueError, match="position"):
        m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
