"""Model forward shape/dtype tests + parameter-count parity with the
reference architectures (SURVEY.md §4 "unit tests")."""

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.models.cnn import PlainCNN, BatchNormCNN


def test_plain_cnn_shapes():
    m = PlainCNN()
    x = jnp.zeros((4, 28, 28, 1))
    vars_ = m.init(jax.random.key(0), x, train=False)
    logits = m.apply(vars_, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_plain_cnn_param_count_matches_keras():
    # dwk:32-44: conv 32*(3*3*1)+32=320; dense 64: 13*13*32*64+64=346176+64? ->
    # after valid conv 26x26, pool 13x13 -> flatten 5408; 5408*64+64=346176;
    # dense 10: 64*10+10=650. Total 347146.
    m = PlainCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)), train=False)
    n = sum(p.size for p in jax.tree_util.tree_leaves(vars_["params"]))
    assert n == 347146


def test_bn_cnn_shapes_and_batch_stats():
    m = BatchNormCNN()
    x = jnp.zeros((4, 784))
    vars_ = m.init(jax.random.key(0), x, train=False)
    assert "batch_stats" in vars_
    logits, mutated = m.apply(
        vars_, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(1)},
    )
    assert logits.shape == (4, 10)
    assert "batch_stats" in mutated


def test_bn_cnn_param_count_matches_keras():
    # mnist_keras:79-109 trainable params:
    # conv1 3*3*1*6=54, bn beta 6; conv2 6*6*6*12=2592, bn 12;
    # conv3 6*6*12*24=10368, bn 24; dense 7*7*24*200=235200, bn 200;
    # dense10 200*10+10=2010. total trainable = 250466.
    m = BatchNormCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)
    n = sum(p.size for p in jax.tree_util.tree_leaves(vars_["params"]))
    assert n == 250466


def test_bn_cnn_accepts_flat_and_image_input():
    m = BatchNormCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)
    a = m.apply(vars_, jnp.ones((2, 784)), train=False)
    b = m.apply(vars_, jnp.ones((2, 28, 28, 1)), train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
