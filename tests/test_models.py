"""Model forward shape/dtype tests + parameter-count parity with the
reference architectures (SURVEY.md §4 "unit tests")."""

import jax
import jax.numpy as jnp
import numpy as np

from tfde_tpu.models.cnn import PlainCNN, BatchNormCNN


def test_plain_cnn_shapes():
    m = PlainCNN()
    x = jnp.zeros((4, 28, 28, 1))
    vars_ = m.init(jax.random.key(0), x, train=False)
    logits = m.apply(vars_, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_plain_cnn_param_count_matches_keras():
    # dwk:32-44: conv 32*(3*3*1)+32=320; dense 64: 13*13*32*64+64=346176+64? ->
    # after valid conv 26x26, pool 13x13 -> flatten 5408; 5408*64+64=346176;
    # dense 10: 64*10+10=650. Total 347146.
    m = PlainCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)), train=False)
    n = sum(p.size for p in jax.tree_util.tree_leaves(vars_["params"]))
    assert n == 347146


def test_bn_cnn_shapes_and_batch_stats():
    m = BatchNormCNN()
    x = jnp.zeros((4, 784))
    vars_ = m.init(jax.random.key(0), x, train=False)
    assert "batch_stats" in vars_
    logits, mutated = m.apply(
        vars_, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(1)},
    )
    assert logits.shape == (4, 10)
    assert "batch_stats" in mutated


def test_bn_cnn_param_count_matches_keras():
    # mnist_keras:79-109 trainable params:
    # conv1 3*3*1*6=54, bn beta 6; conv2 6*6*6*12=2592, bn 12;
    # conv3 6*6*12*24=10368, bn 24; dense 7*7*24*200=235200, bn 200;
    # dense10 200*10+10=2010. total trainable = 250466.
    m = BatchNormCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)
    n = sum(p.size for p in jax.tree_util.tree_leaves(vars_["params"]))
    assert n == 250466


def test_bn_cnn_accepts_flat_and_image_input():
    m = BatchNormCNN()
    vars_ = m.init(jax.random.key(0), jnp.zeros((1, 784)), train=False)
    a = m.apply(vars_, jnp.ones((2, 784)), train=False)
    b = m.apply(vars_, jnp.ones((2, 28, 28, 1)), train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_model_summary_matches_real_counts():
    """model.summary() parity (mnist_keras:117/tf2_mnist:143): grouped table
    via abstract shapes only, totals matching the real init."""
    import jax
    import jax.numpy as jnp

    from tfde_tpu.models.cnn import BatchNormCNN
    from tfde_tpu.utils import model_summary

    model = BatchNormCNN()
    text = model_summary(model, jnp.zeros((4, 784)))
    variables = model.init(jax.random.key(0), jnp.zeros((4, 784)))
    total = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    assert f"Total params: {total:,}" in text
    assert "Conv" in text and "Dense" in text
    # non-trainable batch stats reported separately
    stats = sum(x.size for x in jax.tree_util.tree_leaves(variables["batch_stats"]))
    assert f"batch_stats: {stats:,}" in text


def test_model_summary_duck_typed_model():
    """Works for non-flax models (PipelinedLM duck-types init)."""
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.models.pipelined import pipelined_tiny_test
    from tfde_tpu.utils import model_summary

    model = pipelined_tiny_test()
    text = model_summary(model, np.zeros((8, 16), np.int32))
    assert "stages" in text and "Total params:" in text
