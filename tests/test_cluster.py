"""runtime/cluster.py role mapping: the reference's TF_CONFIG contract onto
SPMD ranks — master/chief -> 0, worker i offset when a master exists, the
documented ps-entry drop — plus malformed CLUSTER_SPEC/TF_CONFIG errors."""

import json

import pytest

from tfde_tpu.runtime.cluster import (
    ClusterInfo,
    _rank_from_tf_config,
    coordinator_endpoint,
    resolve_cluster,
)

CLUSTER = {
    "master": ["host0:2222"],
    "worker": ["host1:2222", "host2:2222"],
    "ps": ["host3:2222"],
}


def _cfg(job_type, index, cluster=CLUSTER):
    return {"cluster": cluster, "task": {"type": job_type, "index": index}}


def test_master_maps_to_rank_zero():
    num, pid, norm, idx, coord = _rank_from_tf_config(_cfg("master", 0))
    assert pid == 0 and norm == "chief"
    assert num == 3  # master + 2 workers; the ps entry is dropped
    assert coord == "host0:2222"


def test_chief_alias_maps_to_rank_zero():
    cluster = {"chief": ["c:2222"], "worker": ["w:2222"]}
    num, pid, norm, _, coord = _rank_from_tf_config(_cfg("chief", 0, cluster))
    assert (num, pid, norm) == (2, 0, "chief")
    assert coord == "c:2222"


@pytest.mark.parametrize("i", [0, 1])
def test_worker_offset_by_one_when_master_exists(i):
    num, pid, norm, idx, _ = _rank_from_tf_config(_cfg("worker", i))
    assert pid == i + 1  # master holds rank 0
    assert norm == "worker" and idx == i and num == 3


def test_worker_zero_without_chief_becomes_chief():
    cluster = {"worker": ["w0:2222", "w1:2222"]}
    _, pid0, norm0, _, _ = _rank_from_tf_config(_cfg("worker", 0, cluster))
    _, pid1, norm1, _, _ = _rank_from_tf_config(_cfg("worker", 1, cluster))
    assert (pid0, norm0) == (0, "chief")  # no chief entry: worker 0 is it
    assert (pid1, norm1) == (1, "worker")


def test_ps_entries_dropped_from_ranking():
    num, _, _, _, _ = _rank_from_tf_config(_cfg("master", 0))
    assert num == 3  # not 4: ps hosts provide no SPMD rank


def test_ps_role_refuses_to_launch():
    with pytest.raises(RuntimeError, match="JOB_NAME=ps"):
        _rank_from_tf_config(_cfg("ps", 0))


def test_malformed_cluster_spec_fails_loudly(monkeypatch):
    monkeypatch.delenv("TF_CONFIG", raising=False)
    monkeypatch.delenv("TFDE_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("CLUSTER_SPEC", "{not json")
    with pytest.raises(ValueError, match="CLUSTER_SPEC"):
        resolve_cluster()


def test_malformed_tf_config_fails_loudly(monkeypatch):
    monkeypatch.delenv("TFDE_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("TF_CONFIG", "][")
    with pytest.raises(ValueError, match="TF_CONFIG"):
        resolve_cluster()


def test_cluster_spec_synthesis_roundtrip(monkeypatch):
    # setenv-to-empty (falsy, parsed as absent) rather than delenv: the code
    # under test writes the synthesized TF_CONFIG into os.environ, and
    # monkeypatch only restores vars it touched — this guarantees teardown
    # removes the leak
    monkeypatch.setenv("TF_CONFIG", "")
    monkeypatch.delenv("TFDE_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("CLUSTER_SPEC", json.dumps(CLUSTER))
    monkeypatch.setenv("JOB_NAME", "worker")
    monkeypatch.setenv("TASK_INDEX", "1")
    info = resolve_cluster()
    assert info.num_processes == 3
    assert info.process_id == 2  # worker 1 behind the master
    assert info.job_type == "worker" and info.task_index == 1
    assert not info.is_chief and info.is_distributed
    # the reference contract: the synthesized TF_CONFIG lands in the env
    import os

    synth = json.loads(os.environ["TF_CONFIG"])
    assert synth["cluster"] == CLUSTER


def test_native_contract_takes_precedence(monkeypatch):
    monkeypatch.setenv("TFDE_NUM_PROCESSES", "4")
    monkeypatch.setenv("TFDE_PROCESS_ID", "2")
    monkeypatch.setenv("TFDE_COORDINATOR", "coord:1234")
    monkeypatch.setenv("TF_CONFIG", "ignored garbage")  # never parsed
    info = resolve_cluster()
    assert info == ClusterInfo(4, 2, "coord:1234", "worker", 2)


def test_no_env_is_local_single_process(monkeypatch):
    for var in ("TF_CONFIG", "CLUSTER_SPEC", "TFDE_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    info = resolve_cluster()
    assert info.num_processes == 1 and info.job_type == "local"
    assert info.is_chief and not info.is_distributed


def test_coordinator_endpoint_derives_port():
    assert coordinator_endpoint("host0:2222") == "host0:3233"  # +1011
    assert coordinator_endpoint("host0") == "host0:8476"  # no port: default


def test_coordinator_endpoint_env_override(monkeypatch):
    monkeypatch.setenv("TFDE_COORD_PORT", "9999")
    assert coordinator_endpoint("host0:2222") == "host0:9999"
