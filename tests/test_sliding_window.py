"""Sliding-window attention (the Mistral-family capability): band masking
in the reference einsum, windowed tile skipping in the flash kernel, the
decode-cache band mask, and the GPT `sliding_window` field end to end.

The oracle chain: hand-built band mask -> reference_attention(window=) ->
flash_attention(window=) -> windowed decode == windowed full forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.ops.attention import grouped_attention, reference_attention
from tfde_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=1, s=64, h=2, d=8, dtype=jnp.float32):
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        for _ in range(3)
    )


def test_window_matches_explicit_band_mask(rng):
    q, k, v = _qkv(rng)
    s = q.shape[1]
    rows = jnp.arange(s)[:, None]
    cols = jnp.arange(s)[None, :]
    band = jnp.logical_and(rows >= cols, rows - cols < 7)
    ref = reference_attention(q, k, v, mask=band)
    win = reference_attention(q, k, v, causal=True, window=7)
    np.testing.assert_allclose(np.asarray(win), np.asarray(ref), atol=1e-6)


def test_window_geq_seq_equals_plain_causal(rng):
    q, k, v = _qkv(rng)
    full = reference_attention(q, k, v, causal=True)
    win = reference_attention(q, k, v, causal=True, window=q.shape[1])
    np.testing.assert_allclose(np.asarray(win), np.asarray(full), atol=1e-6)


def test_window_one_attends_self_only(rng):
    q, k, v = _qkv(rng)
    win = reference_attention(q, k, v, causal=True, window=1)
    # softmax over a single position == that position's value row
    np.testing.assert_allclose(np.asarray(win), np.asarray(v), atol=1e-5)


def test_window_requires_causal(rng):
    q, k, v = _qkv(rng)
    with pytest.raises(ValueError, match="causal"):
        reference_attention(q, k, v, window=4)


def test_window_with_gqa(rng):
    q, _, _ = _qkv(rng, h=4)
    _, k, v = _qkv(rng, h=2)
    s = q.shape[1]
    rows = jnp.arange(s)[:, None]
    cols = jnp.arange(s)[None, :]
    band = jnp.logical_and(rows >= cols, rows - cols < 5)
    ref = grouped_attention(q, k, v, mask=band)
    win = grouped_attention(q, k, v, causal=True, window=5)
    np.testing.assert_allclose(np.asarray(win), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("window", [16, 64, 100])
def test_flash_window_matches_reference(rng, window):
    q, k, v = _qkv(rng, s=256, d=16)
    ref = reference_attention(q, k, v, causal=True, window=window)
    fl = flash_attention(q, k, v, causal=True, window=window,
                         block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(fl), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bwd", ["jax", "pallas"])
def test_flash_window_backward_matches_reference(rng, bwd, monkeypatch):
    monkeypatch.setenv("TFDE_FLASH_BWD", bwd)
    q, k, v = _qkv(rng, s=128, d=8)

    def ref_loss(q, k, v):
        return jnp.sum(
            reference_attention(q, k, v, causal=True, window=48) ** 2
        )

    def fl_loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, window=48,
                            block_q=32, block_k=32, interpret=True) ** 2
        )

    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(fl_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_window_requires_causal(rng):
    q, k, v = _qkv(rng, s=128)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=8, interpret=True)


def test_gpt_sliding_window_is_banded(rng):
    """Full-sequence forward: logits at position i must be independent of
    tokens older than i - window + 1 (change them; logits stay put) and
    dependent on tokens inside the band."""
    model = gpt_tiny_test(sliding_window=4)
    tokens = jnp.asarray(rng.integers(0, 97, size=(1, 16)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    base = model.apply({"params": params}, tokens, train=False)
    # mutate a token far outside the last position's band
    far = tokens.at[0, 2].set((tokens[0, 2] + 1) % 97)
    out_far = model.apply({"params": params}, far, train=False)
    np.testing.assert_allclose(np.asarray(base[0, -1]),
                               np.asarray(out_far[0, -1]), atol=1e-5)
    # mutate a token inside the band: logits must move
    near = tokens.at[0, 14].set((tokens[0, 14] + 1) % 97)
    out_near = model.apply({"params": params}, near, train=False)
    assert float(jnp.max(jnp.abs(base[0, -1] - out_near[0, -1]))) > 1e-4


@pytest.mark.slow
def test_windowed_decode_matches_windowed_forward(rng):
    """Greedy generation with the cache must reproduce the windowed
    full-forward rollout token for token (the decode-path band mask is the
    same math as the training band)."""
    from tfde_tpu.inference.decode import generate

    model = gpt_tiny_test(sliding_window=6)
    prompt = jnp.asarray(rng.integers(0, 97, size=(2, 5)), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    toks, _ = generate(model, params, prompt, 10)

    # rollout oracle: repeatedly run the full windowed forward
    cur = prompt
    for _ in range(10):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(cur))


def test_windowed_decode_prefill_longer_than_window(rng):
    """Prefill LONGER than the window: the band must clip cache columns
    already during the prompt forward (the sq>1 branch of the decode
    mask), not just during single-token steps."""
    from tfde_tpu.inference.decode import generate

    model = gpt_tiny_test(sliding_window=3)
    prompt = jnp.asarray(rng.integers(0, 97, size=(2, 9)), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    toks, _ = generate(model, params, prompt, 6)

    cur = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(cur))


@pytest.mark.slow
def test_windowed_decode_with_rope_and_gqa(rng):
    from tfde_tpu.inference.decode import generate

    model = gpt_tiny_test(sliding_window=5, position="rope", num_kv_heads=2)
    prompt = jnp.asarray(rng.integers(0, 97, size=(2, 4)), jnp.int32)
    params = model.init(jax.random.key(0), prompt)["params"]
    toks, _ = generate(model, params, prompt, 8)

    cur = prompt
    for _ in range(8):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(cur))


@pytest.mark.parametrize("window", [4, 8, 100])
def test_window_through_seq_ring_matches_reference(rng, window):
    """The sliding band composes with the 'seq' ring: the ring body masks
    on GLOBAL positions, so bands that span shard boundaries (window > the
    8-position shard) are exact — long-context sliding-window models train
    under sequence parallelism."""
    from tfde_tpu.ops.attention import attention, reference_attention
    from tfde_tpu.parallel import axes as axes_lib
    from tfde_tpu.runtime.mesh import make_mesh

    q, k, v = _qkv(rng, b=2, s=32)
    mesh = make_mesh({"seq": 4, "data": 2})
    expect = reference_attention(q, k, v, causal=True, window=window)
    with axes_lib.use_axes(mesh):
        got = jax.jit(
            lambda q, k, v: attention(q, k, v, causal=True, window=window)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_windowed_gqa_mistral_trains_under_seq_ring(rng):
    """The full Mistral combination — sliding window + GQA + sequence
    parallelism — trains end to end: band and grouping both ride the ring
    body, loss falls."""
    import optax

    from tfde_tpu.data.datasets import synthetic_tokens
    from tfde_tpu.models.gpt import GPT, next_token_loss
    from tfde_tpu.parallel.strategies import SequenceParallelStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    model = GPT(vocab_size=97, hidden_size=32, depth=2, num_heads=4,
                mlp_dim=64, max_position=32, dtype=jnp.float32,
                num_kv_heads=2, sliding_window=8, position="rope")
    strategy = SequenceParallelStrategy(data=2)
    state, _ = init_state(model, optax.adamw(3e-3), strategy,
                          np.zeros((8, 32), np.int32))
    step = make_custom_train_step(strategy, state, next_token_loss,
                                  donate=False)
    toks = synthetic_tokens(128, 32, vocab=96)
    gen = np.random.default_rng(0)
    first = None
    for _ in range(25):
        idx = gen.integers(0, len(toks), 8)
        state, m = step(state, (jnp.asarray(toks[idx]),), jax.random.key(0))
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first - 0.2, (first, float(m["loss"]))
