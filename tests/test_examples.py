"""Entrypoint integration tests (SURVEY.md §4): each reference-equivalent
example runs a few steps on the fake-device mesh, loss decreases, and the
expected artifacts (checkpoint, export) appear — mirroring §3.1-3.4."""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from examples import mnist_estimator, mnist_multiworker, mnist_tf2  # noqa: E402
from tfde_tpu.utils import compat  # noqa: E402


def test_multiworker_example_runs(tmp_path):
    state = mnist_multiworker.main(
        ["--epochs", "2", "--steps-per-epoch", "3", "--model-dir", str(tmp_path)]
    )
    assert int(jax.device_get(state.step)) == 6


def test_estimator_example_end_to_end(tmp_path):
    state, metrics = mnist_estimator.main(
        [
            "--working-dir", str(tmp_path / "wd"),
            "--num-epochs", "0.02",  # ~9 steps at batch 128 over 60k
            "--batch-size", "128",
            "--learning-rate", "0.1",
            "--no-tensorboard",
        ]
    )
    assert int(jax.device_get(state.step)) == int(0.02 * 60000 // 128)
    assert np.isfinite(metrics["loss"])
    # checkpoint + export artifacts (mnist_keras:245-248, §3.4)
    assert os.path.isdir(tmp_path / "wd" / "checkpoints")
    export_root = tmp_path / "wd" / "export" / "exporter"
    stamps = os.listdir(export_root)
    assert stamps, "FinalExporter must write a timestamped artifact"
    from tfde_tpu.export.serving import load_serving

    served = load_serving(str(export_root))
    probs = served.predict(np.zeros((2, 784), np.float32))
    assert probs.shape == (2, 10)


def test_tf2_example_custom_loop():
    state = mnist_tf2.main(["--custom-loop", "--max-steps", "5"])
    assert int(jax.device_get(state.step)) == 5


def test_tf2_example_estimator_path(tmp_path):
    state, metrics = mnist_tf2.main(
        ["--model-dir", str(tmp_path / "m"), "--max-steps", "4"]
    )
    assert int(jax.device_get(state.step)) == 4
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_cifar_resnet_example_smoke():
    from examples import cifar10_resnet

    state = cifar10_resnet.main(
        ["--max-steps", "2", "--batch-size", "8"]  # 8 fake devices -> divisible
    )
    assert int(jax.device_get(state.step)) == 2


@pytest.mark.skipif(
    not compat.supports_partial_manual(),
    reason="3D pp x tp needs partial-auto shard_map, unsupported on this jax",
)
def test_gpt_lm_example_3d_smoke():
    """gpt_lm's 3D surface (--pipeline x --tensor) runs a couple of steps
    end-to-end on the fake mesh."""
    from examples import gpt_lm

    state, metrics = gpt_lm.main(
        ["--tiny", "--seq-len", "32", "--max-steps", "2", "--batch-size",
         "16", "--pipeline", "2", "--tensor", "2"]
    )
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_gpt_lm_example_moe_smoke():
    from examples import gpt_lm

    state, metrics = gpt_lm.main(
        ["--tiny", "--seq-len", "32", "--max-steps", "2", "--batch-size",
         "16", "--moe", "4"]
    )
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


@pytest.mark.slow
def test_lora_finetune_example():
    """The LoRA entrypoint end to end: inline base pretrain, q/v-adapter
    fine-tune, merge, generate from the merged params — all on the fake
    mesh. The merged tree must be base-shaped (the export contract)."""
    from examples import lora_finetune

    base, merged = lora_finetune.main(
        ["--tiny", "--max-steps", "5", "--pretrain-steps", "5",
         "--seq-len", "16", "--batch-size", "16", "--generate", "4"]
    )
    # base-shaped: same tree structure and leaf shapes as the frozen base
    assert (jax.tree_util.tree_structure(merged)
            == jax.tree_util.tree_structure(base))
    for mb, bb in zip(jax.tree_util.tree_leaves(merged),
                      jax.tree_util.tree_leaves(base)):
        assert mb.shape == bb.shape
        assert np.isfinite(np.asarray(mb)).all()


def test_serve_gpt_text_requests(tmp_path):
    """--tokenizer + --prompt: text requests ride the continuous batcher
    end to end — encoded offline, decoded back to text. The tokenizer is
    built programmatically (hermetic; nothing downloaded)."""
    pytest.importorskip("tokenizers")
    transformers = pytest.importorskip("transformers")
    from tokenizers import Tokenizer, models, pre_tokenizers

    PreTrainedTokenizerFast = transformers.PreTrainedTokenizerFast

    from examples import serve_gpt

    vocab = {w: i for i, w in enumerate(
        ["[UNK]", "the", "cat", "sat", "on", "mat"]
    )}
    t = Tokenizer(models.WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = pre_tokenizers.Whitespace()
    tok = PreTrainedTokenizerFast(tokenizer_object=t, unk_token="[UNK]")
    tok.save_pretrained(str(tmp_path))

    done = serve_gpt.main(
        ["--tiny", "--tokenizer", str(tmp_path),
         "--prompt", "the cat sat", "--prompt", "on the mat",
         "--max-new-tokens", "4", "--batch-size", "2", "--max-len", "32"]
    )
    assert len(done) == 2 and all(len(toks) for _, toks in done)


def test_serve_gpt_example():
    """The continuous-batching serving demo drains its queue with every
    request completed at full budget."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples import serve_gpt

    done = serve_gpt.main(
        ["--tiny", "--requests", "5", "--batch-size", "2",
         "--max-new-tokens", "6", "--max-len", "32"]
    )
    assert len(done) == 5
    assert all(len(toks) == 6 for _, toks in done)
    # the draft-accelerated path drains the same queue
    done = serve_gpt.main(
        ["--tiny", "--requests", "3", "--batch-size", "2",
         "--max-new-tokens", "5", "--max-len", "32", "--num-draft", "2"]
    )
    assert len(done) == 3
    assert all(len(toks) == 5 for _, toks in done)


@pytest.mark.slow
def test_t5_seq2seq_example_smoke():
    """The encoder-decoder entrypoint: seq2seq training + generation run
    end-to-end on the fake mesh."""
    from examples import t5_seq2seq

    state, metrics = t5_seq2seq.main(
        ["--tiny", "--seq-len", "8", "--max-steps", "2", "--batch-size",
         "16", "--generate", "2"]
    )
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    assert int(jax.device_get(state.step)) == 2


def test_gpt_lm_packed_smoke():
    from examples import gpt_lm

    state, metrics = gpt_lm.main(
        ["--tiny", "--rope", "--packed", "--seq-len", "32", "--max-steps",
         "2", "--batch-size", "16", "--train-examples", "64"]
    )
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
