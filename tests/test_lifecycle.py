"""Estimator lifecycle integration tests (SURVEY.md §4 "integration tests"):
few-step runs on fake devices asserting loss decreases and checkpoint +
export artifacts appear — the observable behavior of §3.1-3.4."""

import glob
import os

import jax
import numpy as np
import optax
import pytest

from tfde_tpu.data import Dataset, datasets
from tfde_tpu.export.serving import FinalExporter, load_serving
from tfde_tpu.models.cnn import BatchNormCNN, PlainCNN
from tfde_tpu.training.lifecycle import (
    Estimator,
    EvalSpec,
    RunConfig,
    TrainSpec,
    train_and_evaluate,
)


def _input_fns(flatten=True, batch=64, eval_batch=None):
    (tx, ty), (ex, ey) = datasets.mnist(flatten=flatten, n_train=512, n_test=128)

    def train_fn():
        return (
            Dataset.from_tensor_slices((tx, ty))
            .shuffle(len(tx), seed=0)
            .repeat()
            .batch(batch, drop_remainder=True)
        )

    def eval_fn():
        return Dataset.from_tensor_slices((ex, ey)).batch(eval_batch or batch)

    return train_fn, eval_fn


@pytest.mark.slow
def test_train_and_evaluate_end_to_end(tmp_path):
    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(
        model_dir=str(tmp_path / "run"),
        save_summary_steps=5,
        log_step_count_steps=10,
        save_checkpoints_steps=10,
    )
    est = Estimator(BatchNormCNN(), optax.sgd(0.2, momentum=0.9), config=cfg)
    exporter = FinalExporter("exporter", (None, 784))
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(train_fn, max_steps=200),
        EvalSpec(eval_fn, exporters=[exporter], start_delay_secs=0, throttle_secs=5),
    )
    est.close()

    assert int(jax.device_get(state.step)) == 200
    # BN running averages (momentum .99, Keras default) need ~150 steps to
    # track the batch statistics before eval-mode accuracy catches up
    assert metrics["accuracy"] > 0.9
    # checkpoint artifact (save_checkpoints_steps=10 -> steps 10,...,200)
    ckpts = os.listdir(tmp_path / "run" / "checkpoints")
    assert any(d.isdigit() for d in ckpts)
    # summaries (train) + eval summaries
    assert glob.glob(str(tmp_path / "run" / "events.out.tfevents.*"))
    assert glob.glob(str(tmp_path / "run" / "eval" / "events.out.tfevents.*"))
    # export artifact serves
    served = load_serving(str(tmp_path / "run" / "export" / "exporter"))
    probs = served.predict(np.zeros((3, 784), np.float32))
    assert probs.shape == (3, 10)


def test_resume_skips_completed_steps(tmp_path):
    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"), save_checkpoints_steps=5)

    est1 = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    est1.train(train_fn, max_steps=7)
    est1.close()

    # "restarted process": new Estimator, same model_dir
    est2 = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    state = est2.train(train_fn, max_steps=7)  # already done -> no-op
    assert int(jax.device_get(state.step)) == 7
    state = est2.train(train_fn, max_steps=10)  # continues 7 -> 10
    assert int(jax.device_get(state.step)) == 10
    est2.close()


def test_evaluate_full_pass_weighting(tmp_path):
    """steps=None must weight by batch size over a ragged final batch."""
    train_fn, eval_fn = _input_fns(eval_batch=50)  # 128 eval -> 50+50+28, padded+masked
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=RunConfig())
    est.train(train_fn, max_steps=2)
    m = est.evaluate(eval_fn)
    assert 0.0 <= m["accuracy"] <= 1.0
    assert np.isfinite(m["loss"])


def test_predict_yields_probabilities():
    train_fn, eval_fn = _input_fns()
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=RunConfig())
    est.train(train_fn, max_steps=2)
    batch_probs = next(iter(est.predict(eval_fn)))
    assert batch_probs.shape[-1] == 10
    np.testing.assert_allclose(batch_probs.sum(-1), 1.0, rtol=1e-5)


def test_evaluate_and_predict_from_checkpoint_after_restart(tmp_path):
    """tf.estimator eval-from-checkpoint flow: a fresh process with the same
    model_dir can evaluate/predict/export without re-training."""
    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"), save_checkpoints_steps=5)
    est1 = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    est1.train(train_fn, max_steps=6)
    est1.close()

    est2 = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)  # "restart"
    m = est2.evaluate(eval_fn)
    assert np.isfinite(m["loss"])
    probs = next(iter(est2.predict(eval_fn)))
    assert probs.shape[-1] == 10
    out = est2.export_saved_model(FinalExporter("exporter", (None, 28, 28, 1)))
    assert out is not None and os.path.exists(os.path.join(out, "model.stablehlo"))
    est2.close()


def test_evaluate_without_state_or_checkpoint_errors():
    _, eval_fn = _input_fns()
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=RunConfig())
    with pytest.raises(RuntimeError, match="no checkpoint"):
        est.evaluate(eval_fn)


@pytest.mark.slow
def test_profile_window_writes_trace(tmp_path):
    """RunConfig.profile_steps captures an XProf trace under
    <model_dir>/plugins/profile — the reference's ProfilerHook capability
    (mnist_keras_distributed.py:235-237,261) restored first-class."""
    train_fn, _ = _input_fns()
    cfg = RunConfig(
        model_dir=str(tmp_path / "run"),
        save_checkpoints_steps=None,
        profile_steps=(2, 4),
    )
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    est.train(train_fn, max_steps=5)
    est.close()
    found = glob.glob(
        os.path.join(str(tmp_path / "run"), "plugins", "profile", "*", "*")
    )
    assert found, "no profiler trace artifacts under model_dir"


def test_eval_distribute_matches_train_strategy_eval(tmp_path):
    """eval_strategy (the reference's DistributeConfig eval_distribute,
    mnist_keras_distributed.py:241-243): training under ParameterServer
    (ZeRO-1) while evaluating under Mirrored must give metrics identical to
    evaluating under the training strategy itself."""
    from tfde_tpu.parallel.strategies import (
        MirroredStrategy,
        ParameterServerStrategy,
    )

    train_fn, eval_fn = _input_fns()
    est_same = Estimator(
        PlainCNN(), optax.sgd(0.1),
        strategy=ParameterServerStrategy(), config=RunConfig(seed=0),
    )
    est_same.train(train_fn, max_steps=4)
    m_same = est_same.evaluate(eval_fn)

    est_cross = Estimator(
        PlainCNN(), optax.sgd(0.1),
        strategy=ParameterServerStrategy(),
        eval_strategy=MirroredStrategy(),
        config=RunConfig(seed=0),
    )
    est_cross.train(train_fn, max_steps=4)
    m_cross = est_cross.evaluate(eval_fn)

    assert m_cross["accuracy"] == m_same["accuracy"]
    np.testing.assert_allclose(m_cross["loss"], m_same["loss"], rtol=1e-6)

    # training continues fine after a cross-strategy eval (state untouched)
    state = est_cross.train(train_fn, max_steps=6)
    assert int(jax.device_get(state.step)) == 6


@pytest.mark.slow
def test_profile_repeating_windows(tmp_path):
    """profile_steps="every:N" re-traces like the reference's
    ProfilerHook(save_steps=100): multiple windows from one training run."""
    train_fn, _ = _input_fns()
    cfg = RunConfig(
        model_dir=str(tmp_path / "run"),
        save_checkpoints_steps=None,
        profile_steps="every:3:1",  # trace 1 step at steps 3, 6, 9...
    )
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    # reach into train's profiler via a fresh one to assert window math,
    # then check the real run produced trace artifacts
    from tfde_tpu.observability.profiler import StepWindowProfiler

    p = StepWindowProfiler.__new__(StepWindowProfiler)
    p._window = ("every", 3, 1)
    assert [s for s in range(1, 10) if p._in_window(s)] == [3, 6, 9]

    est.train(train_fn, max_steps=8)  # windows at 3 and 6
    est.close()
    found = glob.glob(
        os.path.join(str(tmp_path / "run"), "plugins", "profile", "*")
    )
    assert found, "no profiler trace artifacts under model_dir"


def test_continuous_eval_from_checkpoint(tmp_path):
    """eval_mode='from_checkpoint' (the reference's concurrent evaluator,
    mnist_keras_distributed.py:255-283): training runs to completion without
    inline eval pauses while a background evaluator follows the checkpoint
    stream; the final checkpoint is always evaluated."""
    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(
        model_dir=str(tmp_path / "run"),
        save_checkpoints_steps=5,
        save_summary_steps=100,
    )
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(train_fn, max_steps=20),
        EvalSpec(eval_fn, start_delay_secs=0, throttle_secs=0.2),
        eval_mode="from_checkpoint",
    )
    est.close()
    assert int(jax.device_get(state.step)) == 20
    # the evaluator caught the trainer's final force-saved checkpoint
    assert np.isfinite(metrics["loss"])
    assert 0.0 <= metrics["accuracy"] <= 1.0
    # eval summaries came from the evaluator thread
    assert glob.glob(str(tmp_path / "run" / "eval" / "events.out.tfevents.*"))


def test_continuous_eval_standalone_evaluator_job(tmp_path):
    """continuous_eval() as a dedicated evaluator: a separate Estimator
    (fresh process analog) follows checkpoints until stop_after_step."""
    from tfde_tpu.training.lifecycle import continuous_eval

    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"), save_checkpoints_steps=5)
    trainer = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    trainer.train(train_fn, max_steps=10)
    trainer.close()

    evaluator = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    step, metrics = continuous_eval(
        evaluator, EvalSpec(eval_fn, throttle_secs=0.1),
        stop_after_step=10,
    )
    evaluator.close()
    assert step == 10
    assert np.isfinite(metrics["loss"])


def test_continuous_eval_requires_checkpointing():
    train_fn, eval_fn = _input_fns()
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=RunConfig())
    with pytest.raises(ValueError, match="model_dir"):
        train_and_evaluate(
            est, TrainSpec(train_fn, max_steps=2), EvalSpec(eval_fn),
            eval_mode="from_checkpoint",
        )


def test_continuous_eval_under_different_strategy(tmp_path):
    """The two round-3 eval features compose: a PS-trained (ZeRO-1) run with
    a continuous evaluator that restores checkpoints directly into a
    MirroredStrategy layout."""
    from tfde_tpu.parallel.strategies import (
        MirroredStrategy,
        ParameterServerStrategy,
    )

    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"), save_checkpoints_steps=5)
    est = Estimator(
        PlainCNN(), optax.sgd(0.1),
        strategy=ParameterServerStrategy(),
        eval_strategy=MirroredStrategy(),
        config=cfg,
    )
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(train_fn, max_steps=12),
        EvalSpec(eval_fn, start_delay_secs=0, throttle_secs=0.2),
        eval_mode="from_checkpoint",
    )
    est.close()
    assert int(jax.device_get(state.step)) == 12
    assert np.isfinite(metrics["loss"])

    # and the metrics equal an inline same-checkpoint eval
    ref = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    m2 = ref.evaluate(eval_fn)
    ref.close()
    assert metrics["accuracy"] == m2["accuracy"]
    np.testing.assert_allclose(metrics["loss"], m2["loss"], rtol=1e-6)


def test_profiler_window_validation():
    from tfde_tpu.observability.profiler import StepWindowProfiler, _parse_window

    # 'every:0' means disabled, like the documented '0'
    assert _parse_window("every:0") is None
    assert _parse_window("0") is None
    assert _parse_window("every:100") == ("every", 100, 10)
    assert _parse_window("every:100:25") == ("every", 100, 25)
    assert _parse_window("7:12") == (7, 12)
    # span >= period would open a trace that never closes
    with pytest.raises(ValueError, match="never closes"):
        _parse_window("every:10:10")
    with pytest.raises(ValueError, match="span"):
        StepWindowProfiler("/tmp/x", ("every", 10, 12))
    # disabled tuples pass through quietly
    p = StepWindowProfiler("/tmp/x", ("every", 0, 10))
    assert not p.enabled


def test_best_exporter_gates_on_metric(tmp_path):
    """BestExporter (tf.estimator.BestExporter analog): exports only when
    the monitored metric improves; the bar persists in best_metric.json,
    so a worse later eval leaves the artifact set unchanged."""
    import json

    from tfde_tpu.export.serving import BestExporter

    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"),
                    save_checkpoints_steps=100, save_summary_steps=100)
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    best = BestExporter("best", (None, 784), metric="loss")
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(train_fn, max_steps=10),
        EvalSpec(eval_fn, exporters=[best], start_delay_secs=0,
                 throttle_secs=0.0),
    )
    export_dir = tmp_path / "run" / "export" / "best"
    stamps = [d for d in os.listdir(export_dir) if d.isdigit()]
    assert stamps, "an improving first eval must export"
    bar = json.loads((export_dir / "best_metric.json").read_text())
    assert bar["metric"] == "loss" and np.isfinite(bar["value"])
    n_before = len(stamps)

    # a fresh maybe_export with a WORSE metric must refuse
    out = est.export_saved_model(
        best, metrics={"loss": bar["value"] + 100.0}
    )
    assert out is None
    stamps = [d for d in os.listdir(export_dir) if d.isdigit()]
    assert len(stamps) == n_before
    # and a better one exports again and moves the bar
    out = est.export_saved_model(best, metrics={"loss": bar["value"] - 1.0})
    assert out is not None
    bar2 = json.loads((export_dir / "best_metric.json").read_text())
    assert bar2["value"] == bar["value"] - 1.0
    est.close()

    # monitoring a nonexistent metric is loud
    import pytest as _pytest

    with _pytest.raises(ValueError, match="monitors"):
        est2 = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
        est2.export_saved_model(
            BestExporter("best2", (None, 784), metric="nope"),
            metrics={"loss": 1.0},
        )


def test_best_exporter_runs_per_eval_in_continuous_mode(tmp_path):
    """from_checkpoint mode: BestExporter gates inside the evaluator loop
    (per evaluated checkpoint), and the final catch-up keeps the bar
    consistent — artifacts + best_metric.json appear without an inline
    eval ever running."""
    import json

    from tfde_tpu.export.serving import BestExporter

    train_fn, eval_fn = _input_fns()
    cfg = RunConfig(model_dir=str(tmp_path / "run"),
                    save_checkpoints_steps=5, save_summary_steps=100)
    est = Estimator(PlainCNN(), optax.sgd(0.1), config=cfg)
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(train_fn, max_steps=15),
        EvalSpec(eval_fn, exporters=[BestExporter("best", (None, 784))],
                 start_delay_secs=0, throttle_secs=0.2),
        eval_mode="from_checkpoint",
    )
    est.close()
    export_dir = tmp_path / "run" / "export" / "best"
    stamps = [d for d in os.listdir(export_dir) if d.isdigit()]
    assert stamps
    bar = json.loads((export_dir / "best_metric.json").read_text())
    assert np.isfinite(bar["value"])
