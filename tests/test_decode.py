"""Autoregressive generation tests (inference/decode.py): KV-cache
equivalence with the full forward, prefill consistency, sampling filters,
EOS/length bookkeeping, MoE decode, and generation under a data mesh.

The cache-equivalence tests are the decode analog of SURVEY.md §4's
numerics-oracle strategy: the cached incremental decode must reproduce the
uncached full-sequence forward bit-for-bit-ish (fp32 tiny model, tight
tolerances), exactly as TP/PP/EP are tested against their single-device
oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate, init_cache, sample_logits
from tfde_tpu.models.gpt import GPT, gpt_tiny_test


@pytest.fixture(scope="module")
def tiny_lm():
    m = gpt_tiny_test()
    ids = jnp.zeros((2, 8), jnp.int32)
    params = m.init(jax.random.key(1), ids)["params"]
    return m, params


def _full_forward_greedy(model, params, prompt, n_new):
    """Oracle: re-run the whole (uncached) model per token, argmax."""
    toks = np.asarray(prompt, np.int32)
    for _ in range(n_new):
        logits = model.apply({"params": params}, jnp.asarray(toks))
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return toks


@pytest.mark.slow
def test_greedy_cache_matches_full_forward_rollout(tiny_lm, rng):
    model, params = tiny_lm
    prompt = jnp.asarray(rng.integers(0, 97, (2, 5)), jnp.int32)
    out, lengths = generate(model, params, prompt, max_new_tokens=9)
    oracle = _full_forward_greedy(model, params, prompt, 9)
    np.testing.assert_array_equal(np.asarray(out), oracle)
    np.testing.assert_array_equal(np.asarray(lengths), [14, 14])


def test_prefill_logits_match_full_forward(tiny_lm, rng):
    """The cached prefill's last-position logits must equal the uncached
    forward's — same math, different K/V storage."""
    model, params = tiny_lm
    ids = jnp.asarray(rng.integers(0, 97, (2, 6)), jnp.int32)
    full = model.apply({"params": params}, ids)
    dm = model.clone(decode=True)
    cache = init_cache(model, 2, 12)
    cached, _ = dm.apply({"params": params, "cache": cache}, ids,
                         mutable=["cache"])
    np.testing.assert_allclose(
        np.asarray(full[:, -1]), np.asarray(cached[:, -1]),
        rtol=1e-5, atol=1e-5,
    )


def test_decode_step_positions_advance(tiny_lm, rng):
    """After a prefill of length P, each 1-token step must see position
    P, P+1, ... — verified against full-forward logits at those positions."""
    model, params = tiny_lm
    ids = np.asarray(rng.integers(0, 97, (1, 7)), np.int32)
    dm = model.clone(decode=True)
    cache = init_cache(model, 1, 7)
    _, vars_ = dm.apply({"params": params, "cache": cache},
                        jnp.asarray(ids[:, :4]), mutable=["cache"])
    cache = vars_["cache"]
    for t in range(4, 7):
        step_logits, vars_ = dm.apply(
            {"params": params, "cache": cache}, jnp.asarray(ids[:, t:t + 1]),
            mutable=["cache"],
        )
        cache = vars_["cache"]
        full = model.apply({"params": params}, jnp.asarray(ids[:, :t + 1]))
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full[:, -1]),
            rtol=1e-5, atol=1e-5,
        )


def test_top_k1_equals_greedy(tiny_lm, rng):
    model, params = tiny_lm
    prompt = jnp.asarray(rng.integers(0, 97, (2, 4)), jnp.int32)
    greedy, _ = generate(model, params, prompt, max_new_tokens=6)
    topk1, _ = generate(model, params, prompt, max_new_tokens=6,
                        temperature=1.0, top_k=1,
                        rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))


def test_eos_pads_and_lengths(tiny_lm, rng):
    """Pick the token greedy decoding emits first as the EOS: the row must
    freeze at pad_id right after it and lengths must count through it."""
    model, params = tiny_lm
    prompt = jnp.asarray(rng.integers(0, 97, (1, 4)), jnp.int32)
    free, _ = generate(model, params, prompt, max_new_tokens=8)
    eos = int(np.asarray(free)[0, 4])  # first generated token
    out, lengths = generate(model, params, prompt, max_new_tokens=8,
                            eos_id=eos, pad_id=0)
    out = np.asarray(out)
    assert out[0, 4] == eos
    np.testing.assert_array_equal(out[0, 5:], np.zeros(7, np.int32))
    assert int(lengths[0]) == 5  # prompt 4 + the EOS token


def test_sample_logits_filters(rng):
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0]], jnp.float32)
    # top_k=2 may only ever emit ids 3 and 2
    seen = {
        int(sample_logits(logits, jax.random.key(i), temperature=1.0, top_k=2)[0])
        for i in range(50)
    }
    assert seen <= {2, 3} and seen
    # top_p tiny keeps only the argmax (its exclusive mass is 0 < p)
    seen_p = {
        int(sample_logits(logits, jax.random.key(i), temperature=1.0,
                          top_p=1e-6)[0])
        for i in range(20)
    }
    assert seen_p == {3}
    # temperature=0 ignores rng entirely
    assert int(sample_logits(logits, jax.random.key(0),
                             temperature=0.0)[0]) == 3


def test_generate_rejects_over_budget_prompt(tiny_lm):
    model, params = tiny_lm  # max_position=64
    prompt = jnp.zeros((1, 60), jnp.int32)
    with pytest.raises(ValueError, match="max_position"):
        generate(model, params, prompt, max_new_tokens=10)


@pytest.mark.slow
def test_moe_gpt_decodes(rng):
    """Routed-expert MLPs work per-token (capacity is per group, linear in
    this call's tokens — models/moe.py), so MoE-GPT must decode unchanged."""
    m = GPT(vocab_size=61, hidden_size=32, depth=2, num_heads=4, mlp_dim=64,
            max_position=32, dtype=jnp.float32, num_experts=2, moe_every=2)
    ids = jnp.zeros((2, 6), jnp.int32)
    params = m.init(jax.random.key(0), ids)["params"]
    prompt = jnp.asarray(rng.integers(0, 61, (2, 4)), jnp.int32)
    out, lengths = generate(m, params, prompt, max_new_tokens=5)
    assert out.shape == (2, 9)
    oracle = _full_forward_greedy(m, params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(out), oracle)


def test_generate_under_data_mesh(tiny_lm, rng):
    """Generation traced inside use_axes(mesh): the activation constraints
    (and the decode path's cache constraints) must compose with a data-
    sharded batch on the 8-device mesh."""
    from tfde_tpu.parallel.axes import use_axes
    from tfde_tpu.runtime.mesh import make_mesh

    model, params = tiny_lm
    mesh = make_mesh({"data": 8}, jax.devices())
    prompt = jnp.asarray(rng.integers(0, 97, (8, 4)), jnp.int32)
    with use_axes(mesh):
        out, _ = generate(model, params, prompt, max_new_tokens=4)
    ref, _ = generate(model, params, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_under_tensor_mesh(tiny_lm, rng):
    """Generation traced inside a dp x tp mesh: the decode path's cache
    constraints carry the 'tensor' axis (heads sharded) and the result must
    equal the meshless run."""
    from tfde_tpu.parallel.axes import use_axes
    from tfde_tpu.runtime.mesh import make_mesh

    model, params = tiny_lm  # 4 heads: tensor=2 shards them
    mesh = make_mesh({"data": 2, "tensor": 2}, jax.devices()[:4])
    prompt = jnp.asarray(rng.integers(0, 97, (4, 4)), jnp.int32)
    with use_axes(mesh):
        out, _ = generate(model, params, prompt, max_new_tokens=4)
    ref, _ = generate(model, params, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_decode_refuses_remat():
    m = gpt_tiny_test(remat=True).clone(decode=True)
    with pytest.raises(ValueError, match="remat"):
        m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))


def test_generate_serves_remat_trained_model(rng):
    """A remat training config must not make the model unservable:
    generate() clones with remat off (remat only shapes the backward, which
    decode doesn't have) and must match the remat-free model exactly."""
    base = gpt_tiny_test()
    remat = gpt_tiny_test(remat="full")
    params = base.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 97, (1, 4)), jnp.int32)
    out_r, _ = generate(remat, params, prompt, max_new_tokens=5)
    out_b, _ = generate(base, params, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out_r), np.asarray(out_b))


def test_generate_rejects_zero_new_tokens(tiny_lm):
    model, params = tiny_lm
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, params, jnp.zeros((1, 4), jnp.int32),
                 max_new_tokens=0)


def test_ragged_matches_solo_rows(tiny_lm, rng):
    """The ragged-batch oracle: each row of a right-padded variable-length
    batch must generate exactly what a solo generate() on the unpadded row
    produces (teacher-forcing through the prompt tail keeps the cache
    padding-free, so the math per row is identical)."""
    from tfde_tpu.inference.decode import generate_ragged

    model, params = tiny_lm
    lengths = [3, 7, 5]
    p_max, n_new = max(lengths), 6
    prompt = np.zeros((3, p_max), np.int32)
    rows = [rng.integers(0, 97, (l,)).astype(np.int32) for l in lengths]
    for i, r in enumerate(rows):
        prompt[i, : len(r)] = r
    out, out_lengths = generate_ragged(
        model, params, jnp.asarray(prompt), lengths, max_new_tokens=n_new
    )
    out = np.asarray(out)
    np.testing.assert_array_equal(np.asarray(out_lengths),
                                  [l + n_new for l in lengths])
    for i, (r, l) in enumerate(zip(rows, lengths)):
        solo, _ = generate(model, params, jnp.asarray(r[None]),
                           max_new_tokens=n_new)
        np.testing.assert_array_equal(out[i, : l + n_new],
                                      np.asarray(solo)[0])
        assert (out[i, l + n_new:] == 0).all()  # pad beyond the row's end


def test_ragged_eos_per_row(tiny_lm, rng):
    """EOS stops one row's generation without touching the others."""
    from tfde_tpu.inference.decode import generate_ragged

    model, params = tiny_lm
    lengths = [4, 6]
    prompt = np.zeros((2, 6), np.int32)
    rows = [rng.integers(0, 97, (l,)).astype(np.int32) for l in lengths]
    for i, r in enumerate(rows):
        prompt[i, : len(r)] = r
    free, _ = generate_ragged(model, params, jnp.asarray(prompt), lengths,
                              max_new_tokens=5)
    eos = int(np.asarray(free)[0, 4])  # row 0's first generated token
    out, out_lengths = generate_ragged(
        model, params, jnp.asarray(prompt), lengths, max_new_tokens=5,
        eos_id=eos, pad_id=0,
    )
    out = np.asarray(out)
    assert int(out_lengths[0]) == 5  # prompt 4 + the EOS token
    assert (out[0, 5:] == 0).all()
    # row 1 runs its full budget unless it also sampled the eos token
    assert int(out_lengths[1]) >= 7


def test_ragged_validates_inputs(tiny_lm):
    from tfde_tpu.inference.decode import generate_ragged

    model, params = tiny_lm
    prompt = jnp.zeros((2, 6), jnp.int32)
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate_ragged(model, params, prompt, [3], max_new_tokens=2)
    with pytest.raises(ValueError, match=r"\[1, 6\]"):
        generate_ragged(model, params, prompt, [3, 9], max_new_tokens=2)
    with pytest.raises(ValueError, match="prefill_len"):
        generate_ragged(model, params, prompt, [3, 5], max_new_tokens=2,
                        prefill_len=4)


def test_repetition_penalty_sampling_math(rng):
    """CTRL/HF rule on seen ids: positive logits divide by the penalty,
    negative multiply; unseen logits untouched; greedy argmax flips when
    the winner is penalized below the runner-up."""
    from tfde_tpu.inference.decode import sample_logits

    logits = jnp.asarray([[2.0, 1.5, -1.0]], jnp.float32)
    seen = jnp.asarray([[True, False, False]])
    # unpenalized greedy picks 0; penalty 2.0 drops it to 1.0 < 1.5 -> 1
    assert int(sample_logits(logits, jax.random.key(0),
                             temperature=0.0)[0]) == 0
    assert int(sample_logits(logits, jax.random.key(0), temperature=0.0,
                             repetition_penalty=2.0, seen=seen)[0]) == 1
    # negative seen logits get WORSE (multiply)
    logits2 = jnp.asarray([[-0.5, -1.0, -2.0]], jnp.float32)
    seen2 = jnp.asarray([[True, False, False]])
    out = sample_logits(logits2, jax.random.key(0), temperature=0.0,
                        repetition_penalty=3.0, seen=seen2)
    assert int(out[0]) == 1  # -0.5*3=-1.5 < -1.0


def test_generate_repetition_penalty_breaks_loops(rng):
    """A tiny random model greedily loops; the penalty forbids emitting
    the same token twice at high strength, so every output token in the
    budget is distinct (prompt ids count as seen, the HF convention)."""
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.gpt import gpt_tiny_test

    model = gpt_tiny_test()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray(rng.integers(0, 97, (2, 4)), jnp.int32)
    plain, _ = generate(model, params, prompt, max_new_tokens=10)
    pen, _ = generate(model, params, prompt, max_new_tokens=10,
                      repetition_penalty=1e9)
    new = np.asarray(pen[:, 4:])
    for row, pr in zip(new, np.asarray(prompt)):
        emitted = list(pr) + []
        for t in row:
            assert t not in emitted, (t, emitted)
            emitted.append(t)
    # and the knob actually changed the output vs plain greedy
    assert not np.array_equal(np.asarray(plain), np.asarray(pen))


def test_min_p_filters_below_adaptive_floor(rng):
    """min-p keeps exactly the tokens whose probability reaches
    min_p * max-probability; composition after top-k/top-p holds."""
    from tfde_tpu.inference.decode import sample_logits

    # probs ~ [0.643, 0.237, 0.087, 0.032]; floor at 0.5*0.643 = 0.321
    logits = jnp.log(jnp.asarray([[0.643, 0.237, 0.087, 0.032]], jnp.float32))
    picks = set()
    for i in range(200):
        t = sample_logits(logits, jax.random.key(i), temperature=1.0,
                          min_p=0.5)
        picks.add(int(t[0]))
    assert picks == {0}  # only the top token clears the 0.32 floor
    picks = set()
    for i in range(400):
        t = sample_logits(logits, jax.random.key(i), temperature=1.0,
                          min_p=0.3)
        picks.add(int(t[0]))
    assert picks == {0, 1}  # 0.237 clears 0.193; 0.087 does not
