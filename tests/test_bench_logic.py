"""Unit tests for bench.py's trust layer — the pure logic only (peak table,
gating, FLOP formulas, JSON salvage); the measurement paths run on hardware
via the driver and in TFDE_BENCH_SMOKE mode."""

import json

import bench


def test_chip_peak_table_known_kinds():
    assert bench.chip_peak_flops("TPU v5 lite")[0] == 197e12
    assert bench.chip_peak_flops("TPU v5e")[0] == 197e12
    assert bench.chip_peak_flops("TPU v4")[0] == 275e12
    assert bench.chip_peak_flops("TPU v6e")[0] == 918e12
    peak, known = bench.chip_peak_flops("TPU vNext mystery")
    assert not known and peak == bench.DEFAULT_PEAK


def test_gate_withholds_impossible_numbers():
    """The round-2 failure mode (2531 TFLOPs on a 197-TFLOP chip) must be a
    refusal, not a headline."""
    r = {}
    assert not bench._gate(r, "bert", achieved=2531e12, peak=197e12)
    assert "withheld" in r["bert_error"]
    r2 = {}
    assert bench._gate(r2, "bert", achieved=88e12, peak=197e12)
    assert r2 == {}
    # 5% tolerance: just over peak passes (clock jitter), 6% over fails
    assert bench._gate({}, "x", 197e12 * 1.04, 197e12)
    assert not bench._gate({}, "x", 197e12 * 1.06, 197e12)


def test_bert_flops_formula_scales_correctly():
    f = bench.bert_train_flops_per_token
    base = f(768, 3072, 12, 512, 32768)
    # attention term is the only seq-dependent piece: doubling seq adds
    # exactly 3 * depth * 4 * seq * hidden
    assert f(768, 3072, 12, 1024, 32768) - base == 3 * 12 * 4 * 512 * 768
    # BERT-base fwd+bwd ~ 5.8 TFLOP at 8192 tokens/step (the sanity figure
    # VERDICT r2 quoted)
    assert 5e12 < base * 8192 < 7e12


def test_gpt_flops_formula_vs_bert():
    # GPT drops the MLM transform dense (2H^2) and counts causal attention
    # at the EXACT in-band figure — mean (S+1)/2 attended keys vs BERT's
    # bidirectional S (ops/roofline.py; the flash kernels skip future
    # tiles in forward AND backward, so counting full would inflate MFU)
    b = bench.bert_train_flops_per_token(768, 3072, 12, 512, 50257)
    g = bench.gpt_train_flops_per_token(768, 3072, 12, 512, 50257)
    attn_delta = 12 * (4 * 512 * 768 - 4 * 768 * (512 + 1) / 2)
    assert b - g == 3 * (2 * 768 * 768 + attn_delta)


def test_last_json_salvages_cumulative_lines():
    out = "\n".join([
        "some stderr-ish noise",
        json.dumps({"metric": "m", "value": 1, "partial": True}),
        "not json {",
        json.dumps({"metric": "m", "value": 2, "partial": True}),
    ])
    parsed = bench._last_json(out)
    assert parsed["value"] == 2
    assert bench._last_json("no json here") is None
    assert bench._last_json("") is None


def _write(path, obj):
    path.write_text(json.dumps(obj))


def test_newest_builder_artifact_picks_trustworthy(tmp_path):
    """Fallback artifact selection (VERDICT r4 next #1a): newest by mtime
    among captures that parse, ran on tpu, carry the metric contract, and
    passed the calibration trust gate."""
    import os
    import time

    good_old = {"metric": "m", "value": 100.0, "platform": "tpu",
                "calib_frac_of_peak": 0.9}
    good_new = {"metric": "m", "value": 200.0, "platform": "tpu",
                "calib_frac_of_peak": 0.85,
                "watch_captured_at": "2026-07-31T03:40:00Z"}
    bad_calib = {"metric": "m", "value": 300.0, "platform": "tpu",
                 "calib_frac_of_peak": 0.5}
    bad_cpu = {"metric": "m", "value": 400.0, "platform": "cpu",
               "calib_frac_of_peak": 0.99}
    bad_zero = {"metric": "m", "value": 0.0, "platform": "tpu",
                "calib_frac_of_peak": 0.9}
    _write(tmp_path / "BENCH_builder_r03.json", good_old)
    _write(tmp_path / "BENCH_builder_r04.json", good_new)
    _write(tmp_path / "BENCH_builder_watch.json", bad_calib)
    _write(tmp_path / "BENCH_builder_cpu.json", bad_cpu)
    _write(tmp_path / "BENCH_builder_zero.json", bad_zero)
    (tmp_path / "BENCH_builder_garbage.json").write_text("{not json")
    now = time.time()
    os.utime(tmp_path / "BENCH_builder_r03.json", (now - 100, now - 100))
    # untrustworthy files are newer — must still lose to the newest GOOD one
    for f in ("BENCH_builder_watch.json", "BENCH_builder_cpu.json",
              "BENCH_builder_zero.json"):
        os.utime(tmp_path / f, (now + 50, now + 50))
    os.utime(tmp_path / "BENCH_builder_r04.json", (now, now))

    art, fname = bench._newest_builder_artifact(str(tmp_path))
    assert fname == "BENCH_builder_r04.json"
    assert art["value"] == 200.0


def test_newest_builder_artifact_none_when_empty(tmp_path):
    assert bench._newest_builder_artifact(str(tmp_path)) is None
    _write(tmp_path / "BENCH_builder_bad.json",
           {"metric": "m", "value": 1.0, "platform": "tpu",
            "calib_frac_of_peak": 0.2})
    assert bench._newest_builder_artifact(str(tmp_path)) is None


def test_emit_fallback_provenance(tmp_path, monkeypatch, capsys):
    """The outage line must carry the artifact's numbers AND loud
    provenance — never a silent relabel of stale numbers as live, never a
    bare 0.0 when a trustworthy capture exists."""
    art = {"metric": "mnist_bncnn_train_images_per_sec_per_chip",
           "value": 156988.6, "unit": "images/sec/chip", "platform": "tpu",
           "calib_frac_of_peak": 0.9031, "bert_mfu": 0.423,
           "watch_captured_at": "2026-07-31T03:40:31Z"}
    _write(tmp_path / "BENCH_builder_r04.json", art)
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    ok = bench._emit_fallback("TPU backend unavailable after 7 attempts",
                              "probe_failed", "probe hang", 7, 1200.0)
    assert ok
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["value"] == 156988.6
    assert line["bert_mfu"] == 0.423
    assert line["source"] == "builder_watch_artifact"
    assert line["source_file"] == "BENCH_builder_r04.json"
    assert line["captured_at"] == "2026-07-31T03:40:31Z"
    assert "NOT live" in line["staleness_note"]
    assert "unavailable" in line["live_probe_error"]


def test_emit_fallback_false_without_artifact(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    assert not bench._emit_fallback("down", "rc", "tail", 1, 10.0)


def test_moe_flops_formula():
    """Routed FLOPs: k=1 with E tiny reduces to ~dense; k=2 on half the
    layers adds exactly n_moe * 3 * (4HF + 2HE) over dense."""
    h, f, d, s, v = 768, 3072, 12, 1024, 50257
    dense = bench.gpt_train_flops_per_token(h, f, d, s, v)
    moe = bench.moe_gpt_train_flops_per_token(h, f, d, s, v,
                                              num_experts=8,
                                              experts_per_token=2,
                                              moe_every=2)
    n_moe = d // 2
    assert moe - dense == 3 * n_moe * (4 * h * f + 2 * h * 8)


def test_probe_give_up_policy():
    """The r03/r04 failure mode: consecutive probe hangs must hit a cap
    (default 3) or a cumulative probe budget, never the whole bench
    budget. A live backend between failures re-arms the cap (the driver
    resets the consecutive count), so only the pure policy is pinned here."""
    # under both limits: keep probing
    up, _ = bench._probe_give_up(1, 100.0, 1200.0)
    assert not up
    # consecutive cap
    up, why = bench._probe_give_up(3, 100.0, 1200.0)
    assert up and "consecutive" in why
    # cumulative budget (default 40% of the whole budget)
    up, why = bench._probe_give_up(1, 700.0, 1200.0)
    assert up and "consumed" in why
    # cap is configurable
    up, _ = bench._probe_give_up(3, 0.0, 1200.0, max_fails=5)
    assert not up
    # zero budget never divides by zero / trips the fraction rule
    up, _ = bench._probe_give_up(0, 50.0, 0.0)
    assert not up


def test_bench_meta_structure(monkeypatch):
    """Every emitted line's provenance block: schema version, git sha,
    backend identity, and the active TFDE_* knob snapshot (BASELINE.md
    bench_meta schema note)."""
    monkeypatch.setenv("TFDE_BENCH_SMOKE", "1")
    monkeypatch.setenv("TFDE_PROFILE", "every:100")
    meta = bench._bench_meta("tpu", "TPU v4", 4)
    assert meta["schema"] == bench.BENCH_SCHEMA_VERSION == 2
    assert meta["backend"] == {"platform": "tpu", "device_kind": "TPU v4",
                               "n_chips": 4}
    # this repo is a git checkout, so the sha must resolve here
    assert isinstance(meta["git_sha"], str) and len(meta["git_sha"]) == 40
    assert meta["knobs"]["TFDE_BENCH_SMOKE"] == "1"
    assert meta["knobs"]["TFDE_PROFILE"] == "every:100"
    assert all(k.startswith("TFDE_") for k in meta["knobs"])
    # driver-side lines (backend unreachable) omit the backend block
    assert "backend" not in bench._bench_meta()
