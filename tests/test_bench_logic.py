"""Unit tests for bench.py's trust layer — the pure logic only (peak table,
gating, FLOP formulas, JSON salvage); the measurement paths run on hardware
via the driver and in TFDE_BENCH_SMOKE mode."""

import json

import bench


def test_chip_peak_table_known_kinds():
    assert bench.chip_peak_flops("TPU v5 lite")[0] == 197e12
    assert bench.chip_peak_flops("TPU v5e")[0] == 197e12
    assert bench.chip_peak_flops("TPU v4")[0] == 275e12
    assert bench.chip_peak_flops("TPU v6e")[0] == 918e12
    peak, known = bench.chip_peak_flops("TPU vNext mystery")
    assert not known and peak == bench.DEFAULT_PEAK


def test_gate_withholds_impossible_numbers():
    """The round-2 failure mode (2531 TFLOPs on a 197-TFLOP chip) must be a
    refusal, not a headline."""
    r = {}
    assert not bench._gate(r, "bert", achieved=2531e12, peak=197e12)
    assert "withheld" in r["bert_error"]
    r2 = {}
    assert bench._gate(r2, "bert", achieved=88e12, peak=197e12)
    assert r2 == {}
    # 5% tolerance: just over peak passes (clock jitter), 6% over fails
    assert bench._gate({}, "x", 197e12 * 1.04, 197e12)
    assert not bench._gate({}, "x", 197e12 * 1.06, 197e12)


def test_bert_flops_formula_scales_correctly():
    f = bench.bert_train_flops_per_token
    base = f(768, 3072, 12, 512, 32768)
    # attention term is the only seq-dependent piece: doubling seq adds
    # exactly 3 * depth * 4 * seq * hidden
    assert f(768, 3072, 12, 1024, 32768) - base == 3 * 12 * 4 * 512 * 768
    # BERT-base fwd+bwd ~ 5.8 TFLOP at 8192 tokens/step (the sanity figure
    # VERDICT r2 quoted)
    assert 5e12 < base * 8192 < 7e12


def test_gpt_flops_formula_vs_bert():
    # GPT drops the MLM transform dense (2H^2) and counts causal attention
    # at half the bidirectional figure (the flash kernel skips future
    # tiles; counting full would inflate MFU)
    b = bench.bert_train_flops_per_token(768, 3072, 12, 512, 50257)
    g = bench.gpt_train_flops_per_token(768, 3072, 12, 512, 50257)
    assert b - g == 3 * (2 * 768 * 768 + 12 * 2 * 512 * 768)


def test_last_json_salvages_cumulative_lines():
    out = "\n".join([
        "some stderr-ish noise",
        json.dumps({"metric": "m", "value": 1, "partial": True}),
        "not json {",
        json.dumps({"metric": "m", "value": 2, "partial": True}),
    ])
    parsed = bench._last_json(out)
    assert parsed["value"] == 2
    assert bench._last_json("no json here") is None
    assert bench._last_json("") is None
