"""Roofline accounting (ops/roofline.py): the analytic attention-flop
model that bench.py credits MFU with, and the tile-visit pins proving the
flash forward AND backward execute only in-band tiles — the acceptance
gate for the tile-skipping backward (visits <= O(S * window / block^2)
per Q tile for both the dq and dk/dv passes).

Oracle chain: brute-force position loops -> closed-form flop model ->
static tile plan (same predicate the kernels branch on) -> interpret-mode
traced visit counts -> runtime-executed scan steps."""

import numpy as np
import pytest

import bench
from tfde_tpu.ops import flash_attention as fa
from tfde_tpu.ops import roofline as rl


# ---------------------------------------------------------------- flop model


def test_mean_attended_keys_bidirectional_is_full():
    assert rl.mean_attended_keys(512, causal=False) == 512.0
    assert rl.mean_attended_keys(512, causal=False, window=9999) == 512.0


def test_mean_attended_keys_causal_is_exact_triangle():
    # query i attends i+1 keys; the model must be the EXACT mean, not S/2
    for s in (1, 7, 64, 4096):
        brute = sum(i + 1 for i in range(s)) / s
        assert rl.mean_attended_keys(s, causal=True) == pytest.approx(brute)
    assert rl.mean_attended_keys(4096) == 4097 / 2


@pytest.mark.parametrize("s,w", [(37, 5), (64, 64), (256, 1), (512, 128)])
def test_mean_attended_keys_windowed_matches_brute_force(s, w):
    brute = sum(min(i + 1, w) for i in range(s)) / s
    assert rl.mean_attended_keys(s, True, w) == pytest.approx(brute)


def test_mean_attended_keys_window_geq_seq_is_plain_causal():
    assert rl.mean_attended_keys(64, True, 1000) == \
        rl.mean_attended_keys(64, True)


def test_mean_attended_keys_rejects_bad_window():
    with pytest.raises(ValueError, match="window"):
        rl.mean_attended_keys(64, True, 0)


def test_attention_flops_per_token_is_4_width_meankeys():
    assert rl.attention_flops_per_token(768, 512, causal=False) \
        == 4.0 * 768 * 512
    assert rl.attention_flops_per_token(768, 512, causal=True) \
        == pytest.approx(4.0 * 768 * 513 / 2)


def test_stacked_alternate_windows_even_layers_only():
    # transformer.Encoder 'alternate': even block indices banded -> with
    # depth=3 that is layers {0, 2}, i.e. ceil(depth/2) banded layers
    full = rl.attention_flops_per_token(64, 256, True, None)
    band = rl.attention_flops_per_token(64, 256, True, 32)
    got = rl.stacked_attention_flops_per_token(64, 256, 3, True, 32,
                                               "alternate")
    assert got == pytest.approx(2 * band + 1 * full)
    assert rl.stacked_attention_flops_per_token(
        64, 256, 4, True, 32, "all") == pytest.approx(4 * band)
    # no window -> pattern is irrelevant, every layer full
    assert rl.stacked_attention_flops_per_token(
        64, 256, 4, True, None, "alternate") == pytest.approx(4 * full)


def test_stacked_rejects_unknown_pattern():
    with pytest.raises(ValueError, match="window_pattern"):
        rl.stacked_attention_flops_per_token(64, 256, 2,
                                             window_pattern="every_third")


def test_bench_flop_model_credits_windowed_configs():
    """bench.gpt_train_flops_per_token must charge windowed/alternate
    configs their true in-band work (the gpt_long_win MFU denominator),
    and the delta from plain causal must be exactly the attention term."""
    h, m, d, s, v = 768, 3072, 12, 4096, 50257
    full = bench.gpt_train_flops_per_token(h, m, d, s, v)
    alt = bench.gpt_train_flops_per_token(h, m, d, s, v, window=1024,
                                          window_pattern="alternate")
    allw = bench.gpt_train_flops_per_token(h, m, d, s, v, window=1024,
                                           window_pattern="all")
    assert allw < alt < full
    want_delta = 3.0 * (
        rl.stacked_attention_flops_per_token(h, s, d, True)
        - rl.stacked_attention_flops_per_token(h, s, d, True, 1024,
                                               "alternate")
    )
    assert full - alt == pytest.approx(want_delta)


# ------------------------------------------------------------ static plan


def test_static_causal_plan_is_exact_triangle():
    plan = rl.tile_visits(512, 64, 64, causal=True)
    n = 512 // 64
    assert plan["fwd"] == n * (n + 1) // 2 == 36
    assert plan["bwd_dq"] == plan["bwd_dkv"] == plan["fwd"]
    assert plan["grid"] == n * n


def test_static_windowed_plan_respects_band_ceiling():
    """The acceptance bound: per Q tile, at most O(window / block) K tiles
    (window/block in-band plus diagonal/partial straddles) for BOTH
    backward passes — and the total is far below the causal triangle."""
    s, b, w = 1024, 64, 128
    plan = rl.tile_visits(s, b, b, causal=True, window=w)
    ceiling = rl.max_band_tiles_per_q_tile(b, b, w)
    n_q = s // b
    assert plan["max_visits_per_q_tile"] <= ceiling
    assert plan["bwd_dq"] <= n_q * ceiling
    assert plan["bwd_dkv"] <= n_q * ceiling
    causal_full = rl.tile_visits(s, b, b, causal=True)["fwd"]
    assert plan["fwd"] < causal_full / 2  # 46 visits vs the 136 triangle


def test_band_pairs_match_positionwise_brute_force():
    """The tile predicate against the mask semantics themselves: a tile is
    in-band iff it contains at least one (row, col) with row >= col and
    row - col < window. Asymmetric block sizes on purpose."""
    s, bq, bk, w = 256, 64, 32, 48
    rows = np.arange(s)[:, None]
    cols = np.arange(s)[None, :]
    band = (rows >= cols) & (rows - cols < w)
    live = band.reshape(s // bq, bq, s // bk, bk).any(axis=(1, 3))
    brute = {(qi, kb) for qi, kb in zip(*np.nonzero(live))}
    plan = fa.bwd_tile_plan(s, bq, bk, causal=True, window=w)
    assert {tuple(p) for p in plan["pairs"]} == brute
    assert plan["visits"] == len(brute)


# ------------------------------------- traced + runtime-executed schedule


def test_measured_visits_match_plan_causal():
    st = rl.tile_visits(256, 64, 64, causal=True)
    m = rl.measured_tile_visits(seq=256, block_q=64, block_k=64)
    assert m["fwd_visits"] == st["fwd"]
    assert m["bwd_dq_visits"] == st["bwd_dq"]
    assert m["bwd_dkv_visits"] == st["bwd_dkv"]
    # the scan genuinely RAN only the in-band steps (runtime counter
    # bumped from inside the backward's scan body)
    assert m["bwd_steps_executed"] == st["bwd_dq"]


def test_measured_windowed_backward_skips_out_of_band_tiles():
    """The tentpole claim, asserted end to end: with a window the backward
    executes only O(S * window / block^2) tile visits — strictly fewer
    than the causal triangle — and the runtime-executed count agrees.
    Softcap on, so the capped kernels keep the same schedule."""
    s, b, w = 512, 64, 128
    st = rl.tile_visits(s, b, b, causal=True, window=w)
    m = rl.measured_tile_visits(seq=s, block_q=b, block_k=b, window=w,
                                logit_cap=50.0)
    n_q = s // b
    ceiling = rl.max_band_tiles_per_q_tile(b, b, w)
    triangle = n_q * (n_q + 1) // 2
    for key in ("bwd_dq", "bwd_dkv"):
        assert m[f"{key}_visits"] == st[key]
        assert st[key] <= n_q * ceiling < triangle
    assert m["fwd_visits"] == st["fwd"]
    assert m["bwd_steps_executed"] == st["bwd_dq"]


def test_measured_pallas_backward_visits_match_plan(monkeypatch):
    """The Pallas dq/dkv kernel pair (TFDE_FLASH_BWD=pallas) predicates on
    the same band: its traced visit counts per pass must equal the plan."""
    monkeypatch.setenv("TFDE_FLASH_BWD", "pallas")
    st = rl.tile_visits(256, 64, 64, causal=True, window=64)
    m = rl.measured_tile_visits(seq=256, block_q=64, block_k=64, window=64)
    assert m["bwd_dq_visits"] == st["bwd_dq"]
    assert m["bwd_dkv_visits"] == st["bwd_dkv"]


def test_check_tile_visits_gate_passes():
    """The same gate tools/tier1.sh runs via tools/roofline.py
    --check-tiles (covers the GQA head-folded case too)."""
    assert rl.check_tile_visits() == []
