"""Heartbeat / stall-watchdog tests. Poll-style tests run in virtual time;
the watchdog-thread test uses short real timeouts."""

import time

import pytest

from tfde_tpu.observability import counters
from tfde_tpu.resilience.health import Heartbeat, StallError


class VirtualClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_check_passes_while_beating():
    clk = VirtualClock()
    hb = Heartbeat(stall_timeout_secs=10.0, clock=clk)
    hb.beat(1)
    clk.now += 5.0
    hb.check()  # within budget
    hb.beat(2)
    clk.now += 9.9
    hb.check()
    assert hb.last_step == 2


def test_check_raises_stall_error_with_context():
    clk = VirtualClock()
    hb = Heartbeat(stall_timeout_secs=10.0, clock=clk)
    hb.beat(17)
    clk.now += 10.1
    with pytest.raises(StallError) as ei:
        hb.check()
    assert ei.value.last_step == 17
    assert ei.value.age == pytest.approx(10.1)


def test_no_beat_arms_on_first_observation():
    clk = VirtualClock()
    hb = Heartbeat(stall_timeout_secs=5.0, clock=clk)
    hb.check()  # first check arms the timer instead of raising
    clk.now += 5.1
    with pytest.raises(StallError):
        hb.check()


def test_stalls_are_counted():
    counters.reset("resilience/")
    clk = VirtualClock()
    hb = Heartbeat(stall_timeout_secs=1.0, clock=clk)
    hb.beat()
    clk.now += 2.0
    with pytest.raises(StallError):
        hb.check()
    assert counters.value("resilience/stalls_detected") == 1


def test_watchdog_thread_escalates_once_per_stall():
    fired = []
    hb = Heartbeat(stall_timeout_secs=0.2, on_stall=lambda: fired.append(1))
    with hb:
        hb.beat(1)
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        assert fired == [1]
        # still stalled: must NOT re-fire until a beat re-arms
        time.sleep(0.5)
        assert fired == [1]
        hb.beat(2)  # recover ...
        deadline = time.time() + 5.0
        while len(fired) < 2 and time.time() < deadline:
            time.sleep(0.02)  # ... then wedge again -> second escalation
        assert fired == [1, 1]
    assert hb._thread is None  # stop() joined the watchdog


def test_invalid_timeout_rejected():
    with pytest.raises(ValueError):
        Heartbeat(stall_timeout_secs=0.0)
