"""Raw-text pipeline (data/text.py): document splitting, tokenization
with EOS/vocab guards, and the packed batch stream feeding real packed
training end to end with a real (local) tokenizer."""

import numpy as np
import pytest

from tfde_tpu.data.text import (
    packed_text_batches,
    read_documents,
    tokenize_documents,
)

transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tok_dir(tmp_path_factory):
    """A tiny real tokenizer saved locally — character-level WordLevel so
    the test is hermetic (no downloads)."""
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast

    chars = {c: i for i, c in enumerate(
        "abcdefghijklmnopqrstuvwxyz .,!?"
    )}
    chars["<eos>"] = len(chars)
    chars["<unk>"] = len(chars)
    t = Tokenizer(models.WordLevel(chars, unk_token="<unk>"))
    t.pre_tokenizer = pre_tokenizers.Split("", "isolated")
    fast = PreTrainedTokenizerFast(tokenizer_object=t, eos_token="<eos>",
                                   unk_token="<unk>")
    d = tmp_path_factory.mktemp("tok")
    fast.save_pretrained(str(d))
    return str(d)


@pytest.fixture()
def corpus(tmp_path):
    a = tmp_path / "a.txt"
    a.write_text("the cat sat.\n\non the mat!\n\nbirds fly high.")
    b = tmp_path / "b.txt"
    b.write_text("one line\nper document\nhere")
    return a, b


def test_read_documents_splits(corpus):
    a, b = corpus
    assert len(read_documents([str(a)], split="paragraph")) == 3
    assert len(read_documents([str(b)], split="line")) == 3
    assert len(read_documents([str(a), str(b)], split="file")) == 2


def test_tokenize_appends_eos_and_guards_vocab(tok_dir, corpus):
    from tfde_tpu.data.text import load_tokenizer

    tok = load_tokenizer(tok_dir)
    docs = read_documents([str(corpus[0])], split="paragraph")
    arrs = tokenize_documents(docs, tok, append_eos=True)
    assert all(a[-1] == tok.eos_token_id for a in arrs)
    with pytest.raises(ValueError, match="vocab"):
        tokenize_documents(docs, tok, vocab_limit=3)


@pytest.mark.slow
def test_packed_text_batches_train_end_to_end(tok_dir, corpus, rng):
    """The whole journey: text files -> tokenizer -> packed batches ->
    packed training step; loss falls on the tiny corpus."""
    import jax
    import optax

    from tfde_tpu.data.packing import packed_next_token_loss
    from tfde_tpu.data.text import load_tokenizer
    from tfde_tpu.models.gpt import gpt_tiny_test
    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    tok = load_tokenizer(tok_dir)
    m = gpt_tiny_test(position="rope")
    stream = packed_text_batches(
        [str(p) for p in corpus], tok, seq_len=16, batch_size=8,
        vocab_limit=m.vocab_size, seed=0,
    )
    tokens, seg = next(stream)
    assert tokens.shape == (8, 16) and seg.shape == (8, 16)
    assert (tokens[seg > 0] < m.vocab_size).all()

    s = MirroredStrategy()
    state, _ = init_state(m, optax.adamw(3e-3), s, np.zeros_like(tokens),
                          seed=0)
    step = make_custom_train_step(s, state, packed_next_token_loss,
                                  donate=False)
    key = jax.random.key(0)
    first = last = None
    for i in range(20):
        state, metr = step(state, next(stream), key)
        if first is None:
            first = float(metr["loss"])
        last = float(metr["loss"])
    assert last < first, (first, last)
