"""Numerics sentry acceptance: the pure-jnp update() semantics, the host
poll cadence, the no-extra-dispatch/no-callback jaxpr guarantee for the
fused train step, and the end-to-end NaN -> sentry trip -> supervisor
NUMERICS abort path (without hanging)."""

import glob

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.cnn import PlainCNN
from tfde_tpu.observability import metrics
from tfde_tpu.observability.sentry import (
    FLAG_NONFINITE,
    FLAG_SPIKE,
    NumericsError,
    SentryConfig,
    SentryMonitor,
    init_state,
    resolve,
    update,
)
from tfde_tpu.parallel.strategies import MirroredStrategy
from tfde_tpu.training.step import init_state as init_train_state
from tfde_tpu.training.step import make_train_step


# -- update(): the fused device-side check ------------------------------------
def test_finite_steps_never_trip():
    cfg = SentryConfig()
    s = init_state()
    for step in range(5):
        s = update(cfg, s, step, loss=0.5, grad_norm=1.0)
    assert int(s["flag"]) == 0
    assert int(s["trip_step"]) == -1
    assert int(s["count"]) == 5


def test_nonfinite_loss_trips_and_trip_step_is_sticky():
    cfg = SentryConfig()
    s = init_state()
    s = update(cfg, s, 0, loss=1.0)
    s = update(cfg, s, 1, loss=float("nan"))
    assert int(s["flag"]) & FLAG_NONFINITE
    assert int(s["trip_step"]) == 1
    # later trips must NOT move trip_step: the first blow-up is the one
    # the post-mortem wants
    s = update(cfg, s, 2, loss=float("inf"))
    assert int(s["trip_step"]) == 1
    assert int(s["flag"]) & FLAG_NONFINITE


def test_nonfinite_grad_norm_trips():
    s = update(SentryConfig(), init_state(), 0, loss=1.0,
               grad_norm=float("inf"))
    assert int(s["flag"]) & FLAG_NONFINITE


def test_grad_spike_trips_only_after_warmup():
    cfg = SentryConfig(spike_ratio=10.0, warmup_steps=3, ewma_decay=0.5)
    s = init_state()
    # a huge first step is NOT a spike: no baseline yet
    s = update(cfg, s, 0, loss=1.0, grad_norm=100.0)
    assert int(s["flag"]) == 0
    s2 = init_state()
    for step in range(3):  # build the ~1.0 baseline through warmup
        s2 = update(cfg, s2, step, loss=1.0, grad_norm=1.0)
    assert int(s2["flag"]) == 0
    s2 = update(cfg, s2, 3, loss=1.0, grad_norm=100.0)  # 100x the EWMA
    assert int(s2["flag"]) & FLAG_SPIKE
    assert int(s2["trip_step"]) == 3


def test_nan_grad_does_not_poison_ewma_baseline():
    cfg = SentryConfig(warmup_steps=1, ewma_decay=0.5)
    s = init_state()
    s = update(cfg, s, 0, loss=1.0, grad_norm=2.0)
    ewma_before = float(s["ewma"])
    s = update(cfg, s, 1, loss=1.0, grad_norm=float("nan"))
    assert float(s["ewma"]) == ewma_before  # NaN skipped, baseline intact
    assert int(s["count"]) == 1             # ...and not counted


def test_config_validation_and_resolve_sugar():
    with pytest.raises(ValueError):
        SentryConfig(spike_ratio=0.0)
    with pytest.raises(ValueError):
        SentryConfig(ewma_decay=1.5)
    with pytest.raises(ValueError):
        SentryConfig(poll_every=0)
    with pytest.raises(ValueError):
        SentryConfig(action="explode")
    assert resolve(None) is None
    assert resolve(False) is None
    assert isinstance(resolve(True), SentryConfig)
    cfg = SentryConfig(poll_every=7)
    assert resolve(cfg) is cfg
    with pytest.raises(TypeError):
        resolve("yes")


# -- fused step: one dispatch, no callbacks -----------------------------------
def _fused_step_and_args():
    strategy = MirroredStrategy()
    images = np.random.default_rng(0).random((32, 784), np.float32)
    labels = np.zeros((32, 1), np.int32)
    state, _ = init_train_state(PlainCNN(), optax.sgd(0.1), strategy,
                                images)
    step = make_train_step(strategy, state, sentry=SentryConfig())
    return step, state, (images, labels), jax.random.key(0), init_state()


def test_sentry_step_lowering_has_no_host_callback():
    """The satellite guarantee: the sentry rides INSIDE the existing jitted
    step — no pure_callback/io_callback/debug.print sneaks into the
    program, so there is no per-step host sync."""
    step, state, batch, rng, sstate = _fused_step_and_args()
    text = step.lower(state, batch, rng, sstate).as_text()
    assert "callback" not in text
    assert "outfeed" not in text


def test_sentry_step_executes_and_threads_carry():
    step, state, batch, rng, sstate = _fused_step_and_args()
    for i in range(3):
        state, m, sstate = step(state, batch, rng, sstate)
    assert int(sstate["flag"]) == 0
    assert int(sstate["count"]) == 3
    assert np.isfinite(float(m["loss"]))


# -- SentryMonitor: poll cadence + escalation ---------------------------------
def _tripped_state(step=4):
    s = init_state()
    s["flag"] = jnp.asarray(FLAG_NONFINITE, jnp.int32)
    s["trip_step"] = jnp.asarray(step, jnp.int32)
    return s


def test_monitor_skips_off_cadence_steps():
    mon = SentryMonitor(SentryConfig(poll_every=5),
                        registry=metrics.Registry())
    # flag is set, but step 4 is off-cadence: NO device_get, no escalation
    assert mon.maybe_poll(_tripped_state(), 4) is None
    assert mon.trips == 0


def test_monitor_raises_on_cadence_with_action_raise():
    mon = SentryMonitor(SentryConfig(poll_every=5, action="raise"),
                        registry=metrics.Registry())
    assert mon.maybe_poll(init_state(), 5) is None  # clean flag: no trip
    with pytest.raises(NumericsError) as ei:
        mon.maybe_poll(_tripped_state(step=4), 5)
    assert ei.value.flag == FLAG_NONFINITE
    assert ei.value.trip_step == 4
    assert ei.value.observed_step == 5


def test_monitor_warn_action_reports_and_continues():
    reg = metrics.Registry()
    mon = SentryMonitor(SentryConfig(poll_every=2, action="warn"),
                        registry=reg)
    info = mon.maybe_poll(_tripped_state(step=1), 2)
    assert info == {"flag": FLAG_NONFINITE, "trip_step": 1,
                    "observed_step": 2}
    assert mon.trips == 1
    assert reg.counter("sentry/trips").value == 1
    assert reg.gauge("sentry/trip_step").value == 1


# -- end to end: NaN at step k -> supervisor NUMERICS abort, no hang ----------
def test_nan_trips_sentry_and_aborts_supervisor(tmp_path):
    from tfde_tpu.observability import flightrec
    from tfde_tpu.resilience.supervisor import (
        FailureKind,
        Supervisor,
        SupervisorAborted,
        SupervisorConfig,
        classify_failure,
    )
    from tfde_tpu.training.lifecycle import Estimator, RunConfig

    rngd = np.random.default_rng(0)
    images = rngd.random((32, 784), np.float32)
    labels = rngd.integers(0, 10, (32, 1)).astype(np.int32)

    def input_fn():
        def gen():
            while True:
                yield (images, labels)
        return gen()

    def bad_loss(state, params, batch, rng):
        x, y = batch
        logits = state.apply_fn({"params": params}, x, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y[:, 0]).mean()
        # blows up at step >= 2: deterministic, so a restart from the
        # pre-NaN checkpoint would replay it — exactly why NUMERICS is
        # classified non-restartable
        loss = jnp.where(state.step >= 2, jnp.nan, loss)
        return loss, {"loss": loss,
                      "grad_norm": jnp.asarray(0.0, jnp.float32)}

    md = str(tmp_path / "run")

    def factory():
        return Estimator(
            PlainCNN(), optax.sgd(0.1), loss_fn=bad_loss,
            config=RunConfig(model_dir=md, save_checkpoints_steps=None,
                             save_summary_steps=10_000,
                             log_step_count_steps=10_000,
                             sentry=SentryConfig(poll_every=2)),
        )

    sup = Supervisor(factory, SupervisorConfig(max_restarts=3))
    with pytest.raises(SupervisorAborted) as ei:
        sup.run(input_fn, 20)

    cause = ei.value.__cause__
    assert isinstance(cause, NumericsError)
    assert classify_failure(cause) is FailureKind.NUMERICS
    assert sup.restarts == 0  # non-restartable: no retry before the abort

    # the flight ring was dumped on abort and tells the whole story
    files = glob.glob(md + "/debug/flight_*.jsonl")
    assert files, "no flight dump after NUMERICS abort"
    kinds = [e["kind"] for e in flightrec.load(files[0])]
    assert "sentry_trip" in kinds
    assert "supervisor_failure" in kinds
    assert "supervisor_abort" in kinds
    trip = next(e for e in flightrec.load(files[0])
                if e["kind"] == "sentry_trip")
    assert trip["trip_step"] >= 2  # first NaN step, not the poll step
