"""GPT causal-LM tests: GPT-2 parameter parity, causality of the full model,
next-token objective, sequence-parallel training, example smoke
(SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tfde_tpu.models.gpt import GPT2Small, gpt_tiny_test, next_token_loss
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    SequenceParallelStrategy,
)
from tfde_tpu.training.step import init_state, make_custom_train_step
import pytest


def test_gpt2_small_param_count():
    m = GPT2Small()
    v = jax.eval_shape(m.init, jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
    # Analytic GPT-2 124M (tied head): wte + wpe + 12 blocks + final LN
    V, P_, H, L, F = 50257, 1024, 768, 12, 3072
    per_block = 4 * (H * H + H) + 2 * 2 * H + H * F + F + F * H + H
    assert n == V * H + P_ * H + L * per_block + 2 * H


def test_gpt_is_causal(rng):
    m = gpt_tiny_test()
    ids = jnp.asarray(rng.integers(0, 97, (2, 16)), jnp.int32)
    v = m.init(jax.random.key(0), ids)
    out = m.apply(v, ids)
    assert out.shape == (2, 16, 97)
    # changing future tokens must not change earlier logits
    ids2 = np.asarray(ids).copy()
    ids2[:, 10:] = (ids2[:, 10:] + 1) % 97
    out2 = m.apply(v, jnp.asarray(ids2))
    np.testing.assert_allclose(
        np.asarray(out)[:, :10], np.asarray(out2)[:, :10], rtol=1e-4, atol=1e-4
    )
    assert not np.allclose(np.asarray(out)[:, 10:], np.asarray(out2)[:, 10:])


@pytest.mark.slow
def test_gpt_next_token_loss_learns_structure(rng):
    """The Markov synthetic stream is predictable; loss must fall well below
    the uniform floor ln(96) within a few steps on a tiny model."""
    from tfde_tpu.data.datasets import synthetic_tokens

    strategy = MultiWorkerMirroredStrategy()
    m = gpt_tiny_test()
    tokens = synthetic_tokens(512, 16, vocab=96)
    state, _ = init_state(
        m, optax.adamw(3e-3), strategy, np.zeros((32, 16), np.int32)
    )
    step = make_custom_train_step(strategy, state, next_token_loss, donate=False)
    nrng = np.random.default_rng(0)
    key = jax.random.key(0)
    for i in range(30):
        idx = nrng.integers(0, len(tokens), 32)
        state, metrics = step(state, (tokens[idx],), key)
    floor = np.log(96)
    assert float(metrics["loss"]) < 0.9 * floor
    assert float(metrics["next_token_accuracy"]) > 0.1


@pytest.mark.slow
def test_gpt_seq_parallel_matches_dp(rng):
    """Causal ring attention end-to-end: GPT train step on a data x seq mesh
    reproduces pure-DP numerics."""
    tokens = rng.integers(0, 96, (8, 16)).astype(np.int32)

    def run(strategy):
        m = gpt_tiny_test()
        state, _ = init_state(
            m, optax.sgd(0.1), strategy, np.zeros((8, 16), np.int32), seed=0
        )
        step = make_custom_train_step(strategy, state, next_token_loss,
                                      donate=False)
        key = jax.random.key(0)
        for _ in range(2):
            state, metrics = step(state, (tokens,), key)
        return jax.device_get(state.params), float(metrics["loss"])

    p_dp, l_dp = run(MultiWorkerMirroredStrategy())
    p_sp, l_sp = run(SequenceParallelStrategy(data=2))
    np.testing.assert_allclose(l_dp, l_sp, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5),
        p_dp, p_sp,
    )


def test_gpt_example_smoke():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples import gpt_lm

    state, metrics = gpt_lm.main(
        ["--tiny", "--seq-len", "32", "--max-steps", "2", "--batch-size", "8",
         "--train-examples", "64", "--seq-parallel", "2"]
    )
    assert int(jax.device_get(state.step)) == 2
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_gpt_example_pp_sp_and_1f1b_smoke():
    """The example entrypoint drives the round-4 compositions: pp x sp
    (ring inside the manual pipe) and the 1F1B schedule."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples import gpt_lm

    state, metrics = gpt_lm.main(
        ["--tiny", "--seq-len", "32", "--max-steps", "2", "--batch-size",
         "8", "--train-examples", "64", "--pipeline", "2",
         "--seq-parallel", "2"]
    )
    assert int(jax.device_get(state.step)) == 2
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    state, metrics = gpt_lm.main(
        ["--tiny", "--seq-len", "32", "--max-steps", "2", "--batch-size",
         "16", "--train-examples", "64", "--pipeline", "2",
         "--schedule", "1f1b"]
    )
    assert int(jax.device_get(state.step)) == 2
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
