"""Sequence packing (data/packing.py + GPT segment_ids): coverage law,
the exactness oracle (packed logits == solo logits per document, rope),
boundary-masked labels, and training under DP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.data.packing import (
    IGNORE_ID,
    pack_documents,
    packed_labels,
    packed_next_token_loss,
)
from tfde_tpu.models.gpt import GPT, gpt_tiny_test


def test_pack_documents_covers_every_token_once(rng):
    docs = [rng.integers(1, 97, (n,)).astype(np.int32)
            for n in (5, 12, 3, 7, 16, 2, 9)]
    tokens, seg = pack_documents(docs, seq_len=16)
    assert tokens.shape == seg.shape
    # every document appears exactly once, contiguous and in order,
    # within one (row, segment) pair — reassemble and compare multisets
    recovered = []
    for i in range(tokens.shape[0]):
        for s in range(1, seg[i].max() + 1):
            recovered.append(tokens[i][seg[i] == s])
    key = lambda a: (len(a), tuple(a))
    assert sorted(map(key, recovered)) == sorted(map(key, docs))
    # padding is exactly the seg==0 region
    assert ((seg == 0) == (np.cumsum(seg[:, ::-1] > 0, axis=1)[:, ::-1]
                           == 0)).all()


def test_pack_documents_splits_long_docs(rng):
    doc = rng.integers(1, 97, (40,)).astype(np.int32)
    tokens, seg = pack_documents([doc], seq_len=16)
    recovered = np.concatenate(
        [tokens[i][seg[i] == s]
         for i in range(tokens.shape[0])
         for s in range(1, seg[i].max() + 1)]
    )
    np.testing.assert_array_equal(np.sort(recovered), np.sort(doc))


def test_packed_labels_mask_boundaries():
    tokens = np.array([[10, 11, 12, 13, 0, 0]], np.int32)
    seg = np.array([[1, 1, 2, 2, 0, 0]], np.int32)
    labels = packed_labels(tokens, seg)
    # first token of each segment and padding are ignored
    np.testing.assert_array_equal(
        labels[0], [IGNORE_ID, 11, IGNORE_ID, 13, IGNORE_ID, IGNORE_ID]
    )


def test_packed_forward_equals_solo_forward(rng):
    """THE exactness oracle: with rope positions, each packed document's
    logits equal its solo run bit-for-float — the segment mask blocks
    cross-document attention and rope cares only about relative
    position."""
    m = gpt_tiny_test(position="rope")
    d1 = rng.integers(1, 97, (6,)).astype(np.int32)
    d2 = rng.integers(1, 97, (5,)).astype(np.int32)
    tokens, seg = pack_documents([d1, d2], seq_len=16)
    assert tokens.shape[0] == 1 and seg[0].max() == 2
    v = m.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))
    packed = np.asarray(m.apply(
        {"params": v["params"]}, jnp.asarray(tokens),
        segment_ids=jnp.asarray(seg),
    ))
    solo1 = np.asarray(m.apply({"params": v["params"]},
                               jnp.asarray(d1[None, :])))
    solo2 = np.asarray(m.apply({"params": v["params"]},
                               jnp.asarray(d2[None, :])))
    np.testing.assert_allclose(packed[0, :6], solo1[0], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(packed[0, 6:11], solo2[0], rtol=1e-5,
                               atol=1e-5)
    # and WITHOUT the mask the second document's logits differ (the mask
    # is load-bearing, not decorative)
    unmasked = np.asarray(m.apply({"params": v["params"]},
                                  jnp.asarray(tokens)))
    assert np.abs(unmasked[0, 6:11] - solo2[0]).max() > 1e-3


@pytest.mark.slow
def test_packed_training_loss_falls(rng):
    import optax

    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    m = gpt_tiny_test(position="rope")
    docs = [rng.integers(1, 97, (rng.integers(4, 14),)).astype(np.int32)
            for _ in range(64)]
    tokens, seg = pack_documents(docs, seq_len=16)
    n = (len(tokens) // 8) * 8
    tokens, seg = tokens[:n], seg[:n]
    s = MirroredStrategy()
    # init on the tokens alone: segment_ids is an optional kwarg and does
    # not change parameter shapes
    state, _ = init_state(m, optax.adamw(3e-3), s, np.zeros_like(tokens),
                          seed=0)
    step = make_custom_train_step(s, state, packed_next_token_loss,
                                  donate=False)
    key = jax.random.key(0)
    first = last = None
    for i in range(25):
        state, metr = step(state, (tokens, seg), key)
        if first is None:
            first = float(metr["loss"])
        last = float(metr["loss"])
    assert last < first, (first, last)
    assert "grad_weight" not in metr  # reserved key consumed by the step


def test_segment_ids_refused_in_decode_and_window():
    m = gpt_tiny_test(position="rope").clone(decode=True)
    v = gpt_tiny_test(position="rope").init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )
    with pytest.raises(NotImplementedError, match="packing"):
        m.apply({"params": v["params"]}, jnp.zeros((1, 8), jnp.int32),
                segment_ids=jnp.ones((1, 8), jnp.int32),
                mutable=["cache"])
    mw = gpt_tiny_test(position="rope", sliding_window=4)
    vw = mw.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(NotImplementedError, match="sliding_window"):
        mw.apply({"params": vw["params"]}, jnp.zeros((1, 8), jnp.int32),
                 segment_ids=jnp.ones((1, 8), jnp.int32))


def test_packed_moe_sown_losses_join_objective(rng):
    """A routed GPT over packed batches must still train its balance
    losses (review r5: the packed loss initially dropped them)."""
    import optax

    from tfde_tpu.parallel.strategies import MirroredStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step

    m = gpt_tiny_test(position="rope", num_experts=4, moe_every=2,
                      router_z_loss_weight=1e-3)
    docs = [rng.integers(1, 97, (6,)).astype(np.int32) for _ in range(16)]
    tokens, seg = pack_documents(docs, seq_len=16)
    n = (len(tokens) // 8) * 8
    s = MirroredStrategy()
    state, _ = init_state(m, optax.sgd(0.01), s,
                          np.zeros_like(tokens[:n]), seed=0)
    step = make_custom_train_step(s, state, packed_next_token_loss,
                                  donate=False)
    _, metr = step(state, (tokens[:n], seg[:n]), jax.random.key(0))
    assert "moe_aux" in metr and "moe_z" in metr
    assert float(metr["moe_aux"]) > 0.0


def test_pack_documents_bounded_open_rows(rng):
    """The open-row cap keeps packing linear; density stays high and
    coverage exact even with a tiny pool."""
    docs = [rng.integers(1, 97, (rng.integers(2, 15),)).astype(np.int32)
            for _ in range(300)]
    tokens, seg = pack_documents(docs, seq_len=16, max_open_rows=2)
    recovered = [
        tokens[i][seg[i] == s_]
        for i in range(tokens.shape[0])
        for s_ in range(1, seg[i].max() + 1)
    ]
    key = lambda a: (len(a), tuple(a))
    assert sorted(map(key, recovered)) == sorted(map(key, docs))
    assert (seg > 0).mean() > 0.5  # still reasonably dense
