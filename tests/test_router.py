"""Multi-replica serving router (inference/router.py): SSE round trips
must match solo decoding bit for bit, placement must follow least
outstanding tokens, dead replicas must be marked down and their traffic
rerouted, /drain must stop new placement, the prefill tier must prime
remotely, and aggregator push-staleness must count as a down signal."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.inference.decode import generate
from tfde_tpu.inference.router import ReplicaServer, Router, request_generate
from tfde_tpu.inference.server import ContinuousBatcher
from tfde_tpu.models.gpt import gpt_tiny_test
from tfde_tpu.observability import metrics
from tfde_tpu.observability.aggregate import ClusterAggregator


@pytest.fixture(scope="module")
def lm():
    m = gpt_tiny_test()
    params = m.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return m, params


# generate() is rolling-window and therefore always fp (int8 KV refuses
# rolling), so every replica batcher in this file pins kv_quant="fp":
# routing drills compare tokens bit-exact against this reference and
# must stay exact under the TFDE_KV_QUANT=int8 tier-1 sweep.
def _solo(model, params, prompt, n):
    prompt = np.asarray(prompt, np.int64)
    toks, lengths = generate(
        model, params, jnp.asarray(prompt[None, :], jnp.int32),
        max_new_tokens=n,
    )
    return np.asarray(toks)[0, prompt.size : int(lengths[0])].tolist()


def _mk_replica(model, params, idx, role="both", batch=2):
    b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=batch, max_len=64,
                          role=role)
    return ReplicaServer(b, replica_id=idx).start()


@pytest.fixture()
def pair(lm):
    """Two live replicas + a router over them, torn down per test (tests
    kill/drain replicas, so state must not leak across tests)."""
    model, params = lm
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url]).start()
    yield model, params, r0, r1, router
    for s in (router, r0, r1):
        try:
            s.close()
        except OSError:
            pass  # a test may have closed it already (dead-replica drill)


def test_sse_round_trip_matches_solo(pair, rng):
    model, params, _r0, _r1, router = pair
    p = rng.integers(1, 90, 6).tolist()
    out = request_generate(router.url, p, 8)
    assert out["tokens"] == _solo(model, params, p, 8)
    assert out["replica"] in (0, 1)
    assert out["ttft_s"] is not None and out["ttft_s"] > 0
    # progress streaming: first-token event, at least one middle chunk,
    # and the final done event
    assert out["events"] >= 3


def test_least_outstanding_tokens_placement(pair, rng):
    """Holding replica 0's step lock stalls its decode while it still
    accepts the submit, so its outstanding-token estimate stays high;
    a second concurrent request must be placed on replica 1."""
    model, params, r0, _r1, router = pair
    long_p = rng.integers(1, 90, 4).tolist()
    short_p = rng.integers(1, 90, 6).tolist()
    res = {}
    with r0.lock:
        t = threading.Thread(
            target=lambda: res.update(a=request_generate(router.url,
                                                         long_p, 40))
        )
        t.start()
        deadline = time.monotonic() + 10
        while (not any(r.outstanding for r in router._reps)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert any(r.outstanding for r in router._reps)
        out2 = request_generate(router.url, short_p, 6)
    t.join(timeout=60)
    assert res["a"]["replica"] == 0 and out2["replica"] == 1
    # the stalled stream still finishes correctly once the lock drops
    assert res["a"]["tokens"] == _solo(model, params, long_p, 40)
    assert out2["tokens"] == _solo(model, params, short_p, 6)


def test_dead_replica_reroutes_and_marks_down(pair, rng):
    model, params, r0, _r1, router = pair
    reg = metrics.default_registry()
    reg.reset("router/")
    # kill replica 0's front door out from under the router
    r0._httpd.shutdown()
    r0._httpd.server_close()
    outs = [
        request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
        for _ in range(3)
    ]
    assert all(o["replica"] == 1 for o in outs)
    for o in outs:
        # rerouted sessions still decode correctly on the survivor
        assert len(o["tokens"]) > 0
    tab = {row["replica"]: row for row in router.table()}
    assert tab[0]["up"] is False and tab[1]["up"] is True
    assert reg.get("router/replicas_lost").value >= 1
    assert reg.get("router/replica0/up").value == 0
    assert reg.get("router/requests").value == 3


def test_drain_stops_new_placement(pair, rng):
    model, params, _r0, _r1, router = pair
    req = urllib.request.Request(
        router.url + "/drain",
        data=json.dumps({"replica": 0}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=5).read())
    assert body == {"drained": 0, "tier": "decode"}
    outs = [
        request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
        for _ in range(3)
    ]
    assert all(o["replica"] == 1 for o in outs)
    tab = {row["replica"]: row for row in router.table()}
    # drained is not down: the replica stays up, just unplaced
    assert tab[0]["drained"] is True and tab[0]["up"] is True


def test_drain_validation(pair):
    """/drain must 400 on a missing or garbage index and 404 on an
    unknown one — a silent 200 used to hide typos in the runbook's
    drain procedure."""
    _model, _params, _r0, _r1, router = pair

    def post(payload):
        req = urllib.request.Request(
            router.url + "/drain", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            return urllib.request.urlopen(req, timeout=5).status
        except urllib.error.HTTPError as e:
            return e.code

    assert post({}) == 400
    assert post({"replica": "zero"}) == 400
    assert post({"replica": 0, "tier": "bogus"}) == 400
    assert post({"replica": 7}) == 404
    assert post({"replica": 7, "tier": "prefill"}) == 404
    assert post({"replica": 1}) == 200
    tab = {row["replica"]: row for row in router.table()}
    assert tab[1]["drained"] is True and tab[0]["drained"] is False


def test_client_disconnect_cancels_request(lm, rng):
    """Dropping the SSE connection mid-stream must cancel the request on
    the replica — otherwise the batcher decodes the abandoned work to
    completion and its progress entry leaks forever."""
    model, params = lm
    rep = _mk_replica(model, params, 0, batch=1)
    b = rep.batcher
    try:
        payload = json.dumps({
            "prompt": rng.integers(1, 90, 5).tolist(),
            "max_new_tokens": 50,
        }).encode()
        req = urllib.request.Request(
            rep.url + "/generate", data=payload,
            headers={"Content-Type": "application/json"},
        )
        resp = urllib.request.urlopen(req, timeout=10)
        resp.readline()          # first event arrived: request in flight
        with rep.lock:           # stall decode so tokens remain pending
            resp.close()         # client walks away mid-stream
            time.sleep(0.05)     # let the reset land before writes resume
        deadline = time.monotonic() + 60
        while ((not b.idle or b._stream)
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert b.idle
        assert not b._stream
    finally:
        rep.close()


def test_prefill_tier_disaggregated_parity(lm, rng):
    """A prefill-role replica primes the prompt; the decode replica
    scatters the shipped K/V and streams — outputs must match solo."""
    model, params = lm
    pre_b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=64,
                              role="prefill")
    pre = ReplicaServer(pre_b, replica_id=0).start()
    dec = _mk_replica(model, params, 1)
    router = Router([dec.url], prefill_replicas=[pre.url]).start()
    try:
        for k in (7, 5):
            p = rng.integers(1, 90, k).tolist()
            out = request_generate(router.url, p, 8)
            assert out["tokens"] == _solo(model, params, p, 8)
        assert pre_b._dispatches > 0
    finally:
        for s in (router, pre, dec):
            s.close()


def test_stale_push_marks_down_never_pushed_stays_up(lm, rng):
    """Aggregator staleness is a down signal — but only for replicas that
    HAVE pushed and then went silent. A replica that never pushed (e.g.
    push wiring disabled) must stay routable."""
    model, params = lm
    agg = ClusterAggregator(stale_after=0.2)
    agg.ingest({"host": 0, "metrics": {}})
    r0 = _mk_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url], aggregator=agg).start()
    try:
        time.sleep(0.3)  # host 0's one push goes stale
        out = request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
        assert out["replica"] == 1
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["up"] is False
        assert tab[0]["push_age_s"] is not None
        assert tab[1]["up"] is True and tab[1]["push_age_s"] is None
    finally:
        for s in (router, r0, r1):
            s.close()


# --------------------------------------------------------------------------
# Overload protection: 429 + Retry-After, priority propagation, brownout
# --------------------------------------------------------------------------

def test_replica_queue_full_maps_to_429_with_retry_after(lm, rng,
                                                         monkeypatch):
    """A capped batcher's QueueFull must surface as HTTP 429 with an
    integer Retry-After header and the pinned JSON schema — NOT the
    generic 400 the RuntimeError clause would produce."""
    from tfde_tpu.inference.admission import AdmissionController

    model, params = lm
    b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=64,
                          admission_ctl=AdmissionController(max_queue=1))
    rep = ReplicaServer(b, replica_id=0).start()
    try:
        # stall decode WITHOUT holding rep.lock (load() now takes it):
        # a no-op step keeps every submit queued forever
        monkeypatch.setattr(b, "step", lambda: time.sleep(0.01))
        payload = {"prompt": rng.integers(1, 90, 4).tolist(),
                   "max_new_tokens": 6}
        req = urllib.request.Request(
            rep.url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        first = urllib.request.urlopen(req, timeout=10)
        first.readline()               # request #1 sits in the queue
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        e = ei.value
        assert e.code == 429
        retry_after = e.headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
        body = json.loads(e.read())
        assert body["error"] == "queue full"
        assert body["reason"] == "queue_depth"
        assert body["queue_depth"] == 1
        assert body["retry_after_s"] >= 0.5
        # /load advertises the saturation the router's gate reads
        load = json.loads(urllib.request.urlopen(
            rep.url + "/load", timeout=5).read())
        assert load["saturated"] is True
        assert load["queued_tokens"] == 6
        assert load["retry_after_s"] > 0
        first.close()
    finally:
        rep.close()


def test_router_rejects_fast_when_all_replicas_saturated(lm, rng,
                                                         monkeypatch):
    """With every live replica's /load reporting saturation, the router
    answers 429 + Retry-After at the front door without spending a
    replica round trip per doomed request."""
    from tfde_tpu.inference.admission import AdmissionController
    from tfde_tpu.observability import metrics

    model, params = lm
    b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=1, max_len=64,
                          admission_ctl=AdmissionController(max_queue=1))
    rep = ReplicaServer(b, replica_id=0).start()
    router = Router([rep.url]).start()
    reg = metrics.default_registry()
    reg.reset("router/rejected")
    try:
        monkeypatch.setattr(b, "step", lambda: time.sleep(0.01))
        p = rng.integers(1, 90, 4).tolist()
        payload = {"prompt": p, "max_new_tokens": 6}
        req = urllib.request.Request(
            rep.url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        first = urllib.request.urlopen(req, timeout=10)
        first.readline()               # the lone replica is now saturated
        with pytest.raises(urllib.error.HTTPError) as ei:
            request_generate(router.url, p, 6)
        e = ei.value
        assert e.code == 429
        assert int(e.headers.get("Retry-After")) >= 1
        body = json.loads(e.read())
        assert body["reason"] in ("saturated",)
        assert body["retriable"] is True
        assert reg.get("router/rejected_429").value >= 1
        assert reg.get("router/rejected_saturated").value >= 1
        first.close()
    finally:
        router.close()
        rep.close()


def test_priority_round_trip_and_validation(pair, rng):
    """priority in the /v1/generate body (or the X-Tfde-Priority header)
    must reach the replica's submit(); an unknown class 400s at the
    front door."""
    from tfde_tpu.inference.admission import PRIORITY_HEADER

    model, params, r0, r1, router = pair
    seen = []
    for rep in (r0, r1):
        b = rep.batcher
        orig = b.submit

        def spy(prompt, max_new_tokens, _orig=orig, **kw):
            seen.append(kw.get("priority"))
            return _orig(prompt, max_new_tokens, **kw)

        rep.batcher.submit = spy
    p = rng.integers(1, 90, 5).tolist()
    out = request_generate(router.url, p, 6, priority="batch")
    assert out["tokens"] == _solo(model, params, p, 6)
    assert seen == ["batch"]
    # header spelling, mixed case, no body field
    req = urllib.request.Request(
        router.url + "/v1/generate",
        data=json.dumps({"prompt": p, "max_new_tokens": 4}).encode(),
        headers={"Content-Type": "application/json",
                 PRIORITY_HEADER: "Best_Effort"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body["tokens"] == _solo(model, params, p, 4)
    assert seen[-1] == "best_effort"
    # unknown class: loud 400, nothing submitted
    n_before = len(seen)
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_generate(router.url, p, 4, priority="urgent")
    assert ei.value.code == 400
    assert len(seen) == n_before


def test_brownout_sheds_strictly_in_priority_order(lm, rng):
    """Under fast-window SLO burn past the thresholds the router sheds
    best_effort first, then batch, and never interactive — each rejected
    class gets a well-formed 429 while interactive still decodes with
    solo parity."""
    from tfde_tpu.observability import metrics
    from tfde_tpu.observability.slo import SLOTracker

    model, params = lm
    rep = _mk_replica(model, params, 0)

    def burned_tracker():
        t = SLOTracker(ttft_target_ms=1.0, objective=0.99)
        for _ in range(10):            # >= MIN_BURN_SAMPLES, all missed
            t.record(ttft_ms=1000.0)
        return t                       # fast-window burn == 100

    p = rng.integers(1, 90, 5).tolist()

    def expect_429(router, priority):
        with pytest.raises(urllib.error.HTTPError) as ei:
            request_generate(router.url, p, 4, priority=priority)
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body["reason"] == "brownout"
        assert int(ei.value.headers.get("Retry-After")) >= 1

    reg = metrics.default_registry()
    # level 2: burn 100 >= both thresholds -> best_effort AND batch shed
    router = Router([rep.url], slo=burned_tracker(),
                    brownout_burn=8.0, brownout_burn_batch=16.0).start()
    try:
        expect_429(router, "best_effort")
        expect_429(router, "batch")
        out = request_generate(router.url, p, 4)   # interactive: never shed
        assert out["tokens"] == _solo(model, params, p, 4)
        assert reg.get("router/brownout_level").value == 2
    finally:
        router.close()
    # level 1: burn 100 >= 8 but < the (huge) batch threshold -> only
    # best_effort sheds; batch passes. This IS the strict ordering.
    router = Router([rep.url], slo=burned_tracker(),
                    brownout_burn=8.0, brownout_burn_batch=1e9).start()
    try:
        expect_429(router, "best_effort")
        out = request_generate(router.url, p, 4, priority="batch")
        assert out["tokens"] == _solo(model, params, p, 4)
        assert reg.get("router/brownout_level").value == 1
    finally:
        router.close()
        rep.close()


# --------------------------------------------------------------------------
# Boot & readiness: the router places only on `ready` replicas
# --------------------------------------------------------------------------

def _mk_booting_replica(model, params, idx, phase="warmup"):
    """A replica whose externally driven boot ledger has NOT reached
    ready — the joining-replica shape (router.py boot_ledger param)."""
    from tfde_tpu.observability import boot as boot_lib

    led = boot_lib.BootLedger(registry=metrics.Registry(),
                              compile_probe=lambda: (0, 0.0))
    led.begin(phase)
    b = ContinuousBatcher(model, params, kv_quant="fp", batch_size=2, max_len=64)
    return ReplicaServer(b, replica_id=idx, boot_ledger=led).start(), led


def test_readiness_matrix_no_placement_until_ready_then_flip(lm, rng):
    """The matrix: a warming replica gets ZERO placements while its
    sibling serves everything; /healthz and /replicas carry its state;
    once the ledger flips ready (and the router's load snapshot ages
    out) placement resumes with solo parity; /drain walks the table row
    to draining."""
    model, params = lm
    r0, led = _mk_booting_replica(model, params, 0)
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url]).start()
    try:
        # liveness stays 200 while booting; readiness rides the body
        hz = json.loads(urllib.request.urlopen(
            r0.url + "/healthz", timeout=5).read())
        assert hz == {"ok": False, "state": "warming", "replica": 0}
        outs = [
            request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
            for _ in range(4)
        ]
        assert all(o["replica"] == 1 for o in outs)
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["state"] == "warming"
        assert tab[0]["ready_seen"] is False
        assert tab[0]["up"] is True          # not-ready is NOT down
        body = json.loads(urllib.request.urlopen(
            router.url + "/replicas", timeout=5).read())
        assert body["boot"]["0"]["state"] == "warming"
        assert body["boot"]["1"]["state"] == "ready"

        led.ready()                          # the joiner finishes booting
        time.sleep(router._load_ttl + 0.05)  # let the snapshot age out
        hz = json.loads(urllib.request.urlopen(
            r0.url + "/healthz", timeout=5).read())
        assert hz["ok"] is True and hz["state"] == "ready"
        # both idle -> least-outstanding tie breaks to replica 0 now
        p = rng.integers(1, 90, 5).tolist()
        placed = {request_generate(router.url, p, 6)["replica"]
                  for _ in range(4)}
        assert 0 in placed
        out = request_generate(router.url, p, 6)
        assert out["tokens"] == _solo(model, params, p, 6)
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["state"] == "ready" and tab[0]["ready_seen"] is True

        # drain transition: the table row walks to draining
        req = urllib.request.Request(
            router.url + "/drain",
            data=json.dumps({"replica": 0}).encode(),
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=5).read()
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["state"] == "draining"
    finally:
        for s in (router, r0, r1):
            s.close()
    assert led.state == "draining"           # close() walks the ledger


def test_ready_require_off_restores_legacy_placement(lm, rng,
                                                     monkeypatch):
    """TFDE_BOOT_READY_REQUIRE=off: the pre-readiness behavior — a
    still-booting replica is placeable (and decodes correctly; readiness
    is a placement gate, not a capability)."""
    monkeypatch.setenv("TFDE_BOOT_READY_REQUIRE", "off")
    model, params = lm
    r0, _led = _mk_booting_replica(model, params, 0, phase="compile")
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url]).start()
    try:
        p = rng.integers(1, 90, 5).tolist()
        outs = [request_generate(router.url, p, 6) for _ in range(4)]
        assert any(o["replica"] == 0 for o in outs)
        for o in outs:
            assert o["tokens"] == _solo(model, params, p, 6)
    finally:
        for s in (router, r0, r1):
            s.close()


def test_boot_grace_shields_never_ready_from_staleness(lm, rng,
                                                       monkeypatch):
    """A never-ready replica whose pushes went stale is busy booting,
    not dead: within TFDE_BOOT_READY_GRACE_S it stays up (and unplaced,
    because it is not ready) instead of being marked down."""
    monkeypatch.setenv("TFDE_BOOT_READY_GRACE_S", "60")
    model, params = lm
    agg = ClusterAggregator(stale_after=0.2)
    agg.ingest({"host": 0, "metrics": {}})
    r0, _led = _mk_booting_replica(model, params, 0, phase="compile")
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url], aggregator=agg).start()
    try:
        time.sleep(0.3)                      # host 0's push is now stale
        out = request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
        assert out["replica"] == 1
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["up"] is True          # shielded by the grace
        assert tab[1]["up"] is True
    finally:
        for s in (router, r0, r1):
            s.close()


def test_never_ready_death_books_separately_from_lost(lm, rng,
                                                      monkeypatch):
    """With the grace elapsed, a stale never-ready replica IS marked
    down — but under router/replicas_never_ready (a failed boot), not
    router/replicas_lost (lost serving capacity)."""
    monkeypatch.setenv("TFDE_BOOT_READY_GRACE_S", "0")
    model, params = lm
    reg = metrics.default_registry()
    reg.reset("router/")
    agg = ClusterAggregator(stale_after=0.2)
    agg.ingest({"host": 0, "metrics": {}})
    r0, _led = _mk_booting_replica(model, params, 0, phase="restore")
    r1 = _mk_replica(model, params, 1)
    router = Router([r0.url, r1.url], aggregator=agg).start()
    try:
        time.sleep(0.3)
        out = request_generate(router.url, rng.integers(1, 90, 5).tolist(), 6)
        assert out["replica"] == 1
        tab = {row["replica"]: row for row in router.table()}
        assert tab[0]["up"] is False
        assert reg.get("router/replicas_never_ready").value >= 1
        lost = reg.get("router/replicas_lost")
        assert lost is None or lost.value == 0
    finally:
        for s in (router, r0, r1):
            s.close()


def test_deadline_shed_surfaces_as_inband_sse_error(lm, rng,
                                                    monkeypatch):
    """A request shed at dequeue AFTER the SSE stream opened cannot
    become a 429 — it must surface as an in-band retriable
    `deadline_shed` event, which request_generate raises."""
    model, params = lm
    rep = _mk_replica(model, params, 0, batch=1)
    router = Router([rep.url]).start()
    b = rep.batcher
    try:
        p = rng.integers(1, 90, 4).tolist()
        real_step = b.step
        # hold the queue for a few steps so the 1ms deadline expires
        # before the shed check runs at dequeue
        state = {"n": 0}

        def slow_step(*a, **kw):
            state["n"] += 1
            if state["n"] < 4:
                time.sleep(0.02)
                return []
            return real_step(*a, **kw)

        monkeypatch.setattr(b, "step", slow_step)
        with pytest.raises(RuntimeError, match="deadline_shed"):
            request_generate(router.url, p, 6, ttft_deadline_ms=1.0)
    finally:
        router.close()
        rep.close()
