"""HF checkpoint conversion (models/convert.py): tiny randomly-initialized
transformers models are the oracle — our forward on the converted params
must reproduce their logits.

fp32 on both sides; tolerances cover reduction-order noise plus (BERT only)
the tanh-approximate gelu our Mlp shares with GPT-2's gelu_new."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from tfde_tpu.models.convert import (  # noqa: E402
    bert_from_hf,
    gpt2_from_hf,
    llama_from_hf,
)


@pytest.fixture(scope="module")
def hf_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    m = transformers.GPT2LMHeadModel(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(1)
    m = transformers.BertForMaskedLM(cfg)
    m.eval()
    return m


def test_gpt2_logits_match(hf_gpt2, rng):
    model, params = gpt2_from_hf(hf_gpt2, dtype=jnp.float32)
    ids = rng.integers(0, 97, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_gpt2(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gpt2_converted_model_generates(hf_gpt2, rng):
    """The converted model runs through the serving path: greedy cached
    generation must equal HF's own greedy generate."""
    from tfde_tpu.inference.decode import generate

    model, params = gpt2_from_hf(hf_gpt2, dtype=jnp.float32)
    prompt = rng.integers(0, 97, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_gpt2.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_bert_logits_match(hf_bert, rng):
    model, params = bert_from_hf(hf_bert, dtype=jnp.float32)
    ids = rng.integers(0, 97, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_bert(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    # exact-gelu (HF bert) vs tanh-gelu (ours): ~1e-3 logit delta expected
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


@pytest.fixture(scope="module")
def hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def hf_gemma():
    # head_dim 16 with hidden 32 / 4 heads: attention width 64 != hidden —
    # the gemma-7b-shaped decoupling (GPT(head_dim=...))
    cfg = transformers.GemmaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64, attention_dropout=0.0,
        hidden_activation="gelu_pytorch_tanh",
    )
    torch.manual_seed(3)
    m = transformers.GemmaForCausalLM(cfg)
    m.eval()
    return m


def test_gemma_logits_match(hf_gemma, rng):
    """Gemma = LLaMA shape + geglu MLP + sqrt(h)-scaled embeddings +
    zero-centered RMSNorm (folded to 1+w at conversion) + tied head +
    decoupled head_dim (7b-shaped) — one converted forward checks all of
    it against transformers."""
    from tfde_tpu.models.convert import gemma_from_hf

    model, params = gemma_from_hf(hf_gemma, dtype=jnp.float32)
    assert model.mlp_act == "geglu" and model.tie_embeddings
    assert model.embed_scale == pytest.approx(32 ** 0.5)
    assert model.head_dim == 16  # != hidden // heads
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_gemma(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gemma_untied_refused():
    """An untied Gemma-arch checkpoint carries a distinct lm_head.weight
    this converter would silently drop — refuse loudly instead."""
    from tfde_tpu.models.convert import gemma_from_hf

    cfg = transformers.GemmaConfig(
        vocab_size=51, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
        head_dim=8, max_position_embeddings=32,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    m = transformers.GemmaForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="untied"):
        gemma_from_hf(m, dtype=jnp.float32)


def test_gemma_converted_generates_like_hf(hf_gemma, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import gemma_from_hf

    model, params = gemma_from_hf(hf_gemma, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_gemma.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.fixture(scope="module")
def hf_qwen2():
    cfg = transformers.Qwen2Config(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0,
        tie_word_embeddings=False, use_sliding_window=False,
    )
    torch.manual_seed(4)
    m = transformers.Qwen2ForCausalLM(cfg)
    m.eval()
    return m


def test_qwen2_logits_match(hf_qwen2, rng):
    """Qwen2 = LLaMA shape + biased q/k/v beside bias-free out/MLP
    (GPT(qkv_bias=True)) — converted logits must match transformers."""
    from tfde_tpu.models.convert import qwen2_from_hf

    model, params = qwen2_from_hf(hf_qwen2, dtype=jnp.float32)
    assert model.qkv_bias and not model.use_bias
    attn = params["decoder"]["block_0"]["attn"]
    assert attn["query"]["bias"].shape == (4, 8)
    assert "bias" not in attn["out"]
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen2(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_qwen2_converted_generates_like_hf(hf_qwen2, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import qwen2_from_hf

    model, params = qwen2_from_hf(hf_qwen2, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen2.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen2_sliding_window_refused():
    from tfde_tpu.models.convert import qwen2_from_hf

    cfg = transformers.Qwen2Config(
        vocab_size=51, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=32, use_sliding_window=True,
        sliding_window=16, max_window_layers=1,
    )
    torch.manual_seed(0)
    m = transformers.Qwen2ForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="use_sliding_window"):
        qwen2_from_hf(m, dtype=jnp.float32)


def test_llama_logits_match(hf_llama, rng):
    """LLaMA = RoPE + GQA + RMSNorm + SwiGLU + bias-free + untied head —
    one converted forward checks all five against transformers."""
    model, params = llama_from_hf(hf_llama, dtype=jnp.float32)
    assert model.position == "rope" and model.num_kv_heads == 2
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_llama(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_llama_converted_generates_like_hf(hf_llama, rng):
    from tfde_tpu.inference.decode import generate

    model, params = llama_from_hf(hf_llama, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_llama.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_param_trees_are_complete(hf_gpt2, hf_bert, hf_llama, hf_gemma,
                                  hf_qwen2, hf_phi, hf_neox,
                                  hf_bigcode, hf_opt):
    """Converted trees must match the models' own init structure exactly —
    a missing/extra leaf means a silently unconverted weight."""
    from tfde_tpu.models.convert import (bigcode_from_hf, gemma_from_hf,
                                         neox_from_hf, opt_from_hf,
                                         phi_from_hf, qwen2_from_hf)

    for hf, conv, sample in (
        (hf_gpt2, gpt2_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_bert, bert_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_llama, llama_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_gemma, gemma_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_qwen2, qwen2_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_phi, phi_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_neox, neox_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_bigcode, bigcode_from_hf, jnp.zeros((1, 8), jnp.int32)),
        (hf_opt, opt_from_hf, jnp.zeros((1, 8), jnp.int32)),
    ):
        model, params = conv(hf, dtype=jnp.float32)
        ref = model.init(jax.random.key(0), sample)["params"]
        ref_paths = {
            jax.tree_util.keystr(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(ref)[0]
        }
        got_paths = {
            jax.tree_util.keystr(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        }
        assert ref_paths == got_paths
        for (p1, a), (p2, b) in zip(
            jax.tree_util.tree_flatten_with_path(ref)[0],
            jax.tree_util.tree_flatten_with_path(params)[0],
        ):
            assert np.asarray(b).shape == a.shape, (p1, a.shape,
                                                    np.asarray(b).shape)


def test_convert_cli_round_trip(tmp_path, hf_gpt2, rng):
    """The offline CLI path: save_pretrained dir -> params.npz +
    model_config.json -> rebuilt model reproduces the HF logits."""
    import json

    from tfde_tpu.export.serving import _unflatten_params
    from tfde_tpu.models.convert import _cli
    from tfde_tpu.models.gpt import GPT

    src = str(tmp_path / "hf")
    out = str(tmp_path / "converted")
    hf_gpt2.save_pretrained(src)
    _cli(["gpt2", src, out])

    from tfde_tpu.models.convert import load_converted

    conf = json.load(open(f"{out}/model_config.json"))
    assert conf["family"] == "gpt2"
    model, params = load_converted(out, dtype=jnp.float32)
    assert isinstance(model, GPT)
    ids = rng.integers(0, 97, (1, 10)).astype(np.int32)
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    with torch.no_grad():
        ref = hf_gpt2(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


@pytest.fixture(scope="module")
def hf_mistral():
    cfg = transformers.MistralConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0,
        sliding_window=8, tie_word_embeddings=False,
    )
    torch.manual_seed(3)
    m = transformers.MistralForCausalLM(cfg)
    m.eval()
    return m


def test_mistral_logits_match(hf_mistral, rng):
    """Mistral = the LLaMA stack + sliding-window attention; a sequence
    LONGER than the window makes the band mask load-bearing in the
    comparison (transformers applies its own sliding-window mask)."""
    from tfde_tpu.models.convert import mistral_from_hf

    model, params = mistral_from_hf(hf_mistral, dtype=jnp.float32)
    assert model.sliding_window == 8
    ids = rng.integers(0, 101, (2, 24)).astype(np.int32)
    with torch.no_grad():
        ref = hf_mistral(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_mistral_converted_generates_like_hf(hf_mistral, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import mistral_from_hf

    model, params = mistral_from_hf(hf_mistral, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_mistral.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.mark.parametrize("family", ["gpt2", "llama", "qwen2", "mistral"])
def test_roundtrip_to_hf_logits_exact(family, hf_gpt2, hf_llama, hf_qwen2,
                                      rng):
    """from_hf -> to_hf reconstructs a transformers model with IDENTICAL
    logits — the deploy-anywhere half of the migration story (fine-tune
    here, export back)."""
    from tfde_tpu.models.convert import (
        gpt2_to_hf,
        llama_to_hf,
        mistral_from_hf,
        qwen2_from_hf,
    )

    if family == "gpt2":
        hf = hf_gpt2
        model, params = gpt2_from_hf(hf, dtype=jnp.float32)
        hf2 = gpt2_to_hf(model, params)
    elif family == "llama":
        hf = hf_llama
        model, params = llama_from_hf(hf, dtype=jnp.float32)
        hf2 = llama_to_hf(model, params)
    elif family == "qwen2":
        hf = hf_qwen2
        model, params = qwen2_from_hf(hf, dtype=jnp.float32)
        hf2 = llama_to_hf(model, params)
    else:  # mistral: llama shape + sliding window in the config
        cfg = transformers.MistralConfig(
            vocab_size=101, hidden_size=32, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            attention_dropout=0.0, sliding_window=16,
        )
        torch.manual_seed(5)
        hf = transformers.MistralForCausalLM(cfg)
        hf.eval()
        model, params = mistral_from_hf(hf, dtype=jnp.float32)
        assert model.sliding_window == 16
        hf2 = llama_to_hf(model, params)
        assert hf2.config.sliding_window == 16

    vocab = hf.config.vocab_size
    ids = torch.tensor(rng.integers(0, vocab, (2, 12)).astype(np.int64))
    with torch.no_grad():
        a = hf(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


def test_to_hf_refuses_foreign_arrangements():
    from tfde_tpu.models.convert import gpt2_to_hf, llama_to_hf
    from tfde_tpu.models.gpt import GPT

    rope = GPT(vocab_size=51, hidden_size=16, depth=1, num_heads=2,
               mlp_dim=32, max_position=32, position="rope", norm="rms",
               mlp_act="swiglu", use_bias=False)
    with pytest.raises(NotImplementedError, match="GPT-2 arrangement"):
        gpt2_to_hf(rope, {})
    gemma_ish = rope.clone(mlp_act="geglu", embed_scale=4.0)
    with pytest.raises(NotImplementedError, match="LLaMA arrangement"):
        llama_to_hf(gemma_ish, {})


@pytest.fixture(scope="module")
def hf_phi():
    cfg = transformers.PhiConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        attention_dropout=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(7)
    m = transformers.PhiForCausalLM(cfg)
    m.eval()
    return m


def test_phi_logits_match(hf_phi, rng):
    """Phi = parallel blocks (one LN, attn + MLP side by side) + partial
    rotary (rope_dim = 0.5 * head_dim) + biased everything including the
    untied lm_head — one converted forward checks all of it."""
    from tfde_tpu.models.convert import phi_from_hf

    model, params = phi_from_hf(hf_phi, dtype=jnp.float32)
    assert model.norm_style == "parallel" and model.head_bias
    assert model.rope_dim == 4  # 0.5 * head_dim(8)
    assert "ln_mlp" not in params["decoder"]["block_0"]  # one LN per block
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_phi(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_phi_converted_generates_like_hf(hf_phi, rng):
    """Partial rotary through the KV cache: cached decode must equal HF
    greedy generation (the rotation boundary rides the cache offsets)."""
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import phi_from_hf

    model, params = phi_from_hf(hf_phi, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_phi.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.fixture(scope="module")
def hf_neox():
    cfg = transformers.GPTNeoXConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.5,
        use_parallel_residual=True, attention_dropout=0.0,
        hidden_dropout=0.0,
    )
    torch.manual_seed(8)
    m = transformers.GPTNeoXForCausalLM(cfg)
    m.eval()
    return m


def test_neox_logits_match(hf_neox, rng):
    """NeoX/Pythia = parallel residual with separate attn/MLP LayerNorms
    (norm_style='parallel2') + 50%-partial rotary + per-head-interleaved
    fused qkv, de-interleaved at conversion + untied bias-free head."""
    from tfde_tpu.models.convert import neox_from_hf

    model, params = neox_from_hf(hf_neox, dtype=jnp.float32)
    assert model.norm_style == "parallel2" and not model.tie_embeddings
    assert model.rope_dim == 4  # 0.5 * head_dim(8)
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_neox(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    # exact-gelu (HF neox) vs tanh-gelu (ours): ~1e-3 delta, BERT precedent
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


def test_neox_sequential_residual_maps_to_pre(rng):
    """use_parallel_residual=False NeoX checkpoints are plain pre-LN —
    the converter maps them to norm_style='pre' and still logit-matches."""
    from tfde_tpu.models.convert import neox_from_hf

    cfg = transformers.GPTNeoXConfig(
        vocab_size=53, hidden_size=16, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2,
        max_position_embeddings=32, rotary_pct=0.25,
        use_parallel_residual=False, attention_dropout=0.0,
        hidden_dropout=0.0,
    )
    torch.manual_seed(9)
    hf = transformers.GPTNeoXForCausalLM(cfg)
    hf.eval()
    model, params = neox_from_hf(hf, dtype=jnp.float32)
    assert model.norm_style == "pre"
    ids = rng.integers(0, 53, (2, 10)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


def test_neox_converted_generates_like_hf(hf_neox, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import neox_from_hf

    model, params = neox_from_hf(hf_neox, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_neox.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.mark.parametrize("family", ["phi", "neox"])
def test_roundtrip_phi_neox_to_hf(family, hf_phi, rng):
    """from_hf -> to_hf for the parallel-block families reconstructs a
    transformers model with identical logits (re-interleaving the NeoX
    fused qkv on the way back)."""
    from tfde_tpu.models.convert import (
        neox_from_hf,
        neox_to_hf,
        phi_from_hf,
        phi_to_hf,
    )

    if family == "phi":
        hf = hf_phi
        model, params = phi_from_hf(hf, dtype=jnp.float32)
        hf2 = phi_to_hf(model, params)
    else:
        # a tanh-gelu source, so the round trip tests the invariant to_hf
        # provides (exact equality to OUR math; an erf-gelu original
        # differs by the documented ~1e-3 import approximation)
        cfg = transformers.GPTNeoXConfig(
            vocab_size=101, hidden_size=32, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, rotary_pct=0.5,
            use_parallel_residual=True, attention_dropout=0.0,
            hidden_dropout=0.0, hidden_act="gelu_pytorch_tanh",
        )
        torch.manual_seed(8)
        hf = transformers.GPTNeoXForCausalLM(cfg)
        hf.eval()
        model, params = neox_from_hf(hf, dtype=jnp.float32)
        hf2 = neox_to_hf(model, params)
    vocab = hf.config.vocab_size
    ids = torch.tensor(rng.integers(0, vocab, (2, 12)).astype(np.int64))
    with torch.no_grad():
        a = hf(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


def test_save_converted_roundtrip(tmp_path, rng):
    """save_converted -> load_converted: the persist half of the artifact
    contract (WORKFLOWS recipe 1) — a fine-tuned model written to disk
    reloads with identical structure, config, and forward."""
    from tfde_tpu.models.convert import load_converted, save_converted
    from tfde_tpu.models.gpt import GPT

    model = GPT(vocab_size=53, hidden_size=16, depth=1, num_heads=2,
                mlp_dim=32, max_position=32, dtype=jnp.float32,
                position="rope", norm="rms", mlp_act="swiglu",
                use_bias=False, num_kv_heads=1, tie_embeddings=False)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    out = str(tmp_path / "art")
    save_converted(model, params, out, "llama")
    m2, p2 = load_converted(out, dtype=jnp.float32)
    assert m2.num_kv_heads == 1 and m2.mlp_act == "swiglu"
    ids = jnp.asarray(rng.integers(0, 53, (2, 8)), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": params}, ids, train=False)),
        np.asarray(m2.apply({"params": p2}, ids, train=False)),
        rtol=1e-6, atol=1e-6,
    )
    with pytest.raises(ValueError, match="unknown family"):
        save_converted(model, params, str(tmp_path / "bad"), "nope")


@pytest.fixture(scope="module")
def hf_bigcode():
    cfg = transformers.GPTBigCodeConfig(
        vocab_size=101, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        multi_query=True, attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(10)
    m = transformers.GPTBigCodeForCausalLM(cfg)
    m.eval()
    return m


def test_bigcode_logits_match(hf_bigcode, rng):
    """StarCoder = GPT-2 arrangement + multi-query attention; the fused
    c_attn [q | k | v] rows split into the kv=1 projection kernels.
    gelu_pytorch_tanh is our exact gelu — tight tolerance."""
    from tfde_tpu.models.convert import bigcode_from_hf

    model, params = bigcode_from_hf(hf_bigcode, dtype=jnp.float32)
    assert model.num_kv_heads == 1 and model.position == "learned"
    assert params["decoder"]["block_0"]["attn"]["key"]["kernel"].shape == (
        32, 1, 8)
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_bigcode(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_bigcode_converted_generates_like_hf(hf_bigcode, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import bigcode_from_hf

    model, params = bigcode_from_hf(hf_bigcode, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_bigcode.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_bigcode_mha_interleave(rng):
    """multi_query=False GPTBigCode stores the fused qkv PER-HEAD
    interleaved (unlike the flat MQA blocks) — converted logits must
    still match transformers."""
    from tfde_tpu.models.convert import bigcode_from_hf

    cfg = transformers.GPTBigCodeConfig(
        vocab_size=53, n_embd=16, n_layer=1, n_head=2, n_positions=32,
        multi_query=False, attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    torch.manual_seed(11)
    hf = transformers.GPTBigCodeForCausalLM(cfg)
    hf.eval()
    model, params = bigcode_from_hf(hf, dtype=jnp.float32)
    assert model.num_kv_heads == 2  # == heads: classic MHA
    ids = rng.integers(0, 53, (2, 10)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


@pytest.fixture(scope="module")
def hf_opt():
    cfg = transformers.OPTConfig(
        vocab_size=101, hidden_size=32, ffn_dim=96, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        word_embed_proj_dim=32, do_layer_norm_before=True,
        attention_dropout=0.0, dropout=0.0,
    )
    torch.manual_seed(12)
    m = transformers.OPTForCausalLM(cfg)
    m.eval()
    return m


def test_opt_logits_match(hf_opt, rng):
    """OPT = pre-LN + relu MLP + offset-2 learned positions (the table
    slice at conversion makes our 0-based lookup identical) + tied head."""
    from tfde_tpu.models.convert import opt_from_hf

    model, params = opt_from_hf(hf_opt, dtype=jnp.float32)
    assert model.mlp_act == "relu" and model.tie_embeddings
    assert params["wpe"]["embedding"].shape == (64, 32)  # offset sliced
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_opt(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_opt_converted_generates_like_hf(hf_opt, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import opt_from_hf

    model, params = opt_from_hf(hf_opt, dtype=jnp.float32)
    prompt = rng.integers(1, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_opt.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=1,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_opt_projected_embeddings_refused():
    from tfde_tpu.models.convert import opt_from_hf

    cfg = transformers.OPTConfig(
        vocab_size=53, hidden_size=16, ffn_dim=32, num_hidden_layers=1,
        num_attention_heads=2, max_position_embeddings=32,
        word_embed_proj_dim=8,
    )
    torch.manual_seed(0)
    m = transformers.OPTForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="word_embed_proj_dim"):
        opt_from_hf(m, dtype=jnp.float32)


@pytest.mark.parametrize("family", ["gemma", "bigcode", "bigcode_mha", "opt"])
def test_roundtrip_new_families_to_hf(family, hf_gemma, hf_bigcode, hf_opt,
                                      rng):
    """from_hf -> to_hf for the families VERDICT r4 flagged as one-way
    (Gemma's 1+w norm un-fold, StarCoder's two c_attn refusions, OPT's
    offset-2 table rebuild): the reconstructed transformers model must
    produce IDENTICAL logits on unpadded input."""
    from tfde_tpu.models.convert import (
        bigcode_from_hf,
        bigcode_to_hf,
        gemma_from_hf,
        gemma_to_hf,
        opt_from_hf,
        opt_to_hf,
    )

    if family == "gemma":
        hf = hf_gemma
        model, params = gemma_from_hf(hf, dtype=jnp.float32)
        hf2 = gemma_to_hf(model, params)
        assert hf2.config.head_dim == 16
    elif family == "bigcode":
        hf = hf_bigcode
        model, params = bigcode_from_hf(hf, dtype=jnp.float32)
        hf2 = bigcode_to_hf(model, params)
        assert hf2.config.multi_query
    elif family == "bigcode_mha":
        cfg = transformers.GPTBigCodeConfig(
            vocab_size=53, n_embd=16, n_layer=1, n_head=2, n_positions=32,
            multi_query=False, attn_pdrop=0.0, embd_pdrop=0.0,
            resid_pdrop=0.0,
        )
        torch.manual_seed(11)
        hf = transformers.GPTBigCodeForCausalLM(cfg)
        hf.eval()
        model, params = bigcode_from_hf(hf, dtype=jnp.float32)
        hf2 = bigcode_to_hf(model, params)
        assert not hf2.config.multi_query
    else:  # opt
        hf = hf_opt
        model, params = opt_from_hf(hf, dtype=jnp.float32)
        hf2 = opt_to_hf(model, params)
        # offset rows rebuilt: HF table is max_position + 2
        assert hf2.model.decoder.embed_positions.weight.shape[0] == 66

    vocab = hf.config.vocab_size
    ids = torch.tensor(rng.integers(0, vocab, (2, 12)).astype(np.int64))
    with torch.no_grad():
        a = hf(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


def test_roundtrip_bert_to_hf(hf_bert, rng):
    """bert_from_hf -> bert_to_hf: the exported BertForMaskedLM must match
    OUR forward exactly (both run tanh-gelu); vs the erf-gelu source
    checkpoint the usual ~1e-3 activation delta applies."""
    from tfde_tpu.models.convert import bert_to_hf

    model, params = bert_from_hf(hf_bert, dtype=jnp.float32)
    hf2 = bert_to_hf(model, params)
    assert hf2.config.hidden_act == "gelu_pytorch_tanh"
    ids = rng.integers(0, 97, (2, 12)).astype(np.int32)
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = hf2(torch.tensor(ids.astype(np.int64))).logits.numpy()
        src = hf_bert(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(theirs, src, rtol=5e-3, atol=5e-3)


def test_roundtrip_bert_classifier_to_hf(rng):
    from tfde_tpu.models.convert import (
        bert_classifier_from_hf,
        bert_classifier_to_hf,
    )

    cfg = transformers.BertConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, num_labels=3,
    )
    torch.manual_seed(6)
    hf = transformers.BertForSequenceClassification(cfg)
    hf.eval()
    model, params = bert_classifier_from_hf(hf, dtype=jnp.float32)
    hf2 = bert_classifier_to_hf(model, params)
    assert hf2.config.num_labels == 3
    ids = rng.integers(0, 97, (2, 12)).astype(np.int32)
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = hf2(torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_new_to_hf_refuse_foreign_arrangements():
    from tfde_tpu.models.bert import Bert
    from tfde_tpu.models.convert import (
        bert_to_hf,
        bigcode_to_hf,
        gemma_to_hf,
        opt_to_hf,
    )
    from tfde_tpu.models.gpt import GPT

    llama_ish = GPT(vocab_size=51, hidden_size=16, depth=1, num_heads=2,
                    mlp_dim=32, max_position=32, position="rope",
                    norm="rms", mlp_act="swiglu", use_bias=False)
    with pytest.raises(NotImplementedError, match="Gemma arrangement"):
        gemma_to_hf(llama_ish, {})
    with pytest.raises(NotImplementedError, match="StarCoder arrangement"):
        bigcode_to_hf(llama_ish, {})
    with pytest.raises(NotImplementedError, match="OPT arrangement"):
        opt_to_hf(llama_ish, {})
    padded = Bert(vocab_size=97, hidden_size=32, depth=1, num_heads=2,
                  mlp_dim=64, max_position=32, pad_vocab=True)
    with pytest.raises(NotImplementedError, match="pad_vocab"):
        bert_to_hf(padded, {})


def test_convert_cli_reverse_new_family(tmp_path, hf_gemma, rng):
    """The full deploy-anywhere loop through the CLI for a family VERDICT
    r4 flagged as one-way: HF dir -> artifact -> --reverse -> a
    save_pretrained checkpoint transformers reloads with identical
    logits."""
    from tfde_tpu.models.convert import _cli

    src = str(tmp_path / "hf")
    art = str(tmp_path / "artifact")
    back = str(tmp_path / "exported")
    hf_gemma.save_pretrained(src)
    _cli(["gemma", src, art])
    _cli(["gemma", art, back, "--reverse"])
    hf2 = transformers.GemmaForCausalLM.from_pretrained(
        back, local_files_only=True
    )
    hf2.eval()
    ids = torch.tensor(rng.integers(0, 101, (2, 12)).astype(np.int64))
    with torch.no_grad():
        a = hf_gemma(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


def _tiny_falcon(new_arch: bool, multi_query: bool = True,
                 parallel_attn: bool = True, seed: int = 30):
    cfg = transformers.FalconConfig(
        vocab_size=101, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2 if new_arch else None,
        new_decoder_architecture=new_arch, multi_query=multi_query,
        parallel_attn=parallel_attn, alibi=False, bias=False,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    torch.manual_seed(seed)
    m = transformers.FalconForCausalLM(cfg)
    m.eval()
    return m


@pytest.mark.parametrize("arrangement", ["7b", "40b", "sequential"])
def test_falcon_logits_match(arrangement, rng):
    """The three Falcon shapes on existing GPT knobs: 7B (multi-query +
    one parallel LN), 40B (grouped kv + dual-LN parallel residual), and
    sequential pre-LN. erf-vs-tanh gelu bounds the delta at ~1e-3 (the
    bert_from_hf precedent)."""
    from tfde_tpu.models.convert import falcon_from_hf

    if arrangement == "7b":
        hf = _tiny_falcon(new_arch=False)
        expect_style, expect_kv = "parallel", 1
    elif arrangement == "40b":
        hf = _tiny_falcon(new_arch=True, seed=31)
        expect_style, expect_kv = "parallel2", 2
    else:
        hf = _tiny_falcon(new_arch=False, parallel_attn=False, seed=32)
        expect_style, expect_kv = "pre", 1
    model, params = falcon_from_hf(hf, dtype=jnp.float32)
    assert model.norm_style == expect_style
    assert (model.num_kv_heads or model.num_heads) == expect_kv
    assert model.position == "rope" and not model.use_bias
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)


def test_falcon_converted_generates_like_hf(rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import falcon_from_hf

    hf = _tiny_falcon(new_arch=True, seed=31)
    model, params = falcon_from_hf(hf, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


@pytest.mark.parametrize("arrangement", ["7b", "40b"])
def test_falcon_roundtrip_to_hf(arrangement, rng):
    from tfde_tpu.models.convert import falcon_from_hf, falcon_to_hf

    hf = (_tiny_falcon(new_arch=False) if arrangement == "7b"
          else _tiny_falcon(new_arch=True, seed=31))
    model, params = falcon_from_hf(hf, dtype=jnp.float32)
    hf2 = falcon_to_hf(model, params)
    assert hf2.config.new_decoder_architecture == (arrangement == "40b")
    ids = torch.tensor(rng.integers(0, 101, (2, 10)).astype(np.int64))
    with torch.no_grad():
        a = hf(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


def test_falcon_alibi_refused():
    from tfde_tpu.models.convert import falcon_from_hf

    cfg = transformers.FalconConfig(
        vocab_size=53, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, alibi=True, bias=True, multi_query=False,
        new_decoder_architecture=False,
    )
    torch.manual_seed(0)
    m = transformers.FalconForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="alibi"):
        falcon_from_hf(m, dtype=jnp.float32)


def test_falcon2_single_ln_new_arch(rng):
    """The Falcon2-11B form: new_decoder_architecture (grouped kv) with
    num_ln_in_parallel_attn=1 — ONE shared LayerNorm, so it maps to
    norm_style='parallel'; round-trips through falcon_to_hf."""
    from tfde_tpu.models.convert import falcon_from_hf, falcon_to_hf

    cfg = transformers.FalconConfig(
        vocab_size=101, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2,
        new_decoder_architecture=True, num_ln_in_parallel_attn=1,
        alibi=False, bias=False, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    torch.manual_seed(33)
    hf = transformers.FalconForCausalLM(cfg)
    hf.eval()
    model, params = falcon_from_hf(hf, dtype=jnp.float32)
    assert model.norm_style == "parallel" and model.num_kv_heads == 2
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=5e-3, atol=5e-3)

    hf2 = falcon_to_hf(model, params)
    assert hf2.config.new_decoder_architecture
    assert hf2.config.num_ln_in_parallel_attn == 1
    ids_t = torch.tensor(ids.astype(np.int64))
    with torch.no_grad():
        d = float((hf(ids_t).logits - hf2(ids_t).logits).abs().max())
    assert d < 1e-4


def test_falcon_rope_scaling_refused():
    from tfde_tpu.models.convert import falcon_from_hf

    cfg = transformers.FalconConfig(
        vocab_size=53, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, alibi=False, bias=False,
        rope_scaling={"rope_type": "linear", "factor": 2.0},
    )
    torch.manual_seed(0)
    m = transformers.FalconForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        falcon_from_hf(m, dtype=jnp.float32)


@pytest.fixture(scope="module")
def hf_mixtral():
    cfg = transformers.MixtralConfig(
        vocab_size=101, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, attention_dropout=0.0,
        sliding_window=None, tie_word_embeddings=False,
    )
    torch.manual_seed(40)
    m = transformers.MixtralForCausalLM(cfg)
    m.eval()
    return m


def test_mixtral_logits_match(hf_mixtral, rng):
    """The routed sparse-MoE LLaMA: top-2 of 4 silu-gated experts per
    layer. Conversion pins the no-drop capacity (C = tokens per group),
    so the converted forward is exact — routing, gating renormalization,
    expert stacks, GQA attention all at once."""
    from tfde_tpu.models.convert import mixtral_from_hf

    model, params = mixtral_from_hf(hf_mixtral, dtype=jnp.float32)
    assert model.num_experts == 4 and model.experts_per_token == 2
    assert model.moe_every == 1 and model.mlp_act == "swiglu"
    assert model.moe_capacity_factor == pytest.approx(2.0)  # E/k: no drops
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_mixtral(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_mixtral_converted_generates_like_hf(hf_mixtral, rng):
    """MoE through the KV-cache decode path (single-token groups route
    with capacity 1): greedy generation must equal HF's."""
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import mixtral_from_hf

    model, params = mixtral_from_hf(hf_mixtral, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_mixtral.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_mixtral_roundtrip_to_hf(hf_mixtral, rng):
    from tfde_tpu.models.convert import mixtral_from_hf, mixtral_to_hf

    model, params = mixtral_from_hf(hf_mixtral, dtype=jnp.float32)
    hf2 = mixtral_to_hf(model, params)
    assert hf2.config.num_local_experts == 4
    ids = torch.tensor(rng.integers(0, 101, (2, 10)).astype(np.int64))
    with torch.no_grad():
        a = hf_mixtral(ids).logits
        b = hf2(ids).logits
    assert float((a - b).abs().max()) < 1e-4


@pytest.mark.slow
def test_mixtral_trains_under_expert_parallelism(hf_mixtral, rng):
    """The converted Mixtral fine-tunes under ExpertParallelStrategy on
    the virtual mesh: expert stacks (including the new experts_gate)
    shard over 'expert', loss falls, and the sown aux loss rides the
    objective."""
    import optax

    from tfde_tpu.models.convert import mixtral_from_hf
    from tfde_tpu.models.gpt import next_token_loss
    from tfde_tpu.parallel.strategies import ExpertParallelStrategy
    from tfde_tpu.training.step import init_state, make_custom_train_step
    from jax.sharding import PartitionSpec as P

    model, params = mixtral_from_hf(hf_mixtral, dtype=jnp.float32)
    s = ExpertParallelStrategy(data=2)  # expert axis = 4
    state, _ = init_state(model, optax.adamw(1e-3), s,
                          np.zeros((8, 16), np.int32), seed=0)
    state = state.replace(params=jax.device_put(
        params, s.params_sharding(params)
    ))
    gate = state.params["decoder"]["block_0"]["moe"]["experts_gate"]
    assert gate.sharding.spec[0] == "expert"
    step = make_custom_train_step(s, state, next_token_loss, donate=False)
    toks = rng.integers(0, 101, (8, 16)).astype(np.int32)
    first = last = None
    for i in range(5):
        state, metr = step(state, (toks,), jax.random.key(i))
        if first is None:
            first = float(metr["loss"])
        last = float(metr["loss"])
    assert "moe_aux" in metr
    assert last < first, (first, last)


def test_mixtral_to_hf_refuses_droppy_capacity(hf_mixtral):
    """HF Mixtral computes every token; a model whose capacity can drop
    overflow learned around those drops — exporting it drop-free would
    silently change its logits."""
    from tfde_tpu.models.convert import mixtral_from_hf, mixtral_to_hf

    model, params = mixtral_from_hf(hf_mixtral, dtype=jnp.float32)
    droppy = model.clone(moe_capacity_factor=1.25)
    with pytest.raises(NotImplementedError, match="capacity"):
        mixtral_to_hf(droppy, params)


@pytest.mark.parametrize("scaling", ["llama3", "linear"])
def test_llama_rope_scaling_logits_match(scaling, rng):
    """Llama-3.1-style rope scaling (and linear position interpolation):
    the scaled-frequency rule (ops/rotary.scale_frequencies) must
    reproduce transformers' logits — the gate on converting every
    Llama-3.1+ checkpoint."""
    from tfde_tpu.models.convert import llama_from_hf

    if scaling == "llama3":
        rs = {"rope_type": "llama3", "factor": 8.0,
              "low_freq_factor": 1.0, "high_freq_factor": 4.0,
              "original_max_position_embeddings": 32}
    else:
        rs = {"rope_type": "linear", "factor": 4.0}
    cfg = transformers.LlamaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, attention_dropout=0.0,
        tie_word_embeddings=False, rope_scaling=dict(rs),
    )
    torch.manual_seed(50)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    model, params = llama_from_hf(hf, dtype=jnp.float32)
    assert model.rope_scaling is not None
    # long enough that scaled and unscaled frequencies visibly diverge
    ids = rng.integers(0, 101, (2, 48)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    # and the scaling actually changes the math (not silently ignored)
    plain = model.clone(rope_scaling=None)
    other = np.asarray(plain.apply({"params": params}, jnp.asarray(ids)))
    assert np.abs(other - ref).max() > 1e-3


def test_llama_rope_scaling_roundtrip_and_artifact(tmp_path, rng):
    """to_hf re-emits the rope_scaling config; the conversion artifact
    persists the tuple through save/load (json list -> tuple)."""
    from tfde_tpu.models.convert import (
        _cli,
        llama_from_hf,
        llama_to_hf,
        load_converted,
    )

    cfg = transformers.LlamaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, attention_dropout=0.0,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
    )
    torch.manual_seed(51)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    model, params = llama_from_hf(hf, dtype=jnp.float32)
    hf2 = llama_to_hf(model, params)
    assert hf2.config.rope_scaling["rope_type"] == "llama3"
    ids = torch.tensor(rng.integers(0, 101, (2, 40)).astype(np.int64))
    with torch.no_grad():
        assert float((hf(ids).logits - hf2(ids).logits).abs().max()) < 1e-4

    src = str(tmp_path / "hf")
    art = str(tmp_path / "art")
    hf.save_pretrained(src)
    _cli(["llama", src, art])
    m2, p2 = load_converted(art, dtype=jnp.float32)
    assert isinstance(m2.rope_scaling, tuple) and m2.rope_scaling[0] == "llama3"
    a = np.asarray(model.apply({"params": params},
                               jnp.asarray(ids.numpy(), jnp.int32)))
    b = np.asarray(m2.apply({"params": p2},
                            jnp.asarray(ids.numpy(), jnp.int32)))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_rope_scaling_tuple_contract():
    from tfde_tpu.models.convert import _rope_scaling_tuple

    # yarn without any original-max source cannot be computed
    with pytest.raises(NotImplementedError, match="max_position"):
        _rope_scaling_tuple({"rope_type": "yarn", "factor": 4.0})
    # ... but falls back to the config's max_position (the HF convention)
    t = _rope_scaling_tuple({"rope_type": "yarn", "factor": 4.0},
                            max_position=128)
    assert t[0] == "yarn" and t[4] == 128.0
    # still-unimplemented rules refuse loudly
    with pytest.raises(NotImplementedError, match="longrope"):
        _rope_scaling_tuple({"rope_type": "longrope", "factor": 4.0})
    assert _rope_scaling_tuple(None) is None
    assert _rope_scaling_tuple({"rope_type": "default"}) is None


def test_gemma_rope_scaling_roundtrips(rng):
    """gemma_to_hf must re-emit rope_scaling (review r5: dropping it
    exported unscaled rope — silently wrong long-context logits)."""
    from tfde_tpu.models.convert import gemma_from_hf, gemma_to_hf

    cfg = transformers.GemmaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=256, attention_dropout=0.0,
        hidden_activation="gelu_pytorch_tanh",
        rope_scaling={"rope_type": "linear", "factor": 4.0},
    )
    torch.manual_seed(52)
    hf = transformers.GemmaForCausalLM(cfg)
    hf.eval()
    model, params = gemma_from_hf(hf, dtype=jnp.float32)
    assert model.rope_scaling == ("linear", 4.0)
    hf2 = gemma_to_hf(model, params)
    assert hf2.config.rope_scaling["factor"] == 4.0
    ids = torch.tensor(rng.integers(0, 101, (2, 40)).astype(np.int64))
    with torch.no_grad():
        assert float((hf(ids).logits - hf2(ids).logits).abs().max()) < 1e-4


@pytest.fixture(scope="module")
def hf_qwen3():
    cfg = transformers.Qwen3Config(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(60)
    m = transformers.Qwen3ForCausalLM(cfg)
    m.eval()
    return m


def test_qwen3_logits_match(hf_qwen3, rng):
    """Qwen3 = bias-free LLaMA arrangement + per-head q/k RMSNorm before
    rotary (GPT(qk_norm=True)) + decoupled head_dim."""
    from tfde_tpu.models.convert import qwen3_from_hf

    model, params = qwen3_from_hf(hf_qwen3, dtype=jnp.float32)
    assert model.qk_norm and not model.qkv_bias and model.head_dim == 16
    assert "q_norm" in params["decoder"]["block_0"]["attn"]
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen3(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    # qk_norm actually participates (not a silently ignored knob)
    off = model.clone(qk_norm=False)
    p2 = jax.tree_util.tree_map(lambda x: x, params)
    for i in range(2):
        p2["decoder"][f"block_{i}"]["attn"].pop("q_norm")
        p2["decoder"][f"block_{i}"]["attn"].pop("k_norm")
    other = np.asarray(off.apply({"params": p2}, jnp.asarray(ids)))
    assert np.abs(other - ref).max() > 1e-3


def test_qwen3_converted_generates_like_hf(hf_qwen3, rng):
    """qk_norm through the KV-cache decode path (norm applied before the
    cache write, matching the training forward)."""
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import qwen3_from_hf

    model, params = qwen3_from_hf(hf_qwen3, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen3.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen3_roundtrip_to_hf(hf_qwen3, rng):
    from tfde_tpu.models.convert import qwen3_from_hf, qwen3_to_hf

    model, params = qwen3_from_hf(hf_qwen3, dtype=jnp.float32)
    hf2 = qwen3_to_hf(model, params)
    ids = torch.tensor(rng.integers(0, 101, (2, 10)).astype(np.int64))
    with torch.no_grad():
        assert float((hf_qwen3(ids).logits - hf2(ids).logits).abs().max()) \
            < 1e-4


def test_mixtral_rope_scaling_roundtrips(rng):
    """Mixtral consumes and re-emits rope_scaling like the llama family
    (review r5: it was left out of the scaling sweep)."""
    from tfde_tpu.models.convert import mixtral_from_hf, mixtral_to_hf

    cfg = transformers.MixtralConfig(
        vocab_size=101, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, attention_dropout=0.0,
        sliding_window=None, tie_word_embeddings=False,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
    )
    torch.manual_seed(41)
    hf = transformers.MixtralForCausalLM(cfg)
    hf.eval()
    model, params = mixtral_from_hf(hf, dtype=jnp.float32)
    assert model.rope_scaling == ("linear", 4.0)
    ids = torch.tensor(rng.integers(0, 101, (2, 40)).astype(np.int64))
    with torch.no_grad():
        ref = hf(ids).logits.numpy()
    ours = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids.numpy(), jnp.int32)
    ))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    hf2 = mixtral_to_hf(model, params)
    assert hf2.config.rope_scaling["factor"] == 4.0
    with torch.no_grad():
        assert float((hf(ids).logits - hf2(ids).logits).abs().max()) < 1e-4


@pytest.mark.parametrize("explicit_att", [False, True])
def test_llama_yarn_rope_scaling(explicit_att, rng):
    """YaRN (NTK-by-parts + attention temperature): the frequency blend
    AND the cos/sin attention factor must reproduce transformers' logits
    — with the factor both mscale-derived and explicit."""
    from tfde_tpu.models.convert import llama_from_hf, llama_to_hf

    rs = {"rope_type": "yarn", "factor": 4.0,
          "original_max_position_embeddings": 32}
    if explicit_att:
        rs.update(beta_fast=16.0, beta_slow=2.0, attention_factor=1.1)
    cfg = transformers.LlamaConfig(
        vocab_size=101, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, attention_dropout=0.0,
        tie_word_embeddings=False, rope_scaling=dict(rs),
    )
    torch.manual_seed(55)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    model, params = llama_from_hf(hf, dtype=jnp.float32)
    assert model.rope_scaling[0] == "yarn"
    ids = torch.tensor(rng.integers(0, 101, (2, 48)).astype(np.int64))
    with torch.no_grad():
        ref = hf(ids).logits.numpy()
    ours = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids.numpy(), jnp.int32)
    ))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    hf2 = llama_to_hf(model, params)
    assert hf2.config.rope_scaling["rope_type"] == "yarn"
    with torch.no_grad():
        assert float((hf(ids).logits - hf2(ids).logits).abs().max()) < 1e-4


def test_qk_norm_models_refused_by_other_exporters(hf_qwen3):
    """llama/mixtral/gemma exporters have no q_norm/k_norm keys to write
    — they must refuse qk_norm models, not silently drop the norms
    (review r5)."""
    from tfde_tpu.models.convert import llama_to_hf, qwen3_from_hf

    model, params = qwen3_from_hf(hf_qwen3, dtype=jnp.float32)
    with pytest.raises(NotImplementedError, match="LLaMA arrangement"):
        llama_to_hf(model, params)


@pytest.fixture(scope="module")
def hf_phi3():
    cfg = transformers.Phi3Config(
        vocab_size=101, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, num_hidden_layers=2,
        max_position_embeddings=64, pad_token_id=0, attention_dropout=0.0,
        resid_pdrop=0.0, embd_pdrop=0.0, sliding_window=None,
    )
    torch.manual_seed(70)
    m = transformers.Phi3ForCausalLM(cfg)
    m.eval()
    return m


def test_phi3_logits_match(hf_phi3, rng):
    """Phi-3 = LLaMA arrangement with FUSED checkpoint layouts: qkv_proj
    splits into q/k/v (GQA widths), gate_up_proj into gate/up."""
    from tfde_tpu.models.convert import phi3_from_hf

    model, params = phi3_from_hf(hf_phi3, dtype=jnp.float32)
    assert model.mlp_act == "swiglu" and model.num_kv_heads == 2
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_phi3(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_phi3_converted_generates_like_hf(hf_phi3, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import phi3_from_hf

    model, params = phi3_from_hf(hf_phi3, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_phi3.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_phi3_roundtrip_to_hf(hf_phi3, rng):
    from tfde_tpu.models.convert import phi3_from_hf, phi3_to_hf

    model, params = phi3_from_hf(hf_phi3, dtype=jnp.float32)
    hf2 = phi3_to_hf(model, params)
    ids = torch.tensor(rng.integers(0, 101, (2, 10)).astype(np.int64))
    with torch.no_grad():
        assert float((hf_phi3(ids).logits - hf2(ids).logits).abs().max()) \
            < 1e-4


def test_phi3_longrope_refused():
    from tfde_tpu.models.convert import phi3_from_hf

    cfg = transformers.Phi3Config(
        vocab_size=53, hidden_size=16, num_attention_heads=2,
        num_key_value_heads=1, intermediate_size=32, num_hidden_layers=1,
        max_position_embeddings=64,
        original_max_position_embeddings=32, pad_token_id=0,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0] * 4,
                      "long_factor": [2.0] * 4},
    )
    torch.manual_seed(0)
    m = transformers.Phi3ForCausalLM(cfg)
    with pytest.raises(NotImplementedError, match="longrope"):
        phi3_from_hf(m, dtype=jnp.float32)


def test_phi3_to_hf_refuses_rope_scaling(hf_phi3):
    """Phi3Config only validates longrope-format scaling dicts; exporting
    a linear/llama3/yarn-scaled model must refuse cleanly, not crash in
    the config validator (review r5)."""
    from tfde_tpu.models.convert import phi3_from_hf, phi3_to_hf

    model, params = phi3_from_hf(hf_phi3, dtype=jnp.float32)
    scaled = model.clone(rope_scaling=("linear", 2.0))
    with pytest.raises(NotImplementedError, match="longrope"):
        phi3_to_hf(scaled, params)


@pytest.fixture(scope="module")
def hf_gemma2():
    cfg = transformers.Gemma2Config(
        vocab_size=101, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, head_dim=8, intermediate_size=64,
        num_hidden_layers=2, max_position_embeddings=64,
        sliding_window=8, attention_dropout=0.0,
    )
    torch.manual_seed(80)
    m = transformers.Gemma2ForCausalLM(cfg)
    m.eval()
    return m


def test_gemma2_logits_match(hf_gemma2, rng):
    """Gemma-2: sandwich norms (4 per block, all 1+w folded), logit
    softcapping (attention + final), query_pre_attn_scalar scale, and
    ALTERNATING sliding/full attention — tested past the window so the
    interleave is load-bearing."""
    from tfde_tpu.models.convert import gemma2_from_hf

    model, params = gemma2_from_hf(hf_gemma2, dtype=jnp.float32)
    assert model.norm_style == "sandwich"
    assert model.sliding_window_pattern == "alternate"
    assert model.attn_logit_cap == 50.0 and model.final_logit_cap == 30.0
    assert model.attn_scale == pytest.approx(256 ** -0.5)
    assert "ln_attn_post" in params["decoder"]["block_0"]
    ids = rng.integers(0, 101, (2, 16)).astype(np.int32)  # 16 > window 8
    with torch.no_grad():
        ref = hf_gemma2(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gemma2_converted_generates_like_hf(hf_gemma2, rng):
    """Generation past the window: even layers decode on the rolling
    window cache, odd layers on the full cache — the per-layer mix must
    still reproduce HF greedy exactly."""
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import gemma2_from_hf

    model, params = gemma2_from_hf(hf_gemma2, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_gemma2.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=12,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt),
                       max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_gemma2_roundtrip_to_hf(hf_gemma2, rng):
    from tfde_tpu.models.convert import gemma2_from_hf, gemma2_to_hf

    model, params = gemma2_from_hf(hf_gemma2, dtype=jnp.float32)
    hf2 = gemma2_to_hf(model, params)
    assert hf2.config.query_pre_attn_scalar == pytest.approx(256.0)
    assert hf2.config.attn_logit_softcapping == 50.0
    ids = torch.tensor(rng.integers(0, 101, (2, 16)).astype(np.int64))
    with torch.no_grad():
        assert float((hf_gemma2(ids).logits - hf2(ids).logits).abs().max()) \
            < 1e-4


@pytest.fixture(scope="module")
def hf_qwen2moe():
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=101, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64,
        moe_intermediate_size=24, shared_expert_intermediate_size=48,
        num_experts=4, num_experts_per_tok=2, num_hidden_layers=2,
        decoder_sparse_step=1, max_position_embeddings=64,
        attention_dropout=0.0, tie_word_embeddings=False,
    )
    torch.manual_seed(90)
    m = transformers.Qwen2MoeForCausalLM(cfg)
    m.eval()
    return m


def test_qwen2moe_logits_match(hf_qwen2moe, rng):
    """Qwen2-MoE: biased q/k/v + every layer routed with RAW top-k
    combine weights (norm_topk_prob=False) + a sigmoid-gated dense
    shared expert — exact at the no-drop capacity."""
    from tfde_tpu.models.convert import qwen2moe_from_hf

    model, params = qwen2moe_from_hf(hf_qwen2moe, dtype=jnp.float32)
    assert model.qkv_bias and not model.moe_normalize_topk
    assert model.moe_shared_expert_dim == 48 and model.moe_every == 1
    assert "shared_expert_gate" in params["decoder"]["block_0"]["moe"]
    ids = rng.integers(0, 101, (2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen2moe(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_qwen2moe_converted_generates_like_hf(hf_qwen2moe, rng):
    from tfde_tpu.inference.decode import generate
    from tfde_tpu.models.convert import qwen2moe_from_hf

    model, params = qwen2moe_from_hf(hf_qwen2moe, dtype=jnp.float32)
    prompt = rng.integers(0, 101, (1, 5)).astype(np.int32)
    with torch.no_grad():
        ref = hf_qwen2moe.generate(
            torch.tensor(prompt.astype(np.int64)), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        ).numpy()
    ours, _ = generate(model, params, jnp.asarray(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(ours), ref)


def test_qwen2moe_roundtrip_to_hf(hf_qwen2moe, rng):
    from tfde_tpu.models.convert import qwen2moe_from_hf, qwen2moe_to_hf

    model, params = qwen2moe_from_hf(hf_qwen2moe, dtype=jnp.float32)
    hf2 = qwen2moe_to_hf(model, params)
    assert hf2.config.shared_expert_intermediate_size == 48
    assert not hf2.config.norm_topk_prob
    ids = torch.tensor(rng.integers(0, 101, (2, 10)).astype(np.int64))
    with torch.no_grad():
        assert float((hf_qwen2moe(ids).logits - hf2(ids).logits)
                     .abs().max()) < 1e-4
