"""Cross-host aggregation acceptance (observability/aggregate.py): the
push/rollup round-trip over a real HTTP server, straggler flagging from
rolling step-time medians, dead-host staleness expiry in virtual time, and
the MetricsServer port-in-use fallback.

Everything runs on private Registry instances and injected clocks/callbacks
so the tests are hermetic against the process-wide default registry."""

import json
import socket
import time
import urllib.request

import pytest

from tfde_tpu.observability import aggregate, metrics
from tfde_tpu.observability.aggregate import (
    ClusterAggregator,
    MetricsPusher,
    push_once,
    snapshot_payload,
)
from tfde_tpu.observability.exposition import MetricsServer, PROM_CONTENT_TYPE


def _payload(host, step_sum, step_count, ts=0.0, extra=None):
    m = {"train/step/sum": step_sum, "train/step/count": step_count}
    m.update(extra or {})
    return {"host": host, "pid": 1, "ts": ts, "metrics": m}


def _sinks():
    """Recorded on_straggler/on_stale callbacks."""
    calls = {"straggler": [], "stale": []}
    return (calls,
            lambda h, r: calls["straggler"].append((h, r)),
            lambda h, a: calls["stale"].append((h, a)))


# -- push/rollup round-trip over real HTTP -----------------------------------
def test_push_rollup_roundtrip_over_http():
    chief_reg = metrics.Registry()
    chief_reg.gauge("train/steps_per_sec").set(10.0)
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=chief_reg, include_local=0,
                            on_straggler=on_strag, on_stale=on_stale)
    srv = MetricsServer(port=0, host="127.0.0.1", registry=chief_reg,
                        aggregator=agg)
    try:
        worker_reg = metrics.Registry()
        worker_reg.gauge("train/steps_per_sec").set(33.0)
        worker_reg.histogram("train/step").observe(0.1)
        url = f"http://127.0.0.1:{srv.port}"
        assert push_once(f"{url}/push", registry=worker_reg, host=1)

        resp = urllib.request.urlopen(f"{url}/metrics")
        ctype = resp.headers.get("Content-Type")
        # proper Prometheus exposition Content-Type, version included
        assert ctype.startswith("text/plain; version=0.0.4")
        assert ctype == PROM_CONTENT_TYPE
        body = resp.read().decode()
        # chief's own series still there...
        assert "tfde_train_steps_per_sec 10.0" in body
        # ...plus the worker's, host-labelled, plus liveness + rollups
        assert 'tfde_train_steps_per_sec{host="1"} 33.0' in body
        assert 'tfde_cluster_host_up{host="1"} 1' in body
        assert 'tfde_cluster_host_up{host="0"} 1' in body  # include_local
        assert "tfde_cluster_hosts_reporting 2" in body
    finally:
        srv.close()


def test_push_rejects_garbage_and_missing_aggregator():
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, on_straggler=on_strag,
                            on_stale=on_stale)
    srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                        aggregator=agg)
    bare = MetricsServer(port=0, host="127.0.0.1", registry=reg)
    try:
        def post(port, data):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/push", data=data,
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                return urllib.request.urlopen(req).status
            except urllib.error.HTTPError as e:
                return e.code

        import urllib.error
        assert post(srv.port, b"not json") == 400
        assert post(srv.port, json.dumps({"metrics": {}}).encode()) == 400
        assert post(bare.port, json.dumps(_payload(1, 1, 1)).encode()) == 404
        # a bad push must not poison the aggregator for good pushes
        assert post(srv.port, json.dumps(_payload(1, 1.0, 10.0)).encode()) == 200
    finally:
        srv.close()
        bare.close()


def test_push_once_unreachable_returns_false_never_raises():
    reg = metrics.Registry()
    assert push_once("http://127.0.0.1:1/push", registry=reg, host=9,
                     timeout=0.2) is False


def test_snapshot_payload_shape():
    reg = metrics.Registry()
    reg.counter("c").incr(2)
    p = snapshot_payload(reg, host=3)
    assert p["host"] == 3 and p["pid"] > 0 and p["ts"] > 0
    assert p["metrics"]["c"] == 2.0


def test_metrics_pusher_thread_pushes_and_final_push():
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, on_straggler=on_strag,
                            on_stale=on_stale)
    srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                        aggregator=agg)
    try:
        wreg = metrics.Registry()
        wreg.gauge("g").set(1.0)
        pusher = MetricsPusher(f"http://127.0.0.1:{srv.port}/push",
                               interval=0.05, registry=wreg, host=2)
        deadline = time.time() + 10.0
        while not agg.hosts().get(2) and time.time() < deadline:
            time.sleep(0.02)
        assert agg.hosts()[2]["pushes"] >= 1
        before = agg.hosts()[2]["pushes"]
        pusher.close()  # close() does one final push
        assert agg.hosts()[2]["pushes"] >= before + 1
    finally:
        srv.close()


# -- straggler detection ------------------------------------------------------
def test_straggler_flagged_and_transition_deduped():
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, straggler_factor=2.0,
                            on_straggler=on_strag, on_stale=on_stale)
    # three hosts, one 10x slower (first push seeds s/c as the sample)
    agg.ingest(_payload(0, 1.0, 10.0))   # 100 ms/step
    agg.ingest(_payload(1, 1.0, 10.0))   # 100 ms/step
    agg.ingest(_payload(2, 10.0, 10.0))  # 1000 ms/step
    out = agg.rollup()
    assert out["straggler_host"] == 2
    assert out["straggler_ratio"] == pytest.approx(10.0)
    assert out["host_medians_ms"][2] == pytest.approx(1000.0)
    assert reg.gauge("cluster/straggler_host").value == 2
    assert calls["straggler"] == [(2, pytest.approx(10.0))]
    agg.rollup()  # same straggler again: callback fires on TRANSITION only
    assert len(calls["straggler"]) == 1
    # rollup gauges present
    assert reg.gauge("cluster/step_time_median_ms").value == pytest.approx(100.0)
    assert reg.gauge("cluster/step_time_max_ms").value == pytest.approx(1000.0)


def test_straggler_needs_two_live_hosts():
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, on_straggler=on_strag,
                            on_stale=on_stale)
    agg.ingest(_payload(0, 50.0, 10.0))  # slow, but alone
    out = agg.rollup()
    assert out["straggler_host"] == -1
    assert calls["straggler"] == []


def test_medians_are_rolling_not_cumulative():
    """A host that WAS slow but recovered must stop being the straggler:
    medians come from per-push deltas over a bounded window."""
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, window=4,
                            on_straggler=on_strag, on_stale=on_stale)
    agg.ingest(_payload(0, 1.0, 10.0))  # host 0 steady at 100 ms
    # host 1: one slow push interval, then fast ones push it out the window
    s, c = 10.0, 10.0
    agg.ingest(_payload(1, s, c))  # 1000 ms/step seed
    for _ in range(5):
        s, c = s + 1.0, c + 10.0  # +100 ms/step intervals
        agg.ingest(_payload(1, s, c))
    out = agg.rollup()
    assert out["host_medians_ms"][1] == pytest.approx(100.0)
    assert out["straggler_host"] == -1


def test_straggler_factor_validated():
    with pytest.raises(ValueError):
        ClusterAggregator(registry=metrics.Registry(), straggler_factor=1.0)


# -- staleness ---------------------------------------------------------------
def test_dead_host_goes_stale_in_virtual_time():
    now = [0.0]
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, stale_after=5.0,
                            on_straggler=on_strag, on_stale=on_stale,
                            clock=lambda: now[0])
    agg.ingest(_payload(0, 1.0, 10.0))
    agg.ingest(_payload(1, 1.0, 10.0))
    assert agg.rollup()["hosts_stale"] == 0

    now[0] = 3.0
    agg.ingest(_payload(0, 2.0, 20.0))  # host 0 keeps pushing; host 1 dies
    now[0] = 6.0  # host 1's last push is 6s old > stale_after=5
    out = agg.rollup()
    assert out["hosts_reporting"] == 1
    assert out["hosts_stale"] == 1 and out["stale_hosts"] == [1]
    assert 1 not in out["host_medians_ms"]  # excluded from rollups
    assert calls["stale"] == [(1, pytest.approx(6.0))]
    agg.rollup()  # still stale: reported once, not per rollup
    assert len(calls["stale"]) == 1

    # prometheus liveness flips too
    text = agg.prometheus_text()
    assert 'tfde_cluster_host_up{host="1"} 0' in text
    assert 'tfde_cluster_host_up{host="0"} 1' in text

    # the host comes back: live again AND the stale latch re-arms
    now[0] = 7.0
    agg.ingest(_payload(1, 3.0, 25.0))
    out = agg.rollup()
    assert out["hosts_stale"] == 0 and out["hosts_reporting"] == 2
    now[0] = 11.0
    agg.ingest(_payload(0, 3.0, 30.0))  # host 0 stays fresh...
    now[0] = 13.0  # ...host 1's comeback push is now 6s old again
    agg.rollup()
    assert calls["stale"] == [(1, pytest.approx(6.0)),
                              (1, pytest.approx(6.0))]  # reported again


def test_scrape_flips_staleness_without_new_pushes():
    """The acceptance path: a worker dies, the chief's /metrics must show
    it stale on the next scrape even though nothing pushes anymore."""
    now = [0.0]
    reg = metrics.Registry()
    calls, on_strag, on_stale = _sinks()
    agg = ClusterAggregator(registry=reg, stale_after=1.0,
                            on_straggler=on_strag, on_stale=on_stale,
                            clock=lambda: now[0])
    srv = MetricsServer(port=0, host="127.0.0.1", registry=reg,
                        aggregator=agg)
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/push",
            data=json.dumps(_payload(1, 1.0, 10.0)).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        urllib.request.urlopen(req)
        body = urllib.request.urlopen(url).read().decode()
        assert "tfde_cluster_hosts_stale 0" in body
        now[0] = 2.0  # ...worker dies; only the scrape-side clock moves
        body = urllib.request.urlopen(url).read().decode()
        assert "tfde_cluster_hosts_stale 1" in body
        assert 'tfde_cluster_host_up{host="1"} 0' in body
    finally:
        srv.close()


# -- port-in-use fallback -----------------------------------------------------
def test_metrics_server_port_in_use_falls_back(caplog):
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    taken = blocker.getsockname()[1]
    try:
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="tfde_tpu.observability.exposition"):
            srv = MetricsServer(port=taken, host="127.0.0.1",
                                registry=metrics.Registry())
        try:
            assert srv.port != taken and srv.port > 0
            assert any("falling back" in r.message for r in caplog.records)
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz").read()
            assert ok == b"ok\n"
        finally:
            srv.close()
    finally:
        blocker.close()
