"""Custom-objective Estimator lifecycle (training/lifecycle.py loss_fn /
eval_fn): a causal LM rides the FULL train_and_evaluate machinery —
checkpoints, resume, summaries, throttled eval — instead of a hand-rolled
loop."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.data.pipeline import Dataset
from tfde_tpu.utils import compat
from tfde_tpu.models.gpt import gpt_tiny_test, next_token_loss
from tfde_tpu.ops.losses import masked_lm_loss
from tfde_tpu.training.lifecycle import Estimator, EvalSpec, RunConfig, TrainSpec


def lm_eval_fn(state, params, batch):
    """Deterministic eval twin of next_token_loss: per-batch means + the
    token count as the aggregation weight."""
    (tokens,) = batch if isinstance(batch, tuple) else (batch,)
    logits = state.apply_fn({"params": params}, tokens, train=False)
    labels = tokens[:, 1:].astype(jnp.int32)
    loss, acc = masked_lm_loss(logits[:, :-1], labels)
    n = jnp.asarray(labels.size, jnp.float32)
    return {"loss": loss, "next_token_accuracy": acc, "weight": n}


def _token_input_fn(seed, n=256, batch=16, seq=16, repeat=None):
    from tfde_tpu.data.datasets import synthetic_tokens

    tokens = synthetic_tokens(n, seq, vocab=96)

    def input_fn():
        ds = Dataset.from_tensor_slices((tokens,)).shuffle(n, seed=seed)
        if repeat is None:
            ds = ds.repeat()
        return iter(ds.batch(batch, drop_remainder=True))

    return input_fn


@pytest.mark.slow
def test_lora_estimator_lifecycle(tmp_path):
    """LoRA through the FULL lifecycle: adapters-only TrainState (tiny
    checkpoints), resume-by-default, eval/predict on the MERGED params,
    base frozen throughout."""
    from tfde_tpu.training.lora import LoraConfig

    model = gpt_tiny_test()
    base = model.init(jax.random.key(5), jnp.zeros((2, 8), jnp.int32),
                      train=False)["params"]
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base))
    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=10)
    mk = lambda: Estimator(
        model, optax.adamw(5e-3), config=cfg, loss_fn=next_token_loss,
        eval_fn=lm_eval_fn, lora=LoraConfig(rank=4),
        lora_base_params=base,
    )
    est = mk()
    state = est.train(_token_input_fn(0), max_steps=20)
    # the TrainState holds adapters, not the base — the checkpoint is tiny
    n_train = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    assert n_train < n_base / 5
    first = est.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert np.isfinite(first["loss"])
    est.close()

    # resume: a fresh estimator restores the adapters and continues
    est2 = mk()
    state = est2.train(_token_input_fn(2), max_steps=70)
    assert int(jax.device_get(state.step)) == 70
    second = est2.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert second["loss"] < first["loss"]
    # the frozen base never changed
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(est2._lora_base)[0]),
        np.asarray(jax.tree_util.tree_leaves(base)[0]),
    )
    est2.close()


def test_lora_continuous_eval_from_checkpoint(tmp_path):
    """LoRA + eval_mode='from_checkpoint': the background evaluator must
    build the same adapters-only state template to restore the trainer's
    tiny checkpoints, and evaluate MERGED params — the regression case
    for the evaluator inheriting lora/lora_base_params."""
    from tfde_tpu.training.lora import LoraConfig

    model = gpt_tiny_test()
    base = model.init(jax.random.key(5), jnp.zeros((2, 8), jnp.int32),
                      train=False)["params"]
    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=5,
                    save_summary_steps=100)
    est = Estimator(model, optax.adamw(5e-3), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn,
                    lora=LoraConfig(rank=4), lora_base_params=base)
    from tfde_tpu.training.lifecycle import train_and_evaluate

    state, metrics = train_and_evaluate(
        est,
        TrainSpec(_token_input_fn(0), max_steps=15),
        EvalSpec(_token_input_fn(1, repeat=1), start_delay_secs=0,
                 throttle_secs=0.2),
        eval_mode="from_checkpoint",
    )
    est.close()
    assert int(jax.device_get(state.step)) == 15
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_lm_estimator_lifecycle_and_resume(tmp_path):
    cfg = RunConfig(model_dir=str(tmp_path), save_summary_steps=5,
                    save_checkpoints_steps=10, log_step_count_steps=10)
    est = Estimator(gpt_tiny_test(), optax.adamw(3e-3), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn)
    est.train(_token_input_fn(0), max_steps=20)
    first = est.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert np.isfinite(first["loss"])
    assert 0.0 <= first["next_token_accuracy"] <= 1.0
    est.close()

    # resume-by-default: a fresh estimator picks up step 20 and trains the
    # remainder only; loss must keep improving on the structured stream
    est2 = Estimator(gpt_tiny_test(), optax.adamw(3e-3), config=cfg,
                     loss_fn=next_token_loss, eval_fn=lm_eval_fn)
    state = est2.train(_token_input_fn(2), max_steps=60)
    assert int(jax.device_get(state.step)) == 60
    second = est2.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert second["loss"] < first["loss"]
    est2.close()

    # summaries were written for train and eval
    files = []
    for root, _, names in os.walk(tmp_path):
        files += [os.path.join(root, f) for f in names if "tfevents" in f]
    assert len(files) >= 2


def test_lm_train_and_evaluate_interleaves(tmp_path):
    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=10)
    est = Estimator(gpt_tiny_test(), optax.adamw(3e-3), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn)
    from tfde_tpu.training.lifecycle import train_and_evaluate

    state, metrics = train_and_evaluate(
        est,
        TrainSpec(input_fn=_token_input_fn(0), max_steps=15),
        EvalSpec(input_fn=_token_input_fn(1, repeat=1), steps=2,
                 start_delay_secs=0, throttle_secs=0),
    )
    assert int(jax.device_get(state.step)) == 15
    assert np.isfinite(metrics["loss"])
    est.close()


@pytest.mark.slow
def test_lm_continuous_eval_from_checkpoint(tmp_path):
    """The evaluator job inherits the custom objective: a background
    evaluator on a custom-loss Estimator must run the eval_fn path, not
    crash in the classification padding protocol."""
    from tfde_tpu.training.lifecycle import train_and_evaluate

    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=5)
    est = Estimator(gpt_tiny_test(), optax.adamw(3e-3), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn)
    state, metrics = train_and_evaluate(
        est,
        TrainSpec(input_fn=_token_input_fn(0), max_steps=10),
        EvalSpec(input_fn=_token_input_fn(1, repeat=1), steps=2,
                 start_delay_secs=0, throttle_secs=0),
        eval_mode="from_checkpoint",
    )
    assert int(jax.device_get(state.step)) == 10
    assert np.isfinite(metrics.get("loss", float("nan")))
    est.close()


def test_train_and_evaluate_fails_fast_without_eval_fn(tmp_path):
    """The missing-eval_fn error must fire BEFORE training, not after the
    budget is spent at the first throttled eval."""
    from tfde_tpu.training.lifecycle import train_and_evaluate

    cfg = RunConfig(model_dir=str(tmp_path))
    est = Estimator(gpt_tiny_test(), optax.adamw(1e-3), config=cfg,
                    loss_fn=next_token_loss)
    with pytest.raises(RuntimeError, match="eval_fn"):
        train_and_evaluate(
            est,
            TrainSpec(input_fn=_token_input_fn(0), max_steps=5),
            EvalSpec(input_fn=_token_input_fn(1, repeat=1), steps=1),
        )
    # nothing trained: the check fired at entry
    assert est._state is None
    est.close()


def test_custom_loss_without_eval_fn_refuses(tmp_path):
    cfg = RunConfig(model_dir=str(tmp_path))
    est = Estimator(gpt_tiny_test(), optax.adamw(1e-3), config=cfg,
                    loss_fn=next_token_loss)
    est.train(_token_input_fn(0), max_steps=2)
    with pytest.raises(RuntimeError, match="eval_fn"):
        est.evaluate(_token_input_fn(1, repeat=1))
    est.close()


def test_lm_estimator_grad_accum(tmp_path):
    cfg = RunConfig(model_dir=None)
    est = Estimator(gpt_tiny_test(), optax.adamw(3e-3), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn,
                    grad_accum=2)
    state = est.train(_token_input_fn(0), max_steps=5)
    assert int(jax.device_get(state.step)) == 5
    est.close()


def test_partial_eval_batch_fails_with_named_cause(tmp_path):
    """A trailing partial batch (input_fn without drop_remainder) must fail
    with an error naming drop_remainder, not an opaque sharding error
    inside device_put/jit (advisor r3)."""
    from tfde_tpu.data.datasets import synthetic_tokens
    from tfde_tpu.parallel.strategies import MirroredStrategy

    cfg = RunConfig(model_dir=str(tmp_path))
    est = Estimator(gpt_tiny_test(), optax.sgd(0.1), config=cfg,
                    loss_fn=next_token_loss, eval_fn=lm_eval_fn,
                    strategy=MirroredStrategy())
    est.train(_token_input_fn(3), max_steps=1)
    tokens = synthetic_tokens(37, 16, vocab=96)  # 37 % 8 devices != 0

    def ragged_input_fn():
        # one full batch of 32, then a partial batch of 5
        return iter(Dataset.from_tensor_slices((tokens,)).batch(32))

    with pytest.raises(ValueError, match="drop_remainder"):
        est.evaluate(ragged_input_fn, name="ragged")


@pytest.mark.skipif(
    not compat.supports_partial_manual(),
    reason="partial-auto shard_map unsupported on this jax",
)
def test_pipelined_1f1b_estimator_lifecycle_and_resume(tmp_path):
    """The full Estimator machinery — checkpointing the pipe-sharded
    [S, L, ...] stage params via orbax, resume-by-default, throttled eval
    — over a PipelinedLM training on the 1F1B schedule. Proves the
    round-4 schedule composes with the round-1 lifecycle, not just with
    bare train steps."""
    from tfde_tpu.models.pipelined import (
        pipelined_next_token_loss,
        pipelined_tiny_test,
    )
    from tfde_tpu.parallel.strategies import PipelineParallelStrategy

    def eval_fn(state, params, batch):
        (tokens,) = batch if isinstance(batch, tuple) else (batch,)
        model = state.apply_fn.__self__
        loss, metrics = model.loss_and_metrics(
            {"params": params}, tokens, train=False
        )
        n = float(tokens.shape[0] * (tokens.shape[1] - 1))
        return {"loss": loss, **metrics,
                "weight": jnp.asarray(n, jnp.float32)}

    model = pipelined_tiny_test(schedule="1f1b")
    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=5,
                    save_summary_steps=5, log_step_count_steps=5)

    def make_est():
        return Estimator(
            model, optax.adamw(3e-3),
            strategy=PipelineParallelStrategy(data=2, pipe=2),
            config=cfg, loss_fn=pipelined_next_token_loss, eval_fn=eval_fn,
        )

    est = make_est()
    est.train(_token_input_fn(0), max_steps=10)
    first = est.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert np.isfinite(first["loss"])
    est.close()

    # resume-by-default: fresh estimator picks up step 10, trains on
    est2 = make_est()
    state = est2.train(_token_input_fn(0), max_steps=14)
    assert int(jax.device_get(state.step)) == 14
    second = est2.evaluate(_token_input_fn(1, repeat=1), name="eval")
    assert second["loss"] < first["loss"] + 0.05  # still improving-ish
    est2.close()


def test_merged_params_restores_in_fresh_process(tmp_path):
    """The deploy step runs in a new process: merged_params(sample_input)
    restores the latest adapters-only checkpoint and returns base-shaped
    params; without a checkpoint it refuses loudly."""
    from tfde_tpu.training.lora import LoraConfig

    model = gpt_tiny_test()
    base = model.init(jax.random.key(5), jnp.zeros((2, 8), jnp.int32),
                      train=False)["params"]
    cfg = RunConfig(model_dir=str(tmp_path), save_checkpoints_steps=3)
    mk = lambda: Estimator(model, optax.adamw(5e-3), config=cfg,
                           loss_fn=next_token_loss,
                           lora=LoraConfig(rank=4), lora_base_params=base)
    est = mk()
    est.train(_token_input_fn(0), max_steps=6)
    est.close()

    est2 = mk()  # fresh-process analog
    merged = est2.merged_params(sample_input=np.zeros((16, 16), np.int32))
    assert (jax.tree_util.tree_structure(merged)
            == jax.tree_util.tree_structure(base))
    est2.close()

    empty_cfg = RunConfig(model_dir=str(tmp_path / "empty"),
                          save_checkpoints_steps=3)
    est3 = Estimator(model, optax.adamw(5e-3), config=empty_cfg,
                     loss_fn=next_token_loss, lora=LoraConfig(rank=4),
                     lora_base_params=base)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="no checkpoint|no trained"):
        est3.merged_params(sample_input=np.zeros((16, 16), np.int32))
