"""BERT MLM config tests: architecture parity, masking recipe statistics,
masked-loss correctness, custom-train-step integration, example smoke
(SURVEY.md §4; BASELINE.json configs[4])."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tfde_tpu.data.mlm import IGNORE_ID, MlmConfig, mask_tokens
from tfde_tpu.models.bert import BertBase, bert_tiny_test
from tfde_tpu.ops.losses import masked_lm_loss
from tfde_tpu.parallel.strategies import MultiWorkerMirroredStrategy
from tfde_tpu.training.step import init_state, make_custom_train_step
import pytest


def test_bert_base_param_count():
    m = BertBase()
    v = jax.eval_shape(m.init, jax.random.key(0), jnp.zeros((1, 16), jnp.int32))
    n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
    # Analytic count, computed independently of the model code:
    V, H, P, T, L, F = 30522, 768, 512, 2, 12, 3072
    emb = V * H + P * H + T * H + 2 * H
    per_layer = (
        3 * (H * H + H)        # q,k,v
        + H * H + H            # out proj
        + 2 * (2 * H)          # two LayerNorms
        + H * F + F            # fc1
        + F * H + H            # fc2
    )
    head = H * H + H + 2 * H + V  # mlm dense + LN + tied-decoder bias
    assert n == emb + L * per_layer + head


def test_bert_tiny_forward_shapes(rng):
    m = bert_tiny_test()
    ids = jnp.asarray(rng.integers(0, 97, (2, 16)), jnp.int32)
    v = m.init(jax.random.key(0), ids, train=False)
    logits = m.apply(v, ids, train=False)
    assert logits.shape == (2, 16, 97)
    assert logits.dtype == jnp.float32


def test_bert_attention_mask_blocks_padding(rng):
    m = bert_tiny_test()
    ids = jnp.asarray(rng.integers(0, 97, (2, 16)), jnp.int32)
    v = m.init(jax.random.key(0), ids, train=False)
    am = np.ones((2, 16), np.float32)
    am[:, 12:] = 0.0
    out = m.apply(v, ids, attention_mask=jnp.asarray(am), train=False)
    ids2 = np.asarray(ids).copy()
    ids2[:, 12:] = 3  # change padded tokens
    out2 = m.apply(v, jnp.asarray(ids2), attention_mask=jnp.asarray(am), train=False)
    np.testing.assert_allclose(
        np.asarray(out)[:, :12], np.asarray(out2)[:, :12], rtol=1e-4, atol=1e-4
    )


def test_mask_tokens_statistics():
    rng = np.random.default_rng(0)
    cfg = MlmConfig(vocab_size=1000, mask_id=999, num_special=5)
    tokens = rng.integers(5, 999, (200, 128)).astype(np.int32)
    input_ids, labels = mask_tokens(tokens, cfg, rng)
    selected = labels != IGNORE_ID
    rate = selected.mean()
    assert 0.13 < rate < 0.17  # ~15%
    # at selected positions labels hold the original token
    np.testing.assert_array_equal(labels[selected], tokens[selected])
    # unselected positions pass through unchanged
    np.testing.assert_array_equal(input_ids[~selected], tokens[~selected])
    # of selected: ~80% mask, ~10% random, ~10% keep
    masked = (input_ids == cfg.mask_id) & selected
    kept = (input_ids == tokens) & selected
    assert 0.75 < masked.sum() / selected.sum() < 0.85
    assert 0.05 < kept.sum() / selected.sum() < 0.15
    # every example has at least one target
    assert selected.any(axis=1).all()


def test_masked_lm_loss_ignores_non_targets(rng):
    logits = jnp.asarray(rng.standard_normal((2, 8, 11)), jnp.float32)
    labels = np.full((2, 8), IGNORE_ID, np.int32)
    labels[0, 2] = 4
    labels[1, 5] = 7
    loss, acc = masked_lm_loss(logits, jnp.asarray(labels))
    expect = np.mean(
        [
            -jax.nn.log_softmax(logits[0, 2])[4],
            -jax.nn.log_softmax(logits[1, 5])[7],
        ]
    )
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)
    # perturbing a non-target position must not move the loss
    logits2 = np.asarray(logits).copy()
    logits2[0, 0] += 100.0
    loss2, _ = masked_lm_loss(jnp.asarray(logits2), jnp.asarray(labels))
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)


@pytest.mark.slow
def test_bert_custom_train_step_loss_decreases(rng):
    strategy = MultiWorkerMirroredStrategy()
    m = bert_tiny_test()
    from examples.bert_mlm import mlm_loss_fn

    state, _ = init_state(
        m, optax.adamw(3e-3), strategy, np.zeros((16, 16), np.int32)
    )
    step = make_custom_train_step(strategy, state, mlm_loss_fn, donate=False)
    cfg = MlmConfig(vocab_size=96, mask_id=96)
    from tfde_tpu.data.datasets import synthetic_tokens

    tokens = synthetic_tokens(256, 16, vocab=96)
    nrng = np.random.default_rng(0)
    key = jax.random.key(0)
    first = None
    for i in range(8):
        idx = nrng.integers(0, len(tokens), 16)
        batch = mask_tokens(tokens[idx], cfg, nrng)
        state, metrics = step(state, batch, key)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
    assert "mlm_accuracy" in metrics


@pytest.mark.slow
def test_bert_example_smoke():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples import bert_mlm

    state, metrics = bert_mlm.main(
        ["--tiny", "--seq-len", "16", "--max-steps", "2", "--batch-size", "16",
         "--train-examples", "64"]
    )
    assert int(jax.device_get(state.step)) == 2
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
