"""Pipeline-parallel LM tests: the pipelined execution path must be
numerically identical to (a) the sequential scan fallback and (b) plain DP
training — the TPU-native analog of the reference's requirement that a
distribution strategy not change the math (SURVEY.md §2c; VERDICT round-1
item 3: "test training a small GPT at pipe=2 to DP-identical numerics")."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.gpt import next_token_loss
from tfde_tpu.models.pipelined import PipelinedLM, pipelined_tiny_test
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    PipelineParallelStrategy,
)
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import init_state, make_custom_train_step
from tfde_tpu.utils import compat

_partial_auto = pytest.mark.skipif(
    not compat.supports_partial_manual(),
    reason="partial-auto shard_map unsupported on this jax",
)


@pytest.fixture(scope="module")
def model():
    return pipelined_tiny_test()


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, 97, (16, 32)).astype(np.int32)


def test_pipelined_forward_matches_sequential(model, tokens):
    """Same params, same tokens: pipe=2 logits == no-mesh sequential logits."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    seq_logits = jax.jit(
        lambda v, t: model.apply(v, t)
    )(variables, tokens)

    mesh = make_mesh({"data": 2, "pipe": 2}, jax.devices()[:4])

    def pipe_forward(v, t):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t)

    pipe_logits = jax.jit(pipe_forward)(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(pipe_logits), np.asarray(seq_logits), rtol=1e-4, atol=1e-5
    )


def test_pipelined_train_matches_dp(model, tokens):
    """5 AdamW steps at pipe=2 x data=2 == 5 steps at data=4 (exact math,
    fp32 tolerance)."""
    strat_p = PipelineParallelStrategy(data=2, pipe=2)
    state_p, _ = init_state(model, optax.adam(1e-3), strat_p, tokens)
    step_p = make_custom_train_step(strat_p, state_p, next_token_loss,
                                    donate=False)

    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state_p, m_p = step_p(state_p, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m_p["loss"]), float(m_d["loss"]), rtol=2e-5
    )
    assert float(m_p["loss"]) < 4.6  # loss actually moved off init (~ln 97)


def test_stage_params_sharded_over_pipe(model, tokens):
    """Each pipe rank must hold only its stage's weights — the memory point
    of pipelining (round-1 VERDICT: replicated microbatches/stages defeat
    it)."""
    strat = PipelineParallelStrategy(data=2, pipe=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        state.params["stages"]
    ):
        spec = leaf.sharding.spec
        assert spec and spec[0] == "pipe", (
            f"stage leaf {jax.tree_util.keystr(path)} not sharded over "
            f"'pipe': {spec}"
        )
    # embedding + head stay replicated
    assert state.params["wte"].sharding.spec == ()
    # optimizer state follows params: stage moments sharded too
    mu = state.opt_state[0].mu["stages"]
    leaf = jax.tree_util.tree_leaves(mu)[0]
    assert leaf.sharding.spec[0] == "pipe"


def test_microbatch_divisibility_error(model):
    strat = PipelineParallelStrategy(data=1, pipe=2)
    bad = np.zeros((6, 32), np.int32)  # 6 % microbatches(4) != 0
    state, _ = init_state(model, optax.adam(1e-3), strat,
                          np.zeros((8, 32), np.int32))
    step = make_custom_train_step(strat, state, next_token_loss, donate=False)
    with pytest.raises(ValueError, match="microbatches"):
        step(state, (bad,), jax.random.key(0))


def test_pipelined_respects_max_position(model):
    too_long = np.zeros((8, 128), np.int32)
    variables = model.init(jax.random.key(0), np.zeros((8, 32), np.int32))
    with pytest.raises(ValueError, match="max_position"):
        model.apply(variables, too_long)


def test_loss_reduce_path_matches_broadcast_path(model, tokens):
    """loss_and_metrics (last-stage reduction, 3-scalar psum) must equal the
    full-logit broadcast path's next_token_loss — values AND grads."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    mesh = make_mesh({"data": 2, "pipe": 2}, jax.devices()[:4])

    def loss_reduce(params):
        with axes_lib.use_axes(mesh):
            loss, _ = model.loss_and_metrics({"params": params}, tokens)
        return loss

    def loss_broadcast(params):
        from tfde_tpu.ops.losses import masked_lm_loss

        with axes_lib.use_axes(mesh):
            logits = model.apply({"params": params}, tokens)
        loss, _ = masked_lm_loss(
            logits[:, :-1], tokens[:, 1:].astype(jnp.int32)
        )
        return loss

    v_r, g_r = jax.jit(jax.value_and_grad(loss_reduce))(variables["params"])
    v_b, g_b = jax.jit(jax.value_and_grad(loss_broadcast))(variables["params"])
    np.testing.assert_allclose(float(v_r), float(v_b), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        g_r, g_b,
    )


@pytest.mark.slow
def test_pipelined_train_reduce_path_matches_dp(model, tokens):
    """Training through pipelined_next_token_loss (last-stage reduction) at
    pipe=2 x data=2 == plain DP at data=4 — the VERDICT r2 #9 'done' bar."""
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    strat_p = PipelineParallelStrategy(data=2, pipe=2)
    state_p, _ = init_state(model, optax.adam(1e-3), strat_p, tokens)
    step_p = make_custom_train_step(strat_p, state_p, pipelined_next_token_loss,
                                    donate=False)

    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state_p, m_p = step_p(state_p, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m_p["loss"]), float(m_d["loss"]), rtol=2e-5
    )


@pytest.mark.slow
def test_pipelined_dropout_in_pipe(tokens):
    """Dropout on (VERDICT r2 weak #8 capability cliff closed): the pipe
    path fires dropout deterministically per seed, with masks UNCORRELATED
    across microbatches and data shards (a naive per-shard mask from one key
    would silently repeat across shards). Exact-numerics parity tests stay
    at dropout 0, like every framework's."""
    from tfde_tpu.parallel import axes as axes_lib

    model = pipelined_tiny_test(dropout_rate=0.5)
    mesh = make_mesh({"data": 2, "pipe": 2}, jax.devices()[:4])
    # identical rows: output rows can only differ through dropout masks
    one_row = tokens[:1]
    same = np.broadcast_to(one_row, tokens.shape).copy()
    variables = model.init(jax.random.key(0), same)
    rngs = {"dropout": jax.random.key(7)}

    def pipe_forward(v, t, r):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t, train=True, rngs=r)

    pipe_fn = jax.jit(pipe_forward)
    a = np.asarray(pipe_fn(variables, same, rngs))
    # deterministic per seed
    b = np.asarray(pipe_fn(variables, same, rngs))
    np.testing.assert_array_equal(a, b)
    # different seed -> different masks
    c = np.asarray(pipe_fn(variables, same, {"dropout": jax.random.key(8)}))
    assert not np.allclose(a, c, atol=1e-3)
    # eval mode (no dropout) differs from train mode
    with axes_lib.use_axes(mesh):
        ev = np.asarray(model.apply(variables, same))
    assert not np.allclose(a, ev, atol=1e-3)
    # no two example rows share a mask: identical inputs, all outputs
    # pairwise distinct across microbatches AND data shards
    rows = a.reshape(a.shape[0], -1)
    for i in range(rows.shape[0]):
        for j in range(i + 1, rows.shape[0]):
            assert not np.allclose(rows[i], rows[j], atol=1e-5), (i, j)
    # the reduce-path loss trains with dropout too (smoke)
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    strat = PipelineParallelStrategy(data=2, pipe=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    state, m = step(state, (tokens,), jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


@_partial_auto
def test_3d_dp_pp_tp_matches_dp(model, tokens):
    """3D parallelism (dp=2 x pipe=2 x tensor=2, 8 devices): stage weights
    shard over BOTH 'pipe' (stage dim) and 'tensor' (Megatron column/row
    dims), the pipe runs in partial-manual mode, and 5 training steps match
    plain dp=4 numerics — parallelism is layout, never math."""
    from jax.sharding import PartitionSpec as P

    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    strat3d = PipelineParallelStrategy(data=2, pipe=2, tensor=2)
    state3, _ = init_state(model, optax.adam(1e-3), strat3d, tokens)

    # qkv kernel [S, L, embed, heads, hd]: pipe on the stage dim, tensor on
    # heads; fc2 kernel [S, L, ffn, embed]: tensor on ffn (row-parallel)
    qkv = state3.params["stages"]["attn"]["query"]["kernel"]
    assert qkv.sharding.spec == P("pipe", None, None, "tensor", None)
    fc2 = state3.params["stages"]["mlp"]["fc2"]["kernel"]
    assert fc2.sharding.spec == P("pipe", None, "tensor", None)
    # Adam moments follow
    mu_qkv = state3.opt_state[0].mu["stages"]["attn"]["query"]["kernel"]
    assert mu_qkv.sharding.spec == P("pipe", None, None, "tensor", None)

    step3 = make_custom_train_step(strat3d, state3, pipelined_next_token_loss,
                                   donate=False)
    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state3, m3 = step3(state3, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m3["loss"]), float(m_d["loss"]), rtol=5e-5
    )
    assert float(m3["loss"]) < 4.6  # moved off init (~ln 97)


def test_tensor_without_pipe_rejected():
    """tensor>1 with pipe<=1 would silently replicate everything across the
    tensor devices — must be a loud error."""
    strat = PipelineParallelStrategy(data=2, pipe=1, tensor=2)
    with pytest.raises(ValueError, match="tensor"):
        strat.params_spec({"stages": {"w": jnp.zeros((1, 2, 4, 4))}})


@_partial_auto
def test_3d_with_dropout_trains(tokens):
    """3D mesh + dropout: auto-mode global masks, one finite training step
    through the last-stage-reduction loss."""
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    model = pipelined_tiny_test(dropout_rate=0.1)
    strat = PipelineParallelStrategy(data=2, pipe=2, tensor=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    state, m = step(state, (tokens,), jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


@_partial_auto
def test_3d_with_remat_dots_trains(tokens):
    """jax.checkpoint('dots' policy) inside the partial-manual pipe: one
    finite training step on the 3D mesh."""
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    model = pipelined_tiny_test(remat="dots")
    strat = PipelineParallelStrategy(data=2, pipe=2, tensor=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    state, m = step(state, (tokens,), jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


@_partial_auto
def test_flash_refused_inside_partial_manual_pipe(tokens):
    """Explicit flash inside the partial-manual 3D pipe must error with
    guidance (the kernel's custom-VJP variance doesn't compose with a
    nested shard_map), and 'auto' must quietly pick the reference einsum
    there — never a silent replicate-or-crash."""
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    strat = PipelineParallelStrategy(data=2, pipe=2, tensor=2)
    m_flash = pipelined_tiny_test(attn_impl="flash")
    state_f, _ = init_state(m_flash, optax.adam(1e-3), strat, tokens)
    step_f = make_custom_train_step(strat, state_f, pipelined_next_token_loss,
                                    donate=False)
    with pytest.raises(NotImplementedError, match="partial-manual"):
        step_f(state_f, (tokens,), jax.random.key(0))


def test_auto_dispatch_skips_flash_under_abstract_mesh(monkeypatch):
    """'auto' never picks flash inside a partial-manual region, even at
    flash-eligible lengths on TPU."""
    import tfde_tpu.ops.attention as att
    from tfde_tpu.parallel import axes as axes_lib

    chosen = []
    monkeypatch.setattr(att, "_on_tpu", lambda: True)
    monkeypatch.setattr(
        att, "reference_attention",
        lambda q, k, v, mask=None, causal=False, window=None, **kw:
        (chosen.append("reference"), q)[1],
    )
    q = jnp.zeros((1, 4096, 1, 4), jnp.bfloat16)
    abstract = compat.abstract_mesh((2,), ("data",))
    with axes_lib.use_axes(abstract):
        att.attention(q, q, q)
    assert chosen == ["reference"]


# --------------------------------------------------------------------------
# 1F1B schedule (parallel/pipeline.pipeline_train_1f1b)
# --------------------------------------------------------------------------

def test_1f1b_loss_and_grads_match_gpipe(model, tokens):
    """The hand-scheduled 1F1B backward must produce the SAME loss and
    gradients as AD through the GPipe forward (both compute exact math;
    only summation order differs -> fp32 tolerance)."""
    from tfde_tpu.parallel import axes as axes_lib

    m_1f1b = pipelined_tiny_test(schedule="1f1b")
    variables = model.init(jax.random.key(0), tokens)
    mesh = make_mesh({"data": 2, "pipe": 2}, jax.devices()[:4])

    def loss_with(mdl):
        def f(params):
            with axes_lib.use_axes(mesh):
                loss, _ = mdl.loss_and_metrics(
                    {"params": params}, tokens, train=True
                )
            return loss
        return f

    v_g, g_g = jax.jit(jax.value_and_grad(loss_with(model)))(
        variables["params"]
    )
    v_1, g_1 = jax.jit(jax.value_and_grad(loss_with(m_1f1b)))(
        variables["params"]
    )
    np.testing.assert_allclose(float(v_1), float(v_g), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        g_1, g_g,
    )


@pytest.mark.slow
def test_1f1b_train_matches_dp(tokens):
    """5 Adam steps through the 1F1B schedule at pipe=2 x data=2 == plain
    DP at data=4 — the same oracle as the GPipe path (VERDICT r3 #5 'done'
    bar)."""
    from tfde_tpu.models.gpt import next_token_loss
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    m_1f1b = pipelined_tiny_test(schedule="1f1b")
    strat_p = PipelineParallelStrategy(data=2, pipe=2)
    state_p, _ = init_state(m_1f1b, optax.adam(1e-3), strat_p, tokens)
    step_p = make_custom_train_step(strat_p, state_p,
                                    pipelined_next_token_loss, donate=False)

    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    plain = pipelined_tiny_test()  # sequential fallback on the DP mesh
    state_d, _ = init_state(plain, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state_p, m_p = step_p(state_p, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m_p["loss"]), float(m_d["loss"]), rtol=2e-5
    )
    assert float(m_p["loss"]) < 4.6


def test_1f1b_single_stage_direct():
    """Degenerate S=1 of pipeline_train_1f1b called directly (the model
    path falls back to the sequential stack at pipe=1, so the schedule's
    S=1 edge — stash_n=1, ticks=M, last rank == rank 0 — only gets
    coverage here)."""
    import jax.numpy as jnp

    from tfde_tpu.parallel.pipeline import pipeline_train_1f1b

    mesh = make_mesh({"data": 1, "pipe": 1}, jax.devices()[:1])
    rng = np.random.default_rng(2)
    stacked = jnp.asarray(rng.normal(size=(1, 3)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, 2, 3)), jnp.float32)
    aux = jnp.asarray(rng.normal(size=(4, 2, 3)), jnp.float32)
    extra = jnp.asarray(rng.normal(size=(3,)), jnp.float32)

    def stage_fn(p, h):
        return jnp.tanh(h * p)

    def loss_fn(e, y, a):
        return {"loss_sum": jnp.sum(e * y * a),
                "count": jnp.asarray(y.size, jnp.float32)}

    sums, grads = jax.jit(lambda s, xx, a, e: pipeline_train_1f1b(
        stage_fn, s, xx, mesh, loss_fn=loss_fn, loss_aux=a, extra_params=e
    ))(stacked, x, aux, extra)

    def ref(s, xx, e):
        return jnp.sum(e * jnp.tanh(xx * s[0]) * aux)

    v, (g_s, g_x, g_e) = jax.value_and_grad(ref, argnums=(0, 1, 2))(
        stacked, x, extra
    )
    np.testing.assert_allclose(float(sums["loss_sum"]), float(v), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["stages"]), np.asarray(g_s),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["x"]), np.asarray(g_x),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["extra"]), np.asarray(g_e),
                               rtol=1e-5)


def test_1f1b_many_microbatches(tokens):
    """M > 2S runs the schedule correctly (steady-state dominates)."""
    from tfde_tpu.parallel import axes as axes_lib

    m8 = pipelined_tiny_test(schedule="1f1b", microbatches=8)
    g8 = pipelined_tiny_test(microbatches=8)
    variables = m8.init(jax.random.key(1), tokens)
    mesh = make_mesh({"data": 1, "pipe": 2}, jax.devices()[:2])

    def loss_fn(mdl):
        def f(params):
            with axes_lib.use_axes(mesh):
                loss, _ = mdl.loss_and_metrics(
                    {"params": params}, tokens, train=True
                )
            return loss
        return f

    v_1, g_1 = jax.jit(jax.value_and_grad(loss_fn(m8)))(variables["params"])
    v_g, g_g = jax.jit(jax.value_and_grad(loss_fn(g8)))(variables["params"])
    np.testing.assert_allclose(float(v_1), float(v_g), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        g_1, g_g,
    )


def test_1f1b_dropout_trains(tokens):
    """Dropout keys pass through the custom_vjp as an explicit argument;
    masks reproduce between the fwd slot and the bwd recompute, so training
    stays finite and deterministic per seed."""
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    model = pipelined_tiny_test(schedule="1f1b", dropout_rate=0.3)
    strat = PipelineParallelStrategy(data=2, pipe=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    state, m = step(state, (tokens,), jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_1f1b_refused_with_tensor_axis(tokens):
    """dp x pp x tp uses AD for its backward; 1F1B must refuse loudly."""
    m = pipelined_tiny_test(schedule="1f1b")
    strat = PipelineParallelStrategy(data=2, pipe=2, tensor=2)
    state, _ = init_state(m, optax.adam(1e-3), strat, tokens)
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    with pytest.raises(NotImplementedError, match="1f1b"):
        step(state, (tokens,), jax.random.key(0))


# --------------------------------------------------------------------------
# pp x sp: ring attention inside the fully-manual pipe
# --------------------------------------------------------------------------

def test_pp_sp_forward_matches_sequential(model, tokens):
    """dp x pipe x seq: sequence sharded over the ring INSIDE pipeline
    stages (ring_attention_manual in the flat manual region) must equal
    the no-mesh sequential forward."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    seq_logits = jax.jit(lambda v, t: model.apply(v, t))(variables, tokens)

    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2}, jax.devices()[:8])

    def pipe_forward(v, t):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t)

    pipe_logits = jax.jit(pipe_forward)(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(pipe_logits), np.asarray(seq_logits), rtol=1e-4,
        atol=1e-5,
    )


@pytest.mark.slow
def test_pp_sp_train_matches_dp(model, tokens):
    """5 Adam steps at dp=2 x pipe=2 x seq=2 == plain DP at data=4 — the
    same numerics oracle as every other strategy family."""
    from tfde_tpu.models.gpt import next_token_loss

    strat_p = PipelineParallelStrategy(data=2, pipe=2, seq=2)
    state_p, _ = init_state(model, optax.adam(1e-3), strat_p, tokens)
    step_p = make_custom_train_step(strat_p, state_p, next_token_loss,
                                    donate=False)

    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state_p, m_p = step_p(state_p, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m_p["loss"]), float(m_d["loss"]), rtol=2e-5
    )
    assert float(m_p["loss"]) < 4.6


def test_pp_sp_loss_and_metrics_routes_outside(model, tokens):
    """loss_and_metrics under a seq axis must route through the full-logit
    path (shift correctness across shard boundaries) and still match the
    sequential loss."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    ref_loss, _ = model.loss_and_metrics(variables, tokens)  # no mesh
    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2}, jax.devices()[:8])

    def f(v, t):
        with axes_lib.use_axes(mesh):
            return model.loss_and_metrics(v, t)

    loss, metrics = jax.jit(f)(variables, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_pp_sp_tp_refused(tokens):
    strat = PipelineParallelStrategy(data=1, pipe=2, tensor=2, seq=2)
    with pytest.raises(ValueError, match="pp x sp x tp"):
        init_state(pipelined_tiny_test(), optax.adam(1e-3), strat,
                   np.zeros((8, 32), np.int32))


@pytest.mark.slow
def test_pp_sp_1f1b_refused(model, tokens):
    from tfde_tpu.models.pipelined import pipelined_next_token_loss

    m = pipelined_tiny_test(schedule="1f1b")
    strat = PipelineParallelStrategy(data=2, pipe=2, seq=2)
    state, _ = init_state(m, optax.adam(1e-3), strat, tokens)
    step = make_custom_train_step(strat, state, pipelined_next_token_loss,
                                  donate=False)
    with pytest.raises(NotImplementedError, match="1f1b"):
        step(state, (tokens,), jax.random.key(0))


def test_1f1b_four_stages(tokens):
    """S=4 (one layer per stage, M=8): the stash ring (2S-1=7 slots) and
    deeper warmup/cooldown windows still reproduce the GPipe grads."""
    from tfde_tpu.parallel import axes as axes_lib

    m4 = pipelined_tiny_test(num_stages=4, layers_per_stage=1,
                             microbatches=8, schedule="1f1b")
    g4 = pipelined_tiny_test(num_stages=4, layers_per_stage=1,
                             microbatches=8)
    variables = m4.init(jax.random.key(0), tokens)
    mesh = make_mesh({"data": 2, "pipe": 4}, jax.devices()[:8])

    def loss(mdl):
        def f(p):
            with axes_lib.use_axes(mesh):
                l, _ = mdl.loss_and_metrics({"params": p}, tokens,
                                            train=True)
            return l
        return f

    v1, g1 = jax.jit(jax.value_and_grad(loss(m4)))(variables["params"])
    vg, gg = jax.jit(jax.value_and_grad(loss(g4)))(variables["params"])
    np.testing.assert_allclose(float(v1), float(vg), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=1e-6
        ),
        g1, gg,
    )


def test_pp_sp_ring_of_four(model, tokens):
    """seq=4 inside pipe=2: multi-hop KV rotation in the manual region."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    ref = jax.jit(lambda v, t: model.apply(v, t))(variables, tokens)
    mesh = make_mesh({"data": 1, "pipe": 2, "seq": 4}, jax.devices()[:8])

    def fwd(v, t):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t)

    got = jax.jit(fwd)(variables, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pp_sp_dropout_trains(tokens):
    """Dropout under pp x sp: keys fold the seq-shard index too, masks are
    deterministic per seed, loss stays finite."""
    from tfde_tpu.parallel import axes as axes_lib

    model = pipelined_tiny_test(dropout_rate=0.3)
    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2}, jax.devices()[:8])
    variables = model.init(jax.random.key(0), tokens)

    def f(v, t, key):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t, train=True, rngs={"dropout": key})

    fn = jax.jit(f)
    a = np.asarray(fn(variables, tokens, jax.random.key(5)))
    b = np.asarray(fn(variables, tokens, jax.random.key(5)))
    np.testing.assert_array_equal(a, b)  # deterministic per seed
    c = np.asarray(fn(variables, tokens, jax.random.key(6)))
    assert not np.allclose(a, c, atol=1e-3)  # seed moves the masks
    assert np.all(np.isfinite(a))
