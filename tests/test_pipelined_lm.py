"""Pipeline-parallel LM tests: the pipelined execution path must be
numerically identical to (a) the sequential scan fallback and (b) plain DP
training — the TPU-native analog of the reference's requirement that a
distribution strategy not change the math (SURVEY.md §2c; VERDICT round-1
item 3: "test training a small GPT at pipe=2 to DP-identical numerics")."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tfde_tpu.models.gpt import next_token_loss
from tfde_tpu.models.pipelined import PipelinedLM, pipelined_tiny_test
from tfde_tpu.parallel.strategies import (
    MultiWorkerMirroredStrategy,
    PipelineParallelStrategy,
)
from tfde_tpu.runtime.mesh import make_mesh
from tfde_tpu.training.step import init_state, make_custom_train_step


@pytest.fixture(scope="module")
def model():
    return pipelined_tiny_test()


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, 97, (16, 32)).astype(np.int32)


def test_pipelined_forward_matches_sequential(model, tokens):
    """Same params, same tokens: pipe=2 logits == no-mesh sequential logits."""
    from tfde_tpu.parallel import axes as axes_lib

    variables = model.init(jax.random.key(0), tokens)
    seq_logits = jax.jit(
        lambda v, t: model.apply(v, t)
    )(variables, tokens)

    mesh = make_mesh({"data": 2, "pipe": 2}, jax.devices()[:4])

    def pipe_forward(v, t):
        with axes_lib.use_axes(mesh):
            return model.apply(v, t)

    pipe_logits = jax.jit(pipe_forward)(variables, tokens)
    np.testing.assert_allclose(
        np.asarray(pipe_logits), np.asarray(seq_logits), rtol=1e-4, atol=1e-5
    )


def test_pipelined_train_matches_dp(model, tokens):
    """5 AdamW steps at pipe=2 x data=2 == 5 steps at data=4 (exact math,
    fp32 tolerance)."""
    strat_p = PipelineParallelStrategy(data=2, pipe=2)
    state_p, _ = init_state(model, optax.adam(1e-3), strat_p, tokens)
    step_p = make_custom_train_step(strat_p, state_p, next_token_loss,
                                    donate=False)

    strat_d = MultiWorkerMirroredStrategy(
        make_mesh({"data": 4}, jax.devices()[:4])
    )
    state_d, _ = init_state(model, optax.adam(1e-3), strat_d, tokens)
    step_d = make_custom_train_step(strat_d, state_d, next_token_loss,
                                    donate=False)

    rng = jax.random.key(0)
    for _ in range(5):
        state_p, m_p = step_p(state_p, (tokens,), rng)
        state_d, m_d = step_d(state_d, (tokens,), rng)
    np.testing.assert_allclose(
        float(m_p["loss"]), float(m_d["loss"]), rtol=2e-5
    )
    assert float(m_p["loss"]) < 4.6  # loss actually moved off init (~ln 97)


def test_stage_params_sharded_over_pipe(model, tokens):
    """Each pipe rank must hold only its stage's weights — the memory point
    of pipelining (round-1 VERDICT: replicated microbatches/stages defeat
    it)."""
    strat = PipelineParallelStrategy(data=2, pipe=2)
    state, _ = init_state(model, optax.adam(1e-3), strat, tokens)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        state.params["stages"]
    ):
        spec = leaf.sharding.spec
        assert spec and spec[0] == "pipe", (
            f"stage leaf {jax.tree_util.keystr(path)} not sharded over "
            f"'pipe': {spec}"
        )
    # embedding + head stay replicated
    assert state.params["wte"].sharding.spec == ()
    # optimizer state follows params: stage moments sharded too
    mu = state.opt_state[0].mu["stages"]
    leaf = jax.tree_util.tree_leaves(mu)[0]
    assert leaf.sharding.spec[0] == "pipe"


def test_microbatch_divisibility_error(model):
    strat = PipelineParallelStrategy(data=1, pipe=2)
    bad = np.zeros((6, 32), np.int32)  # 6 % microbatches(4) != 0
    state, _ = init_state(model, optax.adam(1e-3), strat,
                          np.zeros((8, 32), np.int32))
    step = make_custom_train_step(strat, state, next_token_loss, donate=False)
    with pytest.raises(ValueError, match="microbatches"):
        step(state, (bad,), jax.random.key(0))


def test_pipelined_respects_max_position(model):
    too_long = np.zeros((8, 128), np.int32)
    variables = model.init(jax.random.key(0), np.zeros((8, 32), np.int32))
    with pytest.raises(ValueError, match="max_position"):
        model.apply(variables, too_long)
