"""Request tracing + SLO layer (observability/trace.py, slo.py): ring
bounds and the zero-cost-off contract, cross-process stitching, exemplar
selection, Chrome export schema, burn-rate arithmetic — and one
in-process batcher run proving the serving path actually annotates."""

import json
import threading

import pytest

from tfde_tpu.observability import trace
from tfde_tpu.observability import metrics
from tfde_tpu.observability.slo import SLOTracker


@pytest.fixture(autouse=True)
def _trace_state():
    """Tracing is process-global; every test starts off and leaves off
    (matching the suite's TFDE_TRACE=off default) with a clean ring."""
    was_on = trace.active()
    trace.disable()
    yield
    trace.disable()
    if was_on:  # a TFDE_TRACE=on parity sweep gets its ring back
        trace.enable()


# -- ring semantics + the off contract ----------------------------------------
def test_off_by_default_records_nothing():
    assert not trace.active()
    trace.event("serve/queued", trace="t1", depth=3)
    with trace.span("serve/prefill", trace="t1"):
        pass
    trace.note_exemplar("serving/ttft_ms", 12.0, "t1")
    assert trace.events() == []
    assert trace.exemplars() == {}
    assert trace.dump("off") is None  # not armed, not active


def test_ring_bounds_evict_oldest():
    trace.enable(capacity=4)
    for i in range(7):
        trace.event("e", trace="t", i=i)
    evs = trace.events()
    assert len(evs) == 4
    assert [e["i"] for e in evs] == [3, 4, 5, 6]


def test_reenable_rerings_keeping_newest():
    trace.enable(capacity=8)
    for i in range(6):
        trace.event("e", i=i)
    trace.enable(capacity=2)  # shrink: newest two survive
    assert [e["i"] for e in trace.events()] == [4, 5]


def test_env_capacity_spellings(monkeypatch):
    for spec, want in [("off", None), ("0", None), ("", None),
                       ("on", trace.DEFAULT_CAPACITY),
                       ("1", trace.DEFAULT_CAPACITY),
                       ("4096", 4096)]:
        monkeypatch.setenv("TFDE_TRACE", spec)
        assert trace._env_capacity() == want, spec
    monkeypatch.setenv("TFDE_TRACE", "sideways")  # warn, fail on
    assert trace._env_capacity() == trace.DEFAULT_CAPACITY


def test_event_filter_by_trace_and_traces():
    trace.enable()
    trace.event("a", trace="t1")
    trace.event("b", trace="t2")
    trace.event("wave", traces=["t1", "t2"], rows=2)
    names = [e["name"] for e in trace.events("t1")]
    assert names == ["a", "wave"]
    assert [e["name"] for e in trace.events("t2")] == ["b", "wave"]
    assert len(trace.events()) == 3


def test_span_records_start_timestamp():
    """A duration recorded at block exit is timestamped at block START —
    the waterfall property (events sort by when they began)."""
    trace.enable()
    import time as _t
    before = _t.time()
    with trace.span("slow", trace="t"):
        _t.sleep(0.02)
    (ev,) = trace.events("t")
    assert ev["dur"] >= 0.02
    assert before <= ev["ts"] <= before + 0.01  # start, not end


def test_bind_attaches_thread_local_trace():
    trace.enable()
    with trace.bind("t9"):
        assert trace.current() == "t9"
        trace.event("implicit")  # no explicit trace kwarg
    assert trace.current() is None
    assert [e["name"] for e in trace.events("t9")] == ["implicit"]
    # other threads never see the binding
    seen = {}
    with trace.bind("t9"):
        th = threading.Thread(
            target=lambda: seen.setdefault("cur", trace.current()))
        th.start()
        th.join()
    assert seen["cur"] is None


# -- exemplars ----------------------------------------------------------------
def test_exemplars_keep_slowest():
    trace.enable()
    for i in range(12):
        trace.note_exemplar("serving/ttft_ms", float(i), f"id{i}")
    rows = trace.exemplars("serving/ttft_ms")
    assert len(rows) == trace.EXEMPLAR_KEEP
    assert [r["value"] for r in rows] == [11.0, 10.0, 9.0, 8.0,
                                          7.0, 6.0, 5.0, 4.0]
    assert rows[0]["trace"] == "id11"  # slowest first: the p99 hunt entry
    assert "serving/ttft_ms" in trace.exemplars()


# -- dump / load / stitch -----------------------------------------------------
def test_dump_load_roundtrip(tmp_path):
    trace.enable()
    trace.arm(str(tmp_path))
    trace.event("serve/queued", trace="t1", depth=1)
    trace.event("serve/done", trace="t1", tokens=4)
    path = trace.dump("test")
    assert path is not None and path.endswith(".jsonl")
    with open(path, "a") as f:
        f.write("{truncated crash li")  # load() must tolerate this
    evs = trace.load(path)
    assert [e["name"] for e in evs] == ["serve/queued", "serve/done"]
    assert evs[1]["tokens"] == 4


def test_stitch_dedupes_and_orders_across_procs():
    router = [{"ts": 2.0, "name": "router/done", "proc": "router"},
              {"ts": 0.0, "name": "router/request", "proc": "router"}]
    replica = [{"ts": 1.0, "name": "serve/queued", "proc": "replica0"},
               # the router's local ring seen AGAIN over HTTP (in-process
               # dev / single-host): must collapse to one copy
               {"ts": 0.0, "name": "router/request", "proc": "router"}]
    out = trace.stitch([router, replica])
    assert [e["name"] for e in out] == [
        "router/request", "serve/queued", "router/done"]


# -- Chrome trace-event export ------------------------------------------------
def test_to_chrome_schema():
    evs = [
        {"ts": 10.0, "dur": 0.5, "name": "serve/prefill_cold",
         "proc": "replica0", "pid": 123, "trace": "t1", "rows": 2},
        {"ts": 10.2, "name": "serve/first_token", "proc": "replica0",
         "pid": 123, "trace": "t1"},
        {"ts": 9.9, "name": "router/request", "proc": "router",
         "pid": 7, "trace": "t1"},
    ]
    doc = trace.to_chrome(evs)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    body = json.loads(json.dumps(doc))  # must be pure-JSON serializable
    metas = [e for e in body["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {"router", "replica0"}
    slices = [e for e in body["traceEvents"] if e["ph"] == "X"]
    (sl,) = slices
    assert sl["dur"] == pytest.approx(0.5e6)      # us
    assert sl["ts"] == pytest.approx(10.0 * 1e6)  # epoch us
    assert sl["args"]["rows"] == 2 and sl["args"]["trace"] == "t1"
    instants = [e for e in body["traceEvents"] if e["ph"] == "i"]
    assert len(instants) == 2 and all(e["s"] == "p" for e in instants)
    # one pid row per process, shared by its events
    assert sl["pid"] == next(m["pid"] for m in metas
                             if m["args"]["name"] == "replica0")


# -- SLO tracker --------------------------------------------------------------
def test_slo_attainment_and_burn_rate_arithmetic():
    t = {"now": 1000.0}
    reg = metrics.Registry()
    s = SLOTracker(ttft_target_ms=100.0, tpot_target_ms=50.0,
                   objective=0.99, windows=(60.0,), registry=reg,
                   clock=lambda: t["now"])
    assert s.attainment("ttft") is None        # no samples yet
    assert s.burn_rate("ttft", 60.0) is None
    s.record(ttft_ms=80.0, tpot_ms=40.0)       # both ok
    s.record(ttft_ms=150.0)                    # ttft miss, no tpot sample
    assert s.attainment("ttft") == pytest.approx(0.5)
    assert s.attainment("tpot") == pytest.approx(1.0)
    # burn = (1 - 0.5) / (1 - 0.99) = 50x budget
    assert s.burn_rate("ttft", 60.0) == pytest.approx(50.0)
    assert s.burn_rate("tpot", 60.0) == pytest.approx(0.0)
    # the miss ages out of the window; lifetime attainment keeps it
    t["now"] += 120.0
    s.record(ttft_ms=10.0)
    assert s.attainment("ttft", window=60.0) == pytest.approx(1.0)
    assert s.attainment("ttft") == pytest.approx(2.0 / 3.0)
    assert s.burn_rate("ttft", 60.0) == pytest.approx(0.0)


def test_slo_summary_and_gauges():
    reg = metrics.Registry()
    s = SLOTracker(ttft_target_ms=100.0, tpot_target_ms=50.0,
                   objective=0.9, windows=(300.0, 3600.0), registry=reg)
    s.record(ttft_ms=500.0, tpot_ms=10.0)
    out = s.summary()
    assert out["objective"] == pytest.approx(0.9)
    assert out["ttft_requests"] == 1 and out["tpot_requests"] == 1
    assert out["ttft_attainment"] == pytest.approx(0.0)
    assert out["ttft_burn_rate"]["300s"] == pytest.approx(10.0)
    assert out["windows_s"] == [300.0, 3600.0]
    json.dumps(out)  # the /replicas embed must be JSON-clean
    snap = reg.snapshot()
    assert snap["slo/ttft_attainment"]["value"] == pytest.approx(0.0)
    assert snap["slo/ttft_burn_rate_300s"]["value"] == pytest.approx(10.0)
    assert snap["slo/objective"]["value"] == pytest.approx(0.9)


def test_slo_objective_clamped_off_the_pole():
    s = SLOTracker(objective=1.0, registry=metrics.Registry())
    assert s.objective <= 0.9999
    s.record(ttft_ms=1e9)
    assert s.burn_rate("ttft", s.windows[0]) is not None  # no div-by-zero


# -- the serving path annotates -----------------------------------------------
def test_batcher_emits_request_waterfall():
    """An in-process ContinuousBatcher run with trace ids: the ring must
    tell the request's whole story — queue, prefill wave, first token,
    decode rounds, done — and feed the TTFT exemplar store."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfde_tpu.inference.server import ContinuousBatcher
    from tfde_tpu.models.gpt import gpt_tiny_test

    model = gpt_tiny_test()
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    trace.enable()
    srv = ContinuousBatcher(model, params, batch_size=2, max_len=48)
    rng = np.random.default_rng(0)
    tid = trace.new_id()
    srv.submit(rng.integers(0, 97, 4), 6, trace=tid)
    srv.submit(rng.integers(0, 97, 3), 4)  # untraced neighbour: no events
    done = srv.run()
    assert len(done) == 2
    names = [e["name"] for e in trace.events(tid)]
    assert names[0] == "serve/queued"
    assert any(n.startswith("serve/prefill_") for n in names)
    assert "serve/first_token" in names
    assert "serve/decode_round" in names
    # done lands during the last round's token replay; that round's own
    # decode_round event (recorded at round exit) may trail it
    assert "serve/done" in names
    assert names.index("serve/done") > names.index("serve/first_token")
    # the untraced neighbour must not have minted its own id: every
    # request-tagged ring event points at the one traced request
    # (untagged phase spans — e.g. serving/prefill — are fine)
    for e in trace.events():
        assert e.get("trace") in (None, tid)
        assert set(e.get("traces", ())) <= {tid}
    ex = trace.exemplars("serving/ttft_ms")
    assert [r["trace"] for r in ex] == [tid]
