"""TensorBoard event-file writer tests: the wire format must be readable by
standard TFRecord/proto parsers (we parse it back by hand here; TF, when
present in the env, is the gold check)."""

import glob
import os
import struct

import numpy as np
import pytest

from tfde_tpu.observability import tensorboard as tb


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert tb.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert tb.crc32c(b"123456789") == 0xE3069283


def _read_records(path):
    out = []
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off < len(data):
        (length,) = struct.unpack_from("<Q", data, off)
        (len_crc,) = struct.unpack_from("<I", data, off + 8)
        assert len_crc == tb._masked_crc(data[off : off + 8])
        payload = data[off + 12 : off + 12 + length]
        (crc,) = struct.unpack_from("<I", data, off + 12 + length)
        assert crc == tb._masked_crc(payload)
        out.append(payload)
        off += 12 + length + 4
    return out


def test_event_file_structure(tmp_path):
    w = tb.SummaryWriter(str(tmp_path))
    w.scalars(10, {"loss": 0.5, "accuracy": 0.9})
    w.scalar(20, "loss", 0.25)
    w.close()

    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    records = _read_records(files[0])
    assert len(records) == 3  # file_version + 2 events
    assert b"brain.Event:2" in records[0]
    assert b"loss" in records[1] and b"accuracy" in records[1]
    assert b"loss" in records[2]


@pytest.mark.slow
def test_events_parse_with_tensorflow_if_available(tmp_path):
    tf = pytest.importorskip("tensorflow")
    w = tb.SummaryWriter(str(tmp_path))
    w.scalars(7, {"loss": 1.25})
    w.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    got = []
    for rec in tf.compat.v1.io.tf_record_iterator(path):
        ev = tf.compat.v1.Event.FromString(rec)
        for v in ev.summary.value:
            got.append((ev.step, v.tag, v.simple_value))
    assert got == [(7, "loss", 1.25)]
