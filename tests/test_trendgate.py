"""Perf trendline gate (tools/trendgate.py): the committed BENCH history
must be green with a real comparable pair, every burned round must skip
with a reason (never crash the gate), synthetic regressions must fail
loudly per-metric, and the TFDE_TRENDGATE_INJECT drill must bite."""

import importlib.util
import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tg():
    return _load("trendgate")


@pytest.fixture(scope="module")
def policy():
    with open(os.path.join(ROOT, "tools", "trendgate_policy.json")) as f:
        return json.load(f)


# A minimal trusted capture: tpu platform, calibrated clock, nonzero
# headline — everything parse_capture requires for "comparable".
def _capture(**metrics):
    doc = {"platform": "tpu", "calib_frac_of_peak": 0.95, "value": 1.0}
    doc.update(metrics)
    return doc


def _write(repo, name, doc):
    with open(os.path.join(repo, name), "w") as f:
        if isinstance(doc, str):
            f.write(doc)
        else:
            json.dump(doc, f)


_POLICY = {
    "trust": {"platform": "tpu", "min_calib_frac_of_peak": 0.8},
    "default_slack": 0.10,
    "metrics": {
        "mfu": {"direction": "higher", "slack": 0.10},
        "step_ms": {"direction": "lower", "slack": 0.15},
        "flash_speedup": {"direction": "higher", "gate": False},
    },
}


# -- the committed history itself --------------------------------------------
def test_committed_history_is_green(tg, policy):
    caps = tg.load_history(ROOT, policy.get("trust", {}))
    assert caps, "no committed BENCH_*.json found"
    fails = tg.check(caps, policy)
    assert fails == [], f"committed BENCH history fails its own gate: {fails}"
    # the gate must actually be comparing something: a real pair, not a
    # degenerate <2-comparable pass
    trend = tg.build_trend(caps, policy)
    assert trend["pair"] is not None, (
        "fewer than two comparable captures in the committed history — "
        "the trend gate is vacuous")
    # and every non-comparable round carries a human-readable reason
    for s in trend["skipped"]:
        assert s["reason"]


def test_committed_inject_drill_bites(tg, policy):
    caps = tg.load_history(ROOT, policy.get("trust", {}))
    comp = tg.comparable(caps)
    caps.append(tg.inject_capture(comp[-1], policy))
    fails = tg.check(caps, policy)
    gated = [n for n, mp in policy["metrics"].items()
             if mp.get("gate", True) and n in comp[-1]["metrics"]]
    assert len(fails) == len(gated) > 0
    assert all("trendgate.py --update" in f for f in fails)


# -- skip sorting -------------------------------------------------------------
def test_skip_reasons_cover_burned_rounds(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r01.json", "{not json")
    _write(repo, "BENCH_r02.json", _capture(error="OOM on chip 3"))
    _write(repo, "BENCH_r03.json", dict(_capture(), platform="cpu"))
    nocalib = _capture()
    del nocalib["calib_frac_of_peak"]
    _write(repo, "BENCH_r04.json", nocalib)
    _write(repo, "BENCH_r05.json", _capture(calib_frac_of_peak=0.5))
    _write(repo, "BENCH_r06.json", dict(_capture(), value=0.0))
    # a driver wrapper whose parsed is null and whose tail is truncated
    _write(repo, "BENCH_r07.json",
           {"cmd": ["bench"], "rc": 124, "parsed": None,
            "tail": '{"platform": "tpu", "calib_'})
    caps = tg.load_history(repo, _POLICY["trust"])
    assert len(caps) == 7
    reasons = {c["file"]: c["skip"] for c in caps}
    assert "unparseable" in reasons["BENCH_r01.json"]
    assert "OOM on chip 3" in reasons["BENCH_r02.json"]
    assert "'cpu'" in reasons["BENCH_r03.json"]
    assert "calibration anchor" in reasons["BENCH_r04.json"]
    assert "below trust floor" in reasons["BENCH_r05.json"]
    assert "zero/absent" in reasons["BENCH_r06.json"]
    assert "no parseable payload" in reasons["BENCH_r07.json"]
    # nothing comparable -> no trend, but the gate still passes (a burned
    # history is a missing baseline, not a regression)
    assert tg.check(caps, _POLICY) == []


def test_driver_tail_salvage(tg, tmp_path):
    """A timed-out driver attempt whose tail still ends in a complete
    JSON line is salvaged as a comparable capture."""
    repo = str(tmp_path)
    payload = _capture(mfu=0.42)
    _write(repo, "BENCH_r01.json",
           {"cmd": ["bench"], "rc": 124, "parsed": None,
            "tail": "noise line\n" + json.dumps(payload)})
    caps = tg.load_history(repo, _POLICY["trust"])
    assert caps[0]["skip"] is None
    assert caps[0]["metrics"]["mfu"] == pytest.approx(0.42)


def test_builder_sorts_before_driver_same_round(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r02.json", _capture(mfu=0.5))
    _write(repo, "BENCH_builder_r02.json", _capture(mfu=0.4))
    caps = tg.load_history(repo, _POLICY["trust"])
    assert [c["file"] for c in caps] == ["BENCH_builder_r02.json",
                                        "BENCH_r02.json"]


# -- gating -------------------------------------------------------------------
def test_regression_fails_within_slack_passes(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r01.json", _capture(mfu=0.50, step_ms=100.0))
    # within slack both directions: pass
    _write(repo, "BENCH_r02.json", _capture(mfu=0.46, step_ms=112.0))
    caps = tg.load_history(repo, _POLICY["trust"])
    assert tg.check(caps, _POLICY) == []
    # past slack, both directions: one failure per metric, loud
    _write(repo, "BENCH_r03.json", _capture(mfu=0.40, step_ms=130.0))
    caps = tg.load_history(repo, _POLICY["trust"])
    fails = tg.check(caps, _POLICY)
    assert len(fails) == 2
    assert any("mfu" in f and "dropped" in f for f in fails)
    assert any("step_ms" in f and "rose" in f for f in fails)


def test_ungated_metric_is_informational(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r01.json", _capture(flash_speedup=3.0))
    _write(repo, "BENCH_r02.json", _capture(flash_speedup=1.1))
    caps = tg.load_history(repo, _POLICY["trust"])
    assert tg.check(caps, _POLICY) == []
    rows = {r["metric"]: r for r in tg.build_trend(caps, _POLICY)["rows"]}
    assert rows["flash_speedup"]["status"] == "regressed (informational)"


def test_gated_metric_disappearing_fails(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r01.json", _capture(mfu=0.50))
    _write(repo, "BENCH_r02.json", _capture(step_ms=100.0))
    caps = tg.load_history(repo, _POLICY["trust"])
    fails = tg.check(caps, _POLICY)
    assert len(fails) == 1 and "ABSENT" in fails[0] and "mfu" in fails[0]
    # improvement is never a failure
    _write(repo, "BENCH_r03.json", _capture(mfu=0.9, step_ms=50.0))
    del caps  # recompute: r02 -> r03 adds mfu back (status "new") + improves
    caps = tg.load_history(repo, _POLICY["trust"])
    assert tg.check(caps, _POLICY) == []


# -- report -------------------------------------------------------------------
def test_report_renders_both_outcomes(tg, tmp_path):
    repo = str(tmp_path)
    _write(repo, "BENCH_r01.json", _capture(mfu=0.50))
    _write(repo, "BENCH_r02.json", _capture(mfu=0.30))
    _write(repo, "BENCH_r03.json", "{not json")
    caps = tg.load_history(repo, _POLICY["trust"])
    fails = tg.check(caps, _POLICY)
    report = tg.render_report(caps, _POLICY, fails)
    assert "**FAIL**" in report and "mfu" in report
    assert "skipped: unparseable" in report
    ok = tg.render_report(caps[:1], _POLICY, [])
    assert "Fewer than two comparable captures" in ok
    assert "pass (1 comparable capture(s)" in ok


def test_committed_trend_md_is_current(tg, policy):
    """TREND.md is generated — a drifted checked-in report means someone
    changed the history or policy without running --update."""
    caps = tg.load_history(ROOT, policy.get("trust", {}))
    fails = tg.check(caps, policy)
    want = tg.render_report(caps, policy, fails)
    with open(os.path.join(ROOT, "TREND.md")) as f:
        assert f.read() == want, (
            "TREND.md is stale — regenerate with: "
            "python tools/trendgate.py --update")
