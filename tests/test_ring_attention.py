"""Ring-attention tests: exact numerics vs the reference kernel across ring
sizes, causal + padding masks, grads, and the auto-dispatch path
(SURVEY.md §4 fake-device methodology)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfde_tpu.ops.attention import attention, padding_mask, reference_attention
from tfde_tpu.ops.ring_attention import ring_attention
from tfde_tpu.parallel import axes as axes_lib
from tfde_tpu.runtime.mesh import make_mesh


def _qkv(rng, b=2, s=16, h=2, d=4):
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        for _ in range(3)
    )


def _mesh(shape):
    import math

    n = math.prod(shape.values())
    return make_mesh(shape, jax.devices()[:n])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kv_heads", [1, 2])
def test_ring_gqa_matches_grouped_reference(rng, causal, kv_heads):
    """GQA through the ring: kv_heads-sized KV shards rotate; numerics
    must equal the grouped-einsum oracle across a 4-ring, with and
    without a padding mask."""
    from tfde_tpu.ops.attention import grouped_attention

    mesh = _mesh({"seq": 4})
    b, s, h, d = 2, 16, 4, 4
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv_heads, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv_heads, d)), jnp.float32)
    expect = grouped_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, causal=causal, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)

    valid = np.ones((b, s), np.float32)
    valid[0, 10:] = 0.0
    m = padding_mask(jnp.asarray(valid))
    expect = grouped_attention(q, k, v, mask=m, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mask=m, causal=causal,
                                       mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mesh_shape", [{"seq": 4}, {"data": 2, "seq": 4},
                                        {"seq": 8}])
def test_ring_matches_reference(rng, mesh_shape):
    mesh = _mesh(mesh_shape)
    q, k, v = _qkv(rng)
    expect = reference_attention(q, k, v)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_ring_causal_matches_reference(rng):
    mesh = _mesh({"seq": 4})
    q, k, v = _qkv(rng)
    expect = reference_attention(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, causal=True, mesh=mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_ring_padding_mask_matches_reference(rng):
    mesh = _mesh({"seq": 4})
    q, k, v = _qkv(rng)
    valid = np.ones((2, 16), np.float32)
    valid[0, 10:] = 0.0
    valid[1, 5:] = 0.0
    m = padding_mask(jnp.asarray(valid))
    expect = reference_attention(q, k, v, mask=m)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mask=m, mesh=mesh)
    )(q, k, v)
    # compare only rows with at least one valid key (padded-out query rows
    # are garbage in both impls, by different formulas)
    e, g = np.asarray(expect), np.asarray(got)
    np.testing.assert_allclose(g[0], e[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g[1], e[1], rtol=1e-5, atol=1e-5)


def test_ring_gradients_match_reference(rng):
    mesh = _mesh({"seq": 4})
    q, k, v = _qkv(rng, s=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_auto_dispatch_uses_ring_under_seq_mesh(rng):
    mesh = _mesh({"seq": 4})
    q, k, v = _qkv(rng)

    @jax.jit
    def f(q, k, v):
        with axes_lib.use_axes(mesh):
            return attention(q, k, v, impl="auto")

    got = f(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(reference_attention(q, k, v)),
        rtol=1e-5, atol=1e-5,
    )


def test_ring_requires_seq_axis(rng):
    q, k, v = _qkv(rng)
    with pytest.raises(ValueError, match="seq"):
        ring_attention(q, k, v, mesh=_mesh({"data": 8}))


def test_ring_rejects_dense_mask(rng):
    mesh = _mesh({"seq": 4})
    q, k, v = _qkv(rng)
    dense = jnp.ones((2, 2, 16, 16), jnp.bool_)
    with pytest.raises(NotImplementedError):
        ring_attention(q, k, v, mask=dense, mesh=mesh)


@pytest.mark.slow
def test_bert_train_step_seq_parallel_matches_dp(rng):
    """End-to-end: a BERT train step on a data x seq mesh (ring attention
    engaged via auto-dispatch) reproduces pure-DP numerics."""
    import optax

    from tfde_tpu.models.bert import bert_tiny_test
    from tfde_tpu.parallel.strategies import (
        MultiWorkerMirroredStrategy,
        SequenceParallelStrategy,
    )
    from tfde_tpu.training.step import init_state, make_custom_train_step

    def mlm_like_loss(state, params, batch, rng_):
        ids, labels = batch
        logits = state.apply_fn({"params": params}, ids, train=True,
                                rngs={"dropout": rng_})
        from tfde_tpu.ops.losses import masked_lm_loss

        loss, acc = masked_lm_loss(logits, labels)
        return loss, {"acc": acc}

    ids = rng.integers(0, 96, (8, 16)).astype(np.int32)
    labels = np.where(rng.random((8, 16)) < 0.2, ids, -100).astype(np.int32)

    def run(strategy):
        m = bert_tiny_test()
        state, _ = init_state(m, optax.sgd(0.1), strategy,
                              np.zeros((8, 16), np.int32), seed=0)
        step = make_custom_train_step(strategy, state, mlm_like_loss,
                                      donate=False)
        key = jax.random.key(0)
        for _ in range(2):
            state, metrics = step(state, (ids, labels), key)
        return jax.device_get(state.params), float(metrics["loss"])

    p_dp, l_dp = run(MultiWorkerMirroredStrategy())
    p_sp, l_sp = run(SequenceParallelStrategy(data=2))  # seq=4 ring
    np.testing.assert_allclose(l_dp, l_sp, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5),
        p_dp, p_sp,
    )


def test_ring_blockwise_local_chunks_match_reference(rng):
    """block_k smaller than the per-chip shard forces the chunked local
    path (O(sq*block_k) score memory); numerics must still match the
    reference exactly, causal and not, with and without padding mask."""
    from tfde_tpu.ops.attention import padding_mask, reference_attention
    from tfde_tpu.ops.ring_attention import ring_attention
    from tfde_tpu.runtime.mesh import make_mesh

    mesh = make_mesh({"seq": 2}, jax.devices()[:2])
    b, s, h, d = 2, 128, 2, 16  # 64 per chip; block_k=16 -> 4 chunks/shard
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        for _ in range(3)
    )
    for causal in (False, True):
        ref = reference_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, causal=causal, mesh=mesh, block_k=16)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )
    valid = np.ones((b, s), np.float32)
    valid[:, -37:] = 0.0
    ref = reference_attention(q, k, v, mask=padding_mask(jnp.asarray(valid)))
    out = ring_attention(
        q, k, v, mask=padding_mask(jnp.asarray(valid)), mesh=mesh, block_k=16
    )
    np.testing.assert_allclose(
        np.asarray(out)[:, : s - 37], np.asarray(ref)[:, : s - 37],
        rtol=2e-5, atol=2e-6,
    )
