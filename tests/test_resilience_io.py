"""Retry wiring in the I/O layers: remote fs ops and checkpoint save/restore
survive injected transient failures; deterministic errors still fail fast."""

import types

import jax.numpy as jnp
import pytest

import tfde_tpu.utils.fs as fs_mod
from tfde_tpu.checkpoint.manager import CheckpointManager
from tfde_tpu.resilience.faults import FaultInjector, FaultSchedule
from tfde_tpu.resilience.policy import RetryBudgetExceeded, RetryPolicy


@pytest.fixture()
def fast_fs_retry(monkeypatch):
    """Point the fs layer at a fast retry policy for the test's duration
    (monkeypatch restores the cached module policy afterwards)."""
    monkeypatch.setattr(
        fs_mod, "_RETRY",
        RetryPolicy(max_attempts=3, initial_backoff=0.001, jitter=0.0),
    )


def _memfs():
    import fsspec

    return fsspec.filesystem("memory")


def test_remote_write_survives_transient_blip(fast_fs_retry):
    mem = _memfs()
    with FaultInjector(
        FaultSchedule.fail_on(1, exc_type=ConnectionError)
    ).patch(mem, "pipe_file"):
        fs_mod.write_bytes("memory://retry/blob", b"payload")
    with fs_mod.fs_open("memory://retry/blob") as f:
        assert f.read() == b"payload"


def test_remote_listdir_missing_fails_fast(fast_fs_retry):
    mem = _memfs()
    calls = {"n": 0}
    orig = mem.ls

    def counting_ls(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    mem.ls = counting_ls
    try:
        with pytest.raises(FileNotFoundError):
            fs_mod.listdir("memory://no/such/dir")
        assert calls["n"] == 1  # deterministic miss: no retry burn
    finally:
        mem.ls = orig


def test_remote_op_budget_exhaustion_is_oserror(fast_fs_retry):
    mem = _memfs()
    with FaultInjector(
        FaultSchedule.fail_on(1, 2, 3, 4, exc_type=TimeoutError)
    ).patch(mem, "exists"):
        with pytest.raises(OSError):  # RetryBudgetExceeded is an OSError
            fs_mod.exists("memory://flaky/object")


class _Bag(types.SimpleNamespace):
    def replace(self, **kw):  # the TrainState.replace surface restore uses
        d = dict(self.__dict__)
        d.update(kw)
        return _Bag(**d)


def _tiny_state():
    """The minimal TrainState-shaped bag the manager needs for save/restore."""
    return _Bag(
        step=jnp.asarray(5),
        params={"w": jnp.ones((3,), jnp.float32)},
        batch_stats={},
        opt_state={},
    )


def test_checkpoint_save_retries_past_transient_error(tmp_path):
    mngr = CheckpointManager(
        str(tmp_path / "ckpt"), async_save=False,
        retry_policy=RetryPolicy(max_attempts=3, initial_backoff=0.001,
                                 jitter=0.0),
    )
    # the INNER orbax save fails once; the manager's own retry absorbs it
    with FaultInjector(FaultSchedule.fail_on(1, exc_type=IOError)).patch(
        mngr._mngr, "save"
    ):
        assert mngr.save(_tiny_state()) is True
    assert mngr.latest_step == 5
    mngr.close()


def test_checkpoint_save_budget_exhaustion_surfaces(tmp_path):
    mngr = CheckpointManager(
        str(tmp_path / "ckpt"), async_save=False,
        retry_policy=RetryPolicy(max_attempts=2, initial_backoff=0.001,
                                 jitter=0.0),
    )
    with FaultInjector(
        FaultSchedule.fail_on(1, 2, exc_type=IOError)
    ).patch(mngr._mngr, "save"):
        with pytest.raises(RetryBudgetExceeded):
            mngr.save(_tiny_state())
    mngr.close()


def test_checkpoint_restore_retries_past_transient_error(tmp_path):
    mngr = CheckpointManager(
        str(tmp_path / "ckpt"), async_save=False,
        retry_policy=RetryPolicy(max_attempts=3, initial_backoff=0.001,
                                 jitter=0.0),
    )
    state = _tiny_state()
    assert mngr.save(state)
    with FaultInjector(FaultSchedule.fail_on(1, exc_type=IOError)).patch(
        mngr._mngr, "restore"
    ):
        restored = mngr.restore_latest(state)
    assert restored is not None
    assert int(restored.step) == 5
    mngr.close()
