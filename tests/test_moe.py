"""MoE tests: routing conservation, capacity behavior, aux-loss wiring into
the default train step, expert-parallel sharding + numerics parity with DP
(SURVEY.md §4 fake-device methodology)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tfde_tpu.models.moe import MoEMlp, dispatch_shape, group_capacity
from tfde_tpu.models.transformer import Encoder
from tfde_tpu.parallel.strategies import (
    ExpertParallelStrategy,
    MirroredStrategy,
    MultiWorkerMirroredStrategy,
)


def test_moe_output_shape_and_aux_loss(rng):
    m = MoEMlp(num_experts=4, mlp_dim=32, dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    v = m.init(jax.random.key(0), x)
    # init itself sows into 'losses'; the training path (init_state) keeps
    # only params/batch_stats, so mirror that here
    y, mutated = m.apply({"params": v["params"]}, x, mutable=["losses"])
    assert y.shape == x.shape
    aux = jax.tree_util.tree_leaves(mutated["losses"])
    assert len(aux) == 1
    # balanced-ish random routing: aux ~ weight * E * sum(f*p) ~ weight
    assert 0.0 < float(aux[0]) < 1.0


def test_moe_router_z_loss(rng):
    """ST-MoE z-loss: off by default (one sown loss — the numerics every
    existing test pins); when enabled, a second sown loss appears, equal
    to weight * mean(logsumexp(router logits)^2), and scaling the router
    weights up increases it (the drift it exists to penalize)."""
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    m0 = MoEMlp(num_experts=4, mlp_dim=32, dtype=jnp.float32)
    v = m0.init(jax.random.key(0), x)
    _, mut = m0.apply({"params": v["params"]}, x, mutable=["losses"])
    assert len(jax.tree_util.tree_leaves(mut["losses"])) == 1  # off

    mz = MoEMlp(num_experts=4, mlp_dim=32, dtype=jnp.float32,
                router_z_loss_weight=1e-3)
    _, mut = mz.apply({"params": v["params"]}, x, mutable=["losses"])
    losses = mut["losses"]
    assert "moe_z" in losses and "moe_aux" in losses
    (z,) = jax.tree_util.tree_leaves(losses["moe_z"])
    logits = x.reshape(2, 8, 16).astype(jnp.float32) @ np.asarray(
        v["params"]["router"]["kernel"]
    )
    expect = 1e-3 * float(jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2))
    np.testing.assert_allclose(float(z), expect, rtol=1e-5)

    # bigger router logits -> bigger z penalty
    import flax

    v2 = flax.core.unfreeze(jax.tree_util.tree_map(lambda a: a, v["params"]))
    v2["router"]["kernel"] = v2["router"]["kernel"] * 5.0
    _, mut2 = mz.apply({"params": v2}, x, mutable=["losses"])
    (z2,) = jax.tree_util.tree_leaves(mut2["losses"]["moe_z"])
    assert float(z2) > float(z)


def test_moe_full_capacity_top1_is_lossless_combine(rng):
    """With capacity >= all tokens and k=1, every token is processed by its
    top expert: output must equal the hand-computed per-expert MLP."""
    m = MoEMlp(
        num_experts=2, mlp_dim=8, experts_per_token=1,
        capacity_factor=4.0, dtype=jnp.float32,
    )
    x = jnp.asarray(rng.standard_normal((1, 6, 4)), jnp.float32)
    v = m.init(jax.random.key(0), x)
    y = m.apply(v, x, mutable=["losses"])[0]

    p = v["params"]
    tokens = np.asarray(x).reshape(6, 4)
    logits = tokens @ np.asarray(p["router"]["kernel"])
    top = logits.argmax(-1)
    expect = np.zeros((6, 4), np.float32)
    for i, e in enumerate(top):
        h = tokens[i] @ np.asarray(p["experts_fc1"])[e] + np.asarray(p["experts_b1"])[e, 0]
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        expect[i] = h @ np.asarray(p["experts_fc2"])[e] + np.asarray(p["experts_b2"])[e, 0]
    np.testing.assert_allclose(np.asarray(y).reshape(6, 4), expect,
                               rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_overflow(rng):
    """capacity_factor tiny -> most tokens dropped -> output mostly zeros
    (the residual path handles them in a full block)."""
    m = MoEMlp(
        num_experts=2, mlp_dim=8, experts_per_token=1,
        capacity_factor=0.01, dtype=jnp.float32,
    )
    x = jnp.asarray(rng.standard_normal((1, 64, 4)), jnp.float32)
    v = m.init(jax.random.key(0), x)
    y = m.apply(v, x, mutable=["losses"])[0]
    zero_rows = np.sum(np.all(np.asarray(y).reshape(64, 4) == 0.0, axis=-1))
    assert zero_rows >= 60  # capacity 1 per expert -> <= 2 processed


def _run_encoder(strategy, steps=3):
    from tfde_tpu.training.step import init_state, make_train_step

    import flax.linen as nn

    class Clf(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape(x.shape[0], 8, 8)
            x = nn.Dense(16, dtype=jnp.float32, name="embed")(x)
            x = Encoder(
                depth=2, num_heads=2, head_dim=8, mlp_dim=32,
                dtype=jnp.float32, num_experts=4, moe_every=2,
                name="encoder",
            )(x, train=train)
            return nn.Dense(10, dtype=jnp.float32, name="head")(
                jnp.mean(x, axis=1)
            )

    m = Clf()
    sample = np.zeros((16, 64), np.float32)
    # SGD, not Adam: layout parity is asserted to float tolerance, and
    # Adam's m/sqrt(v) early steps amplify reduction-order noise to O(lr)
    state, _ = init_state(m, optax.sgd(0.1), strategy, sample, seed=0)
    step = make_train_step(strategy, state, donate=False)
    rng = np.random.default_rng(0)
    images = rng.random((16, 64), np.float32)
    labels = rng.integers(0, 10, (16, 1)).astype(np.int32)
    key = jax.random.key(0)
    first = None
    for _ in range(steps):
        state, metrics = step(state, (images, labels), key)
        if first is None:
            first = float(metrics["loss"])
    return jax.device_get(state.params), first, float(metrics["loss"])


def test_dispatch_tensor_linear_in_tokens_at_fixed_group_size():
    """The GShard per-group formulation (VERDICT r2 weak #4): at fixed group
    size, doubling the token count doubles the dispatch tensor — capacity is
    per-group, NOT proportional to the global token count."""
    import math

    base = dispatch_shape(batch=8, seq=512, num_experts=16)
    doubled = dispatch_shape(batch=16, seq=512, num_experts=16)
    assert doubled[0] == 2 * base[0]          # twice the groups
    assert doubled[1:] == base[1:]            # same per-group shape
    assert math.prod(doubled) == 2 * math.prod(base)  # linear, not quadratic

    # BERT-base scale-config sanity (the round-2 blowup case: 256x512 tokens
    # where global capacity c ∝ n made the [n,e,c] dispatch ~TB-scale):
    # per-group fp32 dispatch now stays under 1 GB.
    g, m, e, c = dispatch_shape(batch=256, seq=512, num_experts=64)
    assert c == group_capacity(512, 64, 2, 1.25)  # ∝ seq, not batch*seq
    assert g * m * e * c * 4 < 1e9


def test_group_capacity_is_per_group():
    # 128 tokens/group, 8 experts, k=2, cf=1.0 -> 32 slots per expert/group,
    # independent of how many groups exist
    assert group_capacity(128, 8, 2, 1.0) == 32
    assert dispatch_shape(batch=4, seq=128, num_experts=8,
                          capacity_factor=1.0)[3] == 32
    assert dispatch_shape(batch=400, seq=128, num_experts=8,
                          capacity_factor=1.0)[3] == 32


def test_moe_grouped_routing_matches_reference_per_group(rng):
    """With two identical sequences, full capacity, and k=1, per-group
    routing must give both sequences identical outputs (groups are
    independent)."""
    m = MoEMlp(num_experts=2, mlp_dim=8, experts_per_token=1,
               capacity_factor=4.0, dtype=jnp.float32)
    one = rng.standard_normal((1, 6, 4))
    x = jnp.asarray(np.concatenate([one, one], axis=0), jnp.float32)
    v = m.init(jax.random.key(0), x)
    y = m.apply(v, x, mutable=["losses"])[0]
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(y[1]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_moe_encoder_trains_and_ep_matches_dp():
    p_dp, first_dp, last_dp = _run_encoder(MultiWorkerMirroredStrategy())
    assert last_dp < first_dp  # training works with the sown aux loss
    p_ep, first_ep, last_ep = _run_encoder(ExpertParallelStrategy(data=2))
    np.testing.assert_allclose(first_dp, first_ep, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p_dp, p_ep,
    )


def test_ep_weights_actually_sharded():
    from tfde_tpu.training.step import init_state

    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return MoEMlp(num_experts=8, mlp_dim=32, dtype=jnp.float32)(
                x, train=train
            )

    s = ExpertParallelStrategy(data=1)  # expert=8
    state, _ = init_state(
        M(), optax.sgd(0.1), s, np.zeros((4, 4, 16), np.float32)
    )
    fc1 = state.params["MoEMlp_0"]["experts_fc1"]
    assert fc1.sharding.spec == P("expert", None, None)
    assert state.params["MoEMlp_0"]["router"]["kernel"].sharding.spec in (
        P(), P(None, None),
    )


@pytest.mark.slow
def test_moe_gpt_custom_path_trains_with_sown_losses():
    """VERDICT r4 weak #5 follow-on: the custom-LM path (next_token_loss)
    must collect the sown MoE losses — sow() into an immutable collection
    is a silent no-op, which would train routing unbalanced. The aux and
    z losses must appear in metrics and join the objective."""
    from tfde_tpu.models.gpt import gpt_tiny_test, next_token_loss
    from tfde_tpu.training.step import init_state, make_custom_train_step

    s = MirroredStrategy()
    m = gpt_tiny_test(num_experts=4, moe_every=2, router_z_loss_weight=1e-3)
    sample = np.zeros((8, 16), np.int32)
    state, _ = init_state(m, optax.sgd(0.01), s, sample, seed=0)
    step = make_custom_train_step(s, state, next_token_loss)
    toks = np.random.default_rng(0).integers(0, 97, (8, 16)).astype(np.int32)
    state, metr = step(state, (toks,), jax.random.key(0))
    assert "moe_aux" in metr and "moe_z" in metr
    aux = float(metr["moe_aux"])
    z = float(metr["moe_z"])
    assert aux > 0.0 and z > 0.0
    # dense model through the same path: no sown keys, still trains
    m2 = gpt_tiny_test()
    state2, _ = init_state(m2, optax.sgd(0.01), s, sample, seed=0)
    step2 = make_custom_train_step(s, state2, next_token_loss)
    _, metr2 = step2(state2, (toks,), jax.random.key(0))
    assert "moe_aux" not in metr2
